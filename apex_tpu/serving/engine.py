"""Continuous-batching inference engine: chunked prefill + multi-step
fused decode over the paged KV-cache, with a fixed-shape scheduler,
prefix caching, and optimistic admission backed by preemption.

The Orca/vLLM serving loop (PAPERS.md) restated for XLA, where a shape
change means a recompile and a recompile means a multi-second stall
mid-traffic. The engine therefore holds a **fixed-program contract**:

- ``prefill``: one request at a time at the fixed shape
  ``[1, prefill_chunk]``, iterated over the prompt — each chunk's K/V
  are scattered into the sequence's cache blocks, then the chunk's
  queries attend against EVERYTHING cached so far (matched prefix
  blocks, earlier chunks, the chunk itself) through the block table
  (Sarathi-style chunked prefill: a long prompt no longer head-of-line
  blocks the decode slots, and prompts up to ``max_seq_len`` are
  admissible regardless of the chunk size). The FIRST generated token
  is sampled from the last real position's logits of the final chunk.
- ``decode``: ALL slots at once, ``decode_steps`` (K) iterations fused
  into ONE dispatch via ``jax.lax.scan`` — each inner step writes the
  previous token's K/V through the block table, attends, samples one
  token per lane (per-lane PRNG keys, see below), advances per-lane
  context lengths on-device, and feeds the token back as the next
  query. A per-lane active mask freezes lanes that hit EOS or their
  ``max_new_tokens`` budget mid-scan: frozen lanes stop writing
  (``write_start`` pushes their scatter out of the valid range) and
  emit a ``-1`` sentinel. The program returns ``[max_batch, K]`` tokens
  (``-1`` sentinels past each lane's emitted prefix), and the host
  fetch is DEFERRED: the next tick's admission and prefill work is
  dispatched before the host blocks on the in-flight decode, so
  scheduler overhead overlaps device compute. ``K == 1`` runs the same
  single-token computation and scheduling cadence as the pre-multistep
  engine (greedy outputs are unchanged; sampled draws come from the
  rekeyed per-request scheme below, which intentionally replaced the
  old step-counter keys at every K). Non-decoding lanes (empty, or
  still prefilling) ride along masked (their table rows point out of
  bounds, so their writes drop and their outputs are ignored).
- ``cow copy`` (rare): one block duplicated when a sequence would
  append into a block it shares with another sequence — compiled
  lazily, only if copy-on-write ever triggers.

**Speculative decoding** (``spec_tokens > 0``, docs/serving.md) swaps
the decode program — same slot in the contract, still exactly one
compilation — for draft-and-verify: a host-side drafter
(:mod:`~apex_tpu.serving.drafter`, prompt-lookup by default) proposes
up to ``spec_tokens`` continuation tokens per lane each decode phase,
and ONE ``[max_batch, spec_tokens + 1]`` target forward scores every
candidate position through the multi-query paged-prefill path, accepts
a per-lane prefix on-device (the Leviathan et al. rejection rule,
:func:`~apex_tpu.serving.sampling.spec_verify_tokens`), and emits
``1..spec_tokens + 1`` tokens per dispatch under the same ``-1``
sentinel/stop-mask conventions — the deferred-drain contract below is
untouched, the host just advances each lane by its own emitted count.
Blocks are reserved for the worst case (every proposal written) and
the drain returns what rejection stranded
(:meth:`~apex_tpu.serving.kv_cache.BlockAllocator.trim_to`). Greedy
output is bit-identical to non-speculative greedy; a crashing drafter
is quarantined and the engine degrades to non-speculative decoding.

Everything that varies between steps — which slots are live, block
tables, chunk offsets, context lengths, sampling knobs — varies as
*array values*, so XLA compiles one program per shape for the lifetime
of the engine (``stats()["prefill_compilations"] == 1`` and likewise
for decode; the acceptance tests pin this). The block table and the
per-lane sampling/EOS/key arrays are **dirty-tracked device-resident
mirrors** (:class:`~apex_tpu.serving.kv_cache.DeviceMirror`):
re-uploaded when the slot composition or a table row changes, reused
untouched on the steady-state tick.

Sampling determinism is **schedule-invariant**: every request owns a
PRNG key (the engine seed folded with the request's arrival index),
and its ``j``-th generated token is drawn with
``fold_in(request_key, j)`` — on-device, the scan folds the running
per-lane generated-count into the lane's key each iteration. Outputs
are therefore bit-for-bit identical for any ``decode_steps``, any lane
placement, and any preemption/resume schedule (tested).

Scheduling (host-side, between jitted dispatches), per ``step()``:

1. **Admission** fills free decode slots from the FIFO waiting queue
   on *current* need, not worst case: the prompt's uncached tail blocks
   plus one must fit in the pool (free + evictable). With prefix
   caching enabled, the longest block-aligned cached prefix is matched
   by content hash and shared (refcounted) instead of recomputed.
2. **One prefill chunk** runs for the oldest admitted request still
   mid-prompt — at most one chunk per step ahead of the decode
   dispatch, so decode slots keep streaming tokens while a long prompt
   loads (stall-free batching).
3. **Drain** the PREVIOUS tick's decode dispatch (the deferred sync):
   fetch its ``[B, K]`` tokens + counts, append K/V bookkeeping,
   register newly-full blocks, finish/evict satisfied requests, then
   top up admissions into any lanes that just freed.
4. **Decode** dispatches the next fused K-step scan for every started
   slot. When a K-step block reservation fails, the YOUNGEST slot is
   preempted: its references are released and the request re-queued at
   the front carrying its already-generated tokens — on re-admission
   it re-prefills ``prompt + generated[:-1]`` (cheap under prefix
   caching: its own blocks are usually still cached) and continues, so
   emitted tokens are never resampled and per-request output is
   deterministic. Preemption granularity is K tokens: a preempted lane
   loses at most the current dispatch's unconsumed reservation, never
   an emitted token.

Finished requests *release references* instead of freeing: with prefix
caching on, their full blocks stay indexed and evictable (LRU) until
the pool actually needs the space.

**Robustness** (docs/robustness.md): every jitted dispatch runs under a
fault-gated, bounded-backoff retry (``max_dispatch_retries``); a
request whose dispatch keeps failing is *quarantined* — failed with
terminal status instead of killing the engine. Requests carry optional
wall-clock deadlines (``Request.deadline_s``) and expire gracefully
with status ``"timeout"`` and the tokens they emitted.
``snapshot()``/``restore()`` round-trip the complete host-side picture
through JSON: a restored engine re-prefills its live requests (cheap
under prefix caching) and — because sampling is schedule-invariant —
continues bit-identically to the uninterrupted run. ``run()`` raises a
diagnostic :class:`EngineStalledError` instead of spinning if a full
step ever makes no progress while work remains.

**Overload protection** (docs/robustness.md): faults are one failure
mode; too much *legitimate* traffic is the other. The waiting queue is
bounded (``max_waiting``; ``add_request`` raises
:class:`QueueFullError`, ``try_add`` returns ``False`` — explicit
backpressure instead of unbounded memory growth), requests carry an
integer ``priority`` class (0 = most urgent; admission and preemption
order by ``(priority, age)``, and uniform-priority traffic schedules
bit-identically to the pre-priority FIFO), an **admit-time feasibility
gate** sheds requests whose deadline cannot cover even a
contention-free service estimate (status ``"rejected"``, fed by cheap
EWMAs of observed per-dispatch wall time) before they burn pool blocks
they would time out of, and a **degradation ladder** steps the engine
down deterministically under sustained pressure (free-block /
queue-depth watermarks with hysteresis): suspend speculative decoding,
flush the prefix cache aggressively, pause admission of the lowest
priority class — and back up when pressure clears, every transition
counted in ``stats()`` and serialized through snapshot/restore.

**Multi-tenant isolation** (docs/robustness.md): overload protection
treats traffic as one cooperating client; real traffic is mutually
untrusting tenants. Every request carries a ``tenant`` id: admission
WITHIN a priority class is weighted deficit-round-robin across tenants
(strict priority between classes is kept — the documented contract),
per-tenant quotas (:class:`TenantQuota`: waiting entries, fractional
resident-block charge, token rate) shed over-quota submissions with
terminal status ``"throttled"`` before they burn pool blocks, and the
allocator attributes every block reference — shared prefix blocks
fractionally by refcount — so flushes and evictions charge the tenant
that parked them. Two client-lifecycle primitives ride the tenant
ledger: :meth:`InferenceEngine.abort` (cancellation with full
resource reclamation, status ``"cancelled"``) and
:meth:`InferenceEngine.pop_stream_events` (streaming ``(uid, token,
is_last)`` delivery; a disconnect callback maps onto ``abort``).
Tenancy is pure scheduling: sampling stays arrival-keyed, so outputs
are invariant to tenant assignment, and uniform-tenant traffic is
bit-identical to the pre-tenancy engine.

**Observability** (docs/observability.md): pass an
:class:`~apex_tpu.observability.Observability` via ``obs=`` and the
engine narrates itself — per-request span timelines (Perfetto
exportable), a flight-recorder ring of tick/ladder/quarantine/retry
events whose tail rides :class:`EngineStalledError` and the crash-dump
file, and latency histograms (TTFT, inter-token, dispatch service,
queue wait) with Prometheus exposition, merged by ``stats(deep=True)``.
The contract is ZERO perturbation: observers consume events through the
engine's injectable ``_clock`` and never feed a decision, so outputs
with observability attached are bit-identical to without (tested across
greedy/sampled x speculative/not x preemption x snapshot/restore).
Observer state is excluded from the snapshot fingerprint; recorder and
trace tails ride ``snapshot()`` only as an audit section ``restore()``
never reloads.

**Memory tiers** (docs/serving.md): KV memory bounds concurrent
users, so the cache is tiered. ``kv_quantization`` stores int8/fp8
block payloads with per-row scales (quantize inside the jitted write,
dequantize inside the attention read; position-keyed stochastic
rounding keeps every determinism contract, and a quantized block
charges the tenant ledger its reduced byte footprint).
``spill_max_bytes`` adds a bounded host-RAM spill tier: LRU-evicted
and ladder-flushed prefix blocks copy to a host store keyed by their
chain hash and re-admit by device upload instead of recompute —
token-identical, audit-only in snapshots. The read chain itself can
run as one fused Pallas kernel (``APEX_PAGED_ATTENTION_PALLAS=1``,
read side only, fp path bit-identical to the XLA chain).

**Mesh sharding** (docs/serving.md): ``mesh_shape`` promotes the
engine from single-device to mesh-native over a logical
``("batch", "model")`` GSPMD mesh (:mod:`apex_tpu.serving.mesh`) —
the KV pools (payloads AND quantized scales) and the model's
qkv/proj/mlp weights shard their head axis over ``model`` via
:class:`~jax.sharding.NamedSharding` annotations, and the same three
jitted programs compile once under the mesh with the collectives
jit-inserted (``audit_collectives`` pins the program-shape contract:
zero collectives at a 1-sized model axis, all-reduce traffic once
heads split). Everything host-side — admission, DRR, quotas, the
ladder, drafters, snapshot/spill/integrity — is mesh-agnostic (block
ids and chain hashes are layout-independent), so prefix caching, the
spill tier, and fleet migration work unchanged at any shape. Mesh
``(1, 1)``, the default, is certified bit-identical to the pre-mesh
engine; ``mesh_shape`` is part of the restore-fingerprint identity
set.
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.utils.faults import (
    TRANSIENT_ERRORS,
    DispatchFailedError,
    SimulatedCrash,
    guarded_call,
    perturb_json,
    perturb_payload,
    perturb_tokens,
)
from apex_tpu.utils.integrity import (
    IntegrityError,
    payload_checksum,
    seal_record,
    verify_payload,
    verify_record,
)

from apex_tpu.serving.kv_cache import (
    DEFAULT_TENANT,
    KV_QUANT_MODES,
    BlockAllocator,
    CacheOutOfBlocks,
    DeviceMirror,
    HostSpillStore,
    KVCache,
    blocks_needed,
    copy_block,
    device_block_table,
    hash_block_tokens,
    kv_block_bytes,
    seq_block_hashes,
)
from apex_tpu.models.gpt import (
    WEIGHT_QUANT_MODES,
    gpt_param_bytes,
    quantize_gpt_model,
)
from apex_tpu.serving import mesh as mesh_lib
from apex_tpu.serving.drafter import NgramDrafter
from apex_tpu.serving.sampling import (
    SamplingParams,
    sample_tokens,
    sample_tokens_per_lane,
    spec_verify_tokens,
)

# new-observation weight of the per-dispatch wall-time EWMAs feeding
# the admit-time feasibility gate
_EWMA_ALPHA = 0.25
# degradation-ladder rungs (cumulative): 1 = speculation suspended,
# 2 = + prefix cache flushed every tick, 3 = + lowest-class admission
# paused
_LADDER_TOP = 3
# while the dynamic speculation cap (spec_adapt) sits at 0, every Nth
# decode phase runs a 1-token probe so a recovered drafter can earn
# its cap back (a capped-out engine otherwise never observes
# acceptance again and stays degraded forever)
_SPEC_PROBE_EVERY = 16
# the FaultPlan sites where "corrupt" specs perturb a serialized host
# artifact (docs/robustness.md, "Data integrity"): the spill tier's
# write/read paths, the periodic checkpoint, and migration records on
# the way out / in. Corruption-only — see the construction check.
_INTEGRITY_SITES = ("spill_put", "spill_get", "checkpoint",
                    "export", "import")


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Per-tenant resource bounds (``EngineConfig.tenant_quotas``), all
    optional — ``None`` leaves that axis unbounded. Enforcement points
    (docs/robustness.md, isolation):

    - ``max_waiting``: entries the tenant may hold in the waiting queue
      at once; the door sheds past it with terminal status
      ``"throttled"`` (:class:`TenantThrottledError`; ``try_add``
      returns False).
    - ``max_resident_blocks``: the tenant's fractional resident-block
      charge ceiling (:meth:`~apex_tpu.serving.kv_cache.BlockAllocator.
      tenant_charge` — shared prefix blocks charge fractionally by
      refcount). A request whose worst-case private footprint exceeds
      it is shed ``"throttled"`` at the door (it could never run);
      admission skips an over-charge tenant's queue (other tenants
      flow past); decode-time growth past the cap preempts the
      tenant's OWN lowest-class/youngest other lane, never a
      different tenant's.
    - ``tokens_per_s``: token-rate budget, enforced at the door
      against an exponentially-decayed per-tenant rate estimator
      (``tenant_rate_tau_s``); over-rate submissions shed
      ``"throttled"`` before touching the queue or the pool.
    """

    max_waiting: Optional[int] = None
    max_resident_blocks: Optional[int] = None
    tokens_per_s: Optional[float] = None

    def validate(self, tenant: str) -> None:
        if self.max_waiting is not None and self.max_waiting < 1:
            raise ValueError(
                f"tenant {tenant!r}: max_waiting must be >= 1 (or None), "
                f"got {self.max_waiting}")
        if (self.max_resident_blocks is not None
                and self.max_resident_blocks < 1):
            raise ValueError(
                f"tenant {tenant!r}: max_resident_blocks must be >= 1 "
                f"(or None), got {self.max_resident_blocks}")
        if self.tokens_per_s is not None and self.tokens_per_s <= 0:
            raise ValueError(
                f"tenant {tenant!r}: tokens_per_s must be > 0 (or "
                f"None), got {self.tokens_per_s}")


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. ``prompt`` is a token-id sequence;
    generation runs until EOS (if ``eos_token_id`` is set) or
    ``max_new_tokens``, whichever comes first — or until the request
    leaves the engine early: past its ``deadline_s`` TTL (status
    ``"timeout"``) or quarantined after repeated dispatch failures
    (status ``"failed"``). Early exits are graceful: tokens already
    emitted are returned."""

    uid: str
    prompt: Sequence[int]
    max_new_tokens: int = 16
    sampling: SamplingParams = SamplingParams()
    eos_token_id: Optional[int] = None
    # Wall-clock TTL in seconds from add_request, measured against the
    # engine's clock (injectable for tests). None = no deadline.
    deadline_s: Optional[float] = None
    # Priority class, 0 = most urgent. Admission considers classes in
    # ascending value (FIFO within a class) and preemption/quarantine
    # yield the lowest class first (then youngest). A pure SCHEDULING
    # knob: sampling is arrival-keyed, so per-request outputs are
    # identical under any priority assignment (tested), and
    # uniform-priority traffic is bit-identical to the pre-priority
    # FIFO scheduler.
    priority: int = 0
    # The submitting tenant: admission WITHIN a priority class is
    # weighted deficit-round-robin across tenants (strict priority
    # between classes is unchanged), and per-tenant quotas
    # (EngineConfig.tenant_quotas) are enforced against this id. A
    # pure SCHEDULING/ADMISSION label like priority: sampling is
    # arrival-keyed, so per-request outputs are identical under any
    # tenant assignment (tested), and uniform-tenant traffic is
    # bit-identical to the pre-tenancy engine.
    tenant: str = DEFAULT_TENANT
    # Terminal lifecycle status — "finished" | "timeout" | "failed" |
    # "rejected" | "throttled" | "cancelled" — written by the engine
    # via object.__setattr__ when the request leaves it (the one
    # engine-owned field of the frozen request); None while
    # waiting/active. Excluded from equality/hash.
    status: Optional[str] = dataclasses.field(default=None, compare=False)


@dataclasses.dataclass(frozen=True)
class RequestResult:
    """One entry of ``run(return_status=True)``: the generated tokens
    plus the request's terminal status (the result contract in
    docs/serving.md). ``tokens`` may be shorter than ``max_new_tokens``
    for ``"timeout"``/``"failed"``/``"rejected"``/``"throttled"``/
    ``"cancelled"`` exits — everything emitted before the cut is
    preserved."""

    tokens: List[int]
    status: str


class QueueFullError(RuntimeError):
    """``add_request`` refused: the waiting queue already holds
    ``EngineConfig.max_waiting`` entries. The explicit backpressure
    signal — callers shed, retry later, or route to another replica
    instead of growing an unbounded queue that will only manufacture
    timeouts. ``try_add`` is the non-raising variant."""


class TenantThrottledError(RuntimeError):
    """``add_request`` refused by the submitting TENANT's quota
    (:class:`TenantQuota`): its waiting-entry cap, its resident-block
    ceiling (a request that could never fit it), or its token-rate
    budget. Unlike the engine-wide :class:`QueueFullError` door shed,
    a throttled request DOES get a terminal verdict — status
    ``"throttled"``, zero tokens, drained by ``run()`` — because the
    shed is the tenant's own doing, not global load, and the tenant's
    ledger must show it. ``try_add`` returns False for this too."""


class EngineStalledError(RuntimeError):
    """``has_work`` is true but a full ``step()`` made no progress —
    no admission, prefill chunk, decode dispatch, drain, expiry,
    preemption, or quarantine. The scheduler would spin forever;
    ``engine_stats`` carries ``stats()`` at the stall for diagnosis;
    ``recorder_tail`` the flight recorder's last events when an
    :class:`~apex_tpu.observability.Observability` was attached (None
    otherwise) — the stall ships its own post-mortem."""

    def __init__(self, message: str, stats: Dict[str, object],
                 recorder_tail=None):
        super().__init__(f"{message} (stats: {stats})")
        self.engine_stats = stats
        self.recorder_tail = recorder_tail


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_batch: int = 8            # decode slots
    block_size: int = 16
    num_blocks: int = 256         # pool size (per layer)
    max_prefill_len: int = 64     # default prefill chunk (see below)
    max_seq_len: int = 256        # prompt + generation cap per sequence
    # THE prefill shape: prompts are prefilled in [1, prefill_chunk]
    # pieces, so prompts up to max_seq_len are admissible regardless of
    # the chunk. None inherits max_prefill_len (the pre-chunking shape,
    # keeping existing configs' compiled footprint identical).
    prefill_chunk: Optional[int] = None
    # Multi-step fused decode: each decode dispatch runs this many
    # scanned iterations on-device, amortizing one scheduler tick (host
    # table/array work + dispatch + fetch) over K generated tokens.
    # Outputs are bit-identical for any K (per-request, per-token PRNG
    # keys); K trades per-token latency (tokens surface K at a time)
    # for throughput, and makes K tokens the preemption granularity.
    # 1 keeps the pre-multistep single-token cadence (sampled draws
    # use the rekeyed per-request scheme at every K, including 1).
    decode_steps: int = 1
    # Share identical block-aligned prompt prefixes through the
    # allocator's content-hash index; finished requests' blocks stay
    # cached (LRU-evictable) instead of freed. Off by default: caching
    # retains pool blocks after a request finishes, which changes
    # utilization accounting workloads may assert on.
    enable_prefix_caching: bool = False
    kv_dtype: Optional[object] = None   # None = follow the amp policy
    # Quantized block storage (docs/serving.md memory tiers): "int8"
    # (symmetric int8, stochastic-rounded) or "fp8" (float8_e4m3,
    # where the backend has it) K/V payloads with per-row fp32 scales
    # carried block-wise; dequantization happens inside the attention
    # read. None (default) keeps full-precision storage — bit-identical
    # to the pre-quantization engine. Quantized outputs are tolerance-
    # certified against the fp path, not bit-equal to it; the
    # quantized path is itself fully deterministic (position-keyed
    # rounding), so preemption/resume/snapshot bit-identity holds
    # WITHIN a storage mode. A quantized block charges the tenant
    # ledger its reduced byte footprint (the allocator's block_weight).
    kv_quantization: Optional[str] = None
    # Quantized WEIGHT storage (docs/serving.md memory tiers): "int8"
    # or "fp8" re-expresses the GPT qkv/proj/mlp kernels as int8/fp8
    # with per-output-channel fp32 scales at engine construction
    # (models/gpt.quantize_gpt_model — deterministic round-to-nearest,
    # weights are static) and routes those matmuls through the
    # dequant-GEMM read path (apex_tpu.ops.dequant_gemm; the fused
    # Pallas kernel opts in via APEX_DEQUANT_GEMM_PALLAS, single-
    # device meshes only). Quantized logits are tolerance-certified
    # against the fp path, greedy decode token-identical at the
    # certified tolerance; within a mode the engine stays fully
    # deterministic. IDENTITY, not operational: like kv_quantization,
    # the mode joins the restore fingerprint and the process-replica
    # params-checksum handshake — snapshots restore across EQUAL
    # storage modes only, and a replica booted with a mismatched mode
    # is refused. Composes with kv_quantization (weights) x (KV pool)
    # and with the model-axis mesh (scale leaves shard with their
    # kernels — gpt_param_pspec).
    weight_quantization: Optional[str] = None
    # Host-RAM spill tier for the prefix cache (docs/serving.md):
    # LRU-evicted and ladder-flushed prefix blocks are copied to a
    # bounded host store (this many payload bytes) keyed by their
    # chain hash, and a later prefix match re-admits them by device
    # upload instead of recompute. Requires enable_prefix_caching
    # (the tier is keyed by the prefix index's hashes). None = off.
    # Operational, not identity: spill state is audit-only in
    # snapshots and the knob stays out of the restore fingerprint —
    # a re-admitted block is certified token-identical to recompute.
    spill_max_bytes: Optional[int] = None
    # -- pod-scale serving (docs/serving.md, "Mesh sharding") ----------
    # The logical ("batch", "model") GSPMD device mesh the engine's
    # programs compile under (apex_tpu.serving.mesh): the KV pools and
    # the model's qkv/proj/mlp weights shard their HEAD axis over
    # "model" via NamedSharding annotations and jax.jit inserts the
    # collectives — the host-side machinery (admission, DRR, quotas,
    # ladder, drafters, snapshot/spill/integrity) is mesh-agnostic.
    # (1, 1) — the default — is certified bit-identical to the
    # pre-mesh engine (outputs, statuses, full stats()), and the
    # model-axis size must divide the model's num_heads (checked at
    # engine construction, where the model is known). IDENTITY, not
    # operational: mesh_shape stays in the restore fingerprint —
    # sharded snapshots restore across EQUAL meshes only (the records
    # themselves are host-side and layout-free).
    mesh_shape: Tuple[int, int] = (1, 1)
    # Donate the cache pool to the jitted steps so XLA updates it in
    # place instead of materializing a second pool + copy per step
    # (double peak HBM and a full-pool write otherwise). Default off:
    # the axon TPU runtime rejects donated buffers at run time (see
    # bench.py's --donate probe history) and older CPU jaxlibs ignore
    # donation with a warning; flip on for runtimes that support it.
    donate_cache: bool = False
    # Robustness knobs (docs/robustness.md): a failed prefill/decode
    # dispatch is retried up to max_dispatch_retries times with
    # exponential backoff (retry_backoff_s * 2**attempt seconds between
    # attempts; 0 = immediate, the test default) before the offending
    # request is quarantined with terminal status "failed".
    max_dispatch_retries: int = 2
    retry_backoff_s: float = 0.0
    # Speculative decoding (docs/serving.md): > 0 swaps the K-step
    # decode scan for draft-and-verify — a host-side drafter proposes
    # up to spec_tokens continuation tokens per lane, and ONE target
    # forward over [max_batch, spec_tokens + 1] scores every candidate
    # position, accepts a prefix on-device (rejection rule in
    # sampling.spec_verify_tokens), and emits 1..spec_tokens + 1 tokens
    # per dispatch. Greedy output is bit-identical to non-speculative
    # greedy; sampled output is exactly distribution-preserving (its
    # realized draws depend on span boundaries — docs/serving.md).
    # decode_steps is ignored while speculation is on: the verify
    # forward IS the dispatch, there is no scan to fuse.
    spec_tokens: int = 0
    # -- overload protection (docs/robustness.md) ----------------------
    # Bound on the waiting queue: add_request past it raises
    # QueueFullError (try_add returns False) — explicit backpressure
    # instead of unbounded memory growth. None = unbounded (the
    # pre-overload behavior). Preemption/recovery requeues of already-
    # resident requests bypass the bound (at most max_batch extra).
    max_waiting: Optional[int] = None
    # Degradation-ladder watermarks: pressure is queue depth >=
    # queue_high_watermark OR allocatable fraction ((num_free +
    # num_cached) / num_blocks — evictable counts as headroom, or a
    # warm prefix cache would read as overload and sawtooth the
    # ladder) <= free_block_low_watermark. After degrade_patience
    # CONSECUTIVE
    # pressure ticks the engine steps one rung down; after the same
    # number of consecutive clear ticks, one rung up (the hysteresis).
    # Rungs, cumulative: 1 = suspend speculative decoding, 2 = flush
    # the prefix cache every tick, 3 = pause admission of priority
    # classes >= degrade_admit_priority (unless the engine is otherwise
    # idle — an idle engine serves whatever it has). Both watermarks
    # None = ladder off (default).
    queue_high_watermark: Optional[int] = None
    free_block_low_watermark: Optional[float] = None
    degrade_patience: int = 2
    degrade_admit_priority: int = 1
    # -- multi-tenant isolation (docs/robustness.md) -------------------
    # DRR weight per tenant id (>= 1; unlisted tenants weigh 1): each
    # visit of the admission walk credits a tenant weight * drr_quantum
    # deficit "tokens" (a request costs its committed budget,
    # len(prompt) + max_new_tokens, charged ONCE — preemption requeues
    # and restores re-admit free), so a weight-3 tenant admits ~3x the
    # token volume of a weight-1 tenant under contention. None = every
    # tenant weighs 1. Pure scheduling: sampling is arrival-keyed, so
    # outputs are invariant to weights, and single-tenant traffic is
    # bit-identical to the pre-tenancy engine at ANY weight.
    tenant_weights: Optional[Mapping[str, int]] = None
    # Per-tenant resource bounds (TenantQuota); unlisted tenants are
    # unbounded. None = no quotas (the pre-tenancy behavior).
    tenant_quotas: Optional[Mapping[str, "TenantQuota"]] = None
    # The DRR credit per walk visit, in committed-budget tokens.
    # Smaller = finer-grained interleaving across tenants; larger =
    # longer per-tenant admission bursts. Irrelevant with one tenant.
    drr_quantum: int = 64
    # Time constant (seconds) of the per-tenant token-rate estimator
    # feeding TenantQuota.tokens_per_s: the observed rate decays as
    # exp(-dt / tau), and each delivered token adds 1/tau — a larger
    # tau forgives longer bursts around the same average rate.
    tenant_rate_tau_s: float = 1.0
    # -- dynamic speculation (docs/serving.md) -------------------------
    # Adapt the per-plan draft cap to the observed acceptance rate: an
    # EWMA of per-dispatch acceptance shrinks the cap by one (toward 0
    # = speculation off, riding the ladder's rung-1 empty-plan
    # machinery) whenever it sits below spec_accept_low, and restores
    # it by one (toward spec_tokens) above spec_accept_high — the
    # [low, high] dead band is the hysteresis. While the cap is 0, a
    # 1-token probe runs every 16th decode phase so recovery is
    # possible. Requires spec_tokens > 0. When acceptance stays at or
    # above spec_accept_high, the cap never moves and the engine is
    # bit-identical to static speculation (tested).
    spec_adapt: bool = False
    spec_accept_low: float = 0.5
    spec_accept_high: float = 0.8
    # -- fleet serving (docs/fleet.md) ---------------------------------
    # Periodic lightweight checkpointing: every N scheduler ticks the
    # engine refreshes ``last_checkpoint`` with :meth:`checkpoint` — a
    # snapshot-format host picture taken WITHOUT draining the in-flight
    # decode dispatch (no host sync, unlike snapshot()), so a fleet
    # router holds a bounded-staleness failover picture at near-zero
    # steady-state cost. Tokens emitted after the checkpoint are
    # re-derived bit-identically on restore (resume determinism: the
    # records carry prompt + generated-so-far + the arrival PRNG
    # identity). None = off (the default; snapshot() is unchanged).
    # Operational, not identity: excluded from the restore fingerprint
    # like the retry/overload knobs.
    snapshot_interval_ticks: Optional[int] = None
    # -- data integrity (docs/robustness.md, "Data integrity") ---------
    # Verify the SHA-256 content checksums every serialized host
    # artifact carries — spilled KV blocks at re-admission, migration
    # records at import, snapshots/checkpoints at restore, transported
    # KV payloads at spill-tier seeding — at the point of consumption.
    # A mismatch routes through the artifact's existing degradation
    # path (a corrupt spill entry is a miss served by recompute, a
    # corrupt migration import is refused with IntegrityError, a
    # corrupt snapshot refuses to restore); checksum-less LEGACY
    # artifacts always load (detection covers sealed artifacts only).
    # On clean artifacts verification changes nothing — outputs and
    # schedule counters are bit-identical with it on or off (tested) —
    # and False skips both the checksumming and the checks, the
    # byte-identical pre-integrity path. Operational, not identity:
    # excluded from the restore fingerprint.
    verify_artifacts: bool = True
    # Budgeted background scrubbing: every N scheduler ticks the engine
    # re-verifies scrub_spill_blocks spill-tier entries against their
    # put-time checksums (round-robin, corrupt entries discarded and
    # counted) and runs one full allocator/ledger check_integrity
    # audit — rot is found while recompute is still cheap, and a
    # silently-corrupted ledger fails loudly instead of mis-charging
    # forever. None = off (the default). Scrub state is operational:
    # counters ride stats(), the spill cursor rides the audit-only
    # spill snapshot section, and both knobs stay out of the restore
    # fingerprint.
    scrub_interval_ticks: Optional[int] = None
    scrub_spill_blocks: int = 4
    seed: int = 0

    def __post_init__(self):
        # construction-time validation: a bad geometry knob used to
        # surface as a shape error deep inside the first dispatch —
        # fail here, with the knob's name, instead
        for name in ("max_batch", "block_size", "num_blocks",
                     "max_seq_len", "max_prefill_len"):
            v = getattr(self, name)
            if v < 1:
                raise ValueError(f"{name} must be >= 1, got {v}")
        chunk = (self.prefill_chunk if self.prefill_chunk is not None
                 else self.max_prefill_len)
        if chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {chunk}")
        if chunk > self.max_seq_len:
            raise ValueError(
                f"prefill_chunk ({chunk}) exceeds max_seq_len "
                f"({self.max_seq_len})")
        if self.decode_steps < 1:
            raise ValueError(
                f"decode_steps must be >= 1, got {self.decode_steps}")
        if self.kv_quantization not in KV_QUANT_MODES:
            raise ValueError(
                f"kv_quantization must be one of {KV_QUANT_MODES}, "
                f"got {self.kv_quantization!r}")
        if self.weight_quantization not in WEIGHT_QUANT_MODES:
            raise ValueError(
                f"weight_quantization must be one of "
                f"{WEIGHT_QUANT_MODES}, got {self.weight_quantization!r}")
        # normalize (a caller's list restores as the identical
        # fingerprint value) and validate the mesh geometry against the
        # backend, including the batch axis's lane/pool divisibility
        # (a non-dividing split has no equal shard layout); the
        # num_heads divisibility half runs at engine construction,
        # where the model is known
        object.__setattr__(self, "mesh_shape",
                           mesh_lib.validate_mesh_shape(
                               self.mesh_shape,
                               max_batch=self.max_batch,
                               num_blocks=self.num_blocks))
        if self.spill_max_bytes is not None:
            if self.spill_max_bytes < 1:
                raise ValueError(
                    f"spill_max_bytes must be >= 1 (or None for no "
                    f"spill tier), got {self.spill_max_bytes}")
            if not self.enable_prefix_caching:
                raise ValueError(
                    "spill_max_bytes requires enable_prefix_caching: "
                    "the spill tier is keyed by the prefix index's "
                    "hash chains, and nothing registers without it")
        if self.spec_tokens < 0:
            raise ValueError(
                f"spec_tokens must be >= 0, got {self.spec_tokens}")
        if self.max_dispatch_retries < 0:
            raise ValueError(
                f"max_dispatch_retries must be >= 0, got "
                f"{self.max_dispatch_retries}")
        if self.max_waiting is not None and self.max_waiting < 1:
            raise ValueError(
                f"max_waiting must be >= 1 (or None for unbounded), "
                f"got {self.max_waiting}")
        if (self.queue_high_watermark is not None
                and self.queue_high_watermark < 1):
            raise ValueError(
                f"queue_high_watermark must be >= 1, got "
                f"{self.queue_high_watermark}")
        if (self.queue_high_watermark is not None
                and self.max_waiting is not None
                and self.queue_high_watermark
                > self.max_waiting + self.max_batch):
            # client adds cap the queue at max_waiting and requeues
            # overshoot by at most max_batch: a higher watermark is
            # unreachable and the ladder's queue signal silently inert
            raise ValueError(
                f"queue_high_watermark ({self.queue_high_watermark}) is "
                f"unreachable: the queue never exceeds max_waiting + "
                f"max_batch ({self.max_waiting} + {self.max_batch})")
        if (self.free_block_low_watermark is not None
                and not 0.0 < self.free_block_low_watermark <= 1.0):
            raise ValueError(
                f"free_block_low_watermark must be in (0, 1], got "
                f"{self.free_block_low_watermark}")
        if self.degrade_patience < 1:
            raise ValueError(
                f"degrade_patience must be >= 1, got "
                f"{self.degrade_patience}")
        if self.degrade_admit_priority < 1:
            raise ValueError(
                f"degrade_admit_priority must be >= 1 (0 would pause "
                f"every class), got {self.degrade_admit_priority}")
        if self.tenant_weights is not None:
            for t, w in self.tenant_weights.items():
                if int(w) < 1:
                    raise ValueError(
                        f"tenant_weights[{t!r}] must be >= 1, got {w}")
        if self.tenant_quotas is not None:
            for t, q in self.tenant_quotas.items():
                if not isinstance(q, TenantQuota):
                    raise ValueError(
                        f"tenant_quotas[{t!r}] must be a TenantQuota, "
                        f"got {type(q).__name__}")
                q.validate(t)
        if self.drr_quantum < 1:
            raise ValueError(
                f"drr_quantum must be >= 1, got {self.drr_quantum}")
        if self.tenant_rate_tau_s <= 0:
            raise ValueError(
                f"tenant_rate_tau_s must be > 0, got "
                f"{self.tenant_rate_tau_s}")
        if (self.snapshot_interval_ticks is not None
                and self.snapshot_interval_ticks < 1):
            raise ValueError(
                f"snapshot_interval_ticks must be >= 1 (or None for no "
                f"periodic checkpointing), got "
                f"{self.snapshot_interval_ticks}")
        if (self.scrub_interval_ticks is not None
                and self.scrub_interval_ticks < 1):
            raise ValueError(
                f"scrub_interval_ticks must be >= 1 (or None for no "
                f"background scrubbing), got {self.scrub_interval_ticks}")
        if self.scrub_spill_blocks < 1:
            raise ValueError(
                f"scrub_spill_blocks must be >= 1, got "
                f"{self.scrub_spill_blocks}")
        if self.spec_adapt and self.spec_tokens < 1:
            raise ValueError(
                "spec_adapt requires spec_tokens >= 1 (there is no "
                "draft cap to adapt at spec_tokens == 0)")
        if not 0.0 <= self.spec_accept_low <= self.spec_accept_high <= 1.0:
            raise ValueError(
                f"spec acceptance thresholds must satisfy 0 <= low <= "
                f"high <= 1, got low={self.spec_accept_low} "
                f"high={self.spec_accept_high}")


@dataclasses.dataclass
class _QueueEntry:
    """A waiting (or preempted-and-requeued) request. ``generated``
    carries tokens already emitted before a preemption so they are
    never resampled — re-admission re-prefills ``prompt +
    generated[:-1]`` and resumes decoding from ``generated[-1]``.
    ``arrival`` is the request's add_request order: it seeds the
    request's PRNG key, so it must survive preemption unchanged (the
    resumed request continues the SAME key sequence at the next token
    index). ``hashes`` memoizes the prefill sequence's block hash chain
    (the sequence is frozen per entry), so a head blocked on pool
    pressure is not re-hashed on every scheduler tick. ``enq_t`` /
    ``enq_tick`` stamp when the entry (re-)entered the queue — the
    queue-wait observability in ``stats()`` (a preempted requeue
    restarts the wait)."""

    request: Request
    arrival: int = 0
    generated: List[int] = dataclasses.field(default_factory=list)
    hashes: Optional[List[str]] = None
    enq_t: float = 0.0
    enq_tick: int = 0
    # whether the entry's DRR cost (the committed token budget) was
    # already charged against its tenant's deficit: admission charges
    # exactly once, so preemption/crash-recovery requeues and restored
    # residents re-admit FREE and ahead of uncharged work (the old
    # front-of-the-class requeue discipline, tenant-aware)
    drr_charged: bool = False


class _ClassQueue:
    """One priority class of the waiting queue: per-tenant FIFO
    :class:`deque`\\ s plus the class's DRR walk state. ``ring`` lists
    the tenants with non-empty deques in first-enqueue order;
    ``cursor`` is the walk's current ring position, ``credited``
    whether the cursor tenant has received its quantum for the current
    visit, ``deficits`` the per-tenant leftover credit. A tenant whose
    deque drains leaves the ring and forfeits its deficit (standard
    DRR — credit never accumulates while you have nothing queued)."""

    __slots__ = ("queues", "ring", "cursor", "credited", "deficits")

    def __init__(self):
        self.queues: Dict[str, deque] = {}
        self.ring: List[str] = []
        self.cursor: int = 0
        self.credited: bool = False
        self.deficits: Dict[str, float] = {}

    def remove_tenant(self, tenant: str) -> None:
        i = self.ring.index(tenant)
        self.ring.pop(i)
        del self.queues[tenant]
        self.deficits.pop(tenant, None)
        if not self.ring:
            self.cursor, self.credited = 0, False
            return
        if i < self.cursor:
            self.cursor -= 1
        elif i == self.cursor:
            # the cursor now points at the NEXT tenant — a fresh visit
            self.credited = False
            if self.cursor >= len(self.ring):
                self.cursor = 0


class _WaitingQueue:
    """The waiting queue: strict priority BETWEEN classes (scanned in
    ascending class value, 0 = most urgent — the documented PR 8
    contract), weighted deficit-round-robin across TENANTS within each
    class (:class:`_ClassQueue`). ``append`` enqueues at the tail of
    the request's (class, tenant) FIFO, ``appendleft`` (preemption /
    crash-recovery requeues) at its head. Entries whose DRR cost was
    already charged (``drr_charged`` — requeues, restored residents)
    are served OUT OF BAND ahead of the walk, leaving the walk state
    untouched: with a single tenant this collapses to exactly the old
    per-class FIFO + front-requeue discipline, bit-for-bit. Iteration
    order (also the snapshot serialization order) is class by class,
    ring order within, FIFO within a tenant."""

    def __init__(self, weights: Optional[Mapping[str, int]] = None,
                 quantum: int = 64):
        self._classes: Dict[int, _ClassQueue] = {}
        self._weights = dict(weights or {})
        self._quantum = max(1, int(quantum))
        self._tenant_depth: Dict[str, int] = {}

    @staticmethod
    def _cost(entry: _QueueEntry) -> int:
        """The DRR cost of admitting an entry: its committed token
        budget (what it may make the engine serve). Charged once per
        request lifetime (``drr_charged``)."""
        if entry.drr_charged:
            return 0
        return len(entry.request.prompt) + entry.request.max_new_tokens

    def _weight(self, tenant: str) -> int:
        return max(1, int(self._weights.get(tenant, 1)))

    def tenant_depth(self, tenant: str) -> int:
        """Waiting entries currently held by ``tenant`` (all classes) —
        the O(1) backing of TenantQuota.max_waiting's door check."""
        return self._tenant_depth.get(tenant, 0)

    def _classes_ascending(self, below: Optional[int]):
        for p in sorted(self._classes):
            if below is not None and p >= below:
                return
            yield self._classes[p]

    def _note_removed(self, cq: _ClassQueue, tenant: str) -> None:
        self._tenant_depth[tenant] -= 1
        if not self._tenant_depth[tenant]:
            del self._tenant_depth[tenant]
        if not cq.queues[tenant]:
            cq.remove_tenant(tenant)

    def append(self, entry: _QueueEntry) -> None:
        self._enqueue(entry, left=False)

    def appendleft(self, entry: _QueueEntry) -> None:
        self._enqueue(entry, left=True)

    def _enqueue(self, entry: _QueueEntry, left: bool) -> None:
        cq = self._classes.setdefault(entry.request.priority,
                                      _ClassQueue())
        t = entry.request.tenant
        q = cq.queues.get(t)
        if q is None:
            q = cq.queues[t] = deque()
            cq.ring.append(t)           # new tenants join at the tail
            cq.deficits.setdefault(t, 0.0)
        (q.appendleft if left else q.append)(entry)
        self._tenant_depth[t] = self._tenant_depth.get(t, 0) + 1

    def _walk(self, cq: _ClassQueue, skip, mutate: bool):
        """The next entry the class would admit — ``mutate=False``
        peeks, ``mutate=True`` pops it and commits the walk. ``skip``
        tenants are passed over without credit (the engine's per-tick
        quota hold). Returns None when nothing in the class is
        servable."""
        skip = skip or ()
        n = len(cq.ring)
        # phase 1: already-charged heads (preemption requeues, restored
        # residents) serve out of band, ring order from the cursor,
        # without touching the walk state — the old front-of-the-class
        # discipline, tenant-aware
        for k in range(n):
            t = cq.ring[(cq.cursor + k) % n]
            if t in skip:
                continue
            q = cq.queues[t]
            if q and q[0].drr_charged:
                if not mutate:
                    return q[0]
                e = q.popleft()
                self._note_removed(cq, t)
                return e
        # phase 2: the weighted DRR walk
        candidates = [t for t in cq.ring if t not in skip]
        if not candidates:
            return None
        deficits = cq.deficits if mutate else dict(cq.deficits)
        cursor, credited = cq.cursor, cq.credited
        # termination bound (bug guard only): a tenant needs at most
        # ceil(max_cost / quantum) quantum credits, and each credit
        # costs TWO loop iterations (the credit itself, then the
        # cursor advance after the affordability re-check fails), per
        # ring member per cycle — hence the factor 2
        max_cost = max(self._cost(cq.queues[t][0]) for t in candidates)
        limit = 2 * len(cq.ring) * (max_cost // self._quantum + 2) + 16
        for _ in range(limit):
            t = cq.ring[cursor]
            if t in skip:
                cursor = (cursor + 1) % len(cq.ring)
                credited = False
                continue
            head = cq.queues[t][0]
            cost = self._cost(head)
            if deficits[t] >= cost:
                if not mutate:
                    return head
                e = cq.queues[t].popleft()
                deficits[t] -= cost
                e.drr_charged = True
                # the cursor STAYS on the serving tenant: DRR serves
                # while the deficit lasts, then moves on
                cq.cursor, cq.credited = cursor, credited
                self._note_removed(cq, t)
                return e
            if not credited:
                deficits[t] += self._quantum * self._weight(t)
                credited = True
                continue
            cursor = (cursor + 1) % len(cq.ring)
            credited = False
        raise RuntimeError(
            "DRR walk failed to terminate — invariant bug "
            f"(ring={cq.ring}, deficits={deficits})")

    def head(self, below: Optional[int] = None,
             skip=None) -> Optional[_QueueEntry]:
        """The next admissible entry, or None. ``below`` restricts to
        classes < it (the ladder's admission pause); ``skip`` tenants
        are passed over (quota holds) — a class whose every tenant is
        skipped falls through to the next class, so one tenant's quota
        never gates another tenant's lower class."""
        for cq in self._classes_ascending(below):
            e = self._walk(cq, skip, mutate=False)
            if e is not None:
                return e
        return None

    def popleft(self, below: Optional[int] = None,
                skip=None) -> _QueueEntry:
        """Pop exactly the entry :meth:`head` (same arguments)
        returns."""
        for p in sorted(self._classes):
            if below is not None and p >= below:
                break
            cq = self._classes[p]
            e = self._walk(cq, skip, mutate=True)
            if e is not None:
                if not cq.ring:
                    # drop drained classes: priority is an arbitrary
                    # client int, and dead entries would grow the scan
                    # with every distinct value ever submitted
                    del self._classes[p]
                return e
        raise IndexError("pop from an empty waiting queue")

    def has_priority_below(self, limit: int) -> bool:
        return any(True for _ in self._classes_ascending(limit))

    def expel(self, pred) -> List[_QueueEntry]:
        """Remove (and return, in iteration order) every entry matching
        ``pred``, preserving the order of the survivors and the DRR
        walk state of every surviving tenant — the deadline-expiry and
        abort sweep."""
        removed: List[_QueueEntry] = []
        for p in sorted(self._classes):
            cq = self._classes[p]
            for t in list(cq.ring):
                q = cq.queues[t]
                kept: deque = deque()
                while q:
                    e = q.popleft()
                    if pred(e):
                        removed.append(e)
                        self._tenant_depth[t] -= 1
                        if not self._tenant_depth[t]:
                            del self._tenant_depth[t]
                    else:
                        kept.append(e)
                cq.queues[t] = kept
                if not kept:
                    cq.remove_tenant(t)
            if not cq.ring:
                del self._classes[p]
        return removed

    def snapshot_state(self) -> Dict[str, object]:
        """The JSON-able DRR walk state per class: ring order, the
        cursor tenant, its credited flag, and the deficits. Restoring
        them (:meth:`restore_state`) resumes the identical admission
        walk mid-cycle (docs/robustness.md)."""
        out = {}
        for p, cq in self._classes.items():
            out[str(p)] = {
                "ring": list(cq.ring),
                "cursor_tenant": (cq.ring[cq.cursor] if cq.ring
                                  else None),
                "credited": bool(cq.credited),
                "deficits": {t: float(d) for t, d in cq.deficits.items()},
            }
        return out

    def restore_state(self, state: Mapping[str, object]) -> None:
        """Re-apply :meth:`snapshot_state` after the queue's entries
        were re-appended. Tenants present now but absent from the
        serialized ring (previously-resident requests re-queued by
        restore) append at the ring tail; serialized tenants no longer
        present drop out. The cursor re-anchors on its tenant."""
        for key, rec in (state or {}).items():
            cq = self._classes.get(int(key))
            if cq is None:
                continue
            serialized = [t for t in rec.get("ring", ()) if t in cq.queues]
            cq.ring = serialized + [t for t in cq.ring
                                    if t not in serialized]
            for t, d in (rec.get("deficits") or {}).items():
                if t in cq.queues:
                    cq.deficits[t] = float(d)
            cur = rec.get("cursor_tenant")
            if cur in cq.ring:
                cq.cursor = cq.ring.index(cur)
                cq.credited = bool(rec.get("credited", False))
            else:
                cq.cursor, cq.credited = 0, False

    def __iter__(self):
        for p in sorted(self._classes):
            cq = self._classes[p]
            for t in cq.ring:
                yield from cq.queues[t]

    def __len__(self) -> int:
        return sum(self._tenant_depth.values())



@dataclasses.dataclass
class _Slot:
    """Host-side state of one batch lane (prefilling or decoding)."""

    entry: _QueueEntry
    admit_seq: int                # monotonic admission order (preemption
                                  # evicts the largest = youngest)
    tokens: List[int]             # tokens whose K/V belong in the cache;
                                  # grows by one per decoded token
    prefill_len: int              # tokens to cache before decoding starts
    prefill_pos: int              # prompt tokens already cached
    context_len: int              # tokens currently valid in the cache
    blocks: List[int]             # owned/shared block ids, sequence order
    block_hashes: List[str]       # chain hashes per full block (lazy tail)
    num_registered: int           # full blocks already in the prefix index
    generated: List[int]
    last_token: int
    started: bool                 # first token known -> decoding

    @property
    def request(self) -> Request:
        return self.entry.request


class InferenceEngine:
    """Drives a :class:`~apex_tpu.models.gpt.GPTLMHeadModel` (or any
    model exposing the same ``kv_cache=`` apply contract) through
    continuous-batching generation.

    Usage::

        engine = InferenceEngine(model, params, EngineConfig(...))
        engine.add_request(Request("a", prompt, max_new_tokens=32))
        outputs = engine.run()          # {"a": [tok, tok, ...]}

    ``add_request`` may be called at any time, including between
    ``step()`` calls while other requests are mid-generation — that is
    the continuous-batching point.
    """

    def __init__(self, model, params, config: EngineConfig, *,
                 drafter=None, faults=None, clock=None, obs=None,
                 mesh=None):
        cfg = model.cfg
        self.model = model
        self.params = params
        self.config = config
        # quantized weight storage: re-express the params as int8/fp8
        # + per-output-channel scales and rebuild the model to read
        # them through the dequant-GEMM path. Runs FIRST so everything
        # downstream (sharding, program compilation, checksums) sees
        # only the quantized representation — the fp tree never
        # reaches the device when the knob is set.
        self._weight_quant_bytes = None
        if config.weight_quantization is not None:
            fp_bytes = gpt_param_bytes(params)
            self.model, self.params = quantize_gpt_model(
                model, params, config.weight_quantization)
            model, params = self.model, self.params
            self._weight_quant_bytes = (fp_bytes,
                                        gpt_param_bytes(self.params))
        # optional chaos harness (apex_tpu.utils.faults.FaultPlan): every
        # jitted dispatch fires the plan at its site ("prefill"/"decode",
        # plus "draft" around the speculative proposer) before
        # launching, so chaos tests are seeded and reproducible
        self.faults = faults
        if faults is not None:
            # the engine's outputs are integer tokens, so there is no
            # float output the "nan" kind could meaningfully corrupt —
            # reject rather than record a fire that changed nothing
            bad = [s.site for s in getattr(faults, "specs", ())
                   if s.kind == "nan"
                   and s.site in ("prefill", "decode", "draft")]
            if bad:
                raise ValueError(
                    f"nan faults are not supported at serving sites "
                    f"{sorted(set(bad))}; use transient/crash (the "
                    f"train loop's watchdog owns nan handling)")
            # the integrity sites are corruption-only (a transient/
            # crash there would raise from inside host bookkeeping
            # with no defined recovery), and "corrupt" at a dispatch
            # site is meaningful only at "decode" (the SDC model: a
            # wrong token emitted from the drain) — prefill/draft
            # corruption has no defined consumer
            bad = [s.site for s in getattr(faults, "specs", ())
                   if (s.site in _INTEGRITY_SITES
                       and s.kind != "corrupt")
                   or (s.kind == "corrupt"
                       and s.site in ("prefill", "draft"))]
            if bad:
                raise ValueError(
                    f"unsupported fault kind/site combination at "
                    f"{sorted(set(bad))}: integrity sites "
                    f"{_INTEGRITY_SITES} take only 'corrupt' specs, "
                    f"and 'corrupt' dispatch faults are supported at "
                    f"'decode' only (docs/robustness.md)")
        # deadline clock, injectable so TTL tests are deterministic
        self._clock = time.monotonic if clock is None else clock
        # observability (docs/observability.md): tracer + flight
        # recorder + metrics, all OUTPUT-only — no engine decision ever
        # reads observer state (the zero-perturbation contract), and
        # every observer timestamp comes from the engine's own clock so
        # traces are deterministic under fake clocks. None = off, at
        # zero cost on the hot paths.
        self._obs = obs
        if obs is not None:
            obs.bind_engine(self._clock)
            # both storage quantization modes surface as one labeled
            # gauge family the moment the engine exists (the modes are
            # identity, not runtime state — set once, never moved)
            from apex_tpu.observability import QUANT_MODE_CODES
            obs.gauge("kv_quant_mode",
                      QUANT_MODE_CODES[config.kv_quantization])
            obs.gauge("weight_quant_mode",
                      QUANT_MODE_CODES[config.weight_quantization])
            if self._weight_quant_bytes is not None:
                fp_b, q_b = self._weight_quant_bytes
                obs.record("dequant_gemm",
                           mode=config.weight_quantization,
                           fp_bytes=fp_b, quant_bytes=q_b)
        # (dispatch t0, dispatch seq) of the in-flight decode, tracked
        # only while an observer wants the dispatch->drain trace span
        self._pending_obs = None
        self._chunk = (config.prefill_chunk if config.prefill_chunk
                       is not None else config.max_prefill_len)
        # speculative decoding: the drafter defaults to prompt-lookup;
        # a custom one rides the same propose() contract (drafter.py)
        if config.spec_tokens > 0:
            self.drafter = NgramDrafter() if drafter is None else drafter
        elif drafter is not None:
            raise ValueError(
                "a drafter requires spec_tokens >= 1 (speculative "
                "decoding is off at spec_tokens == 0)")
        else:
            self.drafter = None
        # flipped off forever if the drafter is quarantined: the verify
        # program with zero proposals is a plain single-token step, so
        # the engine degrades to non-speculative decoding, not death
        self._drafter_ok = config.spec_tokens > 0
        # the coming dispatch's proposals: {lane: [token, ...]},
        # rebuilt every decode phase (step 4), consumed by the dispatch
        self._draft_plan: Dict[int, List[int]] = {}
        if config.max_seq_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_seq_len ({config.max_seq_len}) exceeds the model's "
                f"max_position_embeddings ({cfg.max_position_embeddings})")
        self.max_blocks_per_seq = blocks_needed(config.max_seq_len,
                                                config.block_size)
        head_dim = cfg.hidden_size // cfg.num_heads
        self.cache = KVCache.create(
            cfg.num_layers, config.num_blocks, config.block_size,
            cfg.num_heads, head_dim, dtype=config.kv_dtype,
            quantization=config.kv_quantization)
        # -- the GSPMD mesh (docs/serving.md, "Mesh sharding") ----------
        # The config's shape was geometry-validated at construction;
        # the model-dependent half (heads must split evenly) runs here.
        # ``mesh=`` lets a fleet router build ONE mesh and thread it
        # through every replica (equal NamedShardings across replicas
        # by construction); it must agree with the config.
        mesh_lib.validate_mesh_shape(config.mesh_shape,
                                     num_heads=cfg.num_heads)
        if mesh is not None:
            if (tuple(mesh.axis_names) != mesh_lib.MESH_AXES
                    or tuple(mesh.devices.shape)
                    != tuple(config.mesh_shape)):
                raise ValueError(
                    f"mesh= (axes {tuple(mesh.axis_names)}, shape "
                    f"{tuple(mesh.devices.shape)}) does not match "
                    f"mesh_shape {tuple(config.mesh_shape)} over axes "
                    f"{mesh_lib.MESH_AXES}")
            self.mesh = mesh
        else:
            self.mesh = mesh_lib.build_mesh(config.mesh_shape)
        if config.mesh_shape[1] > 1:
            from apex_tpu.ops.paged_attention_pallas import (
                pallas_paged_read_wanted)
            if pallas_paged_read_wanted():
                # the fused Pallas read kernel is a single-device
                # program (no SPMD partitioning rule); under a sharded
                # pool it would fail at trace time with a far worse
                # error than this one
                raise ValueError(
                    "APEX_PAGED_ATTENTION_PALLAS is incompatible with "
                    f"a sharded model axis (mesh_shape "
                    f"{tuple(config.mesh_shape)}): the fused paged-read "
                    "kernel is single-device — unset the flag or run "
                    "mesh (1, 1)")
            from apex_tpu.ops.dequant_gemm import dequant_gemm_wanted
            if dequant_gemm_wanted():
                # same single-device story as the paged-read kernel:
                # pallas_call has no SPMD partitioning rule, and the
                # XLA dequant chain partitions collective-free with
                # the scales riding their kernel's shard
                raise ValueError(
                    "APEX_DEQUANT_GEMM_PALLAS is incompatible with a "
                    f"sharded model axis (mesh_shape "
                    f"{tuple(config.mesh_shape)}): the fused "
                    "dequant-GEMM kernel is single-device — unset the "
                    "flag or run mesh (1, 1)")
        # weights and KV pools commit to their mesh layout (head axis
        # over "model"; see gpt.gpt_param_pspec / KVCache.
        # partition_specs), and every jitted program pins its returned
        # cache to the same layout — without the out_shardings pin,
        # GSPMD may hand back a different pool layout and the next
        # dispatch's changed input sharding would recompile, breaking
        # the one-program compile-count contract
        self.params = mesh_lib.shard_params(self.mesh, self.params)
        self.cache = mesh_lib.shard_cache(self.mesh, self.cache)
        self._program_out = mesh_lib.program_out_shardings(self.mesh,
                                                           self.cache)
        # the tenant ledger's per-block charge unit: a quantized block
        # charges its reduced byte footprint relative to the full-
        # precision block this config would otherwise store, so
        # max_resident_blocks quotas are denominated in full-precision
        # block equivalents (1.0 — and the pre-quantization ledger,
        # bit for bit — when quantization is off)
        if config.kv_quantization is not None:
            self._block_weight = (
                kv_block_bytes(cfg.num_layers, config.block_size,
                               cfg.num_heads, head_dim,
                               quantization=config.kv_quantization)
                / kv_block_bytes(cfg.num_layers, config.block_size,
                                 cfg.num_heads, head_dim,
                                 dtype=config.kv_dtype))
        else:
            self._block_weight = 1.0
        # -- the batch axis (docs/serving.md, "The batch axis") --------
        # B > 1 splits the max_batch decode lanes and the block pool
        # into B contiguous shards (lane i -> shard i // lanes_per_
        # shard; block b -> shard b // blocks_per_shard). The allocator
        # enforces shard residency host-side; the sharded programs
        # localize tables by subtracting the shard base. B == 1 keeps
        # every code path byte-identical to the pre-batch-axis engine.
        self._batch_shards = config.mesh_shape[0]
        self._lanes_per_shard = config.max_batch // self._batch_shards
        self._blocks_per_shard = config.num_blocks // self._batch_shards
        self.allocator = BlockAllocator(config.num_blocks,
                                        block_weight=self._block_weight,
                                        num_shards=self._batch_shards)
        # the host-RAM spill tier (docs/serving.md memory tiers):
        # evicted/flushed prefix blocks copy to this bounded host
        # store; _admit re-admits matches by device upload
        self.spill: Optional[HostSpillStore] = None
        self._spill_hits = 0
        self._spill_misses = 0
        # -- data integrity (docs/robustness.md) -----------------------
        self._num_corruptions_detected = 0
        self._num_import_refusals = 0
        self._num_scrubs = 0
        self._num_scrub_blocks_verified = 0
        # the corrupt seed captured at the decode dispatch, applied to
        # the drained tokens (the SDC fault model rides the deferred
        # sync: dispatch fires the plan, drain perturbs the fetch)
        self._pending_corrupt: Optional[int] = None
        if config.spill_max_bytes is not None:
            self.spill = HostSpillStore(
                config.spill_max_bytes,
                verify=config.verify_artifacts,
                # the chaos seam exists only when a plan does — the
                # no-faults engine runs the store's bare read/write
                corrupt_hook=(self._corrupt_payload_hook
                              if faults is not None else None),
                on_corrupt=self._note_corruption)
            self.allocator.attach_spill(self.spill, self._spill_payload)
            # the upload program: one jitted scatter of a host block
            # into the pool (its own jit slot — the prefill/decode
            # compile-count contract is untouched)
            self._upload = jax.jit(
                (self._upload_sharded_impl if self._batch_shards > 1
                 else self._upload_impl),
                donate_argnums=(0,) if config.donate_cache else (),
                **self._cache_out_kw())
        self.slots: List[Optional[_Slot]] = [None] * config.max_batch
        self.waiting = _WaitingQueue(weights=config.tenant_weights,
                                     quantum=config.drr_quantum)
        # every uid currently waiting or resident — the O(1) backing of
        # add_request's duplicate guard (maintained at enqueue/restore,
        # cleared by _set_status at every terminal transition)
        self._live_uids: set = set()
        self.finished: Dict[str, List[int]] = {}
        # terminal status per finished uid ("finished"|"timeout"|"failed");
        # drained alongside `finished` by run()
        self.statuses: Dict[str, str] = {}
        self._deadline: Dict[str, float] = {}   # uid -> absolute deadline
        self._key = jax.random.PRNGKey(config.seed)
        self._arrival_count = 0
        self._admit_count = 0
        self._num_prefills = 0
        self._num_prefill_chunks = 0
        self._num_decode_dispatches = 0
        self._num_tokens_decoded = 0
        self._num_preemptions = 0
        self._num_cow_copies = 0
        self._prefix_hit_blocks = 0
        self._prefix_lookup_blocks = 0
        self._prompt_blocks_allocated = 0
        self._num_timeouts = 0
        self._num_dispatch_retries = 0
        self._num_quarantines = 0
        self._num_draft_tokens = 0
        self._num_accepted_tokens = 0
        self._num_draft_retries = 0
        self._num_drafter_quarantines = 0
        self._num_spec_blocks_rolled_back = 0
        self._num_snapshots = 0
        self._num_restores = 0
        # -- fleet serving (docs/fleet.md) -----------------------------
        # the bounded-staleness failover picture: refreshed every
        # snapshot_interval_ticks by checkpoint(), read by the fleet
        # router when this replica dies
        self.last_checkpoint: Optional[Dict[str, object]] = None
        self._num_checkpoints = 0
        self._num_migrated_in = 0
        self._num_migrated_out = 0
        # the arrival PRNG identity of each uid this engine exported,
        # retained CLEAN on this side of the wire: when a record rots
        # in transit and the target refuses it, the router re-injects
        # the request fresh — and only this index lets the recompute
        # re-draw the same sampled tokens (sampling is arrival-keyed;
        # the corrupted record's own "arrival" field is untrustworthy)
        self._exported_arrivals: Dict[str, int] = {}
        # -- overload protection (docs/robustness.md) ------------------
        self._num_ticks = 0
        self._queue_depth_peak = 0
        self._queue_wait_count = 0
        self._queue_wait_ticks_sum = 0
        self._queue_wait_ticks_max = 0
        self._queue_wait_s_sum = 0.0
        self._queue_wait_s_max = 0.0
        self._num_rejected_queue_full = 0
        self._num_rejected_infeasible = 0
        # cheap service-time estimators feeding the admit-time
        # feasibility gate: EWMAs of observed per-dispatch wall time
        # (None until the first observation — the gate stays open)
        self._ewma_prefill_s: Optional[float] = None
        self._ewma_decode_s: Optional[float] = None
        # the degradation ladder: current rung (0 = normal), the
        # pressure/clear streaks driving its hysteresis, and the
        # transition counters
        self._degradation_level = 0
        self._pressure_streak = 0
        self._clear_streak = 0
        self._num_degrade_steps_down = 0
        self._num_degrade_steps_up = 0
        self._num_degrade_flushed_blocks = 0
        # -- multi-tenant isolation (docs/robustness.md) ---------------
        self._num_throttled = 0
        self._num_cancelled = 0
        # the tenant ledger: every tenant ever submitted to this
        # engine, its delivered-token count, its exponentially-decayed
        # token-rate estimator (value + last-update time), its
        # terminal-status tallies, and its quota-preemption count
        self._tenant_seen: set = {DEFAULT_TENANT}
        self._tenant_tokens: Dict[str, int] = {}
        self._tenant_rate: Dict[str, float] = {}
        self._tenant_rate_t: Dict[str, float] = {}
        self._tenant_status: Dict[str, Dict[str, int]] = {}
        self._tenant_preemptions: Dict[str, int] = {}
        # streaming delivery (docs/serving.md): (uid, token, is_last)
        # events appended as tokens become host-visible, drained by
        # pop_stream_events(); every terminal transition appends a
        # (uid, -1, True) sentinel
        self._stream: deque = deque()
        # dynamic speculation (spec_adapt): the adaptive per-plan draft
        # cap, the acceptance-rate EWMA driving it, and the probe
        # countdown that lets a capped-out engine re-measure
        self._spec_cap = config.spec_tokens
        self._spec_accept_ewma: Optional[float] = None
        self._spec_probe_countdown = _SPEC_PROBE_EVERY
        self._num_spec_cap_shrinks = 0
        self._num_spec_cap_restores = 0
        self._fetch_failures = 0   # consecutive failed deferred drains
        # the in-flight decode dispatch: (device [B, K] tokens, device
        # [B] counts, the lane indices it covers). Fetched — the only
        # host sync of the decode path — at the NEXT tick, after that
        # tick's admission/prefill work is already dispatched.
        self._pending = None
        # dirty-tracked device mirrors of slot-composition state: the
        # decode block table, and the per-lane sampling/EOS/key arrays.
        # Steady-state decode ticks reuse them without a rebuild.
        self._dev_tables = DeviceMirror()
        self._dev_lanes = DeviceMirror()
        self._table_rebuilds = 0
        # the fixed program set; anything else jitted here would break
        # the compile-count contract the tests pin. Arg 1 is the cache
        # pool in every signature (donated when the runtime allows).
        # With speculation on, THE decode program is the verify program
        # — same slot in the contract, still exactly one compilation
        # (zero-proposal lanes run through it as single-token steps, so
        # no second "fallback" program ever exists).
        donate = (1,) if config.donate_cache else ()
        # B > 1 swaps in the batch-axis sharded wrappers (same program
        # slots, same arg signatures, one compilation each — the
        # compile-count contract is shape-based and unchanged); B == 1
        # keeps the exact pre-batch-axis callables, so the (1, 1)
        # bit-identity certification never sees the wrapper.
        sharded = self._batch_shards > 1
        prefill_fn = (self._prefill_sharded_impl if sharded
                      else self._prefill_impl)
        if config.spec_tokens > 0:
            decode_fn = (self._spec_decode_sharded_impl if sharded
                         else self._spec_decode_impl)
        else:
            decode_fn = (self._decode_sharded_impl if sharded
                         else self._decode_impl)
        self._prefill = jax.jit(prefill_fn, donate_argnums=donate,
                                **self._pair_out_kw())
        self._decode = jax.jit(decode_fn, donate_argnums=donate,
                               **self._pair_out_kw())
        self._cow = jax.jit(
            self._cow_sharded_impl if sharded else copy_block,
            donate_argnums=(0,) if config.donate_cache else (),
            **self._cache_out_kw())

    def _pair_out_kw(self) -> Dict[str, object]:
        """``jax.jit`` kwargs pinning a ``(cache, tokens)`` program's
        output layout to the mesh (empty when the mesh layer is
        neutered — the pre-mesh jit, byte for byte)."""
        if self._program_out is None:
            return {}
        return {"out_shardings": self._program_out}

    def _cache_out_kw(self) -> Dict[str, object]:
        """Same, for the cache-only programs (CoW copy, spill upload)."""
        if self._program_out is None:
            return {}
        return {"out_shardings": self._program_out[0]}

    # -- the jitted programs ----------------------------------------------

    def _prefill_impl(self, params, cache, ids, positions, seq_len,
                      write_start, sample_idx, table, key, temp, top_k,
                      top_p):
        logits, cache = self.model.apply(
            params, ids, deterministic=True, kv_cache=cache,
            block_tables=table, cache_positions=positions,
            seq_lens=seq_len, write_start=write_start)
        last = jnp.take_along_axis(
            logits, sample_idx[:, None, None], axis=1)[:, 0]   # [1, V]
        # ``key`` is the REQUEST's key; the first generated token is
        # token index 0 of its per-token key chain (decode continues at
        # index 1), so schedule changes never perturb the draw
        tok = sample_tokens(last, jax.random.fold_in(key, 0),
                            temp, top_k, top_p)
        return cache, tok

    def _decode_impl(self, params, cache, tokens, tables, context_lens,
                     budgets, gen_counts, eos_ids, lane_keys, temp,
                     top_k, top_p):
        """K = ``decode_steps`` fused decode iterations in ONE dispatch.

        Each scan step writes the carried token's K/V at the lane's
        context position, attends through the (loop-invariant) block
        table, samples the next token with the lane's per-token key,
        and feeds it back. Lanes freeze — stop writing, emit ``-1`` —
        once their remaining ``budgets`` hit zero or they sample their
        EOS id (``eos_ids``; ``-1`` = none); a frozen lane's query
        still rides the batch but its ``write_start`` sits one past its
        context position, so the scatter drops. Returns the updated
        cache and ``[B, K]`` emitted tokens — ``-1`` where nothing was
        emitted, so each lane's count is the length of its non-sentinel
        prefix (token ids are always ``>= 0``; the host derives counts
        from the one fetched array instead of a second device output).
        """
        def body(carry, _):
            cache, tok, ctx, budget, gcount = carry
            act = budget > 0
            write_start = jnp.where(act, ctx, ctx + 1)
            logits, cache = self.model.apply(
                params, tok[:, None], deterministic=True, kv_cache=cache,
                block_tables=tables, cache_positions=ctx[:, None],
                seq_lens=ctx + 1, write_start=write_start)
            keys = jax.vmap(jax.random.fold_in)(lane_keys, gcount)
            new = sample_tokens_per_lane(logits[:, 0], keys, temp, top_k,
                                         top_p)
            emitted = act.astype(jnp.int32)
            out = jnp.where(act, new, jnp.int32(-1))
            budget = budget - emitted
            stop = (budget <= 0) | ((eos_ids >= 0) & (new == eos_ids))
            cont = act & ~stop
            # zeroing the budget on EOS folds both stop conditions into
            # the single ``budget > 0`` activity test next iteration
            carry = (cache, jnp.where(cont, new, tok), ctx + emitted,
                     jnp.where(cont, budget, jnp.int32(0)),
                     gcount + emitted)
            return carry, out

        (cache, _, _, _, _), toks = jax.lax.scan(
            body, (cache, tokens, context_lens, budgets, gen_counts),
            None, length=self.config.decode_steps)
        return cache, toks.T

    def _spec_decode_impl(self, params, cache, tokens, drafts, draft_lens,
                          tables, context_lens, budgets, gen_counts,
                          eos_ids, lane_keys, temp, top_k, top_p):
        """Draft-and-verify decode: ONE target forward scores a whole
        drafted span per lane (``spec_tokens > 0`` replaces the K-step
        scan with this program).

        Each lane's query chunk is its carried token followed by its
        ``draft_lens`` proposals, at absolute positions ``ctx .. ctx +
        d`` — the multi-query paged-prefill path, so position ``p``'s
        logits are exactly the target distribution given the drafts
        before it, and the chunk's K/V (the carried token's AND every
        draft's) scatter into the lane's reserved span in the same
        dispatch. The accept rule
        (:func:`~apex_tpu.serving.sampling.spec_verify_tokens`) keeps a
        prefix of the drafts and samples the correction/bonus token
        with the lane's schedule-invariant per-token keys; the same
        stop-mask conventions as the scan then apply — inactive lanes
        emit nothing (and ``write_start`` drops their writes), an
        accepted/emitted EOS truncates the lane's remaining span, and
        the program returns ``[max_batch, spec_tokens + 1]`` tokens
        with ``-1`` sentinels past each lane's emitted prefix, so the
        deferred-drain contract is byte-for-byte the scan's.

        Rejected drafts need no device-side rollback: their K/V sits at
        positions past the lane's new context length, which every
        attention mask already excludes, and the next dispatch's writes
        land over them before the context ever reaches those positions.
        (The HOST-side reservation rollback — returning span blocks the
        rejection stranded — happens at drain time via
        ``BlockAllocator.trim_to``.)
        """
        # lane count from the INPUT (not config.max_batch): under the
        # batch-axis vmap each shard verifies its own lane group; the
        # unsharded program passes all max_batch lanes, so the traced
        # value is unchanged there
        B = tokens.shape[0]
        P = self.config.spec_tokens + 1
        act = budgets > 0
        q_ids = jnp.concatenate([tokens[:, None], drafts], axis=1)
        pos = (context_lens[:, None]
               + jax.lax.broadcasted_iota(jnp.int32, (B, P), 1))
        # the lane's span: carried token + its proposals; padded query
        # slots past it are masked (no write, ignored logits)
        seq_lens = context_lens + 1 + draft_lens
        write_start = jnp.where(act, context_lens, context_lens + P + 1)
        logits, cache = self.model.apply(
            params, q_ids, deterministic=True, kv_cache=cache,
            block_tables=tables, cache_positions=pos, seq_lens=seq_lens,
            write_start=write_start)
        token_idx = (gen_counts[:, None]
                     + jax.lax.broadcasted_iota(jnp.int32, (B, P), 1))
        emitted, n_emit = spec_verify_tokens(
            logits, drafts, draft_lens, lane_keys, token_idx, temp,
            top_k, top_p)
        # stop masks, mirroring the scan: emit only the accepted-prefix
        # + correction window, cut everything after the first EOS, and
        # mask inactive lanes entirely. All three are prefix masks, so
        # the host's count-by-sentinel-prefix drain stays valid.
        ii = jax.lax.broadcasted_iota(jnp.int32, (B, P), 1)
        within = ii < n_emit[:, None]
        is_eos = (within & (eos_ids[:, None] >= 0)
                  & (emitted == eos_ids[:, None]))
        after_eos = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
                     - is_eos.astype(jnp.int32)) > 0
        keep = within & ~after_eos & act[:, None]
        return cache, jnp.where(keep, emitted, jnp.int32(-1))

    # -- the batch-axis sharded programs (docs/serving.md) ----------------
    #
    # At mesh_shape = (B, M) with B > 1 the jitted programs wrap the
    # (1, M) bodies above in a per-shard vmap: the pool's block axis
    # reshapes [L, N, ...] -> [B, L, N/B, ...] exactly on the shard
    # boundaries the NamedSharding put there (a local reshape — GSPMD
    # inserts nothing), lane arrays reshape [max_batch] -> [B, N/B
    # lanes], and the GLOBAL block-table ids localize per shard. The
    # allocator's shard-residency invariant means the owning shard's
    # entries land in [0, blocks_per_shard) and every foreign entry
    # clamps to the out-of-bounds sentinel, where the scatter drops
    # and the gather reads already-masked garbage — so non-owners need
    # no masking and the whole split lowers collective-free (the
    # audit_collectives batch contract). The clamp is explicit because
    # jnp indexing WRAPS negative traced indices Python-style; a raw
    # base subtraction would alias a foreign block into a valid local
    # id.

    def _cache_split(self, cache):
        B = self._batch_shards

        def split(x):
            y = x.reshape((x.shape[0], B, x.shape[1] // B) + x.shape[2:])
            return jnp.moveaxis(y, 1, 0)

        return jax.tree.map(split, cache)

    def _cache_merge(self, scache):
        B = self._batch_shards

        def merge(x):
            y = jnp.moveaxis(x, 0, 1)
            return y.reshape((y.shape[0], B * y.shape[2]) + y.shape[3:])

        return jax.tree.map(merge, scache)

    def _localize_tables(self, tables):
        """``[B, lanes, M]``-shaped global-id tables -> per-shard local
        ids: in-range entries subtract the shard base, everything else
        (foreign shards' blocks, the host's ``num_blocks`` sentinel)
        becomes the local out-of-bounds id ``blocks_per_shard``."""
        Nl = self._blocks_per_shard
        bases = (jnp.arange(self._batch_shards, dtype=jnp.int32)
                 * Nl)[:, None, None]
        local = tables - bases
        return jnp.where((local >= 0) & (local < Nl), local,
                         jnp.int32(Nl))

    def _prefill_sharded_impl(self, params, cache, ids, positions,
                              seq_len, write_start, sample_idx, table,
                              key, temp, top_k, top_p):
        """B > 1 prefill: every shard traces the same ``[1, C]`` chunk
        (inputs broadcast across the vmap), but only the shard owning
        the slot's blocks sees in-range localized table entries — its
        scatter writes the chunk and its attention reads real K/V;
        every other shard's writes drop and its sampled token is
        deterministic garbage the host discards. Returns ``[B]``
        tokens (batch-sharded); the host keeps index ``lane_shard``."""
        B = self._batch_shards
        scache = self._cache_split(cache)
        tbl = self._localize_tables(
            jnp.broadcast_to(table, (B,) + table.shape))

        def one(c, tb):
            return self._prefill_impl(params, c, ids, positions,
                                      seq_len, write_start, sample_idx,
                                      tb, key, temp, top_k, top_p)

        scache, tok = jax.vmap(one)(scache, tbl)
        return self._cache_merge(scache), tok.reshape(B)

    def _decode_sharded_impl(self, params, cache, tokens, tables,
                             context_lens, budgets, gen_counts, eos_ids,
                             lane_keys, temp, top_k, top_p):
        """B > 1 decode: each shard scans its own lane group against
        its own pool range. Tokens return ``[max_batch, K]`` in the
        global lane order (lane = shard * lanes_per_shard + local), so
        the host drain is byte-identical to the unsharded program's."""
        B, Lp = self._batch_shards, self._lanes_per_shard
        scache = self._cache_split(cache)
        tbl = self._localize_tables(tables.reshape(B, Lp, -1))

        def one(c, tb, tok, cx, bud, gc, eo, ky, tp, tk, tpp):
            return self._decode_impl(params, c, tok, tb, cx, bud, gc,
                                     eo, ky, tp, tk, tpp)

        scache, toks = jax.vmap(one)(
            scache, tbl, tokens.reshape(B, Lp),
            context_lens.reshape(B, Lp), budgets.reshape(B, Lp),
            gen_counts.reshape(B, Lp), eos_ids.reshape(B, Lp),
            lane_keys.reshape((B, Lp) + lane_keys.shape[1:]),
            temp.reshape(B, Lp), top_k.reshape(B, Lp),
            top_p.reshape(B, Lp))
        return (self._cache_merge(scache),
                toks.reshape((self.config.max_batch,) + toks.shape[2:]))

    def _spec_decode_sharded_impl(self, params, cache, tokens, drafts,
                                  draft_lens, tables, context_lens,
                                  budgets, gen_counts, eos_ids,
                                  lane_keys, temp, top_k, top_p):
        """B > 1 draft-and-verify: the verify program vmapped over the
        shard axis, same conventions as the sharded scan decode."""
        B, Lp = self._batch_shards, self._lanes_per_shard
        scache = self._cache_split(cache)
        tbl = self._localize_tables(tables.reshape(B, Lp, -1))

        def one(c, tok, dr, dl, tb, cx, bud, gc, eo, ky, tp, tk, tpp):
            return self._spec_decode_impl(params, c, tok, dr, dl, tb,
                                          cx, bud, gc, eo, ky, tp, tk,
                                          tpp)

        scache, toks = jax.vmap(one)(
            scache, tokens.reshape(B, Lp),
            drafts.reshape((B, Lp) + drafts.shape[1:]),
            draft_lens.reshape(B, Lp), tbl,
            context_lens.reshape(B, Lp), budgets.reshape(B, Lp),
            gen_counts.reshape(B, Lp), eos_ids.reshape(B, Lp),
            lane_keys.reshape((B, Lp) + lane_keys.shape[1:]),
            temp.reshape(B, Lp), top_k.reshape(B, Lp),
            top_p.reshape(B, Lp))
        return (self._cache_merge(scache),
                toks.reshape((self.config.max_batch,) + toks.shape[2:]))

    def _cow_sharded_impl(self, cache, src, dst):
        """B > 1 copy-on-write: the owning shard (src and dst share a
        shard — the allocator allocates the private copy on the slot's
        shard) copies localized ids; every other shard targets the
        out-of-bounds id, where the explicit ``mode="drop"`` discards
        the write."""
        B, Nl = self._batch_shards, self._blocks_per_shard
        scache = self._cache_split(cache)
        src = jnp.asarray(src, jnp.int32)
        dst = jnp.asarray(dst, jnp.int32)
        shard_ids = jnp.arange(B, dtype=jnp.int32)
        own = shard_ids == src // Nl
        src_l = jnp.where(own, src % Nl, jnp.int32(Nl))
        dst_l = jnp.where(own, dst % Nl, jnp.int32(Nl))

        def one(c, s, d):
            # copy_block's shape, with explicit drop modes: the
            # non-owning shards' OOB src clamps (reads garbage) and
            # OOB dst drops (writes nothing)
            s = jnp.minimum(s, jnp.int32(Nl - 1))
            out = KVCache(
                k=c.k.at[:, d].set(c.k[:, s], mode="drop"),
                v=c.v.at[:, d].set(c.v[:, s], mode="drop"))
            if c.k_scale is not None:
                out = out._replace(
                    k_scale=c.k_scale.at[:, d].set(c.k_scale[:, s],
                                                   mode="drop"),
                    v_scale=c.v_scale.at[:, d].set(c.v_scale[:, s],
                                                   mode="drop"))
            return out

        return self._cache_merge(jax.vmap(one)(scache, src_l, dst_l))

    def _upload_sharded_impl(self, cache, ids, k_blk, v_blk, *scales):
        """B > 1 spill upload: the ``[max_blocks_per_seq]`` global ids
        localize per shard (foreign/padding entries clamp out of
        bounds and drop), payloads broadcast — each shard scatters
        only the rows it owns."""
        B, Nl = self._batch_shards, self._blocks_per_shard
        scache = self._cache_split(cache)
        bases = (jnp.arange(B, dtype=jnp.int32) * Nl)[:, None]
        local = jnp.asarray(ids, jnp.int32)[None, :] - bases
        ids_l = jnp.where((local >= 0) & (local < Nl), local,
                          jnp.int32(Nl))

        def one(c, i):
            return self._upload_impl(c, i, k_blk, v_blk, *scales)

        return self._cache_merge(jax.vmap(one)(scache, ids_l))

    # -- host-side scheduling ---------------------------------------------

    def add_request(self, request: Request) -> int:
        """Validate, door-check, and enqueue one request. Returns the
        ARRIVAL INDEX assigned to it — the request's PRNG identity
        (sampled draws key on it), which is what makes a completed
        request replayable bit-for-bit on any equal-config engine: the
        fleet router's SDC cross-check (docs/fleet.md) records it per
        accepted request."""
        n = len(request.prompt)
        if n == 0:
            raise ValueError(f"request {request.uid!r}: empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.uid!r}: max_new_tokens must be >= 1 "
                f"(got {request.max_new_tokens}); prefill always samples "
                "the first token")
        if n + request.max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"request {request.uid!r}: prompt + max_new_tokens "
                f"({n} + {request.max_new_tokens}) exceeds max_seq_len "
                f"({self.config.max_seq_len})")
        if request.deadline_s is not None and request.deadline_s <= 0:
            raise ValueError(
                f"request {request.uid!r}: deadline_s must be positive "
                f"(got {request.deadline_s})")
        if request.priority < 0:
            raise ValueError(
                f"request {request.uid!r}: priority must be >= 0 "
                f"(got {request.priority}); 0 is the most urgent class")
        if not isinstance(request.tenant, str) or not request.tenant:
            raise ValueError(
                f"request {request.uid!r}: tenant must be a non-empty "
                f"string (got {request.tenant!r})")
        request.sampling.validate()
        # a uid that is still waiting or resident would collide in the
        # uid-keyed deadline map and the engine-owned status field —
        # and a terminal-but-undrained uid would have its result in
        # finished/statuses silently CLOBBERED by the new lifecycle's
        # exit. Reject both loudly; a DRAINED uid starts a fresh
        # lifecycle as before.
        uid = request.uid
        if uid in self._live_uids:
            raise ValueError(
                f"request uid {uid!r} is already waiting or resident in "
                "this engine; drain it (run()) or pick a distinct uid")
        if uid in self.statuses:
            raise ValueError(
                f"request uid {uid!r} has a terminal result "
                f"({self.statuses[uid]!r}) awaiting drain; run() before "
                "reusing the uid, or pick a distinct one")
        # the engine owns the terminal-status field from here on (a
        # re-submitted request object starts a fresh lifecycle) —
        # cleared BEFORE the queue-full shed, so a door-shed request
        # reads status None, not a stale verdict from its previous
        # lifecycle (the documented "no status" contract)
        object.__setattr__(request, "status", None)
        self._tenant_seen.add(request.tenant)
        # tenant quotas first (the shed is the TENANT's own doing and
        # is charged to its ledger with a real terminal verdict —
        # docs/robustness.md, isolation), then the engine-wide bound
        reason = self._door_throttle_reason(request)
        if reason is not None:
            if self._obs is not None:
                self._obs.note_shed(uid, "throttled", queued=False)
            self.finished[uid] = []
            self._set_status(request, "throttled")
            self._num_throttled += 1
            raise TenantThrottledError(
                f"request {uid!r} throttled: tenant "
                f"{request.tenant!r} {reason}")
        # backpressure: the bounded queue is the overload contract —
        # callers get an explicit shed signal, not unbounded growth
        if (self.config.max_waiting is not None
                and len(self.waiting) >= self.config.max_waiting):
            self._num_rejected_queue_full += 1
            if self._obs is not None:
                # a door shed: the request never entered the engine
                # and gets NO terminal status, but the trace must
                # still show the refusal
                self._obs.note_shed(uid, "queue_full", queued=False)
            raise QueueFullError(
                f"request {uid!r} rejected: waiting queue is at "
                f"max_waiting ({self.config.max_waiting})")
        self._live_uids.add(uid)
        if request.deadline_s is not None:
            self._deadline[request.uid] = self._clock() + request.deadline_s
        enq_t = self._clock()
        arrival = self._arrival_count
        self.waiting.append(_QueueEntry(request=request,
                                        arrival=arrival,
                                        enq_t=enq_t,
                                        enq_tick=self._num_ticks))
        if self._obs is not None:
            # reuse the engine-read timestamp: observation adds no
            # clock call of its own here
            self._obs.note_enqueue(uid, tenant=request.tenant,
                                   priority=request.priority,
                                   prompt_len=n, t=enq_t)
        self._arrival_count += 1
        self._queue_depth_peak = max(self._queue_depth_peak,
                                     len(self.waiting))
        return arrival

    def try_add(self, request: Request) -> bool:
        """Non-raising backpressure variant of :meth:`add_request`:
        returns False when the bounded queue or the tenant's quota
        sheds the request (and counts it; a quota shed additionally
        leaves terminal status ``"throttled"``), True when enqueued.
        Validation errors — bad geometry, duplicate uid — still raise:
        those are caller bugs, not load."""
        try:
            self.add_request(request)
        except (QueueFullError, TenantThrottledError):
            return False
        return True

    # -- the tenant ledger (docs/robustness.md, isolation) -----------------

    def _tenant_quota(self, tenant: str) -> Optional[TenantQuota]:
        quotas = self.config.tenant_quotas
        return None if quotas is None else quotas.get(tenant)

    def _tenant_rate_now(self, tenant: str) -> float:
        """The tenant's token-rate estimate decayed to now (read-only:
        delivery updates happen in :meth:`_note_tenant_tokens`)."""
        r = self._tenant_rate.get(tenant, 0.0)
        if r == 0.0:
            return 0.0
        dt = max(0.0, self._clock() - self._tenant_rate_t[tenant])
        return r * math.exp(-dt / self.config.tenant_rate_tau_s)

    def _note_tenant_tokens(self, tenant: str, n: int) -> None:
        """Account ``n`` delivered tokens to the tenant: the running
        total, and the exponentially-decayed rate estimator the
        ``tokens_per_s`` quota reads (each token adds ``1/tau``, so a
        constant rate R settles the estimator at R)."""
        self._tenant_tokens[tenant] = \
            self._tenant_tokens.get(tenant, 0) + n
        now = self._clock()
        tau = self.config.tenant_rate_tau_s
        r = self._tenant_rate.get(tenant, 0.0)
        if r:
            dt = max(0.0, now - self._tenant_rate_t[tenant])
            r *= math.exp(-dt / tau)
        self._tenant_rate[tenant] = r + n / tau
        self._tenant_rate_t[tenant] = now

    def _door_throttle_reason(self, request: Request) -> Optional[str]:
        """The tenant-quota door check: the reason this submission is
        over quota, or None. Checked BEFORE the request touches the
        queue, the deadline map, or the pool — an over-quota request
        burns nothing."""
        q = self._tenant_quota(request.tenant)
        if q is None:
            return None
        if q.max_resident_blocks is not None:
            # worst-case charge in block_weight units (quantized
            # blocks charge their reduced footprint, so quantization
            # admits requests a full-precision pool would refuse)
            worst = self._block_weight * blocks_needed(
                len(request.prompt) + request.max_new_tokens,
                self.config.block_size)
            if worst > q.max_resident_blocks + 1e-9:
                return (f"needs up to {worst:g} block-units but is "
                        f"capped at max_resident_blocks="
                        f"{q.max_resident_blocks} (it could never run)")
        if (q.max_waiting is not None
                and self.waiting.tenant_depth(request.tenant)
                >= q.max_waiting):
            return (f"already holds {q.max_waiting} waiting entries "
                    f"(max_waiting)")
        if q.tokens_per_s is not None:
            rate = self._tenant_rate_now(request.tenant)
            if rate > q.tokens_per_s:
                return (f"is over its token-rate budget "
                        f"({rate:.1f} > {q.tokens_per_s} tokens/s)")
        return None

    def _tenant_has_resident(self, tenant: str) -> bool:
        return any(s is not None and s.request.tenant == tenant
                   for s in self.slots)

    # -- client lifecycle: cancellation + streaming (docs/serving.md) ------

    def abort(self, uid: str) -> bool:
        """Cancel a WAITING or RESIDENT request: every resource it
        holds is reclaimed now — queue entry removed (DRR walk state
        of the surviving tenants untouched), or its lane freed with
        blocks released via the usual deepest-first discipline — and
        it reaches terminal status ``"cancelled"`` carrying the tokens
        it already emitted. A disconnect callback maps straight onto
        this. Returns False for a uid the engine does not currently
        own (unknown, already terminal, or already drained).

        Safe against the in-flight decode dispatch: the pending drain
        matches lanes by the uid they held AT DISPATCH and discards
        results for an aborted (or re-filled) lane; any K/V the
        dispatch wrote into the freed blocks sits past every live
        sequence's position masks until the blocks' next owner
        overwrites it — the same argument that makes speculative
        rollback and trimmed reservations safe.
        ``check_allocator_integrity`` certifies the reclamation after
        chaos runs mixing aborts with faults and preemptions."""
        if uid not in self._live_uids:
            return False
        removed = self.waiting.expel(lambda e: e.request.uid == uid)
        if removed:
            entry = removed[0]
            self.finished[uid] = list(entry.generated)
            self._set_status(entry.request, "cancelled")
            self._num_cancelled += 1
            return True
        for i, slot in enumerate(self.slots):
            if slot is not None and slot.request.uid == uid:
                self._finish(i, status="cancelled")
                self._num_cancelled += 1
                return True
        return False    # unreachable while _live_uids is consistent

    def pop_stream_events(self) -> List[Tuple[str, int, bool]]:
        """Drain the streaming buffer: ``(uid, token, is_last)`` events
        in emission order, appended as tokens become host-visible (the
        prefill's first token at its fetch, decode tokens at the
        deferred drain) — callers consume tokens as they stream
        instead of waiting on terminal ``run()`` results. Every
        terminal transition — finish, timeout, failure, rejection,
        throttle, cancellation — appends a ``(uid, -1, True)``
        sentinel (the device's -1 "no token" convention), so a
        consumer learns each request's end exactly once; queue-full
        door sheds never entered the engine and emit nothing. The
        buffer grows until popped — a streaming caller should drain it
        every few ticks."""
        out = list(self._stream)
        self._stream.clear()
        return out

    def _request_key(self, entry: _QueueEntry):
        """The request's own PRNG key: engine seed x arrival order.
        Token ``j`` of the request is drawn with ``fold_in(key, j)`` —
        never from a step counter — so draws are invariant to lane
        placement, batch composition, ``decode_steps``, and
        preemption/resume (the re-queued entry keeps its arrival)."""
        return jax.random.fold_in(self._key, entry.arrival)

    def _lane_shard(self, lane: int) -> int:
        """The batch-axis shard owning a lane (contiguous lane groups:
        ``lane // lanes_per_shard``). Always 0 at ``B == 1``."""
        return lane // self._lanes_per_shard

    def _admit_lane_order(self):
        """The free-lane scan order of ``_admit``: plain index order
        unsharded (bit-identical to the pre-batch-axis engine); at
        ``B > 1``, round-robin ACROSS shards (lane 0 of every shard,
        then lane 1, ...) so admissions spread residents — and pool
        pressure — evenly over the data-parallel shards instead of
        filling shard 0 first."""
        if self._batch_shards == 1:
            return range(self.config.max_batch)
        return (s * self._lanes_per_shard + l
                for l in range(self._lanes_per_shard)
                for s in range(self._batch_shards))

    def _invalidate_lanes(self) -> None:
        """Slot composition changed (admit/start/finish/preempt): both
        the decode table and the per-lane arrays must rebuild."""
        self._dev_lanes.invalidate()
        self._dev_tables.invalidate()

    def _invalidate_tables(self) -> None:
        """A lane's block list changed (growth/CoW): same lanes, new
        table rows."""
        self._dev_tables.invalidate()

    def _host_tables(self, decode_only: bool = False) -> np.ndarray:
        """[max_batch, max_blocks_per_seq] host tables (-1 = unmapped).
        ``decode_only`` leaves still-prefilling lanes unmapped so the
        decode step's stray write at position 0 drops out of bounds
        instead of corrupting their first block."""
        t = np.full((self.config.max_batch, self.max_blocks_per_seq), -1,
                    np.int32)
        for i, slot in enumerate(self.slots):
            if slot is None or (decode_only and not slot.started):
                continue
            t[i, : len(slot.blocks)] = slot.blocks
        return t

    def _sampling_arrays(self, per_slot):
        temp = np.zeros(len(per_slot), np.float32)
        top_k = np.zeros(len(per_slot), np.int32)
        top_p = np.ones(len(per_slot), np.float32)
        for i, sp in enumerate(per_slot):
            if sp is not None:
                temp[i], top_k[i], top_p[i] = (sp.temperature, sp.top_k,
                                               sp.top_p)
        return (jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p))

    def _build_decode_tables(self):
        self._table_rebuilds += 1
        return device_block_table(self._host_tables(decode_only=True),
                                  self.config.num_blocks)

    def _build_lane_meta(self):
        """The slot-composition-keyed decode inputs: sampling knobs,
        EOS ids (-1 = none), and per-request PRNG keys, one row per
        lane (zeros/-1 for lanes that are empty or still prefilling —
        their draws are masked to the sentinel on-device)."""
        B = self.config.max_batch
        temp, top_k, top_p = self._sampling_arrays(
            [s.request.sampling if s is not None and s.started else None
             for s in self.slots])
        eos = np.full(B, -1, np.int32)
        arrivals = np.zeros(B, np.int32)
        for i, s in enumerate(self.slots):
            if s is None or not s.started:
                continue
            if s.request.eos_token_id is not None:
                eos[i] = s.request.eos_token_id
            arrivals[i] = s.entry.arrival
        keys = jax.vmap(lambda a: jax.random.fold_in(self._key, a))(
            jnp.asarray(arrivals))
        return temp, top_k, top_p, jnp.asarray(eos), keys

    def _set_status(self, request: Request, status: str,
                    lane: Optional[int] = None) -> None:
        """Record a terminal status: in the drain-able ``statuses`` map,
        on the request object itself, out of the deadline watch and
        the live-uid set, into the tenant's status tally, and onto the
        stream as the ``(uid, -1, True)`` terminal sentinel (every
        terminal transition funnels through here — the uid is
        re-usable from this point, and stream consumers learn
        terminality exactly once). ``lane`` is the slot the request
        exited from (None for queue-side exits) — trace-only context:
        the terminal event closes the lane's residency span."""
        self.statuses[request.uid] = status
        object.__setattr__(request, "status", status)
        self._deadline.pop(request.uid, None)
        self._live_uids.discard(request.uid)
        tally = self._tenant_status.setdefault(request.tenant, {})
        tally[status] = tally.get(status, 0) + 1
        self._stream.append((request.uid, -1, True))
        if self._obs is not None:
            self._obs.note_terminal(request.uid, status, lane=lane)
        self._prune_tenant_if_idle(request.tenant)

    def _tenant_is_listed(self, tenant: str) -> bool:
        """Tenants named in the config (weights or quotas) plus the
        default tenant keep permanent ledger rows."""
        return (tenant == DEFAULT_TENANT
                or tenant in (self.config.tenant_weights or {})
                or tenant in (self.config.tenant_quotas or {}))

    def _prune_tenant_if_idle(self, tenant: str) -> None:
        """Drop an UNLISTED tenant's ledger state once it has no
        waiting or resident footprint: ``tenant`` is a free-form
        client string, and a hostile (or buggy) client minting a fresh
        id per request would otherwise grow five per-tenant maps — and
        every snapshot and ``stats()`` call — without bound, in the
        engine whose whole point is surviving hostile tenants (the
        same hygiene the waiting queue applies to dead priority
        classes). The cost: an ephemeral tenant's token/status tallies
        and rate estimator reset once it drains — list a tenant in
        ``tenant_weights``/``tenant_quotas`` to make its row (and its
        rate budget) permanent. Allocator-side attribution (cached
        blocks, evictions) is untouched and still surfaces its row in
        ``stats()["tenants"]`` while any footprint remains."""
        if self._tenant_is_listed(tenant):
            return
        if (self.waiting.tenant_depth(tenant)
                or self._tenant_has_resident(tenant)):
            return
        self._tenant_seen.discard(tenant)
        self._tenant_tokens.pop(tenant, None)
        self._tenant_rate.pop(tenant, None)
        self._tenant_rate_t.pop(tenant, None)
        self._tenant_status.pop(tenant, None)
        self._tenant_preemptions.pop(tenant, None)

    def _yield_key(self, idx: int):
        """Victim-selection order for preemption and decode quarantine-
        by-elimination: the LOWEST priority class first (largest class
        value), then the youngest (largest ``admit_seq``) — ``max()``
        over this key picks the victim, so a victim's class is always
        >= every survivor's. Uniform-priority traffic reduces exactly
        to the pre-priority youngest-first rule."""
        slot = self.slots[idx]
        return (slot.request.priority, slot.admit_seq, idx)

    @staticmethod
    def _resume_tokens(slot: "_Slot") -> List[int]:
        """The tokens a slot's request carries out of residency — into
        ``finished``, a requeue entry, or a snapshot record. A started
        slot owns its live ``generated`` list; one still mid-prefill
        never resampled, so its history is the queue entry's."""
        return (list(slot.generated) if slot.started
                else list(slot.entry.generated))

    def _finish(self, idx: int, status: str = "finished") -> None:
        """Release the slot: refs drop, and with prefix caching on the
        registered blocks stay cached (evictable) rather than freed.
        Released DEEPEST-first: eviction pops the oldest insertion, and
        evicting a chain's head block orphans every descendant (the
        lookup misses at hash 0), so the tail must age out before the
        head for partial chains to stay matchable. ``status`` is the
        terminal outcome ("finished", or "timeout" for a deadline
        expiry mid-generation — the tokens emitted so far are kept)."""
        slot = self.slots[idx]
        self.allocator.free(list(reversed(slot.blocks)),
                            tenant=slot.request.tenant)
        self.finished[slot.request.uid] = self._resume_tokens(slot)
        # clear the lane BEFORE the terminal transition: _set_status's
        # idle-tenant pruning must not see the finishing slot as a
        # live resident
        self.slots[idx] = None
        self._set_status(slot.request, status, lane=idx)
        self._invalidate_lanes()

    def _quarantine_slot(self, idx: int) -> None:
        """Terminal-fail one lane's request after its dispatches
        exhausted every retry: same release path as a normal finish,
        status ``"failed"``, tokens already emitted kept. The engine —
        and every other lane — keeps serving. With a recorder attached
        the quarantine freezes the current event tail as an incident —
        the poisoned dispatch's post-mortem outlives the ring."""
        uid = self.slots[idx].request.uid
        self._finish(idx, status="failed")
        self._num_quarantines += 1
        if self._obs is not None:
            self._obs.record("quarantine", uid=uid, lane=idx)
            self._obs.incident("quarantine", uid=uid)

    def _expire_deadlines(self, include_started: bool) -> int:
        """Finish every request past its deadline with status
        ``"timeout"`` — gracefully: tokens already emitted ride into
        ``finished``. Waiting entries and mid-prefill (unstarted)
        slots expire any time — an in-flight decode only covers
        STARTED lanes; started slots only when no decode dispatch is
        in flight over them (``include_started`` — callers pass True
        only after the drain), because finishing a lane the pending
        fetch still covers would corrupt the drain bookkeeping."""
        if not self._deadline:
            return 0
        now = self._clock()
        # O(#deadlines) pre-check: only rebuild the queue's deques when
        # something actually expired (the common tick touches nothing)
        due = {uid for uid, dl in self._deadline.items() if now >= dl}
        if not due:
            return 0
        expired = 0
        if self.waiting:
            for entry in self.waiting.expel(
                    lambda e: e.request.uid in due):
                self.finished[entry.request.uid] = list(entry.generated)
                self._set_status(entry.request, "timeout")
                self._num_timeouts += 1
                expired += 1
        for i, slot in enumerate(self.slots):
            if slot is None or (slot.started and not include_started):
                continue
            if slot.request.uid in due:
                self._finish(i, status="timeout")
                self._num_timeouts += 1
                expired += 1
        return expired

    def _reset_device_state(self) -> None:
        """The in-process analog of a crash restore: requeue every
        resident request (preemption-style, carrying its emitted
        tokens, oldest at the head), wipe the allocator — refcounts,
        prefix index, LRU set — and zero the pool. Everything
        device-resident re-derives from host state through re-prefill,
        bit-identically (the resume-determinism contract). Used when a
        failed decode drain may have poisoned the pool; also the
        reason fetch-failure recovery needs no rollback copy."""
        live = sorted(((s.admit_seq, i)
                       for i, s in enumerate(self.slots)
                       if s is not None), reverse=True)
        if self._obs is not None:
            self._obs.record("device_reset", residents=len(live),
                             fetch_failures=self._fetch_failures)
            self._obs.incident("device_reset")
        for _, i in live:    # youngest first, so the oldest lands at head
            slot = self.slots[i]
            requeue_t = self._clock()
            self.waiting.appendleft(_QueueEntry(
                request=slot.request, arrival=slot.entry.arrival,
                generated=self._resume_tokens(slot),
                enq_t=requeue_t, enq_tick=self._num_ticks,
                drr_charged=True))
            self.slots[i] = None
            if self._obs is not None:
                self._obs.note_preempt(slot.request.uid, i,
                                       reason="device_reset", t=requeue_t)
                self._obs.note_enqueue(slot.request.uid,
                                       tenant=slot.request.tenant,
                                       priority=slot.request.priority,
                                       requeue=True, t=requeue_t)
        # requeues are the one path that pushes the queue past
        # max_waiting (by at most max_batch) — the exact overshoot the
        # peak metric exists to expose, sampled here before admission
        # can re-absorb it
        self._queue_depth_peak = max(self._queue_depth_peak,
                                     len(self.waiting))
        self.allocator.reset()
        self.cache = jax.tree.map(jnp.zeros_like, self.cache)
        self._draft_plan = {}   # its lanes no longer exist
        self._invalidate_lanes()

    def _guarded_dispatch(self, site: str, fn, *args):
        """One jitted dispatch (including its fetch, when the caller
        folds it into ``fn``) under the shared retry policy
        (:func:`apex_tpu.utils.faults.guarded_call`): transient
        failures — injected, or the runtime's real dispatch errors —
        retry ``max_dispatch_retries`` times with exponential backoff;
        exhaustion raises :class:`DispatchFailedError` for the caller
        to quarantine the offending request. Retry is sound because
        ``donate_cache`` defaults off: a failed attempt's inputs are
        intact (with donation the pool may be consumed; recover via
        snapshot/restore instead)."""

        def count(attempt):
            self._num_dispatch_retries += 1
            if self._obs is not None:
                self._obs.record("fault_retry", site=site,
                                 attempt=attempt)

        out, _ = guarded_call(
            fn, *args, plan=self.faults, site=site,
            retries=self.config.max_dispatch_retries,
            backoff_s=self.config.retry_backoff_s, on_retry=count)
        return out

    @staticmethod
    def _ewma_update(prev: Optional[float], dt: float) -> float:
        """The feasibility gate's service-time estimator: first
        observation seeds it, later ones blend at ``_EWMA_ALPHA``."""
        dt = max(0.0, float(dt))
        return dt if prev is None else (1.0 - _EWMA_ALPHA) * prev \
            + _EWMA_ALPHA * dt

    def _record_token(self, idx: int, token: int,
                      t_vis: Optional[float] = None) -> None:
        """Append a sampled token to a slot, finishing on EOS/max-len.
        The single funnel for FRESH tokens (resumed histories bypass
        it), so it also feeds the stream-event buffer and the tenant's
        delivered-token ledger exactly once per token. ``t_vis`` is
        the host-visibility timestamp the caller already read (prefill
        fetch end / drain fetch end) — the observer reuses it instead
        of reading the clock again."""
        slot = self.slots[idx]
        slot.generated.append(token)
        slot.last_token = token
        req = slot.request
        self._stream.append((req.uid, int(token), False))
        if self._obs is not None:
            self._obs.note_token(req.uid, t=t_vis)
        self._note_tenant_tokens(req.tenant, 1)
        if ((req.eos_token_id is not None and token == req.eos_token_id)
                or len(slot.generated) >= req.max_new_tokens):
            self._finish(idx)

    # -- prefix caching ----------------------------------------------------

    def _seq_hashes(self, tokens: Sequence[int]) -> List[str]:
        return seq_block_hashes(tokens, self.config.block_size)

    def _register_full_blocks(self, slot: _Slot) -> None:
        """Index every newly-FULL block of the slot (prompt blocks as
        chunks land, generated blocks as decode crosses boundaries)."""
        if not self.config.enable_prefix_caching:
            return
        bs = self.config.block_size
        n_full = slot.context_len // bs
        while slot.num_registered < n_full:
            j = slot.num_registered
            if j >= len(slot.block_hashes):
                prev = slot.block_hashes[j - 1] if j else None
                slot.block_hashes.append(hash_block_tokens(
                    prev, slot.tokens[j * bs: (j + 1) * bs]))
            self.allocator.register_prefix(slot.block_hashes[j],
                                           slot.blocks[j],
                                           tenant=slot.request.tenant)
            slot.num_registered += 1

    # -- data integrity (docs/robustness.md, "Data integrity") -------------

    def _corrupt_payload_hook(self, site: str, payload):
        """The spill store's chaos seam: fire the fault plan at the
        store's read/write site and, on a ``"corrupt"`` hit, hand back
        a seeded-deterministically perturbed copy — the bit flip the
        checksums exist to catch. Identity (and zero extra RNG draws)
        when no corrupt spec matches."""
        self.faults.fire(site)
        seed = self.faults.corrupt_seed(site)
        if seed is None:
            return payload
        return perturb_payload(payload, seed)

    def _maybe_corrupt_record(self, site: str, rec: Dict) -> Dict:
        """Fire the fault plan at a record-artifact site (checkpoint /
        export / import) and perturb the record on a corrupt hit —
        AFTER sealing, so the stale checksum is exactly what detection
        sees. No-op without a plan."""
        if self.faults is None:
            return rec
        self.faults.fire(site)
        seed = self.faults.corrupt_seed(site)
        if seed is None:
            return rec
        return perturb_json(rec, seed)

    def _note_corruption(self, site: str, detail: str) -> None:
        """Count one detected corruption and surface it to the flight
        recorder — EVERY detection path funnels through here, so
        ``num_corruptions_detected`` is the one number the chaos certs
        (and an operator) compare against injected faults."""
        self._num_corruptions_detected += 1
        if self._obs is not None:
            self._obs.record("corruption_detected", site=site,
                             detail=str(detail))

    def _maybe_scrub(self) -> None:
        """The budgeted background integrity pass
        (``scrub_interval_ticks``): re-verify ``scrub_spill_blocks``
        spill entries round-robin and audit the allocator/ledger
        invariants exactly. A corrupt spill entry is discarded (a
        future admission recomputes — the tier's normal miss path); a
        violated allocator invariant RAISES, because a corrupt ledger
        has no safe degradation — the process (or the fleet's failover)
        owns that recovery."""
        interval = self.config.scrub_interval_ticks
        if interval is None or self._num_ticks % interval:
            return
        self._num_scrubs += 1
        verified = corrupt = 0
        if self.spill is not None:
            verified, corrupt = self.spill.scrub(
                self.config.scrub_spill_blocks)
            self._num_scrub_blocks_verified += verified
        self.check_allocator_integrity()
        if self._obs is not None:
            self._obs.record("scrub", verified=int(verified),
                             corrupt=int(corrupt))

    # -- the host-RAM spill tier (docs/serving.md memory tiers) ------------

    def _spill_payload(self, block_id: int, record: bool = True):
        """The allocator's spill fetch: one block's device contents as
        host numpy arrays (scales included for quantized pools), or
        None when the device read fails — the spill is an
        optimization, so a transient fetch error (e.g. a poisoned
        in-flight dispatch surfacing at this sync) just skips it; the
        eviction proceeds as a plain discard and the next prefix miss
        recomputes. Never called from ``_reset_device_state``'s
        allocator reset (reset clears without evicting), so a known-
        poisoned pool is never captured into the host tier.
        ``record=False`` suppresses the recorder's ``spill`` event —
        :meth:`export_prefix_payloads` reads blocks for migration
        transport, which is not an eviction."""
        try:
            payload = {"k": np.asarray(self.cache.k[:, block_id]),
                       "v": np.asarray(self.cache.v[:, block_id])}
            if self.cache.k_scale is not None:
                payload["k_scale"] = np.asarray(
                    self.cache.k_scale[:, block_id])
                payload["v_scale"] = np.asarray(
                    self.cache.v_scale[:, block_id])
        except SimulatedCrash:
            raise
        except Exception:
            return None
        if record and self._obs is not None:
            self._obs.record(
                "spill", block=int(block_id),
                bytes=int(sum(a.nbytes for a in payload.values())))
        return payload

    def _upload_args(self, up_blocks, payloads):
        """Fixed-shape inputs for the ONE upload dispatch an admission
        pays regardless of how many blocks it re-admits: ids padded to
        ``[max_blocks_per_seq]`` with the out-of-bounds id (the
        scatter's ``mode="drop"`` discards padding rows), payloads
        zero-padded to match — one compiled program, one full-pool
        functional update per admission instead of one per block."""
        M = self.max_blocks_per_seq
        ids = np.full(M, self.config.num_blocks, np.int32)
        ids[:len(up_blocks)] = up_blocks

        def stack(key):
            proto = payloads[0][key]
            buf = np.zeros((M,) + proto.shape, proto.dtype)
            for i, p in enumerate(payloads):
                buf[i] = p[key]
            return jnp.asarray(buf)

        args = [jnp.asarray(ids), stack("k"), stack("v")]
        if self.cache.k_scale is not None:
            args += [stack("k_scale"), stack("v_scale")]
        return args

    def _upload_impl(self, cache, ids, k_blk, v_blk, *scales):
        """An admission's spilled blocks re-admitted in ONE scatter:
        ``ids`` is ``[max_blocks_per_seq]`` int32 (out-of-bounds
        padding dropped), payloads ``[M, L, bs, H, D]`` (+ scales for
        quantized pools) — the device half of a spill hit. The
        uploaded bytes are exactly the bytes each block held when it
        was spilled, so a re-admitted prefix attends bit-identically
        to the never-evicted one (and, on the fp path, to recompute)."""
        ids = jnp.asarray(ids, jnp.int32)
        out = KVCache(
            k=cache.k.at[:, ids].set(jnp.moveaxis(k_blk, 0, 1),
                                     mode="drop"),
            v=cache.v.at[:, ids].set(jnp.moveaxis(v_blk, 0, 1),
                                     mode="drop"))
        if scales:
            ks, vs = scales
            out = out._replace(
                k_scale=cache.k_scale.at[:, ids].set(
                    jnp.moveaxis(ks, 0, 1), mode="drop"),
                v_scale=cache.v_scale.at[:, ids].set(
                    jnp.moveaxis(vs, 0, 1), mode="drop"))
        return out

    # -- admission (optimistic: current need, not worst case) --------------

    def _admission_priority_limit(self) -> Optional[int]:
        """The ladder's admission pause (rung 3): classes >=
        ``degrade_admit_priority`` are held in the queue while the
        engine sheds load — UNLESS nothing more urgent exists anywhere
        (no resident lane, no admissible higher-class entry): an
        otherwise-idle engine serves whatever it has (work
        conservation; without it, a queue holding only paused classes
        would deadlock against the stall guard)."""
        if self._degradation_level < 3:
            return None
        limit = self.config.degrade_admit_priority
        if (any(s is not None for s in self.slots)
                or self.waiting.has_priority_below(limit)):
            return limit
        return None

    def _estimate_service_s(self, prompt_tail: int, remaining: int,
                            skips_prefill: bool = False) -> Optional[float]:
        """Contention-free service-time estimate for the feasibility
        gate: uncached-prompt chunks at the prefill EWMA plus
        ``ceil(remaining / K)`` decode dispatches at the decode EWMA —
        a LOWER bound on serving the request's FULL ``max_new_tokens``
        budget (it assumes an idle engine), so the gate only sheds
        requests whose committed demand could not meet the deadline
        even alone. The budget is the demand the gate prices: a
        request counting on an early EOS to beat its deadline should
        ask for fewer tokens (the engine cannot know where EOS falls).
        None (gate open) until at least one dispatch was observed.
        A zero tail still costs one chunk — a fresh fully-cached prompt
        runs one write-suppressed pass for its logits — EXCEPT when the
        caller knows the entry skips prefill entirely (a resumed entry
        whose whole history is cached goes straight to decode)."""
        pf, dc = self._ewma_prefill_s, self._ewma_decode_s
        if pf is None and dc is None:
            return None
        if skips_prefill and prompt_tail <= 0:
            chunks = 0
        else:
            chunks = max(1, -(-max(prompt_tail, 0) // self._chunk))
        # speculating, a dispatch GUARANTEES only one token (every
        # proposal may be rejected) — the conservative per-dispatch
        # floor, like the scan's K
        per = 1 if self.config.spec_tokens > 0 else self.config.decode_steps
        dispatches = -(-max(remaining, 0) // per)
        return chunks * (pf or 0.0) + dispatches * (dc or 0.0)

    def _shed_if_infeasible(self, entry: _QueueEntry,
                            uncached_tail: int,
                            below: Optional[int],
                            skip=None) -> bool:
        """The admit-time feasibility gate: a deadline that cannot
        cover even the contention-free service estimate is shed NOW,
        with status ``"rejected"`` — before the request burns pool
        blocks and prefill compute it is guaranteed to time out of.
        Tokens a preempted entry already carries are preserved."""
        req = entry.request
        dl = self._deadline.get(req.uid)
        if dl is None:
            return False
        remaining = req.max_new_tokens - len(entry.generated)
        if not entry.generated:
            # fresh entry: the FINAL prefill chunk emits the first
            # generated token (_record_token in the prefill tick), so
            # decode owes one fewer — a resumed entry's re-prefill
            # emits nothing new (its tokens ride the queue entry)
            remaining -= 1
        est = self._estimate_service_s(
            uncached_tail, remaining,
            # a resumed entry whose whole history is cached skips
            # prefill entirely (_admit starts it decoding directly)
            skips_prefill=bool(entry.generated) and uncached_tail <= 0)
        if est is None or self._clock() + est <= dl:
            return False
        if self._obs is not None:
            self._obs.note_shed(req.uid, "rejected", queued=True)
        self.waiting.popleft(below=below, skip=skip)  # exactly this entry
        self.finished[req.uid] = list(entry.generated)
        self._set_status(req, "rejected")
        self._num_rejected_infeasible += 1
        return True

    def _note_admitted_wait(self, entry: _QueueEntry):
        wait_ticks = self._num_ticks - entry.enq_tick
        now = self._clock()
        wait_s = max(0.0, now - entry.enq_t)
        self._queue_wait_count += 1
        self._queue_wait_ticks_sum += wait_ticks
        self._queue_wait_ticks_max = max(self._queue_wait_ticks_max,
                                         wait_ticks)
        self._queue_wait_s_sum += wait_s
        self._queue_wait_s_max = max(self._queue_wait_s_max, wait_s)
        return wait_s, now

    def _admit(self) -> int:
        """Move waiting requests into free lanes while the pool can
        cover their CURRENT need — the uncached prompt-tail blocks plus
        one (vs. the old worst-case reservation of the full generation
        budget, which collapsed pool utilization under long
        ``max_new_tokens``; over-commit is safe now that decode-time
        exhaustion preempts instead of aborting). Prefix caching makes
        the need smaller still: the longest cached block-aligned prefix
        is shared by reference, and only the tail is prefilled.

        Candidates are considered class by class, weighted-DRR across
        tenants within a class (:class:`_WaitingQueue`); an
        infeasible-deadline head is shed by the gate and the next
        candidate considered; a head whose TENANT is over its
        resident-block quota is held back (the tenant joins this
        pass's ``skip`` set — other tenants flow past it, so one
        tenant's quota never blocks another's admission), while a head
        that merely does not FIT the pool blocks everything behind it
        (head-of-line blocking — no starvation WITHIN a (class,
        tenant) lane; across classes the strict priority order is the
        design: sustained higher-class load starves lower classes,
        bounded only by their deadlines)."""
        bs = self.config.block_size
        admitted = 0
        below = self._admission_priority_limit()
        skip: set = set()
        for idx in self._admit_lane_order():
            if self.slots[idx] is not None:
                continue
            # at B > 1 every allocation/match of this lane is scoped to
            # its shard's pool range (the shard-residency invariant the
            # sharded programs rely on); None = the whole pool,
            # bit-identical to the pre-batch-axis engine
            shard = (self._lane_shard(idx) if self._batch_shards > 1
                     else None)
            while True:
                entry = self.waiting.head(below=below, skip=skip)
                if entry is None:
                    return admitted
                seq = list(entry.request.prompt)
                if entry.generated:
                    seq += entry.generated[:-1]   # resume: re-cache history
                L = len(seq)
                matched: List[int] = []
                hashes: List[str] = []
                if self.config.enable_prefix_caching:
                    if entry.hashes is None:
                        entry.hashes = self._seq_hashes(seq)
                    hashes = entry.hashes
                    matched = self.allocator.lookup_prefix(hashes,
                                                           shard=shard)
                # the spill tier extends the device match: the run of
                # chain hashes CONTINUING the device prefix that the
                # host store still holds re-admits by upload instead
                # of recompute (chain order matters — a spilled block
                # past a gap is unreachable, exactly like the device
                # index)
                spill_run: List[str] = []
                if self.spill is not None:
                    j = len(matched)
                    while j < len(hashes) and hashes[j] in self.spill:
                        spill_run.append(hashes[j])
                        j += 1
                n_up = len(spill_run)
                m_tok = (len(matched) + n_up) * bs
                if self._shed_if_infeasible(entry, L - m_tok, below, skip):
                    continue    # gate shed the head; try the next one
                tail = blocks_needed(L, bs) - len(matched) - n_up
                # current need = blocks through the FIRST decode write
                # (position L): blocks_needed(L + 1). That is tail + 1
                # only when the prompt exactly fills its blocks — an
                # exact-fit request whose whole generation lives in the
                # last partial block needs no headroom at all.
                # Upload blocks are fresh allocations, so they count.
                need = blocks_needed(L + 1, bs) - len(matched)
                # per-tenant block quota: would this admission push the
                # tenant's fractional resident charge over its cap?
                # (new private blocks charge 1 each; acquiring a
                # matched block adds a 1/(refs + 1) share)
                tenant = entry.request.tenant
                q = self._tenant_quota(tenant)
                if q is not None and q.max_resident_blocks is not None:
                    # charges are in block_weight units (quantized
                    # blocks charge their reduced footprint)
                    extra = self._block_weight * (need + sum(
                        1.0 / (self.allocator.refcount(b) + 1)
                        for b in matched))
                    if (self.allocator.tenant_charge(tenant) + extra
                            > q.max_resident_blocks + 1e-9):
                        if not self._tenant_has_resident(tenant):
                            # nothing of this tenant's will ever free a
                            # block — shed instead of wedging its lane
                            # (unreachable for door-validated requests,
                            # kept as the no-deadlock backstop)
                            if self._obs is not None:
                                self._obs.note_shed(entry.request.uid,
                                                    "throttled",
                                                    queued=True)
                            self.waiting.popleft(below=below, skip=skip)
                            self.finished[entry.request.uid] = \
                                list(entry.generated)
                            self._set_status(entry.request, "throttled")
                            self._num_throttled += 1
                            continue
                        # hold the TENANT, not the queue: its own lanes
                        # must drain first; other tenants flow past
                        skip.add(tenant)
                        continue
                # matched blocks that are currently cached (refcount 0)
                # stop being evictable once we take them, so they don't
                # count toward the capacity the tail can draw from
                reviving = sum(1 for b in matched
                               if self.allocator.refcount(b) == 0)
                if shard is None:
                    capacity = (self.allocator.num_free
                                + self.allocator.num_cached)
                else:
                    capacity = (self.allocator.free_in_shard(shard)
                                + self.allocator.cached_in_shard(shard))
                if need > capacity - reviving:
                    if shard is not None:
                        # this SHARD cannot fit the head; another
                        # shard's free lane may — head-of-line blocking
                        # is per shard at B > 1
                        break
                    # head-of-line blocking: don't let a small request
                    # starve the head
                    return admitted
                self.allocator.acquire(matched, tenant=tenant)
                self.waiting.popleft(below=below, skip=skip)
                wait_s, admit_t = self._note_admitted_wait(entry)
                if self._obs is not None:
                    self._obs.note_admit(entry.request.uid, idx, wait_s,
                                         cached_blocks=len(matched),
                                         t=admit_t)
                # spill hits re-admit by upload: fresh device blocks,
                # the host payloads scattered in by ONE fixed-shape
                # dispatch, the chain hashes registered — the slot
                # owns them exactly like matched blocks, and the
                # positions they cover never re-prefill. Payloads are
                # popped BEFORE the alloc: alloc may itself evict
                # cached blocks INTO the spill store, and the store's
                # byte-bound LRU could then drop exactly the entries
                # this admission probed (the probe does not refresh
                # recency) — popping first makes that race impossible.
                up_blocks: List[int] = []
                if spill_run:
                    # pop one entry at a time, stopping at the first
                    # miss — which includes a CHECKSUM MISMATCH (the
                    # store discards the rotten entry, counts it, and
                    # returns None): entries past a miss are
                    # unreachable exactly like the device index, and
                    # the positions the lost entries would have
                    # covered fall back to recompute (spill is an
                    # optimization, never a correctness dependency)
                    payloads = []
                    ok_run: List[str] = []
                    for h in spill_run:
                        p = self.spill.pop(h)
                        if p is None:
                            break
                        ok_run.append(h)
                        payloads.append(p)
                    if len(ok_run) < n_up:
                        # re-plan: the blocks the lost entries would
                        # have uploaded are recomputed instead. Total
                        # fresh allocations are unchanged (need priced
                        # uploads and tail alike), so the capacity and
                        # quota checks above still hold exactly.
                        tail += n_up - len(ok_run)
                        spill_run, n_up = ok_run, len(ok_run)
                        m_tok = (len(matched) + n_up) * bs
                if spill_run:
                    up_blocks = self.allocator.alloc(n_up, tenant=tenant,
                                                     shard=shard)
                    self.cache = self._upload(
                        self.cache,
                        *self._upload_args(up_blocks, payloads))
                    for h, nb in zip(spill_run, up_blocks):
                        self.allocator.register_prefix(h, nb,
                                                       tenant=tenant)
                    self._spill_hits += n_up
                    if self._obs is not None:
                        self._obs.record("spill_upload",
                                         uid=entry.request.uid,
                                         blocks=n_up)
                if self.spill is not None:
                    # per-BLOCK misses, the same unit as the hits (one
                    # per re-admitted block), so spill_hit_rate is the
                    # fraction of spill-eligible blocks the tier
                    # served; counted only at a committed admission
                    # (not per blocked-head re-peek, which would
                    # inflate the denominator)
                    self._spill_misses += (len(hashes) - len(matched)
                                           - n_up)
                blocks = matched + up_blocks \
                    + (self.allocator.alloc(tail, tenant=tenant,
                                            shard=shard)
                       if tail else [])
                self._prefix_lookup_blocks += len(hashes)
                self._prefix_hit_blocks += len(matched)
                self._prompt_blocks_allocated += tail
                self._admit_count += 1
                slot = _Slot(entry=entry, admit_seq=self._admit_count,
                             tokens=seq, prefill_len=L, prefill_pos=m_tok,
                             context_len=m_tok, blocks=blocks,
                             block_hashes=list(hashes),
                             num_registered=len(matched) + n_up,
                             generated=[], last_token=0, started=False)
                if entry.generated and m_tok == L:
                    # resumed and fully cached: nothing to recompute
                    slot.generated = list(entry.generated)
                    slot.last_token = slot.generated[-1]
                    slot.started = True
                self.slots[idx] = slot
                self._invalidate_lanes()
                admitted += 1
                break
        return admitted

    # -- chunked prefill ---------------------------------------------------

    def _prefill_tick(self) -> bool:
        """Run ONE ``[1, prefill_chunk]`` piece for the oldest admitted
        request still mid-prompt — at most one chunk per step, ahead of
        the decode dispatch, so long prompts load without stalling the
        streaming slots. A fully-prefix-cached prompt still runs one
        final pass with writes suppressed (``write_start == L``): the
        last position's logits are recomputed from the shared blocks
        without allocating or touching a single one."""
        cand = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                if s is not None and not s.started]
        if not cand:
            return False
        idx = min(cand)[1]
        slot = self.slots[idx]
        L, C = slot.prefill_len, self._chunk
        if slot.prefill_pos < L:
            start = slot.prefill_pos
        else:                       # fully cached: logits-only pass
            start = max(0, L - C)
        end = min(start + C, L)
        ids = np.zeros((1, C), np.int32)
        ids[0, : end - start] = slot.tokens[start:end]
        positions = (start + np.arange(C, dtype=np.int32))[None]
        table = np.full((1, self.max_blocks_per_seq), -1, np.int32)
        table[0, : len(slot.blocks)] = slot.blocks
        temp, top_k, top_p = self._sampling_arrays([slot.request.sampling])

        # the EWMA times the attempt BODY only (set by the successful
        # attempt): retry backoff sleeps are failure handling, not
        # service time, and folding them in would inflate the
        # feasibility gate's contention-free lower bound into
        # over-shedding after one transient fault
        attempt_s = [0.0, 0.0]   # [dt, t0] of the successful attempt

        def attempt():
            # dispatch AND fetch inside the retry unit — EVERY chunk,
            # deliberately paying one host sync per chunk: prefill's
            # only device output is one token, and async dispatch
            # surfaces real runtime failures at the fetch — `self.cache`
            # is untouched until the whole attempt succeeds, so a retry
            # reruns the identical program (no rollback needed; under
            # donate_cache a failed attempt consumed the pool and the
            # retry's deleted-buffer error propagates as non-transient).
            # A launch-only guard on intermediate chunks would defer an
            # async failure into a LATER dispatch that shares the (now
            # poisoned) cache — decode over other lanes, or the next
            # chunk — quarantining innocent requests or cascading into
            # the drain-failure reset; the per-chunk sync is the price
            # of exact fault isolation, amortized over C tokens of
            # forward compute
            t0 = self._clock()
            cache, tok = self._prefill(
                self.params, self.cache, jnp.asarray(ids),
                jnp.asarray(positions),
                jnp.asarray([end], jnp.int32),
                jnp.asarray([slot.prefill_pos], jnp.int32),   # write_start
                jnp.asarray([(L - 1) - start], jnp.int32),    # sample_idx
                device_block_table(table, self.config.num_blocks),
                self._request_key(slot.entry), temp, top_k, top_p)
            # the owning shard's sampled token (index 0 == the whole
            # program's single token at B == 1; at B > 1 the sharded
            # prefill returns one candidate per shard and only the
            # lane's shard attended over real K/V)
            tok0 = int(tok[self._lane_shard(idx)
                           if self._batch_shards > 1 else 0])
            # the fetch is part of service time
            attempt_s[0] = self._clock() - t0
            attempt_s[1] = t0
            return cache, tok0

        try:
            self.cache, tok0 = self._guarded_dispatch("prefill", attempt)
        except DispatchFailedError:
            # the failing program saw exactly one request: quarantine it
            # (terminal "failed", blocks released) and keep serving
            self._quarantine_slot(idx)
            return True
        self._ewma_prefill_s = self._ewma_update(self._ewma_prefill_s,
                                                 attempt_s[0])
        self._num_prefill_chunks += 1
        if self._obs is not None:
            self._obs.note_prefill_chunk(slot.request.uid, idx, start,
                                         end, attempt_s[1], attempt_s[0])
        slot.prefill_pos = end
        slot.context_len = max(slot.context_len, end)
        self._register_full_blocks(slot)
        if end == L:
            self._num_prefills += 1
            slot.started = True
            self._invalidate_lanes()
            if slot.entry.generated:
                # resumed after preemption: the history's tokens are
                # already emitted — never resample them
                slot.generated = list(slot.entry.generated)
                slot.last_token = slot.generated[-1]
            else:
                self._record_token(idx, tok0,
                                   t_vis=attempt_s[1] + attempt_s[0])
        return True

    # -- speculative drafting (docs/serving.md) ----------------------------

    def _build_draft_plan(self, active: List[int]) -> None:
        """Ask the drafter for up to ``spec_tokens`` proposals per
        decoding lane — the host half of draft-and-verify, run once per
        decode phase BEFORE the span reservation (the reservation is
        sized by each lane's proposal count).

        Per lane the proposal budget is ``min(spec_tokens, remaining -
        1)``: capping one under the lane's remaining ``max_new_tokens``
        means the verify program can never emit past the budget (it
        emits at most ``proposals + 1`` tokens), which also keeps every
        span write inside ``max_seq_len`` (``add_request`` bounds
        ``prompt + max_new_tokens``). Proposals are sanitized — the
        drafter is third-party code — by truncating at the first token
        outside the vocabulary.

        The drafter runs under the shared retry policy
        (:func:`~apex_tpu.utils.faults.guarded_call`, site ``"draft"``).
        A drafter that exhausts its retries — or raises anything
        non-transient — is **quarantined**: ``_drafter_ok`` flips off
        for the engine's lifetime and every future plan is empty, so
        the verify program degrades to plain single-token decoding
        (bit-identically — a zero-proposal verify IS one decode step)
        instead of the crash killing the engine."""
        self._draft_plan = {}
        if not self._drafter_ok:
            return
        if self._degradation_level >= 1:
            # ladder rung 1: speculation suspended — the same
            # empty-plan degrade path quarantine uses (a zero-proposal
            # verify IS a single decode step, greedy-bit-identically),
            # but REVERSIBLE: plans resume when pressure clears
            return
        S = self.config.spec_tokens
        if self.config.spec_adapt:
            S = min(S, self._spec_cap)
            if S == 0:
                # capped out: every _SPEC_PROBE_EVERY-th plan runs a
                # 1-token probe so acceptance is re-measured and the
                # cap can climb back (otherwise no observations ever
                # arrive and the degrade is permanent)
                self._spec_probe_countdown -= 1
                if self._spec_probe_countdown > 0:
                    return
                self._spec_probe_countdown = _SPEC_PROBE_EVERY
                S = 1
        vocab = self.model.cfg.vocab_size
        plan: Dict[int, List[int]] = {}

        def count(attempt):
            self._num_draft_retries += 1

        for i in active:
            slot = self.slots[i]
            cap = min(S, slot.request.max_new_tokens
                      - len(slot.generated) - 1)
            if cap < 1:
                continue
            history = list(slot.request.prompt) + slot.generated
            try:
                props, _ = guarded_call(
                    self.drafter.propose, history, cap,
                    plan=self.faults, site="draft",
                    retries=self.config.max_dispatch_retries,
                    backoff_s=self.config.retry_backoff_s,
                    on_retry=count)
            except SimulatedCrash:
                raise
            except Exception:
                # retries exhausted (DispatchFailedError) or a drafter
                # bug: degrade to non-speculative decoding, permanently
                self._drafter_ok = False
                self._num_drafter_quarantines += 1
                if self._obs is not None:
                    self._obs.record("drafter_quarantine")
                    self._obs.incident("drafter_quarantine")
                return
            clean: List[int] = []
            for t in list(props)[:cap]:
                t = int(t)
                if not 0 <= t < vocab:
                    break
                clean.append(t)
            if clean:
                plan[i] = clean
        self._draft_plan = plan
        # num_draft_tokens is counted at DISPATCH, not here: proposals
        # a preemption or failed dispatch drops before verification
        # must not dilute the acceptance rate

    # -- decode-time block growth, CoW, preemption -------------------------

    def _preempt_for(self, requester: int) -> bool:
        """Free the lowest-class, youngest lane (:meth:`_yield_key`) to
        un-wedge an allocation for ``requester``; its request re-queues
        at the front of its class carrying its generated tokens. The
        victim's class is >= every survivor's, so preemption never
        inverts priority, and within the class youngest-first
        guarantees the oldest request always progresses, so the system
        drains. Returns False when the requester is the only lane
        (nothing to free — the pool is simply too small for it). At
        ``B > 1`` victims come only from the REQUESTER'S shard: a
        foreign shard's lane frees blocks the requester's shard-scoped
        allocation can never draw from."""
        cand = [i for i, s in enumerate(self.slots) if s is not None
                and (self._batch_shards == 1
                     or self._lane_shard(i)
                     == self._lane_shard(requester))]
        if len(cand) <= 1:
            return False
        idx = max(cand, key=self._yield_key)
        return self._preempt_slot(idx)

    def _preempt_tenant_lane(self, tenant: str, requester: int) -> bool:
        """Quota-driven preemption: a lane growing past its TENANT's
        ``max_resident_blocks`` evicts the tenant's OWN lowest-class,
        youngest other lane — the tenant pays for its growth out of its
        own residency, never another tenant's. Only lanes whose release
        can actually LOWER the tenant's fractional charge are
        candidates: a lane holds such charge iff it owns a block
        privately (refcount 1 — freeing returns a whole unit) or a
        block some OTHER tenant co-holds (freeing shrinks this
        tenant's fraction). A sibling whose every block is fully
        shared within the tenant contributes nothing reclaimable —
        freeing it just re-concentrates the same charge — so evicting
        it would churn lanes without relieving the quota. False when
        no reducing candidate exists (growth proceeds: residency is
        then bounded by lane count x the door-validated worst case)."""
        alloc = self.allocator

        def reduces(slot: "_Slot") -> bool:
            return any(alloc.refcount(b) == 1
                       or alloc.tenant_refcount(b, tenant)
                       < alloc.refcount(b)
                       for b in slot.blocks)

        cand = [i for i, s in enumerate(self.slots)
                if s is not None and i != requester
                and s.request.tenant == tenant and reduces(s)]
        if not cand:
            return False
        idx = max(cand, key=self._yield_key)
        tally = self._tenant_preemptions
        tally[tenant] = tally.get(tenant, 0) + 1
        return self._preempt_slot(idx, reason="quota")

    def _preempt_slot(self, idx: int,
                      reason: str = "pool_pressure") -> bool:
        slot = self.slots[idx]
        gen = self._resume_tokens(slot)
        # deepest-first, same as _finish: keep evictable chains matchable
        self.allocator.free(list(reversed(slot.blocks)),
                            tenant=slot.request.tenant)
        requeue_t = self._clock()
        self.waiting.appendleft(_QueueEntry(request=slot.request,
                                            arrival=slot.entry.arrival,
                                            generated=gen,
                                            enq_t=requeue_t,
                                            enq_tick=self._num_ticks,
                                            drr_charged=True))
        # sample the peak at the requeue itself — admission may
        # re-absorb the entry before step()'s end-of-tick sample
        self._queue_depth_peak = max(self._queue_depth_peak,
                                     len(self.waiting))
        self.slots[idx] = None
        self._invalidate_lanes()
        self._num_preemptions += 1
        if self._obs is not None:
            self._obs.note_preempt(slot.request.uid, idx, reason=reason,
                                   t=requeue_t)
            self._obs.note_enqueue(slot.request.uid,
                                   tenant=slot.request.tenant,
                                   priority=slot.request.priority,
                                   requeue=True, t=requeue_t)
        return True

    def _ensure_decode_blocks(self) -> None:
        """Each started slot is about to write K/V at positions
        ``context_len .. context_len + span - 1`` (``span`` = the
        coming dispatch's write bound: ``decode_steps`` capped by the
        lane's remaining budget — or, speculating, the carried token
        plus the lane's proposal count, every candidate K/V landing in
        the same dispatch whether or not it is accepted) — make sure
        PRIVATE blocks
        cover the whole span: allocate the missing tail (preempting the
        youngest lane if the pool is dry), and copy-on-write any
        covering block shared with another sequence (a full-block
        prefix match never shares a partial tail, so CoW is a guard for
        exotic sharing patterns, not the steady state). Reserving the
        span UP FRONT keeps the scan free of host intervention: a
        mid-scan allocation failure is impossible, so preemption
        granularity is K tokens, decided before the dispatch."""
        bs = self.config.block_size
        K = self.config.decode_steps
        order = sorted((s.admit_seq, i) for i, s in enumerate(self.slots)
                       if s is not None and s.started)
        for _, i in order:
            while self.slots[i] is not None:
                slot = self.slots[i]
                if self.config.spec_tokens > 0:
                    # verify-span writes: the carried token + every
                    # proposal (rejected ones too — the drain trims
                    # blocks the rejection strands back to the pool)
                    span = 1 + len(self._draft_plan.get(i, ()))
                else:
                    span = min(K, slot.request.max_new_tokens
                               - len(slot.generated))
                need = blocks_needed(slot.context_len + span, bs)
                if len(slot.blocks) < need:
                    grow = need - len(slot.blocks)
                    tenant = slot.request.tenant
                    q = self._tenant_quota(tenant)
                    if (q is not None
                            and q.max_resident_blocks is not None
                            and self.allocator.tenant_charge(tenant)
                            + grow * self._block_weight
                            > q.max_resident_blocks + 1e-9
                            and self._preempt_tenant_lane(tenant, i)):
                        # over quota: the tenant paid with its own
                        # youngest lane — re-check (the freed charge
                        # usually covers the growth). When no other
                        # lane of the tenant exists, growth proceeds:
                        # a single lane's private worst case fits the
                        # quota by the door bound.
                        continue
                    try:
                        slot.blocks.extend(
                            self.allocator.alloc(
                                grow, tenant=tenant,
                                shard=(self._lane_shard(i)
                                       if self._batch_shards > 1
                                       else None)))
                        self._invalidate_tables()
                    except CacheOutOfBlocks:
                        if not self._preempt_for(i):
                            if self._obs is not None:
                                self._obs.record(
                                    "alloc_pressure",
                                    uid=slot.request.uid,
                                    free=self.allocator.num_free)
                            raise CacheOutOfBlocks(
                                f"request {slot.request.uid!r} cannot grow "
                                f"past {slot.context_len} cached tokens: "
                                f"{self.allocator.num_free} blocks free of "
                                f"{self.allocator.num_blocks} and no other "
                                "lane left to preempt")
                    continue   # re-check: the slot itself may be gone
                first = slot.context_len // bs
                last = (slot.context_len + span - 1) // bs
                j = next((j for j in range(first, last + 1)
                          if self.allocator.refcount(slot.blocks[j]) > 1),
                         None)
                if j is None:
                    break
                try:
                    # CoW rides outside the tenant quota check: it nets
                    # +1 - (shared fraction) charge, bounded by the
                    # same door-validated worst case. The private copy
                    # lands on the slot's shard (src and dst must share
                    # one for the sharded copy program).
                    nb = self.allocator.alloc(
                        1, tenant=slot.request.tenant,
                        shard=(self._lane_shard(i)
                               if self._batch_shards > 1 else None))[0]
                except CacheOutOfBlocks:
                    if not self._preempt_for(i):
                        if self._obs is not None:
                            self._obs.record(
                                "alloc_pressure", uid=slot.request.uid,
                                free=self.allocator.num_free)
                        raise CacheOutOfBlocks(
                            f"request {slot.request.uid!r}: cannot "
                            "copy-on-write a shared block, pool "
                            "exhausted and no lane left to preempt")
                    continue
                b = slot.blocks[j]
                self.cache = self._cow(self.cache,
                                       jnp.int32(b), jnp.int32(nb))
                self.allocator.free([b], tenant=slot.request.tenant)
                slot.blocks[j] = nb
                self._invalidate_tables()
                # the copy diverges from the indexed contents the
                # moment we append; registration state stays with
                # the ORIGINAL block
                if slot.num_registered > j:
                    slot.num_registered = j
                self._num_cow_copies += 1
                # loop again: the span may cross FURTHER shared blocks

    # -- the fused decode dispatch + deferred drain ------------------------

    def _dispatch_decode(self, active: List[int]) -> None:
        """Launch the K-step fused decode for ``active`` lanes and
        leave the result in flight (``self._pending``). Only the small
        per-tick arrays (tokens, context lens, budgets, counts) upload
        here; the block table and lane meta come from their mirrors.

        When the dispatch exhausts its retries, the batch is poisoned
        but nothing says which lane: isolation is by elimination — the
        lowest-class youngest lane is quarantined (same yield order as
        preemption, :meth:`_yield_key`) and the
        dispatch is rebuilt over the survivors, until it launches or no
        decoding lane remains. A persistent site-wide fault therefore
        fails requests one at a time instead of killing the engine."""
        B = self.config.max_batch
        spec = self.config.spec_tokens > 0
        while active:
            tokens = np.zeros(B, np.int32)
            ctx = np.zeros(B, np.int32)
            budgets = np.zeros(B, np.int32)
            gcounts = np.zeros(B, np.int32)
            for i in active:
                slot = self.slots[i]
                tokens[i] = slot.last_token
                ctx[i] = slot.context_len
                budgets[i] = (slot.request.max_new_tokens
                              - len(slot.generated))
                gcounts[i] = len(slot.generated)
            tables = self._dev_tables.get(self._build_decode_tables)
            temp, top_k, top_p, eos, keys = self._dev_lanes.get(
                self._build_lane_meta)
            if spec:
                # this tick's draft plan, as fixed-shape arrays: the
                # verify program's ONE compiled shape regardless of
                # how many proposals each lane actually carries
                drafts = np.zeros((B, self.config.spec_tokens), np.int32)
                dlens = np.zeros(B, np.int32)
                for i in active:
                    p = self._draft_plan.get(i, ())
                    drafts[i, : len(p)] = p
                    dlens[i] = len(p)
                args = (self.params, self.cache, jnp.asarray(tokens),
                        jnp.asarray(drafts), jnp.asarray(dlens), tables,
                        jnp.asarray(ctx), jnp.asarray(budgets),
                        jnp.asarray(gcounts), eos, keys, temp, top_k,
                        top_p)
            else:
                args = (self.params, self.cache, jnp.asarray(tokens),
                        tables, jnp.asarray(ctx), jnp.asarray(budgets),
                        jnp.asarray(gcounts), eos, keys, temp, top_k,
                        top_p)
            try:
                self.cache, toks = self._guarded_dispatch(
                    "decode", self._decode, *args)
            except DispatchFailedError:
                idx = max(active, key=self._yield_key)
                self._quarantine_slot(idx)
                active = [i for i, s in enumerate(self.slots)
                          if s is not None and s.started]
                continue
            self._num_decode_dispatches += 1
            # the SDC fault model (docs/robustness.md): a "corrupt"
            # spec at the decode site marks THIS dispatch's output for
            # a seeded wrong-token perturbation at the drain — the
            # silent wrong-compute no checksum can catch (the fleet's
            # determinism cross-check exists for exactly this)
            self._pending_corrupt = (
                self.faults.corrupt_seed("decode")
                if self.faults is not None else None)
            if spec:
                # count drafted tokens HERE, for the lanes this
                # dispatch actually verifies — plan-time counting would
                # inflate the acceptance-rate denominator with
                # proposals that preemption or a failed dispatch
                # dropped before any verification could accept them
                self._num_draft_tokens += int(dlens.sum())
            # the uid each covered lane held at dispatch: the drain
            # discards results for lanes whose request was aborted (or
            # whose lane was re-filled) while the dispatch was in
            # flight — matching on uid, not lane index
            self._pending = (toks, list(active),
                             {i: self.slots[i].request.uid
                              for i in active})
            if self._obs is not None:
                self._pending_obs = (self._clock(),
                                     self._num_decode_dispatches)
            return

    def _drain_decode(self) -> bool:
        """The deferred host sync: fetch the in-flight dispatch's
        ``[B, K]`` tokens (the ONLY decode-path block on the device)
        and replay them through the per-token bookkeeping —
        cache-token append, block registration, EOS/budget finish. The
        device's stop mask mirrors ``_record_token`` exactly, so a lane
        that froze mid-scan finishes here on the same token.

        Dispatch is asynchronous, so a REAL runtime failure surfaces
        here, at the fetch, not at the launch `_guarded_dispatch`
        guards — and a failed program poisons every output it produced,
        including the new pool. Recovery is the in-process analog of a
        crash restore (:meth:`_reset_device_state`): every resident
        request re-queues carrying its emitted tokens, the allocator
        and prefix index reset, the pool zeroes, and re-prefill
        re-derives everything — bit-identical continuation by the same
        resume determinism ``restore()`` leans on, and valid even under
        ``donate_cache`` (nothing from the failed dispatch is reused).
        Consecutive drain failures count against
        ``max_dispatch_retries``; exhaustion quarantines the youngest
        covered lane before the reset."""
        if self._pending is None:
            return False
        toks, active, uids = self._pending
        self._pending = None
        pending_obs, self._pending_obs = self._pending_obs, None
        corrupt_seed, self._pending_corrupt = self._pending_corrupt, None
        # the decode EWMA times THIS fetch block only — the remaining
        # in-flight device time at drain. The full launch->drain span
        # would fold caller inter-tick pauses and host scheduling into
        # the feasibility gate's "contention-free lower bound" and
        # over-shed (the same reasoning that keeps retry backoff out of
        # the prefill EWMA); under-measuring merely sheds less — the
        # safe direction for a lower bound.
        t_fetch = self._clock()
        try:
            toks = np.asarray(toks)
        except SimulatedCrash:
            raise
        except TRANSIENT_ERRORS:
            self._fetch_failures += 1
            if self._fetch_failures > self.config.max_dispatch_retries:
                # exhausted — same attempt arithmetic as guarded_call
                # (N retries = N+1 attempts, no sleep after the last),
                # so serving/training retry counters stay comparable
                live = [i for i in active
                        if self.slots[i] is not None
                        and self.slots[i].started
                        # a lane aborted (and possibly re-filled)
                        # mid-flight was no part of the failed
                        # dispatch: never quarantine its new owner
                        and self.slots[i].request.uid == uids[i]]
                if live:
                    idx = max(live, key=self._yield_key)
                    self._quarantine_slot(idx)
                self._fetch_failures = 0
            else:
                self._num_dispatch_retries += 1
                if self._obs is not None:
                    self._obs.record("fault_retry", site="decode_drain",
                                     attempt=self._fetch_failures)
                if self.config.retry_backoff_s > 0.0:
                    time.sleep(self.config.retry_backoff_s
                               * (2 ** (self._fetch_failures - 1)))
            self._reset_device_state()
            return True
        self._fetch_failures = 0
        t_end = self._clock()
        self._ewma_decode_s = self._ewma_update(
            self._ewma_decode_s, t_end - t_fetch)
        # each lane's emitted tokens are its non-sentinel prefix (lanes
        # freeze permanently mid-scan, and real token ids are >= 0)
        counts = (toks >= 0).sum(axis=1)
        if corrupt_seed is not None:
            # the injected SDC: one emitted token flips to a different
            # in-vocabulary id. Deliberately applied BEFORE any host
            # bookkeeping — the wrong token feeds the KV append, the
            # stream, and the next dispatch's context exactly like a
            # real flaky-chip sample would, and NOTHING in this engine
            # can tell (detection is the fleet cross-check's job).
            toks = perturb_tokens(toks, counts,
                                  self.model.cfg.vocab_size,
                                  corrupt_seed)
        if self._obs is not None and pending_obs is not None:
            # trace the dispatch BEFORE replaying its tokens, so each
            # request's timeline reads decode -> drain -> terminal in
            # emission order; aborted/re-filled lanes (uid mismatch)
            # are excluded exactly as the replay below excludes them
            self._obs.note_decode_drained(
                pending_obs[1], pending_obs[0], t_end, t_end - t_fetch,
                [(uids[i], i, int(counts[i])) for i in active
                 if self.slots[i] is not None
                 and self.slots[i].request.uid == uids[i]])
        spec = self.config.spec_tokens > 0
        bs = self.config.block_size
        drafted_this = accepted_this = 0
        for i in active:
            slot = self.slots[i]
            if slot is None or slot.request.uid != uids[i]:
                # the lane's request was aborted (and the lane possibly
                # re-filled by admission) while this dispatch was in
                # flight: its results are DISCARDED — the blocks were
                # already reclaimed, and any K/V the dispatch wrote to
                # them sits past every live sequence's masks until
                # overwritten (docs/serving.md, cancellation)
                continue
            n = int(counts[i])
            for j in range(n):
                slot.tokens.append(slot.last_token)   # its K/V landed
                slot.context_len += 1
                self._register_full_blocks(slot)
                self._record_token(i, int(toks[i, j]), t_vis=t_end)
                if self.slots[i] is None:
                    break
            self._num_tokens_decoded += n
            if not spec:
                continue
            # speculative bookkeeping: an emitted token that matches
            # the lane's proposal at its index IS an accepted draft
            # (the correction is drawn with the draft masked out and a
            # greedy rejection means argmax != draft, so a match can
            # only be an acceptance; the bonus sits past the plan)
            prop = self._draft_plan.get(i, ())
            drafted_this += len(prop)
            for j in range(min(n, len(prop))):
                if int(toks[i, j]) != prop[j]:
                    break
                self._num_accepted_tokens += 1
                accepted_this += 1
            # reservation rollback: the span was reserved for EVERY
            # proposal's write, but rejection advanced the context by
            # less — blocks holding only unaccepted K/V go back to the
            # pool now instead of idling on the slot (the K/V itself
            # needs no rollback: it sits past the context length every
            # attention mask already excludes)
            slot = self.slots[i]
            if slot is not None:
                keep = blocks_needed(slot.context_len, bs)
                if len(slot.blocks) > keep:
                    trimmed = len(slot.blocks) - keep
                    slot.blocks = self.allocator.trim_to(
                        slot.blocks, keep, tenant=slot.request.tenant)
                    self._num_spec_blocks_rolled_back += trimmed
                    # deliberately NO table invalidation: the trimmed
                    # entries sit past blocks_needed(context_len), so
                    # every gather of them is position-masked, and any
                    # future span reaching that region must first
                    # allocate (need > len(blocks)) — which invalidates
                    # and rebuilds. Skipping it here keeps the device
                    # mirror warm in the low-acceptance regime, where
                    # trim would otherwise force a rebuild every tick.
                    # (Eager reclaim itself is load-bearing: held
                    # reservations would let a low-acceptance engine
                    # squat on spec_tokens-worth of blocks per lane,
                    # changing admission/preemption under tight pools.)
        if spec and self.config.spec_adapt and drafted_this:
            # dynamic speculation (docs/serving.md): the acceptance
            # EWMA walks the per-plan draft cap one step per
            # observation — below spec_accept_low shrink toward 0
            # (riding the rung-1 empty-plan machinery), above
            # spec_accept_high restore toward spec_tokens; the dead
            # band between them is the hysteresis. While acceptance
            # stays >= high the cap never moves, so the engine is
            # bit-identical to static speculation.
            self._spec_accept_ewma = self._ewma_update(
                self._spec_accept_ewma, accepted_this / drafted_this)
            if (self._spec_accept_ewma < self.config.spec_accept_low
                    and self._spec_cap > 0):
                self._spec_cap -= 1
                self._num_spec_cap_shrinks += 1
                if self._obs is not None:
                    self._obs.record("spec_cap", cap=self._spec_cap,
                                     direction="shrink",
                                     ewma=self._spec_accept_ewma)
            elif (self._spec_accept_ewma > self.config.spec_accept_high
                    and self._spec_cap < self.config.spec_tokens):
                self._spec_cap += 1
                self._num_spec_cap_restores += 1
                if self._obs is not None:
                    self._obs.record("spec_cap", cap=self._spec_cap,
                                     direction="restore",
                                     ewma=self._spec_accept_ewma)
        return True

    # -- the degradation ladder (docs/robustness.md) -----------------------

    def _ladder_enabled(self) -> bool:
        return (self.config.queue_high_watermark is not None
                or self.config.free_block_low_watermark is not None)

    def _under_pressure(self) -> bool:
        """The watermark signal: queue depth at/over the high mark, or
        the ALLOCATABLE fraction — free plus evictable (cached)
        blocks, the headroom ``alloc()`` can actually draw on — at/
        under the low mark. Counting evictable as headroom matters: a
        warm prefix cache under light traffic parks most of the pool
        at refcount 0, and a bare free-list signal would read that
        healthy state as overload and drive a perpetual
        degrade/flush/re-warm sawtooth. The flip side is that rung 2's
        flush does not relieve THIS signal (free + cached is invariant
        under it) — correct, since block pressure the flush can't fix
        is active-sequence pressure, which only draining relieves;
        the flush's value is making that headroom 1-hop allocatable."""
        cfg = self.config
        if (cfg.queue_high_watermark is not None
                and len(self.waiting) >= cfg.queue_high_watermark):
            return True
        if cfg.free_block_low_watermark is not None:
            allocatable = (self.allocator.num_free
                           + self.allocator.num_cached)
            if (allocatable / max(self.allocator.num_blocks, 1)
                    <= cfg.free_block_low_watermark):
                return True
        return False

    def _update_ladder(self) -> bool:
        """One hysteresis tick of the degradation ladder: after
        ``degrade_patience`` CONSECUTIVE pressure ticks, step one rung
        down; after as many consecutive clear ticks, one rung up —
        deterministic, single-rung transitions, so a given (traffic,
        clock) schedule always walks the same ladder path. While at
        rung >= 2 every tick flushes the prefix cache's evictable
        blocks back to the free list (trading future hits for
        allocatable headroom). Rung 1 (speculation suspended) is
        enforced in :meth:`_build_draft_plan`; rung 3 (lowest-class
        admission pause) in :meth:`_admission_priority_limit`. Returns
        whether a transition happened (it counts as step progress)."""
        if not self._ladder_enabled():
            return False
        transition = False
        if self._under_pressure():
            self._pressure_streak += 1
            self._clear_streak = 0
            if (self._degradation_level < _LADDER_TOP
                    and self._pressure_streak
                    >= self.config.degrade_patience):
                self._degradation_level += 1
                self._pressure_streak = 0
                self._num_degrade_steps_down += 1
                transition = True
                if self._obs is not None:
                    self._obs.record("ladder", direction="down",
                                     level=self._degradation_level)
        else:
            self._clear_streak += 1
            self._pressure_streak = 0
            if (self._degradation_level > 0
                    and self._clear_streak >= self.config.degrade_patience):
                self._degradation_level -= 1
                self._clear_streak = 0
                self._num_degrade_steps_up += 1
                transition = True
                if self._obs is not None:
                    self._obs.record("ladder", direction="up",
                                     level=self._degradation_level)
        if self._degradation_level >= 2:
            self._num_degrade_flushed_blocks += \
                self.allocator.flush_evictable()
        return transition

    def step(self) -> bool:
        """One scheduler tick: update the degradation ladder, expire
        deadlines, admit, run at most one prefill chunk, drain the
        previous tick's in-flight decode, then dispatch one fused
        K-step decode for every started slot (if any). The drain comes
        AFTER admission/prefill on purpose — tick t+1's host scheduling
        work overlaps tick t's device decode (the deferred sync) — with
        an admission top-up behind it so lanes freed by the drain (or a
        timeout) don't idle a tick.

        Returns True when the tick made progress — admitted, chunked,
        drained, expired, shed, dispatched, preempted, quarantined, or
        stepped the ladder. ``run()`` turns a no-progress tick with
        work remaining into :class:`EngineStalledError` instead of
        spinning.
        """
        self._num_ticks += 1
        pre_shed = self._num_rejected_infeasible
        stepped = self._update_ladder()
        # waiting entries and mid-prefill slots are expirable up front
        # (so an expired slot never gets one last wasted chunk);
        # started slots only when no decode dispatch is in flight over
        # them — otherwise the post-drain sweep picks them up
        expired = self._expire_deadlines(
            include_started=self._pending is None)
        admitted = self._admit()
        chunked = self._prefill_tick()
        synced = self._drain_decode()
        # the in-flight dispatch (if any) is drained now, so resident
        # slots are safe to expire too
        expired += self._expire_deadlines(include_started=True)
        if synced or expired:
            admitted += self._admit()
        self._queue_depth_peak = max(self._queue_depth_peak,
                                     len(self.waiting))
        shed = self._num_rejected_infeasible - pre_shed
        made = bool(admitted or chunked or synced or expired or stepped
                    or shed)
        if all(s is None for s in self.slots):
            if self.waiting and not made:
                # zero live sequences and nothing in flight means
                # nothing will ever free a block — the queue head can
                # never be admitted (the pool is undersized for it).
                # Raise, don't spin. (The ladder cannot park us here:
                # its admission pause yields to work conservation the
                # moment nothing more urgent exists.)
                entry = self.waiting.head()
                need = blocks_needed(len(entry.request.prompt) + 1,
                                     self.config.block_size)
                if self._obs is not None:
                    self._obs.record("alloc_pressure",
                                     uid=entry.request.uid, need=need)
                raise CacheOutOfBlocks(
                    f"request {entry.request.uid!r} needs {need} blocks "
                    f"to admit but only {self.allocator.num_blocks} exist "
                    "in the pool")
            self._maybe_scrub()
            self._maybe_checkpoint()
            self._record_tick(admitted, chunked, synced, expired, shed,
                              made)
            return made
        pre_preempt = self._num_preemptions
        pre_quarantine = self._num_quarantines
        active = [i for i, s in enumerate(self.slots)
                  if s is not None and s.started]
        if active and self.config.spec_tokens > 0:
            # proposals first: the span reservation below is sized by
            # each lane's proposal count
            self._build_draft_plan(active)
        if active:
            self._ensure_decode_blocks()
            # preemption may have cleared lanes — re-collect
            active = [i for i, s in enumerate(self.slots)
                      if s is not None and s.started]
        if active:
            self._dispatch_decode(active)
        progressed = bool(made or self._pending is not None
                          or self._num_preemptions > pre_preempt
                          or self._num_quarantines > pre_quarantine)
        self._maybe_scrub()
        self._maybe_checkpoint()
        self._record_tick(admitted, chunked, synced, expired, shed,
                          progressed)
        return progressed

    def _maybe_checkpoint(self) -> None:
        """The ``snapshot_interval_ticks`` cadence: refresh
        ``last_checkpoint`` at the end of every N-th tick. Lightweight
        by construction (:meth:`checkpoint` never drains), so the
        steady-state tick pays only the host-side record build."""
        interval = self.config.snapshot_interval_ticks
        if interval is not None and self._num_ticks % interval == 0:
            self.checkpoint()

    def _record_tick(self, admitted: int, chunked: bool, synced: bool,
                     expired: int, shed: int, progress: bool) -> None:
        """One flight-recorder ``tick`` summary per ``step()`` — the
        rolling narration of what the scheduler decided, O(1) per tick
        and only when a recorder is attached."""
        obs = self._obs
        if obs is None or obs.recorder is None:
            return
        obs.record(
            "tick", tick=self._num_ticks, admitted=int(admitted),
            chunked=bool(chunked), drained=bool(synced),
            expired=int(expired), shed=int(shed),
            progress=bool(progress),
            active=sum(s is not None for s in self.slots),
            waiting=len(self.waiting),
            blocks_free=self.allocator.num_free,
            level=self._degradation_level)

    @property
    def has_work(self) -> bool:
        """True while anything is queued, resident in a lane, or IN
        FLIGHT (an undrained decode dispatch). This is ``run()``'s loop
        condition, public so external step-at-a-time drivers (bench.py
        samples utilization per tick) drain completely without
        duplicating it — a hand-rolled ``waiting or slots`` check would
        silently drop the last dispatch's tokens."""
        return (bool(self.waiting) or self._pending is not None
                or any(s is not None for s in self.slots))

    def run(self, return_status: bool = False):
        """Drain: step until every queued, active, and in-flight
        request reaches a terminal state. Returns ``{uid:
        generated_token_ids}`` — or, with ``return_status=True``,
        ``{uid: RequestResult(tokens, status)}`` where ``status`` is
        ``"finished"`` | ``"timeout"`` | ``"failed"`` | ``"rejected"``
        | ``"throttled"`` | ``"cancelled"`` (the result
        contract in docs/serving.md; the same status is written onto
        each ``Request.status``). If a full step makes no progress
        while work remains, raises :class:`EngineStalledError` with
        ``stats()`` attached instead of spinning forever (plus the
        flight recorder's tail when an observer is attached — and any
        exception escaping the drive loop writes the observer's crash
        dump to its ``crash_dump_path`` before propagating, so the
        next dead bench section ships its own post-mortem)."""
        try:
            while self.has_work:
                if not self.step():
                    tail = None
                    if self._obs is not None:
                        self._obs.record("stall")
                        if self._obs.recorder is not None:
                            tail = self._obs.recorder.tail()
                    raise EngineStalledError(
                        "engine has work but a full step made no "
                        "progress", self.stats(), recorder_tail=tail)
        except Exception as e:
            if self._obs is not None:
                self._obs.crash_dump(e)
            raise
        out, self.finished = self.finished, {}
        statuses, self.statuses = self.statuses, {}
        # run() IS the non-streaming consumption path: the terminal
        # result dict it returns supersedes any unconsumed stream
        # events, so drop them — otherwise every run()-based caller
        # (which never calls pop_stream_events) leaks one buffered
        # event per token for the engine's lifetime. Streaming callers
        # drain via pop_stream_events BEFORE the terminal run().
        self._stream.clear()
        if return_status:
            return {uid: RequestResult(tokens=toks,
                                       status=statuses.get(uid, "finished"))
                    for uid, toks in out.items()}
        return out

    # -- the fleet surface (docs/fleet.md) ---------------------------------

    def pop_results(self) -> Dict[str, "RequestResult"]:
        """Drain every terminal result accumulated so far WITHOUT
        stepping the engine — the fleet router's per-tick result
        collection (``run()`` is the drive-to-completion variant; this
        is the incremental one). Each drained uid becomes reusable,
        exactly as after ``run()``. Stream events are left alone:
        streaming callers drain them via :meth:`pop_stream_events`."""
        out, self.finished = self.finished, {}
        statuses, self.statuses = self.statuses, {}
        return {uid: RequestResult(tokens=toks,
                                   status=statuses.get(uid, "finished"))
                for uid, toks in out.items()}

    def load(self) -> Dict[str, float]:
        """The cheap health/load surface a fleet router polls per
        routing decision — a strict (float-valued) subset of
        ``stats()``, built without the full dict: queue depth, active
        lanes, the feasibility-gate service EWMAs, and allocatable
        headroom (free + evictable blocks, the same measure the
        degradation ladder reads)."""
        return {
            "queue_depth": float(len(self.waiting)),
            "active_slots": float(
                sum(s is not None for s in self.slots)),
            "ewma_prefill_dispatch_s": float(self._ewma_prefill_s or 0.0),
            "ewma_decode_dispatch_s": float(self._ewma_decode_s or 0.0),
            "blocks_allocatable": float(self.allocator.num_free
                                        + self.allocator.num_cached),
        }

    # the replica-surface discriminator a router reads to know whether
    # this replica lives in its own OS process (ProcessReplica reports
    # "process"); a class attribute so even a dead slot still answers
    mode = "in_process"

    @property
    def block_weight(self) -> float:
        """The per-block resident-cost weight (1.0 unquantized; the
        packed fraction under KV quantization) — part of the narrow
        replica surface so the router's door throttle can price tenant
        block charges without reaching into engine internals (which a
        process replica could not serve)."""
        return float(self._block_weight)

    @property
    def queue_depth(self) -> int:
        """``len(waiting)`` as a surface method — the router's
        ``stats()`` aggregate reads this, not the queue object."""
        return len(self.waiting)

    @property
    def active_slot_count(self) -> int:
        """Occupied decode lanes — same narrow-surface rationale as
        :attr:`queue_depth`."""
        return sum(s is not None for s in self.slots)

    def tenant_charge(self, tenant: str) -> int:
        """The tenant's resident-block charge (allocator attribution),
        surfaced for the router's per-tenant door throttle."""
        return self.allocator.tenant_charge(tenant)

    def tenant_depth(self, tenant: str) -> int:
        """The tenant's waiting-queue depth, surfaced for the router's
        per-tenant door throttle."""
        return self.waiting.tenant_depth(tenant)

    def probe_prefix(self, hashes: Sequence[str]) -> int:
        """How many leading blocks of a hash chain this engine could
        serve WITHOUT recompute: the device prefix index's longest
        match, extended by the contiguous run of spilled hashes the
        host tier holds (the same lookup :meth:`_admit` performs, read
        only — no references taken, no LRU perturbation). The fleet
        router's prefix-affinity signal: SHA-256 chain hashes are
        globally comparable, so any replica can score any prompt."""
        if not self.config.enable_prefix_caching:
            return 0
        n = len(self.allocator.lookup_prefix(hashes))
        if self.spill is not None:
            while n < len(hashes) and hashes[n] in self.spill:
                n += 1
        return n

    def spilled_hashes(self) -> Dict[str, str]:
        """Chain hash -> owning tenant for every entry resident in the
        local host spill tier — the fleet router's shared-tier publish
        sweep reads this to learn what this replica evicted (and whose
        it was), then pulls the payloads it wants through
        :meth:`export_prefix_payloads`. Read-only, host-side,
        JSON-friendly: part of the narrow replica surface. Empty with
        no spill tier configured."""
        if self.spill is None:
            return {}
        return self.spill.entry_tenants()

    def decoding_uids(self) -> List[str]:
        """Uids of resident slots whose prefill has COMPLETED (first
        token known, decode phase entered), in admission order. The
        disaggregated fleet's handoff signal (docs/fleet.md,
        "Disaggregated roles"): a prefill-specialist replica's router
        migrates exactly these to a decode specialist each tick —
        waiting entries and mid-prefill lanes stay put. Read-only,
        host-side, no sync."""
        started = [(s.admit_seq, s.request.uid) for s in self.slots
                   if s is not None and s.started]
        return [uid for _, uid in sorted(started)]

    def export_requests(self, uids: Optional[Sequence[str]] = None
                        ) -> List[Dict]:
        """Drain-and-migrate EXPORT: remove the given waiting/resident
        requests (all of them when ``uids`` is None) from this engine
        and return them as snapshot-format entry records —
        :meth:`import_requests` on another replica resumes them. The
        in-flight decode is drained first (one host sync — migration
        is a deliberate synchronous operation), so the records carry
        every emitted token; each resident's blocks release through
        the usual deepest-first discipline (cached and re-matchable
        under prefix caching) and its deadline serializes as remaining
        budget. Requests already terminal (awaiting ``pop_results``)
        are NOT exported — their verdicts stay here. Because the
        records preserve the arrival PRNG identity, a migrated request
        resumed on a replica with the same seed continues its token
        stream bit-identically (docs/fleet.md, migration protocol)."""
        self._drain_decode()
        want = None if uids is None else {str(u) for u in uids}
        now = self._clock()
        records: List[Dict] = []
        live = sorted((s.admit_seq, i) for i, s in enumerate(self.slots)
                      if s is not None)
        for _, i in live:
            slot = self.slots[i]
            if want is not None and slot.request.uid not in want:
                continue
            records.append(self._entry_record(
                _QueueEntry(request=slot.request,
                            arrival=slot.entry.arrival,
                            generated=self._resume_tokens(slot),
                            drr_charged=True), now))
            self.allocator.free(list(reversed(slot.blocks)),
                                tenant=slot.request.tenant)
            self.slots[i] = None
            self._invalidate_lanes()
            self._release_exported(slot.request)
        for entry in self.waiting.expel(
                lambda e: want is None or e.request.uid in want):
            records.append(self._entry_record(entry, now))
            self._release_exported(entry.request)
        # stash each record's arrival identity BEFORE the chaos site
        # can touch the caller's copy (see _exported_arrivals)
        for rec in records:
            self._exported_arrivals[str(rec["uid"])] = \
                int(rec["arrival"])
        # each record is sealed for the wire (import_requests verifies
        # it), THEN run through the "export" chaos site — one fire per
        # record, so a seeded plan can rot exactly the record it means
        # to (docs/robustness.md, "Data integrity")
        records = [self._maybe_corrupt_record("export", seal_record(rec))
                   for rec in records]
        self._num_migrated_out += len(records)
        return records

    def drop_stream_events(self, uid: str) -> int:
        """Discard this engine's UNDRAINED stream events for ``uid`` —
        the refused-import recompute's companion: the re-injected
        request re-derives (and re-emits) every token past the
        router's delivered watermark, so stale copies the router never
        drained would otherwise arrive twice — once stale, once
        re-derived — and shift every later position in the delivered
        ledger. Returns how many events were dropped."""
        uid = str(uid)
        before = len(self._stream)
        self._stream = deque(ev for ev in self._stream
                             if ev[0] != uid)
        return before - len(self._stream)

    def exported_arrival(self, uid: str) -> Optional[int]:
        """The arrival PRNG index this engine last exported for
        ``uid`` — the clean, source-side copy the router's
        refused-import recompute reads so a re-injected request keeps
        its sampled-token identity (``None`` when the uid never left
        through :meth:`export_requests`)."""
        v = self._exported_arrivals.get(str(uid))
        return None if v is None else int(v)

    def _release_exported(self, request: Request) -> None:
        """Forget an exported request WITHOUT a terminal transition:
        it is still alive, just owned by another replica now — no
        status, no stream sentinel (unlike every other exit path),
        and fleet-wide uid uniqueness stays the router's job."""
        self._live_uids.discard(request.uid)
        self._deadline.pop(request.uid, None)
        self._prune_tenant_if_idle(request.tenant)

    def import_requests(self, records: Sequence[Dict]) -> int:
        """Drain-and-migrate IMPORT: enqueue entry records exported by
        another replica (or read from its checkpoint) into this
        engine's waiting queue. Records keep their arrival PRNG
        identity (``_arrival_count`` advances past every imported
        index so future local arrivals never collide) and their
        ``drr_charged`` standing — a migrated RESIDENT re-admits ahead
        of the DRR walk exactly like a preemption requeue, a migrated
        waiting entry rejoins the walk uncharged. A record without an
        ``arrival`` (a router re-injecting a post-checkpoint accept it
        only knows as a Request) gets a fresh local index. Deadlines
        re-anchor their remaining budget on this clock. Deliberately
        NO door-quota check: quota enforcement happened at the
        original door, and failover/migration of already-accepted work
        must never manufacture a shed (docs/fleet.md, zero-lost
        contract). Raises ``ValueError`` — before touching anything —
        if any uid is already live or awaiting drain here, and
        :class:`~apex_tpu.utils.integrity.IntegrityError` — likewise
        before touching anything — if a SEALED record fails its
        checksum (``verify_artifacts``): a corrupt migration import is
        REFUSED, so the router's copy (and the source replica) stay
        the request's truth instead of corrupt state re-entering the
        fleet. Checksum-less LEGACY records import as before (the
        fleet seals every hop — export, failover placement — so only
        hand-built records arrive unsealed)."""
        now = self._clock()
        if self.faults is not None:
            # target-side chaos: one "import" fire per received record
            # (in-transit rot arriving at this replica)
            records = [self._maybe_corrupt_record("import", rec)
                       for rec in records]
        for rec in records:
            if self.config.verify_artifacts:
                try:
                    verify_record(rec, "import")
                except IntegrityError as e:
                    self._num_import_refusals += 1
                    self._note_corruption("import", e.detail)
                    raise
            uid = rec["uid"]
            if uid in self._live_uids:
                raise ValueError(
                    f"cannot import uid {uid!r}: already waiting or "
                    "resident in this engine")
            if uid in self.statuses:
                raise ValueError(
                    f"cannot import uid {uid!r}: a terminal result "
                    "awaits drain here")
        for rec in records:
            deadline = rec.get("deadline_remaining_s")
            req = Request(
                uid=rec["uid"], prompt=list(rec["prompt"]),
                max_new_tokens=int(rec["max_new_tokens"]),
                sampling=SamplingParams(
                    temperature=rec["sampling"]["temperature"],
                    top_k=rec["sampling"]["top_k"],
                    top_p=rec["sampling"]["top_p"]),
                eos_token_id=rec.get("eos_token_id"),
                deadline_s=deadline,
                priority=int(rec.get("priority", 0)),
                tenant=str(rec.get("tenant", DEFAULT_TENANT)))
            if deadline is not None:
                # an already-blown deadline stays blown (<= now)
                self._deadline[req.uid] = now + float(deadline)
            arrival = rec.get("arrival")
            if arrival is None:
                arrival = self._arrival_count
            arrival = int(arrival)
            self._arrival_count = max(self._arrival_count, arrival + 1)
            # the uid lives HERE now: any stale source-side export
            # stamp of ours is superseded by this admission
            self._exported_arrivals.pop(req.uid, None)
            self._live_uids.add(req.uid)
            self._tenant_seen.add(req.tenant)
            self.waiting.append(_QueueEntry(
                request=req, arrival=arrival,
                generated=[int(t) for t in rec.get("generated", ())],
                enq_t=now, enq_tick=self._num_ticks,
                drr_charged=bool(rec.get("drr_charged", False))))
            if self._obs is not None:
                # anchor the migrated request's timeline exactly as
                # restore() anchors restored records (requeue, not
                # enqueue: its submit time belongs to the source)
                self._obs.note_enqueue(req.uid, tenant=req.tenant,
                                       priority=req.priority,
                                       prompt_len=len(req.prompt),
                                       requeue=True, t=now)
        self._num_migrated_in += len(records)
        self._queue_depth_peak = max(self._queue_depth_peak,
                                     len(self.waiting))
        return len(records)

    def export_prefix_payloads(self, hashes: Sequence[str]
                               ) -> Dict[str, Dict]:
        """The leading run of a hash chain as host payloads — the
        cross-replica KV transport (docs/fleet.md): device-indexed
        blocks read out through the spill fetch path, spilled ones
        through :meth:`~apex_tpu.serving.kv_cache.HostSpillStore.
        export_entry`. Stops at the first hash served by neither (a
        payload past a gap is unreachable, like the prefix match) or
        at the first failed device read (transport is an optimization,
        never a dependency — the importer just recomputes)."""
        out: Dict[str, Dict] = {}
        if not self.config.enable_prefix_caching:
            return out
        for h in hashes:
            b = self.allocator.indexed_block(h)
            if b is not None:
                payload = self._spill_payload(b, record=False)
            elif self.spill is not None:
                payload = self.spill.export_entry(h)
            else:
                payload = None
            if payload is None:
                break
            if self.config.verify_artifacts:
                # a detached content checksum rides the payload dict
                # (string-valued, skipped by the array checksum and by
                # the upload path) — the importer verifies the bytes
                # end to end across the transport
                payload = dict(payload)
                payload["checksum"] = payload_checksum(payload)
            out[h] = payload
        return out

    def import_prefix_payloads(self, payloads: Mapping[str, Dict]) -> int:
        """Seed this engine's spill tier with payloads another replica
        exported: the next admission matching those chain hashes
        re-admits them by device upload instead of recompute —
        token-identical, by the spill-tier equivalence cert. Hashes a
        device block already serves are skipped (the disjointness
        invariant); returns how many entries the tier accepted (0 with
        no spill tier configured — the transport is optional)."""
        if self.spill is None:
            return 0
        n = 0
        for h, payload in payloads.items():
            if self.allocator.indexed_block(h) is not None:
                continue
            payload = dict(payload)
            checksum = payload.pop("checksum", None)
            if self.config.verify_artifacts and checksum is not None:
                try:
                    verify_payload(payload, checksum, "import_payload")
                except IntegrityError as e:
                    # a corrupt transported block is SKIPPED, not
                    # refused: each payload is an independent cache
                    # seed, and a skip just means the importer
                    # recomputes that block (the tier's normal miss)
                    self._note_corruption("import_payload", e.detail)
                    continue
            if self.spill.import_entry(h, payload):
                n += 1
        return n

    # -- crash-consistent snapshot / restore (docs/robustness.md) ---------

    def _config_fingerprint(self) -> Dict[str, object]:
        """The engine config as JSON-able values; a snapshot only
        restores into an engine built with the identical config (the
        compiled-program shapes, pool geometry, and PRNG seed all hang
        off it — any drift breaks the bit-identity contract). The
        retry knobs are operational, not identity: an operator
        recovering from an incident may legitimately restore into an
        engine with a bigger retry budget or no backoff, and outputs
        are unaffected, so they stay out of the fingerprint. The
        overload knobs (queue bound, ladder watermarks, admission-pause
        class) are operational in the same sense — restoring into a
        replica with a bigger queue or different watermarks is exactly
        the incident-recovery move — so they stay out too."""
        d = dataclasses.asdict(self.config)
        d["kv_dtype"] = (None if self.config.kv_dtype is None
                         else str(jnp.dtype(self.config.kv_dtype)))
        # as a LIST, not a tuple: the fingerprint must compare equal
        # before and after riding the JSON wire (which has no tuples),
        # and mesh_shape IS identity — a sharded snapshot restores
        # across equal meshes only
        d["mesh_shape"] = [int(v) for v in self.config.mesh_shape]
        for knob in ("max_dispatch_retries", "retry_backoff_s",
                     # the spill tier is operational capacity tuning:
                     # a re-admitted block is certified token-identical
                     # to recompute, so restoring into a replica with a
                     # different (or no) spill bound changes nothing
                     # the fingerprint protects. kv_quantization AND
                     # weight_quantization STAY in the fingerprint:
                     # quantized outputs are not the fp outputs —
                     # storage mode IS identity.
                     "spill_max_bytes",
                     "max_waiting", "queue_high_watermark",
                     "free_block_low_watermark", "degrade_patience",
                     "degrade_admit_priority",
                     # the tenancy knobs are operational in the same
                     # sense: restoring into a replica with different
                     # weights or quotas is the incident-recovery move,
                     # and outputs are arrival-keyed (tenant-invariant)
                     "tenant_weights", "tenant_quotas", "drr_quantum",
                     "tenant_rate_tau_s",
                     # spec_adapt changes SCHEDULE (span boundaries),
                     # not identity; its cap state rides the overload
                     # section with the same config-guard as the ladder
                     "spec_adapt", "spec_accept_low",
                     "spec_accept_high",
                     # periodic checkpointing is pure observation of
                     # host state (checkpoint() never drains or
                     # mutates scheduling) — restoring into a replica
                     # with a different cadence changes nothing
                     "snapshot_interval_ticks",
                     # the integrity knobs are operational in the same
                     # sense: verification and scrubbing are pure
                     # detection on clean artifacts (certified
                     # bit-identical on or off), and restoring a
                     # verify-off snapshot into a verify-on engine is
                     # exactly the hardening-after-an-incident move
                     "verify_artifacts", "scrub_interval_ticks",
                     "scrub_spill_blocks"):
            d.pop(knob, None)
        return d

    def _entry_record(self, entry: _QueueEntry, now: float) -> Dict:
        req = entry.request
        rec = {
            "uid": req.uid,
            "prompt": [int(t) for t in req.prompt],
            "max_new_tokens": int(req.max_new_tokens),
            "eos_token_id": (None if req.eos_token_id is None
                             else int(req.eos_token_id)),
            "sampling": {"temperature": float(req.sampling.temperature),
                         "top_k": int(req.sampling.top_k),
                         "top_p": float(req.sampling.top_p)},
            "arrival": int(entry.arrival),
            "priority": int(req.priority),
            "tenant": str(req.tenant),
            "drr_charged": bool(entry.drr_charged),
            "generated": [int(t) for t in entry.generated],
        }
        dl = self._deadline.get(req.uid)
        if dl is not None:
            # deadlines serialize as REMAINING budget: the restoring
            # process re-anchors them on its own clock
            rec["deadline_remaining_s"] = float(dl - now)
        return rec

    def snapshot(self) -> Dict[str, object]:
        """Crash-consistent, JSON-serializable picture of the engine.

        Drains the in-flight decode first (one host sync), so no
        emitted token is ever lost to a snapshot boundary. Live slots
        serialize as preempted-style resumable entries — prompt,
        emitted tokens, arrival index (the PRNG identity) — in
        admission order, ahead of the waiting queue; ``finished``,
        terminal statuses, remaining deadline budgets, and the config
        fingerprint ride along. The block tables and allocator state
        (refcounts, prefix index, LRU order) are included as an AUDIT
        section: :meth:`restore` deliberately does not reload them,
        because KV block contents do not survive a process — the
        restored engine re-prefills through the prefix cache and
        rebuilds them (bit-identically, by resume determinism)."""
        self._drain_decode()
        self._num_snapshots += 1
        snap = self._build_snapshot()
        if self._obs is not None:
            self._obs.record("snapshot", requests=len(snap["requests"]))
        return snap

    def checkpoint(self) -> Dict[str, object]:
        """The LIGHTWEIGHT snapshot variant (docs/fleet.md): the same
        restore()-loadable picture as :meth:`snapshot`, built WITHOUT
        draining the in-flight decode dispatch — no host sync, so a
        periodic caller (``snapshot_interval_ticks``, or a fleet
        router's health loop) never stalls the pipeline. The price is
        bounded staleness: tokens riding the undrained dispatch (at
        most ``decode_steps``/``spec_tokens + 1`` per lane) are absent
        from the records and are RE-DERIVED bit-identically on restore
        (resume determinism — the records carry prompt + emitted
        history + the arrival PRNG identity). The result is stored on
        ``last_checkpoint`` — the failover picture a fleet router
        reads when this replica dies — and also returned."""
        self._num_checkpoints += 1
        snap = self._build_snapshot(lightweight=True)
        # the chaos seam (docs/robustness.md): a "corrupt" spec at the
        # "checkpoint" site rots the just-sealed record — the fleet's
        # failover verification must then refuse it and fall back to
        # fresh re-injection
        snap = self._maybe_corrupt_record("checkpoint", snap)
        self.last_checkpoint = snap
        if self._obs is not None:
            self._obs.record("snapshot", requests=len(snap["requests"]),
                             lightweight=True)
        return snap

    def _build_snapshot(self, lightweight: bool = False
                        ) -> Dict[str, object]:
        """The shared snapshot/checkpoint body: pure host-state READS
        (plus the counter the caller already bumped) — nothing here
        drains, allocates, or touches scheduling state, which is what
        makes :meth:`checkpoint` safe on every tick and callable even
        from a replica whose last dispatch just raised."""
        now = self._clock()
        live = sorted((s.admit_seq, i) for i, s in enumerate(self.slots)
                      if s is not None)
        requests = []
        for _, i in live:
            slot = self.slots[i]
            requests.append(self._entry_record(
                _QueueEntry(request=slot.request, arrival=slot.entry.arrival,
                            generated=self._resume_tokens(slot),
                            # a resident's DRR cost was paid at its
                            # admission: restore re-admits it free,
                            # leaving the serialized walk untouched
                            drr_charged=True), now))
        for entry in self.waiting:
            requests.append(self._entry_record(entry, now))
        snap = {
            "version": 1,
            "config": self._config_fingerprint(),
            "arrival_count": int(self._arrival_count),
            "requests": requests,
            "finished": {uid: [int(t) for t in toks]
                         for uid, toks in self.finished.items()},
            "statuses": dict(self.statuses),
            "counters": self.stats(),
            # behavioral, not audit: a quarantined drafter must STAY
            # quarantined across restore — resumed speculation would
            # draw accept/resample uniforms the uninterrupted
            # (empty-plan) run never drew, breaking sampled-lane
            # restore bit-identity
            "drafter_ok": bool(self._drafter_ok),
            # behavioral too: a restored engine continues the SAME
            # ladder walk (its rung gates speculation and admission),
            # streaks included so hysteresis resumes mid-count — and
            # the feasibility-gate EWMAs ride along, or the restored
            # gate would reopen blind and admit doomed tight-deadline
            # requests at exactly the moment load is highest (restore
            # re-queues every previously resident request)
            "overload": {
                "degradation_level": int(self._degradation_level),
                "pressure_streak": int(self._pressure_streak),
                "clear_streak": int(self._clear_streak),
                "ewma_prefill_s": self._ewma_prefill_s,
                "ewma_decode_s": self._ewma_decode_s,
                # the dynamic-speculation refinement rides here too: a
                # restored engine resumes the same cap walk (sampled
                # lanes' realized draws depend on span boundaries, so
                # silently resetting the cap would break restore
                # bit-identity under spec_adapt)
                "spec_cap": int(self._spec_cap),
                "spec_accept_ewma": self._spec_accept_ewma,
                "spec_probe_countdown": int(self._spec_probe_countdown),
            },
            # the tenant ledger: DRR walk state per class (ring order
            # is implied by the requests' serialization order), the
            # token-rate estimators (ages re-anchor on the restoring
            # clock, like deadlines), and the observability tallies
            "tenancy": {
                "classes": self.waiting.snapshot_state(),
                "rates": {t: {"rate": float(r),
                              "age_s": float(now - self._tenant_rate_t[t])}
                          for t, r in self._tenant_rate.items()},
                "tokens": {t: int(n)
                           for t, n in self._tenant_tokens.items()},
                "status_counts": {t: dict(c) for t, c in
                                  self._tenant_status.items()},
                "preemptions": dict(self._tenant_preemptions),
                "seen": sorted(self._tenant_seen),
            },
            "block_tables": {
                self.slots[i].request.uid: [int(b) for b in
                                            self.slots[i].blocks]
                for _, i in live},
            "allocator": self.allocator.snapshot_state(),
        }
        if self.spill is not None:
            # AUDIT-ONLY, like the allocator section: spilled K/V
            # bytes do not ride a JSON snapshot and restore() never
            # reads this — a restored engine starts with an empty
            # spill tier and re-warms it (hits are an optimization,
            # never identity; the fingerprint excludes the knob). The
            # scrub cursor rides here under the same policy: the
            # restored store is empty, so the walk restarts.
            snap["spill"] = dict(self.spill.stats(), audit_only=True,
                                 hits=int(self._spill_hits),
                                 misses=int(self._spill_misses),
                                 scrub_cursor=int(
                                     self.spill._scrub_cursor))
        if self._obs is not None:
            # AUDIT-ONLY, like the block tables: the flight-recorder
            # tail and trace depth ride along for post-mortems, and
            # restore() deliberately never reads this section —
            # observer state must not influence a restored engine
            # (the zero-perturbation contract), and it is excluded
            # from the config fingerprint for the same reason
            audit = {"audit_only": True}
            if self._obs.recorder is not None:
                audit["recorder_tail"] = self._obs.recorder.tail()
                audit["recorder_dropped"] = self._obs.recorder.dropped
            if self._obs.tracer is not None:
                audit["trace_events"] = len(self._obs.tracer)
            snap["observability"] = audit
        if lightweight:
            snap["lightweight"] = True
        # sealed LAST (docs/robustness.md, "Data integrity"): the
        # embedded checksum covers every field above, survives the
        # JSON wire format bit-for-bit, and is verified by restore()
        # and by the fleet router before a failover trusts the record
        return seal_record(snap)

    def restore(self, snap: Dict[str, object]) -> None:
        """Load a :meth:`snapshot` into a FRESHLY constructed engine
        (same model, params, and config — the fingerprint is checked,
        the params are the caller's contract). Every unfinished request
        re-enters the waiting queue in snapshot order carrying its
        emitted tokens and original arrival index, so re-admission
        re-prefills ``prompt + generated[:-1]`` (cheap when its blocks
        are still/again cached) and the schedule-invariant sampler
        continues the exact token stream: a restored ``run()`` is
        bit-identical to the uninterrupted one (tested, including
        across processes)."""
        # integrity FIRST (docs/robustness.md): a sealed snapshot must
        # verify before ANY field of it is believed — including the
        # version number, which is itself a corruptible numeric leaf
        # (acting on it first would mis-report a detected corruption
        # as "unknown version" and dodge the detection counter). A
        # corrupt snapshot refuses to restore (the operator recovers
        # from an older artifact, a fleet router falls back to fresh
        # re-injection); checksum-less legacy snapshots load as
        # before — detection covers sealed artifacts only.
        if self.config.verify_artifacts:
            try:
                verify_record(snap, "restore")
            except IntegrityError as e:
                self._note_corruption("restore", e.detail)
                raise
        if snap.get("version") != 1:
            raise ValueError(f"unknown snapshot version {snap.get('version')!r}")
        mine, theirs = self._config_fingerprint(), dict(snap["config"])
        # compare by .get() so a knob ADDED since the snapshot was
        # taken (absent key) equals its None default — an older
        # snapshot restores into an engine that leaves the new knob
        # off, which is exactly the config it ran under
        diff = {k: (theirs.get(k), mine.get(k))
                for k in set(mine) | set(theirs)
                if mine.get(k) != theirs.get(k)}
        if diff:
            raise ValueError(
                f"snapshot config mismatch (snapshot vs engine): {diff}")
        if self.has_work or self._arrival_count or self.finished:
            raise RuntimeError(
                "restore() requires a fresh engine: this one has queued, "
                "resident, in-flight, or finished requests")
        now = self._clock()
        for rec in snap["requests"]:
            deadline = rec.get("deadline_remaining_s")
            req = Request(
                uid=rec["uid"], prompt=list(rec["prompt"]),
                max_new_tokens=int(rec["max_new_tokens"]),
                sampling=SamplingParams(
                    temperature=rec["sampling"]["temperature"],
                    top_k=rec["sampling"]["top_k"],
                    top_p=rec["sampling"]["top_p"]),
                eos_token_id=rec.get("eos_token_id"),
                deadline_s=deadline,
                priority=int(rec.get("priority", 0)),
                tenant=str(rec.get("tenant", DEFAULT_TENANT)))
            if deadline is not None:
                # an already-blown deadline stays blown (<= now)
                self._deadline[req.uid] = now + deadline
            self._live_uids.add(req.uid)
            self._tenant_seen.add(req.tenant)
            self.waiting.append(_QueueEntry(
                request=req, arrival=int(rec["arrival"]),
                generated=[int(t) for t in rec["generated"]],
                enq_t=now, enq_tick=self._num_ticks,
                drr_charged=bool(rec.get("drr_charged", False))))
            if self._obs is not None:
                # anchor the restored request's timeline (requeue, not
                # enqueue: no fresh-request counter, no TTFT state —
                # its true submit time belongs to the dead process)
                self._obs.note_enqueue(req.uid, tenant=req.tenant,
                                       priority=req.priority,
                                       prompt_len=len(req.prompt),
                                       requeue=True, t=now)
        self._arrival_count = int(snap["arrival_count"])
        self.finished.update({uid: [int(t) for t in toks]
                              for uid, toks in snap["finished"].items()})
        self.statuses.update(snap["statuses"])
        # drafter-quarantine state is behavioral (see snapshot): a
        # pre-quarantine snapshot restores with speculation live, a
        # post-quarantine one stays degraded — either way the restored
        # token stream matches the uninterrupted run. The drafter
        # OBJECT itself is the caller's contract, like params: restore
        # with an equivalent (pure-function-of-history) drafter.
        self._drafter_ok = (bool(snap["drafter_ok"])
                            and self.config.spec_tokens > 0)
        # the ladder resumes where the snapshot left it — rungs gate
        # speculation and admission, so a restore mid-degradation must
        # not silently jump back to full service (the restoring
        # engine's own watermarks walk it up when pressure clears).
        # UNLESS this engine's ladder is disabled (no watermarks — the
        # overload knobs are legitimately restorable-across, like the
        # retry knobs): _update_ladder could then never step the rung
        # back up, leaving speculation/admission degraded FOREVER —
        # same config-mismatch guard as drafter_ok above
        overload = snap.get("overload", {})
        if self._ladder_enabled():
            self._degradation_level = int(
                overload.get("degradation_level", 0))
            self._pressure_streak = int(overload.get("pressure_streak", 0))
            self._clear_streak = int(overload.get("clear_streak", 0))
        # the gate's estimators restore UNCONDITIONALLY (they exist
        # independent of the ladder): a blind re-opened gate would
        # admit doomed deadlines right when the requeued backlog is
        # largest. Absent keys (older snapshots) leave the gate open.
        for attr, key in (("_ewma_prefill_s", "ewma_prefill_s"),
                          ("_ewma_decode_s", "ewma_decode_s")):
            v = overload.get(key)
            if v is not None:
                setattr(self, attr, float(v))
        # the dynamic-speculation cap resumes its walk ONLY when this
        # engine adapts too (same guard shape as the ladder rung: a
        # non-adapting engine could never restore the cap, leaving
        # speculation degraded forever)
        if self.config.spec_adapt:
            self._spec_cap = int(overload.get("spec_cap",
                                              self.config.spec_tokens))
            ewma = overload.get("spec_accept_ewma")
            if ewma is not None:
                self._spec_accept_ewma = float(ewma)
            self._spec_probe_countdown = int(
                overload.get("spec_probe_countdown", _SPEC_PROBE_EVERY))
        # the tenant ledger: DRR walk state re-anchors after the
        # re-appends above (serialized ring order wins; restored
        # residents' tenants join at ring tails), rate estimators
        # re-anchor their ages on this clock, tallies carry over
        tenancy = snap.get("tenancy", {})
        self.waiting.restore_state(tenancy.get("classes", {}))
        for t, rec in (tenancy.get("rates") or {}).items():
            self._tenant_rate[t] = float(rec["rate"])
            self._tenant_rate_t[t] = now - max(0.0, float(rec["age_s"]))
        for t, n in (tenancy.get("tokens") or {}).items():
            self._tenant_tokens[t] = int(n)
        for t, counts in (tenancy.get("status_counts") or {}).items():
            self._tenant_status[t] = {s: int(c)
                                      for s, c in counts.items()}
        for t, n in (tenancy.get("preemptions") or {}).items():
            self._tenant_preemptions[t] = int(n)
        self._tenant_seen.update(tenancy.get("seen", ()))
        # the snapshot's "observability" audit section (if any) is
        # deliberately NOT read: observer state never shapes behavior
        self._num_restores += 1
        if self._obs is not None:
            self._obs.record("restore", requests=len(snap["requests"]))

    # -- mesh program-shape audit (docs/serving.md, "Mesh sharding") -------

    def program_collective_stats(self, program: str) -> Dict[str, Dict]:
        """Collective ops/bytes of one compiled engine program
        (:func:`apex_tpu.utils.hlo_audit.collective_stats`), lowered
        from ABSTRACT arguments at the program's real call shapes and
        the engine's committed shardings — no dispatch runs, and the
        explicit AOT lowering leaves the jit call caches (the pinned
        ``*_compilations`` counters) untouched. ``program``:
        ``"prefill"``, ``"decode"``, or ``"verify"`` (the last two are
        the same jit slot — ``"verify"`` just insists speculation is
        on, so a contract test cannot silently audit the wrong
        program)."""
        B = self.config.max_batch
        M = self.max_blocks_per_seq

        def i32(shape):
            return jax.ShapeDtypeStruct(shape, jnp.int32)

        def f32(shape):
            return jax.ShapeDtypeStruct(shape, jnp.float32)

        def abstract(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=getattr(x, "sharding",
                                                         None))

        aparams = jax.tree.map(abstract, self.params)
        acache = jax.tree.map(abstract, self.cache)
        if program == "prefill":
            C = self._chunk
            fn, args = self._prefill, (
                aparams, acache, i32((1, C)), i32((1, C)), i32((1,)),
                i32((1,)), i32((1,)), i32((1, M)),
                jax.ShapeDtypeStruct((2,), jnp.uint32),
                f32((1,)), i32((1,)), f32((1,)))
        elif program in ("decode", "verify"):
            if program == "verify" and self.config.spec_tokens < 1:
                raise ValueError(
                    "program 'verify' requires spec_tokens >= 1 (the "
                    "decode slot holds the plain scan otherwise)")
            keys = jax.ShapeDtypeStruct((B, 2), jnp.uint32)
            if self.config.spec_tokens > 0:
                S = self.config.spec_tokens
                args = (aparams, acache, i32((B,)), i32((B, S)),
                        i32((B,)), i32((B, M)), i32((B,)), i32((B,)),
                        i32((B,)), i32((B,)), keys, f32((B,)),
                        i32((B,)), f32((B,)))
            else:
                args = (aparams, acache, i32((B,)), i32((B, M)),
                        i32((B,)), i32((B,)), i32((B,)), i32((B,)),
                        keys, f32((B,)), i32((B,)), f32((B,)))
            fn = self._decode
        else:
            raise ValueError(
                f"unknown program {program!r} (expected 'prefill', "
                "'decode', or 'verify')")
        from apex_tpu.utils.hlo_audit import collective_stats

        return collective_stats(fn.lower(*args).compile().as_text())

    def audit_collectives(self) -> Dict[str, Dict[str, Dict]]:
        """Check every compiled program against the mesh's collective
        contract (:func:`apex_tpu.serving.mesh.expected_collectives`):
        zero collectives while the model axis is 1 (the bit-identity
        precondition), reduction traffic — and nothing exotic — once
        the heads split. Raises ``AssertionError`` on violation;
        returns ``{program: collective_stats}`` for reporting."""
        from apex_tpu.utils.hlo_audit import assert_collective_contract

        contract = mesh_lib.expected_collectives(self.config.mesh_shape)
        out = {}
        programs = ["prefill",
                    "verify" if self.config.spec_tokens > 0 else "decode"]
        for prog in programs:
            stats = self.program_collective_stats(prog)
            assert_collective_contract(
                stats,
                label=f"{prog}@mesh{tuple(self.config.mesh_shape)}",
                **contract)
            out[prog] = stats
        return out

    def check_allocator_integrity(self) -> None:
        """Cross-check the allocator against the engine's own
        bookkeeping: internal invariants plus an EXACT refcount match —
        each block's count must equal the number of resident slots
        referencing it (chaos tests call this after restore + LRU
        churn). The per-tenant reference split is cross-checked too:
        each block's tenant refs must equal the residents referencing
        it, split by their tenants — the certification that aborts,
        quota sheds, and preemptions reclaimed exactly what they
        owned. With a sharded ``batch`` axis (``mesh_shape[0] > 1``)
        every resident's blocks must additionally live on its LANE's
        shard — the invariant the sharded programs' subtraction
        localization silently depends on (a foreign block would read
        masked garbage, not raise)."""
        expected: Dict[int, int] = {}
        expected_tenants: Dict[int, Dict[str, int]] = {}
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            t = slot.request.tenant
            for b in slot.blocks:
                expected[b] = expected.get(b, 0) + 1
                per = expected_tenants.setdefault(b, {})
                per[t] = per.get(t, 0) + 1
                if (self._batch_shards > 1
                        and self.allocator.shard_of(b)
                        != self._lane_shard(i)):
                    raise AssertionError(
                        f"slot {i} (shard {self._lane_shard(i)}) holds "
                        f"block {b} on shard "
                        f"{self.allocator.shard_of(b)}: batch-axis "
                        "shard residency violated")
        self.allocator.check_integrity(
            expected_refcounts=expected,
            expected_tenant_refs=expected_tenants)

    def stats(self, deep: bool = False) -> Dict[str, object]:
        """The observability counters. Honest typing note: despite its
        long life as ``Dict[str, float]``, the dict has carried the
        NESTED per-tenant ledger (``"tenants"``) since PR 9 — the
        value type is ``object``; flatten nested sections with
        :func:`apex_tpu.observability.flatten_stats` when a scalar
        map is needed. ``deep=True`` additionally merges the attached
        observer's section (metric values, recorder/trace depths)
        under ``"observability"`` — absent entirely when no observer
        is attached or at the default ``deep=False``."""
        alloc = self.allocator
        lookups = self._prefix_lookup_blocks
        out = {
            "prefill_compilations": self._prefill._cache_size(),
            "decode_compilations": self._decode._cache_size(),
            # the GSPMD mesh the programs compiled under (docs/
            # serving.md "Mesh sharding"): static per config, so equal
            # configs keep full-stats identity certs byte-comparable
            "mesh_devices": (self.config.mesh_shape[0]
                             * self.config.mesh_shape[1]),
            "mesh_model_axis": self.config.mesh_shape[1],
            "mesh_batch_axis": self.config.mesh_shape[0],
            # the storage quantization modes (docs/serving.md memory
            # tiers): static per config like the mesh keys — equal
            # configs keep full-stats identity certs byte-comparable —
            # closing the asymmetry where the modes rode the restore
            # fingerprint but no observable surface
            "kv_quantization": self.config.kv_quantization,
            "weight_quantization": self.config.weight_quantization,
            "num_prefills": self._num_prefills,
            "num_prefill_chunks": self._num_prefill_chunks,
            "num_decode_dispatches": self._num_decode_dispatches,
            # tokens actually emitted by decode dispatches (drained
            # ones; an in-flight dispatch counts after its sync). The
            # dispatches:tokens ratio is the multi-step amortization.
            "num_tokens_decoded": self._num_tokens_decoded,
            # back-compat alias: pre-multistep dashboards/tests read
            # num_decode_steps, which meant DISPATCHES (at K=1 the two
            # were indistinguishable)
            "num_decode_steps": self._num_decode_dispatches,
            "decode_table_rebuilds": self._table_rebuilds,
            "num_preemptions": self._num_preemptions,
            "num_cow_copies": self._num_cow_copies,
            "num_cache_evictions": alloc.num_evictions,
            "active_slots": sum(s is not None for s in self.slots),
            "waiting": len(self.waiting),
            "cache_utilization": alloc.utilization,
            "blocks_free": alloc.num_free,
            "blocks_cached": alloc.num_cached,
            "blocks_active": alloc.num_used,
            "prefix_lookup_blocks": lookups,
            "prefix_hit_blocks": self._prefix_hit_blocks,
            "prefix_cache_hit_rate": (self._prefix_hit_blocks / lookups
                                      if lookups else 0.0),
            "prompt_blocks_allocated": self._prompt_blocks_allocated,
            # the host-RAM spill tier (docs/serving.md memory tiers):
            # current residency, lifetime traffic, and the re-admit
            # hit rate — all zero with the tier off
            # `is not None`, not truthiness: the store defines __len__
            # and an empty (fully re-admitted) store is falsy
            "spill_blocks": (len(self.spill) if self.spill is not None
                             else 0),
            "spill_bytes": (self.spill.total_bytes
                            if self.spill is not None else 0),
            "num_blocks_spilled": (self.spill.puts
                                   if self.spill is not None else 0),
            "num_spill_evictions": (self.spill.evictions
                                    if self.spill is not None else 0),
            "spill_hits": self._spill_hits,
            "spill_misses": self._spill_misses,
            "spill_hit_rate": (
                self._spill_hits
                / (self._spill_hits + self._spill_misses)
                if self._spill_hits + self._spill_misses else 0.0),
            # the uniform spill refusal/corruption surface + the data-
            # integrity counters (docs/robustness.md "Data integrity"):
            # oversize puts the store refused, entries discarded on a
            # checksum mismatch, total detections across every
            # verification point, refused migration imports, and the
            # background scrub's cadence/coverage
            "num_spill_refused": (self.spill.refused
                                  if self.spill is not None else 0),
            "num_spill_corrupt_discards": (
                self.spill.corrupt_discards
                if self.spill is not None else 0),
            "num_corruptions_detected": self._num_corruptions_detected,
            "num_import_refusals": self._num_import_refusals,
            "num_scrubs": self._num_scrubs,
            "num_scrub_blocks_verified": self._num_scrub_blocks_verified,
            # robustness counters (docs/robustness.md): every failure
            # path feeds one, so chaos runs are assertable from stats()
            "num_timeouts": self._num_timeouts,
            "num_dispatch_retries": self._num_dispatch_retries,
            "num_quarantines": self._num_quarantines,
            "num_snapshots": self._num_snapshots,
            "num_restores": self._num_restores,
            # fleet serving (docs/fleet.md): the periodic lightweight
            # checkpoint cadence and the drain-and-migrate traffic
            # through this replica
            "num_checkpoints": self._num_checkpoints,
            "num_migrated_in": self._num_migrated_in,
            "num_migrated_out": self._num_migrated_out,
            # overload observability (docs/robustness.md): queue depth
            # and wait, shed counters, and the degradation ladder —
            # overload must be visible HERE before the first timeout
            # ever fires
            "num_ticks": self._num_ticks,
            "queue_depth": len(self.waiting),
            "queue_depth_peak": self._queue_depth_peak,
            "queue_wait_mean_ticks": (
                self._queue_wait_ticks_sum / self._queue_wait_count
                if self._queue_wait_count else 0.0),
            "queue_wait_max_ticks": self._queue_wait_ticks_max,
            "queue_wait_mean_s": (
                self._queue_wait_s_sum / self._queue_wait_count
                if self._queue_wait_count else 0.0),
            "queue_wait_max_s": self._queue_wait_s_max,
            "num_rejected_queue_full": self._num_rejected_queue_full,
            "num_rejected_infeasible": self._num_rejected_infeasible,
            "ewma_prefill_dispatch_s": float(self._ewma_prefill_s or 0.0),
            "ewma_decode_dispatch_s": float(self._ewma_decode_s or 0.0),
            "degradation_level": self._degradation_level,
            "num_degrade_steps_down": self._num_degrade_steps_down,
            "num_degrade_steps_up": self._num_degrade_steps_up,
            "num_degrade_flushed_blocks": self._num_degrade_flushed_blocks,
            "admission_paused": int(
                self._admission_priority_limit() is not None),
            # speculative decoding (docs/serving.md): proposed vs
            # accepted draft tokens — the acceptance rate is THE
            # speculation health metric (tokens per target forward =
            # 1 + rate * spec_tokens, roughly); speculation_active
            # drops to 0 when a crashing drafter was quarantined
            "num_draft_tokens": self._num_draft_tokens,
            "num_accepted_tokens": self._num_accepted_tokens,
            "draft_acceptance_rate": (
                self._num_accepted_tokens / self._num_draft_tokens
                if self._num_draft_tokens else 0.0),
            "num_draft_retries": self._num_draft_retries,
            "num_drafter_quarantines": self._num_drafter_quarantines,
            "num_spec_blocks_rolled_back":
                self._num_spec_blocks_rolled_back,
            # 0 while quarantined (permanent) OR suspended by the
            # degradation ladder (reversible)
            "speculation_active": int(self._drafter_ok
                                      and self._degradation_level < 1),
            # dynamic speculation (spec_adapt): the adaptive per-plan
            # cap, the acceptance EWMA driving it, and its transitions
            "spec_cap": self._spec_cap,
            "spec_accept_ewma": float(self._spec_accept_ewma or 0.0),
            "num_spec_cap_shrinks": self._num_spec_cap_shrinks,
            "num_spec_cap_restores": self._num_spec_cap_restores,
            # multi-tenant isolation (docs/robustness.md): the global
            # shed/cancel counters, the streaming backlog, and the
            # per-tenant ledger
            "num_throttled": self._num_throttled,
            "num_cancelled": self._num_cancelled,
            "stream_backlog": len(self._stream),
            "tenants": self._tenant_section(),
        }
        if deep and self._obs is not None:
            out["observability"] = self._obs.deep_stats()
        return out

    def _tenant_section(self) -> Dict[str, Dict[str, object]]:
        """``stats()["tenants"]``: one row per tenant ever seen —
        delivered tokens, the decayed rate estimate, current queue and
        residency footprint (fractional block charge), the
        eviction/flush attribution, quota preemptions, and terminal
        statuses. The numbers an operator needs to tell WHICH tenant
        is eating the replica."""
        alloc_ts = self.allocator.tenant_stats()
        out: Dict[str, Dict[str, object]] = {}
        for t in sorted(self._tenant_seen | set(alloc_ts)):
            a = alloc_ts.get(t, {})
            out[t] = {
                "tokens": self._tenant_tokens.get(t, 0),
                "rate_tokens_per_s": round(self._tenant_rate_now(t), 6),
                "waiting": self.waiting.tenant_depth(t),
                "resident_slots": sum(
                    1 for s in self.slots
                    if s is not None and s.request.tenant == t),
                "resident_block_charge":
                    a.get("resident_block_charge", 0.0),
                "cached_blocks": a.get("cached_blocks", 0),
                "evicted_blocks": a.get("evicted_blocks", 0),
                "flushed_blocks": a.get("flushed_blocks", 0),
                "quota_preemptions": self._tenant_preemptions.get(t, 0),
                "statuses": dict(self._tenant_status.get(t, {})),
            }
        return out
