"""apex_tpu — a TPU-native training-acceleration framework.

A from-scratch rebuild of the capability surface of NVIDIA Apex
(reference: ``guanbin1994/apex``) on JAX/XLA/Pallas/pjit:

- ``apex_tpu.amp``        — mixed precision: O0–O3 policies, dynamic loss
  scaling, trace-time autocast (the TPU-native analog of
  ``apex/amp/frontend.py`` + ``apex/amp/scaler.py``; see SURVEY.md §2.1).
- ``apex_tpu.optimizers`` — FusedAdam / FusedLAMB / FusedSGD / FusedNovoGrad /
  FusedAdagrad lowered to single fused XLA computations over flat buffers
  (analog of ``apex/optimizers/*`` + ``csrc/multi_tensor_*.cu``).
- ``apex_tpu.multi_tensor_apply`` — the ``multi_tensor_applier`` dispatch
  surface (analog of ``apex/multi_tensor_apply/multi_tensor_apply.py``).
- ``apex_tpu.normalization`` — FusedLayerNorm / FusedRMSNorm backed by Pallas
  TPU kernels (analog of ``apex/normalization/fused_layer_norm.py`` +
  ``csrc/layer_norm_cuda_kernel.cu``).
- ``apex_tpu.parallel``   — DistributedDataParallel-semantics gradient
  synchronization, SyncBatchNorm, LARC over ``jax.lax.psum`` on ICI/DCN
  (analog of ``apex/parallel/*``).
- ``apex_tpu.transformer`` — Megatron-style tensor/pipeline/sequence
  parallelism on a named device mesh (analog of ``apex/transformer/*``).
- ``apex_tpu.contrib``    — xentropy, clip_grad, sparsity (ASP), multihead
  attention, distributed (ZeRO-style) optimizers (analog of ``apex/contrib``).
- ``apex_tpu.serving``    — the inference leg (beyond the reference's
  training-only surface): paged KV-cache, continuous-batching
  prefill/decode engine, jit-stable sampling (docs/serving.md).
- ``apex_tpu.train``      — the composed training step: amp + scanned
  gradient accumulation + DDP + fused optimizer compiled into one
  donated-buffer dispatch, with deferred host metrics
  (docs/training.md).
- ``apex_tpu.observability`` — request-lifecycle tracing (Perfetto
  export), the engine flight recorder, and the metrics registry
  (Prometheus exposition) behind ``obs=`` on the engine and
  ``TrainLoop`` — zero-perturbation certified (docs/observability.md).

Design stance (SURVEY.md §7): a functional JAX core with an apex-shaped API
veneer — capability and knob parity with the reference, mesh/pjit-native
internals. Nothing in here is a port; the reference is CUDA/C++/torch.
"""

__version__ = "0.1.0"

from apex_tpu import amp  # noqa: F401
from apex_tpu import multi_tensor_apply  # noqa: F401
from apex_tpu import optimizers  # noqa: F401
from apex_tpu import normalization  # noqa: F401
from apex_tpu import parallel  # noqa: F401
from apex_tpu import fp16_utils  # noqa: F401
from apex_tpu import mlp  # noqa: F401
from apex_tpu import reparameterization  # noqa: F401
from apex_tpu import RNN  # noqa: F401
from apex_tpu import fused_dense  # noqa: F401
from apex_tpu import observability  # noqa: F401
from apex_tpu import serving  # noqa: F401
from apex_tpu import train  # noqa: F401
