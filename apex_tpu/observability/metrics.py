"""Metrics registry: counters, gauges, and fixed-bucket histograms with
a Prometheus-style text exposition (docs/observability.md).

The engine and :class:`~apex_tpu.train.TrainLoop` have carried scalar
counters in ``stats()`` since PR 2; the admission gate's EWMAs are the
only latency signal, and an EWMA cannot answer "what is p99 TTFT".
Histograms here are **fixed log-spaced buckets** (:func:`log_buckets`):
``observe()`` is one bisect — O(log #buckets), allocation-free — and the
bucket bounds never depend on the data, so two replicas' histograms
merge by adding counts. The EWMAs keep feeding the feasibility gate
unchanged; the registry is the *observable* surface layered beside
them, never a behavioral input (the zero-perturbation contract in
docs/observability.md).

Also home of the ONE shared percentile helper (:func:`percentile`):
``StepTimer.summary()``, bench.py's TTFT/ITL reporting, and the
histogram quantile estimator all interpolate the same way (numpy's
default "linear" rule), so a p50 printed by any of them means the same
thing. (The old ``StepTimer`` median was ``ts[n // 2]`` — the upper
neighbor, not the median, for even n.)
"""

from __future__ import annotations

import math
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple


def percentile(xs: Sequence[float], q: float) -> float:
    """The q-th percentile (0 <= q <= 100) of ``xs`` under linear
    interpolation between closest ranks — numpy's default rule: the
    rank is ``q/100 * (n - 1)``, fractional ranks blend the two
    neighbors. ``xs`` need not be sorted. Raises on an empty sequence
    (a percentile of nothing is a caller bug, not 0.0 — callers with a
    legitimate empty case guard it themselves)."""
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile q must be in [0, 100], got {q}")
    n = len(xs)
    if n == 0:
        raise ValueError("percentile of an empty sequence")
    ts = sorted(xs)
    rank = (q / 100.0) * (n - 1)
    lo = int(math.floor(rank))
    hi = int(math.ceil(rank))
    if lo == hi:
        return float(ts[lo])
    frac = rank - lo
    return float(ts[lo] * (1.0 - frac) + ts[hi] * frac)


def log_buckets(lo: float, hi: float, count: int) -> Tuple[float, ...]:
    """``count`` log-spaced upper bounds from ``lo`` to ``hi``
    inclusive — the fixed histogram geometry (data-independent, so
    histograms from different replicas/runs merge by adding counts).
    The implicit ``+Inf`` bucket is NOT included (the histogram adds
    it)."""
    if not 0.0 < lo < hi:
        raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
    if count < 2:
        raise ValueError(f"need >= 2 buckets, got {count}")
    ratio = (hi / lo) ** (1.0 / (count - 1))
    return tuple(lo * ratio ** i for i in range(count))


# default latency geometry: 100us .. 100s, 25 log-spaced bounds —
# ~1.78x per bucket, wide enough for a CPU-smoke prefill and a TPU
# microsecond decode alike
DEFAULT_LATENCY_BUCKETS = log_buckets(1e-4, 100.0, 25)


def _fmt(v: float) -> str:
    """Prometheus sample formatting: integers without the trailing .0
    (matches client_golang), everything else via repr."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def labeled_name(name: str, labels) -> str:
    """The Prometheus sample name for (family, labels):
    ``family{k="v",...}`` with label keys sorted (so one logical
    metric always produces one registry key), or the bare family name
    when there are no labels."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic counter. ``inc()`` only goes up."""

    kind = "counter"
    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) < 0")
        self.value += n

    def as_value(self):
        return self.value

    def expose(self) -> List[str]:
        return [f"{self.name} {_fmt(self.value)}"]


class Gauge:
    """Point-in-time value. ``set()`` overwrites.

    Optionally labeled: ``labels={"kind": "kv"}`` makes this one
    sample of the family ``family`` — its registry key and exposed
    sample name become ``family{kind="kv"}``, and the exposition
    groups every sample of the family under ONE ``# HELP``/``# TYPE``
    header (the Prometheus family convention). Unlabeled gauges are
    byte-identical to the pre-label registry."""

    kind = "gauge"
    __slots__ = ("name", "help", "value", "family", "labels")

    def __init__(self, name: str, help: str, labels=None):
        self.family = name
        self.labels = dict(labels) if labels else {}
        self.name = labeled_name(name, self.labels)
        self.help = help
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def as_value(self):
        return self.value

    def expose(self) -> List[str]:
        return [f"{self.name} {_fmt(self.value)}"]


class Histogram:
    """Fixed-bound histogram: ``observe()`` is one bisect into the
    precomputed bounds (O(1)-ish, allocation-free), plus sum and count.
    Exposition follows the Prometheus convention: CUMULATIVE
    ``_bucket{le="..."}`` lines ending at ``+Inf``, then ``_sum`` and
    ``_count``.

    :meth:`quantile` estimates a percentile from the bucket counts by
    the same linear-interpolation rule as :func:`percentile` — here
    between bucket BOUNDS (assuming uniform mass within a bucket),
    since the raw observations are gone. Exact for the count/sum
    moments, approximate (one bucket wide) for quantiles — the price
    of O(1) memory."""

    kind = "histogram"
    __slots__ = ("name", "help", "bounds", "counts", "sum", "count")

    def __init__(self, name: str, help: str,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.help = help
        bounds = tuple(buckets if buckets is not None
                       else DEFAULT_LATENCY_BUCKETS)
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError(
                f"histogram {name}: bucket bounds must be strictly "
                f"increasing, got {bounds}")
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)   # + the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.counts[bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def quantile(self, q: float) -> float:
        """Bucket-interpolated percentile estimate (0 when empty — a
        dashboard reading, not a math error)."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"quantile q must be in [0, 100], got {q}")
        if self.count == 0:
            return 0.0
        rank = (q / 100.0) * (self.count - 1)
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c > rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = (self.bounds[i] if i < len(self.bounds)
                      else self.bounds[-1])
                frac = (rank - seen + 1) / c
                return float(lo + (hi - lo) * min(1.0, frac))
            seen += c
        return float(self.bounds[-1])

    def as_value(self) -> Dict[str, object]:
        return {
            "count": self.count,
            "sum": self.sum,
            "p50": self.quantile(50),
            "p90": self.quantile(90),
            "p99": self.quantile(99),
        }

    def expose(self) -> List[str]:
        lines = []
        cum = 0
        for bound, c in zip(self.bounds, self.counts):
            cum += c
            lines.append(f'{self.name}_bucket{{le="{_fmt(bound)}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {self.count}')
        lines.append(f"{self.name}_sum {_fmt(self.sum)}")
        lines.append(f"{self.name}_count {self.count}")
        return lines


class MetricsRegistry:
    """Name-keyed collection of metrics with get-or-create semantics
    (re-registering the same (name, kind) returns the existing metric —
    the engine and a bench harness may both ask for the same handle;
    a kind clash raises)."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, help: str, **kw):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m
        m = cls(name, help, **kw)
        self._metrics[name] = m
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "", labels=None) -> Gauge:
        """Get-or-create keyed by the full sample name, so each label
        combination of a family is its own gauge (``names()``/
        ``as_dict()`` list the labeled sample names literally)."""
        key = labeled_name(name, labels)
        m = self._metrics.get(key)
        if m is not None:
            if not isinstance(m, Gauge):
                raise ValueError(
                    f"metric {key!r} already registered as {m.kind}, "
                    f"requested gauge")
            return m
        m = Gauge(name, help, labels=labels)
        self._metrics[key] = m
        return m

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str):
        return self._metrics[name]

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def as_dict(self) -> Dict[str, object]:
        """Plain-dict dump for ``stats(deep=True)`` and JSON records:
        counters/gauges as scalars, histograms as their summary
        dicts."""
        return {name: self._metrics[name].as_value()
                for name in sorted(self._metrics)}

    def exposition(self) -> str:
        """Prometheus text format (version 0.0.4): ``# HELP`` /
        ``# TYPE`` headers then the samples, one metric family per
        block, newline-terminated."""
        names = sorted(self._metrics)
        blocks = []
        done = set()
        for name in names:
            m = self._metrics[name]
            family = getattr(m, "family", m.name)
            if family in done:
                continue
            done.add(family)
            # every sample of the family (labeled gauges share one),
            # in sample-name order, under one HELP/TYPE header —
            # identical to the pre-label output for unlabeled metrics
            members = [self._metrics[n] for n in names
                       if getattr(self._metrics[n], "family",
                                  self._metrics[n].name) == family]
            lines = []
            help_text = next((x.help for x in members if x.help), "")
            if help_text:
                lines.append(f"# HELP {family} {help_text}")
            lines.append(f"# TYPE {family} {m.kind}")
            for x in members:
                lines.extend(x.expose())
            blocks.append("\n".join(lines))
        return "\n".join(blocks) + ("\n" if blocks else "")
