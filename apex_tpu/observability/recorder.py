"""Flight recorder: a bounded ring buffer of structured engine events
(docs/observability.md).

BENCH_r01/r05 died and left NOTHING — the motivation written into
bench.py's section records, restated here for the engine itself:
when a dispatch chain wedges, the operator needs the last N decisions
(tick summaries, ladder transitions, quarantines, retries, cap walks),
not a point-in-time ``stats()`` dict that says only where the counters
ended up. The recorder is that black box: O(1) per event while enabled
(one dict append into a ``deque(maxlen=...)``), nothing at all when
absent, and NEVER an input to any engine decision (the
zero-perturbation contract).

``incident()`` freezes the current tail into a small bounded side
buffer at the moment something notable happens (a quarantine, a
device reset, a stall) — so the post-mortem survives even after the
ring itself rolls past the event.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional


# The closed vocabulary of recorder event kinds. Every kind must be
# documented in docs/observability.md (tools/check_docs.py enforces);
# record() rejects strays so a typo'd kind cannot silently dodge the
# lint.
RECORDER_EVENT_KINDS = (
    "tick",                 # per-scheduler-tick summary (engine)
    "ladder",               # degradation-ladder transition
    "quarantine",           # a request terminally failed by retry exhaustion
    "drafter_quarantine",   # the speculative drafter flipped off for good
    "fault_retry",          # one transient-failure retry at a dispatch site
    "spec_cap",             # spec_adapt moved the dynamic draft cap
    "alloc_pressure",       # CacheOutOfBlocks with no lane left to preempt
    "preempt",              # a lane preempted for pool pressure or quota
    "shed",                 # a request shed (queue_full/throttled/rejected)
    "spill",                # an evicted prefix block copied to the host tier
    "spill_upload",         # spilled blocks re-admitted by device upload
    "dequant_gemm",         # quantized weight storage committed at boot
    "corruption_detected",  # a checksummed artifact failed verification
    "scrub",                # one background integrity pass completed
    "sdc_suspect",          # the fleet cross-check caught a diverging replica
    "snapshot",             # snapshot() taken (lightweight=True: checkpoint())
    "restore",              # restore() applied
    "replica_down",         # a fleet replica declared dead (or retired)
    "failover",             # the dead replica's requests re-homed
    "migrate",              # drain-and-migrate moved requests off a replica
    "prefill_handoff",      # disaggregated prefill->decode handoff sweep
    "shared_publish",       # blocks published into the fleet shared tier
    "shared_hit",           # shared-tier blocks seeded into a replica
    "replica_spawn",        # the autoscaler grew the fleet by one replica
    "replica_retire",       # the autoscaler drained a replica away
    "rpc_timeout",          # a process-replica RPC exceeded its deadline
    "device_reset",         # drain-failure crash-restore (_reset_device_state)
    "stall",                # EngineStalledError about to raise
    "watchdog",             # TrainLoop non-finite-loss watchdog action
    "checkpoint",           # TrainLoop checkpoint saved
    "train_step",           # per-train-step summary (TrainLoop)
)

_KIND_SET = frozenset(RECORDER_EVENT_KINDS)


class FlightRecorder:
    """Bounded ring of ``{"kind", "seq", "t", ...fields}`` event dicts.

    ``seq`` is the lifetime event number (monotonic even after the ring
    wraps — ``dropped`` = ``seq_head - len(ring)`` tells the reader how
    much history rolled off). ``t`` comes from the injected clock (the
    engine passes its own ``_clock``, so recorder timelines are
    deterministic under fake clocks)."""

    def __init__(self, capacity: int = 256, clock=None,
                 max_incidents: int = 8):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._clock = time.monotonic if clock is None else clock
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self.incidents: deque = deque(maxlen=max_incidents)

    def use_clock(self, clock) -> None:
        self._clock = clock

    @property
    def dropped(self) -> int:
        return self._seq - len(self._ring)

    def __len__(self) -> int:
        return len(self._ring)

    def record(self, kind: str, **fields) -> None:
        if kind not in _KIND_SET:
            raise ValueError(
                f"unknown recorder event kind {kind!r} (known: "
                f"{RECORDER_EVENT_KINDS})")
        # an explicit t= reuses a timestamp the caller already read
        # (no extra clock call); otherwise stamp here
        t = fields.pop("t", None)
        ev = {"kind": kind, "seq": self._seq,
              "t": float(self._clock() if t is None else t)}
        ev.update(fields)
        self._seq += 1
        self._ring.append(ev)

    def tail(self, n: Optional[int] = None) -> List[Dict]:
        """The most recent ``n`` events (all, when ``n`` is None),
        oldest first — copied dicts, safe to serialize or mutate."""
        evs = list(self._ring)
        if n is not None:
            evs = evs[-n:]
        return [dict(e) for e in evs]

    def incident(self, label: str, **fields) -> Dict:
        """Freeze the current tail as a named incident (kept in a
        bounded side buffer so it survives ring wrap). Returns the
        incident record."""
        inc = {"label": label, "t": float(self._clock()),
               "events": self.tail()}
        inc.update(fields)
        self.incidents.append(inc)
        return inc

    def dump(self) -> Dict[str, object]:
        """JSON-able picture: the ring, the incidents, and the drop
        accounting — the recorder half of ``Observability.dump()``."""
        return {
            "capacity": self.capacity,
            "dropped": self.dropped,
            "events": self.tail(),
            "incidents": [dict(i) for i in self.incidents],
        }
