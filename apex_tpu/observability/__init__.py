"""apex_tpu.observability — the engine/train observability layer.

Three composable pieces (docs/observability.md), threaded through
:class:`~apex_tpu.serving.InferenceEngine` and
:class:`~apex_tpu.train.TrainLoop` behind one coordinator
(:class:`Observability`):

- request-lifecycle tracing (:mod:`~apex_tpu.observability.trace`):
  per-request span timelines, Perfetto-loadable Chrome-trace export;
- flight recorder (:mod:`~apex_tpu.observability.recorder`): a bounded
  ring of structured engine events, frozen into incidents at
  quarantines/resets/stalls and dumped to a file on unhandled engine
  exceptions;
- metrics registry (:mod:`~apex_tpu.observability.metrics`):
  counters/gauges/log-bucket histograms with Prometheus text
  exposition, merged into ``stats(deep=True)``.

The governing contract is **zero perturbation**: observers consume
events, never produce decisions — engine output with observability
attached is bit-identical to without, across greedy/sampled,
speculative/not, preemption, and snapshot/restore (certified in
tests/test_observability.py). Observer state is excluded from the
snapshot fingerprint; recorder/trace tails ride ``snapshot()`` only as
an audit section that ``restore()`` never reloads.

Usage::

    obs = Observability(crash_dump_path="engine_crash.json")
    engine = InferenceEngine(model, params, config, obs=obs)
    ...
    obs.metrics.exposition()       # Prometheus text
    obs.tracer.chrome_trace()      # load in Perfetto
    obs.dump_to("run_dump.json")   # tools/trace_summary.py input
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Tuple

from apex_tpu.observability.metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    log_buckets,
    percentile,
)
from apex_tpu.observability.recorder import (  # noqa: F401
    RECORDER_EVENT_KINDS,
    FlightRecorder,
)
from apex_tpu.observability.trace import (  # noqa: F401
    TRACE_EVENT_TYPES,
    RequestTracer,
)

DUMP_FORMAT = "apex_tpu-obs-dump-v1"


def flatten_stats(stats: Dict[str, object], sep: str = ".",
                  exclude: Tuple[str, ...] = ()) -> Dict[str, object]:
    """The ONE sanctioned flattener for nested ``stats()`` dicts:
    nested dict keys join with ``sep`` (``tenants.acme.tokens``),
    scalar leaves pass through, ``exclude`` drops top-level keys
    (bench's scheduler record excludes the per-tenant ledger, which
    has its own arm). Replaces the ad-hoc ``isinstance(v, dict)``
    special-casing bench had to carry once ``stats()`` grew its first
    nested section."""
    out: Dict[str, object] = {}

    def walk(prefix: str, d: Dict[str, object]) -> None:
        for k, v in d.items():
            if not prefix and k in exclude:
                continue
            key = f"{prefix}{sep}{k}" if prefix else str(k)
            if isinstance(v, dict):
                walk(key, v)
            else:
                out[key] = v

    walk("", stats)
    return out


# -- the metric surfaces (names enforced documented by check_docs) --------

# numeric encoding of the storage quantization modes for the labeled
# ``serving_quantization_mode`` gauges (a Prometheus gauge is a float;
# the mode strings ride the restore fingerprint, the codes ride the
# dashboard): 0 = full precision, 1 = int8, 2 = fp8
QUANT_MODE_CODES = {None: 0.0, "int8": 1.0, "fp8": 2.0}


def register_engine_metrics(registry: MetricsRegistry) -> Dict[str, object]:
    """Register the serving engine's metric set (idempotent) and return
    the handles. The histograms replace scalar-only EWMAs as the
    OBSERVABLE latency surface — the EWMAs keep feeding the admission
    gate unchanged."""
    return {
        "ttft": registry.histogram(
            "serving_ttft_s",
            "submit to first host-visible token, seconds"),
        "itl": registry.histogram(
            "serving_itl_s",
            "gap between successive host-visible tokens of one "
            "request, seconds"),
        "prefill": registry.histogram(
            "serving_prefill_dispatch_s",
            "one prefill-chunk dispatch+fetch, seconds"),
        "decode": registry.histogram(
            "serving_decode_dispatch_s",
            "one decode/verify drain fetch block, seconds"),
        "queue_wait": registry.histogram(
            "serving_queue_wait_s",
            "enqueue to admission, seconds"),
        "requests": registry.counter(
            "serving_requests_total", "requests accepted into the queue"),
        "tokens": registry.counter(
            "serving_tokens_total", "fresh tokens delivered"),
        "sheds": registry.counter(
            "serving_sheds_total",
            "requests shed (queue_full + throttled + rejected)"),
        "preemptions": registry.counter(
            "serving_preemptions_total", "lane preemptions"),
        # one labeled family, one sample per storage surface — the
        # engine sets both at construction from its config
        # (QUANT_MODE_CODES), closing the asymmetry where
        # kv_quantization rode the restore fingerprint but no
        # observable surface
        "kv_quant_mode": registry.gauge(
            "serving_quantization_mode",
            "storage quantization mode code (0=off, 1=int8, 2=fp8)",
            labels={"kind": "kv"}),
        "weight_quant_mode": registry.gauge(
            "serving_quantization_mode",
            "storage quantization mode code (0=off, 1=int8, 2=fp8)",
            labels={"kind": "weight"}),
    }


def register_train_metrics(registry: MetricsRegistry) -> Dict[str, object]:
    """Register :class:`~apex_tpu.train.TrainLoop`'s metric set
    (idempotent) and return the handles."""
    return {
        "step": registry.histogram(
            "train_step_s",
            "one TrainLoop.step() host span (dispatch + deferred "
            "fetch), seconds"),
        "steps": registry.counter(
            "train_steps_total", "train steps dispatched"),
        "retries": registry.counter(
            "train_retries_total", "transient train-step retries"),
        "nonfinite": registry.counter(
            "train_nonfinite_total", "non-finite losses observed"),
        "checkpoints": registry.counter(
            "train_checkpoints_total", "checkpoints saved"),
    }


_SHED_REASONS = ("queue_full", "throttled", "rejected")


class Observability:
    """The coordinator the engine and train loop thread events through.

    All three members are optional and independently disableable
    (``trace=False``, ``recorder_capacity=0``, ``metrics=False``); a
    disabled member costs nothing, an enabled one O(1) per event. The
    ``note_*`` methods are the engine-facing vocabulary; they fan each
    logical event out to whichever members exist. One Observability
    may serve one engine OR one train loop (its per-request state is
    engine-scoped); share a single :class:`MetricsRegistry` across
    several via the ``metrics=`` argument when aggregating."""

    def __init__(self, *, trace: bool = True,
                 recorder_capacity: int = 256,
                 metrics: object = True,
                 trace_max_events: int = 100_000,
                 crash_dump_path: Optional[str] = None,
                 clock=None):
        self._clock = time.monotonic if clock is None else clock
        self.tracer = (RequestTracer(clock=self._clock,
                                     max_events=trace_max_events)
                       if trace else None)
        self.recorder = (FlightRecorder(recorder_capacity,
                                        clock=self._clock)
                         if recorder_capacity else None)
        if metrics is True:
            self.metrics: Optional[MetricsRegistry] = MetricsRegistry()
        elif metrics:
            self.metrics = metrics          # a shared registry
        else:
            self.metrics = None
        self.crash_dump_path = crash_dump_path
        self._m: Dict[str, object] = {}
        # per-request metric state: uid -> [submit_t, last_token_t]
        self._req: Dict[str, List[Optional[float]]] = {}

    # -- binding -----------------------------------------------------------

    def use_clock(self, clock) -> None:
        """Rebind every member onto ``clock`` — the engine passes its
        own injectable ``_clock`` so traces are deterministic under
        the fake clocks the deadline tests use. The clock must be a
        PURE READ (no side effects, not advanced by calling — like
        ``time.monotonic``): metric-bearing hooks reuse timestamps the
        engine already read, but trace/recorder instants make
        additional reads (docs/observability.md, clock contract)."""
        self._clock = clock
        if self.tracer is not None:
            self.tracer.use_clock(clock)
        if self.recorder is not None:
            self.recorder.use_clock(clock)

    def now(self) -> float:
        return float(self._clock())

    def bind_engine(self, clock) -> None:
        self.use_clock(clock)
        if self.metrics is not None:
            self._m.update(register_engine_metrics(self.metrics))

    def bind_train(self, clock=None) -> None:
        if clock is not None:
            self.use_clock(clock)
        if self.metrics is not None:
            self._m.update(register_train_metrics(self.metrics))

    # -- pass-throughs -----------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.record(kind, **fields)

    def incident(self, label: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.incident(label, **fields)

    def trace_event(self, etype: str, uid: str, **kw) -> None:
        if self.tracer is not None:
            self.tracer.event(etype, uid, **kw)

    def observe(self, handle: str, v: float) -> None:
        """Observe into a bound metric handle (no-op when metrics are
        off or the handle is unbound)."""
        m = self._m.get(handle)
        if m is not None:
            m.observe(v)

    def inc(self, handle: str, n: float = 1) -> None:
        m = self._m.get(handle)
        if m is not None:
            m.inc(n)

    def gauge(self, handle: str, v: float) -> None:
        """Set a bound gauge handle (no-op when metrics are off or the
        handle is unbound)."""
        m = self._m.get(handle)
        if m is not None:
            m.set(v)

    # -- the engine-facing event vocabulary --------------------------------

    def note_enqueue(self, uid: str, *, tenant: str = "", priority: int = 0,
                     prompt_len: int = 0, requeue: bool = False,
                     t: Optional[float] = None) -> None:
        if t is None:
            t = self.now()
        if not requeue:
            self._req.setdefault(uid, [t, None])
            self.inc("requests")
        self.trace_event("requeue" if requeue else "enqueue", uid, t=t,
                         tenant=tenant, priority=priority,
                         prompt_len=prompt_len)

    def note_shed(self, uid: str, reason: str, *, queued: bool) -> None:
        assert reason in _SHED_REASONS, reason
        self.inc("sheds")
        self.trace_event("shed", uid, reason=reason, queued=queued)
        self.record("shed", uid=uid, reason=reason)

    def note_admit(self, uid: str, lane: int, wait_s: float,
                   cached_blocks: int = 0,
                   t: Optional[float] = None) -> None:
        self.observe("queue_wait", wait_s)
        self.trace_event("admit", uid, lane=lane, t=t, wait_s=wait_s,
                         cached_blocks=cached_blocks)

    def note_prefill_chunk(self, uid: str, lane: int, start: int, end: int,
                           t_start: float, dur_s: float) -> None:
        self.observe("prefill", dur_s)
        self.trace_event("prefill_chunk", uid, lane=lane, t=t_start,
                         dur_s=dur_s, start=start, end=end)

    def note_decode_drained(self, dispatch: int, t_start: float,
                            t_end: float, fetch_s: float,
                            lanes) -> None:
        """One drained decode/verify dispatch: ``lanes`` is
        ``[(uid, lane, tokens)]`` for the lanes whose results were
        kept. The histogram observes the fetch block (the same measure
        the gate's EWMA uses); the trace span covers dispatch→drain
        (what a timeline viewer wants to see)."""
        self.observe("decode", fetch_s)
        dur = max(0.0, t_end - t_start)
        for uid, lane, tokens in lanes:
            self.trace_event("decode", uid, lane=lane, t=t_start,
                             dur_s=dur, dispatch=dispatch, tokens=tokens)
            self.trace_event("drain", uid, t=t_end, tokens=tokens,
                             dispatch=dispatch)

    def note_token(self, uid: str, t: Optional[float] = None) -> None:
        """One fresh host-visible token: feeds the TTFT histogram on a
        request's first, the inter-token-latency histogram after.
        ``t`` is the host-visibility timestamp the ENGINE already read
        (the prefill fetch or the drain) — reused so observation adds
        no clock call of its own on the token path."""
        self.inc("tokens")
        st = self._req.get(uid)
        if st is None:
            return
        if t is None:
            t = self.now()
        if st[1] is None:
            self.observe("ttft", t - st[0])
        else:
            self.observe("itl", t - st[1])
        st[1] = t

    def note_preempt(self, uid: str, lane: int,
                     reason: str = "pool_pressure",
                     t: Optional[float] = None) -> None:
        self.inc("preemptions")
        self.trace_event("preempt", uid, lane=lane, t=t, reason=reason)
        self.record("preempt", uid=uid, lane=lane, t=t, reason=reason)

    def note_terminal(self, uid: str, status: str,
                      lane: Optional[int] = None) -> None:
        self._req.pop(uid, None)
        self.trace_event("terminal", uid, lane=lane, status=status)

    # -- dumps -------------------------------------------------------------

    def deep_stats(self) -> Dict[str, object]:
        """The ``stats(deep=True)`` merge section."""
        out: Dict[str, object] = {}
        if self.metrics is not None:
            out["metrics"] = self.metrics.as_dict()
        if self.recorder is not None:
            out["recorder_events"] = len(self.recorder)
            out["recorder_dropped"] = self.recorder.dropped
            out["recorder_incidents"] = len(self.recorder.incidents)
        if self.tracer is not None:
            out["trace_events"] = len(self.tracer)
            out["trace_dropped"] = self.tracer.dropped
        return out

    def dump(self, include_chrome: bool = False) -> Dict[str, object]:
        """The full JSON-able picture — the input contract of
        tools/trace_summary.py. ``include_chrome`` embeds the
        Perfetto rendering too (off by default: the timelines already
        carry every event once; ``tracer.chrome_trace()`` regenerates
        it on demand)."""
        out: Dict[str, object] = {"format": DUMP_FORMAT}
        if self.tracer is not None:
            out["trace"] = self.tracer.dump(include_chrome)
        if self.recorder is not None:
            out["recorder"] = self.recorder.dump()
        if self.metrics is not None:
            out["metrics"] = {"values": self.metrics.as_dict(),
                              "exposition": self.metrics.exposition()}
        return out

    def dump_to(self, path: str, include_chrome: bool = False) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.dump(include_chrome), f, indent=1,
                      default=str)
        return path

    def crash_dump(self, error: BaseException) -> Optional[str]:
        """Write the post-mortem (recorder incident + full dump) to
        ``crash_dump_path``; a dump failure is swallowed — the
        original exception must keep propagating."""
        try:
            self.incident("crash", error=f"{type(error).__name__}: {error}")
            if self.crash_dump_path is None:
                return None
            payload = self.dump()
            payload["error"] = f"{type(error).__name__}: {error}"
            with open(self.crash_dump_path, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, default=str)
            return self.crash_dump_path
        except Exception:
            return None
