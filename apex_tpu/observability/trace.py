"""Request-lifecycle tracing with Chrome-trace (Perfetto) export
(docs/observability.md).

Every request gets a span timeline: enqueue, admit/shed (with reason),
each prefill chunk, each decode/verify dispatch it rode, preemption,
requeue, drain, terminal status. Two read surfaces over ONE event
store:

- :meth:`RequestTracer.request_timeline` / :meth:`timelines` — plain
  per-request dicts, the API tests and the future fleet router consume
  (the router routes on "who is waiting how long where", not on a UI
  format);
- :meth:`RequestTracer.chrome_trace` — Chrome-trace-format JSON
  (``chrome://tracing`` / https://ui.perfetto.dev loadable): ``ph``
  ``B``/``E`` lane-residency spans, ``X`` complete events for prefill
  chunks and decode dispatches, ``i`` instants for queue transitions;
  ``pid`` is the engine, ``tid 0`` the waiting queue, ``tid i+1`` lane
  ``i``.

Timestamps come from the injected clock — the ENGINE's own
``_clock`` — so traces are deterministic under the fake clocks the
deadline/overload tests already use, and the tracer is NEVER an input
to a scheduling decision (the zero-perturbation contract: tracing on
is bit-identical to tracing off, certified in
tests/test_observability.py).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional


# The closed vocabulary of trace event types. Every type must be
# documented in docs/observability.md (tools/check_docs.py enforces);
# event() rejects strays.
TRACE_EVENT_TYPES = (
    "enqueue",        # request entered the waiting queue
    "requeue",        # re-entered after preemption / device reset
    "admit",          # moved into a lane (begins the lane-residency span)
    "shed",           # refused: reason queue_full | throttled | rejected
    "prefill_chunk",  # one [1, prefill_chunk] piece ran (span, dur_s)
    "decode",         # one decode/verify dispatch the request rode (span)
    "drain",          # its tokens from that dispatch became host-visible
    "preempt",        # evicted from its lane (ends the residency span)
    "terminal",       # reached a terminal status (finished/timeout/...)
)

_TYPE_SET = frozenset(TRACE_EVENT_TYPES)

# events that END the lane-residency span a matching "admit" began
_LANE_END = ("preempt", "terminal")
_QUEUE_TID = 0


class RequestTracer:
    """Append-only event store with per-request indexing.

    Each record is ``{"type", "uid", "t", "lane", "dur_s", ...args}``
    (``lane`` None for queue-side events). The store is bounded by
    ``max_events``: past it, NEW events are counted in ``dropped``
    instead of stored — a trace is a forensic artifact, and silently
    losing its beginning is worse than truncating its end (the flight
    recorder owns the rolling-tail role)."""

    def __init__(self, clock=None, max_events: int = 100_000):
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        self._clock = time.monotonic if clock is None else clock
        self._max_events = max_events
        self._events: List[Dict] = []
        self._by_uid: Dict[str, List[Dict]] = {}
        self.dropped = 0

    def use_clock(self, clock) -> None:
        self._clock = clock

    def __len__(self) -> int:
        return len(self._events)

    def event(self, etype: str, uid: str, *, lane: Optional[int] = None,
              t: Optional[float] = None, dur_s: Optional[float] = None,
              **args) -> None:
        if etype not in _TYPE_SET:
            raise ValueError(
                f"unknown trace event type {etype!r} (known: "
                f"{TRACE_EVENT_TYPES})")
        if len(self._events) >= self._max_events:
            self.dropped += 1
            return
        rec = {"type": etype, "uid": uid,
               "t": float(self._clock() if t is None else t),
               "lane": lane}
        if dur_s is not None:
            rec["dur_s"] = float(dur_s)
        rec.update(args)
        self._events.append(rec)
        self._by_uid.setdefault(uid, []).append(rec)

    # -- the plain dict API ------------------------------------------------

    def request_timeline(self, uid: str) -> List[Dict]:
        """The request's events in emission order (copies)."""
        return [dict(e) for e in self._by_uid.get(uid, ())]

    def timelines(self) -> Dict[str, List[Dict]]:
        return {uid: [dict(e) for e in evs]
                for uid, evs in self._by_uid.items()}

    # -- Chrome-trace / Perfetto export ------------------------------------

    @staticmethod
    def _tid(rec: Dict) -> int:
        lane = rec.get("lane")
        return _QUEUE_TID if lane is None else int(lane) + 1

    def chrome_trace(self) -> Dict[str, object]:
        """The trace as a Chrome-trace-format dict (``json.dumps`` it
        into a ``.json`` Perfetto opens directly). Timestamps are
        microseconds relative to the first event; events are emitted
        sorted by timestamp (stable, so same-timestamp events keep
        emission order and ``B`` precedes its ``E``)."""
        evs = self._events
        epoch = evs[0]["t"] if evs else 0.0
        out: List[Dict] = [
            {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
             "args": {"name": "engine"}},
            {"ph": "M", "pid": 1, "tid": _QUEUE_TID,
             "name": "thread_name", "args": {"name": "queue"}},
        ]
        lanes_seen = set()
        body: List[Dict] = []
        for rec in evs:
            tid = self._tid(rec)
            if tid != _QUEUE_TID:
                lanes_seen.add(tid)
            ts = (rec["t"] - epoch) * 1e6
            uid = rec["uid"]
            etype = rec["type"]
            args = {k: v for k, v in rec.items()
                    if k not in ("type", "uid", "t", "lane", "dur_s")}
            args["uid"] = uid
            base = {"pid": 1, "tid": tid, "ts": ts, "cat": etype,
                    "args": args}
            if etype in ("prefill_chunk", "decode"):
                base.update(ph="X", name=f"{etype} {uid}",
                            dur=rec.get("dur_s", 0.0) * 1e6)
            elif etype == "admit":
                base.update(ph="B", name=f"req {uid}")
            elif etype in _LANE_END and tid != _QUEUE_TID:
                base.update(ph="E", name=f"req {uid}")
            else:
                # queue-side instants: enqueue/requeue/shed/drain and
                # off-lane terminals (timeout/abort/shed while waiting)
                base.update(ph="i", name=f"{etype} {uid}", s="t")
            body.append(base)
        for tid in sorted(lanes_seen):
            out.append({"ph": "M", "pid": 1, "tid": tid,
                        "name": "thread_name",
                        "args": {"name": f"lane {tid - 1}"}})
        body.sort(key=lambda e: e["ts"])     # stable: ties keep order
        out.extend(body)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def dump(self, include_chrome: bool = False) -> Dict[str, object]:
        """JSON-able dump. The timelines ARE the full event store;
        the Chrome rendering is a pure function of them, so it is
        omitted by default (a crash dump need not carry every event
        twice) — regenerate via :meth:`chrome_trace`, or pass
        ``include_chrome=True`` to embed it."""
        out = {
            "dropped": self.dropped,
            "num_events": len(self._events),
            "timelines": self.timelines(),
        }
        if include_chrome:
            out["chrome_trace"] = self.chrome_trace()
        return out
