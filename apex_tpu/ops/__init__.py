"""apex_tpu.ops — the native-kernel stratum (L0 in SURVEY.md §1).

Where the reference has CUDA kernels (``csrc/``), this package has XLA
flat-buffer fusions (:mod:`apex_tpu.ops.multi_tensor`) and Pallas TPU
kernels (:mod:`apex_tpu.ops.layer_norm`, :mod:`apex_tpu.ops.softmax`,
:mod:`apex_tpu.ops.flash_attention`, :mod:`apex_tpu.ops.ring_attention`).
"""

from apex_tpu.ops import multi_tensor  # noqa: F401
from apex_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_dropout_keep_mask,
)
from apex_tpu.ops.ring_attention import ring_attention  # noqa: F401
from apex_tpu.ops.ulysses_attention import ulysses_attention  # noqa: F401
