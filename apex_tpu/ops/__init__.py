"""apex_tpu.ops — the native-kernel stratum (L0 in SURVEY.md §1).

Where the reference has CUDA kernels (``csrc/``), this package has XLA
flat-buffer fusions (:mod:`apex_tpu.ops.multi_tensor`) and Pallas TPU
kernels (:mod:`apex_tpu.ops.layer_norm`, :mod:`apex_tpu.ops.softmax`, ...).
"""

from apex_tpu.ops import multi_tensor  # noqa: F401
