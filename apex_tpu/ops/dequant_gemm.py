"""Fused Pallas dequant-GEMM for quantized weight storage.

Weight quantization (:func:`apex_tpu.models.gpt.quantize_gpt_params`)
stores the six GPT qkv/proj/mlp kernels as int8/fp8 with a
per-OUTPUT-channel fp32 scale. The read chain is: dequantize
(``w_q.astype(f32) * scale[None, :]``), then matmul. The composed XLA
form (:func:`dequant_matmul_reference`) materializes the full
dequantized ``(K, N)`` fp32 kernel in HBM on every dispatch —
surrendering the very HBM-traffic win quantization bought on the
weight-bound decode path. This module fuses the chain into ONE
``pallas_call``: the grid walks the output-channel (N) axis in lane
tiles, each step streams one int8/fp8 kernel tile plus its scale
sliver into VMEM, dequantizes in-register, and contracts the full K
axis against the activations — the fp32 weights never exist outside
VMEM, so HBM reads stay at the quantized byte width.

READ SIDE ONLY, by design: the BENCH_r01 lesson recorded in ROADMAP.md
is that Pallas TPU has no scatter lowering — quantization itself (the
*write* of the quantized tree, a one-time construction-cost in
``quantize_gpt_params``) stays in XLA, and the kernel reads what XLA
wrote. Same division of labor as ``paged_attention_pallas.py``.

Numerical contract (certified in tests/test_weight_quant.py, interpret
mode): the kernel performs the SAME primitive sequence as the XLA
chain — elementwise dequant in fp32, then one fp32
``jnp.dot(..., preferred_element_type=f32)`` over the full K axis —
and the grid tiles ONLY the output-channel axis, never K. Output
column ``j`` is a K-reduction over ``x`` and ``w[:, j]`` alone, so
tiling N leaves every column's reduction order untouched and the
kernel is BIT-IDENTICAL to :func:`dequant_matmul_reference` (a K-split
with a partial-sum accumulator would not be — that is why there isn't
one; K lives entirely in VMEM per step).

Selection: ``dequant_matmul(..., use_pallas=True)`` or the
``APEX_DEQUANT_GEMM_PALLAS=1`` env flag (read at trace time); the
static shape gate (:func:`dequant_gemm_supported`) keeps the XLA
chain as the universal fallback — interpret mode (every non-TPU
backend) always qualifies, native TPU additionally needs
lane/sublane-tileable operands and a VMEM-feasible working set.

SINGLE-DEVICE ONLY: ``pallas_call`` has no SPMD partitioning rule, so
the kernel cannot run over GSPMD-sharded kernels (docs/serving.md
"Mesh sharding" — the engine rejects the env flag when its mesh's
``model`` axis is > 1, where the XLA chain partitions collective-free
instead, scales riding their kernel's shard).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._common import interpret_mode as _interpret

_ENV_FLAG = "APEX_DEQUANT_GEMM_PALLAS"

# native-TPU VMEM budget for one grid step's working set (activations +
# kernel tile + output tile, fp32); shapes past it fall back to XLA
_VMEM_BUDGET = 8 * 1024 * 1024

_LANE_TILE = 128


def dequant_gemm_wanted(use_pallas=None) -> bool:
    """Whether the caller asked for the fused kernel: an explicit
    ``use_pallas`` wins; ``None`` consults the env flag (read at trace
    time — set it before the engine compiles its programs)."""
    if use_pallas is not None:
        return bool(use_pallas)
    return os.environ.get(_ENV_FLAG, "").strip().lower() in (
        "1", "true", "on", "yes")


def dequant_gemm_supported(m: int, k: int, n: int) -> bool:
    """Static shape gate for the native kernel: operands must be
    Mosaic-tileable (K and N lane/sublane-aligned for the int8 tile
    shape, M a sublane multiple) and one grid step's fp32 working set
    must fit VMEM. Interpret mode (every non-TPU backend) has no
    tiling constraints and always qualifies — which is what lets the
    CPU bit-identity certification drive every shape the model uses."""
    if _interpret():
        return True
    if m % 8 != 0 or k % _LANE_TILE != 0 or n % _LANE_TILE != 0:
        return False
    tn = _LANE_TILE
    if 4 * (m * k + k * tn + m * tn) > _VMEM_BUDGET:
        return False
    return True


def dequant_matmul_reference(x, w_q, scale):
    """The composed XLA dequant-then-matmul chain — the universal
    fallback and the certification reference: dequantize the whole
    kernel to fp32, one fp32 dot. ``x: (..., K)``, ``w_q: (K, N)``
    int8/fp8, ``scale: (N,)`` fp32 -> ``(..., N)`` fp32."""
    w = w_q.astype(jnp.float32) * scale.astype(jnp.float32)[None, :]
    return jnp.dot(x.astype(jnp.float32), w,
                   preferred_element_type=jnp.float32)


def _dequant_gemm_kernel(x_ref, w_ref, s_ref, o_ref):
    """One output-channel tile: dequantize this tile's columns in
    VMEM, contract the FULL K axis. Same two primitives, same order,
    same fp32 types as the reference — see the module docstring for
    why N-only tiling makes this bit-identical."""
    w = w_ref[...].astype(jnp.float32) * s_ref[0][None, :]
    o_ref[...] = jnp.dot(x_ref[...].astype(jnp.float32), w,
                         preferred_element_type=jnp.float32)


def _pallas_dequant_gemm(x2d, w_q, scale):
    M, K = x2d.shape
    N = w_q.shape[1]
    TN = _LANE_TILE if N % _LANE_TILE == 0 else N
    out = pl.pallas_call(
        _dequant_gemm_kernel,
        grid=(N // TN,),
        in_specs=[
            pl.BlockSpec((M, K), lambda j: (0, 0)),
            pl.BlockSpec((K, TN), lambda j: (0, j)),
            pl.BlockSpec((1, TN), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((M, TN), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
        interpret=_interpret(),
    )(x2d, w_q, scale.astype(jnp.float32).reshape(1, N))
    return out


def dequant_matmul(x, w_q, scale, use_pallas=None):
    """Quantized-weight matmul: ``(..., K) @ dequant((K, N)) ->
    (..., N)`` fp32. Owns the flag/gate/fallback arbitration — the
    fused kernel runs only when wanted (explicit ``use_pallas`` or the
    ``APEX_DEQUANT_GEMM_PALLAS`` env flag) AND the static gate admits
    the shape; everything else takes :func:`dequant_matmul_reference`.
    ``QuantDense`` (models/gpt.py) is the production caller."""
    lead = x.shape[:-1]
    K = x.shape[-1]
    N = w_q.shape[1]
    x2d = x.reshape(-1, K)
    if (dequant_gemm_wanted(use_pallas)
            and dequant_gemm_supported(x2d.shape[0], K, N)):
        out = _pallas_dequant_gemm(x2d, w_q, scale)
    else:
        out = dequant_matmul_reference(x2d, w_q, scale)
    return out.reshape(*lead, N)
