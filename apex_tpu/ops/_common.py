"""Shared helpers for the Pallas kernel wrappers."""

from __future__ import annotations

import jax

LANE = 128  # TPU vector lane width (minor tile dim)


def round_up(x: int, m: int) -> int:
    """Round x up to a multiple of m (tile/lane alignment)."""
    return (x + m - 1) // m * m


def interpret_mode() -> bool:
    """Pallas kernels run compiled on TPU, interpreted elsewhere (the
    CPU-sim test path exercises identical kernel code)."""
    return jax.default_backend() != "tpu"


def keep_threshold(dropout_rate):
    """uint32 threshold shared by every fused-dropout kernel: a lane is
    kept iff its random bits are < this. keep_prob maps onto the full
    uint32 range so the kept fraction is exact to 2^-32 (the reference
    Philox kernels use the same compare-against-scaled-keep-prob
    construction)."""
    import jax.numpy as jnp

    keep = 1.0 - dropout_rate
    return jnp.uint32(min(int(keep * 4294967296.0), 4294967295))


def mix_seed(seed, n):
    """Decorrelated int32 PRNG seed from (seed, n): golden-ratio
    multiplicative hash in uint32 wraparound arithmetic, masked to
    non-negative int32. Shared by every consumer that derives per-rank /
    per-block dropout seeds (ring block pairs, Ulysses context ranks) so
    the derivation can't drift between them; sequential `seed + n` would
    give adjacent consumers correlated hardware-PRNG streams, and the
    uint32 round-trip avoids int32 overflow near 2^31."""
    import jax.numpy as jnp

    mixed = (jnp.asarray(seed, jnp.int32).astype(jnp.uint32)
             ^ (jnp.asarray(n).astype(jnp.uint32) * jnp.uint32(0x9E3779B9)))
    return (mixed & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32)


def _vma_of(x):
    """The varying-axes set of a value, or empty on JAX versions without
    ``jax.typeof``/vma tracking (pre-0.6 releases: shard_map there has no
    vma checking, so "varies over no axes" is the correct answer)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return frozenset(getattr(typeof(x), "vma", ()))


def use_jnp_fallback(*arrays) -> bool:
    """True when the Pallas interpreter cannot be used: non-TPU backend AND
    inputs varying over shard_map axes (this JAX version's HLO interpreter
    mishandles vma inside its internal loops). The jnp fallbacks compute
    the identical formulas; real TPU always takes the compiled kernels."""
    if jax.default_backend() == "tpu":
        return False
    return any(_vma_of(a) for a in arrays if a is not None)


def match_vma(cotangent, primal_example):
    """Align a custom_vjp cotangent's varying-axes set to its primal's.

    Inside ``shard_map``, autodiff inserts boundary psums for primitives
    automatically, but a custom_vjp bwd rule is on its own: if the
    incoming gradient varies over more mesh axes than the primal input
    (e.g. params replicated across ``data`` receiving data-sharded
    batch gradients), the bwd rule must psum over the extra axes itself.
    """
    want = _vma_of(primal_example)
    have = _vma_of(cotangent)
    extra = have - want
    if extra:
        cotangent = jax.lax.psum(cotangent, tuple(sorted(extra)))
    return cotangent


def out_struct(shape, dtype, *like):
    """``ShapeDtypeStruct`` whose varying-axes set is the union of the
    inputs'. Inside ``shard_map`` with vma checking, pallas_call outputs
    must declare how they vary across mesh axes; outside, the empty set is
    accepted and ignored."""
    vma = frozenset()
    for r in like:
        vma |= _vma_of(r)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax without the vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)
