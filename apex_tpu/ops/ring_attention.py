"""Ring attention: context-parallel flash attention over ``ppermute``.

Long-context stretch target (SURVEY.md §5 long-context row): the
reference tops out at Megatron-SP + seq-length-limited fused kernels;
ring attention shards the SEQUENCE across a mesh axis and never
materializes more than one (S/cp)-block of keys/values per device —
sequence length scales linearly with the ring size.

TPU-native design: each device holds its (B, H, S/cp, D) shard of
q/k/v. A ``lax.scan`` runs ``cp`` steps; at each step the device
attends its queries against the CURRENT k/v block with the Pallas flash
kernel (which already returns per-row logsumexp), folds the block's
contribution into fp32 running (accumulator, lse) via the standard
log-sum-exp merge, and rotates k/v to the ring neighbor with
``ppermute`` — compute and the ICI transfer of the NEXT block overlap
under XLA's latency-hiding scheduler (the Ring Attention overlap,
scheduled by the compiler instead of by hand).

Causality across blocks uses the block-index relation (full / in-block
causal / skip via ``lax.switch``); gradients flow by autodiff — the
reverse of the scan replays the ring in the opposite direction
(AD of ppermute is the inverse permutation), with ``jax.checkpoint``
on the per-step body so only O(S/cp) activations persist per step.

Run inside ``shard_map`` with the context axis in scope; sequence
shards are contiguous: device i holds tokens [i*S/cp, (i+1)*S/cp).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import (
    flash_attention_with_lse,
    mha_reference,
)


def _block_attend(q, k, v, key_mask, causal, scale,
                  dropout_rate=0.0, dropout_seed=None):
    """(out, lse) for one q-block vs one kv-block; lse is (B, H, 1, Sq)
    fp32. Differentiable on both paths — the flash kernel variant folds
    the lse cotangent into its recompute backward."""
    out, lse = flash_attention_with_lse(q, k, v, key_mask, causal, scale,
                                        dropout_rate, dropout_seed)
    return out.astype(jnp.float32), lse


def _block_seed(seed, q_block, kv_block, cp):
    """Per-(global q-block, global kv-block) dropout seed: the base seed
    hashed with the block-pair id (shared :func:`mix_seed` derivation).
    Every tile of the global attention matrix draws an independent PRNG
    stream, and backward replays the same mask because
    (q_block, kv_block) is recomputed identically on the reverse ring
    pass."""
    from apex_tpu.ops._common import mix_seed

    return mix_seed(seed, q_block.astype(jnp.uint32) * jnp.uint32(cp)
                    + kv_block.astype(jnp.uint32))


def ring_attention(q, k, v, key_mask=None, causal: bool = False,
                   scale: float = 1.0, axis_name: str = "context",
                   dropout_rate: float = 0.0, dropout_seed=None):
    """Context-parallel attention over the ring.

    Args:
      q, k, v: this device's (B, H, S_local, D) sequence shard.
      key_mask: optional (B, S_local) boolean padding mask for THIS
        device's keys (True = masked); rotates with k/v.
      causal: causal attention over GLOBAL positions (contiguous
        sharding: device i owns tokens [i*S_local, (i+1)*S_local)).
      scale: softmax temperature.
      axis_name: the context-parallel mesh axis.
      dropout_rate: attention-probability dropout, fused into the
        per-block flash kernels. Correctness across the lse merge: each
        block's kernel applies its keep-mask only to the ``p @ v``
        accumulation while (m, l, lse) stay pre-dropout, so the merged
        ``sum_i exp(lse_i - lse_total) * out_i`` equals composed
        dropout(softmax(s_global)) @ v exactly (the flash linearity
        argument extends across blocks — nothing is double-counted).
      dropout_seed: int32 scalar; per-block masks derive from it hashed
        with the GLOBAL (q-block=this rank, kv-block=source rank) pair
        id, so every tile of the global attention matrix gets an
        independent stream and the reverse ring pass replays the same
        masks. May be shared across ranks (the tile hash decorrelates).

    Returns:
      (B, H, S_local, D) attention outputs for this device's queries,
      in q's dtype.
    """
    from apex_tpu.utils.collectives import mark_varying

    cp = jax.lax.psum(1, axis_name)
    my_rank = jax.lax.axis_index(axis_name)
    B, H, S_local, D = q.shape

    # everything the ring touches is device-varying over the context axis
    # (plus whatever axes q/k/v already vary over)
    vma = frozenset({axis_name})
    for ref in (q, k, v):
        vma |= frozenset(getattr(jax.typeof(ref), "vma", None) or ())
    mark = tuple(vma)

    if key_mask is None:
        key_mask = jnp.zeros((B, S_local), bool)
    # the mask rotates through ppermute like k/v: its carry slot must be
    # device-varying even when the caller passed an invariant (or default
    # all-False) mask
    key_mask = mark_varying(key_mask, mark)

    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError(
            "ring_attention with dropout_rate > 0 requires dropout_seed")

    def step_body(q, kv_rank, k_blk, v_blk, mask_blk):
        seed = (None if dropout_rate == 0.0
                else _block_seed(dropout_seed, my_rank, kv_rank, cp))
        if not causal:
            return _block_attend(q, k_blk, v_blk, mask_blk, False, scale,
                                 dropout_rate, seed)

        def full(_):
            return _block_attend(q, k_blk, v_blk, mask_blk, False, scale,
                                 dropout_rate, seed)

        def diag(_):
            return _block_attend(q, k_blk, v_blk, mask_blk, True, scale,
                                 dropout_rate, seed)

        def skip(_):
            return (mark_varying(
                jnp.zeros((B, H, S_local, D), jnp.float32), mark),
                mark_varying(
                    jnp.full((B, H, 1, S_local), -jnp.inf, jnp.float32),
                    mark))

        # kv_rank < my_rank: every key precedes every query -> full;
        # equal: in-block causal; greater: all masked -> skip
        case = jnp.clip(jnp.sign(kv_rank - my_rank) + 1, 0, 2)
        return jax.lax.switch(case, [full, diag, skip], None)

    step_body = jax.checkpoint(step_body, static_argnums=())

    def tick(carry, i):
        acc, lse_acc, k_blk, v_blk, mask_blk = carry
        kv_rank = (my_rank - i) % cp  # block i arrived from rank my-i
        out_i, lse_i = step_body(q, kv_rank, k_blk, v_blk, mask_blk)

        # log-sum-exp merge of the block contribution
        new_lse = jnp.logaddexp(lse_acc, lse_i)
        # fully-masked rows: keep weights finite (0 contribution)
        w_old = jnp.where(jnp.isfinite(new_lse),
                          jnp.exp(lse_acc - new_lse), 0.0)
        w_new = jnp.where(jnp.isfinite(new_lse),
                          jnp.exp(lse_i - new_lse), 0.0)
        acc = acc * w_old[:, :, 0, :, None] + out_i * w_new[:, :, 0, :, None]

        # rotate k/v/mask to the next device for the following step
        n = jax.lax.psum(1, axis_name)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
        return (acc, new_lse, k_blk, v_blk, mask_blk), None

    # the running accumulators become device-varying from step 1 on
    # (they mix in ppermuted blocks); mark the init to keep the scan
    # carry type stable under shard_map's vma checking. k/v must be
    # marked too: a caller may pass context-INVARIANT tensors (cp=1
    # mesh, or replicated q/k/v) and the body's ppermute makes the
    # carry slots varying regardless.
    init = (
        mark_varying(jnp.zeros((B, H, S_local, D), jnp.float32), mark),
        mark_varying(jnp.full((B, H, 1, S_local), -jnp.inf, jnp.float32),
                     mark),
        mark_varying(k, mark), mark_varying(v, mark), key_mask,
    )
    (acc, lse, _, _, _), _ = jax.lax.scan(tick, init, jnp.arange(cp))
    return acc.astype(q.dtype)


def ring_attention_reference(q_full, k_full, v_full, key_mask=None,
                             causal=False, scale=1.0):
    """Unsharded reference (full attention) for parity tests."""
    return mha_reference(q_full, k_full, v_full, key_mask, causal, scale)
