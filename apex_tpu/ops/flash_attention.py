"""Pallas TPU flash attention: tiled online-softmax fwd + recompute bwd.

Rebuild of the reference's fused multi-head attention tier
(``apex/contrib/csrc/fmha/`` — the MLPerf-BERT seqlen<=512 kernels — and
``apex/contrib/csrc/multihead_attn/``, SURVEY.md §2.2): attention without
ever materializing the (B, H, Sq, Sk) score tensor in HBM.

TPU design notes:
- Forward: grid ``(B, H, nq, nk)`` with the key-block dimension innermost.
  Each (b, h, iq) row-block keeps fp32 running statistics (row max ``m``,
  normalizer ``l``) and an fp32 ``(bq, D)`` accumulator in VMEM scratch,
  which persists across the sequentially-executed ``ik`` steps — the
  online-softmax recurrence. Score tiles live only in VMEM; HBM traffic is
  O(S*D) instead of O(S^2).
- The padding mask is a per-key boolean (True = masked), folded in with
  the same finite ``-30000`` fill the reference kernels use (finite so
  fully-masked rows degrade to a uniform distribution instead of NaN,
  matching ``scaled_masked_softmax`` semantics).
- Forward also emits the per-row logsumexp; backward recomputes score
  tiles from (q, k, lse) instead of saving probabilities — the flash
  rematerialization. Two kernels: dq (grid over q blocks, accumulating
  over k blocks) and dk/dv (grid over k blocks, accumulating over q
  blocks); ``delta = rowsum(dout * out)`` is a cheap O(S*D) jnp reduction.
- All matmuls carry ``preferred_element_type=fp32`` so bf16 tiles hit the
  MXU with fp32 accumulation.
- Head dim and sequence lengths are padded to the 128-lane tile in the
  wrapper; padded keys are masked, padded query rows are sliced away (and
  receive zero cotangents in backward).

On non-TPU backends the kernels run under ``interpret=True`` (same code
path, CPU-sim testable); a pure-jnp reference is used under shard_map vma
on CPU (see ops/_common.py) and for parity tests.

Dropout inside the probability matrix is NOT fused (the composed-softmax
path covers training-time attention dropout); callers gate on
``attention_dropout == 0`` — the inference/MLPerf-eval configuration the
reference fmha kernels target as well.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._common import (
    LANE,
    interpret_mode as _interpret,
    match_vma,
    out_struct,
    round_up as _round_up,
    use_jnp_fallback,
)

FILL = -30000.0  # finite masked fill, matches ops/softmax.py



def _dot(a, b, dims, prec):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=prec)


def _prec(dtype):
    """fp32 inputs get true-fp32 MXU passes; low-precision inputs use the
    native single-pass MXU path with fp32 accumulation."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                acc_s, m_s, l_s, *, scale, causal, bq, bk):
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, -1e30)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    q = q_ref[0, 0]                                # (bq, D)
    k = k_ref[0, 0]                                # (bk, D)
    prec = _prec(q.dtype)
    s = _dot(q, k, ((1,), (1,)), prec) * scale     # (bq, bk)

    # mask codes: 0 = live, 1 = user-masked (finite FILL — a fully-masked
    # row degrades to uniform over the TRUE keys), 2 = wrapper padding
    # (excluded from the distribution entirely, else an unaligned Sk
    # inflates the denominator by Skp/Sk)
    mrow = mask_ref[0, 0][None, :]                 # (1, bk) -> broadcast
    s = jnp.where(mrow != 0, FILL, s)
    if causal:
        iq = pl.program_id(2)
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
        s = jnp.where(row >= col, s, FILL)

    m_prev = m_s[:, :1]                            # (bq, 1)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)                         # (bq, bk)
    p = jnp.where(mrow >= 2, 0.0, p)
    alpha = jnp.exp(m_prev - m_new)                # (bq, 1)
    l_new = alpha * l_s[:, :1] + jnp.sum(p, axis=1, keepdims=True)

    v = v_ref[0, 0]                                # (bk, D)
    pv = _dot(p.astype(v.dtype), v, ((1,), (0,)), prec)
    acc_s[:] = acc_s[:] * alpha + pv
    m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
    l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_s[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, 0] = (m_s[:, :1] + jnp.log(safe_l))[:, 0]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_s, *, scale, causal, bq, bk):
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    prec = _prec(q.dtype)
    s = _dot(q, k, ((1,), (1,)), prec) * scale
    mrow = mask_ref[0, 0][None, :]
    s = jnp.where(mrow != 0, FILL, s)
    if causal:
        iq = pl.program_id(2)
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
        s = jnp.where(row >= col, s, FILL)

    lse = lse_ref[0, 0, 0][:, None]                # (bq, 1)
    p = jnp.exp(s - lse)                           # (bq, bk)
    p = jnp.where(mrow >= 2, 0.0, p)               # padded keys: p exactly 0
    do = do_ref[0, 0]                              # (bq, D)
    v = v_ref[0, 0]                                # (bk, D)
    dp = _dot(do, v, ((1,), (1,)), prec)
    delta = delta_ref[0, 0, 0][:, None]            # (bq, 1)
    ds = p * (dp - delta) * scale                  # (bq, bk)
    dq_s[:] = dq_s[:] + _dot(ds.astype(k.dtype), k, ((1,), (0,)), prec)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_s[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_s, dv_s, *, scale, causal, bq, bk):
    iq = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    q = q_ref[0, 0]                                # (bq, D)
    k = k_ref[0, 0]                                # (bk, D)
    prec = _prec(q.dtype)
    s = _dot(q, k, ((1,), (1,)), prec) * scale
    mrow = mask_ref[0, 0][None, :]
    s = jnp.where(mrow != 0, FILL, s)
    if causal:
        ik = pl.program_id(2)
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
        s = jnp.where(row >= col, s, FILL)

    lse = lse_ref[0, 0, 0][:, None]
    p = jnp.exp(s - lse)                           # (bq, bk)
    p = jnp.where(mrow >= 2, 0.0, p)               # padded keys: p exactly 0
    do = do_ref[0, 0]                              # (bq, D)
    # dv += p^T @ do
    dv_s[:] = dv_s[:] + _dot(p.astype(do.dtype), do, ((0,), (0,)), prec)
    v = v_ref[0, 0]
    dp = _dot(do, v, ((1,), (1,)), prec)
    delta = delta_ref[0, 0, 0][:, None]
    ds = p * (dp - delta) * scale                  # (bq, bk)
    # dk += ds^T @ q
    dk_s[:] = dk_s[:] + _dot(ds.astype(q.dtype), q, ((0,), (0,)), prec)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers (operate on padded (B, H, S, D) tensors)
# ---------------------------------------------------------------------------

def _spec4(bs, D, index_map):
    """BlockSpec for a (B, H, S, D) tensor blocked along S."""
    return pl.BlockSpec((1, 1, bs, D), index_map)


def _flash_fwd_call(q, k, v, mask, *, scale, causal, bq, bk):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    grid = (B, H, Sq // bq, Sk // bk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _spec4(bq, D, lambda b, h, iq, ik: (b, h, iq, 0)),
            _spec4(bk, D, lambda b, h, iq, ik: (b, h, ik, 0)),
            _spec4(bk, D, lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, h, iq, ik: (b, 0, ik)),
        ],
        out_specs=(
            _spec4(bq, D, lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, iq, ik: (b, h, 0, iq)),
        ),
        out_shape=(
            out_struct((B, H, Sq, D), q.dtype, q, k, v),
            out_struct((B, H, 1, Sq), jnp.float32, q, k, v),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, LANE), jnp.float32),
            pltpu.VMEM((bq, LANE), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, mask)
    return out, lse


def _flash_bwd_call(q, k, v, mask, do, lse, delta, *, scale, causal, bq, bk):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(B, H, Sq // bq, Sk // bk),
        in_specs=[
            _spec4(bq, D, lambda b, h, iq, ik: (b, h, iq, 0)),
            _spec4(bk, D, lambda b, h, iq, ik: (b, h, ik, 0)),
            _spec4(bk, D, lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, h, iq, ik: (b, 0, ik)),
            _spec4(bq, D, lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, iq, ik: (b, h, 0, iq)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, iq, ik: (b, h, 0, iq)),
        ],
        out_specs=_spec4(bq, D, lambda b, h, iq, ik: (b, h, iq, 0)),
        out_shape=out_struct((B, H, Sq, D), q.dtype, q, k, v, do),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, mask, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(B, H, Sk // bk, Sq // bq),
        in_specs=[
            _spec4(bq, D, lambda b, h, ik, iq: (b, h, iq, 0)),
            _spec4(bk, D, lambda b, h, ik, iq: (b, h, ik, 0)),
            _spec4(bk, D, lambda b, h, ik, iq: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, h, ik, iq: (b, 0, ik)),
            _spec4(bq, D, lambda b, h, ik, iq: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, ik, iq: (b, h, 0, iq)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, ik, iq: (b, h, 0, iq)),
        ],
        out_specs=(
            _spec4(bk, D, lambda b, h, ik, iq: (b, h, ik, 0)),
            _spec4(bk, D, lambda b, h, ik, iq: (b, h, ik, 0)),
        ),
        out_shape=(
            out_struct((B, H, Sk, D), k.dtype, q, k, v, do),
            out_struct((B, H, Sk, D), v.dtype, q, k, v, do),
        ),
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, mask, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API (custom_vjp over padded wrappers)
# ---------------------------------------------------------------------------

def _pad_inputs(q, k, v, key_mask, bq, bk):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    Dp = _round_up(D, LANE)
    Sqp = _round_up(Sq, bq)
    Skp = _round_up(Sk, bk)
    if key_mask is None:
        mask = jnp.zeros((B, 1, Sk), jnp.int32)
    else:
        mask = key_mask.astype(jnp.int32)[:, None, :]
    if (Dp, Sqp, Skp) != (D, Sq, Sk):
        q = jnp.pad(q, ((0, 0), (0, 0), (0, Sqp - Sq), (0, Dp - D)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, Skp - Sk), (0, Dp - D)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, Skp - Sk), (0, Dp - D)))
        # padding code 2: excluded from the softmax denominator in-kernel
        # (code 1 = user-masked keys still count toward a fully-masked
        # row's uniform fallback, matching the composed reference)
        mask = jnp.pad(mask, ((0, 0), (0, 0), (0, Skp - Sk)), constant_values=2)
    return q, k, v, mask


# Relative per-FLOP cost of a block size (v5e measurement: 512-blocks
# beat 128-blocks by 2.1x; intermediate sizes interpolated). Used to
# trade padding waste against block efficiency.
_BLOCK_COST = {512: 1.0, 384: 1.08, 256: 1.25, 128: 2.1}


def _block_dim(S):
    """Pick the block size minimizing (padded_len/S) * per-FLOP cost.

    Neither extreme is right alone: always padding to 512-blocks wastes
    2.5x FLOPs at S=640, while insisting the block divide round_up(S,128)
    forces 128-blocks at S=896 (no larger divisor) — ~60% slower than
    padding 896→1024 with 512-blocks. The cost model arbitrates."""
    best, best_cost = LANE, None
    for b, c in _BLOCK_COST.items():
        cost = (_round_up(S, b) / max(S, 1)) * c
        if best_cost is None or cost < best_cost:
            best, best_cost = b, cost
    return best


def _block_sizes(Sq, Sk):
    """Measured on v5e: large blocks win — at S=512, (512, 512) runs the
    whole attention row per grid step (the shape the reference fmha
    specializes for) and beats (128, 128) by 2.1x; VMEM stays bounded
    (score tile 512*512*4B = 1 MB). Longer sequences tile with the
    online-softmax recurrence across key blocks."""
    return (_block_dim(Sq), _block_dim(Sk))


def _scores(q, k, key_mask, causal, scale):
    """(B, H, Sq, Sk) fp32 masked scores — shared by every composed path."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :], FILL, s)
    if causal:
        Sq, Sk = s.shape[-2:]
        row = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where((row >= col)[None, None], s, FILL)
    return s


def mha_reference(q, k, v, key_mask=None, causal=False, scale=1.0):
    """Composed-ops reference: materializes (B, H, Sq, Sk) scores."""
    p = jax.nn.softmax(_scores(q, k, key_mask, causal, scale), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash_attention(q, k, v, key_mask=None, causal: bool = False,
                    scale: float = 1.0):
    """Multi-head attention without materializing the score matrix.

    Args:
      q, k, v: ``(B, H, S, D)`` (any floating dtype; fp32 accumulation).
      key_mask: optional ``(B, Sk)`` boolean, True = key position masked
        (the reference's padding-mask convention).
      causal: apply the upper-triangular causal mask in-kernel.
      scale: softmax temperature (typically ``1/sqrt(D)``).

    Replaces the reference's ``fmha``/``fast_multihead_attn`` fused
    attention. Differentiable via the flash recompute backward.
    """
    out, _ = _flash_fwd(q, k, v, key_mask, causal, scale)
    return out


def _flash_fwd(q, k, v, key_mask, causal, scale):
    if use_jnp_fallback(q, k, v, key_mask):
        out = mha_reference(q, k, v, key_mask, causal, scale)
        return out, None
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq, bk = _block_sizes(Sq, Sk)
    qp, kp, vp, mask = _pad_inputs(q, k, v, key_mask, bq, bk)
    out, lse = _flash_fwd_call(qp, kp, vp, mask, scale=scale, causal=causal,
                               bq=bq, bk=bk)
    return out[:, :, :Sq, :D], lse


def _flash_vjp_fwd(q, k, v, key_mask, causal, scale):
    out, lse = _flash_fwd(q, k, v, key_mask, causal, scale)
    return out, (q, k, v, key_mask, out, lse)


def _kernel_bwd(causal, scale, q, k, v, key_mask, out, lse_padded, g,
                g_lse=None):
    """Shared recompute backward for both vjps. ``lse_padded`` is the
    kernel's padded-width lse; ``g_lse`` (optional, (B, H, 1, Sq)) is the
    lse cotangent, folded into delta (d lse/d s = p, so
    ds = p * (dP - (rowsum(dO*O) - dlse)))."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq, bk = _block_sizes(Sq, Sk)
    qp, kp, vp, mask = _pad_inputs(q, k, v, key_mask, bq, bk)
    Sqp = qp.shape[2]
    Dp = qp.shape[3]
    gp = g
    outp = out
    if (Sqp, Dp) != (Sq, D):
        gp = jnp.pad(g, ((0, 0), (0, 0), (0, Sqp - Sq), (0, Dp - D)))
        outp = jnp.pad(out, ((0, 0), (0, 0), (0, Sqp - Sq), (0, Dp - D)))
    # lse was computed on padded shapes in fwd, so it already covers any
    # padded query rows. delta is carried (B, H, 1, Sq) to match lse's
    # Mosaic-friendly layout (size-1 block dims must equal array dims).
    delta = jnp.sum(gp.astype(jnp.float32) * outp.astype(jnp.float32),
                    axis=-1)[:, :, None, :]
    if g_lse is not None:
        glp = g_lse
        if Sqp != Sq:
            glp = jnp.pad(g_lse, ((0, 0), (0, 0), (0, 0), (0, Sqp - Sq)))
        delta = delta - glp.astype(jnp.float32)
    dq, dk, dv = _flash_bwd_call(qp, kp, vp, mask, gp, lse_padded, delta,
                                 scale=scale, causal=causal, bq=bq, bk=bk)
    return (match_vma(dq[:, :, :Sq, :D].astype(q.dtype), q),
            match_vma(dk[:, :, :Sk, :D].astype(k.dtype), k),
            match_vma(dv[:, :, :Sk, :D].astype(v.dtype), v),
            None)


def _flash_vjp_bwd(causal, scale, res, g):
    q, k, v, key_mask, out, lse = res
    if lse is None:  # jnp fallback path: differentiate the reference
        def f(q, k, v):
            return mha_reference(q, k, v, key_mask, causal, scale)

        _, vjp = jax.vjp(f, q, k, v)
        dq, dk, dv = vjp(g)
        return (match_vma(dq, q), match_vma(dk, k), match_vma(dv, v), None)
    return _kernel_bwd(causal, scale, q, k, v, key_mask, out, lse, g)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# (out, lse) variant for blockwise consumers (ring attention)
# ---------------------------------------------------------------------------

def _with_lse_reference(q, k, v, key_mask, causal, scale):
    """Composed (out, lse): the differentiable fallback path."""
    s = _scores(q, k, key_mask, causal, scale)
    lse = jax.nn.logsumexp(s, axis=-1)[:, :, None, :]
    p = jnp.exp(s - lse.transpose(0, 1, 3, 2))
    out = jnp.einsum("bhqk,bhkd->bhqd", p,
                     v.astype(jnp.float32)).astype(q.dtype)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash_attention_with_lse(q, k, v, key_mask=None, causal: bool = False,
                             scale: float = 1.0):
    """Flash attention returning ``(out, lse)`` with lse trimmed to the
    true Sq — the building block for blockwise/ring consumers that merge
    per-block results via log-sum-exp. Differentiable INCLUDING the lse
    output: its cotangent folds into the recompute backward's delta
    (``delta = rowsum(dO*O) - dlse``; d lse/d s = p)."""
    if use_jnp_fallback(q, k, v, key_mask):
        return _with_lse_reference(q, k, v, key_mask, causal, scale)
    out, lse = _flash_fwd(q, k, v, key_mask, causal, scale)
    return out, lse[..., :q.shape[2]]


def _fwl_fwd(q, k, v, key_mask, causal, scale):
    if use_jnp_fallback(q, k, v, key_mask):
        out, lse_t = _with_lse_reference(q, k, v, key_mask, causal, scale)
        return (out, lse_t), (q, k, v, key_mask, out, None)
    out, lse = _flash_fwd(q, k, v, key_mask, causal, scale)
    return (out, lse[..., :q.shape[2]]), (q, k, v, key_mask, out, lse)


def _fwl_bwd(causal, scale, res, cotangents):
    q, k, v, key_mask, out, lse_padded = res
    g, g_lse = cotangents
    if lse_padded is None:  # fallback path: autodiff the composed form
        def f(q, k, v):
            return _with_lse_reference(q, k, v, key_mask, causal, scale)

        _, vjp = jax.vjp(f, q, k, v)
        dq, dk, dv = vjp((g, g_lse))
        return (match_vma(dq, q), match_vma(dk, k), match_vma(dv, v), None)
    return _kernel_bwd(causal, scale, q, k, v, key_mask, out, lse_padded,
                       g, g_lse)


flash_attention_with_lse.defvjp(_fwl_fwd, _fwl_bwd)
