"""Pallas TPU flash attention: tiled online-softmax fwd + recompute bwd.

Rebuild of the reference's fused multi-head attention tier
(``apex/contrib/csrc/fmha/`` — the MLPerf-BERT seqlen<=512 kernels — and
``apex/contrib/csrc/multihead_attn/``, SURVEY.md §2.2): attention without
ever materializing the (B, H, Sq, Sk) score tensor in HBM.

TPU design notes:
- Forward: grid ``(B, H, nq, nk)`` with the key-block dimension innermost.
  Each (b, h, iq) row-block keeps fp32 running statistics (row max ``m``,
  normalizer ``l``) and an fp32 ``(bq, D)`` accumulator in VMEM scratch,
  which persists across the sequentially-executed ``ik`` steps — the
  online-softmax recurrence. Score tiles live only in VMEM; HBM traffic is
  O(S*D) instead of O(S^2).
- The padding mask is a per-key boolean (True = masked), folded in with
  the same finite ``-30000`` fill the reference kernels use (finite so
  fully-masked rows degrade to a uniform distribution instead of NaN,
  matching ``scaled_masked_softmax`` semantics).
- Forward also emits the per-row logsumexp; backward recomputes score
  tiles from (q, k, lse) instead of saving probabilities — the flash
  rematerialization. Two kernels: dq (grid over q blocks, accumulating
  over k blocks) and dk/dv (grid over k blocks, accumulating over q
  blocks); ``delta = rowsum(dout * out)`` is a cheap O(S*D) jnp reduction.
- All matmuls carry ``preferred_element_type=fp32`` so bf16 tiles hit the
  MXU with fp32 accumulation.
- Head dim and sequence lengths are padded to the 128-lane tile in the
  wrapper; padded keys are masked, padded query rows are sliced away (and
  receive zero cotangents in backward).

On non-TPU backends the kernels run under ``interpret=True`` (same code
path, CPU-sim testable); a pure-jnp reference is used under shard_map vma
on CPU (see ops/_common.py) and for parity tests.

Attention dropout is FUSED (the reference fmha kernels generate their
Philox dropout in-kernel; this is the MLPerf-BERT *training* config):
- On real TPU the keep-mask is generated in-kernel from the hardware PRNG
  (``pltpu.prng_seed`` keyed by ``(seed, b, h, iq, ik)`` +
  ``prng_random_bits``), so no (B, H, Sq, Sk) mask ever touches HBM. The
  backward pass re-seeds identically per tile and replays the exact mask
  during recompute.
- The dropout multiplies the *unnormalized* probability tile only where it
  feeds the ``p @ v`` accumulation; the online-softmax statistics (m, l,
  lse) stay pre-dropout, so the math equals composed
  ``dropout(softmax(s)) @ v`` by linearity of the final ``acc / l``.
- ``delta = rowsum(dO * O)`` already equals ``rowsum(P_dropped * dP)``
  when O carries dropout, so the backward needs no extra correction — the
  keep-mask is simply replayed onto ``dp`` (and onto ``p`` for dv).
- Interpret mode (CPU sim) has no TPU PRNG; there the same kernels take a
  precomputed uint32 bits tensor generated host-side from the seed — the
  identical thresholding math, deterministic across fwd/bwd.
``flash_dropout_keep_mask`` reproduces the kernel's exact mask on either
backend so tests can compose a bit-matched reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._common import (
    LANE,
    interpret_mode as _interpret,
    keep_threshold as _keep_threshold,
    match_vma,
    out_struct,
    round_up as _round_up,
    use_jnp_fallback,
)

FILL = -30000.0  # finite masked fill, matches ops/softmax.py



def _dot(a, b, dims, prec):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=prec)


def _prec(dtype):
    """fp32 inputs get true-fp32 MXU passes; low-precision inputs use the
    native single-pass MXU path with fp32 accumulation."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _tile_id(b, h, iq, ik, H, nq, nk):
    """Injective int32 id of score tile (iq, ik) of head (b, h) — the
    PRNG seed coordinate shared by fwd/dq/dkv regardless of their own
    grid iteration order (Mosaic's prng_seed takes at most 2 values, so
    the coordinates are flattened into one)."""
    return ((b * H + h) * nq + iq) * nk + ik


def _keep_mask(drop_ref, tile_id, bq, bk, dropout_rate, native_prng,
               interp_idx=(0, 0)):
    """(bq, bk) boolean keep-mask for one score tile.

    native_prng: seed the TPU hardware PRNG with (user seed, tile id) —
    any kernel regenerates the identical mask for the same tile.
    Otherwise drop_ref is a precomputed uint32 block (interpret mode)
    and ``interp_idx`` selects the (bq, bk) slice (head-pair kernels
    carry two heads per block)."""
    if native_prng:
        pltpu.prng_seed(drop_ref[0], tile_id)
        bits = pltpu.bitcast(pltpu.prng_random_bits((bq, bk)), jnp.uint32)
    else:
        bits = drop_ref[interp_idx]
    return bits < _keep_threshold(dropout_rate)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, *rest, scale, causal, bq, bk,
                dropout_rate=0.0, native_prng=True):
    if dropout_rate > 0.0:
        drop_ref, o_ref, lse_ref, acc_s, m_s, l_s = rest
    else:
        drop_ref, (o_ref, lse_ref, acc_s, m_s, l_s) = None, rest
    b, hh = pl.program_id(0), pl.program_id(1)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, -1e30)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    q = q_ref[0, 0]                                # (bq, D)
    k = k_ref[0, 0]                                # (bk, D)
    prec = _prec(q.dtype)
    s = _dot(q, k, ((1,), (1,)), prec) * scale     # (bq, bk)

    # mask codes: 0 = live, 1 = user-masked (finite FILL — a fully-masked
    # row degrades to uniform over the TRUE keys), 2 = wrapper padding
    # (excluded from the distribution entirely, else an unaligned Sk
    # inflates the denominator by Skp/Sk)
    mrow = mask_ref[0, 0][None, :]                 # (1, bk) -> broadcast
    s = jnp.where(mrow != 0, FILL, s)
    if causal:
        iq = pl.program_id(2)
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
        s = jnp.where(row >= col, s, FILL)

    m_prev = m_s[:, :1]                            # (bq, 1)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)                         # (bq, bk)
    p = jnp.where(mrow >= 2, 0.0, p)
    alpha = jnp.exp(m_prev - m_new)                # (bq, 1)
    l_new = alpha * l_s[:, :1] + jnp.sum(p, axis=1, keepdims=True)

    v = v_ref[0, 0]                                # (bk, D)
    # dropout multiplies only the p @ v path; m/l/lse stay pre-dropout so
    # the final acc/l equals composed dropout(softmax) @ v by linearity
    if dropout_rate > 0.0:
        tid = _tile_id(b, hh, pl.program_id(2), ik, pl.num_programs(1),
                       pl.num_programs(2), nk)
        keep = _keep_mask(drop_ref, tid, bq, bk, dropout_rate, native_prng)
        p_av = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
    else:
        p_av = p
    pv = _dot(p_av.astype(v.dtype), v, ((1,), (0,)), prec)
    acc_s[:] = acc_s[:] * alpha + pv
    m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
    l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_s[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, 0] = (m_s[:, :1] + jnp.log(safe_l))[:, 0]


def _fwd_single_kernel(q_ref, k_ref, v_ref, mask_ref, *rest, scale, causal,
                       bq, bk, dropout_rate=0.0, native_prng=True):
    """Single-tile forward (nq == nk == 1): the whole attention row fits
    one tile, so the softmax is direct — no VMEM running-statistics
    scratch, no alpha rescale of the accumulator, no @pl.when phases."""
    if dropout_rate > 0.0:
        drop_ref, o_ref, lse_ref = rest
    else:
        drop_ref, (o_ref, lse_ref) = None, rest
    b, hh = pl.program_id(0), pl.program_id(1)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    prec = _prec(q.dtype)
    s = _dot(q, k, ((1,), (1,)), prec) * scale
    mrow = mask_ref[0, 0][None, :]
    s = jnp.where(mrow != 0, FILL, s)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(row >= col, s, FILL)

    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(mrow >= 2, 0.0, p)
    l = jnp.sum(p, axis=1, keepdims=True)
    if dropout_rate > 0.0:
        tid = _tile_id(b, hh, 0, 0, pl.num_programs(1), 1, 1)
        keep = _keep_mask(drop_ref, tid, bq, bk, dropout_rate, native_prng)
        p_av = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
    else:
        p_av = p
    v = v_ref[0, 0]
    pv = _dot(p_av.astype(v.dtype), v, ((1,), (0,)), prec)
    safe_l = jnp.where(l > 0, l, 1.0)
    o_ref[0, 0] = (pv / safe_l).astype(o_ref.dtype)
    lse_ref[0, 0, 0] = (m + jnp.log(safe_l))[:, 0]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                   *rest, scale, causal, bq, bk,
                   dropout_rate=0.0, native_prng=True):
    if dropout_rate > 0.0:
        drop_ref, dq_ref, dq_s = rest
    else:
        drop_ref, (dq_ref, dq_s) = None, rest
    b, hh = pl.program_id(0), pl.program_id(1)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    prec = _prec(q.dtype)
    s = _dot(q, k, ((1,), (1,)), prec) * scale
    mrow = mask_ref[0, 0][None, :]
    s = jnp.where(mrow != 0, FILL, s)
    if causal:
        iq = pl.program_id(2)
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
        s = jnp.where(row >= col, s, FILL)

    lse = lse_ref[0, 0, 0][:, None]                # (bq, 1)
    p = jnp.exp(s - lse)                           # (bq, bk)
    p = jnp.where(mrow >= 2, 0.0, p)               # padded keys: p exactly 0
    do = do_ref[0, 0]                              # (bq, D)
    v = v_ref[0, 0]                                # (bk, D)
    dp = _dot(do, v, ((1,), (1,)), prec)
    if dropout_rate > 0.0:
        # replay the forward's exact keep-mask onto dp (dP = mask/keep *
        # dO·V); delta already carries the dropout through O
        tid = _tile_id(b, hh, pl.program_id(2), ik, pl.num_programs(1),
                       pl.num_programs(2), nk)
        keep = _keep_mask(drop_ref, tid, bq, bk, dropout_rate, native_prng)
        dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - dropout_rate))
    delta = delta_ref[0, 0, 0][:, None]            # (bq, 1)
    ds = p * (dp - delta) * scale                  # (bq, bk)
    dq_s[:] = dq_s[:] + _dot(ds.astype(k.dtype), k, ((1,), (0,)), prec)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_s[:].astype(dq_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                      delta_ref, *rest, scale, causal, bq, bk,
                      dropout_rate=0.0, native_prng=True):
    """Single-tile backward (nq == nk == 1 — the reference fmha's
    seqlen<=512 specialization): one (b, h) grid step recomputes s and p
    ONCE and emits dq, dk, AND dv — 5 matmuls instead of the 7 the
    split dq/dkv kernels pay (each recomputes s, and dp is computed
    twice), plus one kernel launch instead of two."""
    if dropout_rate > 0.0:
        drop_ref, dq_ref, dk_ref, dv_ref = rest
    else:
        drop_ref, (dq_ref, dk_ref, dv_ref) = None, rest
    b, hh = pl.program_id(0), pl.program_id(1)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    prec = _prec(q.dtype)
    s = _dot(q, k, ((1,), (1,)), prec) * scale
    mrow = mask_ref[0, 0][None, :]
    s = jnp.where(mrow != 0, FILL, s)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(row >= col, s, FILL)

    lse = lse_ref[0, 0, 0][:, None]
    p = jnp.exp(s - lse)
    p = jnp.where(mrow >= 2, 0.0, p)
    do = do_ref[0, 0]
    v = v_ref[0, 0]
    dp = _dot(do, v, ((1,), (1,)), prec)
    if dropout_rate > 0.0:
        tid = _tile_id(b, hh, 0, 0, pl.num_programs(1), 1, 1)
        keep = _keep_mask(drop_ref, tid, bq, bk, dropout_rate, native_prng)
        inv_keep = 1.0 / (1.0 - dropout_rate)
        p_av = jnp.where(keep, p, 0.0) * inv_keep
        dp = jnp.where(keep, dp, 0.0) * inv_keep
    else:
        p_av = p
    dv_ref[0, 0] = _dot(p_av.astype(do.dtype), do, ((0,), (0,)),
                        prec).astype(dv_ref.dtype)
    delta = delta_ref[0, 0, 0][:, None]
    ds = p * (dp - delta) * scale
    dq_ref[0, 0] = _dot(ds.astype(k.dtype), k, ((1,), (0,)),
                        prec).astype(dq_ref.dtype)
    dk_ref[0, 0] = _dot(ds.astype(q.dtype), q, ((0,), (0,)),
                        prec).astype(dk_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                    *rest, scale, causal, bq, bk,
                    dropout_rate=0.0, native_prng=True):
    if dropout_rate > 0.0:
        drop_ref, dk_ref, dv_ref, dk_s, dv_s = rest
    else:
        drop_ref, (dk_ref, dv_ref, dk_s, dv_s) = None, rest
    b, hh = pl.program_id(0), pl.program_id(1)
    iq = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    q = q_ref[0, 0]                                # (bq, D)
    k = k_ref[0, 0]                                # (bk, D)
    prec = _prec(q.dtype)
    s = _dot(q, k, ((1,), (1,)), prec) * scale
    mrow = mask_ref[0, 0][None, :]
    s = jnp.where(mrow != 0, FILL, s)
    if causal:
        ik = pl.program_id(2)
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
        s = jnp.where(row >= col, s, FILL)

    lse = lse_ref[0, 0, 0][:, None]
    p = jnp.exp(s - lse)                           # (bq, bk)
    p = jnp.where(mrow >= 2, 0.0, p)               # padded keys: p exactly 0
    do = do_ref[0, 0]                              # (bq, D)
    v = v_ref[0, 0]
    dp = _dot(do, v, ((1,), (1,)), prec)
    if dropout_rate > 0.0:
        # seed with (iq, ik) — the same tile coordinates the forward
        # used — even though this kernel's grid iterates (ik, iq)
        tid = _tile_id(b, hh, iq, pl.program_id(2), pl.num_programs(1),
                       nq, pl.num_programs(2))
        keep = _keep_mask(drop_ref, tid, bq, bk, dropout_rate, native_prng)
        inv_keep = 1.0 / (1.0 - dropout_rate)
        p_av = jnp.where(keep, p, 0.0) * inv_keep
        dp = jnp.where(keep, dp, 0.0) * inv_keep
    else:
        p_av = p
    # dv += dropout(p)^T @ do
    dv_s[:] = dv_s[:] + _dot(p_av.astype(do.dtype), do, ((0,), (0,)), prec)
    delta = delta_ref[0, 0, 0][:, None]
    ds = p * (dp - delta) * scale                  # (bq, bk)
    # dk += ds^T @ q
    dk_s[:] = dk_s[:] + _dot(ds.astype(q.dtype), q, ((0,), (0,)), prec)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers (operate on padded (B, H, S, D) tensors)
# ---------------------------------------------------------------------------

def _spec4(bs, D, index_map):
    """BlockSpec for a (B, H, S, D) tensor blocked along S."""
    return pl.BlockSpec((1, 1, bs, D), index_map)


def _drop_arg(drop_in, bq, bk, index_map):
    """(inputs, in_specs) extension for the dropout source: the (1,) SMEM
    seed for the native-PRNG path, or the blocked uint32 bits tensor for
    interpret mode."""
    if drop_in is None:
        return [], []
    if drop_in.ndim == 1:  # native path: scalar seed
        return [drop_in], [pl.BlockSpec(memory_space=pltpu.SMEM)]
    return [drop_in], [pl.BlockSpec((1, 1, bq, bk), index_map)]


def _flash_fwd_call(q, k, v, mask, *, scale, causal, bq, bk,
                    dropout_rate=0.0, drop_in=None):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    grid = (B, H, Sq // bq, Sk // bk)
    native = drop_in is not None and drop_in.ndim == 1

    if Sq == bq and Sk == bk:
        extra, extra_specs = _drop_arg(drop_in, bq, bk,
                                       lambda b, h: (b, h, 0, 0))
        return pl.pallas_call(
            functools.partial(_fwd_single_kernel, scale=scale,
                              causal=causal, bq=bq, bk=bk,
                              dropout_rate=dropout_rate,
                              native_prng=native),
            grid=(B, H),
            in_specs=[
                _spec4(bq, D, lambda b, h: (b, h, 0, 0)),
                _spec4(bk, D, lambda b, h: (b, h, 0, 0)),
                _spec4(bk, D, lambda b, h: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, bk), lambda b, h: (b, 0, 0)),
            ] + extra_specs,
            out_specs=(
                _spec4(bq, D, lambda b, h: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 1, bq), lambda b, h: (b, h, 0, 0)),
            ),
            out_shape=(
                out_struct((B, H, Sq, D), q.dtype, q, k, v),
                out_struct((B, H, 1, Sq), jnp.float32, q, k, v),
            ),
            interpret=_interpret(),
        )(q, k, v, mask, *extra)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
        dropout_rate=dropout_rate, native_prng=native)
    extra, extra_specs = _drop_arg(drop_in, bq, bk,
                                   lambda b, h, iq, ik: (b, h, iq, ik))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _spec4(bq, D, lambda b, h, iq, ik: (b, h, iq, 0)),
            _spec4(bk, D, lambda b, h, iq, ik: (b, h, ik, 0)),
            _spec4(bk, D, lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, h, iq, ik: (b, 0, ik)),
        ] + extra_specs,
        out_specs=(
            _spec4(bq, D, lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, iq, ik: (b, h, 0, iq)),
        ),
        out_shape=(
            out_struct((B, H, Sq, D), q.dtype, q, k, v),
            out_struct((B, H, 1, Sq), jnp.float32, q, k, v),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, LANE), jnp.float32),
            pltpu.VMEM((bq, LANE), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, mask, *extra)
    return out, lse


def _flash_bwd_call(q, k, v, mask, do, lse, delta, *, scale, causal, bq, bk,
                    dropout_rate=0.0, drop_in=None):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    native = drop_in is not None and drop_in.ndim == 1

    if Sq == bq and Sk == bk:
        # whole attention row in one tile: fused dq+dk+dv kernel
        extra, extra_specs = _drop_arg(drop_in, bq, bk,
                                       lambda b, h: (b, h, 0, 0))
        return pl.pallas_call(
            functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                              bq=bq, bk=bk, dropout_rate=dropout_rate,
                              native_prng=native),
            grid=(B, H),
            in_specs=[
                _spec4(bq, D, lambda b, h: (b, h, 0, 0)),
                _spec4(bk, D, lambda b, h: (b, h, 0, 0)),
                _spec4(bk, D, lambda b, h: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, bk), lambda b, h: (b, 0, 0)),
                _spec4(bq, D, lambda b, h: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 1, bq), lambda b, h: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 1, bq), lambda b, h: (b, h, 0, 0)),
            ] + extra_specs,
            out_specs=(
                _spec4(bq, D, lambda b, h: (b, h, 0, 0)),
                _spec4(bk, D, lambda b, h: (b, h, 0, 0)),
                _spec4(bk, D, lambda b, h: (b, h, 0, 0)),
            ),
            out_shape=(
                out_struct((B, H, Sq, D), q.dtype, q, k, v, do),
                out_struct((B, H, Sk, D), k.dtype, q, k, v, do),
                out_struct((B, H, Sk, D), v.dtype, q, k, v, do),
            ),
            interpret=_interpret(),
        )(q, k, v, mask, do, lse, delta, *extra)

    extra, extra_specs = _drop_arg(drop_in, bq, bk,
                                   lambda b, h, iq, ik: (b, h, iq, ik))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, dropout_rate=dropout_rate,
                          native_prng=native),
        grid=(B, H, Sq // bq, Sk // bk),
        in_specs=[
            _spec4(bq, D, lambda b, h, iq, ik: (b, h, iq, 0)),
            _spec4(bk, D, lambda b, h, iq, ik: (b, h, ik, 0)),
            _spec4(bk, D, lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, h, iq, ik: (b, 0, ik)),
            _spec4(bq, D, lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, iq, ik: (b, h, 0, iq)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, iq, ik: (b, h, 0, iq)),
        ] + extra_specs,
        out_specs=_spec4(bq, D, lambda b, h, iq, ik: (b, h, iq, 0)),
        out_shape=out_struct((B, H, Sq, D), q.dtype, q, k, v, do),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, mask, do, lse, delta, *extra)

    extra, extra_specs = _drop_arg(drop_in, bq, bk,
                                   lambda b, h, ik, iq: (b, h, iq, ik))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, dropout_rate=dropout_rate,
                          native_prng=native),
        grid=(B, H, Sk // bk, Sq // bq),
        in_specs=[
            _spec4(bq, D, lambda b, h, ik, iq: (b, h, iq, 0)),
            _spec4(bk, D, lambda b, h, ik, iq: (b, h, ik, 0)),
            _spec4(bk, D, lambda b, h, ik, iq: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, h, ik, iq: (b, 0, ik)),
            _spec4(bq, D, lambda b, h, ik, iq: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, ik, iq: (b, h, 0, iq)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, ik, iq: (b, h, 0, iq)),
        ] + extra_specs,
        out_specs=(
            _spec4(bk, D, lambda b, h, ik, iq: (b, h, ik, 0)),
            _spec4(bk, D, lambda b, h, ik, iq: (b, h, ik, 0)),
        ),
        out_shape=(
            out_struct((B, H, Sk, D), k.dtype, q, k, v, do),
            out_struct((B, H, Sk, D), v.dtype, q, k, v, do),
        ),
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, mask, do, lse, delta, *extra)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API (custom_vjp over padded wrappers)
# ---------------------------------------------------------------------------

def _pad_inputs(q, k, v, key_mask, bq, bk):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    # Pad D to a 64 multiple, NOT the 128 lane width: Mosaic handles a
    # 64-lane minor block (verified identical outputs on-chip), while
    # padding 64->128 physically doubles q/k/v/o (+ their gradients')
    # HBM traffic AND pays a pad-copy of every operand per call — the
    # D=64-per-head flagship shape was paying both on every layer.
    Dp = _round_up(D, 64)
    Sqp = _round_up(Sq, bq)
    Skp = _round_up(Sk, bk)
    if key_mask is None:
        mask = jnp.zeros((B, 1, Sk), jnp.int32)
    else:
        mask = key_mask.astype(jnp.int32)[:, None, :]
    if (Dp, Sqp, Skp) != (D, Sq, Sk):
        q = jnp.pad(q, ((0, 0), (0, 0), (0, Sqp - Sq), (0, Dp - D)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, Skp - Sk), (0, Dp - D)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, Skp - Sk), (0, Dp - D)))
        # padding code 2: excluded from the softmax denominator in-kernel
        # (code 1 = user-masked keys still count toward a fully-masked
        # row's uniform fallback, matching the composed reference)
        mask = jnp.pad(mask, ((0, 0), (0, 0), (0, Skp - Sk)), constant_values=2)
    return q, k, v, mask


# Relative per-FLOP cost of a block size (v5e measurement: 512-blocks
# beat 128-blocks by 2.1x; intermediate sizes interpolated). Used to
# trade padding waste against block efficiency.
_BLOCK_COST = {512: 1.0, 384: 1.08, 256: 1.25, 128: 2.1}


def _block_dim(S):
    """Pick the block size minimizing (padded_len/S) * per-FLOP cost.

    Neither extreme is right alone: always padding to 512-blocks wastes
    2.5x FLOPs at S=640, while insisting the block divide round_up(S,128)
    forces 128-blocks at S=896 (no larger divisor) — ~60% slower than
    padding 896→1024 with 512-blocks. The cost model arbitrates."""
    best, best_cost = LANE, None
    for b, c in _BLOCK_COST.items():
        cost = (_round_up(S, b) / max(S, 1)) * c
        if best_cost is None or cost < best_cost:
            best, best_cost = b, cost
    return best


def _block_sizes(Sq, Sk):
    """Measured on v5e: large blocks win — at S=512, (512, 512) runs the
    whole attention row per grid step (the shape the reference fmha
    specializes for) and beats (128, 128) by 2.1x; VMEM stays bounded
    (score tile 512*512*4B = 1 MB). Longer sequences tile with the
    online-softmax recurrence across key blocks."""
    return (_block_dim(Sq), _block_dim(Sk))


def _drop_input(dropout_rate, seed, B, H, Sqp, Skp):
    """Dropout source array for the kernels: the (1,) int32 seed on real
    TPU (in-kernel PRNG), or the full precomputed uint32 bits tensor in
    interpret mode (no TPU PRNG emulation on CPU). Deterministic in the
    seed, so the backward regenerates the identical bits."""
    if dropout_rate == 0.0:
        return None
    if seed is None:
        raise ValueError(
            "flash_attention with dropout_rate > 0 requires dropout_seed "
            "(an int32 scalar; fold in the training step / layer index)")
    seed = jnp.asarray(seed, jnp.int32).reshape(())
    if _interpret():
        return jax.random.bits(jax.random.PRNGKey(seed),
                               (B, H, Sqp, Skp), jnp.uint32)
    return seed.reshape((1,))


def flash_dropout_keep_mask(B, H, Sq, Sk, dropout_rate, seed):
    """The exact (B, H, Sq, Sk) boolean keep-mask the flash kernels apply
    for this shape/rate/seed — bit-identical to the in-kernel generation
    on either backend, so tests can run composed attention with the same
    mask and assert numerical parity with the fused path."""
    bq, bk = _block_sizes(Sq, Sk)
    Sqp, Skp = _round_up(Sq, bq), _round_up(Sk, bk)
    if _interpret():
        bits = jax.random.bits(
            jax.random.PRNGKey(jnp.asarray(seed, jnp.int32)),
            (B, H, Sqp, Skp), jnp.uint32)
        return (bits < _keep_threshold(dropout_rate))[:, :, :Sq, :Sk]

    def mask_kernel(seed_ref, o_ref):
        tid = _tile_id(pl.program_id(0), pl.program_id(1),
                       pl.program_id(2), pl.program_id(3),
                       pl.num_programs(1), pl.num_programs(2),
                       pl.num_programs(3))
        keep = _keep_mask(seed_ref, tid, bq, bk, dropout_rate, True)
        o_ref[0, 0] = keep.astype(o_ref.dtype)

    keep = pl.pallas_call(
        mask_kernel,
        grid=(B, H, Sqp // bq, Skp // bk),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((1, 1, bq, bk),
                               lambda b, h, iq, ik: (b, h, iq, ik)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sqp, Skp), jnp.float32),
        interpret=_interpret(),
    )(jnp.asarray(seed, jnp.int32).reshape((1,)))
    return (keep > 0.5)[:, :, :Sq, :Sk]


def _scores(q, k, key_mask, causal, scale):
    """(B, H, Sq, Sk) fp32 masked scores — shared by every composed path."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :], FILL, s)
    if causal:
        Sq, Sk = s.shape[-2:]
        row = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where((row >= col)[None, None], s, FILL)
    return s


def mha_reference(q, k, v, key_mask=None, causal=False, scale=1.0,
                  dropout_rate=0.0, dropout_seed=None):
    """Composed-ops reference: materializes (B, H, Sq, Sk) scores.

    With dropout the mask comes from ``jax.random`` (same distribution as
    the kernel's hardware PRNG, different bits — use
    ``flash_dropout_keep_mask`` + ``mha_with_mask_reference`` for
    bit-matched parity tests)."""
    p = jax.nn.softmax(_scores(q, k, key_mask, causal, scale), axis=-1)
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError(
                "mha_reference with dropout_rate > 0 requires dropout_seed")
        keep = jax.random.bernoulli(
            jax.random.PRNGKey(jnp.asarray(dropout_seed, jnp.int32)),
            1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def mha_with_mask_reference(q, k, v, keep, key_mask=None, causal=False,
                            scale=1.0, dropout_rate=0.0):
    """Composed attention with an EXPLICIT keep-mask — pair with
    ``flash_dropout_keep_mask`` to reproduce the fused path exactly."""
    p = jax.nn.softmax(_scores(q, k, key_mask, causal, scale), axis=-1)
    p = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, key_mask=None, causal: bool = False,
                    scale: float = 1.0, dropout_rate: float = 0.0,
                    dropout_seed=None):
    """Multi-head attention without materializing the score matrix.

    Args:
      q, k, v: ``(B, H, S, D)`` (any floating dtype; fp32 accumulation).
      key_mask: optional ``(B, Sk)`` boolean, True = key position masked
        (the reference's padding-mask convention).
      causal: apply the upper-triangular causal mask in-kernel.
      scale: softmax temperature (typically ``1/sqrt(D)``).
      dropout_rate: attention-probability dropout, fused in-kernel (the
        reference fmha's Philox dropout; static Python float).
      dropout_seed: int32 scalar (may be traced) seeding the in-kernel
        PRNG; required when ``dropout_rate > 0``. Vary it per step (and
        per TP rank for head-sharded attention) for fresh masks.

    Replaces the reference's ``fmha``/``fast_multihead_attn`` fused
    attention. Differentiable via the flash recompute backward, which
    replays the identical dropout mask from the seed.
    """
    out, _ = _flash_fwd(q, k, v, key_mask, causal, scale, dropout_rate,
                        dropout_seed)
    return out


def _flash_fwd(q, k, v, key_mask, causal, scale, dropout_rate=0.0,
               dropout_seed=None):
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError(
            "flash_attention with dropout_rate > 0 requires dropout_seed "
            "(an int32 scalar; fold in the training step / layer index)")
    if use_jnp_fallback(q, k, v, key_mask):
        out = mha_reference(q, k, v, key_mask, causal, scale,
                            dropout_rate, dropout_seed)
        return out, None
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq, bk = _block_sizes(Sq, Sk)
    qp, kp, vp, mask = _pad_inputs(q, k, v, key_mask, bq, bk)
    drop_in = _drop_input(dropout_rate, dropout_seed, B, H,
                          qp.shape[2], kp.shape[2])
    out, lse = _flash_fwd_call(qp, kp, vp, mask, scale=scale, causal=causal,
                               bq=bq, bk=bk, dropout_rate=dropout_rate,
                               drop_in=drop_in)
    return out[:, :, :Sq, :D], lse


def _flash_vjp_fwd(q, k, v, key_mask, causal, scale, dropout_rate,
                   dropout_seed):
    out, lse = _flash_fwd(q, k, v, key_mask, causal, scale, dropout_rate,
                          dropout_seed)
    return out, (q, k, v, key_mask, out, lse, dropout_seed)


def _kernel_bwd(causal, scale, q, k, v, key_mask, out, lse_padded, g,
                g_lse=None, dropout_rate=0.0, dropout_seed=None):
    """Shared recompute backward for both vjps. ``lse_padded`` is the
    kernel's padded-width lse; ``g_lse`` (optional, (B, H, 1, Sq)) is the
    lse cotangent, folded into delta (d lse/d s = p, so
    ds = p * (dP - (rowsum(dO*O) - dlse)))."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq, bk = _block_sizes(Sq, Sk)
    qp, kp, vp, mask = _pad_inputs(q, k, v, key_mask, bq, bk)
    Sqp = qp.shape[2]
    Dp = qp.shape[3]
    drop_in = _drop_input(dropout_rate, dropout_seed, B, H,
                          Sqp, kp.shape[2])
    gp = g
    outp = out
    if (Sqp, Dp) != (Sq, D):
        gp = jnp.pad(g, ((0, 0), (0, 0), (0, Sqp - Sq), (0, Dp - D)))
        outp = jnp.pad(out, ((0, 0), (0, 0), (0, Sqp - Sq), (0, Dp - D)))
    # lse was computed on padded shapes in fwd, so it already covers any
    # padded query rows. delta is carried (B, H, 1, Sq) to match lse's
    # Mosaic-friendly layout (size-1 block dims must equal array dims).
    delta = jnp.sum(gp.astype(jnp.float32) * outp.astype(jnp.float32),
                    axis=-1)[:, :, None, :]
    if g_lse is not None:
        glp = g_lse
        if Sqp != Sq:
            glp = jnp.pad(g_lse, ((0, 0), (0, 0), (0, 0), (0, Sqp - Sq)))
        delta = delta - glp.astype(jnp.float32)
    dq, dk, dv = _flash_bwd_call(qp, kp, vp, mask, gp, lse_padded, delta,
                                 scale=scale, causal=causal, bq=bq, bk=bk,
                                 dropout_rate=dropout_rate, drop_in=drop_in)
    return (match_vma(dq[:, :, :Sq, :D].astype(q.dtype), q),
            match_vma(dk[:, :, :Sk, :D].astype(k.dtype), k),
            match_vma(dv[:, :, :Sk, :D].astype(v.dtype), v),
            None)


def _flash_vjp_bwd(causal, scale, dropout_rate, res, g):
    q, k, v, key_mask, out, lse, dropout_seed = res
    if lse is None:  # jnp fallback path: differentiate the reference
        def f(q, k, v):
            return mha_reference(q, k, v, key_mask, causal, scale,
                                 dropout_rate, dropout_seed)

        _, vjp = jax.vjp(f, q, k, v)
        dq, dk, dv = vjp(g)
        return (match_vma(dq, q), match_vma(dk, k), match_vma(dv, v),
                None, None)
    dq, dk, dv, dmask = _kernel_bwd(causal, scale, q, k, v, key_mask, out,
                                    lse, g, dropout_rate=dropout_rate,
                                    dropout_seed=dropout_seed)
    return dq, dk, dv, dmask, None


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# (out, lse) variant for blockwise consumers (ring attention)
# ---------------------------------------------------------------------------

def _with_lse_reference(q, k, v, key_mask, causal, scale,
                        dropout_rate=0.0, dropout_seed=None):
    """Composed (out, lse): the differentiable fallback path. With
    dropout it reproduces the kernel semantics exactly — the keep-mask
    comes from :func:`flash_dropout_keep_mask` (bit-identical bits to
    the in-kernel generation for this backend), applied to the
    NORMALIZED probabilities while lse stays pre-dropout."""
    s = _scores(q, k, key_mask, causal, scale)
    lse = jax.nn.logsumexp(s, axis=-1)[:, :, None, :]
    p = jnp.exp(s - lse.transpose(0, 1, 3, 2))
    if dropout_rate > 0.0:
        B, H, Sq, _ = q.shape
        keep = flash_dropout_keep_mask(B, H, Sq, k.shape[2], dropout_rate,
                                       dropout_seed)
        p = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
    out = jnp.einsum("bhqk,bhkd->bhqd", p,
                     v.astype(jnp.float32)).astype(q.dtype)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention_with_lse(q, k, v, key_mask=None, causal: bool = False,
                             scale: float = 1.0, dropout_rate: float = 0.0,
                             dropout_seed=None):
    """Flash attention returning ``(out, lse)`` with lse trimmed to the
    true Sq — the building block for blockwise/ring consumers that merge
    per-block results via log-sum-exp. Differentiable INCLUDING the lse
    output: its cotangent folds into the recompute backward's delta
    (``delta = rowsum(dO*O) - dlse``; d lse/d s = p).

    Dropout composes with the lse merge: the kernels apply the keep-mask
    only where the probability tile feeds ``p @ v`` while every
    statistic (m, l, lse) stays PRE-dropout, so a blockwise consumer
    that rescales partial outputs by ``exp(lse_i - lse_total)`` gets
    exactly ``sum_j drop(p_hat_j) v_j`` — composed dropout(softmax) @ v
    over the merged distribution, nothing double-counted. Blockwise
    callers must pass a DISTINCT seed per (global q-block, global
    kv-block) pair (see ring_attention's hashed tile seeds) so tiles
    draw independent streams and backward replays the same mask."""
    if use_jnp_fallback(q, k, v, key_mask):
        return _with_lse_reference(q, k, v, key_mask, causal, scale,
                                   dropout_rate, dropout_seed)
    out, lse = _flash_fwd(q, k, v, key_mask, causal, scale, dropout_rate,
                          dropout_seed)
    return out, lse[..., :q.shape[2]]


def _fwl_fwd(q, k, v, key_mask, causal, scale, dropout_rate, dropout_seed):
    if use_jnp_fallback(q, k, v, key_mask):
        out, lse_t = _with_lse_reference(q, k, v, key_mask, causal, scale,
                                         dropout_rate, dropout_seed)
        return (out, lse_t), (q, k, v, key_mask, out, None, dropout_seed)
    out, lse = _flash_fwd(q, k, v, key_mask, causal, scale, dropout_rate,
                          dropout_seed)
    return ((out, lse[..., :q.shape[2]]),
            (q, k, v, key_mask, out, lse, dropout_seed))


def _fwl_bwd(causal, scale, dropout_rate, res, cotangents):
    q, k, v, key_mask, out, lse_padded, dropout_seed = res
    g, g_lse = cotangents
    if lse_padded is None:  # fallback path: autodiff the composed form
        def f(q, k, v):
            return _with_lse_reference(q, k, v, key_mask, causal, scale,
                                       dropout_rate, dropout_seed)

        _, vjp = jax.vjp(f, q, k, v)
        dq, dk, dv = vjp((g, g_lse))
        return (match_vma(dq, q), match_vma(dk, k), match_vma(dv, v),
                None, None)
    dq, dk, dv, dmask = _kernel_bwd(causal, scale, q, k, v, key_mask, out,
                                    lse_padded, g, g_lse,
                                    dropout_rate=dropout_rate,
                                    dropout_seed=dropout_seed)
    return dq, dk, dv, dmask, None


flash_attention_with_lse.defvjp(_fwl_fwd, _fwl_bwd)


# ---------------------------------------------------------------------------
# (B, S, NH*D)-layout entry: attention without head split/merge transposes
# ---------------------------------------------------------------------------
#
# The transposed (B, NH, S, D) convention costs the model 4 layout copies
# per layer forward (q, k, v head-split + context merge) and their 4
# mirrors in backward — ~8 x 17 MB of pure HBM traffic per BERT-large
# layer. Here the kernel reads heads directly out of the flat activation
# via the BlockSpec index map and writes the context back the same way,
# so the model keeps everything (B, S, H) end to end.
#
# Mosaic requires lane-dim blocks to be multiples of 128, so a D=64 head
# cannot be block-sliced alone out of a 1024-lane activation; instead
# each grid step owns a HEAD PAIR — a (1, S, 2*D=128) block holding
# heads 2h and 2h+1 side by side — and the kernel computes the two
# heads' attention from in-register lane slices of the pair. (This also
# halves the grid, amortizing per-step overheads.) Constraints for the
# kernel path: 2*D % 128 == 0, even NH, and the single-tile sequence
# regime (S <= 512 — the flagship shape); anything else falls back to
# the transposed entry transparently.


def _bsh_hpb(NH, D):
    """Heads per block for the bsh kernels: the widest of {4, 2, 1}
    whose lane block (hpb*D) is a 128 multiple and divides NH. 0 means
    the layout can't be block-sliced (fallback to the transposed entry).
    hpb=2 at D=64 is the Mosaic-minimum 128-lane block; hpb=4 was A/B'd
    at the headline as an alternative (fewer grid steps, more VMEM per
    step)."""
    import os

    forced = os.environ.get("APEX_BSH_HPB")
    cand = (4, 2, 1)
    if forced:
        try:
            cand = (int(forced),)
        except ValueError:
            cand = ()
        if not any(h > 0 and NH % h == 0 and (h * D) % 128 == 0
                   for h in cand):
            # an unusable forced value must NOT silently divert to the
            # transposed entry — the A/B the env var exists for would
            # record the wrong code path; warn and use the default sweep
            import warnings

            warnings.warn(
                f"APEX_BSH_HPB={forced!r} is not a valid head grouping "
                f"for NH={NH}, D={D}; using the default (4, 2, 1) sweep "
                f"instead", stacklevel=3)
            cand = (4, 2, 1)
    for h in cand:
        if h > 0 and NH % h == 0 and (h * D) % 128 == 0:
            return h
    return 0  # no valid grouping: caller falls back to transposed entry


def _fwd_single_kernel_bsh(q_ref, k_ref, v_ref, mask_ref, *rest, scale,
                           causal, bq, bk, NH, D, hpb,
                           dropout_rate=0.0, native_prng=True):
    """Head-group single-tile forward on (B, S, NH*D)-layout refs: the
    (1, bq, hpb*D) blocks hold heads hp*hpb .. hp*hpb+hpb-1; same math
    as _fwd_single_kernel per head."""
    if dropout_rate > 0.0:
        drop_ref, o_ref, lse_ref = rest
    else:
        drop_ref, (o_ref, lse_ref) = None, rest
    b, hp = pl.program_id(0), pl.program_id(1)
    mrow = mask_ref[0, 0][None, :]
    q2, k2, v2 = q_ref[0], k_ref[0], v_ref[0]       # (bq, hpb*D)
    prec = _prec(q2.dtype)
    outs = []
    for j in range(hpb):
        q = q2[:, j * D:(j + 1) * D]
        k = k2[:, j * D:(j + 1) * D]
        s = _dot(q, k, ((1,), (1,)), prec) * scale
        s = jnp.where(mrow != 0, FILL, s)
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(row >= col, s, FILL)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        p = jnp.where(mrow >= 2, 0.0, p)
        l = jnp.sum(p, axis=1, keepdims=True)
        if dropout_rate > 0.0:
            # per-HEAD tile id (hpb*hp + j): identical mask stream to
            # the transposed entry at the same (b, h) coordinates
            tid = _tile_id(b, hpb * hp + j, 0, 0, NH, 1, 1)
            keep = _keep_mask(drop_ref, tid, bq, bk, dropout_rate,
                              native_prng, interp_idx=(0, j))
            p_av = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
        else:
            p_av = p
        v = v2[:, j * D:(j + 1) * D]
        pv = _dot(p_av.astype(v.dtype), v, ((1,), (0,)), prec)
        safe_l = jnp.where(l > 0, l, 1.0)
        outs.append((pv / safe_l).astype(o_ref.dtype))
        lse_ref[0, j, 0] = (m + jnp.log(safe_l))[:, 0]
    o_ref[0] = outs[0] if hpb == 1 else jnp.concatenate(outs, axis=1)


def _bwd_fused_kernel_bsh(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                          delta_ref, *rest, scale, causal, bq, bk, NH, D,
                          hpb, dropout_rate=0.0, native_prng=True):
    """Head-group single-tile fused backward on (B, S, NH*D)-layout
    refs: recomputes s and p once per head and emits dq, dk, dv for the
    group (same 5-matmul-per-head economy as _bwd_fused_kernel)."""
    if dropout_rate > 0.0:
        drop_ref, dq_ref, dk_ref, dv_ref = rest
    else:
        drop_ref, (dq_ref, dk_ref, dv_ref) = None, rest
    b, hp = pl.program_id(0), pl.program_id(1)
    mrow = mask_ref[0, 0][None, :]
    q2, k2, v2, do2 = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    prec = _prec(q2.dtype)
    dqs, dks, dvs = [], [], []
    for j in range(hpb):
        q = q2[:, j * D:(j + 1) * D]
        k = k2[:, j * D:(j + 1) * D]
        s = _dot(q, k, ((1,), (1,)), prec) * scale
        s = jnp.where(mrow != 0, FILL, s)
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(row >= col, s, FILL)
        lse = lse_ref[0, j, 0][:, None]
        p = jnp.exp(s - lse)
        p = jnp.where(mrow >= 2, 0.0, p)
        do = do2[:, j * D:(j + 1) * D]
        v = v2[:, j * D:(j + 1) * D]
        dp = _dot(do, v, ((1,), (1,)), prec)
        if dropout_rate > 0.0:
            tid = _tile_id(b, hpb * hp + j, 0, 0, NH, 1, 1)
            keep = _keep_mask(drop_ref, tid, bq, bk, dropout_rate,
                              native_prng, interp_idx=(0, j))
            inv_keep = 1.0 / (1.0 - dropout_rate)
            p_av = jnp.where(keep, p, 0.0) * inv_keep
            dp = jnp.where(keep, dp, 0.0) * inv_keep
        else:
            p_av = p
        dvs.append(_dot(p_av.astype(do.dtype), do, ((0,), (0,)),
                        prec).astype(dv_ref.dtype))
        delta = delta_ref[0, j, 0][:, None]
        ds = p * (dp - delta) * scale
        dqs.append(_dot(ds.astype(k.dtype), k, ((1,), (0,)),
                        prec).astype(dq_ref.dtype))
        dks.append(_dot(ds.astype(q.dtype), q, ((0,), (0,)),
                        prec).astype(dk_ref.dtype))
    if hpb == 1:
        dq_ref[0], dk_ref[0], dv_ref[0] = dqs[0], dks[0], dvs[0]
    else:
        dq_ref[0] = jnp.concatenate(dqs, axis=1)
        dk_ref[0] = jnp.concatenate(dks, axis=1)
        dv_ref[0] = jnp.concatenate(dvs, axis=1)


def _bsh_spec(bs, D2):
    """BlockSpec slicing head group hp of a (B, S_padded, NH*D) tensor
    (lane block hpb*D, a 128 multiple)."""
    return pl.BlockSpec((1, bs, D2), lambda b, hp: (b, 0, hp))


def _bsh_drop_arg(drop_in, bq, bk, hpb):
    """Dropout input for the group kernels: scalar seed (native) or the
    (B, NH, Sqp, Skp) bits tensor blocked (1, hpb, bq, bk) per group."""
    if drop_in is None:
        return [], []
    if drop_in.ndim == 1:
        return [drop_in], [pl.BlockSpec(memory_space=pltpu.SMEM)]
    return [drop_in], [pl.BlockSpec((1, hpb, bq, bk),
                                    lambda b, hp: (b, hp, 0, 0))]


def _flash_fwd_call_bsh(q, k, v, mask, *, scale, causal, bq, bk, NH, D,
                        hpb, dropout_rate=0.0, drop_in=None):
    B, Sp, _ = q.shape
    native = drop_in is not None and drop_in.ndim == 1
    extra, extra_specs = _bsh_drop_arg(drop_in, bq, bk, hpb)
    return pl.pallas_call(
        functools.partial(_fwd_single_kernel_bsh, scale=scale,
                          causal=causal, bq=bq, bk=bk, NH=NH, D=D,
                          hpb=hpb, dropout_rate=dropout_rate,
                          native_prng=native),
        grid=(B, NH // hpb),
        in_specs=[
            _bsh_spec(bq, hpb * D),
            _bsh_spec(bk, hpb * D),
            _bsh_spec(bk, hpb * D),
            pl.BlockSpec((1, 1, bk), lambda b, hp: (b, 0, 0)),
        ] + extra_specs,
        out_specs=(
            _bsh_spec(bq, hpb * D),
            pl.BlockSpec((1, hpb, 1, bq), lambda b, hp: (b, hp, 0, 0)),
        ),
        out_shape=(
            out_struct((B, Sp, NH * D), q.dtype, q, k, v),
            out_struct((B, NH, 1, Sp), jnp.float32, q, k, v),
        ),
        interpret=_interpret(),
    )(q, k, v, mask, *extra)


def _flash_bwd_call_bsh(q, k, v, mask, do, lse, delta, *, scale, causal,
                        bq, bk, NH, D, hpb, dropout_rate=0.0,
                        drop_in=None):
    B, Sp, _ = q.shape
    native = drop_in is not None and drop_in.ndim == 1
    extra, extra_specs = _bsh_drop_arg(drop_in, bq, bk, hpb)
    return pl.pallas_call(
        functools.partial(_bwd_fused_kernel_bsh, scale=scale,
                          causal=causal, bq=bq, bk=bk, NH=NH, D=D,
                          hpb=hpb, dropout_rate=dropout_rate,
                          native_prng=native),
        grid=(B, NH // hpb),
        in_specs=[
            _bsh_spec(bq, hpb * D),
            _bsh_spec(bk, hpb * D),
            _bsh_spec(bk, hpb * D),
            pl.BlockSpec((1, 1, bk), lambda b, hp: (b, 0, 0)),
            _bsh_spec(bq, hpb * D),
            pl.BlockSpec((1, hpb, 1, bq), lambda b, hp: (b, hp, 0, 0)),
            pl.BlockSpec((1, hpb, 1, bq), lambda b, hp: (b, hp, 0, 0)),
        ] + extra_specs,
        out_specs=(
            _bsh_spec(bq, hpb * D),
            _bsh_spec(bk, hpb * D),
            _bsh_spec(bk, hpb * D),
        ),
        out_shape=(
            out_struct((B, Sp, NH * D), q.dtype, q, k, v, do),
            out_struct((B, Sp, NH * D), k.dtype, q, k, v, do),
            out_struct((B, Sp, NH * D), v.dtype, q, k, v, do),
        ),
        interpret=_interpret(),
    )(q, k, v, mask, do, lse, delta, *extra)


def _bsh_kernel_ok(S, H, num_heads):
    """Static gate for the bsh kernel path: a head group must tile the
    128-lane block exactly, and the single-tile regime must hold."""
    if H % num_heads:
        return False
    if _bsh_hpb(num_heads, H // num_heads) == 0:
        return False
    bq = _block_dim(S)
    return _round_up(S, bq) == bq  # single tile after padding


def _bsh_transpose_fallback(q, k, v, key_mask, num_heads, causal, scale,
                            dropout_rate, dropout_seed):
    B, S, H = q.shape
    D = H // num_heads

    def split(t):
        return t.reshape(B, S, num_heads, D).transpose(0, 2, 1, 3)

    out = flash_attention(split(q), split(k), split(v), key_mask, causal,
                          scale, dropout_rate, dropout_seed)
    return out.transpose(0, 2, 1, 3).reshape(B, S, H)


def _bsh_pad(q, k, v, key_mask, bq):
    """Row-pad (B, S, H) activations to the block size; padded keys get
    mask code 2 (excluded from the softmax denominator)."""
    B, S, H = q.shape
    Sp = _round_up(S, bq)
    if key_mask is None:
        mask = jnp.zeros((B, 1, S), jnp.int32)
    else:
        mask = key_mask.astype(jnp.int32)[:, None, :]
    if Sp != S:
        q = jnp.pad(q, ((0, 0), (0, Sp - S), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, Sp - S), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, Sp - S), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, 0), (0, Sp - S)),
                       constant_values=2)
    return q, k, v, mask


def flash_attention_bsh(q, k, v, key_mask=None, num_heads=None,
                        causal: bool = False, scale: float = 1.0,
                        dropout_rate: float = 0.0, dropout_seed=None):
    """Flash attention on flat (B, S, NH*D) activations — no head
    split/merge transposes anywhere. Heads are interleaved in the lane
    dim (head h owns columns [h*D, (h+1)*D)); the kernel slices them via
    its BlockSpec index maps, and gradients come back in the same flat
    layout. Semantics (masking, causal, fused dropout, seeds) are
    identical to :func:`flash_attention` on the transposed layout.

    Falls back to transpose + :func:`flash_attention` when the kernel
    constraints don't hold (D not a multiple of 64, or S beyond the
    single-tile regime), and to the composed reference under shard_map
    on CPU — callers use one entry everywhere.
    """
    if num_heads is None:
        raise ValueError("flash_attention_bsh requires num_heads")
    B, S, H = q.shape
    if use_jnp_fallback(q, k, v, key_mask) or not _bsh_kernel_ok(
            S, H, num_heads):
        return _bsh_transpose_fallback(q, k, v, key_mask, num_heads,
                                       causal, scale, dropout_rate,
                                       dropout_seed)
    return _flash_bsh_core(q, k, v, key_mask, num_heads, causal, scale,
                           dropout_rate, dropout_seed)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_bsh_core(q, k, v, key_mask, num_heads, causal, scale,
                    dropout_rate, dropout_seed=None):
    out, _ = _bsh_fwd_impl(q, k, v, key_mask, num_heads, causal, scale,
                           dropout_rate, dropout_seed)
    return out


def _bsh_fwd_impl(q, k, v, key_mask, num_heads, causal, scale,
                  dropout_rate, dropout_seed):
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError(
            "flash_attention_bsh with dropout_rate > 0 requires "
            "dropout_seed")
    B, S, H = q.shape
    D = H // num_heads
    bq = bk = _block_dim(S)
    qp, kp, vp, mask = _bsh_pad(q, k, v, key_mask, bq)
    drop_in = _drop_input(dropout_rate, dropout_seed, B, num_heads,
                          qp.shape[1], kp.shape[1])
    out, lse = _flash_fwd_call_bsh(qp, kp, vp, mask, scale=scale,
                                   causal=causal, bq=bq, bk=bk,
                                   NH=num_heads, D=D,
                                   hpb=_bsh_hpb(num_heads, D),
                                   dropout_rate=dropout_rate,
                                   drop_in=drop_in)
    return out[:, :S], lse


def _bsh_vjp_fwd(q, k, v, key_mask, num_heads, causal, scale,
                 dropout_rate, dropout_seed=None):
    out, lse = _bsh_fwd_impl(q, k, v, key_mask, num_heads, causal, scale,
                             dropout_rate, dropout_seed)
    return out, (q, k, v, key_mask, out, lse, dropout_seed)


def _bsh_vjp_bwd(num_heads, causal, scale, dropout_rate, res, g):
    q, k, v, key_mask, out, lse, dropout_seed = res
    B, S, H = q.shape
    D = H // num_heads
    bq = bk = _block_dim(S)
    qp, kp, vp, mask = _bsh_pad(q, k, v, key_mask, bq)
    Sp = qp.shape[1]
    drop_in = _drop_input(dropout_rate, dropout_seed, B, num_heads, Sp, Sp)
    gp, outp = g, out
    if Sp != S:
        gp = jnp.pad(g, ((0, 0), (0, Sp - S), (0, 0)))
        outp = jnp.pad(out, ((0, 0), (0, Sp - S), (0, 0)))
    # per-head delta = rowsum_D(dO * O): (B, Sp, NH) -> (B, NH, 1, Sp)
    delta = (gp.astype(jnp.float32) * outp.astype(jnp.float32)).reshape(
        B, Sp, num_heads, D).sum(-1).transpose(0, 2, 1)[:, :, None, :]
    dq, dk, dv = _flash_bwd_call_bsh(qp, kp, vp, mask, gp, lse, delta,
                                     scale=scale, causal=causal, bq=bq,
                                     bk=bk, NH=num_heads, D=D,
                                     hpb=_bsh_hpb(num_heads, D),
                                     dropout_rate=dropout_rate,
                                     drop_in=drop_in)
    return (match_vma(dq[:, :S].astype(q.dtype), q),
            match_vma(dk[:, :S].astype(k.dtype), k),
            match_vma(dv[:, :S].astype(v.dtype), v),
            None, None)


_flash_bsh_core.defvjp(_bsh_vjp_fwd, _bsh_vjp_bwd)


# ---------------------------------------------------------------------------
# paged decode attention (single-query attention against a block table)
# ---------------------------------------------------------------------------
#
# The serving decode step: each sequence contributes ONE query token that
# attends over its entire cached context, which lives scattered across
# the paged KV pool (apex_tpu.serving.kv_cache) rather than in a
# contiguous (B, S, H, D) tensor. The score tensor is (B, H, 1, ctx) —
# there is no S_q dimension to tile, no online-softmax recurrence to
# carry, and no backward pass (inference only), so the flash machinery
# above buys nothing here; what matters is the GATHER (block table ->
# pool rows) and the fp32 masked softmax, which XLA fuses into a
# bandwidth-bound gather + GEMV chain on both CPU and TPU. Masking
# follows this file's conventions: fp32 accumulation via
# preferred_element_type, the finite FILL for dead positions (a fully
# empty context — an inactive batch slot — degrades to a uniform read of
# zero-initialized pool rows instead of NaN).


def paged_decode_attention(q, k_pages, v_pages, block_tables, context_lens,
                           scale: float = 1.0, k_scales=None,
                           v_scales=None, use_pallas=None):
    """Single-query attention against the paged KV pool.

    Args:
      q: ``[B, H, D]`` — one query token per sequence (the token being
        decoded, whose K/V must already be written into the pool).
      k_pages, v_pages: ``[num_blocks, block_size, H, D]`` — ONE layer's
        block pool (callers index the stacked ``[L, ...]`` cache).
      block_tables: ``[B, max_blocks_per_seq]`` int32 block ids in
        sequence order; entries past a sequence's allocation may be any
        value (out-of-bounds ids are clipped into the pool and the
        positions masked by ``context_lens``).
      context_lens: ``[B]`` int32 — valid tokens per sequence INCLUDING
        the current one.
      scale: softmax temperature (typically ``1/sqrt(D)``).
      k_scales, v_scales: ``[num_blocks, block_size, H]`` fp32 per-row
        dequantization scales of a quantized pool (None = the pool is
        full precision). Dequantization happens inside the read.
      use_pallas: route the read chain through the fused Pallas kernel
        (:mod:`apex_tpu.ops.paged_attention_pallas`); None consults the
        ``APEX_PAGED_ATTENTION_PALLAS`` env flag.

    Returns ``[B, H, D]`` in ``q.dtype``.
    """
    # decode IS the single-query case of the chunked-prefill kernel: a
    # one-token "chunk" at position context_len - 1 (its causal mask
    # kpos <= ctx-1 is exactly the decode mask kpos < ctx, including
    # the empty-context lane, where both degrade to the uniform FILL
    # read). One gather/mask/softmax chain to maintain, not two.
    # q_positions=None selects the collapsed single-comparison mask —
    # this call sits inside the engine's K-step decode scan, so the
    # per-query mask broadcast it skips would otherwise run K times
    # per dispatch.
    return paged_prefill_attention(
        q[:, None], k_pages, v_pages, block_tables,
        None, context_lens, scale, k_scales=k_scales,
        v_scales=v_scales, use_pallas=use_pallas)[:, 0]


def paged_prefill_attention(q, k_pages, v_pages, block_tables, q_positions,
                            context_lens, scale: float = 1.0,
                            k_scales=None, v_scales=None,
                            use_pallas=None):
    """Chunked-prefill attention: a fixed-size chunk of queries against
    the paged KV pool.

    The serving engine prefills a prompt in fixed ``[1, chunk]`` pieces
    (docs/serving.md): each chunk's K/V are scattered into the pool
    first, then its queries attend over EVERYTHING the sequence has
    cached so far — the shared-prefix blocks matched at admission, the
    earlier chunks, and the chunk itself — under a causal-by-absolute-
    position mask. Like :func:`paged_decode_attention` there is no
    backward pass and the work is gather-dominated, so this is the same
    fp32 masked-softmax chain, just with a query axis: scores are
    ``[B, H, C, ctx_max]`` where ``C`` is the (small, fixed) chunk and
    ``ctx_max`` the table's span. Dead key positions take the finite
    FILL; a query past its sequence's length (chunk padding) still sees
    at least key position 0, so padding lanes stay finite and are
    simply ignored by the caller.

    Args:
      q: ``[B, C, H, D]`` — the chunk's query tokens.
      k_pages, v_pages: ``[num_blocks, block_size, H, D]`` — ONE layer's
        block pool (callers index the stacked ``[L, ...]`` cache); must
        already contain this chunk's K/V.
      block_tables: ``[B, max_blocks_per_seq]`` int32 block ids in
        sequence order (out-of-bounds ids are clipped into the pool and
        the positions masked by ``context_lens``).
      q_positions: ``[B, C]`` int32 absolute position of each query
        token (the chunk's offset into the sequence) — or ``None``, the
        decode fast path: every query is THE LAST cached position
        (``context_lens - 1``), so the causal and length masks collapse
        into the single comparison ``kpos < context_lens`` and the
        per-query ``[B, C, ctx_max]`` mask broadcast is skipped
        entirely (the mask VALUES are bit-identical; only the work to
        build them goes away). The engine's multi-step decode scan runs
        this mask once per inner iteration, which is what makes the
        skip worth having.
      context_lens: ``[B]`` int32 — valid tokens in the cache INCLUDING
        this chunk's.
      scale: softmax temperature (typically ``1/sqrt(D)``).
      k_scales, v_scales: ``[num_blocks, block_size, H]`` fp32 per-row
        dequantization scales of a quantized pool (None = full
        precision; the fp path is untouched when absent, bit for bit).
        The scales gather through the SAME clipped table as the
        payload and dequantize inside the read — quantized K/V never
        materializes at full precision outside this chain.
      use_pallas: run the gather→mask→softmax→weighted-sum chain as
        ONE fused ``pallas_call``
        (:mod:`apex_tpu.ops.paged_attention_pallas`) instead of the
        composed XLA chain — READ side only (writes stay in XLA:
        Pallas TPU has no scatter lowering, the BENCH_r01 lesson).
        None consults the ``APEX_PAGED_ATTENTION_PALLAS`` env flag;
        either way the kernel is taken only when its static shape
        gate holds (interpret mode always qualifies), so the XLA
        path below remains the universal fallback.

    Returns ``[B, C, H, D]`` in ``q.dtype``.

    Mesh sharding (docs/serving.md): under the engine's GSPMD mesh
    the pool, the scales, and the queries all arrive sharded on the
    HEAD axis (``H`` over ``"model"``), and the whole chain here is
    head-elementwise — gather and mask index only block/position
    axes, the softmax reduces over keys, both einsums contract ``d``
    or ``k`` per head — so GSPMD partitions it with ZERO collectives;
    the all-reduce lives in the model's row-parallel output
    projection, not in attention. (The fused Pallas route is
    single-device: the engine rejects the env flag on a sharded
    model axis.)
    """
    B, C, H, D = q.shape
    N = k_pages.shape[0]
    from apex_tpu.ops.paged_attention_pallas import (
        pallas_paged_read_wanted, pallas_paged_read_supported,
        paged_read_attention)

    if (pallas_paged_read_wanted(use_pallas)
            and pallas_paged_read_supported(k_pages,
                                            block_tables.shape[1], C)
            and not use_jnp_fallback(q, k_pages, v_pages)):
        return paged_read_attention(
            q, k_pages, v_pages, block_tables, q_positions,
            context_lens, scale, k_scales=k_scales, v_scales=v_scales)
    tbl = jnp.minimum(block_tables, N - 1)
    k = k_pages[tbl].reshape(B, -1, H, D)        # [B, ctx_max, H, D]
    v = v_pages[tbl].reshape(B, -1, H, D)
    if k_scales is not None:
        k = k.astype(jnp.float32) \
            * k_scales[tbl].reshape(B, -1, H)[..., None]
        v = v.astype(jnp.float32) \
            * v_scales[tbl].reshape(B, -1, H)[..., None]
    ctx_max = k.shape[1]

    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    kpos = jax.lax.broadcasted_iota(jnp.int32, (B, ctx_max), 1)
    if q_positions is None:
        # decode: kpos <= ctx-1 AND kpos < ctx are the same predicate;
        # [B, 1, ctx_max] broadcasts over both H and the C=1 query axis
        visible = (kpos < context_lens[:, None])[:, None, :]
    else:
        visible = ((kpos[:, None, :] <= q_positions[:, :, None])
                   & (kpos[:, None, :] < context_lens[:, None, None]))
    s = jnp.where(visible[:, None], s, FILL)     # [B, H, C, ctx_max]
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
