"""Pallas TPU kernels: fused scale + mask + softmax (fwd + bwd).

Rebuild of the reference's ``csrc/megatron/scaled_masked_softmax*.cu`` and
``scaled_upper_triang_masked_softmax*.cu`` (SURVEY.md §2.2): attention-
score softmax with the scale multiply and (padding or causal) mask folded
into one pass — the op behind ``FusedScaleMaskSoftmax``
(``apex/transformer/functional``).

TPU design: rows are flattened to (N, Sk) and tiled into VMEM row blocks;
max/sum are VPU lane reductions; the causal mask is generated in-kernel
from ``broadcasted_iota`` (no mask tensor traffic, like the reference's
upper-triang variant); the key dim is padded to the 128-lane width with
``-inf``-equivalent so padded lanes contribute zero probability. Backward
uses the saved softmax output: dx = scale * y * (g - sum(g*y)).

Unlike the CUDA kernels (hard seq-len limits 16..16384, pow-2 shapes —
their ``is_kernel_available`` gate), any shape works here; the module
keeps the gate trivially true.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._common import (
    LANE,
    interpret_mode as _interpret,
    out_struct,
    round_up as _round_up,
)

_NEG = -30000.0  # large-negative fill, safe in bf16/fp32 (reference: -10000)
# wrapper-padding fill: far below _NEG so padded lanes contribute exactly
# zero even in a fully-user-masked row (whose live lanes all sit at _NEG
# and must degrade to a uniform distribution over the TRUE keys only)
_PAD_NEG = -1e30


def _block_rows(n):
    if n >= 256:
        return 256
    return _round_up(max(n, 1), 8)


def _fwd_kernel(x_ref, *rest, scale, causal, sq, true_k, padded, mask_mode):
    if mask_mode is not None:
        m_ref, y_ref = rest
    else:
        m_ref, (y_ref,) = None, rest
    x = x_ref[:].astype(jnp.float32) * scale
    # mask applied AFTER the scale multiply — the reference kernel's
    # order, valid for any scale incl. <= 0
    if mask_mode == "add":
        x = x + m_ref[:].astype(jnp.float32)
    elif mask_mode == "fill":
        # boolean-mask semantics: REPLACE with the finite fill (so a
        # fully-masked row degrades to uniform, like the reference)
        x = jnp.where(m_ref[:] > 0, _NEG, x)
    rows = x.shape[0]
    if causal:
        # global row index = block_start + local row; key col must be <= the
        # query position (row % sq when rows are (b*h*sq))
        row0 = pl.program_id(0) * rows
        local = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
        q_pos = (row0 + local) % sq
        col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        x = jnp.where(col <= q_pos, x, _NEG)
    if padded:
        # LAST, so no finite mask/causal fill re-raises a padded lane
        col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        x = jnp.where(col < true_k, x, _PAD_NEG)
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=1, keepdims=True)
    y_ref[:] = (e / s).astype(y_ref.dtype)


def _bwd_kernel(g_ref, y_ref, dx_ref, *, scale):
    g = g_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    dot = jnp.sum(g * y, axis=1, keepdims=True)
    dx_ref[:] = (scale * y * (g - dot)).astype(dx_ref.dtype)


def _pallas_softmax_fwd(x2, m2=None, *, scale, causal, sq, true_k,
                        mask_mode=None):
    n, kpad = x2.shape
    br = _block_rows(n)
    in_specs = [pl.BlockSpec((br, kpad), lambda i: (i, 0))]
    args = [x2]
    if m2 is not None:
        in_specs.append(pl.BlockSpec((br, kpad), lambda i: (i, 0)))
        args.append(m2)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, sq=sq,
                          true_k=true_k, padded=(true_k != kpad),
                          mask_mode=mask_mode if m2 is not None else None),
        grid=(n // br,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((br, kpad), lambda i: (i, 0)),
        out_shape=out_struct((n, kpad), x2.dtype, x2),
        interpret=_interpret(),
    )(*args)


def _pallas_softmax_bwd(g2, y2, *, scale):
    n, kpad = g2.shape
    br = _block_rows(n)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, kpad), lambda i: (i, 0)),
            pl.BlockSpec((br, kpad), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, kpad), lambda i: (i, 0)),
        out_shape=out_struct((n, kpad), g2.dtype, g2, y2),
        interpret=_interpret(),
    )(g2, y2)


def _fwd4_kernel(x_ref, *rest, scale, causal, true_k, padded, mask_mode):
    """4D variant: block (1, 1, br, kpad) of (B, H, Sq, Sk); the mask
    block keeps its broadcast dims (size-1 B/H/Sq), so a (B, 1, 1, Sk)
    attention mask is read as-is instead of being materialized at
    (B, H, Sq, Sk)."""
    if mask_mode is not None:
        m_ref, y_ref = rest
    else:
        m_ref, (y_ref,) = None, rest
    x = x_ref[0, 0].astype(jnp.float32) * scale
    if mask_mode == "add":
        x = x + m_ref[0, 0].astype(jnp.float32)   # (1|br, kpad) broadcasts
    elif mask_mode == "fill":
        x = jnp.where(m_ref[0, 0] > 0, _NEG, x)
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    if causal:
        row0 = pl.program_id(2) * x.shape[0]
        local = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
        x = jnp.where(col <= row0 + local, x, _NEG)
    if padded:
        x = jnp.where(col < true_k, x, _PAD_NEG)
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=1, keepdims=True)
    y_ref[0, 0] = (e / s).astype(y_ref.dtype)


def _mask_4d_compatible(mshape, xshape):
    return (len(mshape) == 4 and len(xshape) == 4
            and mshape[0] in (1, xshape[0]) and mshape[1] in (1, xshape[1])
            and mshape[2] in (1, xshape[2]) and mshape[3] == xshape[3])


def _pallas_softmax_fwd4(x, m, *, scale, causal, mask_mode):
    B, H, Sq, K = x.shape
    kpad = _round_up(K, LANE)
    br = _block_rows(Sq)
    sqp = _round_up(Sq, br)
    xp = jnp.pad(x, ((0, 0), (0, 0), (0, sqp - Sq), (0, kpad - K)))
    ms = m.shape[2]
    mp = jnp.pad(m.astype(jnp.float32),
                 ((0, 0), (0, 0), (0, (sqp - Sq) if ms > 1 else 0),
                  (0, kpad - K)))
    mb, mh, msq = mp.shape[0], mp.shape[1], mp.shape[2]
    mbr = br if msq > 1 else 1

    def m_idx(b, h, j):
        return (b if mb > 1 else 0, h if mh > 1 else 0,
                j if msq > 1 else 0, 0)

    yp = pl.pallas_call(
        functools.partial(_fwd4_kernel, scale=scale, causal=causal,
                          true_k=K, padded=(K != kpad),
                          mask_mode=mask_mode),
        grid=(B, H, sqp // br),
        in_specs=[
            pl.BlockSpec((1, 1, br, kpad), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, mbr, kpad), m_idx),
        ],
        out_specs=pl.BlockSpec((1, 1, br, kpad),
                               lambda b, h, j: (b, h, j, 0)),
        out_shape=out_struct((B, H, sqp, kpad), x.dtype, x, m),
        interpret=_interpret(),
    )(xp, mp)
    return yp[:, :, :Sq, :K]


def _prep(x):
    k = x.shape[-1]
    lead = x.shape[:-1]
    n = 1
    for d in lead:
        n *= d
    x2 = x.reshape(n, k)
    kpad = _round_up(k, LANE)
    npad = _round_up(n, _block_rows(n))
    if kpad != k or npad != n:
        x2 = jnp.pad(x2, ((0, npad - n), (0, kpad - k)))
    return x2, lead, n, k


def _softmax_impl(x, m, scale, causal, sq, mask_mode):
    from apex_tpu.ops._common import use_jnp_fallback

    if use_jnp_fallback(x, m):
        ref_mask = None if m is None else (
            m > 0 if mask_mode == "fill" else m)
        return softmax_reference(x, ref_mask, scale, causal)
    if m is not None and _mask_4d_compatible(m.shape, x.shape):
        return _pallas_softmax_fwd4(x, m, scale=scale, causal=causal,
                                    mask_mode=mask_mode)
    x2, lead, n, k = _prep(x)
    m2 = None
    if m is not None:
        m2, _, _, _ = _prep(jnp.broadcast_to(m, x.shape)
                            .astype(jnp.float32))
    y2 = _pallas_softmax_fwd(x2, m2, scale=scale, causal=causal, sq=sq,
                             true_k=k, mask_mode=mask_mode)
    return y2[:n, :k].reshape(*lead, k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _fused_softmax(x, m, scale, causal, mask_mode=None):
    """softmax over the last dim of masked ``scale * x``. ``m`` is an
    optional fp32 mask tile applied in-kernel after the scale multiply —
    added when ``mask_mode == "add"``, or a 0/1 fill indicator replacing
    masked lanes with the finite ``_NEG`` when ``mask_mode == "fill"``
    (boolean-mask reference semantics: fully-masked rows degrade to
    uniform). Constant wrt autodiff, so the softmax backward is
    unchanged."""
    sq = x.shape[-2] if causal else 0
    return _softmax_impl(x, m, scale, causal, sq, mask_mode)


def _fs_fwd(x, m, scale, causal, mask_mode):
    sq = x.shape[-2] if causal else 0
    y = _softmax_impl(x, m, scale, causal, sq, mask_mode)
    return y, (y, m)


def _mask_cotangent(y, g, m, mask_mode):
    """d loss / d additive-mask. The mask enters as ``scale*x + m``, so
    its cotangent is the softmax backward WITHOUT the scale factor,
    summed back over the mask's broadcast axes. "fill" masks are 0/1
    indicators (boolean origin) — no meaningful cotangent."""
    from apex_tpu.ops._common import match_vma

    if m is None or mask_mode != "add":
        return None
    yf = y.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    dot = jnp.sum(gf * yf, axis=-1, keepdims=True)
    dm = yf * (gf - dot)
    mshape = (1,) * (dm.ndim - m.ndim) + tuple(m.shape)
    axes = tuple(i for i in range(dm.ndim)
                 if mshape[i] == 1 and dm.shape[i] != 1)
    if axes:
        dm = jnp.sum(dm, axis=axes, keepdims=True)
    return match_vma(dm.reshape(m.shape).astype(m.dtype), m)


def _fs_bwd(scale, causal, mask_mode, res, g):
    from apex_tpu.ops._common import match_vma, use_jnp_fallback

    y, m = res
    dm = _mask_cotangent(y, g, m, mask_mode)
    if use_jnp_fallback(y, g):
        yf = y.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        dot = jnp.sum(gf * yf, axis=-1, keepdims=True)
        return (match_vma((scale * yf * (gf - dot)).astype(g.dtype), y),
                dm)
    y2, lead, n, k = _prep(y)
    g2, _, _, _ = _prep(g)
    dx2 = _pallas_softmax_bwd(g2, y2, scale=scale)
    return (match_vma(dx2[:n, :k].reshape(*lead, k), y), dm)


_fused_softmax.defvjp(_fs_fwd, _fs_bwd)


def scaled_softmax(x, scale: float = 1.0):
    """softmax(scale * x) (reference: ``scaled_softmax_cuda``)."""
    return _fused_softmax(x, None, float(scale), False, None)


def scaled_masked_softmax(x, mask, scale: float = 1.0,
                          causal: bool = False):
    """softmax(scale * x + mask) for a padding mask (reference:
    ``scaled_masked_softmax_cuda``). ``mask`` is boolean (True = masked,
    the reference convention) or additive float; broadcastable to x.
    Any ``scale`` (including <= 0) is supported — like the reference,
    the mask is applied after the scale multiply.

    Two kernel routes, chosen for traffic:
    - boolean mask with a scale where the large-negative fill divides
      exactly (the overwhelmingly common attention case): pre-fold
      ``fill/scale`` into x host-side — the ``where`` fuses into the
      kernel's input producer, zero extra HBM reads, and the in-kernel
      multiply restores the exact fill;
    - anything else (float masks, scale <= 0, fills that would clamp):
      pass the mask into the kernel as an additive fp32 tile applied
      after the scale — reference-order semantics at the cost of one
      extra tensor read."""
    scale = float(scale)
    if mask is None:
        return _fused_softmax(x, None, scale, causal, None)
    if (mask.dtype == jnp.bool_ and scale > 0.0
            and _NEG / scale >= float(jnp.finfo(x.dtype).min)):
        x = jnp.where(mask, jnp.asarray(_NEG / scale, x.dtype), x)
        return _fused_softmax(x, None, scale, causal, None)
    if mask.dtype == jnp.bool_:
        return _fused_softmax(x, mask.astype(jnp.float32), scale, causal,
                              "fill")
    return _fused_softmax(x, mask.astype(jnp.float32), scale, causal, "add")


def scaled_upper_triang_masked_softmax(x, scale: float = 1.0):
    """Causal softmax(scale * x) over (..., sq, sk) with sq == sk
    (reference: ``scaled_upper_triang_masked_softmax_cuda``); the causal
    mask is generated in-kernel."""
    if x.shape[-1] != x.shape[-2]:
        raise ValueError("causal softmax requires square (sq, sk) trailing dims")
    return _fused_softmax(x, None, float(scale), True, None)


def softmax_reference(x, mask=None, scale=1.0, causal=False):
    """Pure-jnp reference for tests."""
    xf = x.astype(jnp.float32) * scale
    if mask is not None:
        if mask.dtype == jnp.bool_:
            xf = jnp.where(mask, _NEG, xf)
        else:
            xf = xf + mask
    if causal:
        q = xf.shape[-2]
        kk = xf.shape[-1]
        tri = jnp.tril(jnp.ones((q, kk), bool))
        xf = jnp.where(tri, xf, _NEG)
    return jax.nn.softmax(xf, axis=-1).astype(x.dtype)
