"""Pallas TPU kernels: fused scale + mask + softmax (fwd + bwd).

Rebuild of the reference's ``csrc/megatron/scaled_masked_softmax*.cu`` and
``scaled_upper_triang_masked_softmax*.cu`` (SURVEY.md §2.2): attention-
score softmax with the scale multiply and (padding or causal) mask folded
into one pass — the op behind ``FusedScaleMaskSoftmax``
(``apex/transformer/functional``).

TPU design: rows are flattened to (N, Sk) and tiled into VMEM row blocks;
max/sum are VPU lane reductions; the causal mask is generated in-kernel
from ``broadcasted_iota`` (no mask tensor traffic, like the reference's
upper-triang variant); the key dim is padded to the 128-lane width with
``-inf``-equivalent so padded lanes contribute zero probability. Backward
uses the saved softmax output: dx = scale * y * (g - sum(g*y)).

Unlike the CUDA kernels (hard seq-len limits 16..16384, pow-2 shapes —
their ``is_kernel_available`` gate), any shape works here; the module
keeps the gate trivially true.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._common import (
    LANE,
    interpret_mode as _interpret,
    out_struct,
    round_up as _round_up,
)

_NEG = -30000.0  # large-negative fill, safe in bf16/fp32 (reference: -10000)


def _block_rows(n):
    if n >= 256:
        return 256
    return _round_up(max(n, 1), 8)


def _fwd_kernel(x_ref, y_ref, *, scale, causal, sq, true_k, padded):
    x = x_ref[:].astype(jnp.float32) * scale
    rows = x.shape[0]
    if padded:
        col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        x = jnp.where(col < true_k, x, _NEG)
    if causal:
        # global row index = block_start + local row; key col must be <= the
        # query position (row % sq when rows are (b*h*sq))
        row0 = pl.program_id(0) * rows
        local = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
        q_pos = (row0 + local) % sq
        col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        x = jnp.where(col <= q_pos, x, _NEG)
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=1, keepdims=True)
    y_ref[:] = (e / s).astype(y_ref.dtype)


def _bwd_kernel(g_ref, y_ref, dx_ref, *, scale):
    g = g_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    dot = jnp.sum(g * y, axis=1, keepdims=True)
    dx_ref[:] = (scale * y * (g - dot)).astype(dx_ref.dtype)


def _pallas_softmax_fwd(x2, *, scale, causal, sq, true_k):
    n, kpad = x2.shape
    br = _block_rows(n)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, sq=sq,
                          true_k=true_k, padded=(true_k != kpad)),
        grid=(n // br,),
        in_specs=[pl.BlockSpec((br, kpad), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((br, kpad), lambda i: (i, 0)),
        out_shape=out_struct((n, kpad), x2.dtype, x2),
        interpret=_interpret(),
    )(x2)


def _pallas_softmax_bwd(g2, y2, *, scale):
    n, kpad = g2.shape
    br = _block_rows(n)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, kpad), lambda i: (i, 0)),
            pl.BlockSpec((br, kpad), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, kpad), lambda i: (i, 0)),
        out_shape=out_struct((n, kpad), g2.dtype, g2, y2),
        interpret=_interpret(),
    )(g2, y2)


def _prep(x):
    k = x.shape[-1]
    lead = x.shape[:-1]
    n = 1
    for d in lead:
        n *= d
    x2 = x.reshape(n, k)
    kpad = _round_up(k, LANE)
    npad = _round_up(n, _block_rows(n))
    if kpad != k or npad != n:
        x2 = jnp.pad(x2, ((0, npad - n), (0, kpad - k)))
    return x2, lead, n, k


def _softmax_impl(x, scale, causal, sq):
    from apex_tpu.ops._common import use_jnp_fallback

    if use_jnp_fallback(x):
        return softmax_reference(x, None, scale, causal)
    x2, lead, n, k = _prep(x)
    y2 = _pallas_softmax_fwd(x2, scale=scale, causal=causal, sq=sq, true_k=k)
    return y2[:n, :k].reshape(*lead, k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _fused_softmax(x, scale, causal):
    sq = x.shape[-2] if causal else 0
    return _softmax_impl(x, scale, causal, sq)


def _fs_fwd(x, scale, causal):
    sq = x.shape[-2] if causal else 0
    y = _softmax_impl(x, scale, causal, sq)
    return y, y


def _fs_bwd(scale, causal, y, g):
    from apex_tpu.ops._common import match_vma, use_jnp_fallback

    if use_jnp_fallback(y, g):
        yf = y.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        dot = jnp.sum(gf * yf, axis=-1, keepdims=True)
        return (match_vma((scale * yf * (gf - dot)).astype(g.dtype), y),)
    y2, lead, n, k = _prep(y)
    g2, _, _, _ = _prep(g)
    dx2 = _pallas_softmax_bwd(g2, y2, scale=scale)
    return (match_vma(dx2[:n, :k].reshape(*lead, k), y),)


_fused_softmax.defvjp(_fs_fwd, _fs_bwd)


def scaled_softmax(x, scale: float = 1.0):
    """softmax(scale * x) (reference: ``scaled_softmax_cuda``)."""
    return _fused_softmax(x, float(scale), False)


def scaled_masked_softmax(x, mask, scale: float = 1.0):
    """softmax(scale * x + mask) for a padding mask (reference:
    ``scaled_masked_softmax_cuda``). ``mask`` is boolean (True = masked,
    the reference convention) or additive float; broadcastable to x.

    The mask is pre-folded as mask/scale so the kernel's scale multiply
    restores it exactly; that requires scale > 0 (a non-positive scale
    would flip the fill sign and *un*-mask). The reference applies mask
    after scale and so has no such constraint, but also no use for
    scale <= 0 — reject it loudly rather than mis-mask silently."""
    scale = float(scale)
    if mask is not None:
        if scale <= 0.0:
            raise ValueError(
                f"scaled_masked_softmax requires scale > 0 when a mask "
                f"is given (got {scale}): the mask is pre-divided by scale "
                "so the in-kernel multiply restores it."
            )
        if mask.dtype == jnp.bool_:
            # _NEG/scale can exceed the input dtype's range for small
            # scales (fp16 tops out at 65504); clamp to the dtype's finite
            # min so fully-masked rows stay finite (uniform prob), not NaN
            fill_val = max(_NEG / scale, float(jnp.finfo(x.dtype).min))
            x = jnp.where(mask, jnp.asarray(fill_val, x.dtype), x)
        else:
            x = x + (mask / scale).astype(x.dtype)
    return _fused_softmax(x, scale, False)


def scaled_upper_triang_masked_softmax(x, scale: float = 1.0):
    """Causal softmax(scale * x) over (..., sq, sk) with sq == sk
    (reference: ``scaled_upper_triang_masked_softmax_cuda``); the causal
    mask is generated in-kernel."""
    if x.shape[-1] != x.shape[-2]:
        raise ValueError("causal softmax requires square (sq, sk) trailing dims")
    return _fused_softmax(x, float(scale), True)


def softmax_reference(x, mask=None, scale=1.0, causal=False):
    """Pure-jnp reference for tests."""
    xf = x.astype(jnp.float32) * scale
    if mask is not None:
        if mask.dtype == jnp.bool_:
            xf = jnp.where(mask, _NEG, xf)
        else:
            xf = xf + mask
    if causal:
        q = xf.shape[-2]
        kk = xf.shape[-1]
        tri = jnp.tril(jnp.ones((q, kk), bool))
        xf = jnp.where(tri, xf, _NEG)
    return jax.nn.softmax(xf, axis=-1).astype(x.dtype)
