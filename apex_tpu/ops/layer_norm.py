"""Pallas TPU kernels: fused LayerNorm / RMSNorm forward + backward.

Rebuild of the reference's ``csrc/layer_norm_cuda_kernel.cu`` (SURVEY.md
§2.2 — an explicit north-star item): LayerNorm and RMSNorm fwd/bwd with
affine and mixed-dtype variants (low-precision activations, fp32 weights —
the ``MixedFused*`` / ``*AffineMixedDtypes`` surface).

TPU design notes:
- One grid dimension over row blocks; each kernel instance normalizes a
  ``(block_rows, H)`` tile resident in VMEM. Row statistics are plain VPU
  reductions along the lane dimension — the Welford/warp-shuffle machinery
  of the CUDA kernel exists to cope with rows spread across threads, which
  has no analog here.
- The backward kernel *recomputes* (mean, rstd) from the x tile instead of
  saving them: on TPU the recompute is two cheap VPU reductions over data
  already in VMEM, cheaper than an extra HBM round-trip — the
  rematerialization idiom (and the semantics of the reference's
  ``memory_efficient=True`` mode, which it reaches by reconstructing
  inputs).
- Backward computes dx in one pass and ACCUMULATES dgamma/dbeta in-kernel
  across the sequential row-block grid into one VMEM-resident (8, H)
  output block (constant index map) — where the CUDA
  ``cuComputeGradGammaBeta`` needs a second kernel pass over a partials
  buffer, the TPU grid's sequential execution makes the reduction free.
- All in-kernel arithmetic is fp32 regardless of I/O dtype (matching the
  CUDA kernels' float accumulators).
- H is padded to the 128-lane width by the wrapper when needed; padded
  columns are masked in-kernel and statistics divide by the true H.

On non-TPU backends the same kernels run under ``interpret=True`` so the
test suite exercises identical code paths on the 8-device CPU sim.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._common import (
    LANE,
    interpret_mode as _interpret,
    out_struct,
    round_up as _round_up,
)


def _block_rows(n_rows: int, hpad: int) -> int:
    """Row-block size, tuned per hidden size (the role of the reference's
    contrib ``fast_layer_norm`` per-hidden-size kernels): keep the fp32
    working tile near ~2 MB so VMEM holds the in/out/scratch set at any
    H — 256 rows up to H=2048, shrinking for wider rows (H=8192 -> 64
    rows) instead of blowing the ~16 MB budget."""
    budget_rows = max(2 * 1024 * 1024 // (hpad * 4), 8)
    cap = min(256, _round_up(budget_rows, 8))
    if n_rows >= cap:
        return cap
    return _round_up(max(n_rows, 1), 8)


def _stats(x, true_h, rms):
    """fp32 (mean, rstd) of the valid columns of a padded fp32 tile."""
    h = jnp.float32(true_h)
    if rms:
        mean = jnp.zeros((x.shape[0], 1), jnp.float32)
    else:
        mean = jnp.sum(x, axis=1, keepdims=True) / h
    centered = x - mean
    return mean, centered


def _mask_tile(x, true_h):
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    return jnp.where(col < true_h, x, 0.0)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, *, eps, true_h, rms, padded):
    x = x_ref[:].astype(jnp.float32)
    if padded:
        x = _mask_tile(x, true_h)
    h = jnp.float32(true_h)
    mean, centered = _stats(x, true_h, rms)
    if padded:
        centered = _mask_tile(centered, true_h)
    var = jnp.sum(centered * centered, axis=1, keepdims=True) / h
    rstd = jax.lax.rsqrt(var + eps)
    y = centered * rstd * w_ref[:].astype(jnp.float32)
    if b_ref is not None:
        y = y + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)


def _fwd_kernel_b(x_ref, w_ref, b_ref, y_ref, **kw):
    _fwd_kernel(x_ref, w_ref, b_ref, y_ref, **kw)


def _fwd_kernel_nb(x_ref, w_ref, y_ref, **kw):
    _fwd_kernel(x_ref, w_ref, None, y_ref, **kw)


def _bwd_kernel(g_ref, x_ref, w_ref, dx_ref, dw_ref, db_ref, dw_s, db_s,
                *, eps, true_h, rms, padded):
    i = pl.program_id(0)
    g = g_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    if padded:
        g = _mask_tile(g, true_h)
        x = _mask_tile(x, true_h)
    h = jnp.float32(true_h)

    mean, centered = _stats(x, true_h, rms)
    if padded:
        centered = _mask_tile(centered, true_h)
    var = jnp.sum(centered * centered, axis=1, keepdims=True) / h
    rstd = jax.lax.rsqrt(var + eps)
    xhat = centered * rstd
    wg = g * w

    # dgamma/dbeta accumulate IN-KERNEL across the sequential row-block
    # grid in VMEM scratch, flushed to the (8, H) outputs at the last
    # step — no (grid*8, H) partial buffer in HBM, no host-side
    # reduction over it (round-3 design summed grid*8 rows outside).
    # Scratch (not a revisited output block) keeps the accumulator out
    # of Mosaic's output-DMA pipeline: accumulating directly into a
    # constant-index output block measured 0.66x (inter-step
    # read-after-write stalls), scratch restores full overlap. Partials
    # stay 8 sublanes tall (the fp32 min tile): each block's (br, H)
    # product folds to (br/8, 8, H) -> sum over axis 0, and the caller
    # sums the final 8 rows.
    br = x.shape[0]
    dw_p = jnp.sum((g * xhat).reshape(br // 8, 8, x.shape[1]), axis=0)
    db_p = jnp.sum(g.reshape(br // 8, 8, x.shape[1]), axis=0)

    @pl.when(i == 0)
    def _init():
        dw_s[:] = dw_p
        db_s[:] = db_p

    @pl.when(i > 0)
    def _acc():
        dw_s[:] = dw_s[:] + dw_p
        db_s[:] = db_s[:] + db_p

    @pl.when(i == pl.num_programs(0) - 1)
    def _flush():
        dw_ref[:] = dw_s[:]
        db_ref[:] = db_s[:]

    # dx (standard fused layernorm backward)
    c1 = jnp.sum(wg * xhat, axis=1, keepdims=True) / h
    if rms:
        dx = (wg - xhat * c1) * rstd
    else:
        c2 = jnp.sum(wg, axis=1, keepdims=True) / h
        dx = (wg - xhat * c1 - c2) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------

def _pallas_forward(x2, weight, bias, *, eps, true_h, rms):
    n, hpad = x2.shape
    br = _block_rows(n, hpad)
    kernel = functools.partial(
        _fwd_kernel_nb if bias is None else _fwd_kernel_b,
        eps=eps, true_h=true_h, rms=rms, padded=(true_h != hpad),
    )
    in_specs = [
        pl.BlockSpec((br, hpad), lambda i: (i, 0)),
        pl.BlockSpec((hpad,), lambda i: (0,)),
    ]
    args = [x2, weight]
    if bias is not None:
        in_specs.append(pl.BlockSpec((hpad,), lambda i: (0,)))
        args.append(bias)
    return pl.pallas_call(
        kernel,
        grid=(n // br,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((br, hpad), lambda i: (i, 0)),
        out_shape=out_struct((n, hpad), x2.dtype, *args),
        interpret=_interpret(),
    )(*args)


def _pallas_backward(g2, x2, weight, *, eps, true_h, rms):
    n, hpad = x2.shape
    br = _block_rows(n, hpad)
    grid = n // br
    kernel = functools.partial(
        _bwd_kernel, eps=eps, true_h=true_h, rms=rms, padded=(true_h != hpad),
    )
    dx, dw_part, db_part = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, hpad), lambda i: (i, 0)),
            pl.BlockSpec((br, hpad), lambda i: (i, 0)),
            pl.BlockSpec((hpad,), lambda i: (0,)),
        ],
        out_specs=(
            pl.BlockSpec((br, hpad), lambda i: (i, 0)),
            # constant index maps: the (8, H) accumulators stay VMEM-
            # resident across the whole sequential grid (see _bwd_kernel)
            pl.BlockSpec((8, hpad), lambda i: (0, 0)),
            pl.BlockSpec((8, hpad), lambda i: (0, 0)),
        ),
        out_shape=(
            out_struct((n, hpad), g2.dtype, g2, x2, weight),
            out_struct((8, hpad), jnp.float32, g2, x2, weight),
            out_struct((8, hpad), jnp.float32, g2, x2, weight),
        ),
        scratch_shapes=[
            pltpu.VMEM((8, hpad), jnp.float32),
            pltpu.VMEM((8, hpad), jnp.float32),
        ],
        interpret=_interpret(),
    )(g2, x2, weight)
    return dx, dw_part.sum(axis=0), db_part.sum(axis=0)


# ---------------------------------------------------------------------------
# public functional API (custom_vjp)
# ---------------------------------------------------------------------------

def _prep(x, weight, bias):
    """Flatten leading dims; pad H to the lane width and N to the row-block
    size (padded rows are zeros: their stats are finite and their outputs
    are sliced away; in backward their zero grads contribute nothing)."""
    h = x.shape[-1]
    lead = x.shape[:-1]
    n = 1
    for d in lead:
        n *= d
    x2 = x.reshape(n, h)
    hpad = _round_up(h, LANE)
    npad = _round_up(n, _block_rows(n, hpad))
    if hpad != h or npad != n:
        x2 = jnp.pad(x2, ((0, npad - n), (0, hpad - h)))
        weight = jnp.pad(weight, (0, hpad - h))
        if bias is not None:
            bias = jnp.pad(bias, (0, hpad - h))
    return x2, weight, bias, lead, n, h, hpad


# Widest hidden size the Pallas training path wins at (v5e, marginal
# timing 2026-07-31): at H=1024 the kernels match XLA fusion at roofline
# and win ~3 ms/step at the BERT-large headline (in-kernel dgamma
# accumulation); at H in {4096, 8192} the lane-dim reductions over wide
# rows lose to XLA's fusion by ~1.4x — wide rows dispatch to the jnp
# formula (XLA autodiff) instead.
_PALLAS_MAX_H = 2048


def _fwd_impl(x, weight, bias, eps, rms):
    from apex_tpu.ops._common import use_jnp_fallback

    if use_jnp_fallback(x, weight, bias) or x.shape[-1] > _PALLAS_MAX_H:
        if rms:
            return rms_norm_reference(x, weight, eps)
        return layer_norm_reference(x, weight, bias, eps)
    x2, w2, b2, lead, n, h, hpad = _prep(x, weight, bias)
    y2 = _pallas_forward(x2, w2, b2, eps=eps, true_h=h, rms=rms)
    return y2[:n, :h].reshape(*lead, h)


def _bwd_jnp(g, x, weight, eps, rms):
    """Same math as _bwd_kernel, in plain jnp (interpreter fallback)."""
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    w = weight.astype(jnp.float32)
    if rms:
        mean = 0.0
    else:
        mean = xf.mean(-1, keepdims=True)
    centered = xf - mean
    var = (centered * centered).mean(-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = centered * rstd
    wg = gf * w
    c1 = (wg * xhat).mean(-1, keepdims=True)
    if rms:
        dx = (wg - xhat * c1) * rstd
    else:
        c2 = wg.mean(-1, keepdims=True)
        dx = (wg - xhat * c1 - c2) * rstd
    reduce_axes = tuple(range(x.ndim - 1))
    dw = jnp.sum(gf * xhat, axis=reduce_axes)
    db = jnp.sum(gf, axis=reduce_axes)
    return dx.astype(x.dtype), dw, db


def _bwd_impl(g, x, weight, eps, rms):
    from apex_tpu.ops._common import use_jnp_fallback

    if use_jnp_fallback(g, x, weight) or x.shape[-1] > _PALLAS_MAX_H:
        return _bwd_jnp(g, x, weight, eps, rms)
    x2, w2, _, lead, n, h, hpad = _prep(x, weight, None)
    g2 = g.reshape(n, h)
    npad = x2.shape[0]
    if hpad != h or npad != n:
        g2 = jnp.pad(g2, ((0, npad - n), (0, hpad - h)))
    dx2, dw, db = _pallas_backward(g2, x2, w2, eps=eps, true_h=h, rms=rms)
    return dx2[:n, :h].reshape(*lead, h), dw[:h], db[:h]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_layer_norm_affine(x, weight, bias, eps: float = 1e-5,
                            memory_efficient: bool = True):
    """LayerNorm with affine transform, Pallas-fused fwd+bwd.

    Reference surface: ``FusedLayerNormAffineFunction`` /
    ``FusedLayerNormAffineMixedDtypesFunction``
    (``apex/normalization/fused_layer_norm.py``). Mixed-dtype by
    construction: any floating x with fp32 (or matching) weight/bias;
    output dtype follows x. ``memory_efficient`` is accepted for parity —
    the TPU backward always recomputes statistics (see module docstring).

    Mode-dependent kernel selection (docs/kernels.md measured table):
    this primal body runs only when the call is NOT being differentiated
    (inference/serving), where letting XLA fuse the jnp formula into its
    neighbors beats the standalone Pallas kernel by ~9 ms at BERT-large
    shapes (a separate kernel is an HBM fusion barrier). Under autodiff,
    custom_vjp dispatches to ``_ln_affine_fwd`` instead — the Pallas
    fwd+bwd pair, the measured-best training combination.

    Numerical parity note: the two bodies agree to float rounding but are
    NOT bitwise identical (jnp two-pass moments vs the kernel's Welford
    accumulation in a different summation order), so the same call can
    yield bitwise-different outputs depending on differentiation context.
    Train-vs-eval logit-matching tests must compare with a dtype-scaled
    tolerance, not exact equality.
    """
    return layer_norm_reference(x, weight, bias, eps)


# Training-path forward selection (round 5). Measured on v5e at the
# (8192, 1024) transformer-layer shape (LN between GEMMs, fwd+bwd,
# marginal timing): XLA-fused jnp fwd + Pallas bwd = 5.19 ms/call vs
# 7.01 stock-XLA and 7.23 all-Pallas — the standalone Pallas fwd kernel
# is an HBM fusion barrier between the LN and the GEMM that consumes
# it, while the Pallas BWD pair (one-pass dx + in-kernel dgamma/dbeta
# accumulation, recomputed stats) beats XLA's save-xhat autodiff. The
# "pallas" setting keeps the all-Pallas fwd for A/B runs.
def _ln_fwd_mode() -> str:
    # read per TRACE (not per import) so APEX_TPU_LN_FWD set mid-process
    # affects subsequent jit traces; already-compiled programs keep the
    # mode they were traced with (the jit cache does not key on env)
    import os

    return os.environ.get("APEX_TPU_LN_FWD", "xla")


def _ln_affine_fwd(x, weight, bias, eps, memory_efficient):
    if _ln_fwd_mode() == "pallas":
        return _fwd_impl(x, weight, bias, eps, rms=False), (x, weight)
    return layer_norm_reference(x, weight, bias, eps), (x, weight)


def _ln_affine_bwd(eps, memory_efficient, res, g):
    from apex_tpu.ops._common import match_vma

    x, weight = res
    dx, dw, db = _bwd_impl(g, x, weight, eps, rms=False)
    return (
        match_vma(dx, x),
        match_vma(dw.astype(weight.dtype), weight),
        match_vma(db.astype(weight.dtype), weight),
    )


fused_layer_norm_affine.defvjp(_ln_affine_fwd, _ln_affine_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_rms_norm_affine(x, weight, eps: float = 1e-5,
                          memory_efficient: bool = True):
    """RMSNorm with affine transform, Pallas-fused fwd+bwd.

    Reference surface: ``FusedRMSNormAffineFunction`` /
    ``FusedRMSNormAffineMixedDtypesFunction``. Same mode-dependent
    kernel selection as :func:`fused_layer_norm_affine`: jnp (XLA-fused)
    when not differentiating, Pallas fwd+bwd under autodiff — and the
    same parity caveat: the two bodies agree to rounding, not bitwise."""
    return rms_norm_reference(x, weight, eps)


def _rms_affine_fwd(x, weight, eps, memory_efficient):
    if _ln_fwd_mode() == "pallas":
        return _fwd_impl(x, weight, None, eps, rms=True), (x, weight)
    return rms_norm_reference(x, weight, eps), (x, weight)


def _rms_affine_bwd(eps, memory_efficient, res, g):
    from apex_tpu.ops._common import match_vma

    x, weight = res
    dx, dw, _ = _bwd_impl(g, x, weight, eps, rms=True)
    return match_vma(dx, x), match_vma(dw.astype(weight.dtype), weight)


fused_rms_norm_affine.defvjp(_rms_affine_fwd, _rms_affine_bwd)


def fused_layer_norm(x, normalized_shape=None, eps: float = 1e-5):
    """Elementwise-affine-free LayerNorm (reference: ``fused_layer_norm``)."""
    h = x.shape[-1]
    w = jnp.ones((h,), jnp.float32)
    b = jnp.zeros((h,), jnp.float32)
    return fused_layer_norm_affine(x, w, b, eps)


def fused_rms_norm(x, normalized_shape=None, eps: float = 1e-5):
    """Affine-free RMSNorm (reference: ``fused_rms_norm``)."""
    h = x.shape[-1]
    w = jnp.ones((h,), jnp.float32)
    return fused_rms_norm_affine(x, w, eps)


# Pure-jnp references (used by tests and as a documented fallback).

def layer_norm_reference(x, weight, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm_reference(x, weight, eps=1e-5):
    xf = x.astype(jnp.float32)
    ms = (xf * xf).mean(-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + eps) * weight.astype(jnp.float32)
    return y.astype(x.dtype)
