"""Fused Pallas read kernel for paged attention (docs/serving.md).

The serving decode/prefill read chain —
:func:`apex_tpu.ops.flash_attention.paged_prefill_attention` — is a
gather (block table -> pool rows), a position mask, an fp32 softmax,
and a weighted sum. The composed XLA form materializes the gathered
``[B, ctx_max, H, D]`` K and V (two full copies of every resident
token's cache, per layer, per dispatch) before attending. This module
fuses the whole chain into ONE ``pallas_call``: the kernel walks the
block table with the scalar-prefetch pattern (the table rides in SMEM
and the ``BlockSpec`` index map picks which pool block each grid step
streams into VMEM), so gathered K/V tiles live only in VMEM and HBM
traffic drops to one pass over the pool rows the table actually names
plus the ``[B, C, H, D]`` output.

READ SIDE ONLY, by design: the BENCH_r01 lesson recorded in ROADMAP.md
is that Pallas TPU has no scatter lowering — the K/V *writes*
(:func:`apex_tpu.serving.kv_cache.write_kv`) stay in XLA, whose
``scatter mode="drop"`` is exactly right for them, and the kernel
reads what XLA wrote.

Numerical contract (certified in tests/test_kv_memory.py, interpret
mode): the kernel performs the SAME primitive sequence as the XLA
chain — fp32 einsum scores, the shared finite ``FILL`` mask,
``jax.nn.softmax`` over the full context row (NOT an online-softmax
recurrence: scores for one batch lane accumulate in a VMEM scratch
across the table walk and normalize once), one fp32 einsum weighted
sum — so the fp path is BIT-IDENTICAL to the XLA fallback, decode
(C == 1) included. Two structural choices are load-bearing for that:
the grid is ``(B, num_table_entries)`` with ALL heads per kernel step,
and both contractions are head-batched einsums — per-head 2-D
matmuls (or a per-head grid axis) lower the C == 1 GEMV with a
different XLA:CPU reduction order and drift by 1 ulp. Quantized pools
(int8/fp8 + per-row scales) dequantize inside the kernel, tile by
tile, and certify against the XLA dequantizing chain to tight
tolerance.

Selection: ``paged_prefill_attention(..., use_pallas=True)`` or the
``APEX_PAGED_ATTENTION_PALLAS=1`` env flag (read at trace time); the
static shape gate (:func:`pallas_paged_read_supported`) keeps the XLA
chain as the universal fallback — interpret mode (every non-TPU
backend) always qualifies, native TPU additionally needs lane/sublane-
tileable blocks and a VMEM-feasible score scratch.

SINGLE-DEVICE ONLY: ``pallas_call`` has no SPMD partitioning rule, so
the kernel cannot run over a GSPMD-sharded pool (docs/serving.md
"Mesh sharding" — the engine rejects the env flag when its mesh's
``model`` axis is > 1, where the XLA chain partitions collective-free
instead; a future shard_map-wrapped variant could lift this).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._common import interpret_mode as _interpret

# the shared finite masked fill (ops/flash_attention.FILL) — redeclared
# here to avoid a circular import; the equality is pinned by a test
FILL = -30000.0

_ENV_FLAG = "APEX_PAGED_ATTENTION_PALLAS"

# native-TPU VMEM budget for the kernel's scratch (score buffer +
# gathered V); shapes past it fall back to the XLA chain
_VMEM_SCRATCH_BUDGET = 8 * 1024 * 1024


def pallas_paged_read_wanted(use_pallas=None) -> bool:
    """Whether the caller asked for the fused kernel: an explicit
    ``use_pallas`` wins; ``None`` consults the env flag (read at trace
    time — set it before the engine compiles its programs)."""
    if use_pallas is not None:
        return bool(use_pallas)
    return os.environ.get(_ENV_FLAG, "").strip().lower() in (
        "1", "true", "on", "yes")


def pallas_paged_read_supported(k_pages, num_table_entries=None,
                                chunk=None) -> bool:
    """Static shape gate for the native kernel: pool rows must be
    Mosaic-tileable ((bs, H*D) tiles — lane dim a 128 multiple,
    sublane a multiple of 8) and the full-softmax scratch must fit
    VMEM. Interpret mode (every non-TPU backend) has no tiling
    constraints and always qualifies — which is also what lets the
    CPU equivalence tests drive every shape the engine uses."""
    if _interpret():
        return True
    _, bs, H, D = k_pages.shape
    if (H * D) % 128 != 0 or bs % 8 != 0:
        return False
    if num_table_entries is not None and chunk is not None:
        ctx = num_table_entries * bs
        scratch = 4 * (H * chunk * ctx + ctx * H * D)
        if scratch > _VMEM_SCRATCH_BUDGET:
            return False
    return True


def _read_kernel(tbl_ref, ctx_ref, qpos_ref, q_ref, k_ref, v_ref, *rest,
                 scale, bs, C, H, D, M, decode, quant):
    """One (batch b, table step i) grid step: stream pool block
    ``tbl[b, i]``'s full rows (all heads) into VMEM, score them
    against the lane's whole query chunk into the score scratch, park
    the (dequantized) V rows in the value scratch; the LAST table step
    normalizes the full context row and emits the output —
    full-softmax semantics, accumulated across the walk, so the math
    (and on the fp path the bits) equals the composed XLA chain."""
    if quant:
        ks_ref, vs_ref, o_ref, s_buf, v_buf = rest
    else:
        ks_ref, vs_ref = None, None
        o_ref, s_buf, v_buf = rest
    b = pl.program_id(0)
    i = pl.program_id(1)

    q = q_ref[0].reshape(C, H, D).astype(jnp.float32)
    k = k_ref[0].reshape(bs, H, D).astype(jnp.float32)
    v = v_ref[0].reshape(bs, H, D).astype(jnp.float32)
    if quant:
        k = k * ks_ref[0][:, :, None]             # (bs, H) scale rows
        v = v * vs_ref[0][:, :, None]
    s = jnp.einsum("qhd,khd->hqk", q, k,
                   preferred_element_type=jnp.float32) * scale

    # the block's absolute key positions; same mask algebra as the XLA
    # chain (decode: the collapsed single comparison; prefill/verify:
    # causal-by-absolute-position AND the context-length bound)
    kpos = i * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
    ctx = ctx_ref[b]
    if decode:
        visible = jnp.broadcast_to(kpos < ctx, (C, bs))
    else:
        qpos = qpos_ref[b, :][:, None]            # (C, 1)
        visible = (kpos <= qpos) & (kpos < ctx)
    s = jnp.where(visible[None], s, FILL)         # (H, C, bs)
    s_buf[:, :, pl.ds(i * bs, bs)] = s
    v_buf[pl.ds(i * bs, bs), :] = v.reshape(bs, H * D)

    @pl.when(i == M - 1)
    def _finish():
        p = jax.nn.softmax(s_buf[:], axis=-1)     # (H, C, M*bs)
        out = jnp.einsum("hqk,khd->qhd", p,
                         v_buf[:].reshape(M * bs, H, D),
                         preferred_element_type=jnp.float32)
        o_ref[0] = out.reshape(C, H * D).astype(o_ref.dtype)


def paged_read_attention(q, k_pages, v_pages, block_tables, q_positions,
                         context_lens, scale: float = 1.0,
                         k_scales=None, v_scales=None):
    """The fused read chain: same signature semantics as
    :func:`apex_tpu.ops.flash_attention.paged_prefill_attention`
    (``q_positions=None`` = the decode fast path). Callers normally
    reach this THROUGH ``paged_prefill_attention(use_pallas=...)``,
    which owns the flag/gate/fallback arbitration."""
    B, C, H, D = q.shape
    N, bs = k_pages.shape[0], k_pages.shape[1]
    M = block_tables.shape[1]
    quant = k_scales is not None
    decode = q_positions is None

    # the pool's trailing (H, D) collapses to H*D so one block's rows
    # are a contiguous tile (metadata reshape, no copy); the table
    # clips exactly like the XLA chain (device convention:
    # out-of-bounds id for unmapped entries — their rows are read but
    # masked by context_lens)
    tbl = jnp.minimum(block_tables, N - 1).astype(jnp.int32)
    ctx = jnp.asarray(context_lens, jnp.int32)
    qpos = (jnp.zeros((B, C), jnp.int32) if decode
            else jnp.asarray(q_positions, jnp.int32))

    kernel = functools.partial(
        _read_kernel, scale=scale, bs=bs, C=C, H=H, D=D, M=M,
        decode=decode, quant=quant)
    # index maps see the scalar-prefetch refs after the grid indices:
    # the table ref IS the gather — grid step (b, i) streams pool
    # block tbl[b, i]'s rows
    in_specs = [
        pl.BlockSpec((1, C, H * D), lambda b, i, t, c, p: (b, 0, 0)),
        pl.BlockSpec((1, bs, H * D),
                     lambda b, i, t, c, p: (t[b, i], 0, 0)),
        pl.BlockSpec((1, bs, H * D),
                     lambda b, i, t, c, p: (t[b, i], 0, 0)),
    ]
    inputs = [q.reshape(B, C, H * D), k_pages.reshape(N, bs, H * D),
              v_pages.reshape(N, bs, H * D)]
    if quant:
        in_specs += [
            pl.BlockSpec((1, bs, H),
                         lambda b, i, t, c, p: (t[b, i], 0, 0)),
            pl.BlockSpec((1, bs, H),
                         lambda b, i, t, c, p: (t[b, i], 0, 0)),
        ]
        inputs += [k_scales.astype(jnp.float32),
                   v_scales.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, C, H * D),
                               lambda b, i, t, c, p: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, C, M * bs), jnp.float32),
            pltpu.VMEM((M * bs, H * D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, C, H * D), q.dtype),
        interpret=_interpret(),
    )(tbl, ctx, qpos, *inputs)
    return out.reshape(B, C, H, D)
