"""Fused multi-tensor ops — the TPU-native ``amp_C`` kernel set.

Rebuild of the reference's ``csrc/multi_tensor_*.cu`` family (SURVEY.md
§2.2): one fused pass over *lists* of tensors for scaling/unscaling with
inf/nan detection, L2 norms, and every optimizer update.

TPU design: the CUDA ``multi_tensor_apply.cuh`` mechanism (chunking device
pointers into kernel-arg structs, ≤36 tensor addrs per launch, 320 blocks)
exists to amortize *kernel-launch* overhead, which has no analog under
XLA: everything below lives inside one jitted step, so the elementwise
update chain for every leaf fuses into a handful of HBM-bandwidth-bound
kernels with zero dispatch overhead regardless of the number of parameter
tensors. The math is therefore done **per leaf, in the leaf's natural
shape** (fp32 working precision):

- Model leaves are naturally 2-D matrices — already tile-friendly for the
  TPU's (8, 128) layout.
- An earlier design raveled every list into one giant 1-D fp32 buffer
  ("flat-buffer" analog of ``apex_C.flatten``). That was a mistake on real
  hardware: XLA horizontally packs the paired elementwise output streams
  (e.g. Adam's m/v EMAs) of huge same-shaped 1-D values into an ``[N, 2]``
  op, and the TPU tiled layout pads the size-2 minor dimension to 128 — a
  64x memory blowup (a 94 GB allocation at BERT-large scale). The flat
  concat also costs a full extra HBM round-trip per list per call. Per-leaf
  avoids both; XLA still fuses each leaf's chain into one pass.

Per-tensor semantics (LAMB trust ratios, NovoGrad per-layer moments) use
per-leaf reductions; XLA concatenates these small reductions into a
handful of fusions.

Op signatures follow the reference convention
``op(chunk_size, noop_flag, tensor_lists, *args)`` so
``multi_tensor_applier`` call sites port verbatim. ``noop_flag`` is a
traced bool (or None): when truthy, outputs are the unmodified inputs —
the functional translation of the CUDA kernels' early-exit on
``*noop_flag != 0``. Ops that detect non-finite values return an updated
flag.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


def _f32(t: Array) -> Array:
    return t.astype(jnp.float32)


def stochastic_round(x: Array, dtype, key) -> Array:
    """Stochastically round fp32 ``x`` to ``dtype``: add uniform noise
    below the target precision, truncate. E[round(x)] == x, which
    keeps low-precision EMA state (optimizer moments) from stalling when
    per-step increments round-to-nearest to zero — the reason the
    bf16-moments optimizer tier exists. Non-finite values pass through
    unperturbed. fp32 targets return a plain cast (no-op rounding).

    Integer targets (the quantized KV-cache path,
    :mod:`apex_tpu.serving.kv_cache`): ``floor(x + U[0, 1))`` — the same
    unbiased-truncation construction in value space instead of bit
    space — clamped to the SYMMETRIC integer range (``[-127, 127]`` for
    int8, so a dequantized magnitude never exceeds its scale's design
    max). Non-finite values round to 0 (integers have no non-finite
    encoding; the KV quantizer never feeds them)."""
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        lim = float(min(-(info.min + 1), info.max))
        u = jax.random.uniform(key, x.shape, jnp.float32)
        r = jnp.clip(jnp.floor(x.astype(jnp.float32) + u), -lim, lim)
        return jnp.where(jnp.isfinite(x), r, 0.0).astype(dtype)
    if dtype == jnp.float32:
        return x.astype(dtype)
    if dtype != jnp.bfloat16:
        raise NotImplementedError(
            f"stochastic_round supports bf16/f32/integer targets, "
            f"got {dtype}")
    bits = jax.random.bits(key, x.shape, jnp.uint16).astype(jnp.uint32)
    xi = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    trunc = jax.lax.bitcast_convert_type(
        (xi + bits) & jnp.uint32(0xFFFF0000), jnp.float32)
    # The uint32 add can carry into the exponent: finite values in the
    # last bf16 ULP below bf16-max (or between bf16-max and fp32-max)
    # would round to +/-inf, and an inf written into an EMA moment is
    # sticky — it permanently zeroes that parameter's updates (ADVICE
    # r5 #1). Clamp to the finite bf16 range; saturation at the max is
    # the standard round-to-nearest overflow behavior for these values.
    bf16_max = jnp.float32(jnp.finfo(jnp.bfloat16).max)
    trunc = jnp.clip(trunc, -bf16_max, bf16_max)
    return jnp.where(jnp.isfinite(x), trunc, x).astype(dtype)


def _check_parallel(tensor_lists) -> None:
    """Parallel tensor lists must have equal length (the flat-buffer design
    failed loudly on mismatch; per-leaf zips would truncate silently)."""
    lengths = {len(l) for l in tensor_lists}
    if len(lengths) > 1:
        raise ValueError(
            f"parallel tensor lists have mismatched lengths: "
            f"{[len(l) for l in tensor_lists]}")


def _all_finite(leaves: Sequence[Array]):
    """One bool: every element of every leaf is finite (vacuously True)."""
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack([jnp.all(jnp.isfinite(t)) for t in leaves]).all()


def _apply_noop(noop_flag, new_lists, old_lists):
    if noop_flag is None:
        return new_lists
    return [
        [jnp.where(noop_flag, o, n) for n, o in zip(new, old)]
        for new, old in zip(new_lists, old_lists)
    ]


# ---------------------------------------------------------------------------
# scale / axpby / l2norm  (csrc/multi_tensor_{scale,axpby,l2norm}.cu)
# ---------------------------------------------------------------------------

def _scaled_with_flag(noop_flag, tensor_lists, scale):
    """Shared core of the scale-family ops: fp32-scale the first list,
    detect non-finite results, fold into the incoming noop flag, and
    revert outputs to the inputs when that flag was already set (the CUDA
    kernels' early-exit). Returns ``(scaled_f32, outs, flag_out)``."""
    _check_parallel(tensor_lists)
    src = tensor_lists[0]
    out_dtypes = [t.dtype for t in tensor_lists[-1]]
    scaled = [_f32(t) * jnp.float32(scale) for t in src]
    found = jnp.logical_not(_all_finite(scaled))
    flag_out = found if noop_flag is None else jnp.logical_or(noop_flag, found)
    outs = [o.astype(d) for o, d in zip(scaled, out_dtypes)]
    if noop_flag is not None:
        outs = [jnp.where(noop_flag, s.astype(d), o)
                for s, o, d in zip(src, outs, out_dtypes)]
    return scaled, outs, flag_out


def multi_tensor_scale(chunk_size, noop_flag, tensor_lists, scale):
    """out = in * scale, detecting non-finite values in one fused pass.

    Reference: ``amp_C.multi_tensor_scale`` — the hot op of loss unscaling
    (SURVEY.md §3.2). Returns ``(out_list, noop_flag_out)``.
    """
    _, outs, flag_out = _scaled_with_flag(noop_flag, tensor_lists, scale)
    return outs, flag_out


def multi_tensor_axpby(chunk_size, noop_flag, tensor_lists, a, b):
    """out = a*x + b*y over parallel lists (``amp_C.multi_tensor_axpby``)."""
    _check_parallel(tensor_lists)
    x_list, y_list = tensor_lists[0], tensor_lists[1]
    out_dtypes = [t.dtype for t in tensor_lists[-1]]
    out = [jnp.float32(a) * _f32(x) + jnp.float32(b) * _f32(y)
           for x, y in zip(x_list, y_list)]
    found = jnp.logical_not(_all_finite(out))
    flag_out = found if noop_flag is None else jnp.logical_or(noop_flag, found)
    outs = [o.astype(d) for o, d in zip(out, out_dtypes)]
    (outs,) = _apply_noop(noop_flag, [outs], [tensor_lists[-1]])
    return outs, flag_out


def multi_tensor_l2norm(chunk_size, noop_flag, tensor_lists, per_tensor=False):
    """L2 norms: global and optionally per-tensor
    (``amp_C.multi_tensor_l2norm``; feeds LAMB stage 1 and clip_grad).

    Per-tensor squared norms are small per-leaf reductions; the global norm
    is their sum — all fused by XLA into one pass over the data.
    """
    tensors = tensor_lists[0]
    sq = jnp.stack([jnp.sum(jnp.square(_f32(t))) for t in tensors])
    global_norm = jnp.sqrt(jnp.sum(sq))
    if per_tensor:
        return global_norm, jnp.sqrt(sq)
    return global_norm, None


def multi_tensor_l2norm_scale(chunk_size, noop_flag, tensor_lists, scale,
                              per_tensor=False):
    """Fused scale + L2 norm. RETURN SHAPE DIVERGES FROM THE REFERENCE
    BINDING: this returns the 4-tuple ``(out_list, global_norm,
    per_tensor_norms_or_None, noop_flag_out)``, while
    ``amp_C.multi_tensor_l2norm_scale`` returns ``(norm, per_tensor)``
    and writes outputs in place — functional JAX has no in-place write,
    so porters unpacking two values must rebind ``(_, norm, per, _)``.

    Semantics: ``out = in * scale`` while reducing the L2 norms of the
    *scaled* values in the same pass (reference
    ``csrc/multi_tensor_l2norm_scale_kernel.cu`` (U) — used by the
    distributed LAMB path to unscale gradients and get their norms with
    one read of HBM; here the scale, square, and sum fuse under XLA the
    same way).
    """
    scaled, outs, flag_out = _scaled_with_flag(noop_flag, tensor_lists, scale)
    sq = jnp.stack([jnp.sum(jnp.square(s)) for s in scaled]) if scaled else (
        jnp.zeros((0,), jnp.float32))
    if noop_flag is not None:
        # early-exit contract: under a set incoming flag the CUDA kernel
        # never writes its zero-initialized norm buffer, so the norms must
        # report 0 — not the (possibly non-finite) skipped computation
        sq = jnp.where(noop_flag, jnp.zeros_like(sq), sq)
    global_norm = jnp.sqrt(jnp.sum(sq))
    per = jnp.sqrt(sq) if per_tensor else None
    return outs, global_norm, per, flag_out


# ---------------------------------------------------------------------------
# Adam / Adagrad  (csrc/multi_tensor_adam.cu, multi_tensor_adagrad.cu)
# ---------------------------------------------------------------------------

ADAM_MODE_L2 = 0       # classic Adam: wd folded into the gradient
ADAM_MODE_ADAMW = 1    # decoupled weight decay


def multi_tensor_adam(
    chunk_size,
    noop_flag,
    tensor_lists,
    lr,
    beta1,
    beta2,
    eps,
    step,
    mode,
    bias_correction,
    weight_decay,
    sr_key=None,
):
    """Fused Adam/AdamW update over [grads, params, exp_avg, exp_avg_sq]
    (+ optional trailing fp32 master-param list, mirroring the reference's
    ``master_weights`` variant).

    ``sr_key`` (beyond the reference binding): a PRNG key enabling
    stochastic rounding of the moment writes — required for unbiased
    EMAs when the m/v lists are stored in bf16 (the round-5 low-HBM
    optimizer tier); with fp32 moments it is a no-op.

    Returns ``([new_params, new_m, new_v] (+ [new_master]), )`` in fp32
    working precision cast back to the input dtypes.
    """
    _check_parallel(tensor_lists)
    has_master = len(tensor_lists) == 5
    g_list, p_list, m_list, v_list = tensor_lists[:4]
    master_list = tensor_lists[4] if has_master else None
    # With master weights, the fp32 master buffer is the source of truth.
    src_list = master_list if has_master else p_list

    if bias_correction:
        bc1 = 1.0 - beta1 ** step
        bc2 = 1.0 - beta2 ** step
    else:
        bc1 = bc2 = 1.0

    def round_to(x, like, key):
        if key is not None and like.dtype != jnp.float32:
            return stochastic_round(x, like.dtype, key)
        return x.astype(like.dtype)

    new_p, new_m, new_v, new_master = [], [], [], []
    for i in range(len(g_list)):
        g = _f32(g_list[i])
        p = _f32(src_list[i])
        m = _f32(m_list[i])
        v = _f32(v_list[i])
        if mode == ADAM_MODE_L2 and weight_decay != 0.0:
            g = g + weight_decay * p
        m = beta1 * m + (1.0 - beta1) * g
        v = beta2 * v + (1.0 - beta2) * g * g
        update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
        if mode == ADAM_MODE_ADAMW and weight_decay != 0.0:
            update = update + weight_decay * p
        stepped = p - lr * update
        new_p.append(stepped.astype(p_list[i].dtype))
        km = kv = None
        if sr_key is not None:
            km = jax.random.fold_in(sr_key, 2 * i)
            kv = jax.random.fold_in(sr_key, 2 * i + 1)
        new_m.append(round_to(m, m_list[i], km))
        new_v.append(round_to(v, v_list[i], kv))
        if has_master:
            new_master.append(stepped.astype(master_list[i].dtype))

    old = [p_list, m_list, v_list]
    new = [new_p, new_m, new_v]
    if has_master:
        new.append(new_master)
        old.append(master_list)
    return _apply_noop(noop_flag, new, old)


def multi_tensor_adagrad(chunk_size, noop_flag, tensor_lists, lr, eps, mode, weight_decay):
    """Fused Adagrad over [grads, params, state_sums]
    (+ optional trailing fp32 master-param list)
    (``amp_C.multi_tensor_adagrad``)."""
    _check_parallel(tensor_lists)
    has_master = len(tensor_lists) == 4
    g_list, p_list, h_list = tensor_lists[:3]
    master_list = tensor_lists[3] if has_master else None
    src_list = master_list if has_master else p_list

    new_p, new_h, new_master = [], [], []
    for i in range(len(g_list)):
        g = _f32(g_list[i])
        p = _f32(src_list[i])
        h = _f32(h_list[i])
        if mode == ADAM_MODE_L2 and weight_decay != 0.0:
            g = g + weight_decay * p
        h = h + g * g
        stepped = p - lr * g / (jnp.sqrt(h) + eps)
        if mode == ADAM_MODE_ADAMW and weight_decay != 0.0:
            stepped = stepped - lr * weight_decay * p
        new_p.append(stepped.astype(p_list[i].dtype))
        new_h.append(h.astype(h_list[i].dtype))
        if has_master:
            new_master.append(stepped.astype(master_list[i].dtype))

    new = [new_p, new_h]
    old = [p_list, h_list]
    if has_master:
        new.append(new_master)
        old.append(master_list)
    return _apply_noop(noop_flag, new, old)


# ---------------------------------------------------------------------------
# SGD  (csrc/multi_tensor_sgd_kernel.cu)
# ---------------------------------------------------------------------------

def multi_tensor_sgd(
    chunk_size,
    noop_flag,
    tensor_lists,
    weight_decay,
    momentum,
    dampening,
    lr,
    nesterov,
    first_run,
    wd_after_momentum,
    scale=1.0,
):
    """Fused SGD over [grads, params, momentum_buffers]
    (+ optional trailing fp32 master-param list).

    Mirrors the reference kernel's knobs: nesterov, dampening,
    wd_after_momentum, grad pre-scale, and first_run momentum init.
    """
    _check_parallel(tensor_lists)
    has_master = len(tensor_lists) == 4
    g_list, p_list, mom_list = tensor_lists[:3]
    master_list = tensor_lists[3] if has_master else None
    src_list = master_list if has_master else p_list

    new_p, new_mom, new_master = [], [], []
    for i in range(len(g_list)):
        g = _f32(g_list[i]) * jnp.float32(scale)
        p = _f32(src_list[i])
        mom = _f32(mom_list[i])

        if weight_decay != 0.0 and not wd_after_momentum:
            g = g + weight_decay * p

        if momentum != 0.0:
            mom_new = jnp.where(
                jnp.bool_(first_run), g, momentum * mom + (1.0 - dampening) * g)
            d = g + momentum * mom_new if nesterov else mom_new
        else:
            mom_new = mom
            d = g

        if weight_decay != 0.0 and wd_after_momentum:
            d = d + weight_decay * p

        stepped = p - lr * d
        new_p.append(stepped.astype(p_list[i].dtype))
        new_mom.append(mom_new.astype(mom_list[i].dtype))
        if has_master:
            new_master.append(stepped.astype(master_list[i].dtype))

    new = [new_p, new_mom]
    old = [p_list, mom_list]
    if has_master:
        new.append(new_master)
        old.append(master_list)
    return _apply_noop(noop_flag, new, old)


# ---------------------------------------------------------------------------
# LAMB  (csrc/multi_tensor_lamb.cu + lamb_stage_1/2)
# ---------------------------------------------------------------------------

def lamb_scalars(beta1, beta2, step, bias_correction, grad_averaging,
                 global_grad_norm, max_global_grad_norm,
                 grad_pre_scale=1.0):
    """(clip, bc1, bc2, beta3): the scalar prelude shared by LAMB
    stage 1 and the bf16-moments path (one definition — the two paths
    must compute the SAME optimizer)."""
    clip = jnp.where(
        global_grad_norm > max_global_grad_norm,
        max_global_grad_norm / global_grad_norm,
        1.0,
    ) if max_global_grad_norm > 0 else jnp.float32(1.0)
    if bias_correction:
        bc1 = 1.0 - beta1 ** step
        bc2 = 1.0 - beta2 ** step
    else:
        bc1 = bc2 = 1.0
    beta3 = (1.0 - beta1) if grad_averaging else 1.0
    return clip * grad_pre_scale, bc1, bc2, beta3


def lamb_update_direction(m32, v32, p32, bc1, bc2, eps, weight_decay):
    """Adam-style update direction with decoupled wd (fp32 inputs)."""
    u = (m32 / bc1) / (jnp.sqrt(v32 / bc2) + eps)
    if weight_decay != 0.0:
        u = u + weight_decay * p32
    return u


def lamb_trust_ratio(w_norm, u_norm):
    """Reference trust-ratio rule: ||p||/||u||, 1.0 when either is 0."""
    return jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm,
                     jnp.float32(1.0))


def multi_tensor_lamb_stage1(
    chunk_size, noop_flag, tensor_lists, beta1, beta2, eps, step,
    bias_correction, weight_decay, grad_averaging, global_grad_norm,
    max_global_grad_norm, grad_pre_scale=1.0,
):
    """LAMB stage 1 (``multi_tensor_lamb_stage_1``): clip by global grad
    norm, update moments, produce per-tensor update directions.

    ``grad_pre_scale`` multiplies every gradient before use — folded into
    the same elementwise chain as the clip, so unscaling loss-scaled
    gradients here is FREE (no separate unscale pass over HBM; the
    reference reaches the same economy by passing the combined scale
    into its stage-1 kernel). ``global_grad_norm`` must already be the
    UNSCALED norm when a pre-scale is used.

    Returns ``(update_list, new_m_list, new_v_list)`` in fp32.
    """
    _check_parallel(tensor_lists)
    g_list, p_list, m_list, v_list = tensor_lists

    clip, bc1, bc2, beta3 = lamb_scalars(
        beta1, beta2, step, bias_correction, grad_averaging,
        global_grad_norm, max_global_grad_norm, grad_pre_scale)

    updates, new_m, new_v = [], [], []
    for g, p, m, v in zip(g_list, p_list, m_list, v_list):
        g32 = _f32(g) * clip
        p32 = _f32(p)
        m32 = beta1 * _f32(m) + beta3 * g32
        v32 = beta2 * _f32(v) + (1.0 - beta2) * g32 * g32
        updates.append(lamb_update_direction(m32, v32, p32, bc1, bc2,
                                             eps, weight_decay))
        new_m.append(m32)
        new_v.append(v32)
    return updates, new_m, new_v


def multi_tensor_lamb_stage2(
    chunk_size, noop_flag, tensor_lists, lr, weight_decay=0.0, use_nvlamb=False,
):
    """LAMB stage 2 (``multi_tensor_lamb_stage_2``): per-tensor trust
    ratios from ||p|| / ||update||, then the parameter step.

    Reference semantics: the trust ratio is applied only when the tensor is
    weight-decayed or ``use_nvlamb`` is set; otherwise the step is a plain
    Adam step (ratio 1) — NVLAMB applies the ratio unconditionally.

    tensor_lists = [params, updates] (+ optional fp32 master list).
    """
    _check_parallel(tensor_lists)
    has_master = len(tensor_lists) == 3
    p_list, u_list = tensor_lists[:2]
    master_list = tensor_lists[2] if has_master else None
    src_list = master_list if has_master else p_list
    apply_ratio = use_nvlamb or weight_decay != 0.0

    new_p, new_master = [], []
    for i, (p, u) in enumerate(zip(src_list, u_list)):
        p32 = _f32(p)
        u32 = _f32(u)
        if apply_ratio:
            w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            u_norm = jnp.sqrt(jnp.sum(jnp.square(u32)))
            ratio = lamb_trust_ratio(w_norm, u_norm)
        else:
            ratio = jnp.float32(1.0)
        stepped = p32 - lr * ratio * u32
        new_p.append(stepped.astype(p_list[i].dtype))
        if has_master:
            new_master.append(stepped)
    if has_master:
        return new_p, new_master
    return new_p


def multi_tensor_novograd(
    chunk_size, noop_flag, tensor_lists, lr, beta1, beta2, eps, step,
    bias_correction, weight_decay, grad_averaging, norm_type,
    init_zero=False,
):
    """Fused NovoGrad over [grads, params, exp_avg] with per-tensor second
    moments (``amp_C.multi_tensor_novograd``; v is a scalar per tensor).

    tensor_lists = [grads, params, exp_avg, v (+ optional master list)];
    ``v`` (per-tensor second moments) is a stacked vector. ``init_zero``
    selects the reference's v-initialization: True applies the EMA formula
    from a zero v at step 1 (larger first steps), False (default) seeds v
    with the first step's squared gradient norms.
    Returns ``(new_params, new_m, new_v[, new_master])``.
    """
    # tensor_lists[3] (per-tensor v) is a stacked vector, not a list
    _check_parallel(list(tensor_lists[:3]) + list(tensor_lists[4:]))
    has_master = len(tensor_lists) == 5
    g_list, p_list, m_list = tensor_lists[:3]
    v = tensor_lists[3]  # stacked per-tensor second moments, shape (n,)
    master_list = tensor_lists[4] if has_master else None
    src_list = master_list if has_master else p_list

    if bias_correction:
        bc1 = 1.0 - beta1 ** step
        bc2 = 1.0 - beta2 ** step
    else:
        bc1 = bc2 = 1.0
    beta3 = (1.0 - beta1) if grad_averaging else 1.0

    g_norms = jnp.stack(
        [jnp.sqrt(jnp.sum(jnp.square(_f32(g)))) for g in g_list]
    )
    ema = beta2 * v + (1.0 - beta2) * g_norms ** 2
    if init_zero:
        v_new = ema
    else:
        v_new = jnp.where(jnp.bool_(step == 1), g_norms ** 2, ema)
    denom = jnp.sqrt(v_new / bc2) + eps

    new_p, new_m, new_master = [], [], []
    for i, (g, p, m) in enumerate(zip(g_list, src_list, m_list)):
        p32 = _f32(p)
        g32 = _f32(g) / denom[i]
        if weight_decay != 0.0:
            g32 = g32 + weight_decay * p32
        m32 = beta1 * _f32(m) + beta3 * g32
        upd = m32 / bc1
        stepped = p32 - lr * upd
        new_p.append(stepped.astype(p_list[i].dtype))
        new_m.append(m32.astype(m.dtype))
        if has_master:
            new_master.append(stepped.astype(master_list[i].dtype))
    if has_master:
        return new_p, new_m, v_new, new_master
    return new_p, new_m, v_new
