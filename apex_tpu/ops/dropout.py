"""Pallas TPU fused dropout: hardware-PRNG mask, regenerated in backward.

Why this exists (measured on v5e, BERT-large B=16 S=512): the composed
``nn.Dropout`` path draws its masks from JAX's threefry, which is pure
ALU work on the VPU — the ~49 hidden-dropout sites of a BERT-large step
cost ~42 ms/step, dwarfing the attention-dropout kernel (~3.5 ms). The
reference never pays this because cuDNN/Philox dropout is fused into its
kernels (``apex/contrib/csrc/multihead_attn/`` dropout epilogues). Here:

- forward: one elementwise Pallas pass; the keep-mask comes from the TPU
  hardware PRNG (``pltpu.prng_seed``/``prng_random_bits``) seeded by
  (user seed, tile id) — no mask tensor is ever written to HBM;
- backward: the cotangent pass re-seeds identically and replays the
  exact mask — dropout becomes pure bandwidth (read + write) with zero
  mask storage and zero threefry FLOPs.

Interpret mode (CPU sim) has no TPU PRNG: the same kernel takes
precomputed uint32 bits generated host-side from the seed (deterministic
across fwd/bwd). Under shard_map-on-CPU vma contexts a pure-jnp replica
of the kernel runs on the SAME bits/threshold/layout — bit-identical, so
a forward/backward pair may take different routes without mask skew.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._common import (
    LANE,
    interpret_mode as _interpret,
    keep_threshold as _keep_threshold,
    match_vma,
    round_up as _round_up,
    use_jnp_fallback,
)

_BLOCK_R = 512  # (512, 512) f32 tile = 1 MB VMEM; bandwidth-bound anyway
_BLOCK_C = 512


def _kernel(x_ref, *rest, rate, native_prng):
    if native_prng:
        seed_ref, o_ref = rest
        pltpu.prng_seed(seed_ref[0], pl.program_id(0))
        bits = pltpu.bitcast(
            pltpu.prng_random_bits(x_ref.shape[1:]), jnp.uint32)
    else:
        bits_ref, o_ref = rest
        bits = bits_ref[0]
    keep = bits < _keep_threshold(rate)
    x = x_ref[0]
    o_ref[0] = jnp.where(keep, x * (1.0 / (1.0 - rate)),
                         jnp.zeros_like(x)).astype(o_ref.dtype)


def _call(x2, drop_in, rate):
    R, C = x2.shape[1:]
    native = drop_in.ndim == 1
    extra_spec = (pl.BlockSpec(memory_space=pltpu.SMEM) if native
                  else pl.BlockSpec((1, R, C), lambda i: (i, 0, 0)))
    return pl.pallas_call(
        functools.partial(_kernel, rate=rate, native_prng=native),
        grid=(x2.shape[0],),
        in_specs=[pl.BlockSpec((1, R, C), lambda i: (i, 0, 0)), extra_spec],
        out_specs=pl.BlockSpec((1, R, C), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        interpret=_interpret(),
    )(x2, drop_in)


def _shape2(n):
    """Factor a flat length into (tiles, rows, cols) tile geometry."""
    c = min(_round_up(n, LANE), _BLOCK_C)
    rows_total = _round_up(n, c) // c
    r = min(_round_up(rows_total, 8), _BLOCK_R)
    tiles = _round_up(rows_total, r) // r
    return tiles, r, c


def _drop_in(seed, tiles, r, c):
    seed = jnp.asarray(seed, jnp.int32).reshape(())
    if _interpret():
        return jax.random.bits(jax.random.PRNGKey(seed), (tiles, r, c),
                               jnp.uint32)
    return seed.reshape((1,))


def _apply(x, rate, seed, force_jnp=False):
    n = x.size
    tiles, r, c = _shape2(n)
    x2 = jnp.pad(x.reshape(-1), (0, tiles * r * c - n)).reshape(tiles, r, c)
    if force_jnp:
        # pure-jnp replica of the interpret kernel — SAME bits tensor,
        # SAME threshold, SAME padded layout — for shard_map-vma contexts
        # the Pallas HLO interpreter mishandles. Bit-identical to the
        # kernel path, so a forward/backward pair may mix routes freely.
        bits = jax.random.bits(
            jax.random.PRNGKey(jnp.asarray(seed, jnp.int32)),
            (tiles, r, c), jnp.uint32)
        y2 = jnp.where(bits < _keep_threshold(rate),
                       x2 * (1.0 / (1.0 - rate)), jnp.zeros_like(x2))
    else:
        y2 = _call(x2, _drop_in(seed, tiles, r, c), rate)
    return y2.reshape(-1)[:n].reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def fused_dropout(x, rate: float, seed=None):
    """``dropout(x, rate)`` with the keep-mask generated in-kernel and
    replayed (never stored) in the backward pass.

    Args:
      x: any-shape floating tensor.
      rate: static drop probability in [0, 1).
      seed: int32 scalar (may be traced); required when rate > 0. Vary
        per call site and step.
    """
    if rate == 0.0:
        return x
    if seed is None:
        raise ValueError("fused_dropout with rate > 0 requires a seed")
    return _apply(x, rate, seed, force_jnp=use_jnp_fallback(x))


def _fd_fwd(x, rate, seed):
    return fused_dropout(x, rate, seed), seed


def _fd_bwd(rate, seed, g):
    if rate == 0.0:
        return g, None
    # replay: dropout is self-adjoint up to the same mask/scale
    return match_vma(fused_dropout(g, rate, seed), g), None


fused_dropout.defvjp(_fd_fwd, _fd_bwd)
