"""Ulysses-style all-to-all sequence-parallel attention.

The second long-context mechanism (besides :mod:`ring_attention`): the
DeepSpeed-Ulysses decomposition. Sequence-sharded activations are
re-sharded HEAD-wise for the attention core — one ``all_to_all``
converts (B, H, S/cp, D) into (B, H/cp, S, D), each device runs flash
attention over the FULL sequence for its head subset, and a second
``all_to_all`` restores sequence sharding. Two collectives per
attention — three with a padding mask, whose shards are all-gathered —
(vs the ring's cp ppermute hops), at the cost of requiring
``H % cp == 0`` and O(S) keys per device during the core (the ring
keeps O(S/cp)).

When to use which (both run inside ``shard_map`` over the context axis):
- ``ulysses_attention``: moderate sequence lengths where a full-S k/v
  block fits per device — fewer collectives, perfectly load-balanced
  causal attention.
- ``ring_attention``: extreme lengths where even one full-S k/v tensor
  per device is too large.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention, mha_reference


def ulysses_attention(q, k, v, key_mask=None, causal: bool = False,
                      scale: float = 1.0, axis_name: str = "context",
                      dropout_rate: float = 0.0, dropout_seed=None):
    """Sequence-parallel attention via head re-sharding.

    Args:
      q, k, v: this device's (B, H, S_local, D) sequence shard
        (contiguous sharding, like ring_attention).
      key_mask: optional (B, S_local) boolean shard (True = masked).
      causal: causal over global positions.
      scale: softmax temperature.
      axis_name: the context-parallel mesh axis; H must be divisible by
        its size.
      dropout_rate/dropout_seed: fused attention-probability dropout.
        Each Ulysses rank runs plain flash attention over the FULL
        sequence for its head subset, so the in-kernel dropout applies
        directly; the context rank is folded into the seed here so
        different ranks' (global) heads get decorrelated masks despite
        sharing local head indices. (Ring attention also supports fused
        dropout, via global block-pair seed hashing — see
        ring_attention.)

    Returns:
      (B, H, S_local, D) outputs for this device's sequence shard.
    """
    cp = jax.lax.psum(1, axis_name)
    B, H, S_local, D = q.shape
    if H % cp != 0:
        raise ValueError(
            f"ulysses_attention requires num_heads ({H}) divisible by the "
            f"context axis size ({cp}); use ring_attention otherwise")

    def to_heads(t):
        # (B, H, S/cp, D) -> (B, H/cp, S, D): split heads, concat seq
        return jax.lax.all_to_all(t, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    full_mask = None
    if key_mask is not None:
        from apex_tpu.utils.collectives import mark_varying

        # an axis-invariant (e.g. default all-False) mask must be cast
        # varying before the gather, same as ring_attention's rotation
        full_mask = jax.lax.all_gather(
            mark_varying(key_mask, axis_name), axis_name, axis=1,
            tiled=True)
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError(
                "ulysses_attention with dropout_rate > 0 requires "
                "dropout_seed")
        # hashed rank fold (shared mix_seed derivation): adjacent ranks
        # get decorrelated PRNG streams, not the sequential seeds a
        # plain `seed + rank` would produce
        from apex_tpu.ops._common import mix_seed

        dropout_seed = mix_seed(dropout_seed,
                                jax.lax.axis_index(axis_name))
    out = flash_attention(qh, kh, vh, full_mask, causal, scale,
                          dropout_rate, dropout_seed)
    # (B, H/cp, S, D) -> (B, H, S/cp, D)
    return jax.lax.all_to_all(out, axis_name, split_axis=2,
                              concat_axis=1, tiled=True)


def ulysses_attention_reference(q_full, k_full, v_full, key_mask=None,
                                causal=False, scale=1.0):
    """Unsharded reference for parity tests."""
    return mha_reference(q_full, k_full, v_full, key_mask, causal, scale)
