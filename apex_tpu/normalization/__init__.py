"""apex_tpu.normalization — fused norms backed by Pallas TPU kernels
(SURVEY.md §2.1 L3; kernels in apex_tpu.ops.layer_norm)."""

from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
)
from apex_tpu.ops.layer_norm import (  # noqa: F401
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)
