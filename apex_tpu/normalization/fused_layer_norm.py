"""FusedLayerNorm / FusedRMSNorm modules.

Rebuild of ``apex/normalization/fused_layer_norm.py`` (SURVEY.md §2.1):
drop-in norm modules backed by the Pallas kernels in
:mod:`apex_tpu.ops.layer_norm`. Provided as flax ``nn.Module`` s (the
idiomatic JAX module system) with the reference's knob surface:
``normalized_shape``, ``eps``, ``elementwise_affine``,
``memory_efficient``; the ``MixedFused*`` variants pin fp32 params under
low-precision activations (the reference's mixed-dtype contract).
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.layer_norm import (
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)


def _last_dim(normalized_shape) -> int:
    if isinstance(normalized_shape, int):
        return normalized_shape
    shape = tuple(normalized_shape)
    if len(shape) != 1:
        raise NotImplementedError(
            "apex_tpu norms fuse over the last dimension; multi-dim "
            "normalized_shape should be reshaped by the caller."
        )
    return shape[0]


class FusedLayerNorm(nn.Module):
    """Reference: ``apex.normalization.FusedLayerNorm``."""

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = _last_dim(self.normalized_shape)
        if x.shape[-1] != h:
            raise ValueError(f"expected trailing dim {h}, got {x.shape[-1]}")
        if not self.elementwise_affine:
            return fused_layer_norm(x, h, self.eps)
        weight = self.param("scale", nn.initializers.ones, (h,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (h,), self.param_dtype)
        return fused_layer_norm_affine(x, weight, bias, self.eps, self.memory_efficient)


class FusedRMSNorm(nn.Module):
    """Reference: ``apex.normalization.FusedRMSNorm``."""

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = _last_dim(self.normalized_shape)
        if x.shape[-1] != h:
            raise ValueError(f"expected trailing dim {h}, got {x.shape[-1]}")
        if not self.elementwise_affine:
            return fused_rms_norm(x, h, self.eps)
        weight = self.param("scale", nn.initializers.ones, (h,), self.param_dtype)
        return fused_rms_norm_affine(x, weight, self.eps, self.memory_efficient)


class MixedFusedLayerNorm(FusedLayerNorm):
    """fp32 params under low-precision activations (reference:
    ``MixedFusedLayerNorm`` — the amp-O2 norm)."""

    param_dtype: jnp.dtype = jnp.float32


class MixedFusedRMSNorm(FusedRMSNorm):
    """Reference: ``MixedFusedRMSNorm``."""

    param_dtype: jnp.dtype = jnp.float32
