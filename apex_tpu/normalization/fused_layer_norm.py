"""FusedLayerNorm / FusedRMSNorm modules.

Rebuild of ``apex/normalization/fused_layer_norm.py`` (SURVEY.md §2.1):
drop-in norm modules backed by the Pallas kernels in
:mod:`apex_tpu.ops.layer_norm`. Provided as flax ``nn.Module`` s (the
idiomatic JAX module system) with the reference's knob surface:
``normalized_shape``, ``eps``, ``elementwise_affine``,
``memory_efficient``; the ``MixedFused*`` variants pin fp32 params under
low-precision activations (the reference's mixed-dtype contract).
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.layer_norm import (
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)


def _norm_shape(normalized_shape) -> tuple:
    """Normalized-shape tuple (apex accepts an int or a trailing-dims
    tuple; multi-dim shapes normalize over ALL the trailing dims)."""
    if isinstance(normalized_shape, int):
        return (normalized_shape,)
    return tuple(int(d) for d in normalized_shape)


def _check_trailing(x, shape):
    k = len(shape)
    if tuple(x.shape[-k:]) != shape:
        raise ValueError(
            f"expected trailing dims {shape}, got {tuple(x.shape[-k:])}")


def _flatten_trailing(x, shape):
    """Collapse the trailing ``len(shape)`` dims into one (the fused
    kernels normalize over the last axis; a multi-dim normalized_shape
    is the same computation on the flattened view)."""
    k = len(shape)
    if k == 1:
        return x, x.shape
    lead = x.shape[:-k]
    n = 1
    for d in shape:
        n *= d
    return x.reshape(*lead, n), x.shape


class FusedLayerNorm(nn.Module):
    """Reference: ``apex.normalization.FusedLayerNorm``.

    ``normalized_shape`` may be an int or a tuple of trailing dims
    (apex parity): multi-dim shapes normalize over all the trailing
    dims via a flattened view, and affine params keep the full
    ``normalized_shape`` shape so checkpoints match apex's layout."""

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _norm_shape(self.normalized_shape)
        _check_trailing(x, shape)
        x2, orig = _flatten_trailing(x, shape)
        h = x2.shape[-1]
        if not self.elementwise_affine:
            return fused_layer_norm(x2, h, self.eps).reshape(orig)
        weight = self.param("scale", nn.initializers.ones, shape,
                            self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, shape,
                          self.param_dtype)
        y = fused_layer_norm_affine(x2, weight.reshape(h), bias.reshape(h),
                                    self.eps, self.memory_efficient)
        return y.reshape(orig)


class FusedRMSNorm(nn.Module):
    """Reference: ``apex.normalization.FusedRMSNorm``. Accepts int or
    multi-dim ``normalized_shape`` like :class:`FusedLayerNorm`."""

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _norm_shape(self.normalized_shape)
        _check_trailing(x, shape)
        x2, orig = _flatten_trailing(x, shape)
        h = x2.shape[-1]
        if not self.elementwise_affine:
            return fused_rms_norm(x2, h, self.eps).reshape(orig)
        weight = self.param("scale", nn.initializers.ones, shape,
                            self.param_dtype)
        y = fused_rms_norm_affine(x2, weight.reshape(h), self.eps,
                                  self.memory_efficient)
        return y.reshape(orig)


class MixedFusedLayerNorm(FusedLayerNorm):
    """fp32 params under low-precision activations (reference:
    ``MixedFusedLayerNorm`` — the amp-O2 norm)."""

    param_dtype: jnp.dtype = jnp.float32


class MixedFusedRMSNorm(FusedRMSNorm):
    """Reference: ``MixedFusedRMSNorm``."""

    param_dtype: jnp.dtype = jnp.float32
