"""Deprecated per-device process launcher (parity shim).

The reference ``apex/parallel/multiproc.py`` spawns one process per GPU and
was long deprecated in favor of ``torch.distributed.launch``. On TPU,
process bootstrap belongs to ``jax.distributed.initialize`` (one process
per host; devices discovered automatically), so this module only explains
the migration.
"""

import sys


def main():
    sys.stderr.write(
        "apex_tpu.parallel.multiproc is deprecated (as its reference was). "
        "On TPU, launch one process per host and call "
        "jax.distributed.initialize(); the mesh covers all chips.\n"
    )
    raise SystemExit(1)


if __name__ == "__main__":
    main()
