"""LARC — layer-wise adaptive rate clipping/scaling.

Rebuild of ``apex/parallel/LARC.py`` (SURVEY.md §2.1): wraps an optimizer,
computing a per-parameter adaptive learning rate

    local_lr = trust_coefficient * ||p|| / (||g|| + weight_decay*||p|| + eps)

and, like the reference, folding the wrapped optimizer's weight decay into
the gradient before scaling (the inner optimizer then runs with wd=0).
``clip=True`` caps the adaptive rate at the base lr (scale ≤ 1);
``clip=False`` is pure LARS scaling.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LARC:
    optimizer: Any
    trust_coefficient: float = 0.02
    clip: bool = True
    eps: float = 1e-8

    @property
    def lr(self):
        return self.optimizer.lr

    def with_master_weights(self, flag: bool = True):
        return dataclasses.replace(
            self, optimizer=self.optimizer.with_master_weights(flag)
        )

    def init(self, params):
        return self.optimizer.init(params)

    def _adjust(self, g, p, lr, weight_decay):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        p_norm = jnp.sqrt(jnp.sum(p32 * p32))
        g_norm = jnp.sqrt(jnp.sum(g32 * g32))
        adaptive_lr = (
            self.trust_coefficient * p_norm
            / (g_norm + p_norm * weight_decay + self.eps)
        )
        if self.clip:
            # reference: grad *= min(adaptive_lr/lr, 1) -> step capped at lr
            scale = jnp.minimum(adaptive_lr / lr, 1.0)
        else:
            # reference: grad *= adaptive_lr (inner optimizer applies lr on
            # top) -> step = lr * adaptive_lr * g
            scale = adaptive_lr
        # Reference: the whole adjustment (wd fold-in AND scaling) happens
        # only inside the `p_norm != 0 and g_norm != 0` branch; zero-norm
        # params keep their raw gradient and get no decay at all.
        adjusted = (g32 + weight_decay * p32) * scale
        active = (p_norm > 0) & (g_norm > 0)
        return jnp.where(active, adjusted, g32).astype(g.dtype)

    def step(self, grads, state, params, skip_if=None, lr=None):
        base_lr = self.optimizer.lr if lr is None else lr
        wd = getattr(self.optimizer, "weight_decay", 0.0)
        adjusted = jax.tree.map(
            lambda g, p: self._adjust(g, p, base_lr, wd), grads, params
        )
        inner = self.optimizer.replace(weight_decay=0.0) if wd else self.optimizer
        return inner.step(adjusted, state, params, skip_if=skip_if, lr=lr)
