"""Multi-host process bootstrap.

The reference never initializes the process group itself — user scripts
call ``torch.distributed.init_process_group("nccl", ...)`` before
touching ``apex.parallel`` (SURVEY.md §2.4). The JAX analog is
``jax.distributed.initialize``: one process per host, called BEFORE any
backend use, after which ``jax.devices()`` spans every chip in the
slice/pod and any ``jax.sharding.Mesh`` built from them (including
``parallel_state.initialize_model_parallel``) lays its collectives over
ICI within a slice and DCN across slices automatically.

This module wraps that call with env-driven conventions
(``MASTER_ADDR``/``MASTER_PORT`` for the coordinator;
``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID`` for the per-HOST process
layout) so a training script ports with one renamed call. Call it first
thing in ``main()`` — before any jax operation that would initialize a
backend. torchrun-style ``WORLD_SIZE``/``RANK`` are deliberately NOT
consumed: their torch semantics are per-GPU while a JAX process is
per-host, so silently mapping them would stand up a wrong-shaped (or
hung) cluster on any multi-chip host.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

# "" = not bootstrapped; "noop" = single-process fast path taken;
# "initialized" = jax.distributed.initialize ran
_mode = ""


def init_process_group(coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None,
                       local_device_ids=None,
                       auto: bool = False) -> None:
    """``torch.distributed.init_process_group("nccl")`` analog.

    Resolution order:

    1. Explicit args, or env: ``MASTER_ADDR`` (+``MASTER_PORT``, default
       8476) for the coordinator and ``JAX_NUM_PROCESSES`` /
       ``JAX_PROCESS_ID`` for the per-HOST process count/index →
       ``jax.distributed.initialize(coordinator, num, id)``. All three
       must resolve or this raises (no guessing). torch ``WORLD_SIZE``/
       ``RANK`` are per-GPU and intentionally ignored — export the JAX
       per-host values instead.
    2. ``auto=True`` → bare ``jax.distributed.initialize()`` (cluster
       auto-discovery: GCE TPU-pod metadata, SLURM, etc.).
    3. Neither → single-process no-op, matching how apex scripts run
       unmodified on one GPU. NOTE a multi-host TPU pod is NOT detected
       implicitly — pass ``auto=True`` (or set the env vars) on pods,
       or each host silently trains alone.

    A later call that carries args/``auto`` after a no-op first call is
    honored (it will raise jax's must-run-before-backend error if JAX
    was used in between — loud, not silent); after a real initialize,
    further calls are idempotent no-ops.
    """
    global _mode
    wants_cluster = auto or any(
        v is not None for v in (coordinator_address, num_processes,
                                process_id)) or "MASTER_ADDR" in os.environ
    if _mode == "initialized" or (_mode == "noop" and not wants_cluster):
        return
    if coordinator_address is None and "MASTER_ADDR" in os.environ:
        port = os.environ.get("MASTER_PORT", "8476")
        coordinator_address = f"{os.environ['MASTER_ADDR']}:{port}"
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])

    explicit = [coordinator_address, num_processes, process_id]
    if any(v is not None for v in explicit):
        if any(v is None for v in explicit):
            raise ValueError(
                "init_process_group: coordinator_address, num_processes, "
                "and process_id must all be provided (args, or MASTER_ADDR"
                " + JAX_NUM_PROCESSES + JAX_PROCESS_ID env; torch "
                "WORLD_SIZE/RANK are per-GPU and are not consumed) — got "
                f"{coordinator_address=}, {num_processes=}, {process_id=}")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
        _mode = "initialized"
    elif auto:
        # cluster auto-discovery happens inside initialize() itself
        jax.distributed.initialize(local_device_ids=local_device_ids)
        _mode = "initialized"
    else:
        # single-process run — nothing to bootstrap
        _mode = "noop"


def get_world_size() -> int:
    """CHIP world size, ``jax.device_count()`` — the value ported
    gradient-averaging / LR-scaling math wants. (torch ranks are
    per-GPU; JAX processes are per-host, so ``jax.process_count()`` is
    NOT the torch world size.)

    .. warning:: This does NOT pair with :func:`get_rank`, which returns
       the HOST index — ``get_rank()`` is not in
       ``range(get_world_size())`` on multi-chip hosts. Self-consistent
       pairs are (:func:`get_host_rank`, :func:`get_host_count`) for
       per-process logic and ``jax.lax.axis_index`` over a mesh axis for
       per-chip logic; ported ``data[rank::world_size]`` idioms must use
       one of those, never this mixed pair."""
    return jax.device_count()


def get_chip_count() -> int:
    """Alias for :func:`get_world_size` with an unambiguous name."""
    return jax.device_count()


def get_host_count() -> int:
    """Number of processes (hosts), ``jax.process_count()`` — the
    denominator that pairs with :func:`get_host_rank`."""
    return jax.process_count()


def get_host_rank() -> int:
    """This process's index in ``range(get_host_count())`` — the
    self-consistent (rank, world) pair for per-process sharding such as
    input pipelines."""
    return jax.process_index()


def get_rank() -> int:
    """Host (process) index — NOT a per-chip rank, and NOT an index into
    :func:`get_world_size` (which counts chips): on a 4-chip host this
    returns 0 while ``get_world_size()`` returns 4. Use the
    (:func:`get_host_rank`, :func:`get_host_count`) pair for per-process
    logic. There is no global per-chip rank outside a mesh context —
    inside ``shard_map`` use ``jax.lax.axis_index`` on the relevant mesh
    axis, which is what ported per-rank logic should key on."""
    return jax.process_index()
