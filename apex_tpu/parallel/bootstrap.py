"""Multi-host process bootstrap.

The reference never initializes the process group itself — user scripts
call ``torch.distributed.init_process_group("nccl", ...)`` before
touching ``apex.parallel`` (SURVEY.md §2.4). The JAX analog is
``jax.distributed.initialize``: one process per host, called BEFORE any
backend use, after which ``jax.devices()`` spans every chip in the
slice/pod and any ``jax.sharding.Mesh`` built from them (including
``parallel_state.initialize_model_parallel``) lays its collectives over
ICI within a slice and DCN across slices automatically.

This module wraps that call with the reference's env-driven conventions
(``MASTER_ADDR``/``MASTER_PORT``/``WORLD_SIZE``/``RANK`` → the
corresponding coordinator settings) so a training script ports with one
renamed call. Call it first thing in ``main()`` — before any jax
operation that would initialize a backend.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_process_group(coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None,
                       local_device_ids=None,
                       auto: bool = False) -> None:
    """``torch.distributed.init_process_group("nccl")`` analog.

    Resolution order:

    1. Explicit args, or the reference-style env vars ``MASTER_ADDR``
       (+``MASTER_PORT``, default 8476), ``WORLD_SIZE``, ``RANK`` →
       ``jax.distributed.initialize(coordinator, num, id)``.
    2. ``auto=True`` → bare ``jax.distributed.initialize()`` (cluster
       auto-discovery: GCE TPU-pod metadata, SLURM, etc.).
    3. Neither → single-process no-op, matching how apex scripts run
       unmodified on one GPU. NOTE a multi-host TPU pod is NOT detected
       implicitly — pass ``auto=True`` (or set the env vars) on pods,
       or each host silently trains alone.

    Must run before the first JAX backend use (a jax constraint); a
    partially-specified env (``MASTER_ADDR`` without ``WORLD_SIZE`` and
    ``RANK``) raises rather than guessing.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and "MASTER_ADDR" in os.environ:
        port = os.environ.get("MASTER_PORT", "8476")
        coordinator_address = f"{os.environ['MASTER_ADDR']}:{port}"
    if num_processes is None and "WORLD_SIZE" in os.environ:
        num_processes = int(os.environ["WORLD_SIZE"])
    if process_id is None and "RANK" in os.environ:
        process_id = int(os.environ["RANK"])

    explicit = [coordinator_address, num_processes, process_id]
    if any(v is not None for v in explicit):
        if any(v is None for v in explicit):
            raise ValueError(
                "init_process_group: coordinator_address, num_processes, "
                "and process_id must all be provided (args or "
                "MASTER_ADDR/WORLD_SIZE/RANK env) — got "
                f"{coordinator_address=}, {num_processes=}, {process_id=}")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    elif auto:
        # cluster auto-discovery happens inside initialize() itself
        jax.distributed.initialize(local_device_ids=local_device_ids)
    # else: single-process run — nothing to bootstrap
    _initialized = True


def get_world_size() -> int:
    """CHIP world size, ``jax.device_count()`` — the value ported
    gradient-averaging / LR-scaling math wants. (torch ranks are
    per-GPU; JAX processes are per-host, so ``jax.process_count()`` is
    NOT the torch world size. For the host count use
    ``jax.process_count()`` directly.)"""
    return jax.device_count()


def get_rank() -> int:
    """Host (process) index. There is no global per-chip rank outside a
    mesh context — inside ``shard_map`` use ``jax.lax.axis_index`` on
    the relevant mesh axis, which is what ported per-rank logic should
    key on."""
    return jax.process_index()
