"""apex_tpu.parallel — distributed data parallel, SyncBatchNorm, LARC
(SURVEY.md §2.1 L4) on jax.lax collectives over ICI/DCN."""

from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedDataParallel,
    flat_dist_call,
)
from apex_tpu.parallel.bootstrap import (  # noqa: F401
    get_chip_count,
    get_host_count,
    get_host_rank,
    get_rank,
    get_world_size,
    init_process_group,
)
from apex_tpu.parallel.larc import LARC  # noqa: F401
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm,
    convert_syncbn_model,
)
