"""DistributedDataParallel-semantics gradient synchronization over ICI/DCN.

Rebuild of ``apex/parallel/distributed.py`` (SURVEY.md §3.4) on XLA
collectives. The reference registers backward hooks that flatten ready
gradients into ``message_size``-element buckets and allreduce each bucket
on a side CUDA stream (NCCL); ``delay_allreduce=True`` instead performs one
flat-buffer allreduce after the full backward.

TPU mapping: gradient synchronization is a pure function applied to the
grad pytree inside ``shard_map``/``pmap`` over a named mesh axis.
``jax.lax.psum`` over ICI replaces NCCL ring-allreduce, and XLA's
latency-hiding scheduler overlaps collectives with the backward
computation — the role of apex's side streams and hook-driven eager
buckets. The knobs keep their reference meaning:

- ``message_size``: bucket size in elements. Buckets are flattened in
  reverse leaf order (the reference fills buckets in reverse
  gradient-ready order, which approximates reverse forward order).
- ``delay_allreduce``: one flat buffer over all gradients (the
  "flat-buffer path" named in the north star).
- ``allreduce_always_fp32``: upcast bucket buffers to fp32 for the
  reduction, cast back after.
- ``gradient_predivide_factor`` / ``gradient_average``: pre-scale by
  ``1/predivide`` before the psum and post-scale by
  ``predivide/world_size`` after (net ``1/world_size`` when averaging) —
  the reference's overflow-resistant two-stage averaging.
- ``num_allreduce_streams``: accepted for parity; XLA schedules collective
  streams itself.

shard_map autodiff note: differentiating wrt a *replicated* (``P()``)
param pytree inside ``shard_map`` already yields the cross-device SUM of
per-device gradients — the transpose of the implicit broadcast is a psum
inserted by autodiff. Such gradients are "unvarying" over the mesh axis
(empty ``vma``); psum-ing them again would multiply by the world size.
``allreduce_grads`` therefore inspects each bucket's varying-axes set and
reduces only device-varying data, then applies the averaging divisor
either way — so it is correct both for autodiff-produced grads and for
manually assembled per-device values.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.collectives import group_size, psum_groups
from apex_tpu.utils.pytree import flatten_buckets, ravel_list, unravel_list


@dataclasses.dataclass(frozen=True)
class DistributedDataParallel:
    axis_name: str = "data"
    message_size: int = 10_000_000
    delay_allreduce: bool = False
    allreduce_always_fp32: bool = False
    gradient_average: bool = True
    gradient_predivide_factor: float = 1.0
    num_allreduce_streams: int = 1  # parity knob; XLA owns scheduling
    retain_allreduce_buffers: bool = False  # parity knob
    axis_index_groups: Optional[tuple] = None  # subgroup reduction support

    def _is_varying(self, x) -> bool:
        """True if ``x`` still differs across the mesh axis (needs a psum).

        Autodiff-produced grads wrt replicated params come back already
        summed (empty vma) — see module docstring."""
        try:
            vma = jax.typeof(x).vma
        except (AttributeError, TypeError):
            return True  # pmap / older tracer: assume varying
        return self.axis_name in vma

    def _reduce_flat(self, flat, needs_psum: bool):
        orig_dtype = flat.dtype
        if self.allreduce_always_fp32:
            flat = flat.astype(jnp.float32)
        if self.gradient_predivide_factor != 1.0:
            flat = flat / self.gradient_predivide_factor
        if needs_psum:
            flat = psum_groups(flat, self.axis_name, self.axis_index_groups)
        if self.gradient_average:
            world = group_size(self.axis_index_groups, self.axis_name)
            post = self.gradient_predivide_factor / world
            flat = flat * post
        elif self.gradient_predivide_factor != 1.0:
            flat = flat * self.gradient_predivide_factor
        return flat.astype(orig_dtype)

    def allreduce_grads(self, grads):
        """Synchronize a gradient pytree across the ``axis_name`` mesh axis.

        Must be called inside ``shard_map``/``pmap`` where ``axis_name`` is
        bound. Returns the synchronized (averaged by default) grads.

        Leaves are segregated by varying-ness BEFORE any concatenation:
        mixing an already-summed (unvarying) leaf into a buffer with a
        varying one would promote it and psum it a second time.
        """
        leaves, treedef = jax.tree.flatten(grads)
        if not leaves:
            return grads

        out = [None] * len(leaves)
        # reverse leaf order approximates the reference's reverse-ready-
        # order bucket assembly
        rev_ids = list(range(len(leaves)))[::-1]
        for needs_psum in (True, False):
            group_ids = [i for i in rev_ids if self._is_varying(leaves[i]) == needs_psum]
            if not group_ids:
                continue
            group = [leaves[i] for i in group_ids]
            if self.delay_allreduce:
                # flat-buffer path: one allreduce over the whole group
                flat, meta = ravel_list(group)
                pieces = unravel_list(self._reduce_flat(flat, needs_psum), meta)
                for piece, i in zip(pieces, group_ids):
                    out[i] = piece
            else:
                for indices, flat, meta in flatten_buckets(group, self.message_size):
                    flat = self._reduce_flat(flat, needs_psum)
                    pieces = unravel_list(flat, meta)
                    for piece, pos in zip(pieces, indices):
                        out[group_ids[pos]] = piece
        return jax.tree.unflatten(treedef, out)

    def allreduce_accumulated(self, acc, accum_steps: int):
        """Single post-scan reduction: average an fp32 gradient
        accumulator over ``accum_steps`` microbatches, then synchronize
        ONCE across the mesh axis.

        This is the fused-train-step contract (``apex_tpu.train``): the
        scan accumulates local grads on-device and the collective runs
        once per GLOBAL step, not once per microbatch — at
        ``accum_steps=8`` that is 8x fewer allreduce launches for
        identical bytes. The divide happens BEFORE the psum (divide-
        then-reduce), which is bit-identical to the hand-wired
        accumulate / average / ``allreduce_grads`` reference loop —
        folding the 1/accum factor into the post-psum averaging multiply
        would save one multiply but change the rounding, breaking the
        fused-vs-reference certification."""
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        if accum_steps > 1:
            # true division, not a reciprocal multiply: 1/accum is
            # inexact for non-power-of-2 accum and would diverge from
            # the reference loop's ``acc / accum`` at the last bit
            acc = jax.tree.map(
                lambda a: (a / jnp.asarray(accum_steps, a.dtype)
                           if jnp.issubdtype(a.dtype, jnp.floating)
                           else a), acc)
        return self.allreduce_grads(acc)

    def __call__(self, grads):
        return self.allreduce_grads(grads)

    def value_and_grad(self, loss_fn, **vg_kwargs):
        """Convenience: ``jax.value_and_grad`` whose grads are synchronized
        (the wrapped-model UX of the reference DDP)."""
        vg = jax.value_and_grad(loss_fn, **vg_kwargs)

        def wrapped(*args, **kwargs):
            val, grads = vg(*args, **kwargs)
            return val, self.allreduce_grads(grads)

        return wrapped


def flat_dist_call(tensors, axis_name: str = "data", op: str = "sum"):
    """Parity helper for the reference's ``flat_dist_call``: flatten a list
    of arrays, apply one collective, unflatten."""
    flat, meta = ravel_list(list(tensors))
    if op == "sum":
        flat = jax.lax.psum(flat, axis_name)
    elif op == "mean":
        flat = jax.lax.pmean(flat, axis_name)
    elif op == "max":
        flat = jax.lax.pmax(flat, axis_name)
    else:
        raise ValueError(f"unsupported op {op!r}")
    return unravel_list(flat, meta)
