"""SyncBatchNorm — cross-replica batch normalization over ICI.

Rebuild of ``apex/parallel/optimized_sync_batchnorm*.py`` (SURVEY.md §3.5):
the reference computes local Welford statistics with a CUDA kernel,
all-gathers (count, mean, var) across the process group, combines them
with ``welford_parallel``, then normalizes. The TPU-native version
computes local (count, sum, sumsq) and combines across replicas with ONE
``psum`` of the stacked triple — algebraically the parallel-Welford
combination

    M2_total = sum_i M2_i + sum_i n_i * (mean_i - mean_total)^2

evaluated via sufficient statistics so a single fused collective suffices
(fp32 accumulation keeps it stable at BN's scale).
Knob parity: ``process_group`` → ``axis_index_groups`` subsets,
``channel_last``, ``track_running_stats``, fp32 running stats under
low-precision activations.
"""

from __future__ import annotations

import warnings
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.utils.collectives import axis_is_bound, psum_groups


class SyncBatchNorm(nn.Module):
    """flax module mirroring ``apex.parallel.SyncBatchNorm``.

    Input layout: channel dim is axis 1 (torch NCHW convention) unless
    ``channel_last`` (then the trailing axis). ``axis_name=None`` degrades
    to plain (single-replica) BatchNorm, like the reference on world size 1.
    ``num_features`` may be left at -1 to infer from the input (used by
    :func:`convert_syncbn_model`, since flax BatchNorm infers too).
    ``use_running_average`` selects eval behavior (flax convention; the
    reference keys off ``module.training``); with
    ``track_running_stats=False`` batch statistics are always used, per
    torch semantics.
    """

    num_features: int = -1
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    use_scale: Optional[bool] = None  # finer-grained than affine, if set
    use_bias: Optional[bool] = None
    track_running_stats: bool = True
    axis_name: Optional[str] = "data"
    process_group: Optional[Any] = None  # axis_index_groups
    channel_last: bool = False
    use_running_average: Optional[bool] = None
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        # torch semantics: without tracked running stats, always normalize
        # with batch statistics, training or not.
        use_ra = use_ra and self.track_running_stats

        ch_axis = (x.ndim - 1) if self.channel_last else min(1, x.ndim - 1)
        nf = self.num_features if self.num_features > 0 else x.shape[ch_axis]
        if x.shape[ch_axis] != nf:
            raise ValueError(
                f"expected {nf} channels on axis {ch_axis}, got shape {x.shape}"
            )
        reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)

        if self.track_running_stats:
            ra_mean = self.variable(
                "batch_stats", "mean", lambda: jnp.zeros((nf,), jnp.float32)
            )
            ra_var = self.variable(
                "batch_stats", "var", lambda: jnp.ones((nf,), jnp.float32)
            )
        else:
            ra_mean = ra_var = None

        xf = x.astype(jnp.float32)
        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            local_count = jnp.float32(x.size // nf)
            local_sum = jnp.sum(xf, axis=reduce_axes)
            local_sumsq = jnp.sum(xf * xf, axis=reduce_axes)
            total_sum, total_sumsq, count = local_sum, local_sumsq, local_count
            if self.axis_name is not None:
                stacked = None
                if axis_is_bound(self.axis_name) is not False:
                    packed = jnp.concatenate(
                        [local_sum, local_sumsq,
                         jnp.full((1,), local_count, jnp.float32)]
                    )
                    try:
                        stacked = psum_groups(packed, self.axis_name,
                                              self.process_group)
                    except NameError:
                        stacked = None  # axis unbound (no axis_env probe)
                if stacked is not None:
                    total_sum = stacked[:nf]
                    total_sumsq = stacked[nf: 2 * nf]
                    count = stacked[-1]
                elif not self.is_initializing():
                    warnings.warn(
                        f"SyncBatchNorm: axis {self.axis_name!r} is not bound "
                        "(not inside shard_map/pmap); falling back to LOCAL "
                        "batch statistics. Pass axis_name=None to silence if "
                        "single-replica use is intended.",
                        stacklevel=2,
                    )
            mean = total_sum / count
            # biased variance for normalization (torch semantics)
            var = total_sumsq / count - mean * mean

            if self.track_running_stats and not self.is_initializing():
                # running stats use the unbiased variance (torch semantics)
                unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
                ra_mean.value = (1 - self.momentum) * ra_mean.value + self.momentum * mean
                ra_var.value = (1 - self.momentum) * ra_var.value + self.momentum * unbiased

        shape = [1] * x.ndim
        shape[ch_axis] = nf
        y = (xf - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + self.eps)
        use_scale = self.affine if self.use_scale is None else self.use_scale
        use_bias = self.affine if self.use_bias is None else self.use_bias
        if use_scale:
            scale = self.param("scale", nn.initializers.ones, (nf,), self.param_dtype)
            y = y * scale.reshape(shape).astype(jnp.float32)
        if use_bias:
            bias = self.param("bias", nn.initializers.zeros, (nf,), self.param_dtype)
            y = y + bias.reshape(shape).astype(jnp.float32)
        return y.astype(x.dtype)


def convert_syncbn_model(module: nn.Module, axis_name: str = "data",
                         process_group=None) -> nn.Module:
    """Best-effort analog of ``apex.parallel.convert_syncbn_model``: return
    a copy of a flax module with any direct ``nn.BatchNorm`` fields replaced
    by :class:`SyncBatchNorm`.

    flax modules are frozen dataclasses constructed per-call, so unlike the
    torch version this cannot rewrite modules instantiated inside
    ``__call__`` bodies — for those, parameterize the model on its norm
    class and pass ``SyncBatchNorm``. Direct submodule fields (the
    ``self.bn = nn.BatchNorm(...)`` setup-style pattern) are converted.
    """
    import dataclasses as dc

    if isinstance(module, nn.BatchNorm):
        return SyncBatchNorm(
            num_features=-1,  # inferred at call, like flax BatchNorm
            eps=module.epsilon,
            momentum=1.0 - module.momentum,  # flax stores the EMA keep-rate
            use_scale=module.use_scale,
            use_bias=module.use_bias,
            use_running_average=module.use_running_average,
            axis_name=axis_name,
            process_group=process_group,
            channel_last=True,  # flax BatchNorm is feature-last
        )
    def walk(mod):
        """Recursively rewrite BatchNorm fields (incl. inside list/tuple/
        dict containers); returns (module, count)."""
        if isinstance(mod, nn.BatchNorm):
            return convert_syncbn_model(mod, axis_name, process_group), 1
        if isinstance(mod, (list, tuple)):
            items = [walk(v) for v in mod]
            n = sum(c for _, c in items)
            if n:
                return type(mod)(v for v, _ in items), n
            return mod, 0
        if isinstance(mod, dict):
            items = {k: walk(v) for k, v in mod.items()}
            n = sum(c for _, c in items.values())
            if n:
                return {k: v for k, (v, _) in items.items()}, n
            return mod, 0
        if not dc.is_dataclass(mod) or not isinstance(mod, nn.Module):
            return mod, 0
        changes, converted = {}, 0
        for f in dc.fields(mod):
            try:
                v = getattr(mod, f.name)
            except AttributeError:
                continue
            if isinstance(v, (nn.Module, list, tuple, dict)):
                new_v, n = walk(v)
                if n:
                    changes[f.name] = new_v
                    converted += n
        if changes:
            return dc.replace(mod, **changes), converted
        return mod, 0

    out, converted = walk(module)
    if converted == 0:
        # The torch version walks the whole runtime module tree; this walk
        # covers (recursively) every submodule held as a dataclass FIELD,
        # but modules created inside @nn.compact __call__ bodies are
        # invisible to it — warn instead of silently no-oping (the
        # reference contract "convert the whole model" did NOT happen).
        warnings.warn(
            "convert_syncbn_model found no nn.BatchNorm among this "
            "module's (recursive) fields. BatchNorms created inside "
            "@nn.compact __call__ bodies cannot be rewritten this way; "
            "parameterize the model on its norm class and pass "
            "SyncBatchNorm instead.",
            stacklevel=2,
        )
    return out
