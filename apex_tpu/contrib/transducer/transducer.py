"""RNN-T transducer joint + loss (reference: ``apex/contrib/transducer/
transducer.py`` + ``apex/contrib/csrc/transducer/``, SURVEY.md §2.2 —
fused speech-recognition ops).

- :func:`transducer_joint` (reference ``TransducerJoint``): the
  broadcast add of the encoder (time) and predictor (label) activations
  with an optional fused ReLU/dropout epilogue — the reference fuses
  this because eager torch materializes two broadcasts; XLA fuses the
  add+activation into one pass over the (B, T, U+1, H) lattice.

- :func:`transducer_loss` (reference ``TransducerLoss``): the RNN-T
  negative log-likelihood via the forward (alpha) recursion over the
  (T, U) lattice, as a ``lax.scan`` over time with a scan over labels
  inside — compiler-friendly sequential DP (no data-dependent Python),
  fp32 log-space. Gradients come from autodiff of the recursion (the
  reference hand-writes the beta pass; AD derives it).

Layout: ``log_probs`` is (B, T, U+1, V) — T encoder frames, U target
labels (+1 for the start), V vocab incl. blank.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# packed layout (reference: the csrc kernels' `packed_input`/`pack_output`
# mode — padding cells removed, per-example segments concatenated)
# ---------------------------------------------------------------------------
#
# Packed cell order matches the reference: example b's valid lattice is the
# row-major (f_len[b], y_len[b]+1) block starting at batch_offset[b], i.e.
# packed[batch_offset[b] + t*(y_len[b]+1) + u] == dense[b, t, u].
# XLA needs static shapes, so the packed buffer has a static capacity
# (its true occupancy is batch_offset[-1] + last block; slack is zeros) —
# the caller computes batch_offset = cumsum-exclusive of
# f_len * (y_len + 1), exactly the reference's helper.


def transducer_batch_offset(f_len, y_len):
    """Exclusive cumulative offsets of each example's packed block
    (the reference computes this on the host; here it stays traced)."""
    sizes = f_len.astype(jnp.int32) * (y_len.astype(jnp.int32) + 1)
    return jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(sizes)[:-1]])


def _packed_coords(packed_size, batch_offset, y_len):
    """Map packed position p -> (b, t, u). Positions past the true total
    yield garbage coords — callers mask them with their own validity
    test (see transducer_pack).

    Zero-size examples (f_len[b] == 0) are safe: they produce duplicate
    offsets, and ``side="right"`` resolves a position at a duplicate run
    to the LAST index with offset <= p — the non-empty successor, never
    the empty example (regression-tested in test_transducer.py)."""
    p = jnp.arange(packed_size, dtype=jnp.int32)
    # b = index of the last offset <= p
    b = (jnp.searchsorted(batch_offset, p, side="right") - 1).astype(jnp.int32)
    b = jnp.clip(b, 0, batch_offset.shape[0] - 1)
    rem = p - batch_offset[b]
    width = y_len.astype(jnp.int32)[b] + 1
    t = rem // width
    u = rem % width
    return b, t, u


def transducer_pack(dense, f_len, y_len, packed_size, batch_offset=None):
    """Pack a dense (B, T, U+1, ...) lattice into (packed_size, ...).

    Gather formulation (one packed row reads one dense cell): static
    shapes, no scatter hazards. Slack rows beyond the true total are
    zero."""
    if batch_offset is None:
        batch_offset = transducer_batch_offset(f_len, y_len)
    b, t, u = _packed_coords(packed_size, batch_offset, y_len)
    total = batch_offset[-1] + (f_len.astype(jnp.int32)[-1]
                                * (y_len.astype(jnp.int32)[-1] + 1))
    valid = jnp.arange(packed_size) < total
    out = dense[b, t, u]
    return jnp.where(valid.reshape((-1,) + (1,) * (out.ndim - 1)), out, 0)


def transducer_unpack(packed, f_len, y_len, T, U1, batch_offset=None,
                      fill=0.0):
    """Unpack (packed_size, ...) back to dense (B, T, U1, ...) — T and
    U1 are static (the dense lattice bounds); padding cells take
    ``fill``. Inverse of :func:`transducer_pack`."""
    if batch_offset is None:
        batch_offset = transducer_batch_offset(f_len, y_len)
    width = y_len.astype(jnp.int32)[:, None, None] + 1
    t = jnp.arange(T, dtype=jnp.int32)[None, :, None]
    u = jnp.arange(U1, dtype=jnp.int32)[None, None, :]
    p = batch_offset[:, None, None] + t * width + u
    valid = ((t < f_len.astype(jnp.int32)[:, None, None]) & (u < width))
    p = jnp.clip(p, 0, packed.shape[0] - 1)
    out = packed[p]  # (B, T, U1, ...)
    mask = valid.reshape(valid.shape + (1,) * (out.ndim - 3))
    return jnp.where(mask, out, fill)


def transducer_joint(f, g, f_len=None, g_len=None, relu: bool = False,
                     dropout_rate: float = 0.0, rng=None):
    """Broadcast-add joint: f (B, T, H) + g (B, U+1, H) -> (B, T, U+1, H).

    ``f_len``/``g_len`` accepted for call-site parity (packing is an HBM
    optimization in the reference; XLA keeps the lattice in registers
    through the fused epilogue, so dense is layout-optimal here).
    """
    del f_len, g_len
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jax.nn.relu(out)
    if dropout_rate > 0.0:
        if rng is None:
            raise ValueError("dropout_rate > 0 requires an rng key")
        keep = 1.0 - dropout_rate
        mask = jax.random.bernoulli(rng, keep, out.shape)
        out = jnp.where(mask, out / keep, 0.0)
    return out


def transducer_loss(log_probs, labels, f_len, y_len, blank_idx: int = 0):
    """RNN-T NLL per example (reference ``TransducerLoss``; unreduced,
    like the CUDA op).

    Args:
      log_probs: (B, T, U+1, V) log-softmax outputs of the joint.
      labels: (B, U) int target labels.
      f_len: (B,) valid encoder frames per example.
      y_len: (B,) valid label count per example.
      blank_idx: the blank symbol.

    Returns:
      (B,) negative log-likelihoods.
    """
    B, T, U1, V = log_probs.shape
    U = U1 - 1
    lp = log_probs.astype(jnp.float32)

    # blank and emit scores per lattice node
    blank = lp[:, :, :, blank_idx]                       # (B, T, U+1)
    emit = jnp.take_along_axis(
        lp[:, :, :U, :],
        labels[:, None, :, None].astype(jnp.int32), axis=3
    )[..., 0]                                            # (B, T, U)

    def time_step(alpha_prev, t):
        # horizontal move (consume a frame): alpha_prev + blank at t-1
        from_blank = jnp.where(
            t == 0,
            jnp.where(jnp.arange(U1)[None, :] == 0, 0.0, _NEG_INF),
            alpha_prev + blank[:, jnp.maximum(t - 1, 0), :],
        )

        # vertical moves within frame t: emit label u-1 at (t, u-1)
        def label_step(carry, u):
            prev = carry  # alpha[t, u-1]
            cur = jnp.logaddexp(
                from_blank[:, u],
                prev + emit[:, t, u - 1],
            )
            return cur, cur

        a0 = from_blank[:, 0]
        _, rest = jax.lax.scan(label_step, a0, jnp.arange(1, U1))
        alpha_t = jnp.concatenate([a0[:, None], rest.T], axis=1)
        return alpha_t, None

    # per-example termination at (f_len-1, y_len): freeze each example's
    # alpha once its frames run out, so the final carry holds alpha at
    # t = f_len-1 regardless of padding
    def frozen_time_step(alpha_prev, t):
        alpha_t, _ = time_step(alpha_prev, t)
        keep = (t < f_len)[:, None]
        return jnp.where(keep, alpha_t, alpha_prev), None

    alpha0 = jnp.full((B, U1), _NEG_INF)
    alpha_final, _ = jax.lax.scan(frozen_time_step, alpha0, jnp.arange(T))

    final_alpha = jnp.take_along_axis(
        alpha_final, y_len[:, None].astype(jnp.int32), axis=1)[:, 0]
    last_blank = blank[jnp.arange(B),
                       jnp.maximum(f_len - 1, 0),
                       y_len]
    return -(final_alpha + last_blank)


class TransducerJoint:
    """Reference class-shape veneer. ``pack_output=True`` returns the
    packed (packed_size, H) lattice (padding cells removed, reference
    packed layout); the caller passes ``batch_offset``
    (:func:`transducer_batch_offset` of ``f_len``/``g_len - 1``) and a
    static ``packed_size`` capacity (XLA shapes are static; the
    reference sizes the buffer dynamically on the host)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: float = 0.0):
        self.pack_output = pack_output
        self.relu = relu
        self.dropout = dropout

    def __call__(self, f, g, f_len=None, g_len=None, batch_offset=None,
                 packed_size=None, rng=None):
        dense = transducer_joint(f, g, f_len, g_len, self.relu,
                                 self.dropout, rng)
        if not self.pack_output:
            return dense
        if f_len is None or g_len is None or packed_size is None:
            raise ValueError(
                "pack_output=True requires f_len, g_len, and a static "
                "packed_size capacity")
        return transducer_pack(dense, f_len, g_len.astype(jnp.int32) - 1,
                               packed_size, batch_offset)


class TransducerLoss:
    """Reference class-shape veneer. ``packed_input=True`` accepts the
    packed (packed_size, V) log-prob lattice plus ``batch_offset`` and
    the static ``max_f_len`` (the reference forward's extra packed-mode
    args); it is unpacked to the dense lattice with a neutral fill and
    fed to the same scan — padding cells never reach the recursion
    (masked by f_len/y_len), so packed and dense losses match
    exactly."""

    def __init__(self, packed_input: bool = False):
        self.packed_input = packed_input

    def __call__(self, x, label, f_len, y_len, batch_offset=None,
                 max_f_len=None, blank_idx: int = 0):
        if self.packed_input:
            if max_f_len is None:
                raise ValueError(
                    "packed_input=True requires max_f_len (static dense "
                    "time bound)")
            U1 = label.shape[1] + 1
            x = transducer_unpack(x, f_len, y_len, int(max_f_len), U1,
                                  batch_offset, fill=_NEG_INF)
        return transducer_loss(x, label, f_len, y_len, blank_idx)
