"""RNN-T transducer joint + loss (reference: ``apex/contrib/transducer/
transducer.py`` + ``apex/contrib/csrc/transducer/``, SURVEY.md §2.2 —
fused speech-recognition ops).

- :func:`transducer_joint` (reference ``TransducerJoint``): the
  broadcast add of the encoder (time) and predictor (label) activations
  with an optional fused ReLU/dropout epilogue — the reference fuses
  this because eager torch materializes two broadcasts; XLA fuses the
  add+activation into one pass over the (B, T, U+1, H) lattice.

- :func:`transducer_loss` (reference ``TransducerLoss``): the RNN-T
  negative log-likelihood via the forward (alpha) recursion over the
  (T, U) lattice, as a ``lax.scan`` over time with a scan over labels
  inside — compiler-friendly sequential DP (no data-dependent Python),
  fp32 log-space. Gradients come from autodiff of the recursion (the
  reference hand-writes the beta pass; AD derives it).

Layout: ``log_probs`` is (B, T, U+1, V) — T encoder frames, U target
labels (+1 for the start), V vocab incl. blank.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def transducer_joint(f, g, f_len=None, g_len=None, relu: bool = False,
                     dropout_rate: float = 0.0, rng=None):
    """Broadcast-add joint: f (B, T, H) + g (B, U+1, H) -> (B, T, U+1, H).

    ``f_len``/``g_len`` accepted for call-site parity (packing is an HBM
    optimization in the reference; XLA keeps the lattice in registers
    through the fused epilogue, so dense is layout-optimal here).
    """
    del f_len, g_len
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jax.nn.relu(out)
    if dropout_rate > 0.0:
        if rng is None:
            raise ValueError("dropout_rate > 0 requires an rng key")
        keep = 1.0 - dropout_rate
        mask = jax.random.bernoulli(rng, keep, out.shape)
        out = jnp.where(mask, out / keep, 0.0)
    return out


def transducer_loss(log_probs, labels, f_len, y_len, blank_idx: int = 0):
    """RNN-T NLL per example (reference ``TransducerLoss``; unreduced,
    like the CUDA op).

    Args:
      log_probs: (B, T, U+1, V) log-softmax outputs of the joint.
      labels: (B, U) int target labels.
      f_len: (B,) valid encoder frames per example.
      y_len: (B,) valid label count per example.
      blank_idx: the blank symbol.

    Returns:
      (B,) negative log-likelihoods.
    """
    B, T, U1, V = log_probs.shape
    U = U1 - 1
    lp = log_probs.astype(jnp.float32)

    # blank and emit scores per lattice node
    blank = lp[:, :, :, blank_idx]                       # (B, T, U+1)
    emit = jnp.take_along_axis(
        lp[:, :, :U, :],
        labels[:, None, :, None].astype(jnp.int32), axis=3
    )[..., 0]                                            # (B, T, U)

    def time_step(alpha_prev, t):
        # horizontal move (consume a frame): alpha_prev + blank at t-1
        from_blank = jnp.where(
            t == 0,
            jnp.where(jnp.arange(U1)[None, :] == 0, 0.0, _NEG_INF),
            alpha_prev + blank[:, jnp.maximum(t - 1, 0), :],
        )

        # vertical moves within frame t: emit label u-1 at (t, u-1)
        def label_step(carry, u):
            prev = carry  # alpha[t, u-1]
            cur = jnp.logaddexp(
                from_blank[:, u],
                prev + emit[:, t, u - 1],
            )
            return cur, cur

        a0 = from_blank[:, 0]
        _, rest = jax.lax.scan(label_step, a0, jnp.arange(1, U1))
        alpha_t = jnp.concatenate([a0[:, None], rest.T], axis=1)
        return alpha_t, None

    # per-example termination at (f_len-1, y_len): freeze each example's
    # alpha once its frames run out, so the final carry holds alpha at
    # t = f_len-1 regardless of padding
    def frozen_time_step(alpha_prev, t):
        alpha_t, _ = time_step(alpha_prev, t)
        keep = (t < f_len)[:, None]
        return jnp.where(keep, alpha_t, alpha_prev), None

    alpha0 = jnp.full((B, U1), _NEG_INF)
    alpha_final, _ = jax.lax.scan(frozen_time_step, alpha0, jnp.arange(T))

    final_alpha = jnp.take_along_axis(
        alpha_final, y_len[:, None].astype(jnp.int32), axis=1)[:, 0]
    last_blank = blank[jnp.arange(B),
                       jnp.maximum(f_len - 1, 0),
                       y_len]
    return -(final_alpha + last_blank)


class TransducerJoint:
    """Reference class-shape veneer."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: float = 0.0):
        if pack_output:
            raise NotImplementedError(
                "packed output is a CUDA-memory optimization; the XLA "
                "path keeps the dense lattice (see transducer_joint)")
        self.relu = relu
        self.dropout = dropout

    def __call__(self, f, g, f_len=None, g_len=None, rng=None):
        return transducer_joint(f, g, f_len, g_len, self.relu,
                                self.dropout, rng)


class TransducerLoss:
    """Reference class-shape veneer."""

    def __init__(self, packed_input: bool = False):
        if packed_input:
            raise NotImplementedError("packed input not supported; dense")

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
