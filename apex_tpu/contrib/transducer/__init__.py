"""Contrib transducer (reference: ``apex/contrib/transducer``)."""

from apex_tpu.contrib.transducer.transducer import (
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
)

__all__ = ["TransducerJoint", "TransducerLoss", "transducer_joint",
           "transducer_loss"]
