"""Contrib transducer (reference: ``apex/contrib/transducer``)."""

from apex_tpu.contrib.transducer.transducer import (
    TransducerJoint,
    TransducerLoss,
    transducer_batch_offset,
    transducer_joint,
    transducer_loss,
    transducer_pack,
    transducer_unpack,
)

__all__ = ["TransducerJoint", "TransducerLoss", "transducer_batch_offset",
           "transducer_joint", "transducer_loss", "transducer_pack",
           "transducer_unpack"]
