"""Contrib tier (reference: ``apex/contrib/``): semi-supported
subpackages, each mirroring an upstream contrib component on TPU-native
machinery. Import subpackages explicitly (``apex_tpu.contrib.optimizers``
etc.), matching the reference's opt-in import style."""
