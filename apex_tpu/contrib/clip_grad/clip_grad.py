"""Gradient clipping on the fused L2-norm pass (reference:
``apex/contrib/clip_grad/clip_grad.py``, SURVEY.md §2.5).

The reference's ``clip_grad_norm_`` replaces torch's per-tensor norm loop
with one ``multi_tensor_l2norm`` launch + one ``multi_tensor_scale``.
Functional form here (grads are values, not ``.grad`` slots): returns
``(clipped_grads, total_norm)`` and reuses the same fused ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops.multi_tensor import multi_tensor_l2norm, multi_tensor_scale


def clip_grad_norm_(grads, max_norm: float, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False):
    """Clip a grad pytree to ``max_norm`` total norm.

    Matches ``torch.nn.utils.clip_grad_norm_`` semantics (the reference
    delegates to them): ``total_norm`` is the norm of the per-tensor
    norms; grads scale by ``max_norm / (total_norm + 1e-6)`` only when
    that coefficient is < 1. Returns ``(clipped_grads, total_norm)``.

    ``norm_type=2`` uses the fused ``multi_tensor_l2norm`` pass; other
    norms (incl. ``inf``) use a jnp reduction.
    """
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return grads, jnp.float32(0.0)
    if norm_type == 2.0:
        total_norm, _ = multi_tensor_applier(
            multi_tensor_l2norm, None, [leaves], False)
    elif norm_type == float("inf"):
        total_norm = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g.astype(jnp.float32))) for g in leaves]))
    else:
        total_norm = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in leaves])) ** (1.0 / norm_type)

    # torch's error_if_nonfinite raises on the host; in-graph the norm is
    # a traced value, so the contract becomes: non-finite norms propagate
    # NaN into the clipped grads (scale below is NaN), and callers check
    # the returned total_norm — the amp scaler's skip_if path does.
    clip_coef = max_norm / (total_norm + 1e-6)
    scale = jnp.minimum(clip_coef, 1.0)
    clipped_leaves, _ = multi_tensor_applier(
        multi_tensor_scale, None, [leaves, leaves], scale)
    clipped = jax.tree.unflatten(jax.tree.structure(grads), clipped_leaves)
    return clipped, total_norm


# reference alias (same function; grads are functional values here)
clip_grad_norm = clip_grad_norm_
