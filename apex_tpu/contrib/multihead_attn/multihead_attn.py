"""Fused multi-head attention modules (reference:
``apex/contrib/multihead_attn/*.py`` + ``apex/contrib/csrc/
multihead_attn/``, SURVEY.md §2.2/§2.5).

The reference fuses QKV GEMMs + softmax + dropout + output projection in
hand-written CUDA, in four variants: self/encdec attention, each with an
optional pre-LayerNorm + residual-add ("norm_add"). Here the projection
GEMMs are XLA (MXU, fp32 accumulation), the attention core is the Pallas
flash kernel (``apex_tpu.ops.flash_attention`` — no (B,H,S,S) tensor),
and norm_add uses the Pallas FusedLayerNorm.

Layout: inputs are ``(T, B, H)`` sequence-first, the reference's
convention (torch ``MultiheadAttention`` compatible). ``key_padding_mask``
is ``(B, S_k)`` boolean, True = masked.

Attention-probability dropout falls back to the composed path (the flash
kernel does not fuse dropout — same policy as the reference's fmha tier,
which targets inference/eval and MLPerf's dropout-free phase).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.ops.flash_attention import FILL, flash_attention


def _attend(q, k, v, key_mask, dropout_rate, deterministic, rng, scale):
    """(B, H, S, D) attention via flash when dropout is inactive."""
    if deterministic or dropout_rate == 0.0:
        return flash_attention(q, k, v, key_mask, False, scale)
    # composed path with probability dropout (training-time parity with
    # the reference's dropout-enabled kernels)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :], FILL, s)
    p = jax.nn.softmax(s, axis=-1)
    keep = 1.0 - dropout_rate
    mask = jax.random.bernoulli(rng, keep, p.shape)
    p = jnp.where(mask, p / keep, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


class SelfMultiheadAttn(nn.Module):
    """Reference: ``SelfMultiheadAttn(embed_dim, num_heads, dropout,
    bias, include_norm_add, impl)``."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"  # parity knob; both impls map to the same kernels
    params_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, query, key_padding_mask=None,
                 is_training: bool = True):
        if self.embed_dim % self.num_heads:
            raise ValueError("num_heads must divide embed_dim")
        T, B, H = query.shape
        hd = H // self.num_heads
        scale = 1.0 / (hd ** 0.5)

        residual = query
        if self.include_norm_add:
            query = FusedLayerNorm(H, name="lyr_nrm")(query)

        qkv = nn.Dense(3 * H, use_bias=self.bias,
                       param_dtype=self.params_dtype,
                       kernel_init=nn.initializers.xavier_uniform(),
                       name="qkv_proj")(query)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # (T, B, H) -> (B, nh, T, hd)
            return t.reshape(T, B, self.num_heads, hd).transpose(1, 2, 0, 3)

        rng = (self.make_rng("dropout")
               if is_training and self.dropout > 0.0 else None)
        ctx = _attend(heads(q), heads(k), heads(v), key_padding_mask,
                      self.dropout, not is_training, rng, scale)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(T, B, H)

        out = nn.Dense(H, use_bias=self.bias,
                       param_dtype=self.params_dtype,
                       kernel_init=nn.initializers.xavier_uniform(),
                       name="out_proj")(ctx)
        if self.include_norm_add:
            out = out + residual
        return out.astype(residual.dtype)  # preserve the input dtype


class EncdecMultiheadAttn(nn.Module):
    """Reference: ``EncdecMultiheadAttn`` — queries from the decoder,
    keys/values from the encoder memory."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    params_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, query, key, key_padding_mask=None,
                 is_training: bool = True):
        if self.embed_dim % self.num_heads:
            raise ValueError("num_heads must divide embed_dim")
        Tq, B, H = query.shape
        Tk = key.shape[0]
        hd = H // self.num_heads
        scale = 1.0 / (hd ** 0.5)

        residual = query
        if self.include_norm_add:
            query = FusedLayerNorm(H, name="lyr_nrm")(query)

        q = nn.Dense(H, use_bias=self.bias, param_dtype=self.params_dtype,
                     kernel_init=nn.initializers.xavier_uniform(),
                     name="q_proj")(query)
        kv = nn.Dense(2 * H, use_bias=self.bias,
                      param_dtype=self.params_dtype,
                      kernel_init=nn.initializers.xavier_uniform(),
                      name="kv_proj")(key)
        k, v = jnp.split(kv, 2, axis=-1)

        def heads(t, L):
            return t.reshape(L, B, self.num_heads, hd).transpose(1, 2, 0, 3)

        rng = (self.make_rng("dropout")
               if is_training and self.dropout > 0.0 else None)
        ctx = _attend(heads(q, Tq), heads(k, Tk), heads(v, Tk),
                      key_padding_mask, self.dropout, not is_training, rng,
                      scale)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(Tq, B, H)

        out = nn.Dense(H, use_bias=self.bias,
                       param_dtype=self.params_dtype,
                       kernel_init=nn.initializers.xavier_uniform(),
                       name="out_proj")(ctx)
        if self.include_norm_add:
            out = out + residual
        return out.astype(residual.dtype)  # preserve the input dtype
