"""Contrib multihead_attn (reference: ``apex/contrib/multihead_attn``)."""

from apex_tpu.contrib.multihead_attn.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)

__all__ = ["EncdecMultiheadAttn", "SelfMultiheadAttn"]
