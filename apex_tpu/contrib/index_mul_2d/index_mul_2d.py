"""Fused gather-multiply (reference: ``apex/contrib/index_mul_2d/`` +
``apex/contrib/csrc/index_mul_2d/``, SURVEY.md §2.2 contrib misc —
an openfold hot op).

``out[i] = in1[idx[i]] * in2[i]``: the reference fuses the gather and
multiply to avoid a materialized gathered copy; XLA performs the same
fusion on ``in1[idx] * in2``, so this is API parity with the gradient
handled by autodiff (scatter-add into ``in1``)."""

from __future__ import annotations

import jax.numpy as jnp


def index_mul_2d(in1, in2, idx):
    """in1: (N, D); in2: (M, D); idx: (M,) int into in1. Returns (M, D)."""
    return in1[idx] * in2
