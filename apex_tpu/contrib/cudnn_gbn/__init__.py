"""Contrib cudnn_gbn (reference: ``apex/contrib/cudnn_gbn`` — the
cudnn-frontend group BatchNorm). Same semantics as the bnp groupbn tier:
NHWC BatchNorm with cross-replica stats over device subgroups, so
:class:`GroupBatchNorm2d` is the groupbn module under the reference's
cudnn_gbn class name."""

from apex_tpu.contrib.cudnn_gbn.batch_norm import GroupBatchNorm2d

__all__ = ["GroupBatchNorm2d"]
