"""GroupBatchNorm2d (reference: ``apex/contrib/cudnn_gbn/batch_norm.py``).

The reference constructor is ``GroupBatchNorm2d(num_features,
group_size, ...)``; this factory preserves that positional signature
(a flax dataclass subclass would misbind ``group_size`` into ``eps``)
and returns the groupbn module that implements the semantics."""

from typing import Optional

from apex_tpu.contrib.groupbn.batch_norm import BatchNorm2d_NHWC


def GroupBatchNorm2d(num_features: int, group_size: int = 1, *,
                     eps: float = 1e-5, momentum: float = 0.1,
                     fuse_relu: bool = False,
                     axis_name: Optional[str] = None) -> BatchNorm2d_NHWC:
    """Reference call-site parity: ``GroupBatchNorm2d(C, group)`` →
    NHWC BatchNorm with cross-replica stats over ``group``-sized device
    subgroups of ``axis_name``."""
    return BatchNorm2d_NHWC(
        num_features=num_features, eps=eps, momentum=momentum,
        fuse_relu=fuse_relu, bn_group=group_size, axis_name=axis_name)
