"""Fused ResNet bottleneck + spatial-parallel halo exchange (reference:
``apex/contrib/bottleneck/{bottleneck,halo_exchangers}.py`` +
``apex/contrib/csrc/{bottleneck,peer_memory,nccl_p2p}/``, SURVEY.md
§2.3 "spatial parallelism" / §2.5).

Two pieces:

- :class:`Bottleneck`: the 1x1 → 3x3 → 1x1 conv stack with NHWC
  BatchNorm and the fused residual add+ReLU epilogue
  (:class:`~apex_tpu.contrib.groupbn.BatchNorm2d_NHWC` with ``z=``).
  The reference hand-fuses this chain in CUDA; XLA fuses the NHWC
  conv+BN+ReLU chain natively on TPU.

- :class:`HaloExchanger1d` + :class:`SpatialBottleneck`: spatial
  parallelism — the image's H dim sharded across devices, with 1-row
  halos exchanged between neighbors so the 3x3 conv sees its cross-shard
  receptive field. The reference moves halos over CUDA P2P / NCCL
  send-recv (``PeerHaloExchanger1d``); on TPU the same exchange is two
  ``lax.ppermute`` shifts over ICI.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC


def _conv(features, kernel, strides=1, name=None):
    return nn.Conv(features, (kernel, kernel), strides=(strides, strides),
                   padding="SAME" if kernel > 1 else "VALID",
                   use_bias=False, param_dtype=jnp.float32,
                   kernel_init=nn.initializers.he_normal(), name=name)


class Bottleneck(nn.Module):
    """Reference ``Bottleneck(in_channels, bottleneck_channels,
    out_channels, stride)`` — NHWC, BN-fused residual add+ReLU."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    use_cudnn: bool = False  # parity knob; ignored (XLA convs)
    bn_group: int = 1                 # cross-replica BN (bnp group)
    axis_name: Optional[str] = None

    def _bn(self, ch, name, fuse_relu=False):
        return BatchNorm2d_NHWC(ch, fuse_relu=fuse_relu,
                                bn_group=self.bn_group,
                                axis_name=self.axis_name, name=name)

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = _conv(self.bottleneck_channels, 1, name="conv1")(x)
        y = self._bn(self.bottleneck_channels, "bn1", fuse_relu=True)(
            y, train=train)
        y = _conv(self.bottleneck_channels, 3, self.stride,
                  name="conv2")(y)
        y = self._bn(self.bottleneck_channels, "bn2", fuse_relu=True)(
            y, train=train)
        y = _conv(self.out_channels, 1, name="conv3")(y)
        if self.stride != 1 or self.in_channels != self.out_channels:
            residual = _conv(self.out_channels, 1, self.stride,
                             name="downsample_conv")(x)
            residual = self._bn(self.out_channels, "downsample_bn")(
                residual, train=train)
        # bn3 with the fused add+relu epilogue (z = residual)
        return self._bn(self.out_channels, "bn3", fuse_relu=True)(
            y, z=residual, train=train)


class HaloExchanger1d:
    """Exchange ``halo`` rows with ring neighbors along a mesh axis
    (reference: ``PeerHaloExchanger1d`` over GPU P2P; here ppermute).

    Operates on the H-sharded (N, H_local, W, C) tensor inside
    ``shard_map``: returns the tensor padded to
    (N, halo + H_local + halo, W, C) with the neighbors' edge rows (zero
    at the true image borders — the first/last shard of each group).

    ``group_size`` (0 = the whole axis) partitions the axis into
    independent spatial groups of consecutive ranks, each holding one
    image: halos never cross group borders (the reference's
    ``peer_group_size``)."""

    def __init__(self, axis_name: str, halo: int = 1, group_size: int = 0):
        self.axis_name = axis_name
        self.halo = halo
        self.group_size = group_size

    def __call__(self, x):
        axis = self.axis_name
        n = jax.lax.psum(1, axis)
        idx = jax.lax.axis_index(axis)
        g = self.group_size or n
        if g > n or n % g:
            # a partial trailing group would let the last rank's halo wrap
            # around the ring to rank 0 — cross-image leakage
            raise ValueError(
                f"group_size ({g}) must divide the '{axis}' axis size "
                f"({n})")
        fwd = [(i, (i + 1) % n) for i in range(n)]
        bwd = [(i, (i - 1) % n) for i in range(n)]
        # my bottom rows -> next shard's top halo; my top rows -> prev's.
        # The permute stays a full ring: rows that would cross a group
        # border are zeroed below, so they never contribute.
        bottom = x[:, -self.halo:]
        top = x[:, :self.halo]
        from_prev = jax.lax.ppermute(bottom, axis, fwd)
        from_next = jax.lax.ppermute(top, axis, bwd)
        # zero halos at each group's image borders (no wraparound and no
        # cross-group receptive field)
        from_prev = jnp.where(idx % g == 0, jnp.zeros_like(from_prev),
                              from_prev)
        from_next = jnp.where(idx % g == g - 1, jnp.zeros_like(from_next),
                              from_next)
        return jnp.concatenate([from_prev, x, from_next], axis=1)


class SpatialBottleneck(nn.Module):
    """Reference ``SpatialBottleneck``: the bottleneck with its 3x3 conv
    computed on H-sharded activations + halo exchange. Run inside
    ``shard_map`` with ``spatial_axis`` in scope; stride-2 spatial
    sharding is not supported (the reference's spatial group also only
    runs stride-1 segments)."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    spatial_axis: str = "spatial"
    halo: int = 1
    bn_group: int = 1                 # cross-replica BN (the reference
    axis_name: Optional[str] = None   # runs group BN on spatial groups)
    # partition the spatial axis into independent groups of this many
    # consecutive ranks, one image per group (the reference wires
    # peer_group_size from PeerMemoryPool into the bottleneck's halo
    # exchange); 0 = the whole axis is one group
    peer_group_size: int = 0

    _bn = Bottleneck._bn

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.halo != 1:
            raise ValueError(
                "SpatialBottleneck supports halo=1 only: the 3x3 conv's "
                "valid-in-H geometry consumes exactly one halo row per "
                "side (use HaloExchanger1d directly for wider halos)")
        residual = x
        y = _conv(self.bottleneck_channels, 1, name="conv1")(x)
        y = self._bn(self.bottleneck_channels, "bn1", fuse_relu=True)(
            y, train=train)
        # 3x3 with cross-shard receptive field: pad with neighbor halos,
        # convolve VALID-in-H, trimming the halo contribution exactly
        exchanger = HaloExchanger1d(self.spatial_axis, self.halo,
                                    group_size=self.peer_group_size)
        y = exchanger(y)
        y = nn.Conv(self.bottleneck_channels, (3, 3), strides=(1, 1),
                    padding=((0, 0), (1, 1)), use_bias=False,
                    param_dtype=jnp.float32,
                    kernel_init=nn.initializers.he_normal(),
                    name="conv2")(y)
        y = self._bn(self.bottleneck_channels, "bn2", fuse_relu=True)(
            y, train=train)
        y = _conv(self.out_channels, 1, name="conv3")(y)
        if self.in_channels != self.out_channels:
            residual = _conv(self.out_channels, 1, name="downsample_conv")(x)
            residual = self._bn(self.out_channels, "downsample_bn")(
                residual, train=train)
        return self._bn(self.out_channels, "bn3", fuse_relu=True)(
            y, z=residual, train=train)
