"""Contrib bottleneck + spatial halo exchange (reference:
``apex/contrib/bottleneck``, ``apex/contrib/peer_memory``)."""

from apex_tpu.contrib.bottleneck.bottleneck import (
    Bottleneck,
    HaloExchanger1d,
    SpatialBottleneck,
)

__all__ = ["Bottleneck", "HaloExchanger1d", "SpatialBottleneck"]
