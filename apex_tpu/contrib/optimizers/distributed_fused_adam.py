"""ZeRO-style sharded-data-parallel fused optimizers.

Rebuild of ``apex/contrib/optimizers/distributed_fused_adam.py`` and
``distributed_fused_lamb.py`` (SURVEY.md §2.3 "ZeRO-style sharded DP"):
the reference reduce-scatters gradients into per-rank fp32 master shards,
runs the fused update on the local shard only, and all-gathers the
updated parameters — optimizer state is sharded ``world_size``-ways, so
fp32 (master, m, v) cost drops from 12 bytes/param to 12/dp.

TPU-native design: the whole step is three collectives on a flat fp32
stream inside ``shard_map`` over the data-parallel mesh axis —

1. ``psum_scatter`` the flattened gradient (tiled): each rank receives
   the SUMMED gradient slice for its shard — the reduce-scatter the
   reference issues per bucket, here one XLA collective that rides ICI.
   ``predivide_grads`` (default) divides by dp for the DDP gradient mean.
2. the Adam/LAMB math on the rank's shard, DELEGATED to the same
   ``ops.multi_tensor`` update functions the unsharded optimizers use,
   so sharded and unsharded trajectories agree by construction. The
   shard is held as a LANE-shaped ``(shard/128, 128)`` 2-D buffer, not
   1-D: elementwise update streams over a huge 1-D vector invite XLA's
   horizontal [N,2] packing whose ``T(8,128)`` tiled layout pads the
   size-2 minor dim 64x (the 94 GB pathology documented in
   ``ops/multi_tensor.py``); a lane-major 2-D shape tiles natively.
   LAMB's per-tensor trust ratios are computed across shard boundaries:
   each rank segment-sums its shard's squared entries into per-tensor
   partials and one ``psum`` completes the exact norms — the analog of
   the reference's partial-norm + allreduce in
   ``distributed_fused_lamb._pipeline_block_reductions``. Segment ids
   come from a ``searchsorted`` over the static leaf-offset table, O(N/dp)
   per device (never a full-length N map).
3. ``all_gather`` (tiled) of the updated shard back to the full flat
   vector. When every parameter shares one low-precision dtype (the O2
   bf16 case) the shard is cast BEFORE the gather, halving the dominant
   per-step collective (the reference all-gathers in model dtype for the
   same reason); mixed-dtype models gather in fp32.

Unlike the CUDA version there are no overlap hooks, streams, or bucket
knobs to manage: XLA's latency-hiding scheduler overlaps the collectives
with surrounding compute, which is what the reference's
``overlap_reductions``/side-stream machinery hand-builds.

Both optimizers follow the functional ``init/step`` contract of
``apex_tpu.optimizers`` (skip_if = amp overflow no-op, lr override). Two
execution modes select how the three collectives are spelled:

- ``flat_mode="collective"`` (default): the explicit ``psum_scatter`` /
  ``psum`` / ``all_gather`` spelling above — must be called inside
  ``shard_map`` with ``process_group`` in scope.
- ``flat_mode="global"``: GLOBAL-math GSPMD spelling for the sharded
  fused train step (``build_train_step(mesh=...)``). State buffers hold
  the FULL padded flat stream as a lane-shaped ``(padded/128, 128)``
  array committed to ``P(process_group, None)`` over ``mesh`` — each
  rank materializes only its row block, the same 12/dp bytes/param as
  the collective mode — and ``with_sharding_constraint`` steers the XLA
  SPMD partitioner to insert the reduce+scatter and gather collectives.
  Two constraint placements are load-bearing (see
  ``_global_grad_rows``): gradients replicate BEFORE the flatten, and
  the flat stream materializes replicated before the ZeRO slice.
  Without a ``mesh`` the global mode degenerates to a world-of-1 local
  optimizer (the meshless arm of the (1,1) bit-identity certification).
  ``predivide_grads`` is ignored: global math is already mean-correct.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops._common import LANE, round_up
from apex_tpu.ops.multi_tensor import (
    ADAM_MODE_ADAMW,
    ADAM_MODE_L2,
    multi_tensor_adam,
    multi_tensor_lamb_stage1,
)
from apex_tpu.optimizers._base import FusedOptimizer
from apex_tpu.utils.pytree import ravel_list, tree_select, unravel_list


class _FlatMeta:
    """Static flattening metadata for a params pytree (trace-time only).

    The padded length is a multiple of ``world * LANE`` so every rank's
    shard reshapes exactly to ``(rows, LANE)`` (see module docstring on
    why the shard must be lane-shaped)."""

    def __init__(self, params, world_size: int):
        leaves = jax.tree.leaves(params)
        self.treedef = jax.tree.structure(params)
        self.meta = [(l.shape, l.dtype, l.size) for l in leaves]
        self.sizes = [m[2] for m in self.meta]
        self.dtypes = [m[1] for m in self.meta]
        self.total = sum(self.sizes)
        self.world = world_size
        self.padded = round_up(max(self.total, 1), world_size * LANE)
        self.shard = self.padded // world_size
        self.rows = self.shard // LANE
        self.num_leaves = len(leaves)
        # static cumulative end-offsets for per-tensor segment lookup
        self.offsets = np.cumsum(self.sizes).astype(np.int32)
        # gather in model dtype when it is a single low-precision dtype
        # (halves the all_gather); otherwise keep the fp32 master stream
        uniq = set(self.dtypes)
        if len(uniq) == 1 and jnp.dtype(next(iter(uniq))).itemsize < 4:
            self.gather_dtype = next(iter(uniq))
        else:
            self.gather_dtype = jnp.float32

    def flatten(self, tree):
        """apex_C.flatten analog (fp32 stream) + ZeRO padding."""
        flat, _ = ravel_list(
            [l.astype(jnp.float32) for l in jax.tree.leaves(tree)])
        if self.padded != self.total:
            flat = jnp.pad(flat, (0, self.padded - self.total))
        return flat

    def unflatten(self, flat):
        leaves = unravel_list(flat[:self.total], self.meta)
        return jax.tree.unflatten(self.treedef, leaves)

    def shard_segment_ids(self, rank):
        """(rows, LANE) int32 leaf index per shard element, computed
        arithmetically from the static offset table (O(shard), not O(N));
        the padding tail maps to the dummy bucket ``num_leaves``."""
        pos = rank * self.shard + jnp.arange(self.shard, dtype=jnp.int32)
        seg = jnp.searchsorted(jnp.asarray(self.offsets), pos, side="right")
        return seg.reshape(self.rows, LANE)

    def shard_slice(self, flat, rank):
        """This rank's lane-shaped shard of a (padded,) stream."""
        return jax.lax.dynamic_slice(
            flat, (rank * self.shard,), (self.shard,)
        ).reshape(self.rows, LANE)


class ShardedOptState(NamedTuple):
    step: jnp.ndarray
    exp_avg: jnp.ndarray      # (shard/128, 128) fp32
    exp_avg_sq: jnp.ndarray   # (shard/128, 128) fp32
    master: jnp.ndarray       # (shard/128, 128) fp32 master params


@dataclasses.dataclass(frozen=True)
class _DistributedFlatOptimizer(FusedOptimizer):
    """Shared reduce-scatter → shard-update → all-gather machinery."""

    process_group: str = "data"   # mesh axis the optimizer shards over
    group_size: int = 0           # 0 = resolve from parallel_state
    predivide_grads: bool = True  # divide the psum'd grad by dp (DDP mean)
    flat_mode: str = "collective"  # "collective" (shard_map) | "global"
    mesh: Any = None              # GSPMD mesh for flat_mode="global"

    def __post_init__(self):
        if self.flat_mode not in ("collective", "global"):
            raise ValueError(
                f"flat_mode must be 'collective' or 'global', "
                f"got {self.flat_mode!r}")
        if self.mesh is not None and self.flat_mode != "global":
            raise ValueError(
                "mesh= requires flat_mode='global' (the collective mode "
                "runs inside shard_map and never sees a Mesh object)")

    def _world(self) -> int:
        if self.mesh is not None:
            return int(self.mesh.shape[self.process_group])
        if self.flat_mode == "global":
            # meshless global math has no axis to shard over: a single
            # world-of-1 "shard" holding the whole padded stream
            if self.group_size not in (0, 1):
                raise ValueError(
                    f"flat_mode='global' without mesh= is the world-of-1 "
                    f"local optimizer; group_size={self.group_size} needs "
                    f"a mesh to shard over")
            return 1
        if self.group_size:
            return self.group_size
        from apex_tpu.transformer import parallel_state

        return parallel_state.get_data_parallel_world_size()

    def _meta(self, params) -> _FlatMeta:
        """The flattening metadata, computed ONCE per (world, treedef,
        leaf-shapes) and cached on the config object — the padding is
        counted a single time and :meth:`stats` reports it without
        recomputing (or disagreeing with) what init/step used."""
        leaves = jax.tree.leaves(params)
        key = (self._world(), jax.tree.structure(params),
               tuple((tuple(l.shape), jnp.dtype(l.dtype).name)
                     for l in leaves))
        cached = getattr(self, "_meta_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        meta = _FlatMeta(params, self._world())
        object.__setattr__(self, "_meta_cache", (key, meta))
        return meta

    def stats(self) -> dict:
        """Flat-buffer accounting of the LAST init/step geometry —
        ``flat_pad_elems`` is the ZeRO padding the donation-alias and
        bench memory records must count as real bytes (the padded tail
        lives in every master/m/v buffer). Raises before the first
        ``init``/``step`` call (no geometry has been built yet)."""
        cached = getattr(self, "_meta_cache", None)
        if cached is None:
            raise ValueError(
                "stats() before init()/step(): the flat-buffer geometry "
                "is built on first use")
        meta = cached[1]
        return {
            "flat_total_elems": int(meta.total),
            "flat_padded_elems": int(meta.padded),
            "flat_pad_elems": int(meta.padded - meta.total),
            "flat_shard_elems": int(meta.shard),
            "flat_world": int(meta.world),
            # fp32 master + exp_avg + exp_avg_sq per shard
            "opt_state_bytes_per_shard": int(meta.shard) * 4 * 3,
        }

    # -- GSPMD global-math spelling (flat_mode="global") -----------------

    def _zspec(self):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh,
                             PartitionSpec(self.process_group, None))

    def _rep(self):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    def _global_grad_rows(self, grads, meta):
        """The reduce-scatter leg, GSPMD spelling: constrain the grad
        leaves REPLICATED before the flatten (so the reshape/concat
        into the flat stream is shard-local — straight into the ZeRO
        spec the partitioner reshards TP-sharded leaves with an
        all-to-all and, at combined (B, M) meshes on the XLA vintage we
        pin, mis-partitions the concat), then materialize the stream
        replicated and slice to ``P(process_group, None)`` — lowered as
        the cross-batch reduction + scatter of exactly one flat
        reduce-scatter (XLA:CPU spells it all-reduce + slice; the
        ``alt_min_ops`` contract accepts both). No predivide: global
        math already averages over the global batch."""
        if self.mesh is not None:
            grads = jax.tree.map(
                lambda l: jax.lax.with_sharding_constraint(l, self._rep()),
                grads)
        rows = meta.flatten(grads).reshape(meta.padded // LANE, LANE)
        if self.mesh is not None:
            rows = jax.lax.with_sharding_constraint(rows, self._rep())
            rows = jax.lax.with_sharding_constraint(rows, self._zspec())
        return rows

    def _global_gather_params(self, new_master, meta, params):
        """The all-gather leg: one replicated materialization of the
        updated flat stream (cast to ``gather_dtype`` first — the
        collective moves the smaller payload), then shard-local
        unflatten; the train step re-constrains the leaves to their
        tensor-parallel specs (a local slice, no second collective).

        Each unflattened leaf is pinned replicated too: left to
        propagation, GSPMD pulls the consumer's tensor-parallel spec
        backward into the 1-D slice and then reshards the reshape with
        an all-to-all / collective-permute chain per leaf; pinning
        keeps the slice+reshape shard-local so the only resharding is
        the free replicated→TP slice downstream."""
        full = new_master.astype(meta.gather_dtype)
        if self.mesh is not None:
            full = jax.lax.with_sharding_constraint(full, self._rep())
        leaves = meta.unflatten(full.reshape(-1))
        if self.mesh is not None:
            leaves = jax.tree.map(
                lambda l: jax.lax.with_sharding_constraint(l, self._rep()),
                leaves)
        return leaves

    def init(self, params) -> ShardedOptState:
        """Build the optimizer-state shard. ``flat_mode="collective"``
        must run inside ``shard_map`` with ``process_group`` in scope
        (uses ``axis_index``); ``flat_mode="global"`` runs eagerly and
        commits the full lane-shaped stream sharded over ``mesh``."""
        meta = self._meta(params)
        if self.flat_mode == "global":
            host = jax.tree.map(
                lambda x: jnp.asarray(jax.device_get(x)), params)
            rows_total = meta.padded // LANE
            master = meta.flatten(host).reshape(rows_total, LANE)
            # distinct zero buffers: a donated state must never hold the
            # same array twice (double-donation raises on XLA:CPU)
            m = jnp.zeros((rows_total, LANE), jnp.float32)
            v = jnp.zeros((rows_total, LANE), jnp.float32)
            step = jnp.zeros((), jnp.int32)
            if self.mesh is not None:
                zspec = self._zspec()
                master = jax.device_put(master, zspec)
                m = jax.device_put(m, zspec)
                v = jax.device_put(v, zspec)
                step = jax.device_put(step, self._rep())
            return ShardedOptState(step=step, exp_avg=m, exp_avg_sq=v,
                                   master=master)
        rank = jax.lax.axis_index(self.process_group)
        master = meta.shard_slice(meta.flatten(params), rank)
        zeros = jnp.zeros((meta.rows, LANE), jnp.float32)
        return ShardedOptState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=zeros,
            exp_avg_sq=zeros,
            master=master,
        )

    def _grad_rows(self, grads, meta):
        if self.flat_mode == "global":
            return self._global_grad_rows(grads, meta)
        return self._reduce_scatter_grads(grads, meta)

    def _reduce_scatter_grads(self, grads, meta):
        flat_g = meta.flatten(grads)
        gshard = jax.lax.psum_scatter(
            flat_g, self.process_group, scatter_dimension=0, tiled=True)
        if self.predivide_grads:
            gshard = gshard / meta.world
        return gshard.reshape(meta.rows, LANE)

    def _gather(self, new_master, meta, params):
        if self.flat_mode == "global":
            return self._global_gather_params(new_master, meta, params)
        return self._gather_params(new_master, meta, params)

    def _gather_params(self, new_master, meta, params):
        full = jax.lax.all_gather(
            new_master.reshape(-1).astype(meta.gather_dtype),
            self.process_group, axis=0, tiled=True)
        return meta.unflatten(full)

    def _finish(self, skip_if, new_params, new_state, params, state):
        if skip_if is None:
            return new_params, new_state
        return (tree_select(skip_if, params, new_params),
                tree_select(skip_if, state, new_state))


@dataclasses.dataclass(frozen=True)
class DistributedFusedAdam(_DistributedFlatOptimizer):
    """Reference: ``apex.contrib.optimizers.DistributedFusedAdam`` —
    Adam/AdamW with ZeRO-sharded fp32 state over the data axis.

    The shard update IS ``multi_tensor_adam`` (the unsharded FusedAdam's
    math) applied to the lane-shaped shard, so trajectories agree with
    the unsharded optimizer to fp32 roundoff."""

    lr: float = 1e-3
    bias_correction: bool = True
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    adam_w_mode: bool = True
    weight_decay: float = 0.0

    def step(self, grads, state: ShardedOptState, params, skip_if=None,
             lr=None):
        lr = self.lr if lr is None else lr
        meta = self._meta(params)
        step = state.step + 1

        g = self._grad_rows(grads, meta)
        new_p_l, new_m_l, new_v_l = multi_tensor_adam(
            0, None,
            [[g], [state.master], [state.exp_avg], [state.exp_avg_sq]],
            lr, self.betas[0], self.betas[1], self.eps, step,
            ADAM_MODE_ADAMW if self.adam_w_mode else ADAM_MODE_L2,
            self.bias_correction, self.weight_decay,
        )
        new_master, m, v = new_p_l[0], new_m_l[0], new_v_l[0]

        new_params = self._gather(new_master, meta, params)
        new_state = ShardedOptState(step, m, v, new_master)
        return self._finish(skip_if, new_params, new_state, params, state)


@dataclasses.dataclass(frozen=True)
class DistributedFusedLAMB(_DistributedFlatOptimizer):
    """Reference: ``apex.contrib.optimizers.DistributedFusedLAMB`` —
    two-stage LAMB with ZeRO-sharded fp32 state.

    Stage 1 (clip + moments + update direction) delegates to
    ``multi_tensor_lamb_stage1`` on the lane-shaped shard with the
    psum-completed global grad norm. Stage 2 cannot delegate: per-tensor
    trust ratios need per-tensor norms across shard boundaries —
    computed via the arithmetic segment map + one psum (see module
    docstring).

    ``grad_averaging`` matches FusedLAMB (folds beta3 only); the DDP mean
    division is the separate ``predivide_grads`` knob."""

    lr: float = 1e-3
    bias_correction: bool = True
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-6
    weight_decay: float = 0.01
    adam_w_mode: bool = True
    grad_averaging: bool = True
    max_grad_norm: float = 1.0
    use_nvlamb: bool = False

    def __post_init__(self):
        if not self.adam_w_mode:
            raise RuntimeError(
                "DistributedFusedLAMB only supports adam_w_mode, matching "
                "the reference kernel.")

    def step(self, grads, state: ShardedOptState, params, skip_if=None,
             lr=None):
        lr = self.lr if lr is None else lr
        meta = self._meta(params)
        step = state.step + 1
        nbuckets = meta.num_leaves + 1  # + dummy padding bucket
        if self.flat_mode == "global":
            # full-stream segment map: in global math every rank sees
            # the whole (padded/128, 128) buffer (sharded), so segment
            # ids cover all of it and no rank index exists
            pos = jnp.arange(meta.padded, dtype=jnp.int32)
            seg = jnp.searchsorted(jnp.asarray(meta.offsets), pos,
                                   side="right").reshape(-1, LANE)
        else:
            rank = jax.lax.axis_index(self.process_group)
            seg = meta.shard_segment_ids(rank)

        g = self._grad_rows(grads, meta)
        p = state.master

        # stage 0: global grad norm (partial on shard, psum completes
        # it; in global math the plain sum is already global — the
        # partitioner inserts the reduction)
        if self.flat_mode == "global":
            global_norm = jnp.sqrt(jnp.sum(g * g))
        else:
            global_norm = jnp.sqrt(
                jax.lax.psum(jnp.sum(g * g), self.process_group))

        # stage 1: clip + moments + update direction (shared math)
        updates, new_m, new_v = multi_tensor_lamb_stage1(
            0, None, [[g], [p], [state.exp_avg], [state.exp_avg_sq]],
            self.betas[0], self.betas[1], self.eps, step,
            self.bias_correction, self.weight_decay, self.grad_averaging,
            global_norm, self.max_grad_norm,
        )
        update, m, v = updates[0], new_m[0], new_v[0]

        # stage 2: exact per-tensor trust ratios across shard boundaries
        apply_ratio = self.use_nvlamb or self.weight_decay != 0.0
        if apply_ratio:
            w_sq = jnp.zeros((nbuckets,), jnp.float32).at[seg].add(p * p)
            u_sq = jnp.zeros((nbuckets,), jnp.float32).at[seg].add(
                update * update)
            if self.flat_mode == "global":
                w_norm, u_norm = jnp.sqrt(w_sq), jnp.sqrt(u_sq)
            else:
                w_norm = jnp.sqrt(jax.lax.psum(w_sq, self.process_group))
                u_norm = jnp.sqrt(jax.lax.psum(u_sq, self.process_group))
            ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                              w_norm / jnp.where(u_norm > 0, u_norm, 1.0),
                              1.0)
            step_scale = ratio[seg]
        else:
            step_scale = jnp.float32(1.0)
        new_master = p - lr * step_scale * update

        new_params = self._gather(new_master, meta, params)
        new_state = ShardedOptState(step, m, v, new_master)
        return self._finish(skip_if, new_params, new_state, params, state)
