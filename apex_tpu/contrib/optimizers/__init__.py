"""Contrib optimizers (reference: ``apex/contrib/optimizers/``).

Besides the distributed (ZeRO-style) optimizers, the reference keeps
deprecated copies of ``FP16_Optimizer``/``FusedAdam``/``FusedSGD`` under
contrib; those names resolve here to the maintained implementations
(``apex_tpu.fp16_utils`` / ``apex_tpu.optimizers``) rather than stale
forks — same import paths, one source of truth.
"""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
    ShardedOptState,
)
from apex_tpu.fp16_utils import FP16_Optimizer  # noqa: F401 (legacy path)
from apex_tpu.optimizers import FusedAdam, FusedSGD  # noqa: F401 (legacy)

__all__ = [
    "DistributedFusedAdam",
    "DistributedFusedLAMB",
    "ShardedOptState",
    "FP16_Optimizer",
    "FusedAdam",
    "FusedSGD",
]
