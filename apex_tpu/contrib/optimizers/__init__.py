"""Contrib optimizers (reference: ``apex/contrib/optimizers/``)."""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
    ShardedOptState,
)

__all__ = [
    "DistributedFusedAdam",
    "DistributedFusedLAMB",
    "ShardedOptState",
]
