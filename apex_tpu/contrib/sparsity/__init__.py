"""Contrib sparsity / ASP (reference: ``apex/contrib/sparsity``)."""

from apex_tpu.contrib.sparsity.asp import (
    ASP,
    MaskedOptimizer,
    apply_masks,
    compute_sparse_masks,
    m4n2_1d_mask,
    sparsity_ratio,
)

__all__ = ["ASP", "MaskedOptimizer", "apply_masks", "compute_sparse_masks",
           "m4n2_1d_mask", "sparsity_ratio"]
