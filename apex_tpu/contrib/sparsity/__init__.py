"""Contrib sparsity / ASP (reference: ``apex/contrib/sparsity``)."""

from apex_tpu.contrib.sparsity.asp import (
    ASP,
    MaskedOptimizer,
    apply_masks,
    compute_sparse_masks,
    m4n2_1d_mask,
    sparsity_ratio,
)
from apex_tpu.contrib.sparsity.permutation_search import (
    magnitude_efficacy,
    permuted_m4n2_mask,
    search_for_good_permutation,
)

__all__ = ["ASP", "MaskedOptimizer", "apply_masks", "compute_sparse_masks",
           "m4n2_1d_mask", "magnitude_efficacy", "permuted_m4n2_mask",
           "search_for_good_permutation", "sparsity_ratio"]
