"""ASP — automatic structured (2:4) sparsity (reference:
``apex/contrib/sparsity/{asp,sparse_masklib}.py``, SURVEY.md §2.5).

The reference computes magnitude-based N:M masks (default ``m4n2_1d``:
in every group of 4 consecutive weights along the reduction dim, keep
the 2 largest |w|), multiplies them into the weights, and monkey-patches
``optimizer.step`` to re-apply masks after every update so pruned slots
stay zero through training.

Functional TPU form: masks are a pytree computed once
(:func:`compute_sparse_masks`), applied with :func:`apply_masks`, and
kept live through training by :class:`MaskedOptimizer` (the
``init_optimizer_for_pruning`` analog — wraps any
``apex_tpu.optimizers`` fused optimizer and re-masks params AND fp32
masters after each step). The mask math itself is one fused
reshape/top-2 pass per weight; XLA compiles it into a handful of
elementwise ops (no sort).

The permutation-search accuracy refinement
(``permutation_search_kernels``) lives in
:mod:`apex_tpu.contrib.sparsity.permutation_search` — pass
``allow_permutation=True`` (the reference knob) to
:func:`compute_sparse_masks` to mask in the searched channel grouping.

Note on layout: weights here are ``(in, out)`` (JAX convention; torch is
``(out, in)``), so groups run along axis 0 — the contraction dim, which
is what 2:4 sparse matrix units consume in both layouts.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


def m4n2_1d_mask(w) -> jnp.ndarray:
    """Boolean keep-mask: 2 largest |w| in each group of 4 along axis 0.
    (Reference ``mask_calculator="m4n2_1d"``.)"""
    if w.shape[0] % 4:
        raise ValueError(f"axis 0 ({w.shape[0]}) not divisible by 4")
    flat = jnp.abs(w.astype(jnp.float32)).reshape(w.shape[0] // 4, 4, -1)
    # rank within each group of 4 without a sort: count strictly-greater
    # entries (ties broken by index so exactly 2 survive)
    a = flat[:, :, None, :]
    b = flat[:, None, :, :]
    idx = jnp.arange(4)
    tie = (a == b) & (idx[None, :, None, None] > idx[None, None, :, None])
    greater = (b > a) | tie
    rank = greater.sum(axis=2)  # 0 = largest
    keep = rank < 2
    return keep.reshape(w.shape)


_CALCULATORS = {"m4n2_1d": m4n2_1d_mask}


def _eligible(path_name: str, leaf, allowed_layer_names,
              disallowed_layer_names) -> bool:
    if leaf.ndim != 2 or leaf.shape[0] % 4:
        return False
    if allowed_layer_names is not None:
        return any(n in path_name for n in allowed_layer_names)
    return not any(n in path_name for n in disallowed_layer_names)


def compute_sparse_masks(params, mask_calculator: str = "m4n2_1d",
                         allowed_layer_names=None,
                         disallowed_layer_names=("embedding", "norm",
                                                 "bias"),
                         allow_permutation: bool = False):
    """Mask pytree: a boolean keep-mask for every eligible 2-D weight,
    ``None`` elsewhere (embeddings/norms/biases by default, mirroring the
    reference's module-type allowlist).

    ``allow_permutation`` (the reference knob of the same name): run the
    offline channel-permutation search per weight
    (``permutation_search.search_for_good_permutation``) and compute the
    mask in the found grouping, mapped back to the original row order —
    more retained magnitude, hence less pruning damage."""
    calc = _CALCULATORS[mask_calculator]
    if allow_permutation and mask_calculator != "m4n2_1d":
        raise ValueError(
            f"allow_permutation=True searches for m4n2 groupings; it does "
            f"not compose with mask_calculator={mask_calculator!r}")
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree.structure(params)
    masks = []
    for path, leaf in flat:
        name = "/".join(str(getattr(p, "key", p)) for p in path).lower()
        if not _eligible(name, leaf, allowed_layer_names,
                         disallowed_layer_names):
            masks.append(None)
        elif allow_permutation:
            from apex_tpu.contrib.sparsity.permutation_search import (
                permuted_m4n2_mask,
            )

            masks.append(permuted_m4n2_mask(leaf)[0])
        else:
            masks.append(calc(leaf))
    return jax.tree.unflatten(treedef, [m if m is not None else _NoMask()
                                        for m in masks])


class _NoMask:
    """Sentinel leaf meaning "leave this parameter dense"."""

    def __repr__(self):
        return "NoMask"


jax.tree_util.register_pytree_node(
    _NoMask, lambda n: ((), None), lambda aux, ch: _NoMask())


def apply_masks(params, masks):
    """Zero the pruned slots (reference: in-place ``weight.data *=
    mask``; functional here)."""
    def mask_one(p, m):
        if isinstance(m, _NoMask) or m is None:
            return p
        return (p * m.astype(p.dtype))

    return jax.tree.map(mask_one, params, masks,
                        is_leaf=lambda x: isinstance(x, _NoMask))


def sparsity_ratio(params, masks) -> float:
    """Fraction of weights pruned across masked leaves (diagnostics)."""
    pruned = total = 0
    for p, m in zip(jax.tree.leaves(params),
                    jax.tree.leaves(masks,
                                    is_leaf=lambda x: isinstance(x, _NoMask))):
        if isinstance(m, _NoMask):
            continue
        pruned += int(jnp.sum(~m))
        total += m.size
    return pruned / total if total else 0.0


@dataclasses.dataclass(frozen=True)
class MaskedOptimizer:
    """Reference ``ASP.init_optimizer_for_pruning``: after every inner
    step, re-apply the masks to params (and the fp32 master copies, so
    pruned slots cannot drift back through the master path)."""

    inner: Any
    masks: Any

    def init(self, params):
        return self.inner.init(apply_masks(params, self.masks))

    def step(self, grads, state, params, skip_if=None, lr=None):
        new_params, new_state = self.inner.step(
            grads, state, params, skip_if=skip_if, lr=lr)
        new_params = apply_masks(new_params, self.masks)
        if getattr(new_state, "master", None) is not None:
            new_state = new_state._replace(
                master=apply_masks(new_state.master, self.masks))
        return new_params, new_state


class ASP:
    """Class-method veneer matching the reference call sites::

        ASP.init_model_for_pruning(params)   # -> (masked_params, masks)
        opt = ASP.init_optimizer_for_pruning(opt)
        ASP.compute_sparse_masks()           # recompute + re-apply
    """

    _masks = None
    _params = None
    _config = None  # (calculator, allowed, disallowed, permutation)

    @classmethod
    def init_model_for_pruning(cls, params, mask_calculator="m4n2_1d",
                               allowed_layer_names=None,
                               disallowed_layer_names=("embedding", "norm",
                                                       "bias"),
                               allow_permutation: bool = False):
        cls._config = (mask_calculator, allowed_layer_names,
                       disallowed_layer_names, allow_permutation)
        cls._masks = compute_sparse_masks(
            params, mask_calculator, allowed_layer_names,
            disallowed_layer_names, allow_permutation=allow_permutation)
        cls._params = apply_masks(params, cls._masks)
        return cls._params, cls._masks

    @classmethod
    def init_optimizer_for_pruning(cls, optimizer):
        if cls._masks is None:
            raise RuntimeError(
                "call ASP.init_model_for_pruning before "
                "init_optimizer_for_pruning (reference asserts the same)")
        return MaskedOptimizer(optimizer, cls._masks)

    @classmethod
    def compute_sparse_masks(cls, params=None):
        """Recompute masks with the SAME calculator/name lists given to
        init_model_for_pruning (the reference's recompute-and-reapply)."""
        if cls._config is None:
            raise RuntimeError("call ASP.init_model_for_pruning first")
        if params is None:
            params = cls._params
        calc, allowed, disallowed, permute = cls._config
        cls._masks = compute_sparse_masks(params, calc, allowed, disallowed,
                                          allow_permutation=permute)
        cls._params = apply_masks(params, cls._masks)
        return cls._params, cls._masks

    @classmethod
    def is_sparsity_enabled(cls) -> bool:
        return cls._masks is not None

    @classmethod
    def restore_pruned_weights(cls):
        """Reference API: forget masks (weights stay as they are; the
        zeroed slots resume training dense)."""
        cls._masks = None
        cls._params = None
        cls._config = None
