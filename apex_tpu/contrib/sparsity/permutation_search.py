"""Permutation search for 2:4 structured sparsity.

Rebuild of ``apex/contrib/sparsity/permutation_search_kernels`` (SURVEY.md
§2.5 sparsity row): before computing N:M masks, find a permutation of the
input channels (rows here — groups run along axis 0, see asp.py) that
maximizes the magnitude retained by the 2-of-4 mask. Random channel
grouping loses accuracy when correlated channels land in one group of 4;
the reference's offline search recovers most of it.

Algorithm (the reference's core strategy, vectorized with numpy instead
of CUDA kernels — this is OFFLINE preprocessing, not a training-loop op):
repeated passes of exhaustive two-group re-splits. For every pair of
groups-of-4, evaluate all 35 ways to split their 8 channels into two new
groups and keep the best (the reference's ``Exhaustive_Search`` over
stripe-group pairs); passes repeat until a fixed point or ``max_passes``.
Each pair-evaluation is one vectorized top-2-of-4 reduction over all
output columns.

Where apex physically permutes the weights and rewires neighboring
layers (a torch graph pass), this functional form keeps weights in place
and returns the permutation + the mask mapped BACK to the original
order: the resulting mask is exactly "2:4-expressible under the found
permutation", which is the property the sparse matrix unit (or a sparse
kernel) consumes, without graph surgery.
"""

from __future__ import annotations

from itertools import combinations
from typing import Optional, Tuple

import numpy as np


def _retained_per_group(wabs: np.ndarray) -> np.ndarray:
    """wabs: (G, 4, C) |w| grouped rows -> (G,) magnitude kept by 2:4."""
    part = np.partition(wabs, 2, axis=1)[:, 2:, :]  # top-2 of each 4
    return part.sum(axis=(1, 2))


def magnitude_efficacy(w: np.ndarray, perm: Optional[np.ndarray] = None) -> float:
    """Total |w| retained by the m4n2 mask under ``perm`` (identity when
    None), normalized by total |w| — 1.0 means lossless pruning."""
    wabs = np.abs(np.asarray(w, np.float32))
    if perm is not None:
        wabs = wabs[perm]
    g = wabs.reshape(-1, 4, wabs.shape[-1])
    return float(_retained_per_group(g).sum() / max(wabs.sum(), 1e-30))


# the 35 ways to choose which 4 of 8 channels form the first group
# (complement forms the second; fixing channel 0 in the first group
# halves the C(8,4)=70 splits to the 35 distinct ones)
_SPLITS = np.asarray(
    [(0,) + c for c in combinations(range(1, 8), 3)], np.int64)
_COMPL = np.asarray(
    [[j for j in range(8) if j not in set(s)] for s in _SPLITS], np.int64)


def search_for_good_permutation(
    w,
    max_passes: int = 10,
    seed: int = 0,
    search_time_limit: float = 60.0,
    max_score_columns: int = 512,
) -> np.ndarray:
    """Find a row permutation of ``w`` (2-D, rows divisible by 4)
    maximizing the magnitude the m4n2 mask retains. Returns the
    permutation as an int array ``perm`` such that ``w[perm]`` is the
    well-grouped layout. Deterministic for a given seed.

    Reference: ``permutation_search_kernels.search_for_good_permutation``
    — same exhaustive two-group strategy, numpy-vectorized, with the
    reference's wall-clock budget (``search_time_limit`` seconds per
    weight; the search stops at the best permutation found so far) and
    column subsampling for the SCORING only (``max_score_columns``
    evenly-strided columns; the final mask is computed on the full
    weight, the sample only steers the heuristic — the reference's
    kernels bound their work the same two ways)."""
    import time as _time

    wabs = np.abs(np.asarray(w, np.float32))
    if wabs.ndim != 2 or wabs.shape[0] % 4:
        raise ValueError(
            f"permutation search needs a 2-D weight with rows divisible "
            f"by 4, got shape {wabs.shape}")
    R = wabs.shape[0]
    G = R // 4
    perm = np.arange(R)
    if G < 2:
        return perm
    rng = np.random.RandomState(seed)
    if wabs.shape[1] > max_score_columns:
        stride = wabs.shape[1] // max_score_columns
        wabs = wabs[:, ::stride][:, :max_score_columns]

    deadline = _time.monotonic() + search_time_limit
    cur = wabs[perm].reshape(G, 4, -1)
    retained = _retained_per_group(cur)

    for _ in range(max_passes):
        improved = False
        # randomized pass order decorrelates from initialization order
        pairs = [(a, b) for a in range(G) for b in range(a + 1, G)]
        rng.shuffle(pairs)
        for a, b in pairs:
            if _time.monotonic() > deadline:
                return perm
            eight = np.concatenate([cur[a], cur[b]], axis=0)  # (8, C)
            # all 35 re-splits at once: (35, 4, C) each side
            ga = eight[_SPLITS]
            gb = eight[_COMPL]
            score = (_retained_per_group(ga) + _retained_per_group(gb))
            best = int(np.argmax(score))
            if score[best] > retained[a] + retained[b] + 1e-7:
                improved = True
                sel_a, sel_b = _SPLITS[best], _COMPL[best]
                # update the permutation bookkeeping
                rows = np.concatenate(
                    [perm[a * 4:(a + 1) * 4], perm[b * 4:(b + 1) * 4]])
                perm[a * 4:(a + 1) * 4] = rows[sel_a]
                perm[b * 4:(b + 1) * 4] = rows[sel_b]
                cur[a] = eight[sel_a]
                cur[b] = eight[sel_b]
                ra = _retained_per_group(cur[a][None])[0]
                rb = _retained_per_group(cur[b][None])[0]
                retained[a], retained[b] = ra, rb
        if not improved:
            break
    return perm


def permuted_m4n2_mask(w, max_passes: int = 10, seed: int = 0):
    """(mask, perm): the m4n2 keep-mask computed in the searched
    permutation's grouping, mapped back to the ORIGINAL row order — the
    mask an accuracy-preserving 2:4 pruning actually applies."""
    import jax.numpy as jnp

    from apex_tpu.contrib.sparsity.asp import m4n2_1d_mask

    perm = search_for_good_permutation(w, max_passes=max_passes, seed=seed)
    w_np = np.asarray(w)
    mask_permuted = np.asarray(m4n2_1d_mask(jnp.asarray(w_np[perm])))
    inv = np.argsort(perm)
    return jnp.asarray(mask_permuted[inv]), perm
