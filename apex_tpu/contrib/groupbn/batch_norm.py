"""NHWC BatchNorm with fused add+ReLU (reference: ``apex/contrib/
groupbn/batch_norm.py`` + ``apex/contrib/csrc/groupbn/``, the MLPerf-
ResNet "bnp" extension; SURVEY.md §2.2/§2.5).

The reference's value is (a) NHWC layout, (b) the fused
``bn_fused_add_relu`` epilogue (BN + residual add + ReLU in one kernel),
and (c) cross-GPU "group" BN over small device groups. On TPU: NHWC is
native, XLA fuses the epilogue chain, and group sync is one Welford
``psum`` over a mesh axis (subgrouped via ``axis_index_groups`` —
the same machinery as :mod:`apex_tpu.parallel.sync_batchnorm`).

Functional state (running stats are carried, not mutated)::

    bn = BatchNorm2d_NHWC(64, fuse_relu=True)
    variables = bn.init(key, x, train=False)
    y, new_state = bn.apply(variables, x, z=residual, train=True,
                            mutable=["batch_stats"])
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class BatchNorm2d_NHWC(nn.Module):
    """Reference class name. ``bn_group``/``axis_name`` enable cross-
    replica stats over contiguous subgroups of ``bn_group`` devices on
    the mesh axis (the bnp multi-GPU group).

    ``momentum`` follows the torch/reference convention:
    ``running = (1 - momentum) * running + momentum * batch`` (default
    0.1) — call sites ported from apex keep their semantics."""

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    fuse_relu: bool = False
    bn_group: int = 1
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, z=None, train: bool = True):
        """x: (N, H, W, C); z: optional residual (the fused add input)."""
        C = self.num_features
        w = self.param("weight", nn.initializers.ones, (C,), jnp.float32)
        b = self.param("bias", nn.initializers.zeros, (C,), jnp.float32)
        running_mean = self.variable(
            "batch_stats", "running_mean",
            lambda: jnp.zeros((C,), jnp.float32))
        running_var = self.variable(
            "batch_stats", "running_var",
            lambda: jnp.ones((C,), jnp.float32))

        xf = x.astype(jnp.float32)
        if train:
            mean = xf.mean(axis=(0, 1, 2))
            var = xf.var(axis=(0, 1, 2))
            if self.bn_group > 1 and self.axis_name is not None:
                # combine (mean, mean_sq) within each bn_group-sized
                # subgroup of the axis (reference: the bnp device group)
                from apex_tpu.utils.collectives import psum_groups

                world = jax.lax.psum(1, self.axis_name)
                world = int(world) if not hasattr(world, "aval") else None
                if world is None:
                    raise RuntimeError(
                        "bn_group sync requires a static axis size")
                if world % self.bn_group:
                    raise ValueError(
                        f"axis size ({world}) not divisible by bn_group "
                        f"({self.bn_group})")
                groups = [list(range(g * self.bn_group,
                                     (g + 1) * self.bn_group))
                          for g in range(world // self.bn_group)]
                mean_sq = var + mean * mean
                mean = psum_groups(mean, self.axis_name,
                                   groups) / self.bn_group
                mean_sq = psum_groups(mean_sq, self.axis_name,
                                      groups) / self.bn_group
                var = mean_sq - mean * mean
            if not self.is_initializing():
                m = self.momentum  # torch convention: weight on the batch
                # torch/cudnn store the UNBIASED variance in running stats
                count = x.shape[0] * x.shape[1] * x.shape[2] * max(
                    self.bn_group, 1)
                unbiased = var * (count / max(count - 1, 1))
                running_mean.value = ((1 - m) * running_mean.value
                                      + m * mean)
                running_var.value = ((1 - m) * running_var.value
                                     + m * unbiased)
        else:
            mean, var = running_mean.value, running_var.value

        out = (xf - mean) * jax.lax.rsqrt(var + self.eps) * w + b
        if z is not None:
            out = out + z.astype(jnp.float32)  # bn_fused_add_(relu)
        if self.fuse_relu:
            out = jax.nn.relu(out)
        return out.astype(x.dtype)
