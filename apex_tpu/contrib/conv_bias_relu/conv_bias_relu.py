"""Fused conv + bias (+ mask) (+ ReLU) (reference:
``apex/contrib/conv_bias_relu/`` over cudnn-frontend fusions, SURVEY.md
§2.2 contrib misc).

The reference exists because eager torch runs conv, bias add, and ReLU
as separate kernels; its cudnn-graph path fuses them. XLA fuses the
NHWC conv+bias+activation chain natively on TPU, so these are API-parity
functionals with fp32 accumulation; gradients by autodiff (the
reference hand-writes the backward through the cudnn graph).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_DN = ("NHWC", "HWIO", "NHWC")


def _conv(x, weight, stride, padding):
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    return jax.lax.conv_general_dilated(
        x, weight.astype(x.dtype), stride, padding, dimension_numbers=_DN,
        preferred_element_type=jnp.float32)


def conv_bias(x, weight, bias, stride=1, padding=0):
    """Reference ``ConvBias``: NHWC conv + bias, fp32 accumulation."""
    return (_conv(x, weight, stride, padding)
            + bias.astype(jnp.float32)).astype(x.dtype)


def conv_bias_relu(x, weight, bias, stride=1, padding=0):
    """Reference ``ConvBiasReLU``: conv + bias + ReLU in one fused pass."""
    return jax.nn.relu(
        _conv(x, weight, stride, padding) + bias.astype(jnp.float32)
    ).astype(x.dtype)


def conv_bias_mask_relu(x, weight, bias, mask, stride=1, padding=0):
    """Reference ``ConvBiasMaskReLU``: conv + bias, elementwise mask
    multiply, then ReLU (the dropout-style mask the cudnn graph fuses)."""
    y = _conv(x, weight, stride, padding) + bias.astype(jnp.float32)
    return jax.nn.relu(y * mask.astype(jnp.float32)).astype(x.dtype)


def conv_frozen_scale_bias_relu(x, weight, scale, bias, stride=1, padding=0):
    """Reference ``ConvFrozenScaleBiasReLU``: conv with a frozen-BN
    affine folded in (y = conv * scale + bias, then ReLU)."""
    y = _conv(x, weight, stride, padding)
    return jax.nn.relu(
        y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    ).astype(x.dtype)
