"""Contrib conv_bias_relu (reference: ``apex/contrib/conv_bias_relu``)."""

from apex_tpu.contrib.conv_bias_relu.conv_bias_relu import (
    conv_bias,
    conv_bias_mask_relu,
    conv_bias_relu,
    conv_frozen_scale_bias_relu,
)

# Reference name parity: the upstream module exposes CamelCase
# autograd-Function handles (ConvBiasReLU etc.); here the fused op IS the
# function (XLA fuses the epilogue), so the aliases point at the same
# callables.
ConvBias = conv_bias
ConvBiasReLU = conv_bias_relu
ConvBiasMaskReLU = conv_bias_mask_relu
ConvFrozenScaleBiasReLU = conv_frozen_scale_bias_relu

__all__ = ["ConvBias", "ConvBiasMaskReLU", "ConvBiasReLU",
           "ConvFrozenScaleBiasReLU", "conv_bias", "conv_bias_mask_relu",
           "conv_bias_relu", "conv_frozen_scale_bias_relu"]
