"""Contrib conv_bias_relu (reference: ``apex/contrib/conv_bias_relu``)."""

from apex_tpu.contrib.conv_bias_relu.conv_bias_relu import (
    conv_bias,
    conv_bias_mask_relu,
    conv_bias_relu,
    conv_frozen_scale_bias_relu,
)

__all__ = ["conv_bias", "conv_bias_mask_relu", "conv_bias_relu",
           "conv_frozen_scale_bias_relu"]
