"""Optimized NHWC GroupNorm (reference: ``apex/contrib/group_norm/`` +
``apex/contrib/csrc/group_norm/``, SURVEY.md §2.2 — the diffusion-
workload kernels).

The reference exists because torch's GroupNorm is NCHW and its NHWC CUDA
path was slow. On TPU, NHWC is the NATIVE conv layout (C on the 128-lane
minor dim) and XLA fuses the normalize/affine/activation chain into the
surrounding convs, so the TPU-idiomatic implementation is the jnp
formula in fp32 over the channels-last tensor — kept as a module for API
parity, including the reference's optional fused ``act="silu"``/
``"swish"`` epilogue.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

_ACTS = {
    "": lambda x: x,
    "identity": lambda x: x,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
}


def group_norm_nhwc(x, num_groups, weight=None, bias=None, eps=1e-5,
                    act: str = ""):
    """Functional NHWC group norm: x is (N, H, W, C) (or (N, ..., C));
    stats are computed per (N, group) in fp32."""
    if act not in _ACTS:
        raise ValueError(f"unsupported act {act!r}; one of {sorted(_ACTS)}")
    C = x.shape[-1]
    if C % num_groups:
        raise ValueError(f"channels ({C}) not divisible by groups "
                         f"({num_groups})")
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    shape = xf.shape
    # (N, spatial..., G, C/G) -> normalize over (spatial..., C/G)
    xg = xf.reshape(shape[0], -1, num_groups, C // num_groups)
    mean = xg.mean(axis=(1, 3), keepdims=True)
    var = xg.var(axis=(1, 3), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    out = xg.reshape(shape)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return _ACTS[act](out).astype(orig_dtype)


class GroupNorm(nn.Module):
    """Module parity with the reference's ``GroupNorm(num_groups,
    num_channels, eps, affine, act)`` (NHWC)."""

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: str = ""

    @nn.compact
    def __call__(self, x):
        w = b = None
        if self.affine:
            w = self.param("weight", nn.initializers.ones,
                           (self.num_channels,), jnp.float32)
            b = self.param("bias", nn.initializers.zeros,
                           (self.num_channels,), jnp.float32)
        return group_norm_nhwc(x, self.num_groups, w, b, self.eps, self.act)
