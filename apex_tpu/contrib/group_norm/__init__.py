"""Contrib group_norm (reference: ``apex/contrib/group_norm``)."""

from apex_tpu.contrib.group_norm.group_norm import GroupNorm, group_norm_nhwc

__all__ = ["GroupNorm", "group_norm_nhwc"]
