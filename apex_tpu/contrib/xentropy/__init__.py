"""Contrib xentropy (reference: ``apex/contrib/xentropy``)."""

from apex_tpu.contrib.xentropy.softmax_xentropy import (
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)

__all__ = ["SoftmaxCrossEntropyLoss", "softmax_cross_entropy_loss"]
