"""Fused softmax cross-entropy with label smoothing (reference:
``apex/contrib/xentropy/softmax_xentropy.py`` + ``apex/contrib/csrc/
xentropy/``, SURVEY.md §2.5).

The reference's CUDA kernel fuses max/logsumexp/gather into one pass to
avoid materializing log-probabilities. Here the fused form is the
logsumexp identity itself —

    loss = logsumexp(logits) - (1-eps) * logits[target]
           - eps * mean(logits)

— which XLA compiles to one reduction pass over the logits; the backward
(softmax(logits) minus the smoothed one-hot) comes from autodiff of the
same expression, again without a log-prob tensor.

API parity: ``SoftmaxCrossEntropyLoss.apply(logits, labels, smoothing,
padding_idx, half_to_float)`` returning PER-EXAMPLE losses (the
reference returns unreduced losses; callers ``.sum()``/``.mean()``), and
zero loss at ``padding_idx`` labels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy_loss(logits, labels, smoothing: float = 0.0,
                               padding_idx: int = 0,
                               half_to_float: bool = False):
    """Per-example smoothed cross-entropy; fp32 math internally.

    Args:
      logits: (..., vocab).
      labels: (...) int targets.
      smoothing: label-smoothing epsilon in [0, 1).
      padding_idx: labels equal to this yield exactly 0 loss (the
        reference's convention; use a negative sentinel to disable).
      half_to_float: return fp32 losses from fp16/bf16 logits (the
        reference knob; fp32 is returned either way here since the loss
        math is fp32 — kept for call-site parity).
    """
    x = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(x, axis=-1)
    safe_labels = jnp.where(labels == padding_idx, 0, labels)
    picked = jnp.take_along_axis(x, safe_labels[..., None], axis=-1)[..., 0]
    if smoothing == 0.0:
        loss = lse - picked
    else:
        mean_x = jnp.mean(x, axis=-1)
        loss = lse - (1.0 - smoothing) * picked - smoothing * mean_x
    loss = jnp.where(labels == padding_idx, 0.0, loss)
    if not half_to_float:
        loss = loss.astype(logits.dtype)
    return loss


class SoftmaxCrossEntropyLoss:
    """Reference class shape: ``SoftmaxCrossEntropyLoss.apply(...)``
    (a torch.autograd.Function there; here the fused expression is
    differentiable by construction)."""

    @staticmethod
    def apply(logits, labels, smoothing: float = 0.0, padding_idx: int = 0,
              half_to_float: bool = False):
        return softmax_cross_entropy_loss(
            logits, labels, smoothing, padding_idx, half_to_float)
