"""OpenFold Evoformer kernels on TPU-native machinery.

Reference surface: ``apex/contrib/openfold_triton/{layer_norm,softmax,
_mha_kernels}.py`` (SURVEY.md §2.2, V? vintage). The Triton kernels
exist because the Evoformer's shapes are hostile to stock CUDA kernels —
many small rows (pair representation ``(B, N, N, c_z)`` with c_z=128,
MSA ``(B, s, N, c_m)`` with c_m=256) and a bias+mask softmax reading
three tensors. On TPU:

- the small-c LayerNorm rides the Pallas row-block kernels (which tile
  any trailing dim to the 128-lane width — c_z=128 is literally one
  lane tile);
- the bias+mask softmax folds into the fused additive-mask softmax
  kernel (one HBM read of scores; the broadcast bias fuses into the
  input producer);
- gated attention composes the flash kernel with a sigmoid-gate
  epilogue XLA fuses into the output projection's producer.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.layer_norm import fused_layer_norm_affine
from apex_tpu.ops.softmax import scaled_masked_softmax


def layer_norm(x, weight, bias, eps: float = 1e-5):
    """Trailing-dim LayerNorm at OpenFold shapes (reference:
    ``openfold_triton.layer_norm``). Accepts any leading shape; weight
    and bias are 1-D of the trailing dim."""
    return fused_layer_norm_affine(x, weight, bias, eps)


class LayerNormSmallShapeOptImpl:
    """API-parity shim for the reference's autotuned small-shape
    LayerNorm entry point (``LayerNormSmallShapeOptImpl.apply``): the
    Triton version selects per-shape tuned kernels; the Pallas kernels
    tune their row-block size per hidden width internally
    (``ops.layer_norm._block_rows``), so ``apply`` simply dispatches."""

    @staticmethod
    def apply(x, normalized_shape, weight, bias, eps: float = 1e-5):
        shape = (tuple(int(d) for d in normalized_shape)
                 if not isinstance(normalized_shape, int)
                 else (int(normalized_shape),))
        # the trailing dims must BE normalized_shape (mirroring
        # fused_layer_norm's _check_trailing) — a divisibility test
        # alone would silently normalize the wrong element grouping
        # whenever a mismatched shape happens to divide x.size
        # (advisor r5 #3)
        k = len(shape)
        if tuple(x.shape[-k:]) != shape:
            raise ValueError(
                f"normalized_shape {shape} does not match trailing dims "
                f"{tuple(x.shape[-k:])} of input shape {tuple(x.shape)}")
        n = 1
        for d in shape:
            n *= d
        if n != x.shape[-1]:
            lead = x.shape
            y = fused_layer_norm_affine(
                x.reshape(-1, n), weight.reshape(n), bias.reshape(n), eps)
            return y.reshape(lead)
        return fused_layer_norm_affine(x, weight.reshape(n),
                                       bias.reshape(n), eps)


def softmax(x, mask: Optional[jax.Array] = None,
            bias: Optional[jax.Array] = None, scale: float = 1.0):
    """``softmax(scale * x + bias)`` over the last dim with an optional
    boolean padding mask (True = masked, the apex convention).

    Reference: ``openfold_triton.softmax`` — the Evoformer score
    softmax whose ``bias`` is the broadcastable pair-bias term
    ``(B, 1, H, N, N)`` added to ``(B, s, H, N, N)`` scores. The bias
    add fuses into the fused-softmax kernel's input producer (it is an
    elementwise producer of the kernel input), so the fused path reads
    the score tensor once, like the Triton kernel."""
    if bias is not None:
        x = x * scale + bias.astype(x.dtype)
        scale = 1.0
    return scaled_masked_softmax(x, mask, scale)


def gated_attention(q, k, v, gate, bias: Optional[jax.Array] = None,
                    mask: Optional[jax.Array] = None, scale: float = 1.0):
    """Evoformer gated MHA core (reference:
    ``openfold_triton._mha_kernels`` / OpenFold ``Attention``):
    ``sigmoid(gate) * softmax(scale*q@k^T + bias, mask) @ v``.

    Shapes: q/k/v/gate ``(..., H, S, D)``; bias broadcastable to the
    ``(..., H, S, S)`` scores; mask boolean broadcastable likewise
    (True = masked). The score path uses the fused bias+mask softmax;
    the sigmoid gate is an elementwise epilogue XLA fuses into the
    context matmul's consumer."""
    scores = jnp.einsum("...qd,...kd->...qk", q, k)
    probs = softmax(scores, mask=mask, bias=bias, scale=scale)
    ctx = jnp.einsum("...qk,...kd->...qd", probs.astype(v.dtype), v)
    return jax.nn.sigmoid(gate.astype(ctx.dtype)) * ctx
