"""FusedAdamSWA — Adam step + stochastic-weight-averaging in one pass.

Rebuild of ``apex/contrib/openfold_triton/fused_adam_swa.py`` (SURVEY.md
§2.2, V? vintage): OpenFold training keeps an SWA copy of the weights
(an exponential/running average of the trained parameters) and apex
fuses the Adam update and the SWA accumulation into one kernel so the
parameter list is read once per step. Here both updates live in the
same per-leaf fp32 elementwise chain, which XLA fuses into one
HBM-bound pass per leaf — the same one-read economy.

SWA semantics (matching OpenFold's ``AlphaFoldSWA`` wrapper): with
``swa_decay_rate = d``, the averaged weights follow
``swa = d * swa + (1 - d) * p_new`` after each step; a fresh state
starts the average AT the first updated parameters (so the average
never mixes with the zero init)."""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops.multi_tensor import (
    ADAM_MODE_ADAMW,
    ADAM_MODE_L2,
    multi_tensor_adam,
)
from apex_tpu.optimizers._base import FusedOptimizer, leaves_of, like_tree


class SWAState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    master: any      # fp32 masters, or None
    swa: any         # fp32 averaged params pytree


@dataclasses.dataclass(frozen=True)
class FusedAdamSWA(FusedOptimizer):
    """Adam(W) with a fused SWA buffer (reference ``FusedAdamSWA``).

    Knobs mirror :class:`apex_tpu.optimizers.FusedAdam` plus
    ``swa_decay_rate``. ``state.swa`` holds the averaged fp32 weights;
    read them out for evaluation via :meth:`swa_params`."""

    lr: float = 1e-3
    bias_correction: bool = True
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    adam_w_mode: bool = True
    weight_decay: float = 0.0
    master_weights: bool = False
    swa_decay_rate: float = 0.9

    def init(self, params) -> SWAState:
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             params)
        zeros2 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                              params)
        return SWAState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=zeros,
            exp_avg_sq=zeros2,
            master=self._master_init(params),
            swa=jax.tree.map(lambda p: p.astype(jnp.float32), params),
        )

    def swa_params(self, state: SWAState, like=None):
        """The averaged weights, cast to ``like``'s dtypes (or fp32)."""
        if like is None:
            return state.swa
        return jax.tree.map(lambda s, p: s.astype(p.dtype), state.swa, like)

    def step(self, grads, state: SWAState, params, skip_if=None,
             lr: Optional[float] = None):
        lr = self.lr if lr is None else lr
        step = state.step + 1

        lists = [leaves_of(grads), leaves_of(params),
                 leaves_of(state.exp_avg), leaves_of(state.exp_avg_sq)]
        if self.master_weights:
            lists.append(leaves_of(state.master))

        out = multi_tensor_applier(
            multi_tensor_adam, None, lists, lr,
            self.betas[0], self.betas[1], self.eps, step,
            ADAM_MODE_ADAMW if self.adam_w_mode else ADAM_MODE_L2,
            self.bias_correction, self.weight_decay,
        )
        new_p = like_tree(out[0], params)
        new_master = (like_tree(out[3], state.master)
                      if self.master_weights else None)

        # SWA accumulation fused into the same pass: the averaged buffer
        # reads the freshly computed fp32 step output (still register-
        # resident in the fused chain), not a second trip through HBM.
        # The FIRST real step (step == 1) copies the updated params
        # instead of blending — the average starts AT the first updated
        # parameters (torch AveragedModel / OpenFold AlphaFoldSWA
        # first-capture semantics), never mixing in the init values
        # (advisor r5 #4). step==1 is a traced condition, so a skipped
        # (overflow) first step correctly retries the copy next step.
        d = jnp.float32(self.swa_decay_rate)
        src = new_master if self.master_weights else new_p
        first = step == 1
        new_swa = jax.tree.map(
            lambda s, p: jnp.where(
                first, p.astype(jnp.float32),
                d * s + (1.0 - d) * p.astype(jnp.float32)),
            state.swa, src)

        new_state = SWAState(
            step=step,
            exp_avg=like_tree(out[1], state.exp_avg),
            exp_avg_sq=like_tree(out[2], state.exp_avg_sq),
            master=new_master,
            swa=new_swa,
        )
        return self._finish_step(skip_if, new_p, new_state, params, state)
