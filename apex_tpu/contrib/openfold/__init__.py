"""OpenFold kernel tier (reference: ``apex/contrib/openfold_triton/``,
SURVEY.md §2.2 — the V?-vintage Triton kernels apex ships for OpenFold /
AlphaFold2 training).

The upstream package provides Triton kernels for the Evoformer's hot
ops — small-trailing-dim LayerNorm, bias+mask softmax over attention
scores, gated multi-head attention, and a fused Adam+SWA optimizer step
(``fused_adam_swa.py``). On TPU each of those maps onto machinery this
framework already owns; this tier provides the OpenFold-shaped surface:

- :func:`layer_norm` / ``LayerNormSmallShapeOptImpl`` — trailing-dim
  LayerNorm at the pair/MSA-representation shapes (c_z=128, c_m=256),
  dispatching to the Pallas training kernels of
  :mod:`apex_tpu.ops.layer_norm`.
- :func:`softmax` — ``softmax(scale*x + bias, mask)`` over the last dim
  with the Evoformer's broadcastable pair-bias term, on the fused
  additive-mask softmax kernels of :mod:`apex_tpu.ops.softmax`.
- :func:`gated_attention` — the MSA row/column attention core:
  ``sigmoid(gate) * attn(q, k, v, bias, mask)``.
- :class:`FusedAdamSWA` — Adam step + stochastic-weight-averaging
  buffer update in one fused pass over the parameter list.
"""

from apex_tpu.contrib.openfold.fused_adam_swa import FusedAdamSWA, SWAState
from apex_tpu.contrib.openfold.kernels import (
    LayerNormSmallShapeOptImpl,
    gated_attention,
    layer_norm,
    softmax,
)

__all__ = [
    "FusedAdamSWA",
    "SWAState",
    "LayerNormSmallShapeOptImpl",
    "gated_attention",
    "layer_norm",
    "softmax",
]
