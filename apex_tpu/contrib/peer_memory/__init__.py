"""apex_tpu.contrib.peer_memory — direct neighbor exchange over ICI.

Rebuild of the reference's ``apex/contrib/peer_memory/`` (U) +
``apex/contrib/csrc/{peer_memory,nccl_p2p}/`` (U): raw GPU-P2P buffer
pools and the 1-D halo exchanger the spatial-parallel bottleneck uses.

TPU mapping: device-to-device moves are ``lax.ppermute`` hops over ICI;
XLA owns the buffers, so the reference's explicitly-managed
``PeerMemoryPool`` has no allocation job left — it survives as the
topology descriptor the exchanger reads (group axis + halo geometry),
keeping reference call sites shaped the same while the data path is the
:class:`~apex_tpu.contrib.bottleneck.HaloExchanger1d` ppermute exchange.
"""

from __future__ import annotations

import dataclasses

import jax

from apex_tpu.contrib.bottleneck import HaloExchanger1d

__all__ = ["PeerMemoryPool", "PeerHaloExchanger1d", "peer_send_recv"]


@dataclasses.dataclass(frozen=True)
class PeerMemoryPool(object):
    """Topology descriptor for peer exchanges (reference: a raw
    cudaMalloc'd P2P buffer pool sized ``static_size``/``dynamic_size``;
    here XLA manages device memory, so the sizes are accepted for call
    -site parity and only the axis matters)."""

    static_size: int = 0
    dynamic_size: int = 0
    peer_group_size: int = 0  # 0 = the full axis
    axis_name: str = "spatial"


class PeerHaloExchanger1d:
    """Reference ``PeerHaloExchanger1d(ranks, rank_in_group, pool,
    half_halo)``: exchange ``half_halo`` edge rows with ring neighbors.
    Here the neighbor hop is ppermute over ``pool.axis_name``; run inside
    ``shard_map`` with that axis in scope.

    ``ranks``/``rank_in_group`` are accepted for reference call-site
    parity and ignored — under SPMD every rank runs the same program and
    ``lax.axis_index`` supplies the rank; the group partitioning comes
    from ``pool.peer_group_size``. The short form
    ``PeerHaloExchanger1d(pool, half_halo)`` also works."""

    def __init__(self, ranks=None, rank_in_group=None, pool=None,
                 half_halo: int = 1):
        if isinstance(ranks, PeerMemoryPool) and pool is None:
            # short form: first positional is the pool
            pool, ranks = ranks, None
            if isinstance(rank_in_group, int):
                half_halo, rank_in_group = rank_in_group, None
        if pool is None:
            raise TypeError("PeerHaloExchanger1d needs a PeerMemoryPool "
                            "(reference arg 3, or first positional)")
        self.pool = pool
        self.half_halo = half_halo
        self._impl = HaloExchanger1d(pool.axis_name, half_halo,
                                     group_size=pool.peer_group_size)

    def __call__(self, x):
        return self._impl(x)


def peer_send_recv(x, axis_name: str, shift: int = 1):
    """One ring hop: every shard receives the ``x`` of its neighbor
    ``shift`` positions back (the nccl_p2p send/recv pair; a single
    ppermute over ICI)."""
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)
