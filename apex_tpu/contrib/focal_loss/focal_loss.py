"""Fused focal loss (reference: ``apex/contrib/focal_loss/focal_loss.py``
+ ``apex/contrib/csrc/focal_loss/``, the retinanet detection kernel;
SURVEY.md §2.2 contrib misc).

FL(p_t) = -alpha_t * (1 - p_t)^gamma * log(p_t) over one-hot class
targets, computed from logits in fp32 without materializing softmax
probabilities separately from the loss (one fused XLA pass; the
backward comes from autodiff of the same expression).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def focal_loss(logits, targets, alpha: float = 0.25, gamma: float = 2.0,
               reduction: str = "sum"):
    """Sigmoid focal loss (the detection formulation the reference
    implements).

    Args:
      logits: (..., num_classes) raw scores.
      targets: (...) int class ids; NEGATIVE ids mean "background /
        ignore" (contribute only the negative-class term, matching the
        reference's handling of unmatched anchors).
      alpha: positive-class weight.
      gamma: focusing exponent.
      reduction: "sum" | "mean" | "none".
    """
    if reduction not in ("sum", "mean", "none"):
        raise ValueError(
            f"reduction must be 'sum', 'mean', or 'none', got {reduction!r}")
    x = logits.astype(jnp.float32)
    C = x.shape[-1]
    t = jax.nn.one_hot(jnp.maximum(targets, 0), C, dtype=jnp.float32)
    t = jnp.where((targets >= 0)[..., None], t, 0.0)

    p = jax.nn.sigmoid(x)
    # numerically-stable BCE-with-logits
    ce = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * t + (1 - p) * (1 - t)
    a_t = alpha * t + (1 - alpha) * (1 - t)
    loss = a_t * (1 - p_t) ** gamma * ce

    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "mean":
        return jnp.mean(loss)
    return loss
