"""Contrib focal_loss (reference: ``apex/contrib/focal_loss``)."""

from apex_tpu.contrib.focal_loss.focal_loss import focal_loss

__all__ = ["focal_loss"]
