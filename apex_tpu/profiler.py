"""Profiling hooks (SURVEY.md §5 tracing row; reference: ``apex.pyprof``
— deprecated upstream — plus the external torch-profiler workflow its
users migrated to).

The reference's pyprof parsed nvprof SQLite dumps to attribute kernels
to model ops. On TPU the equivalent workflow is ``jax.profiler``: traces
land in TensorBoard/Perfetto with XLA-op attribution built in. This
module provides the thin, apex-shaped surface:

- :func:`trace`: context manager around ``jax.profiler.trace`` (the
  ``pyprof.nvtx.init()`` analog: one line around the training loop);
- :func:`annotate`: named trace region (``torch.cuda.nvtx.range`` /
  pyprof op-annotation analog) for attributing loop phases;
- :class:`StepTimer`: host-side per-step wall timing with warmup
  exclusion and a summary dict — the "per-step timing surface" SURVEY
  prescribes, usable on runtimes where the full profiler is unavailable.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax

from apex_tpu.observability.metrics import percentile


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture a profiler trace of the enclosed block into ``log_dir``
    (view with TensorBoard's profile plugin or Perfetto)."""
    with jax.profiler.trace(log_dir,
                            create_perfetto_link=create_perfetto_link):
        yield


def annotate(name: str):
    """Named region inside a trace (shows up on the op timeline)."""
    return jax.profiler.TraceAnnotation(name)


def start_server(port: int = 9012):
    """On-demand profiling server (``jax.profiler.start_server``):
    connect from TensorBoard's capture-profile button."""
    return jax.profiler.start_server(port)


class StepTimer:
    """Per-step wall-clock timing with device synchronization.

    Usage::

        timer = StepTimer(warmup=2)
        for batch in data:
            out = step(...)
            timer.tick(out)          # blocks on out, records dt
        print(timer.summary())       # {mean_ms, p50/p90/p99_ms, ...}

    Percentiles come from the shared interpolating helper
    (:func:`apex_tpu.observability.metrics.percentile` — the one
    bench.py's TTFT/ITL reporting and the metrics histograms use), so
    a p50 here means the same thing everywhere. (The previous median
    was ``ts[n // 2]`` — the upper neighbor, not the median, for
    even n.)
    """

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self._seen = 0
        self._times = []
        self._last: Optional[float] = None

    def tick(self, *sync_on):
        """Record one step boundary; blocks on ``sync_on`` arrays so the
        measurement covers the device work, not just dispatch."""
        if sync_on:
            jax.block_until_ready(sync_on)
        now = time.perf_counter()
        if self._last is not None:
            self._seen += 1
            if self._seen > self.warmup:
                self._times.append(now - self._last)
        self._last = now

    def summary(self) -> dict:
        if not self._times:
            return {"steps": 0}
        ts = sorted(self._times)
        n = len(ts)
        return {
            "steps": n,
            "mean_ms": 1e3 * sum(ts) / n,
            "p50_ms": 1e3 * percentile(ts, 50),
            "p90_ms": 1e3 * percentile(ts, 90),
            "p99_ms": 1e3 * percentile(ts, 99),
            "min_ms": 1e3 * ts[0],
            "max_ms": 1e3 * ts[-1],
        }

    def reset(self):
        self._seen = 0
        self._times.clear()
        self._last = None
