from apex_tpu.multi_tensor_apply.multi_tensor_apply import (  # noqa: F401
    MultiTensorApply,
    multi_tensor_applier,
)
