"""The ``multi_tensor_applier`` dispatch surface.

Rebuild of ``apex/multi_tensor_apply/multi_tensor_apply.py`` (SURVEY.md
§2.1): the thin dispatcher every fused optimizer routes through. The
reference chunks tensor lists into ``chunk_size``-element pieces and
launches one CUDA kernel per metadata batch; here the op itself does
per-leaf fp32 math that XLA fuses (see :mod:`apex_tpu.ops.multi_tensor`),
so the applier's job reduces to signature parity — call sites written for
apex
(``multi_tensor_applier(amp_C.multi_tensor_adam, overflow_buf, lists,
*args)``) port unchanged.

``chunk_size`` is retained (default ``2048*32``, the reference constant)
and forwarded to ops; XLA makes its own tiling decisions, so it is
advisory on TPU.
"""

from __future__ import annotations


class MultiTensorApply:
    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag_buffer, tensor_lists, *args, **kwargs):
        """Apply ``op`` over parallel ``tensor_lists``.

        ``noop_flag_buffer`` is a traced bool scalar or None (the
        functional stand-in for the reference's device int buffer).
        """
        return op(self.chunk_size, noop_flag_buffer, tensor_lists,
                  *args, **kwargs)


multi_tensor_applier = MultiTensorApply(2048 * 32)
