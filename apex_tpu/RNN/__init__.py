"""apex.RNN parity surface (reference: ``apex/RNN`` — deprecated
upstream; kept for surface completeness)."""

from apex_tpu.RNN.cells import GRUCell, LSTMCell, RNNCell
from apex_tpu.RNN.models import GRU, LSTM, RNN, stackedRNN

__all__ = ["GRU", "GRUCell", "LSTM", "LSTMCell", "RNN", "RNNCell",
           "stackedRNN"]
