"""Stacked RNN models (reference: ``apex/RNN/{RNNBackend,models}.py``,
SURVEY.md §2.1 — the deprecated ``apex.RNN`` surface).

``stackedRNN`` drives any cell over the sequence with ``lax.scan``
(compiler-friendly: one compiled step body, no per-timestep Python) and
stacks layers with optional dropout between them; the ``RNN``/``LSTM``/
``GRU`` factories mirror the reference's constructor names.

Layout: ``(T, B, input)`` sequence-first, like the reference.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.RNN.cells import GRUCell, LSTMCell, RNNCell, RNNReLUCell


class stackedRNN(nn.Module):  # noqa: N801 — reference name
    """Reference ``RNNBackend.stackedRNN``: layers of one cell type over
    the sequence, outputs of layer i feeding layer i+1."""

    cell_type: type
    input_size: int
    hidden_size: int
    num_layers: int = 1
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, initial_carries=None,
                 deterministic: bool = True):
        """x: (T, B, input). Returns (outputs (T, B, hidden), carries)."""
        B = x.shape[1]
        carries_out = []
        seq = x
        for layer in range(self.num_layers):
            # parent=None: an unbound throwaway just for the carry shape
            carry0 = (initial_carries[layer] if initial_carries is not None
                      else self.cell_type(self.hidden_size, parent=None)
                      .initialize_carry(B, x.dtype))

            # scan the cell over time: a single compiled step body
            scan_cell = nn.scan(
                self.cell_type,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=0, out_axes=0,
            )(self.hidden_size, name=f"layer_{layer}")
            carry, outs = scan_cell(carry0, seq)
            carries_out.append(carry)
            seq = outs
            if self.dropout > 0.0 and layer < self.num_layers - 1:
                seq = nn.Dropout(self.dropout)(
                    seq, deterministic=deterministic)
        return seq, carries_out


def RNN(input_size, hidden_size, num_layers=1, dropout=0.0,
        nonlinearity="tanh"):
    """Reference factory ``apex.RNN.models.RNN`` (tanh or relu cells)."""
    cells = {"tanh": RNNCell, "relu": RNNReLUCell}
    if nonlinearity not in cells:
        raise ValueError(
            f"nonlinearity must be 'tanh' or 'relu', got {nonlinearity!r}")
    return stackedRNN(cells[nonlinearity], input_size, hidden_size,
                      num_layers, dropout)


def LSTM(input_size, hidden_size, num_layers=1, dropout=0.0):
    """Reference factory ``apex.RNN.models.LSTM``."""
    return stackedRNN(LSTMCell, input_size, hidden_size, num_layers, dropout)


def GRU(input_size, hidden_size, num_layers=1, dropout=0.0):
    """Reference factory ``apex.RNN.models.GRU``."""
    return stackedRNN(GRUCell, input_size, hidden_size, num_layers, dropout)
