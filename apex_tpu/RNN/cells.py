"""RNN cells (reference: ``apex/RNN/cells.py`` — the deprecated fused
LSTM/GRU building blocks, SURVEY.md §2.1).

Standard gate math in fp32 with the reference's combined-GEMM layout:
one input projection and one recurrent projection per step, gates split
from the fused output — the structure the reference's "fused" cells
exist for, which XLA reproduces by fusing the elementwise gate chain
into the two GEMMs.
"""

from __future__ import annotations

from typing import Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def _proj(features, name):
    return nn.Dense(features, param_dtype=jnp.float32,
                    kernel_init=nn.initializers.lecun_normal(), name=name)


class RNNCell(nn.Module):
    """Elman cell: h' = act(W x + U h + b) (reference ``RNNCell``)."""

    hidden_size: int
    activation: Callable = jnp.tanh

    @nn.compact
    def __call__(self, carry, x):
        (h,) = carry
        h_new = self.activation(
            _proj(self.hidden_size, "ih")(x)
            + _proj(self.hidden_size, "hh")(h))
        return (h_new,), h_new

    def initialize_carry(self, batch, dtype=jnp.float32):
        return (jnp.zeros((batch, self.hidden_size), dtype),)


class RNNReLUCell(RNNCell):
    """Elman cell with ReLU (reference ``nonlinearity="relu"``)."""

    activation: Callable = jax.nn.relu


class LSTMCell(nn.Module):
    """Standard LSTM with the i,f,g,o fused-gate layout (reference
    ``LSTMCell``/``mLSTMRNNCell`` family)."""

    hidden_size: int

    @nn.compact
    def __call__(self, carry, x):
        h, c = carry
        gates = (_proj(4 * self.hidden_size, "ih")(x)
                 + _proj(4 * self.hidden_size, "hh")(h))
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    def initialize_carry(self, batch, dtype=jnp.float32):
        z = jnp.zeros((batch, self.hidden_size), dtype)
        return (z, z)


class GRUCell(nn.Module):
    """Standard GRU, r/z/n gates (reference ``GRUCell``)."""

    hidden_size: int

    @nn.compact
    def __call__(self, carry, x):
        (h,) = carry
        rz = jax.nn.sigmoid(
            _proj(2 * self.hidden_size, "ih_rz")(x)
            + _proj(2 * self.hidden_size, "hh_rz")(h))
        r, z = jnp.split(rz, 2, axis=-1)
        n = jnp.tanh(_proj(self.hidden_size, "ih_n")(x)
                     + r * _proj(self.hidden_size, "hh_n")(h))
        h_new = (1.0 - z) * n + z * h
        return (h_new,), h_new

    def initialize_carry(self, batch, dtype=jnp.float32):
        return (jnp.zeros((batch, self.hidden_size), dtype),)
