from apex_tpu.models.bert import (  # noqa: F401
    BertConfig,
    BertForPreTraining,
    BertModel,
    pretraining_loss,
)
