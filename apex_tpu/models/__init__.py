from apex_tpu.models.bert import (  # noqa: F401
    BertConfig,
    BertForPreTraining,
    BertModel,
    pretraining_loss,
)
from apex_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    GPTLMHeadModel,
    GPTModel,
    lm_loss,
)
from apex_tpu.models.resnet import ResNet, ResNetConfig  # noqa: F401
