"""ResNet (NHWC) — the MLPerf-ResNet workload family.

BASELINE configs[3] names "DDP + SyncBatchNorm scaling, ResNet-50"; like
BERT/GPT this model exists to exercise the framework's conv tier end to
end: :class:`~apex_tpu.contrib.bottleneck.Bottleneck` blocks (NHWC convs
+ BatchNorm with the fused residual add+ReLU epilogue), optional
cross-replica BN via ``bn_group``/``axis_name`` (the groupbn/SyncBN
machinery), and DDP-style data parallelism at the train-step level.

NHWC is the native TPU conv layout (C on the 128-lane minor dim) — the
whole reason the reference's groupbn/bottleneck contrib tier exists is
to get torch onto that layout; here it is the default.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.bottleneck import Bottleneck
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    # blocks per stage; (3, 4, 6, 3) = ResNet-50
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)
    width: int = 64
    bn_group: int = 1                 # cross-replica BN group size
    axis_name: Optional[str] = None   # mesh axis for BN sync

    @staticmethod
    def resnet50(**kw):
        return ResNetConfig(**kw)

    @staticmethod
    def tiny(**kw):
        kw.setdefault("num_classes", 10)
        kw.setdefault("stage_sizes", (1, 1))
        kw.setdefault("width", 16)
        return ResNetConfig(**kw)


class ResNet(nn.Module):
    """Bottleneck ResNet over (N, H, W, C) inputs."""

    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        w = cfg.width
        x = nn.Conv(w, (7, 7), strides=(2, 2), padding=((3, 3), (3, 3)),
                    use_bias=False, param_dtype=jnp.float32,
                    kernel_init=nn.initializers.he_normal(),
                    name="conv_stem")(x)
        x = BatchNorm2d_NHWC(w, fuse_relu=True, bn_group=cfg.bn_group,
                             axis_name=cfg.axis_name,
                             name="bn_stem")(x, train=train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        in_ch = w
        for stage, blocks in enumerate(cfg.stage_sizes):
            out_ch = w * (2 ** stage) * 4
            mid_ch = w * (2 ** stage)
            for b in range(blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                x = Bottleneck(in_ch, mid_ch, out_ch, stride=stride,
                               bn_group=cfg.bn_group,
                               axis_name=cfg.axis_name,
                               name=f"stage{stage}_block{b}")(x, train=train)
                in_ch = out_ch

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(cfg.num_classes, param_dtype=jnp.float32,
                        kernel_init=nn.initializers.zeros,
                        name="fc")(x)
