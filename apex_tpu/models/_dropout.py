"""Shared model-side dropout plumbing (BERT + GPT).

One home for the fused-vs-threefry dropout module and the int32 seed
derivation so the two models can't drift (the seed range and the TP-rank
folding are correctness-sensitive: CudaRNGStatesTracker semantics — TP
regions draw from the per-rank model-parallel stream so masks
decorrelate; replicated regions keep the shared stream so all ranks
apply the identical mask)."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def _folded_key(module: nn.Module, tp_fold: bool, fold_axes=()):
    key = module.make_rng("dropout")
    if tp_fold:
        from apex_tpu.transformer.tensor_parallel.random import (
            model_parallel_key,
        )

        key = model_parallel_key(key)
    for ax in fold_axes:
        key = jax.random.fold_in(key, jax.lax.axis_index(ax))
    return key


def dropout_seed(module: nn.Module, tp_fold: bool, fold_axes=()):
    """int32 seed for the fused in-kernel dropout, derived from the flax
    "dropout" stream; ``tp_fold`` mixes in the TP rank so head-sharded
    regions decorrelate across ranks, and ``fold_axes`` mixes in further
    mesh-axis ranks (e.g. the context axis under sequence-sharded
    ring/Ulysses training, where each rank's activation shard must get
    its own masks)."""
    key = _folded_key(module, tp_fold, fold_axes)
    return jax.random.randint(key, (), 0, 2 ** 31 - 1, dtype=jnp.int32)


class TPDropout(nn.Module):
    """Dropout whose key folds in the TP rank (``tp_varying``) and/or
    further mesh-axis ranks (``fold_axes``) when the activation is
    sharded over those axes (see :func:`dropout_seed`)."""

    rate: float
    tp_varying: bool = False
    fold_axes: tuple = ()
    # Pallas hardware-PRNG dropout (ops/dropout.py): measured ~42 ms ->
    # ~4 ms per BERT-large step vs the threefry masks of nn.Dropout
    fused: bool = True

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        if deterministic or self.rate == 0.0:
            return x
        if self.fused:
            from apex_tpu.ops.dropout import fused_dropout

            return fused_dropout(x, self.rate,
                                 dropout_seed(self, self.tp_varying,
                                              self.fold_axes))
        key = _folded_key(self, self.tp_varying, self.fold_axes)
        return nn.Dropout(self.rate)(x, deterministic=False, rng=key)
