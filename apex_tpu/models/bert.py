"""BERT on apex_tpu building blocks — the north-star flagship model.

The reference ships no models (apex is a library; its BERT lives in the
NVIDIA DeepLearningExamples MLPerf harness that BASELINE.json's
``configs[4]`` points at). This module provides the equivalent workload:
BERT-large pretraining (MLM + NSP) assembled from the framework's own
pieces — FusedLayerNorm (Pallas), FusedScaleMaskSoftmax (Pallas),
amp O2 + FusedLAMB + DDP at the training-step level — plus Megatron-style
TP and sequence parallelism via the tensor_parallel layers for multi-chip
meshes.

Layout notes (TPU-first): activations are batch-major ``(B, S, H)``;
under sequence parallelism the per-rank activation is ``(B, S/tp, H)``
and token-major ``(S, B)`` ordering is used across the first-dim
gather/reduce-scatter mappings (the reason Megatron is s,b,h internally).
Matmuls carry ``preferred_element_type=fp32`` so bf16 inputs hit the MXU
with fp32 accumulation. ``fused_kernels=False`` swaps the Pallas norm/
softmax for stock flax/jnp ops — the bench baseline.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.functional import AttnMaskType, FusedScaleMaskSoftmax


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024          # bert-large
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layernorm_eps: float = 1e-12
    dtype: jnp.dtype = jnp.float32   # activation/compute dtype (bf16 for O2)
    remat: bool = True               # activation checkpointing per layer
    # remat policy: "full" recomputes everything in the layer backward
    # (min memory); "dots" saves matmul results and recomputes only the
    # cheap elementwise ops (jax.checkpoint_policies
    # .dots_with_no_batch_dims_saveable) — near-no-remat step time at a
    # fraction of full activation memory, often the best batch-size
    # enabler on a 16 GB chip
    remat_policy: str = "full"       # "full" | "dots"
    fused_kernels: bool = True       # Pallas LN/softmax vs stock ops
    # Pallas flash attention (reference: contrib fmha). Used when the
    # sequence is long enough to win (>= flash_min_seq; measured v5e
    # crossover); attention dropout is fused in-kernel (hardware PRNG),
    # so the training config keeps the flash path.
    flash_attention: bool = True
    flash_min_seq: int = 256
    # multi-chip: use tensor_parallel layers (requires bound "tensor" axis)
    use_tensor_parallel: bool = False
    sequence_parallel: bool = False

    @staticmethod
    def bert_large(**kw):
        return BertConfig(**kw)

    @staticmethod
    def bert_base(**kw):
        return BertConfig(hidden_size=768, num_layers=12, num_heads=12,
                          intermediate_size=3072, **kw)

    @staticmethod
    def tiny(**kw):
        """Test/dryrun config."""
        kw.setdefault("vocab_size", 128)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("max_position_embeddings", 64)
        return BertConfig(**kw)


_BERT_INIT = nn.initializers.normal(stddev=0.02)


def _dense(cfg, features, name):
    return nn.Dense(
        features,
        dtype=cfg.dtype,
        param_dtype=jnp.float32,
        kernel_init=_BERT_INIT,
        name=name,
    )


def _norm(cfg, name):
    if cfg.fused_kernels:
        return FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_eps, name=name)
    return nn.LayerNorm(epsilon=cfg.layernorm_eps, dtype=cfg.dtype,
                        param_dtype=jnp.float32, name=name)


def _attn_softmax(cfg, scores, mask):
    scale = 1.0
    if cfg.fused_kernels:
        return FusedScaleMaskSoftmax(
            attn_mask_type=AttnMaskType.padding, scale=scale,
        )(scores, mask)
    xf = scores.astype(jnp.float32)
    if mask is not None:
        xf = jnp.where(mask, -30000.0, xf)
    return jax.nn.softmax(xf, axis=-1).astype(scores.dtype)


from apex_tpu.models._dropout import (  # noqa: E402 (model-shared)
    TPDropout as _TPDropout,
    dropout_seed as _dropout_seed,
)


# sequence-parallel layout helpers: (B, S_local, H) <-> (S_local*B, H)
# token-major so first-dim gather/scatter stacks along the sequence.

def _sp_enter(x):
    return x.transpose(1, 0, 2).reshape(-1, x.shape[-1])


def _sp_exit(t, batch):
    return t.reshape(-1, batch, t.shape[-1]).transpose(1, 0, 2)


class BertSelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, deterministic: bool = True):
        cfg = self.cfg
        h, nh = cfg.hidden_size, cfg.num_heads
        hd = h // nh
        B = x.shape[0]
        inv_sqrt = 1.0 / (hd ** 0.5)

        if cfg.use_tensor_parallel:
            from apex_tpu.transformer import parallel_state
            from apex_tpu.transformer.tensor_parallel import (
                ColumnParallelLinear,
                RowParallelLinear,
            )

            tp = parallel_state.get_tensor_model_parallel_world_size()
            nh_local, local_h = nh // tp, h // tp
            t = _sp_enter(x) if cfg.sequence_parallel else x.reshape(-1, h)
            qkv_t = ColumnParallelLinear(
                input_size=h, output_size=3 * h, gather_output=False,
                sequence_parallel_enabled=cfg.sequence_parallel,
                init_method=_BERT_INIT, name="qkv")(t)
            qkv = (_sp_exit(qkv_t, B) if cfg.sequence_parallel
                   else qkv_t.reshape(B, -1, 3 * local_h))
            # Megatron layout: this rank's shard is [q_loc | k_loc | v_loc]
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            # three flat (B, S, H) projections, NOT one fused qkv + split:
            # the split is a 3-way copy, and the flat layout feeds the
            # transpose-free flash entry directly (gradients come back
            # flat too — no concat in backward)
            q = _dense(cfg, h, "q")(x)
            k = _dense(cfg, h, "k")(x)
            v = _dense(cfg, h, "v")(x)
            nh_local, local_h = nh, h

        # under SP the block input is the (B, S/tp, H) LOCAL shard but
        # attention runs over the FULL sequence (q is full-S after the
        # SP gather inside ColumnParallelLinear) — gate on the full
        # length, not the shard
        full_seq = x.shape[1] * (tp if (cfg.use_tensor_parallel
                                        and cfg.sequence_parallel) else 1)
        use_flash = (
            cfg.fused_kernels and cfg.flash_attention
            and full_seq >= cfg.flash_min_seq
            # flash takes a BOOLEAN per-key padding mask; the (B, 1, 1, Sk)
            # convention from BertModel reduces to it exactly. Additive
            # float masks must go through the composed-softmax path.
            and (attention_mask is None
                 or (attention_mask.ndim == 4
                     and attention_mask.dtype == jnp.bool_
                     and attention_mask.shape[1] == 1
                     and attention_mask.shape[2] == 1))
        )
        if use_flash:
            from apex_tpu.ops.flash_attention import flash_attention_bsh

            key_mask = (None if attention_mask is None
                        else attention_mask[:, 0, 0, :])
            drop = (0.0 if deterministic else cfg.attention_dropout)
            # fused in-kernel dropout (reference fmha's Philox path);
            # heads are sharded under TP, so fold the TP rank in
            seed = (_dropout_seed(self, cfg.use_tensor_parallel)
                    if drop > 0.0 else None)
            # (B, S, H)-layout kernels: no head split/merge transposes
            # (falls back to the transposed entry off the single-tile
            # regime — see ops/flash_attention.py)
            ctx = flash_attention_bsh(q, k, v, key_mask, nh_local, False,
                                      inv_sqrt, drop, seed)
            ctx = ctx.astype(cfg.dtype)
        else:
            def heads(t):
                return t.reshape(B, -1, nh_local, hd).transpose(0, 2, 1, 3)

            qh, kh, vh = heads(q), heads(k), heads(v)
            scores = jnp.einsum("bnqd,bnkd->bnqk", qh, kh,
                                preferred_element_type=jnp.float32) * inv_sqrt
            probs = _attn_softmax(cfg, scores.astype(cfg.dtype), attention_mask)
            # attention probs are head-sharded under TP: per-rank masks
            probs = _TPDropout(cfg.attention_dropout,
                               tp_varying=cfg.use_tensor_parallel,
                               fused=cfg.fused_kernels)(
                probs, deterministic=deterministic)
            ctx = jnp.einsum("bnqk,bnkd->bnqd", probs.astype(cfg.dtype), vh,
                             preferred_element_type=jnp.float32)
            ctx = ctx.astype(cfg.dtype).transpose(0, 2, 1, 3).reshape(
                B, -1, local_h)

        if cfg.use_tensor_parallel:
            from apex_tpu.transformer.tensor_parallel import RowParallelLinear

            t = (_sp_enter(ctx) if cfg.sequence_parallel
                 else ctx.reshape(-1, local_h))
            out_t = RowParallelLinear(
                input_size=h, output_size=h, input_is_parallel=True,
                sequence_parallel_enabled=cfg.sequence_parallel,
                init_method=_BERT_INIT, name="out")(t)
            out = (_sp_exit(out_t, B) if cfg.sequence_parallel
                   else out_t.reshape(B, -1, h))
        else:
            out = _dense(cfg, h, "out")(ctx)
        return out.astype(cfg.dtype)


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, deterministic: bool = True):
        cfg = self.cfg
        B = x.shape[0]
        attn = BertSelfAttention(cfg, name="attention")(
            x, attention_mask, deterministic)
        # sequence-sharded under SP (per-rank tokens → per-rank masks);
        # replicated under plain TP (masks must agree across ranks)
        sp = cfg.use_tensor_parallel and cfg.sequence_parallel
        attn = _TPDropout(cfg.hidden_dropout, tp_varying=sp,
                          fused=cfg.fused_kernels)(
            attn, deterministic=deterministic)
        x = _norm(cfg, "attention_ln")(x + attn)

        if cfg.use_tensor_parallel:
            from apex_tpu.transformer.tensor_parallel import (
                ColumnParallelLinear,
                RowParallelLinear,
            )

            t = _sp_enter(x) if cfg.sequence_parallel else x.reshape(-1, cfg.hidden_size)
            hmid = ColumnParallelLinear(
                input_size=cfg.hidden_size, output_size=cfg.intermediate_size,
                gather_output=False,
                sequence_parallel_enabled=cfg.sequence_parallel,
                init_method=_BERT_INIT, name="mlp_in")(t)
            hmid = nn.gelu(hmid)
            mlp_t = RowParallelLinear(
                input_size=cfg.intermediate_size, output_size=cfg.hidden_size,
                input_is_parallel=True,
                sequence_parallel_enabled=cfg.sequence_parallel,
                init_method=_BERT_INIT, name="mlp_out")(hmid)
            mlp = (_sp_exit(mlp_t, B) if cfg.sequence_parallel
                   else mlp_t.reshape(B, -1, cfg.hidden_size)).astype(cfg.dtype)
        else:
            hmid = _dense(cfg, cfg.intermediate_size, "mlp_in")(x)
            hmid = nn.gelu(hmid)
            mlp = _dense(cfg, cfg.hidden_size, "mlp_out")(hmid)
        mlp = _TPDropout(cfg.hidden_dropout, tp_varying=sp,
                         fused=cfg.fused_kernels)(
            mlp, deterministic=deterministic)
        return _norm(cfg, "output_ln")(x + mlp)


class BertEmbeddings(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, deterministic: bool = True):
        cfg = self.cfg
        if cfg.use_tensor_parallel:
            from apex_tpu.transformer.tensor_parallel import VocabParallelEmbedding

            word = VocabParallelEmbedding(
                num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
                name="word_embeddings")(input_ids)
        else:
            word = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                            embedding_init=nn.initializers.normal(0.02),
                            param_dtype=jnp.float32,
                            name="word_embeddings")(input_ids)
        S = input_ids.shape[-1]
        pos = self.param(
            "position_embeddings", nn.initializers.normal(0.02),
            (cfg.max_position_embeddings, cfg.hidden_size), jnp.float32)[:S]
        typ = nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                       embedding_init=nn.initializers.normal(0.02),
                       param_dtype=jnp.float32,
                       name="token_type_embeddings")(token_type_ids)
        x = word + pos[None, :, :] + typ
        x = _norm(cfg, "ln")(x.astype(cfg.dtype))
        return _TPDropout(cfg.hidden_dropout, fused=cfg.fused_kernels)(
            x, deterministic=deterministic)


class BertModel(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True):
        cfg = self.cfg
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = BertEmbeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, deterministic)
        # (B, 1, 1, S) boolean: True = masked (reference convention)
        mask4d = None
        if attention_mask is not None:
            mask4d = (attention_mask == 0)[:, None, None, :]

        if cfg.use_tensor_parallel and cfg.sequence_parallel:
            # shard the sequence across TP ranks between blocks (Megatron-SP)
            from apex_tpu.transformer import parallel_state
            from apex_tpu.utils.collectives import mark_varying

            tp = parallel_state.get_tensor_model_parallel_world_size()
            rank = jax.lax.axis_index(parallel_state.TENSOR_AXIS)
            s_local = x.shape[1] // tp
            x = jax.lax.dynamic_slice_in_dim(
                mark_varying(x, parallel_state.TENSOR_AXIS),
                rank * s_local, s_local, axis=1)

        layer_cls = BertLayer
        if cfg.remat:
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            elif cfg.remat_policy == "full":
                policy = None
            else:
                raise ValueError(
                    f"remat_policy must be 'full' or 'dots', got "
                    f"{cfg.remat_policy!r}")
            layer_cls = nn.remat(BertLayer, static_argnums=(3,),
                                 policy=policy)
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, name=f"layer_{i}")(x, mask4d, deterministic)

        if cfg.use_tensor_parallel and cfg.sequence_parallel:
            from apex_tpu.transformer.tensor_parallel import gather_along_first_dim

            B = x.shape[0]
            x = _sp_exit(gather_along_first_dim(_sp_enter(x)), B)

        pooled = jnp.tanh(_dense(cfg, cfg.hidden_size, "pooler")(x[:, 0]))
        return x, pooled


class BertForPreTraining(nn.Module):
    """MLM + NSP heads (the BASELINE configs[4] pretraining objective).

    ``masked_positions`` (B, P) int32: when given, the MLM head
    (transform + LN + vocab decoder) runs ONLY on the gathered masked
    positions — the MLPerf-BERT input format (max_predictions_per_seq),
    which is how the reference harness computes the head: at S=512 with
    P=76 the decoder matmul shrinks 6.7x. ``mlm_logits`` is then
    (B, P, V) and the loss takes the gathered (B, P) labels/weights.
    Without it the head runs over every position (round-3 behavior)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, masked_positions=None):
        cfg = self.cfg
        x, pooled = BertModel(cfg, name="bert")(
            input_ids, token_type_ids, attention_mask, deterministic)
        if masked_positions is not None:
            x = jnp.take_along_axis(
                x, masked_positions[..., None].astype(jnp.int32), axis=1)
        h = _dense(cfg, cfg.hidden_size, "mlm_transform")(x)
        h = nn.gelu(h)
        h = _norm(cfg, "mlm_ln")(h)
        if cfg.use_tensor_parallel:
            from apex_tpu.transformer.tensor_parallel import ColumnParallelLinear

            # local-vocab-shard logits, consumed by vocab_parallel_cross_entropy
            mlm_logits = ColumnParallelLinear(
                input_size=cfg.hidden_size, output_size=cfg.vocab_size,
                gather_output=False, init_method=_BERT_INIT,
                name="mlm_decoder",
            )(h.reshape(-1, cfg.hidden_size)).reshape(*h.shape[:-1], -1)
        else:
            mlm_logits = _dense(cfg, cfg.vocab_size, "mlm_decoder")(h)
        nsp_logits = _dense(cfg, 2, "nsp")(pooled)
        return mlm_logits, nsp_logits


def pretraining_loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels,
                     mlm_weights=None, vocab_parallel: bool = False):
    """Masked-LM + next-sentence loss, fp32 (the MLPerf BERT objective).

    ``mlm_labels``: (B, S) with -1 (ignore) elsewhere. With
    ``vocab_parallel``, ``mlm_logits`` is the local vocab shard and the
    per-token loss comes from :func:`vocab_parallel_cross_entropy`.
    """
    labels = jnp.maximum(mlm_labels, 0)
    if mlm_weights is None:
        mlm_weights = (mlm_labels >= 0).astype(jnp.float32)
    if vocab_parallel:
        from apex_tpu.transformer.tensor_parallel import (
            vocab_parallel_cross_entropy,
        )

        per_token = vocab_parallel_cross_entropy(mlm_logits, labels)
    else:
        # fused logsumexp form (contrib xentropy identity): avoids
        # materializing the fp32 (B, S, V) log-prob tensor — at
        # BERT-large B=8 S=512 that intermediate alone is ~0.5 GB
        xf = mlm_logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(xf, axis=-1)
        picked = jnp.take_along_axis(xf, labels[..., None], axis=-1)[..., 0]
        per_token = lse - picked
    denom = jnp.maximum(mlm_weights.sum(), 1.0)
    mlm_loss = (per_token * mlm_weights).sum() / denom

    nsp_logp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32), axis=-1)
    nsp_loss = -jnp.take_along_axis(
        nsp_logp, nsp_labels[:, None], axis=-1).mean()
    return mlm_loss + nsp_loss
