"""GPT-style causal language model — the decoder-family workload.

Like :mod:`apex_tpu.models.bert`, the reference ships no models (apex is
a library); this is the causal counterpart assembled from the same
framework pieces: pre-LN blocks with Pallas FusedLayerNorm, causal flash
attention (or ring / Ulysses context parallelism for long sequences),
and the fused-logsumexp LM loss (no (B, S, V) log-prob tensor). For
Megatron tensor/sequence parallelism see the BERT flagship, which wires
the tensor_parallel layers; this model focuses on the context-parallel
(long-sequence) axis.

Attention backend selection (``attention_backend``):
- ``"flash"`` (default): single-device Pallas flash attention, causal.
- ``"ring"``: :func:`apex_tpu.ops.ring_attention` over the
  ``context_axis`` mesh axis — activations arrive sequence-sharded
  (B, S_local); O(S/cp) keys per device.
- ``"ulysses"``: :func:`apex_tpu.ops.ulysses_attention` — all-to-all
  head re-sharding; needs ``num_heads % cp == 0``.
Both parallel backends require running inside ``shard_map`` with the
context axis in scope (see ``examples/train_long_context.py`` for the
mesh setup pattern).

Serving: ``apply(..., kv_cache=...)`` (plus ``block_tables`` /
``cache_positions`` / ``seq_lens``) switches to the paged-KV-cache
inference path — prefill writes the prompt's K/V into cache blocks and
runs the ordinary causal attention; a one-token call decodes against
the block table. The engine's multi-step decode traces this one-token
call once as the body of a ``jax.lax.scan`` (K fused iterations per
dispatch), so everything here must be — and is — shape-stable under
traced ``cache_positions``/``seq_lens`` that advance inside the loop.
The same multi-token path doubles as the speculative-decoding
**verify-mode forward**: a ``[B, spec_tokens + 1]`` call whose per-lane
``cache_positions`` start at each lane's own context offset scores a
whole drafted span in one dispatch — the chunk writes the carried
token's and every draft's K/V through the block table and attends
causally by absolute position, so position ``p``'s logits are exactly
the target distribution given drafts ``0..p-1``. Lanes whose proposal
count falls short of the chunk ride with PADDED trailing queries:
their writes are suppressed by ``seq_lens``/``write_start`` and their
logits ignored, but their (clamped) position lookups must stay
in-range — see :class:`GPTModel`. See :mod:`apex_tpu.serving` and
docs/serving.md.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional

import flax.linen as nn

from apex_tpu.models._dropout import (
    TPDropout as _TPDropout,
    dropout_seed as _dropout_seed,
)
import jax
import jax.numpy as jnp

from apex_tpu.normalization import FusedLayerNorm

_INIT = nn.initializers.normal(stddev=0.02)

# serving-mesh layout (docs/serving.md "Mesh sharding"): the modules
# whose output dim splits over the mesh's "model" axis (qkv columns =
# heads; mlp_in columns = the 4h expansion) and those whose INPUT dim
# splits to match (the Megatron row-parallel halves, whose partial
# products GSPMD all-reduces). Everything else — embeddings,
# layernorms — replicates.
_COL_PARALLEL = ("attn_q", "attn_k", "attn_v", "mlp_in")
_ROW_PARALLEL = ("attn_out", "mlp_out")

# the dense modules weight quantization applies to: exactly the six
# qkv/proj/mlp matmuls the mesh layout shards. Embeddings, layernorms,
# and the weight-tied LM head stay full precision — they are a small
# fraction of the bytes and the tied ``wte`` is read by two ops with
# different contraction axes (no single per-channel scale axis).
_QUANT_DENSE = _COL_PARALLEL + _ROW_PARALLEL

# weight storage modes (mirrors serving.kv_cache.KV_QUANT_MODES):
# ``None`` = full precision, ``"int8"`` = symmetric round-to-nearest
# int8, ``"fp8"`` = float8_e4m3 where the backend has the dtype.
# Weights are STATIC, so rounding is deterministic round-to-nearest —
# no position-keyed stochastic rounding like the KV pools need.
WEIGHT_QUANT_MODES = (None, "int8", "fp8")


def fp8_weight_dtype():
    """The fp8 weight storage dtype, or None when this jax has no
    fp8 (same probe as ``serving.kv_cache.fp8_kv_dtype``)."""
    return getattr(jnp, "float8_e4m3fn", None)


def _weight_quant_dtype(mode):
    if mode == "int8":
        return jnp.dtype(jnp.int8)
    if mode == "fp8":
        dt = fp8_weight_dtype()
        if dt is None:
            raise NotImplementedError(
                "weight quantization 'fp8' requires a jax with "
                "jnp.float8_e4m3fn; use 'int8' on this backend")
        return jnp.dtype(dt)
    raise ValueError(
        f"unknown weight quantization {mode!r} "
        f"(expected one of {WEIGHT_QUANT_MODES})")


def _weight_quant_max(mode) -> float:
    """The quantizer's design max: per-output-channel scales are
    ``amax / qmax`` so each column's largest magnitude maps onto the
    representable extreme."""
    if mode == "int8":
        return 127.0
    return float(jnp.finfo(fp8_weight_dtype()).max)


def quantize_dense_kernel(kernel, mode):
    """``(q_kernel, scale)`` for one ``(in, out)`` dense kernel:
    symmetric per-OUTPUT-channel quantization, deterministic
    round-to-nearest (weights are static — same values always quantize
    to the same bytes, which is what lets the process-replica params
    handshake cover the quantized representation)."""
    w = jnp.asarray(kernel, jnp.float32)
    qmax = _weight_quant_max(mode)
    amax = jnp.max(jnp.abs(w), axis=0)                     # (out,)
    scale = jnp.where(amax > 0.0, amax / qmax, 1.0).astype(jnp.float32)
    q = w / scale[None, :]
    if mode == "int8":
        q = jnp.clip(jnp.round(q), -qmax, qmax)
    return q.astype(_weight_quant_dtype(mode)), scale


def quantize_gpt_params(params, mode):
    """The fp GPT param tree re-expressed in quantized storage: every
    ``_QUANT_DENSE`` module's ``kernel`` becomes an int8/fp8 array with
    a per-output-channel fp32 ``scale`` leaf alongside (biases and all
    other leaves pass through untouched). The result is what a model
    built with ``GPTConfig(weight_quantization=mode)`` applies —
    dequantization happens only on the read side, inside the fused
    dequant-GEMM (:mod:`apex_tpu.ops.dequant_gemm`)."""
    _weight_quant_dtype(mode)     # validate mode / fp8 availability

    def walk(node):
        if not isinstance(node, Mapping):
            return node
        out = {}
        for key, child in node.items():
            if (key in _QUANT_DENSE and isinstance(child, Mapping)
                    and "kernel" in child):
                rec = {k: v for k, v in child.items() if k != "kernel"}
                q, scale = quantize_dense_kernel(child["kernel"], mode)
                rec["kernel"] = q
                rec["scale"] = scale
                out[key] = rec
            else:
                out[key] = walk(child)
        return out

    return walk(params)


def quantize_gpt_model(model, params, mode):
    """``(quantized_model, quantized_params)`` for a GPT LM and its fp
    params: the model is rebuilt with ``weight_quantization=mode`` (so
    its dense modules read quantized storage) and the params are
    re-expressed via :func:`quantize_gpt_params`. ``mode=None`` is the
    identity. The serving engine calls this at construction when
    ``EngineConfig.weight_quantization`` is set."""
    if mode not in WEIGHT_QUANT_MODES:
        raise ValueError(
            f"weight_quantization must be one of {WEIGHT_QUANT_MODES}, "
            f"got {mode!r}")
    if mode is None:
        return model, params
    cfg = getattr(model, "cfg", None)
    if not dataclasses.is_dataclass(cfg) or not any(
            f.name == "weight_quantization"
            for f in dataclasses.fields(cfg)):
        raise ValueError(
            "weight_quantization requires a GPT-family model whose "
            f"config carries the knob; got {type(model).__name__}")
    if cfg.weight_quantization is not None:
        # already quantized storage: idempotent for the same mode
        # (the params are already the quantized tree — re-quantizing
        # int8 bytes would corrupt them), a hard error across modes
        if cfg.weight_quantization == mode:
            return model, params
        raise ValueError(
            f"model already carries weight_quantization="
            f"{cfg.weight_quantization!r}; cannot re-quantize to "
            f"{mode!r}")
    qcfg = dataclasses.replace(cfg, weight_quantization=mode)
    return type(model)(qcfg), quantize_gpt_params(params, mode)


def gpt_param_bytes(params) -> int:
    """Total device bytes of a param tree — the number the weight-
    quantization bench arms and the ``dequant_gemm`` recorder event
    compare between the fp and quantized representations."""
    return int(sum(x.size * jnp.dtype(x.dtype).itemsize
                   for x in jax.tree.leaves(params)))


def gpt_num_layers(params) -> int:
    """Transformer-block count of a GPT param tree, read off the tree
    itself (the ``h_{i}`` block subtrees) — lets the sharded-train
    collective contract (``serving.mesh.train_expected_collectives``)
    scale its ``2 * num_layers`` tensor-parallel all-reduce floor
    without threading a :class:`GPTConfig` through the train step.
    Returns 0 for a non-GPT tree (callers fall back to the layer-count-
    unknown floor)."""
    blocks = set()

    def walk(tree):
        if not isinstance(tree, dict):
            return
        for k, v in tree.items():
            if (isinstance(k, str) and k.startswith("h_")
                    and k[2:].isdigit()):
                blocks.add(k)
            walk(v) if isinstance(v, dict) else None

    walk(params)
    return len(blocks)


def gpt_param_pspec(path, model_axis: str = "model"):
    """:class:`~jax.sharding.PartitionSpec` for one GPT param leaf,
    keyed by its pytree path (``jax.tree_util.tree_map_with_path``
    keys) — the model-owned half of the serving mesh layout
    (:mod:`apex_tpu.serving.mesh` binds it to a concrete mesh):

    - ``attn_q``/``attn_k``/``attn_v``/``mlp_in`` kernels
      column-shard (``P(None, model)``) with their biases along
      (``P(model)``) — qkv columns are head-major, so the head split
      of the KV pools lines up with the projection split;
    - ``attn_out``/``mlp_out`` kernels row-shard (``P(model, None)``),
      biases replicated (they add after the all-reduce);
    - quantized-weight ``scale`` leaves (per-OUTPUT-channel fp32, one
      per kernel column — ``weight_quantization``) shard exactly like
      the bias of their module: ``P(model)`` under column-parallel
      (the output dim is the sharded one), replicated under
      row-parallel (the output dim is unsharded there) — the KV-pool
      colocate-scales-with-bytes rule applied to weights: a kernel
      shard and the scales that dequantize it always land on the same
      device, so the fused dequant-GEMM never reaches across the mesh
      for a scale;
    - ``wte``/``wpe``/layernorms replicate.
    """
    from jax.sharding import PartitionSpec as P

    names = [str(getattr(p, "key", getattr(p, "name", p))) for p in path]
    module = names[-2] if len(names) >= 2 else ""
    leaf = names[-1] if names else ""
    if module in _COL_PARALLEL:
        if leaf == "kernel":
            return P(None, model_axis)
        # bias AND the quantized kernel's per-output-channel "scale":
        # both are (out,) vectors along the column-sharded output dim
        return P(model_axis)
    if module in _ROW_PARALLEL:
        if leaf == "kernel":
            return P(model_axis, None)
        # bias and "scale" lie along the UNSHARDED output dim here
        # (they apply after the all-reduce) — replicate
        return P()
    return P()


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 1024
    dropout: float = 0.1
    layernorm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32
    remat: bool = True
    fused_kernels: bool = True
    attention_backend: str = "flash"   # flash | ring | ulysses
    context_axis: str = "context"
    # Mixture-of-experts (0 = dense MLP). Experts shard over the
    # ``expert`` mesh axis when parallel_state is initialized with
    # expert_model_parallel_size_ > 1; see apex_tpu.transformer.moe.
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_layer_freq: int = 1   # every Nth block is MoE (1 = all)
    moe_aux_loss_coeff: float = 0.01
    moe_z_loss_coeff: float = 1e-3
    # Quantized weight storage (None | "int8" | "fp8"): routes the six
    # _QUANT_DENSE matmuls through QuantDense, whose params are the
    # int8/fp8 kernel + per-output-channel fp32 scale that
    # quantize_gpt_params produces. Normally set via
    # quantize_gpt_model / EngineConfig.weight_quantization rather
    # than by hand — the params MUST be the quantized tree.
    weight_quantization: Optional[str] = None

    @staticmethod
    def gpt2_small(**kw):
        return GPTConfig(**kw)

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 128)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("max_position_embeddings", 128)
        return GPTConfig(**kw)


class QuantDense(nn.Module):
    """Dense layer over quantized weight storage: an int8/fp8
    ``kernel`` (in, out) plus a per-output-channel fp32 ``scale``
    (out,) — the leaves :func:`quantize_gpt_params` produces — and an
    fp32 ``bias``. The forward is the fused dequant-GEMM
    (:func:`apex_tpu.ops.dequant_gemm.dequant_matmul`): dequantization
    happens on the read side only, inside the matmul, so the weights
    never materialize at full precision in HBM.

    Param shapes/dtypes must match the quantized tree exactly (flax
    validates shapes against these init_fns even in apply mode); the
    zeros/ones inits only matter for standalone ``init()`` of a
    quantized-config model, e.g. in eval_shape.
    """

    features: int
    mode: str
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        from apex_tpu.ops.dequant_gemm import dequant_matmul

        qdt = _weight_quant_dtype(self.mode)
        kernel = self.param(
            "kernel", nn.initializers.zeros_init(),
            (x.shape[-1], self.features), qdt)
        scale = self.param(
            "scale", nn.initializers.ones_init(),
            (self.features,), jnp.float32)
        bias = self.param(
            "bias", nn.initializers.zeros_init(),
            (self.features,), jnp.float32)
        y = dequant_matmul(x, kernel, scale)
        return (y + bias).astype(self.dtype)


def _dense(cfg, features, name):
    mode = getattr(cfg, "weight_quantization", None)
    if mode is not None and name in _QUANT_DENSE:
        return QuantDense(features, mode=mode, dtype=cfg.dtype,
                          name=name)
    return nn.Dense(features, dtype=cfg.dtype, param_dtype=jnp.float32,
                    kernel_init=_INIT, name=name)


def _norm(cfg, name):
    if cfg.fused_kernels:
        return FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_eps,
                              name=name)
    return nn.LayerNorm(epsilon=cfg.layernorm_eps, dtype=cfg.dtype,
                        param_dtype=jnp.float32, name=name)


def _ctx_fold_axes(cfg):
    """Mesh axes to fold into hidden-dropout seeds: the context axis when
    activations are sequence-sharded (ring/Ulysses), else nothing."""
    if cfg.attention_backend in ("ring", "ulysses"):
        return (cfg.context_axis,)
    return ()


def _causal_attend(cfg, q, k, v, scale, dropout_rate=0.0, seed=None):
    """(B, nh, S, hd) causal attention via the selected backend.
    ``dropout_rate``/``seed``: fused in-kernel attention-probability
    dropout, supported by EVERY backend — flash, composed, Ulysses
    (full-sequence flash after head re-sharding), and ring (per-block
    fused dropout keyed on global block-pair ids; the lse merge keeps
    statistics pre-dropout so nothing double-counts — see
    ops/ring_attention.py). All backends train at the true config."""
    if cfg.attention_backend == "ring":
        from apex_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v, None, True, scale,
                              axis_name=cfg.context_axis,
                              dropout_rate=dropout_rate,
                              dropout_seed=seed)
    if cfg.attention_backend == "ulysses":
        from apex_tpu.ops.ulysses_attention import ulysses_attention

        return ulysses_attention(q, k, v, None, True, scale,
                                 axis_name=cfg.context_axis,
                                 dropout_rate=dropout_rate,
                                 dropout_seed=seed)
    if cfg.fused_kernels:
        from apex_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, None, True, scale,
                               dropout_rate, seed)
    # composed fallback: the shared parity reference
    from apex_tpu.ops.flash_attention import mha_reference

    return mha_reference(q, k, v, None, True, scale, dropout_rate, seed)



def _cached_attention(cfg, q, k, v, kv_cache, layer, block_tables,
                      cache_positions, seq_lens, write_start=None):
    """Serving attention against the paged KV-cache (flat (B, S, H)
    projections in, flat context out, plus the updated cache).

    Both serving modes write the freshly-projected K/V into the cache
    blocks first, then attend:
    - prefill chunk (S > 1): the chunk's queries attend against the
      FULL cached context through the block table — the shared-prefix
      blocks matched at admission, earlier chunks, and the chunk itself
      — via :func:`apex_tpu.ops.flash_attention.paged_prefill_attention`
      (causal by absolute position, padding key-masked by ``seq_lens``).
      Speculative verification is this same mode at ``[B, spec + 1]``:
      each lane's chunk holds its carried token plus its drafted span
      at per-lane absolute positions, so one forward scores every
      candidate position against the drafts before it;
    - decode (S == 1): single-query attention against the block table
      via :func:`apex_tpu.ops.flash_attention.paged_decode_attention`.
    ``write_start`` (``[B]`` int32, optional) suppresses cache writes
    below that absolute position: positions already in the cache — a
    matched shared prefix, or a fully-cached prompt recomputing only
    its last-position logits — must not be re-scattered (a shared block
    belongs to other sequences too). The multi-step decode scan also
    leans on it to FREEZE a lane mid-scan (EOS / budget exhausted):
    setting a lane's ``write_start`` one past its ``cache_positions``
    drops its scatter while the lane's query harmlessly rides the
    batch. The mode is static (S is a trace constant), so an engine
    compiles exactly one program per shape — see docs/serving.md.
    """
    from apex_tpu.serving.kv_cache import write_kv

    B, S, h = q.shape
    nh = cfg.num_heads
    hd = h // nh
    scale = 1.0 / (hd ** 0.5)
    qh = q.reshape(B, S, nh, hd)
    kh = k.reshape(B, S, nh, hd)
    vh = v.reshape(B, S, nh, hd)

    valid = cache_positions < seq_lens[:, None]
    if write_start is not None:
        valid = valid & (cache_positions >= write_start[:, None])
    # write_kv quantizes on the way in when the pool stores quantized
    # blocks (per-row scales scattered through the same coordinates,
    # docs/serving.md memory tiers); a full-precision pool takes
    # exactly the pre-quantization paged_write path, bit for bit
    kv_cache = write_kv(kv_cache, layer, block_tables, cache_positions,
                        kh, vh, valid)
    k_scales = (None if kv_cache.k_scale is None
                else kv_cache.k_scale[layer])
    v_scales = (None if kv_cache.v_scale is None
                else kv_cache.v_scale[layer])

    if S == 1:
        from apex_tpu.ops.flash_attention import paged_decode_attention

        ctx = paged_decode_attention(qh[:, 0], kv_cache.k[layer],
                                     kv_cache.v[layer], block_tables,
                                     seq_lens, scale,
                                     k_scales=k_scales,
                                     v_scales=v_scales)
        return ctx.reshape(B, 1, h), kv_cache

    from apex_tpu.ops.flash_attention import paged_prefill_attention

    ctx = paged_prefill_attention(qh, kv_cache.k[layer],
                                  kv_cache.v[layer], block_tables,
                                  cache_positions, seq_lens, scale,
                                  k_scales=k_scales, v_scales=v_scales)
    return ctx.reshape(B, S, h), kv_cache


class GPTBlock(nn.Module):
    cfg: GPTConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, deterministic: bool = True, kv_cache=None,
                 layer: int = 0, block_tables=None, cache_positions=None,
                 seq_lens=None, write_start=None):
        cfg = self.cfg
        h, nh = cfg.hidden_size, cfg.num_heads
        hd = h // nh
        B, S = x.shape[0], x.shape[1]

        # pre-LN attention: three flat (B, S, H) projections shared by
        # every backend (one param layout — checkpoints stay portable
        # between flash / ring / Ulysses / composed / serving configs)
        y = _norm(cfg, "ln_1")(x)
        q = _dense(cfg, h, "attn_q")(y)
        k = _dense(cfg, h, "attn_k")(y)
        v = _dense(cfg, h, "attn_v")(y)

        # attention-probability dropout never applies on the serving
        # path (inference); the block tail below is shared with training
        attn_drop = (0.0 if deterministic or kv_cache is not None
                     else cfg.dropout)
        # Ulysses ranks share local head indices for different global
        # heads (rank folded into the seed inside ulysses_attention);
        # ring ranks share the base seed and decorrelate via the global
        # block-pair hash inside ring_attention
        seed = (_dropout_seed(self, False) if attn_drop > 0.0 else None)
        if kv_cache is not None:
            ctx, kv_cache = _cached_attention(
                cfg, q, k, v, kv_cache, layer, block_tables,
                cache_positions, seq_lens, write_start)
            ctx = ctx.astype(cfg.dtype)
        elif cfg.attention_backend == "flash" and cfg.fused_kernels:
            from apex_tpu.ops.flash_attention import flash_attention_bsh

            # transpose-free (B, S, H) kernels in the single-tile
            # regime; falls back to the transposed entry beyond it
            ctx = flash_attention_bsh(q, k, v, None, nh, True,
                                      1.0 / (hd ** 0.5), attn_drop,
                                      seed).astype(cfg.dtype)
        else:
            def heads(t):
                return t.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)

            ctx = _causal_attend(cfg, heads(q), heads(k), heads(v),
                                 1.0 / (hd ** 0.5), attn_drop, seed)
            ctx = ctx.astype(cfg.dtype).transpose(0, 2, 1, 3).reshape(
                B, S, h)
        attn = _dense(cfg, h, "attn_out")(ctx)
        ctx_axes = _ctx_fold_axes(cfg)
        attn = _TPDropout(cfg.dropout, fused=cfg.fused_kernels,
                          fold_axes=ctx_axes)(
            attn, deterministic=deterministic)
        x = x + attn

        # pre-LN MLP (dense or mixture-of-experts)
        y = _norm(cfg, "ln_2")(x)
        if self.use_moe:
            from apex_tpu.transformer.moe import MoEMLP

            y, aux, z = MoEMLP(
                hidden_size=h, ffn_hidden_size=4 * h,
                num_experts=cfg.num_experts, top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                dtype=cfg.dtype, name="moe_mlp",
            )(y, deterministic=deterministic)
            self.sow("losses", "moe_aux_loss", cfg.moe_aux_loss_coeff * aux)
            self.sow("losses", "moe_z_loss", cfg.moe_z_loss_coeff * z)
        else:
            y = nn.gelu(_dense(cfg, 4 * h, "mlp_in")(y))
            y = _dense(cfg, h, "mlp_out")(y)
        y = _TPDropout(cfg.dropout, fused=cfg.fused_kernels,
                       fold_axes=ctx_axes)(
            y, deterministic=deterministic)
        if kv_cache is not None:
            return x + y, kv_cache
        return x + y


class GPTModel(nn.Module):
    """Token + (sharded-aware) position embeddings, pre-LN blocks, final
    norm. Returns hidden states; :class:`GPTLMHeadModel` adds the tied
    LM head."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True,
                 position_offset=0, kv_cache=None, block_tables=None,
                 cache_positions=None, seq_lens=None, write_start=None):
        cfg = self.cfg
        B, S_local = input_ids.shape
        wte = self.param("wte", _INIT, (cfg.vocab_size, cfg.hidden_size),
                         jnp.float32)
        wpe = self.param("wpe", _INIT,
                         (cfg.max_position_embeddings, cfg.hidden_size),
                         jnp.float32)
        if kv_cache is not None:
            # Serving path (paged KV-cache): single-device attention only
            # — the context-parallel backends re-shard the sequence axis,
            # which has no meaning for a one-token decode step. Position
            # embeddings are gathered per token (each sequence sits at
            # its own offset), not dynamic-sliced at a shared offset.
            if cfg.attention_backend in ("ring", "ulysses"):
                raise ValueError(
                    "kv_cache serving does not support the "
                    f"{cfg.attention_backend!r} context-parallel backend; "
                    "use attention_backend='flash'")
            if cfg.num_experts > 0:
                raise NotImplementedError(
                    "kv_cache serving does not support MoE blocks yet")
            if (block_tables is None or cache_positions is None
                    or seq_lens is None):
                raise ValueError(
                    "kv_cache requires block_tables, cache_positions, "
                    "and seq_lens")
            # clamp explicitly: verify-mode chunks carry PADDING
            # positions past a lane's real span (draft slots beyond its
            # proposal count, whose writes are suppressed and logits
            # ignored) which may run past the embedding table near the
            # sequence cap — the gather must not depend on jit's
            # implicit out-of-bounds clamping for its correctness story
            pos = jnp.take(
                wpe,
                jnp.minimum(cache_positions,
                            cfg.max_position_embeddings - 1),
                axis=0)                                    # [B, S, H]
            x = (wte[input_ids] + pos).astype(cfg.dtype)
            for i in range(cfg.num_layers):
                x, kv_cache = GPTBlock(cfg, False, name=f"h_{i}")(
                    x, deterministic, kv_cache, i, block_tables,
                    cache_positions, seq_lens, write_start)
            return _norm(cfg, "ln_f")(x), wte, kv_cache
        if cfg.attention_backend in ("ring", "ulysses"):
            # sequence-sharded: this shard's global positions. Validate
            # the table covers the GLOBAL sequence — dynamic_slice would
            # silently clamp and duplicate positions otherwise.
            cp = jax.lax.psum(1, cfg.context_axis)
            rank = jax.lax.axis_index(cfg.context_axis)
            static_off = (position_offset
                          if isinstance(position_offset, int) else 0)
            if isinstance(cp, int) and (static_off + cp * S_local
                                        > cfg.max_position_embeddings):
                raise ValueError(
                    f"global sequence ({cp} shards x {S_local} + offset "
                    f"{static_off}) exceeds max_position_embeddings "
                    f"({cfg.max_position_embeddings}); dynamic_slice "
                    "would silently clamp and duplicate positions")
            position_offset = position_offset + rank * S_local
        elif isinstance(position_offset, int) and (
                position_offset + S_local > cfg.max_position_embeddings):
            raise ValueError(
                f"sequence [{position_offset}, {position_offset + S_local}) "
                f"exceeds max_position_embeddings "
                f"({cfg.max_position_embeddings})")
        pos = jax.lax.dynamic_slice_in_dim(
            wpe, position_offset, S_local, axis=0)
        x = (wte[input_ids] + pos[None]).astype(cfg.dtype)
        x = _TPDropout(cfg.dropout, fused=cfg.fused_kernels,
                       fold_axes=_ctx_fold_axes(cfg))(
            x, deterministic=deterministic)

        block_cls = GPTBlock
        if cfg.remat:
            block_cls = nn.remat(GPTBlock, static_argnums=(2,))
        for i in range(cfg.num_layers):
            use_moe = (cfg.num_experts > 0
                       and i % max(cfg.moe_layer_freq, 1) == 0)
            x = block_cls(cfg, use_moe, name=f"h_{i}")(x, deterministic)
        return _norm(cfg, "ln_f")(x), wte


class GPTLMHeadModel(nn.Module):
    """GPT with the weight-tied LM head (logits = hidden @ wte^T).

    With ``kv_cache=`` (plus ``block_tables``/``cache_positions``/
    ``seq_lens``, see :class:`GPTModel`) the call runs the serving path
    and returns ``(logits, new_kv_cache)`` instead of bare logits —
    the hook :class:`apex_tpu.serving.engine.InferenceEngine` drives.
    """

    cfg: GPTConfig

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True,
                 position_offset=0, kv_cache=None, block_tables=None,
                 cache_positions=None, seq_lens=None, write_start=None):
        if kv_cache is not None:
            x, wte, new_cache = GPTModel(self.cfg, name="transformer")(
                input_ids, deterministic, position_offset,
                kv_cache=kv_cache, block_tables=block_tables,
                cache_positions=cache_positions, seq_lens=seq_lens,
                write_start=write_start)
            logits = jnp.einsum("bsh,vh->bsv", x, wte.astype(x.dtype),
                                preferred_element_type=jnp.float32)
            return logits, new_cache
        x, wte = GPTModel(self.cfg, name="transformer")(
            input_ids, deterministic, position_offset)
        return jnp.einsum("bsh,vh->bsv", x, wte.astype(x.dtype),
                          preferred_element_type=jnp.float32)


def moe_losses_total(collections):
    """Sum the sown MoE auxiliary losses from an ``apply(...,
    mutable=("losses",))`` result: ``logits, col = model.apply(...);
    loss = lm_loss(...) + moe_losses_total(col)``. Returns 0.0 for dense
    models (empty/missing collection)."""
    losses = collections.get("losses", {}) if collections else {}
    total = jnp.float32(0.0)
    for leaf in jax.tree.leaves(losses):
        total = total + jnp.sum(leaf)
    return total


def lm_loss(logits, labels, ignore_index: int = -1):
    """Shifted next-token cross-entropy via the fused logsumexp identity
    (same memory rationale as bert.pretraining_loss)."""
    lg = logits[:, :-1].astype(jnp.float32)
    tgt = labels[:, 1:]
    weights = (tgt != ignore_index).astype(jnp.float32)
    safe = jnp.maximum(tgt, 0)
    lse = jax.nn.logsumexp(lg, axis=-1)
    picked = jnp.take_along_axis(lg, safe[..., None], axis=-1)[..., 0]
    per_token = (lse - picked) * weights
    return per_token.sum() / jnp.maximum(weights.sum(), 1.0)
