"""Native (C) host-side helpers — the ``apex_C`` analog.

The reference builds ``apex_C`` (``csrc/flatten_unflatten.cpp``) with
``--cpp_ext``; here ``csrc/flatten_unflatten.c`` is compiled on first
use with the system C compiler and loaded through ``ctypes`` (this
toolchain has no pybind11 — SURVEY.md's build-system note). Everything
degrades to a numpy fallback when no compiler is available, so the
package never hard-requires the native path.

API (host numpy buffers)::

    flat = flatten([arr0, arr1, ...])          # one contiguous 1-D u8
    bufs = unflatten(flat, metas)              # list of arrays back

Device-side packing belongs to XLA (``apex_tpu.utils.pytree``); use
this for host staging: checkpoint assembly, host-side comm buffers.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import List, Optional, Sequence, Tuple

import numpy as np

_LIB = None
_TRIED = False
_SRC = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                    "csrc", "flatten_unflatten.c")


def build_ctypes_lib(src_path: str, name: str) -> Optional[ctypes.CDLL]:
    """Compile a single C source to a shared lib and dlopen it.

    Shared by every native module (this one, :mod:`apex_tpu.data`): the
    cache is keyed by source CONTENT (mtime lies across checkouts) under
    a per-uid temp dir, built to a temp name + atomic rename so
    concurrent processes never dlopen a half-written file, with a
    cc/gcc/clang fallback chain. Returns None when no compiler works —
    callers keep a numpy fallback."""
    src = os.path.abspath(src_path)
    if not os.path.exists(src):
        return None
    cache = os.path.join(tempfile.gettempdir(),
                         f"apex_tpu_native_{os.getuid()}")
    os.makedirs(cache, exist_ok=True)
    import hashlib

    with open(src, "rb") as f:
        digest = hashlib.sha1(f.read()).hexdigest()[:16]
    lib_path = os.path.join(cache, f"{name}-{digest}.so")
    try:
        if not os.path.exists(lib_path):
            fd, tmp_path = tempfile.mkstemp(suffix=".so", dir=cache)
            os.close(fd)
            for cc in ("cc", "gcc", "clang"):
                try:
                    subprocess.run(
                        [cc, "-O2", "-shared", "-fPIC", src, "-o", tmp_path],
                        check=True, capture_output=True, timeout=60)
                    os.rename(tmp_path, lib_path)
                    break
                except (FileNotFoundError, subprocess.CalledProcessError,
                        subprocess.TimeoutExpired):
                    continue
            else:
                os.unlink(tmp_path)
                return None
        return ctypes.CDLL(lib_path)
    except OSError:
        return None


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    lib = build_ctypes_lib(_SRC, "flatten_unflatten")
    if lib is not None:
        lib.apex_flatten.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_size_t, ctypes.c_void_p]
        lib.apex_unflatten.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t), ctypes.c_size_t]
    _LIB = lib
    return _LIB


def native_available() -> bool:
    return _build_and_load() is not None


def flatten(arrays: Sequence[np.ndarray]):
    """Pack host arrays into one contiguous byte buffer.

    Returns ``(flat_u8, metas)`` where ``metas`` is the
    ``(shape, dtype, nbytes)`` list :func:`unflatten` needs.
    """
    # record shapes BEFORE ascontiguousarray (it promotes 0-d to 1-d)
    metas = [(np.asarray(a).shape, np.asarray(a).dtype,
              np.asarray(a).nbytes) for a in arrays]
    arrays = [np.ascontiguousarray(a) for a in arrays]
    total = sum(m[2] for m in metas)
    out = np.empty(total, np.uint8)
    lib = _build_and_load()
    if lib is None or not arrays:
        off = 0
        for a in arrays:
            out[off:off + a.nbytes] = a.view(np.uint8).reshape(-1)
            off += a.nbytes
        return out, metas
    n = len(arrays)
    srcs = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
    sizes = (ctypes.c_size_t * n)(*[a.nbytes for a in arrays])
    lib.apex_flatten(srcs, sizes, n,
                     out.ctypes.data_as(ctypes.c_void_p))
    return out, metas


def unflatten(flat: np.ndarray, metas) -> List[np.ndarray]:
    """Inverse of :func:`flatten`."""
    flat = np.ascontiguousarray(flat.view(np.uint8).reshape(-1))
    outs = [np.empty(shape, dtype) for shape, dtype, _ in metas]
    lib = _build_and_load()
    if lib is None or not outs:
        off = 0
        for o, (_, _, nbytes) in zip(outs, metas):
            # reshape first: 0-d arrays reject dtype-changing views
            o.reshape(-1).view(np.uint8)[:] = flat[off:off + nbytes]
            off += nbytes
        return outs
    n = len(outs)
    dsts = (ctypes.c_void_p * n)(
        *[o.ctypes.data_as(ctypes.c_void_p).value for o in outs])
    sizes = (ctypes.c_size_t * n)(*[m[2] for m in metas])
    lib.apex_unflatten(flat.ctypes.data_as(ctypes.c_void_p), dsts, sizes, n)
    return outs
