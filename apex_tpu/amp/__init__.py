"""apex_tpu.amp — mixed precision for TPU.

Rebuild of ``apex.amp`` (SURVEY.md §2.1): O0–O3 opt-level properties,
trace-time autocast (the O1 monkey-patch analog), dynamic loss scaling as a
jit-carried pytree, and the ``initialize``/``scale_loss``/``state_dict``
surface.
"""

from apex_tpu.amp._amp_state import (  # noqa: F401
    maybe_print,
    set_ingraph_logging,
    set_verbosity,
)
from apex_tpu.amp.autocast import (  # noqa: F401
    autocast,
    float_function,
    half_function,
    promote_function,
)
from apex_tpu.amp.frontend import (  # noqa: F401
    O0,
    O1,
    O2,
    O3,
    Properties,
    cast_model,
    initialize,
    opt_levels,
)
from apex_tpu.amp.handle import AmpHandle  # noqa: F401
from apex_tpu.amp.scaler import DynamicLossScaler, LossScaler, ScalerState  # noqa: F401

from apex_tpu.amp import _amp_state as _amp_state_mod


def _current_handle() -> AmpHandle:
    h = _amp_state_mod._amp_state.handle
    if h is None:
        raise RuntimeError(
            "Invoked amp function before calling amp.initialize()")
    return h


def scale_loss(loss, state, loss_id: int = 0):
    """Module-level ``amp.scale_loss`` (reference parity): delegates to
    the handle returned by the most recent :func:`initialize`."""
    return _current_handle().scale_loss(loss, state, loss_id)


def state_dict():
    """Module-level ``amp.state_dict()`` (reference parity)."""
    return _current_handle().state_dict()


def load_state_dict(sd):
    """Module-level ``amp.load_state_dict()`` (reference parity)."""
    return _current_handle().load_state_dict(sd)


def master_params(optimizer_state):
    """Iterate the fp32 master params held in a Fused* optimizer state
    (reference: ``amp.master_params(optimizer)``, the generator training
    scripts use for grad clipping on masters). Empty iterator when the
    optimizer runs without master weights (O0/O1)."""
    import jax as _jax

    master = getattr(optimizer_state, "master", None)
    if master is None:
        return iter(())
    return iter(_jax.tree.leaves(master))
