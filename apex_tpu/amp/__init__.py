"""apex_tpu.amp — mixed precision for TPU.

Rebuild of ``apex.amp`` (SURVEY.md §2.1): O0–O3 opt-level properties,
trace-time autocast (the O1 monkey-patch analog), dynamic loss scaling as a
jit-carried pytree, and the ``initialize``/``scale_loss``/``state_dict``
surface.
"""

from apex_tpu.amp._amp_state import (  # noqa: F401
    maybe_print,
    set_ingraph_logging,
    set_verbosity,
)
from apex_tpu.amp.autocast import (  # noqa: F401
    autocast,
    float_function,
    half_function,
    promote_function,
)
from apex_tpu.amp.frontend import (  # noqa: F401
    O0,
    O1,
    O2,
    O3,
    Properties,
    cast_model,
    initialize,
    opt_levels,
)
from apex_tpu.amp.handle import AmpHandle  # noqa: F401
from apex_tpu.amp.scaler import DynamicLossScaler, LossScaler, ScalerState  # noqa: F401
