"""Process-global amp bookkeeping.

Analog of the reference's ``apex/amp/_amp_state.py`` (SURVEY.md §5
metrics/observability row): holds the verbosity knob consulted by
``maybe_print`` and the overflow log line. In the rebuild almost all state
is carried functionally; only human-facing verbosity lives here.
"""

from __future__ import annotations


class AmpState:
    def __init__(self):
        self.verbosity = 1
        self.allow_incoming_model_not_fp32 = False
        # last handle returned by amp.initialize — backs the module-level
        # amp.scale_loss/state_dict conveniences (reference keeps the same
        # process-global handle in its _amp_state)
        self.handle = None
        # None = auto-detect: in-graph overflow logging uses jax.debug.print
        # (a host callback), which some TPU runtimes (axon PJRT) reject at
        # run time. Auto enables it only on the CPU backend; set explicitly
        # via set_ingraph_logging() to override.
        self.ingraph_logging = None

    def maybe_print(self, msg: str, rank0: bool = False):
        # stdout, like the reference's plain print() — downstream scripts
        # grep training stdout for the overflow line
        if self.verbosity >= 1:
            print(msg)


_amp_state = AmpState()


def get_verbosity() -> int:
    return _amp_state.verbosity


def set_verbosity(v: int):
    _amp_state.verbosity = v


def maybe_print(msg: str):
    _amp_state.maybe_print(msg)


def set_ingraph_logging(enabled):
    """Force in-graph (jax.debug.print) overflow logging on or off.

    Pass None to restore auto-detection (enabled only on the CPU backend,
    where host callbacks always work)."""
    _amp_state.ingraph_logging = enabled


def ingraph_logging_enabled() -> bool:
    if _amp_state.ingraph_logging is not None:
        return _amp_state.ingraph_logging
    import jax

    try:
        return jax.default_backend() == "cpu"
    except Exception:
        return False
