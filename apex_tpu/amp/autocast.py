"""Trace-time autocast: the TPU-native analog of apex O1 monkey-patching.

The reference's O1 mode (``apex/amp/amp.py`` + ``wrap.py``, SURVEY.md §3.1)
intercepts ``torch.*`` calls at runtime, casting inputs of whitelisted ops
to fp16 and blacklisted ops to fp32 per the tables in ``apex/amp/lists/``.

There is no runtime dispatch to intercept in JAX — but there is a trace.
:class:`autocast` patches the same op surface (``jax.numpy`` / ``jax.nn`` /
``jax.lax`` functions per :mod:`apex_tpu.amp.lists`) for the duration of a
``with`` block, so any model traced inside it gets the casts baked into its
jaxpr. Because casting happens at trace time, XLA CSE subsumes apex's
"cast cache" (repeated casts of the same weight dedupe for free), and the
cast graph is identical on every step — no per-iteration patch overhead at
all, which is strictly better than the reference's per-call wrappers.

Nesting follows torch/apex semantics: the innermost active context wins,
so ``autocast(enabled=False)`` inside an enabled region restores full
precision for its extent. Implementation: wrappers are installed once and
consult a context stack at call time.

Patching module attributes is thread-local-unsafe by nature (as is apex's);
use one autocast context per trace.
"""

from __future__ import annotations

import contextlib
import importlib

import jax.numpy as jnp

from apex_tpu.amp import lists

# Stack of active autocast contexts; wrappers consult the top at call time
# so nested contexts (including enabled=False) compose correctly.
_STACK = []
# (holder, name, orig) for installed wrappers; installed lazily on first
# enter, removed when the stack empties.
_INSTALLED = []


def _resolve(module_path: str, attr: str):
    mod = importlib.import_module(module_path)
    holder = mod
    parts = attr.split(".")
    for p in parts[:-1]:
        holder = getattr(holder, p)
    return holder, parts[-1]


def _cast_args(args, kwargs, dtype):
    def cast(x):
        if hasattr(x, "dtype") and hasattr(x, "astype") and jnp.issubdtype(
            jnp.result_type(x), jnp.floating
        ):
            return x.astype(dtype)
        # Recurse only into plain containers: NamedTuples (e.g.
        # lax.ConvDimensionNumbers) must pass through untouched.
        if type(x) in (tuple, list):
            return type(x)(cast(v) for v in x)
        return x

    return tuple(cast(a) for a in args), {k: cast(v) for k, v in kwargs.items()}


def _active():
    """The innermost enabled-or-disabled context, or None outside any."""
    return _STACK[-1] if _STACK else None


def _install():
    if _INSTALLED:
        return
    for table, kind in ((lists.WHITELIST, "lo"), (lists.BLACKLIST, "fp32")):
        for module_path, attr in table:
            try:
                holder, name = _resolve(module_path, attr)
                orig = getattr(holder, name)
            except (ImportError, AttributeError):
                continue  # op absent in this jax version; skip like apex does

            def make_wrapper(orig_fn, op_kind):
                def wrapper(*args, **kwargs):
                    ctx = _active()
                    if ctx is None or not ctx.enabled:
                        return orig_fn(*args, **kwargs)
                    dtype = jnp.float32 if op_kind == "fp32" else ctx.compute_dtype
                    args, kwargs = _cast_args(args, kwargs, dtype)
                    return orig_fn(*args, **kwargs)

                wrapper.__name__ = getattr(orig_fn, "__name__", "wrapped")
                wrapper.__wrapped_by_amp__ = True
                return wrapper

            setattr(holder, name, make_wrapper(orig, kind))
            _INSTALLED.append((holder, name, orig))


def _uninstall():
    for holder, name, orig in reversed(_INSTALLED):
        setattr(holder, name, orig)
    _INSTALLED.clear()


class autocast(contextlib.ContextDecorator):
    """Context manager enabling O1-style cast interception at trace time.

    Args:
      compute_dtype: dtype for whitelisted (MXU) ops. Default bf16 — the
        reference casts to fp16 on CUDA; on TPU the native low-precision
        matmul type is bf16 (the north star's "O1–O3 emit bf16").
      enabled: pass False to locally restore default precision (the
        torch/apex idiom for precision-critical subgraphs).
    """

    def __init__(self, compute_dtype=jnp.bfloat16, enabled: bool = True):
        self.compute_dtype = jnp.dtype(compute_dtype)
        self.enabled = enabled

    def __enter__(self):
        _install()
        _STACK.append(self)
        return self

    def __exit__(self, *exc):
        # Pop self (robust to exceptions raised between enter/exit).
        if self in _STACK:
            while _STACK and _STACK[-1] is not self:
                _STACK.pop()
            _STACK.pop()
        if not _STACK:
            _uninstall()
        return False


def half_function(fn):
    """Register-style decorator marking ``fn`` to always run in the compute
    dtype (analog of ``apex.amp.half_function``)."""

    def wrapped(*args, **kwargs):
        ctx = _active()
        dtype = ctx.compute_dtype if ctx is not None else jnp.bfloat16
        args, kwargs = _cast_args(args, kwargs, dtype)
        return fn(*args, **kwargs)

    return wrapped


def float_function(fn):
    """Analog of ``apex.amp.float_function``: force fp32 inputs."""

    def wrapped(*args, **kwargs):
        args, kwargs = _cast_args(args, kwargs, jnp.float32)
        return fn(*args, **kwargs)

    return wrapped


def promote_function(fn):
    """Analog of ``apex.amp.promote_function``: jax.numpy promotion already
    promotes to widest; returned unchanged for API parity."""
    return fn
