"""O1 autocast tables.

Rebuild of the reference's ``apex/amp/lists/{functional_overrides,
torch_overrides,tensor_overrides}.py`` (SURVEY.md §3.1): the fp16
whitelist (matmul-class ops run in the low-precision compute dtype — the
MXU path on TPU), the fp32 blacklist (reductions/transcendentals that are
precision-sensitive), and the promote set.

JAX note: the promote-to-widest behavior apex implements by hand for
binary ops is native to ``jax.numpy`` type promotion, so no promote
wrappers are installed; the table is kept for documentation parity.

Entries are ``(module_path, attr_name)`` resolved at patch time so the
same table drives both the patcher and introspection.
"""

# Ops cast to the policy compute dtype (bf16 on TPU): the FLOP carriers
# that map onto the MXU. Mirrors apex's FP16_FUNCS (conv1d/2d/3d +
# transposed variants, the *mm/*mv/bmm matmul family, matmul, linear,
# ger/outer, prelu — each mapped to its jax carrier; the many torch
# aliases of one GEMM collapse onto dot_general/einsum here).
WHITELIST = [
    ("jax.numpy", "matmul"),
    ("jax.numpy", "dot"),
    ("jax.numpy", "vdot"),
    ("jax.numpy", "inner"),
    ("jax.numpy", "outer"),            # torch ger/addr analog
    ("jax.numpy", "tensordot"),
    ("jax.numpy", "einsum"),
    ("jax.numpy", "linalg.multi_dot"),  # chained addmm analog
    ("jax.lax", "dot_general"),
    ("jax.lax", "dot"),
    ("jax.lax", "conv_general_dilated"),  # conv1d/2d/3d carrier
    ("jax.lax", "conv"),
    ("jax.lax", "conv_with_general_padding"),
    ("jax.lax", "conv_transpose"),     # conv_transpose1d/2d/3d analog
]

# Ops forced to fp32: mirrors apex's FP32_FUNCS (softmax/log_softmax,
# exp/log/pow family, trig/hyperbolic inverses, reciprocal/rsqrt,
# norms, loss functions, cumulative reductions).
BLACKLIST = [
    ("jax.numpy", "exp"),
    ("jax.numpy", "exp2"),
    ("jax.numpy", "expm1"),
    ("jax.numpy", "log"),
    ("jax.numpy", "log1p"),
    ("jax.numpy", "log2"),
    ("jax.numpy", "log10"),
    ("jax.numpy", "logaddexp"),
    ("jax.numpy", "logaddexp2"),
    ("jax.numpy", "power"),
    ("jax.numpy", "float_power"),
    ("jax.numpy", "reciprocal"),
    ("jax.numpy", "cosh"),
    ("jax.numpy", "sinh"),
    ("jax.numpy", "tan"),
    ("jax.numpy", "arccos"),           # torch acos
    ("jax.numpy", "arcsin"),           # torch asin
    ("jax.numpy", "cumsum"),
    ("jax.numpy", "cumprod"),
    ("jax.numpy", "prod"),
    ("jax.numpy", "linalg.norm"),
    ("jax.nn", "softmax"),
    ("jax.nn", "log_softmax"),
    ("jax.nn", "softplus"),
    ("jax.nn", "standardize"),
    ("jax.scipy.special", "logsumexp"),
    ("jax.lax", "rsqrt"),
    ("jax.lax", "erf_inv"),
    # loss family (apex blacklists the torch.nn.functional losses;
    # optax is the jax loss surface). BOTH holders are patched: the
    # top-level alias and the canonical optax.losses module are the
    # same function object, and a call through the unpatched holder
    # would silently bypass the fp32 forcing.
    ("optax", "softmax_cross_entropy"),
    ("optax", "softmax_cross_entropy_with_integer_labels"),
    ("optax", "sigmoid_binary_cross_entropy"),
    ("optax.losses", "softmax_cross_entropy"),
    ("optax.losses", "softmax_cross_entropy_with_integer_labels"),
    ("optax.losses", "sigmoid_binary_cross_entropy"),
]

# Binary ops whose mixed-dtype behavior apex resolves by promote-to-widest.
# jax.numpy promotion already implements exactly this; listed for parity
# docs / tests only. (apex: CASTS / SEQUENCE_CASTS promote tables.)
PROMOTE = [
    ("jax.numpy", "add"),
    ("jax.numpy", "subtract"),
    ("jax.numpy", "multiply"),
    ("jax.numpy", "divide"),
    ("jax.numpy", "equal"),
    ("jax.numpy", "greater"),
    ("jax.numpy", "less"),
    ("jax.numpy", "minimum"),
    ("jax.numpy", "maximum"),
]
