"""O1 autocast tables.

Rebuild of the reference's ``apex/amp/lists/{functional_overrides,
torch_overrides,tensor_overrides}.py`` (SURVEY.md §3.1): the fp16
whitelist (matmul-class ops run in the low-precision compute dtype — the
MXU path on TPU), the fp32 blacklist (reductions/transcendentals that are
precision-sensitive), and the promote set.

JAX note: the promote-to-widest behavior apex implements by hand for
binary ops is native to ``jax.numpy`` type promotion, so no promote
wrappers are installed; the table is kept for documentation parity.

Entries are ``(module_path, attr_name)`` resolved at patch time so the
same table drives both the patcher and introspection.
"""

# Ops cast to the policy compute dtype (bf16 on TPU): the FLOP carriers
# that map onto the MXU. Mirrors apex's FP16_FUNCS (conv*, *mm variants,
# matmul, linear, prelu...).
WHITELIST = [
    ("jax.numpy", "matmul"),
    ("jax.numpy", "dot"),
    ("jax.numpy", "vdot"),
    ("jax.numpy", "inner"),
    ("jax.numpy", "tensordot"),
    ("jax.numpy", "einsum"),
    ("jax.lax", "dot_general"),
    ("jax.lax", "dot"),
    ("jax.lax", "conv_general_dilated"),
    ("jax.lax", "conv"),
    ("jax.lax", "conv_with_general_padding"),
]

# Ops forced to fp32: mirrors apex's FP32_FUNCS (softmax/log_softmax,
# exp/log/pow family, norms, losses, cumulative reductions).
BLACKLIST = [
    ("jax.numpy", "exp"),
    ("jax.numpy", "expm1"),
    ("jax.numpy", "log"),
    ("jax.numpy", "log1p"),
    ("jax.numpy", "log2"),
    ("jax.numpy", "log10"),
    ("jax.numpy", "power"),
    ("jax.numpy", "float_power"),
    ("jax.numpy", "cosh"),
    ("jax.numpy", "sinh"),
    ("jax.numpy", "tan"),
    ("jax.numpy", "cumsum"),
    ("jax.numpy", "cumprod"),
    ("jax.numpy", "prod"),
    ("jax.numpy", "linalg.norm"),
    ("jax.nn", "softmax"),
    ("jax.nn", "log_softmax"),
    ("jax.nn", "standardize"),
    ("jax.scipy.special", "logsumexp"),
    ("jax.lax", "rsqrt"),
    ("jax.lax", "erf_inv"),
]

# Binary ops whose mixed-dtype behavior apex resolves by promote-to-widest.
# jax.numpy promotion already implements exactly this; listed for parity
# docs / tests only. (apex: CASTS / SEQUENCE_CASTS promote tables.)
PROMOTE = [
    ("jax.numpy", "add"),
    ("jax.numpy", "subtract"),
    ("jax.numpy", "multiply"),
    ("jax.numpy", "divide"),
    ("jax.numpy", "equal"),
    ("jax.numpy", "greater"),
    ("jax.numpy", "less"),
    ("jax.numpy", "minimum"),
    ("jax.numpy", "maximum"),
]
