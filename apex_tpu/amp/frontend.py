"""Opt-level properties and ``amp.initialize``.

Rebuild of ``apex/amp/frontend.py`` (SURVEY.md §3.1 / §5 config row): the
O0–O3 ``Properties`` table is preserved verbatim as the amp API contract —
each opt level selects defaults for ``cast_model_type``,
``patch_torch_functions`` (here: trace-time autocast),
``keep_batchnorm_fp32``, ``master_weights`` and ``loss_scale``; explicit
keyword arguments override the level defaults, and overriding a property an
opt level forbids raises, exactly like the reference.

TPU deltas (documented, intentional):
- the low-precision dtype defaults to **bfloat16** (the MXU-native type)
  instead of fp16; pass ``cast_model_type=jnp.float16`` to force fp16.
- "model" is a params pytree and casting is functional: ``initialize``
  returns new params rather than mutating modules.
- dynamic loss scaling is retained even for bf16 (the north star requires
  the scaler machinery intact; with bf16 it simply rarely triggers).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp import _amp_state
from apex_tpu.amp.autocast import autocast
from apex_tpu.amp.handle import AmpHandle
from apex_tpu.amp.scaler import LossScaler


@dataclasses.dataclass
class Properties:
    """The resolved amp property set (reference: ``frontend.Properties``)."""

    opt_level: str = "O0"
    cast_model_type: Optional[Any] = None
    patch_torch_functions: bool = False  # name kept for parity; = autocast
    keep_batchnorm_fp32: Optional[bool] = None
    master_weights: Optional[bool] = None
    loss_scale: Union[str, float] = 1.0
    enabled: bool = True

    @property
    def compute_dtype(self):
        return self.cast_model_type if self.cast_model_type is not None else jnp.bfloat16


class O0:
    brief = "O0: Pure fp32 training."
    more = "Calls .float() on your model, no-ops everything else."

    def __call__(self, properties: Properties) -> Properties:
        properties.opt_level = "O0"
        properties.cast_model_type = jnp.float32
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O1:
    brief = "O1: Insert automatic casts around safe-to-low-precision functions."
    more = ("The model's weights remain fp32; listed functions run in the "
            "compute dtype (bf16 on TPU) via trace-time autocast.")

    def __call__(self, properties: Properties) -> Properties:
        properties.opt_level = "O1"
        properties.cast_model_type = None
        properties.patch_torch_functions = True
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = None
        properties.loss_scale = "dynamic"
        return properties


class O2:
    brief = "O2: Cast the model to the compute dtype, keep norms in fp32, use fp32 master weights."
    more = ("Params are cast to bf16 except normalization params; the "
            "optimizer keeps fp32 master weights; dynamic loss scaling.")

    def __call__(self, properties: Properties) -> Properties:
        properties.opt_level = "O2"
        properties.cast_model_type = jnp.bfloat16
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        properties.loss_scale = "dynamic"
        return properties


class O3:
    brief = "O3: Pure low-precision training."
    more = "Everything in the compute dtype. A speed-of-light baseline."

    def __call__(self, properties: Properties) -> Properties:
        properties.opt_level = "O3"
        properties.cast_model_type = jnp.bfloat16
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = False
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


opt_levels = {"O0": O0(), "O1": O1(), "O2": O2(), "O3": O3()}

# Reference parity: properties each opt level refuses to override.
_DISALLOWED = {
    "O0": {"loss_scale": {"dynamic"}},
    "O1": {},
    "O2": {},
    "O3": {},
}

# Default predicate for keep_batchnorm_fp32: matches normalization-param
# path segments in common flax/haiku naming (BatchNorm_0, LayerNorm, bn1,
# rmsnorm...). The reference keys off module type (torch BN modules);
# functionally we key off the param path.
_NORM_RE = re.compile(
    r"(?i)(batch|layer|group|rms|sync)?[_]?norm"      # *norm, *_norm
    r"|(^|[._/])bn\d*($|[._/])"                        # bn, bn1 segments
    r"|(^|[._/])ln\d*($|[._/])|_ln\d*($|[._/])"        # ln / *_ln segments
)


def _default_norm_filter(path: str) -> bool:
    return bool(_NORM_RE.search(path))


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", getattr(p, "name", getattr(p, "idx", p)))
        parts.append(str(key))
    return "/".join(parts)


def cast_model(params, dtype, keep_fp32_filter: Optional[Callable[[str], bool]] = None):
    """Cast floating leaves of ``params`` to ``dtype``, keeping leaves whose
    path matches ``keep_fp32_filter`` in fp32 (the ``keep_batchnorm_fp32``
    mechanic of O2)."""

    def cast(path, x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        if keep_fp32_filter is not None and keep_fp32_filter(_path_str(path)):
            return x.astype(jnp.float32)
        return x.astype(dtype)

    return jax.tree_util.tree_map_with_path(cast, params)


def initialize(
    params,
    optimizers=None,
    opt_level: str = "O1",
    enabled: bool = True,
    cast_model_type=None,
    patch_torch_functions: Optional[bool] = None,
    keep_batchnorm_fp32: Optional[bool] = None,
    master_weights: Optional[bool] = None,
    loss_scale: Union[str, float, None] = None,
    num_losses: int = 1,
    verbosity: int = 1,
    min_loss_scale: Optional[float] = None,
    max_loss_scale: float = 2.0 ** 24,
    keep_fp32_filter: Optional[Callable[[str], bool]] = None,
):
    """Functional ``amp.initialize`` (reference: ``apex/amp/frontend.py``).

    Args mirror the reference signature. ``params`` is the model param
    pytree ("model"); ``optimizers`` is one of our Fused* optimizers (or a
    list of them, or None). Returns ``(params, optimizers, amp)`` where
    ``amp`` is an :class:`~apex_tpu.amp.handle.AmpHandle` holding the
    resolved :class:`Properties`, one :class:`LossScaler` per loss, and the
    ``state_dict``/``load_state_dict``/``scale_loss`` surface.
    """
    _amp_state.set_verbosity(verbosity)

    if opt_level not in opt_levels:
        raise ValueError(
            f"Unexpected optimization level {opt_level}. Options are 'O0', 'O1', 'O2', 'O3'."
        )

    properties = opt_levels[opt_level](Properties())
    properties.enabled = enabled
    _amp_state.maybe_print(f"Selected optimization level {opt_level}")
    _amp_state.maybe_print(opt_levels[opt_level].brief)

    for name, value in (
        ("cast_model_type", cast_model_type),
        ("patch_torch_functions", patch_torch_functions),
        ("keep_batchnorm_fp32", keep_batchnorm_fp32),
        ("master_weights", master_weights),
        ("loss_scale", loss_scale),
    ):
        if value is not None:
            bad = _DISALLOWED.get(opt_level, {}).get(name)
            if bad and value in bad:
                raise ValueError(f"Currently, {name}={value!r} is not supported with opt_level={opt_level}")
            setattr(properties, name, value)

    if not enabled:
        # The reference contract: enabled=False means "as if amp were
        # absent" but with the full API surface intact — so hand back a
        # static unity scaler whose update is a no-op.
        properties.patch_torch_functions = False
        handle = AmpHandle(
            properties,
            [LossScaler(loss_scale=1.0, loss_id=i) for i in range(num_losses)],
            autocast(enabled=False),
        )
        _amp_state._amp_state.handle = handle
        return params, optimizers, handle

    # Model casting (O2/O3).
    if properties.cast_model_type is not None and properties.cast_model_type != jnp.float32:
        norm_filter = None
        if properties.keep_batchnorm_fp32:
            norm_filter = keep_fp32_filter or _default_norm_filter
        params = cast_model(params, properties.cast_model_type, norm_filter)
    elif properties.cast_model_type == jnp.float32:
        params = cast_model(params, jnp.float32)

    # Loss scalers, one per loss (reference: num_losses). min_loss_scale
    # stays None unless the user sets it (reference default: no floor).
    scalers = [
        LossScaler(
            loss_scale=properties.loss_scale,
            min_loss_scale=min_loss_scale,
            max_loss_scale=max_loss_scale,
            loss_id=i,
        )
        for i in range(num_losses)
    ]

    # Optimizer master-weight configuration: our Fused* optimizers take a
    # ``master_weights`` flag (reference: _process_optimizer's
    # lazy_init_with_master_weights, SURVEY.md §3.1).
    single = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if single else list(optimizers)
    new_opts = []
    for opt in opt_list:
        if opt is not None and properties.master_weights and hasattr(opt, "with_master_weights"):
            opt = opt.with_master_weights(True)
        new_opts.append(opt)
    optimizers = new_opts[0] if single else new_opts

    cast_ctx = autocast(
        compute_dtype=properties.compute_dtype
        if properties.cast_model_type is None
        else properties.cast_model_type,
        enabled=properties.patch_torch_functions,
    )
    handle = AmpHandle(properties, scalers, cast_ctx)
    _amp_state._amp_state.handle = handle
    return params, optimizers, handle
