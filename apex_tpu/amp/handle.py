"""The amp handle: scalers, state_dict, scale_loss.

Rebuild of ``apex/amp/handle.py`` (SURVEY.md §3.2). The reference's
``scale_loss`` is a context manager around ``backward()``; in the
functional rebuild the equivalent one-stop helper is
:meth:`AmpHandle.value_and_grad`, which scales the loss, differentiates,
unscales, and surfaces the overflow flag for in-graph step skipping.

``state_dict()``/``load_state_dict()`` round-trip loss-scaler state with
the same key shape as the reference (``"loss_scaler0": {...}``), the
contract pinned by ``tests/L0/run_amp/test_checkpointing.py`` upstream.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, ScalerState


class AmpHandle:
    def __init__(self, properties, scalers: List[LossScaler], cast_ctx):
        self._properties = properties
        self.scalers = scalers
        self.autocast = cast_ctx
        # Mutable mirror of the traced scaler states for checkpointing in
        # the stateful veneer. Functional users carry ScalerStates
        # themselves and may ignore this.
        self.scaler_states = [s.init() for s in scalers]

    # -- properties passthrough (reference: amp handle exposes Properties) --
    @property
    def opt_level(self):
        return self._properties.opt_level

    @property
    def properties(self):
        return self._properties

    # -- functional step surface -----------------------------------------
    def init_state(self, loss_id: int = 0) -> ScalerState:
        return self.scalers[loss_id].init()

    def scaler(self, loss_id: int = 0) -> LossScaler:
        """The resolved :class:`LossScaler` for ``loss_id`` — the piece a
        step builder (``apex_tpu.train``) threads through its jitted
        program, so scaler STATE rides the donated carry while the
        scaler CONFIG stays a static closure."""
        return self.scalers[loss_id]

    def traced(self, loss_fn):
        """Public form of the opt-level trace wrapper: returns
        ``loss_fn`` traced under autocast when this opt level patches
        functions (O1), unchanged otherwise. Step builders use this to
        bake the whitelist/blacklist casts into their scan body without
        reaching into handle internals."""
        return self._traced(loss_fn)

    def _traced(self, loss_fn):
        """Trace loss_fn under autocast when this opt level patches
        functions (O1), so whitelist/blacklist casts bake into the
        jaxpr."""

        def traced(*args, **kwargs):
            if self._properties.patch_torch_functions:
                with self.autocast:
                    return loss_fn(*args, **kwargs)
            return loss_fn(*args, **kwargs)

        return traced

    def value_and_grad(self, loss_fn, state: ScalerState, loss_id: int = 0,
                       has_aux: bool = False):
        """Scaled value_and_grad; see :meth:`LossScaler.value_and_grad`."""
        return self.scalers[loss_id].value_and_grad(
            self._traced(loss_fn), state, has_aux=has_aux)

    def scaled_value_and_grad(self, loss_fn, state: ScalerState,
                              loss_id: int = 0, has_aux: bool = False):
        """Like :meth:`value_and_grad` but returns SCALED grads with no
        unscale pass — for the fused-tail flow where the optimizer
        unscales during its own first read
        (``opt.step(grads, ..., grad_scale=loss_scale)``)."""
        return self.scalers[loss_id].scaled_value_and_grad(
            self._traced(loss_fn), state, has_aux=has_aux)

    def scale_loss(self, loss, state: ScalerState, loss_id: int = 0):
        """Scale a loss value (enter half of the reference context manager)."""
        return self.scalers[loss_id].scale(loss, state)

    def unscale(self, grads, state: ScalerState, loss_id: int = 0):
        return self.scalers[loss_id].unscale(grads, state)

    def update_scale(self, state: ScalerState, found_inf, loss_id: int = 0):
        return self.scalers[loss_id].update(state, found_inf)

    # -- checkpointing (reference key shape: "loss_scaler0") --------------
    def state_dict(self):
        out = {}
        for i, st in enumerate(self.scaler_states):
            out[f"loss_scaler{i}"] = {
                "loss_scale": float(st.loss_scale),
                "unskipped": int(st.unskipped),
                "steps_skipped": int(st.steps_skipped),
                "hysteresis": int(st.hysteresis),
            }
        return out

    def load_state_dict(self, state_dict):
        for i in range(len(self.scaler_states)):
            entry = state_dict[f"loss_scaler{i}"]
            self.scaler_states[i] = ScalerState(
                loss_scale=jnp.asarray(entry["loss_scale"], jnp.float32),
                unskipped=jnp.asarray(entry["unskipped"], jnp.int32),
                steps_skipped=jnp.asarray(entry.get("steps_skipped", 0), jnp.int32),
                hysteresis=jnp.asarray(
                    entry.get("hysteresis", self.scalers[i].hysteresis),
                    jnp.int32),
            )
