"""Dynamic loss scaling as a jit-carried state pytree.

TPU-native rebuild of the reference's ``apex/amp/scaler.py:LossScaler``
(SURVEY.md §3.2). The contract constants are preserved exactly:

- initial dynamic scale ``2**16``
- backoff: divide by 2 on overflow, reset the growth tracker
- growth: multiply by 2 after 2000 consecutive overflow-free steps
  (``scale_seq_len`` / growth interval)
- default ceiling ``max_loss_scale = 2**24``; optional ``min_loss_scale``

The key TPU design change (SURVEY.md §7 hard part 1): apex performs a host
readback of a CUDA ``noop_flag`` buffer and imperatively skips
``optimizer.step()``. Here the overflow flag is a traced boolean carried
through the step function, and the skip is an in-graph select — no host
sync, no retrace.

On overflow the reference prints
``Gradient overflow.  Skipping step, loss scaler <id> reducing loss scale to <s>``
(``apex/amp/_amp_state.py:maybe_print``, grep'd for by downstream scripts);
we emit the same line via ``jax.debug.print`` when verbosity allows.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp import _amp_state
from apex_tpu.utils.pytree import all_finite, tree_select


class ScalerState(NamedTuple):
    """Traced loss-scaler state (a pytree; carry it through your jit)."""

    loss_scale: jnp.ndarray  # f32 scalar
    unskipped: jnp.ndarray   # i32 scalar: consecutive overflow-free steps
    steps_skipped: jnp.ndarray  # i32 scalar: lifetime skipped-step count
    # remaining consecutive-overflow tolerance before the scale backs off
    # (reference: csrc/update_scale_hysteresis.cu (U) — with the default
    # hysteresis of 1 every overflow backs off, the core-amp behavior)
    hysteresis: int = 1


@dataclasses.dataclass(frozen=True)
class LossScaler:
    """Static loss-scaler configuration.

    ``loss_scale="dynamic"`` reproduces apex's ``DynamicLossScaler``
    behavior; a float gives a static scale (``update`` is then a no-op),
    matching ``amp.initialize(loss_scale=N)``.
    """

    loss_scale: Union[str, float] = "dynamic"
    init_scale: float = 2.0 ** 16
    scale_factor: float = 2.0
    scale_seq_len: int = 2000  # apex: growth every 2000 unskipped steps
    # None (the reference default) = no floor: the scale may back off below
    # 1.0, which is how apex recovers when grads overflow even at scale 1.
    min_loss_scale: Optional[float] = None
    max_loss_scale: float = 2.0 ** 24
    loss_id: int = 0  # apex supports num_losses scalers, each with an id
    # back off only after this many consecutive overflow steps (each still
    # skipped); 1 = reference core-amp behavior. Mirrors the kernel-side
    # hysteresis of ``amp_C.update_scale_hysteresis`` (U).
    hysteresis: int = 1

    @property
    def dynamic(self) -> bool:
        return self.loss_scale == "dynamic"

    def init(self) -> ScalerState:
        scale = self.init_scale if self.dynamic else float(self.loss_scale)
        return ScalerState(
            loss_scale=jnp.asarray(scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32),
            steps_skipped=jnp.asarray(0, jnp.int32),
            hysteresis=jnp.asarray(self.hysteresis, jnp.int32),
        )

    # -- step pieces ------------------------------------------------------

    def scale(self, loss, state: ScalerState):
        """Multiply the loss by the current scale (apex ``scale_loss`` enter)."""
        return jax.tree.map(lambda l: l * state.loss_scale.astype(l.dtype), loss)

    def unscale(self, grads, state: ScalerState):
        """Unscale gradients and detect overflow in one fused pass.

        Analog of ``amp_C.multi_tensor_scale`` over all grads with the
        ``noop_flag`` inf/nan check (SURVEY.md §3.2): XLA fuses the
        multiply and the isfinite reduction over each buffer.

        Returns ``(unscaled_grads, found_inf)`` where ``found_inf`` is a
        traced bool. Non-finite grads are passed through unscaled-but-
        harmless; the caller must skip the step when ``found_inf``.
        """
        inv = (1.0 / state.loss_scale).astype(jnp.float32)
        found_inf = jnp.logical_not(all_finite(grads))
        unscaled = jax.tree.map(lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
        return unscaled, found_inf

    def update(self, state: ScalerState, found_inf) -> ScalerState:
        """Advance scaler state given this step's overflow flag."""
        if not self.dynamic:
            return state._replace(
                steps_skipped=state.steps_skipped + found_inf.astype(jnp.int32)
            )
        # overflow branch: decrement the hysteresis tolerance; only when it
        # is used up does the scale actually back off (hysteresis=1, the
        # default, backs off on every overflow — the reference core-amp
        # contract; >1 mirrors amp_C.update_scale_hysteresis (U))
        hys = jnp.asarray(state.hysteresis, jnp.int32) - found_inf.astype(jnp.int32)
        back_off_now = jnp.logical_and(found_inf, hys <= 0)
        floor = self.min_loss_scale if self.min_loss_scale is not None else 0.0
        backed_off = jnp.maximum(state.loss_scale / self.scale_factor, floor)
        # clean branch
        unskipped = state.unskipped + 1
        grow = unskipped >= self.scale_seq_len
        grown = jnp.where(
            grow,
            jnp.minimum(state.loss_scale * self.scale_factor, self.max_loss_scale),
            state.loss_scale,
        )
        reset_hys = jnp.asarray(self.hysteresis, jnp.int32)
        new = ScalerState(
            loss_scale=jnp.where(
                found_inf, jnp.where(back_off_now, backed_off, state.loss_scale),
                grown),
            unskipped=jnp.where(found_inf, 0, jnp.where(grow, 0, unskipped)).astype(jnp.int32),
            steps_skipped=state.steps_skipped + found_inf.astype(jnp.int32),
            # EVERY clean step replenishes the tolerance to its full value
            # (the cited kernel zeroes then refills hysteresis_tracker on a
            # non-overflow step), so only *consecutive* overflows deplete
            # it: with hysteresis>1, spiky losses whose overflows are
            # separated by clean steps never back the scale off. Note this
            # differs from Megatron's DynamicGradScaler, which replenishes
            # only on a growth event. Clamp the overflow branch at 0 to
            # keep the <=0 test stable instead of drifting negative.
            hysteresis=jnp.where(
                found_inf, jnp.maximum(hys, 0), reset_hys
            ).astype(jnp.int32),
        )
        if _amp_state.ingraph_logging_enabled() and _amp_state.get_verbosity() >= 1:
            # The reference's contractual overflow line. Emitted via a host
            # callback, which not every TPU runtime supports (the axon PJRT
            # plugin rejects host send/recv) — hence the capability gate in
            # ingraph_logging_enabled(); use amp.set_ingraph_logging(True)
            # to force it on runtimes known to support callbacks.
            prefix = ("Gradient overflow.  Skipping step, loss scaler "
                      + str(self.loss_id))

            def _log_reduce(s):
                jax.debug.print(prefix + " reducing loss scale to {scale}",
                                scale=s)

            def _log_hold(s):
                # hysteresis held: skipped, but the scale did NOT change —
                # distinct wording so grep/parse consumers of the
                # "reducing" line never record a phantom reduction
                jax.debug.print(prefix + " hysteresis holding loss scale "
                                "at {scale}", scale=s)

            jax.lax.cond(
                found_inf,
                lambda s: jax.lax.cond(back_off_now, _log_reduce,
                                       _log_hold, s),
                lambda s: None,
                new.loss_scale,
            )
        return new

    # -- convenience ------------------------------------------------------

    def value_and_grad(self, loss_fn, state: ScalerState, has_aux: bool = False):
        """``jax.value_and_grad`` on the *scaled* loss, returning unscaled
        loss/grads plus the overflow flag.

        Usage::

            (loss, found_inf, aux), grads = scaler.value_and_grad(f, st)(params)
        """
        scaled_vg = self.scaled_value_and_grad(loss_fn, state,
                                               has_aux=has_aux)

        def wrapped(*args, **kwargs):
            out, scaled_grads = scaled_vg(*args, **kwargs)
            loss, aux = out if has_aux else (out, None)
            grads, found_inf = self.unscale(scaled_grads, state)
            if has_aux:
                return (loss, found_inf, aux), grads
            return (loss, found_inf), grads

        return wrapped

    def scaled_value_and_grad(self, loss_fn, state: ScalerState,
                              has_aux: bool = False):
        """``jax.value_and_grad`` of the scaled loss returning the SCALED
        gradients and unscaled loss — no unscale pass and no finite check
        here. Pair with an optimizer that folds the unscale into its own
        first gradient read (``FusedLAMB.step(grad_scale=...)``): one
        fewer full read+write of the gradient tree per step than
        :meth:`value_and_grad` + separate ``unscale``, with the overflow
        check riding the optimizer's existing global-norm reduction."""

        def scaled_fn(*args, **kwargs):
            out = loss_fn(*args, **kwargs)
            if has_aux:
                loss, aux = out
            else:
                loss, aux = out, None
            return self.scale(loss, state), (loss, aux)

        vg = jax.value_and_grad(scaled_fn, has_aux=True)

        def wrapped(*args, **kwargs):
            (_, (loss, aux)), scaled_grads = vg(*args, **kwargs)
            if has_aux:
                return (loss, aux), scaled_grads
            return loss, scaled_grads

        return wrapped

    def maybe_apply(self, state: ScalerState, found_inf, updated_tree, old_tree):
        """Select ``updated_tree`` unless this step overflowed (in-graph
        step-skip), and advance the scaler. Returns ``(tree, new_state)``."""
        tree = tree_select(found_inf, old_tree, updated_tree)
        return tree, self.update(state, found_inf)

    # -- observability -----------------------------------------------------

    @staticmethod
    def metrics(state: ScalerState, grad_norm=None, loss=None) -> dict:
        """Per-step metrics dict (SURVEY.md §5 metrics row): the values a
        training harness logs each step. Traced values in, traced values
        out — call inside jit and log on the host after the step."""
        out = {
            "loss_scale": state.loss_scale,
            "unskipped": state.unskipped,
            "steps_skipped": state.steps_skipped,
        }
        if grad_norm is not None:
            out["grad_norm"] = grad_norm
        if loss is not None:
            out["loss"] = loss
        return out

    def host_overflow_report(self, prev_state: ScalerState,
                             new_state: ScalerState) -> bool:
        """Host-side fallback for the contractual overflow line.

        The in-graph ``jax.debug.print`` path in :meth:`update` needs
        host callbacks, which some TPU runtimes (axon PJRT) reject — so
        on those runtimes the line downstream scripts grep for would
        never print. Call this AFTER the step with the device states
        (one small host readback): if the step was skipped, it prints
        the reference's exact line and returns True. When the in-graph
        path already printed the line (dynamic scaler + callback-capable
        runtime), this only reports the boolean — no double line for
        grep-and-count consumers. Static scalers never print in-graph
        (``update`` early-returns), so their line always comes from here,
        and without the "reducing" clause (a static scale never backs
        off).
        """
        skipped = int(new_state.steps_skipped) > int(prev_state.steps_skipped)
        if not skipped:
            return False
        ingraph_already = (self.dynamic
                           and _amp_state.ingraph_logging_enabled())
        if not ingraph_already:
            if self.dynamic:
                # did the tracker back off this step? Mirror the in-graph
                # rule (prev tolerance depleted by this overflow) rather
                # than comparing scales: a back-off pinned at
                # min_loss_scale leaves the value unchanged but is still
                # the reference's "reducing" event.
                reduced = int(prev_state.hysteresis) <= 1
                if reduced:
                    _amp_state.maybe_print(
                        "Gradient overflow.  Skipping step, loss scaler "
                        f"{self.loss_id} reducing loss scale to "
                        f"{float(new_state.loss_scale)}"
                    )
                else:
                    # hysteresis held the scale: same skip event, distinct
                    # wording (no phantom reduction for grep consumers)
                    _amp_state.maybe_print(
                        "Gradient overflow.  Skipping step, loss scaler "
                        f"{self.loss_id} hysteresis holding loss scale at "
                        f"{float(new_state.loss_scale)}"
                    )
            else:
                _amp_state.maybe_print(
                    "Gradient overflow.  Skipping step, loss scaler "
                    f"{self.loss_id} static loss scale "
                    f"{float(new_state.loss_scale)} unchanged"
                )
        return True


# Backwards-handy aliases mirroring apex naming.
DynamicLossScaler = LossScaler
