"""Weight-norm reparameterization (reference:
``apex/reparameterization/{reparameterization,weight_norm}.py``,
SURVEY.md §2.1 — legacy surface).

The reference rewrites a module's weight as ``w = g * v / ||v||``
(Salimans & Kingma) by monkey-patching parameters and pre-forward hooks.
The functional analog operates on param pytrees:

- :func:`apply_weight_norm`: split matching leaves into ``(g, v)`` pairs
  (``w_g``/``w_v`` naming, like torch's) — train THESE;
- :func:`compute_weights` (the pre-forward hook analog): rebuild the
  dense weights from ``(g, v)`` before ``model.apply``;
- :func:`remove_weight_norm`: collapse back to plain weights.

Gradients flow through ``compute_weights`` by autodiff — the hand-written
``backward`` of the reference's ``Reparameterization`` is unnecessary.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

_G_SUFFIX = "_g"
_V_SUFFIX = "_v"


def _norm_except(v, dim: int):
    """||v|| reduced over every axis except ``dim`` (torch ``norm_except_dim``)."""
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)), axis=axes,
                            keepdims=True))


def weight_norm(v, g, dim: int = 0):
    """w = g * v / ||v||  with the norm over all axes but ``dim``."""
    return (g.astype(jnp.float32) * v.astype(jnp.float32)
            / _norm_except(v, dim)).astype(v.dtype)


class Reparameterization:
    """Reference base-class surface: ``compute_weight`` +
    ``reparameterize``/``restore`` over one tensor."""

    @staticmethod
    def reparameterize(w, dim: int = 0):
        """w -> (g, v): v = w, g = ||w|| (so compute_weight(g, v) == w)."""
        g = _norm_except(w, dim).astype(w.dtype)
        return g, w

    @staticmethod
    def compute_weight(g, v, dim: int = 0):
        return weight_norm(v, g, dim)


class WeightNorm(Reparameterization):
    """Reference class name (the only concrete Reparameterization)."""


def _is_dict(x):
    return isinstance(x, dict)


def apply_weight_norm(params, name: str = "kernel", dim: int = 0):
    """Split every leaf whose key equals ``name`` into ``name_g``/
    ``name_v`` throughout the pytree (reference:
    ``apply_weight_norm(module, name, dim)``). Returns the new pytree."""
    if not _is_dict(params):
        return params

    out = {}
    for key, val in params.items():
        if key == name and not _is_dict(val):
            g, v = WeightNorm.reparameterize(val, dim)
            out[key + _G_SUFFIX] = g
            out[key + _V_SUFFIX] = v
        elif _is_dict(val):
            out[key] = apply_weight_norm(val, name, dim)
        else:
            out[key] = val
    return out


def compute_weights(params, name: str = "kernel", dim: int = 0):
    """Rebuild dense weights from the ``(g, v)`` pairs (the pre-forward
    hook): feed the result to ``model.apply``. Differentiable — take
    grads w.r.t. the reparameterized pytree."""
    if not _is_dict(params):
        return params

    out = {}
    for key, val in params.items():
        if key.endswith(_G_SUFFIX) and key[:-len(_G_SUFFIX)] == name:
            v = params[name + _V_SUFFIX]
            out[name] = WeightNorm.compute_weight(val, v, dim)
        elif key.endswith(_V_SUFFIX) and key[:-len(_V_SUFFIX)] == name:
            continue  # consumed with its _g partner
        elif _is_dict(val):
            out[key] = compute_weights(val, name, dim)
        else:
            out[key] = val
    return out


def remove_weight_norm(params, name: str = "kernel", dim: int = 0):
    """Collapse ``(g, v)`` back into plain weights (reference name)."""
    return compute_weights(params, name, dim)
