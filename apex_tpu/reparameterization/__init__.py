"""apex.reparameterization parity surface (reference:
``apex/reparameterization``)."""

from apex_tpu.reparameterization.reparameterization import (
    Reparameterization,
    WeightNorm,
    apply_weight_norm,
    compute_weights,
    remove_weight_norm,
    weight_norm,
)

__all__ = ["Reparameterization", "WeightNorm", "apply_weight_norm",
           "compute_weights", "remove_weight_norm", "weight_norm"]
