"""Headline benchmark: BERT-large pretraining step throughput, one chip.

BASELINE.json configs[4]: amp O2 (bf16 + fp32 masters) + FusedLAMB with
the Pallas fused LayerNorm / scale-mask-softmax / flash-attention
kernels, at the TRUE pretraining config — hidden and attention dropout
0.1, attention dropout fused into the flash kernel (hardware PRNG).
The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
measured in-run against the unfused fp32 recipe (stock flax LayerNorm +
jnp softmax + materialized-score attention, fp32 params, same LAMB math,
same dropout) — i.e. the speedup this framework's mixed-precision +
fused-kernel path delivers over the naive one, which is exactly the
value apex adds over eager torch.

Prints ONE JSON line (on TPU — the BASELINE seq-512-class shape):
  {"metric": "bert_large_pretrain_s512_samples_per_sec_per_chip",
   "value": <optimized samples/sec/chip>, "unit": "samples/sec",
   "vs_baseline": <optimized / fp32-unfused>}
Off-TPU the flow runs as a tiny-model smoke and the metric is named
"bert_tiny_smoke_samples_per_sec" so nothing records it as a real
bert-large number.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def build_step(cfg_kwargs, opt_level, batch, seq):
    import apex_tpu.amp as amp
    from apex_tpu.models import BertConfig, BertForPreTraining, pretraining_loss
    from apex_tpu.optimizers import FusedLAMB

    maker = (BertConfig.bert_large if jax.default_backend() == "tpu"
             else BertConfig.tiny)  # off-TPU smoke: shape-check the flow
    # class-default dropouts (0.1/0.1): the real pretraining config
    cfg = maker(**cfg_kwargs)
    model = BertForPreTraining(cfg)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    types = jnp.zeros((batch, seq), jnp.int32)
    attn = jnp.ones((batch, seq), jnp.int32)
    mlm_labels = jnp.asarray(
        np.where(rng.rand(batch, seq) < 0.15,
                 rng.randint(0, cfg.vocab_size, (batch, seq)), -1))
    nsp_labels = jnp.asarray(rng.randint(0, 2, (batch,)))

    params = model.init(jax.random.PRNGKey(0), ids, types, attn)["params"]
    opt = FusedLAMB(lr=1e-4, weight_decay=0.01)
    params, opt, handle = amp.initialize(
        params, opt, opt_level=opt_level, verbosity=0)
    ost = opt.init(params)
    sst = handle.init_state()

    # The "fp32 unfused" baseline must do true fp32 matmul math: on TPU the
    # default matmul precision computes fp32 matmuls on the MXU in bf16
    # passes, which would silently hand the baseline the optimized path's
    # main speed advantage (this is the eager-fp32-torch analog the
    # reference's value-add is measured against).
    precision = "highest" if opt_level == "O0" else "default"

    def step(params, ost, sst, key):
        key, sub = jax.random.split(key)
        with jax.default_matmul_precision(precision):
            def loss_fn(p):
                mlm, nsp = model.apply({"params": p}, ids, types, attn,
                                       deterministic=False,
                                       rngs={"dropout": sub})
                return pretraining_loss(mlm, nsp, mlm_labels, nsp_labels)

            (loss, found), grads = handle.value_and_grad(loss_fn, sst)(params)
            p2, ost2 = opt.step(grads, ost, params, skip_if=found)
            return p2, ost2, handle.scalers[0].update(sst, found), loss, key

    # NOTE: no donate_argnums — buffer donation triggers a runtime
    # INVALID_ARGUMENT on the axon PJRT backend (re-verified this round:
    # a trivial donated jit works, but donating ANY of this step's args —
    # even the 3-scalar scaler state alone — fails at run time, so it is
    # a runtime limitation, not an aliasing bug here). Donation would
    # halve optimizer-state peak memory (it is what caps S=512 at B=8);
    # revisit when the runtime supports it.
    jitted = jax.jit(step)
    model_info = dict(
        n_params=sum(x.size for x in jax.tree.leaves(params)),
        n_layers=cfg.num_layers, hidden=cfg.hidden_size)
    # The state is returned in a single-element list so time_steps can POP
    # it: without buffer donation (unsupported on axon), any lingering
    # caller reference to the initial 5 GB state tuple keeps it alive for
    # the whole timing loop and OOMs the 16 GB chip at step 1.
    return jitted, [(params, ost, sst, jax.random.PRNGKey(17))], model_info


def time_steps(jitted, state_box, warmup=2, iters=8):
    params, ost, sst, key = state_box.pop()  # take ownership; see build_step
    for _ in range(warmup):
        params, ost, sst, loss, key = jitted(params, ost, sst, key)
    # Block on the FULL output tree: on this runtime individual buffers
    # become ready as they are produced, and `loss` only depends on the
    # forward pass — blocking on it alone under-measures the step by the
    # entire backward + optimizer tail (observed 35x at S=512).
    jax.block_until_ready((params, ost, sst, loss))
    t0 = time.perf_counter()
    for _ in range(iters):
        params, ost, sst, loss, key = jitted(params, ost, sst, key)
    jax.block_until_ready((params, ost, sst, loss))
    dt = (time.perf_counter() - t0) / iters
    return dt, float(loss)


def model_flops_per_step(n_params, batch, seq, n_layers, hidden):
    """Approximate model FLOPs for one fwd+bwd step: 6*N per token for the
    matmul-dominated path plus the attention score/context term
    (12 * L * B * S^2 * H, fwd+bwd)."""
    matmul = 6.0 * n_params * batch * seq
    attn = 12.0 * n_layers * batch * seq * seq * hidden
    return matmul + attn


def peak_flops():
    """Peak bf16 FLOP/s of the attached chip (v5e default)."""
    kind = jax.devices()[0].device_kind.lower()
    if "v5p" in kind or "v5 p" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    return 197e12  # v5e / v5 lite


def _reset():
    """Free the previous config's executables + live buffers: at S=512
    the fp32 baseline only fits on the 16 GB chip if the optimized
    config's state is truly gone (no donation on this runtime)."""
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()


def _measure(batch, seq, iters, with_baseline=True, remat=True):
    """(optimized dt, baseline dt or None, mfu) at one shape."""
    _reset()
    jitted, state, info = build_step(
        dict(dtype=jnp.bfloat16, fused_kernels=True, remat=remat),
        "O2", batch, seq)
    dt_opt, loss_opt = time_steps(jitted, state, warmup=2, iters=iters)
    del jitted, state
    _reset()

    dt_base = loss_base = None
    if with_baseline:
        jitted, state, _ = build_step(
            dict(dtype=jnp.float32, fused_kernels=False), "O0", batch, seq)
        dt_base, loss_base = time_steps(jitted, state, warmup=2,
                                        iters=max(iters // 2, 2))
        del jitted, state
        _reset()

    mfu = model_flops_per_step(
        info["n_params"], batch, seq, info["n_layers"], info["hidden"],
    ) / dt_opt / peak_flops()
    base_txt = ("" if dt_base is None else
                f" | baseline(fp32 unfused) {dt_base*1e3:.1f} ms/step "
                f"(loss {loss_base:.3f})")
    print(
        f"# B={batch} S={seq}: optimized(bf16 O2+fused) "
        f"{dt_opt*1e3:.1f} ms/step = {batch/dt_opt:.1f} samples/s "
        f"MFU={mfu:.3f} (loss {loss_opt:.3f}){base_txt} | "
        f"params={info['n_params']/1e6:.0f}M backend={jax.default_backend()}",
        file=sys.stderr,
    )
    return dt_opt, dt_base, mfu


def main():
    on_tpu = jax.default_backend() == "tpu"
    # Headline: the BASELINE seq-512-class pretraining shape. With the
    # logsumexp MLM loss, B=16 WITHOUT per-layer remat fits the 16 GB
    # chip and beats every remat'd batch (no recompute tax: 73.5 vs
    # 67.6 samples/s at B=32 remat'd). Re-swept on-chip this round:
    # B=20 no-remat drops to 69.1 samples/s (MFU .423) and B>=24 OOMs
    # at any remat policy (incl. dots-only), so B=16 stays the peak.
    # The fp32 baseline keeps remat (its fp32 activations would not
    # fit otherwise).
    batch, seq = (16, 512) if on_tpu else (2, 32)
    dt_opt, dt_base, mfu = _measure(batch, seq, iters=8, remat=not on_tpu)
    if on_tpu and "--all-shapes" in sys.argv:
        # secondary shape for comparison with earlier rounds' S=128 runs
        # (off by default: each extra config costs a slow fresh compile
        # and the driver runs this file under a time budget)
        _measure(64, 128, iters=6, with_baseline=False)

    result = {
        "metric": ("bert_large_pretrain_s512_samples_per_sec_per_chip"
                   if on_tpu else "bert_tiny_smoke_samples_per_sec"),
        "value": round(batch / dt_opt, 3),
        "unit": "samples/sec",
        "vs_baseline": round(dt_base / dt_opt, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
