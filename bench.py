"""Headline benchmark: BERT-large pretraining step throughput, one chip.

BASELINE.json configs[4]: amp O2 (bf16 + fp32 masters) + FusedLAMB with
the Pallas fused LayerNorm / scale-mask-softmax / flash-attention
kernels, at the TRUE pretraining config — hidden and attention dropout
0.1, attention dropout fused into the flash kernel (hardware PRNG).
The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
measured in-run against the unfused fp32 recipe (stock flax LayerNorm +
jnp softmax + materialized-score attention, fp32 params, same LAMB math,
same dropout) — i.e. the speedup this framework's mixed-precision +
fused-kernel path delivers over the naive one, which is exactly the
value apex adds over eager torch.

Prints ONE JSON line (on TPU — the BASELINE seq-512-class shape):
  {"metric": "bert_large_pretrain_s512_samples_per_sec_per_chip",
   "value": <optimized samples/sec/chip>, "unit": "samples/sec",
   "vs_baseline": <optimized / fp32-unfused>}
Off-TPU the flow runs as a tiny-model smoke and the metric is named
"bert_tiny_smoke_samples_per_sec" so nothing records it as a real
bert-large number.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


# Per-invocation entropy for ALL benchmark inputs. The axon runtime
# memoizes (program, inputs) -> results ACROSS PROCESSES: a re-run of a
# bit-identical deterministic benchmark is served from cache and reports
# a physically impossible step time (observed: 5 ms/step for the 367M
# fwd+bwd+LAMB step that really takes ~200 ms). Salting the data seeds
# guarantees every invocation measures fresh execution; the reported
# loss varies in the third decimal run-to-run, which is expected.
_SALT = int(time.time() * 1e3) % (2 ** 30)

# Resolved-once backend cache: _backend_with_cpu_fallback() resolves the
# backend (with the CPU fallback) exactly once and every section reuses
# the answer. BENCH_r01/r05 lost whole rounds (rc=1, parsed: null)
# because sections re-called jax.default_backend() directly — a plugin
# that came up after main()'s probe, then failed mid-run, resurfaced as
# an uncaught init exception in the middle of the perf sweep.
_RESOLVED_BACKEND = None


def _backend_with_cpu_fallback():
    """First touch of the JAX backend, with a CPU fallback: plugin init
    can raise at first use (BENCH_r05: the TPU plugin came up
    ``UNAVAILABLE`` and the whole run died with rc=1, recording
    nothing). A crashed round is strictly worse than a CPU-smoke round
    — fall back to ``JAX_PLATFORMS=cpu`` so the bench trajectory keeps
    recording (the off-TPU metric names already mark smoke runs).
    Memoized: later sections MUST use this (never
    ``jax.default_backend()`` directly) so a mid-run plugin failure
    can't resurface after the first resolution."""
    global _RESOLVED_BACKEND
    if _RESOLVED_BACKEND is not None:
        return _RESOLVED_BACKEND
    try:
        _RESOLVED_BACKEND = jax.default_backend()
    except Exception as e:
        print(f"# backend init failed ({type(e).__name__}: {e}); "
              "falling back to JAX_PLATFORMS=cpu", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        _RESOLVED_BACKEND = jax.default_backend()
    return _RESOLVED_BACKEND


def build_step(cfg_kwargs, opt_level, batch, seq):
    import apex_tpu.amp as amp
    from apex_tpu.models import BertConfig, BertForPreTraining, pretraining_loss
    from apex_tpu.optimizers import FusedLAMB

    maker = (BertConfig.bert_large if _backend_with_cpu_fallback() == "tpu"
             else BertConfig.tiny)  # off-TPU smoke: shape-check the flow
    # class-default dropouts (0.1/0.1): the real pretraining config
    cfg = maker(**cfg_kwargs)
    model = BertForPreTraining(cfg)

    rng = np.random.RandomState(_SALT)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    types = jnp.zeros((batch, seq), jnp.int32)
    attn = jnp.ones((batch, seq), jnp.int32)
    # MLPerf input format (round 4): masked positions as a dense (B, P)
    # list with per-slot weights, P = max_predictions_per_seq (76 at
    # S=512, the MLPerf value) — the MLM head computes ONLY these
    # positions, exactly like the reference harness. Round 3 ran the
    # vocab decoder over all S positions, work the reference never does.
    n_pred = max(int(seq * 0.15), 2)
    if seq == 512:
        n_pred = 76
    pos_np = np.zeros((batch, n_pred), np.int32)
    lab_np = np.zeros((batch, n_pred), np.int32)
    wgt_np = np.zeros((batch, n_pred), np.float32)
    for b in range(batch):
        chosen = rng.choice(seq, size=rng.randint(max(n_pred // 2, 1),
                                                  n_pred + 1),
                            replace=False)
        chosen.sort()
        pos_np[b, :len(chosen)] = chosen
        lab_np[b, :len(chosen)] = rng.randint(0, cfg.vocab_size,
                                              len(chosen))
        wgt_np[b, :len(chosen)] = 1.0
    positions = jnp.asarray(pos_np)
    mlm_labels = jnp.asarray(lab_np)
    mlm_weights = jnp.asarray(wgt_np)
    nsp_labels = jnp.asarray(rng.randint(0, 2, (batch,)))

    params = model.init(jax.random.PRNGKey(0), ids, types, attn)["params"]
    # APEX_BENCH_MOMENTS selects the LAMB moment dtype for the O2 arm
    # (bf16 = the round-5 low-HBM tier: stochastically-rounded bf16 m/v
    # + recompute-update stage 2). Default stays f32: the bf16 arm's
    # headline A/B could not be completed in round 5 — the tunnel's
    # compile service went down mid-A/B (the f32 arm measured 135.9
    # ms/step = 117.8 samples/s just before) — and the recorded bench
    # must not gamble on an unmeasured compile. Flip the default once
    # an A/B lands. The fp32-unfused baseline arm always keeps fp32
    # moments (the naive recipe it represents).
    knob = os.environ.get("APEX_BENCH_MOMENTS", "f32")
    if knob in ("f32", "fp32", "float32"):
        moments = "float32"
    elif knob in ("bf16", "bfloat16"):
        moments = "bfloat16"
    else:
        raise ValueError(f"APEX_BENCH_MOMENTS={knob!r}: use f32 or bf16")
    if opt_level != "O2":
        moments = "float32"
    opt = FusedLAMB(lr=1e-4, weight_decay=0.01, moments_dtype=moments)
    params, opt, handle = amp.initialize(
        params, opt, opt_level=opt_level, verbosity=0)
    ost = opt.init(params)
    sst = handle.init_state()

    # The "fp32 unfused" baseline must do true fp32 matmul math: on TPU the
    # default matmul precision computes fp32 matmuls on the MXU in bf16
    # passes, which would silently hand the baseline the optimized path's
    # main speed advantage (this is the eager-fp32-torch analog the
    # reference's value-add is measured against).
    precision = "highest" if opt_level == "O0" else "default"

    def step(params, ost, sst, key):
        key, sub = jax.random.split(key)
        with jax.default_matmul_precision(precision):
            def loss_fn(p):
                mlm, nsp = model.apply({"params": p}, ids, types, attn,
                                       deterministic=False,
                                       rngs={"dropout": sub},
                                       masked_positions=positions)
                return pretraining_loss(mlm, nsp, mlm_labels, nsp_labels,
                                        mlm_weights)

            if opt_level == "O2":
                # fused tail: scaled grads go straight into LAMB, which
                # unscales inside its own reads and overflow-checks via
                # its global-norm reduction (one fewer full pass over
                # the gradient tree than unscale-then-step)
                loss, grads = handle.scaled_value_and_grad(loss_fn, sst)(
                    params)
                p2, ost2, found = opt.step(grads, ost, params,
                                           grad_scale=sst.loss_scale)
            else:
                (loss, found), grads = handle.value_and_grad(loss_fn, sst)(
                    params)
                p2, ost2 = opt.step(grads, ost, params, skip_if=found)
            return p2, ost2, handle.scalers[0].update(sst, found), loss, key

    # Buffer donation: STILL unsupported on the axon runtime for real
    # steps. Re-probed 2026-07-31 (round 4): a trivial donated jit now
    # works (it failed in round 3), but donating this step's
    # params/ost/sst at any B in {16, 24, 32} still dies at run time
    # with "INVALID_ARGUMENT: TPU backend error (InvalidArgument)".
    # Donation would halve optimizer-state peak (the B=16 cap); re-probe
    # each round with ``--donate``.
    donate = (0, 1, 2) if "--donate" in sys.argv else ()
    jitted = jax.jit(step, donate_argnums=donate)
    model_info = dict(
        n_params=sum(x.size for x in jax.tree.leaves(params)),
        n_layers=cfg.num_layers, hidden=cfg.hidden_size,
        n_pred=n_pred, vocab=cfg.vocab_size)
    # The state is returned in a single-element list so time_steps can POP
    # it: without buffer donation (unsupported on axon), any lingering
    # caller reference to the initial 5 GB state tuple keeps it alive for
    # the whole timing loop and OOMs the 16 GB chip at step 1.
    return jitted, [(params, ost, sst, jax.random.PRNGKey(_SALT))], model_info


def marginal_time(advance, fetch, iters, windows=2):
    """Marginal-fetch timing (round-4 methodology) — THE one timing
    primitive for this runtime; time_steps, _chain_time, and
    tools/profile_step.py all delegate here so a methodology fix lands
    once.

    Every window of chained steps on this runtime carries a constant
    synchronization cost on top of the real compute — measured ~100-140
    ms whether the window ends in ``block_until_ready`` or a value
    fetch (and for some programs ``block_until_ready``/``is_ready``
    return EARLY with the work still pending, so a value fetch is the
    only reliable barrier). Dividing a single window by its iteration
    count therefore inflates every step by overhead/iters — the round-3
    numbers carried ~+12 ms/step of pure window overhead.

    The fix: time two windows of different lengths, each ended by a
    value fetch, and report the MARGINAL cost
    (T_big - T_small) / (n_big - n_small). The constant cancels; what
    remains is the sustained per-step cost a real training loop pays
    (it blocks rarely, so the sustained rate IS the marginal rate).
    Verified linear: T(n) = n*dt + c fits windows of 2 and 6 BERT-large
    steps to <1%.

    Noise guard: a tunnel-latency spike landing in a small window can
    push the marginal non-positive (the sync constant swings +/-30%);
    non-positive marginals are DISCARDED, and if every window pair is
    corrupted the fallback is the big window's mean (a conservative
    upper bound, never negative).

    Args:
      advance: ``advance(n)`` runs n chained steps (state must evolve
        through every call — the runtime memoizes repeated inputs).
      fetch: value-fetch barrier returning a float that depends on the
        full step output.
      iters: big-window length; the small window is ``max(iters//4, 1)``.
    """
    n_small = max(iters // 4, 1)
    if iters <= n_small:  # degenerate window pair (iters=1): no marginal
        t0 = time.perf_counter()
        advance(iters)
        fetch()
        return (time.perf_counter() - t0) / iters
    marginals = []
    t_big_last = None
    for _ in range(windows):
        t0 = time.perf_counter()
        advance(n_small)
        fetch()
        t_small = time.perf_counter() - t0
        t0 = time.perf_counter()
        advance(iters)
        fetch()
        t_big = time.perf_counter() - t0
        t_big_last = t_big
        dt = (t_big - t_small) / (iters - n_small)
        if dt > 0:
            marginals.append(dt)
    if not marginals:  # every pair noise-corrupted: conservative bound
        marginals.append(t_big_last / iters)
    return min(marginals)


def time_steps(jitted, state_box, warmup=2, iters=8, windows=3):
    """Headline-step timing via :func:`marginal_time` (best-of-3 window
    pairs: the headline is the round's recorded number, so it gets one
    more chance against tunnel-latency spikes than the microbenches;
    each extra pair costs ~2 s)."""
    params, ost, sst, key = state_box.pop()  # take ownership; see build_step
    loss = None
    for _ in range(warmup):
        params, ost, sst, loss, key = jitted(params, ost, sst, key)
    float(loss)  # value fetch: the only reliable execution barrier

    def advance(n):
        nonlocal params, ost, sst, key, loss
        for _ in range(n):
            params, ost, sst, loss, key = jitted(params, ost, sst, key)

    dt = marginal_time(advance, lambda: float(loss), iters,
                       windows=windows)
    return dt, float(loss)


def model_flops_per_step(n_params, batch, seq, n_layers, hidden,
                         n_pred=None, vocab=None):
    """Approximate model FLOPs for one fwd+bwd step: 6*N per token for the
    matmul-dominated path plus the attention score/context term
    (12 * L * B * S^2 * H, fwd+bwd).

    ``n_pred``/``vocab``: with the MLPerf gathered-predictions head the
    MLM transform+decoder run on B*P rows, not B*S — their FLOPs are
    counted at the rows actually computed (honest MFU accounting: the
    gather makes the step FASTER without inflating the utilization
    number)."""
    matmul = 6.0 * n_params * batch * seq
    if n_pred is not None:
        tail_params = hidden * hidden + hidden * vocab  # transform+decoder
        matmul -= 6.0 * tail_params * batch * (seq - n_pred)
    attn = 12.0 * n_layers * batch * seq * seq * hidden
    return matmul + attn


def peak_flops():
    """Peak bf16 FLOP/s of the attached chip (v5e default)."""
    kind = jax.devices()[0].device_kind.lower()
    if "v5p" in kind or "v5 p" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    return 197e12  # v5e / v5 lite


def _reset():
    """Free the previous config's executables + live buffers: at S=512
    the fp32 baseline only fits on the 16 GB chip if the optimized
    config's state is truly gone (no donation on this runtime)."""
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()


# every record this invocation printed (metric lines + section
# records), so the end-of-run bench_diff report can compare THIS run
# against the newest recorded BENCH_*.json without waiting for the
# driver to write the new artifact
_RUN_RECORDS = []


def _print_record(rec):
    """Print one JSON record line AND remember it for the end-of-run
    bench_diff report."""
    print(json.dumps(rec))
    _RUN_RECORDS.append(rec)


def _emit_section_record(name, status, wall_s, error=None):
    """One `{"section": ...}` JSON line per bench section: wall time +
    exit status, emitted whether the section lived or died. BENCH_r01
    and r05 lost whole rounds to sections that crashed and simply left
    NOTHING in the artifact — a dead section must be a visible record
    ("status": "failed" + the error), not an absence someone has to
    diff against the previous round to notice."""
    rec = {"section": name, "status": status,
           "wall_time_s": round(wall_s, 3)}
    if error is not None:
        rec["error"] = error
    _print_record(rec)


def _print_bench_diff_report():
    """End-of-full-run satellite (round 15): compare THIS run's records
    against the newest recorded ``BENCH_*.json`` with
    ``tools/bench_diff.py`` and PRINT the report (stderr, so the
    stdout record stream stays machine-parseable). The comparer landed
    in round 13 but nothing invoked it — a section that quietly
    vanished still read as a clean round to a human eyeballing metric
    lines. Strictly informational here: a perf round must record its
    numbers even when they regressed (the verdict line says which),
    so this NEVER fails the run."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        sys.path.insert(0, here)
        from tools.bench_diff import diff, parse_artifact

        priors = sorted(f for f in os.listdir(here)
                        if f.startswith("BENCH_") and f.endswith(".json"))
        if not priors:
            return
        newest = os.path.join(here, priors[-1])
        current = {"rc": None, "metrics": {}, "sections": {}}
        for rec in _RUN_RECORDS:
            if "metric" in rec:
                current["metrics"][str(rec["metric"])] = rec
            elif "section" in rec:
                current["sections"][str(rec["section"])] = rec
        rc, lines = diff(parse_artifact(newest), current)
        print(f"== bench diff vs {priors[-1]} (informational — never "
              "fails the run) ==", file=sys.stderr)
        for line in lines:
            print(line, file=sys.stderr)
        print(f"== bench diff verdict: "
              f"{'REGRESSIONS FLAGGED' if rc else 'ok'} ==",
              file=sys.stderr)
    except Exception as e:  # the report must never kill a perf round
        print(f"# bench_diff report skipped: {type(e).__name__}: {e}",
              file=sys.stderr)


def _run_section(name, fn, retries=1):
    """Run one bench section with the standard transient retry, print
    its JSON result, and ALWAYS follow with the section record. Returns
    True when the section produced a result."""
    t0 = time.perf_counter()
    last_err = None
    for attempt in range(retries + 1):
        try:
            _print_record(fn())
            _emit_section_record(name, "ok", time.perf_counter() - t0)
            return True
        except Exception as e:  # a dying section must not kill the run
            last_err = f"{type(e).__name__}: {e}"
            print(f"# {name} attempt {attempt} failed: {e}",
                  file=sys.stderr)
            _reset()
    _emit_section_record(name, "failed", time.perf_counter() - t0,
                         error=last_err)
    return False


def _measure(batch, seq, iters, with_baseline=True, remat=True):
    """(optimized dt, baseline dt or None, mfu) at one shape."""
    _reset()
    jitted, state, info = build_step(
        dict(dtype=jnp.bfloat16, fused_kernels=True, remat=remat),
        "O2", batch, seq)
    dt_opt, loss_opt = time_steps(jitted, state, warmup=2, iters=iters)
    del jitted, state
    _reset()

    dt_base = loss_base = None
    if with_baseline:
        jitted, state, _ = build_step(
            dict(dtype=jnp.float32, fused_kernels=False), "O0", batch, seq)
        dt_base, loss_base = time_steps(jitted, state, warmup=2,
                                        iters=max(iters // 2, 2))
        del jitted, state
        _reset()

    mfu = model_flops_per_step(
        info["n_params"], batch, seq, info["n_layers"], info["hidden"],
        n_pred=info["n_pred"], vocab=info["vocab"],
    ) / dt_opt / peak_flops()
    base_txt = ("" if dt_base is None else
                f" | baseline(fp32 unfused) {dt_base*1e3:.1f} ms/step "
                f"(loss {loss_base:.3f})")
    print(
        f"# B={batch} S={seq}: optimized(bf16 O2+fused) "
        f"{dt_opt*1e3:.1f} ms/step = {batch/dt_opt:.1f} samples/s "
        f"MFU={mfu:.3f} (loss {loss_opt:.3f}){base_txt} | "
        f"params={info['n_params']/1e6:.0f}M "
        f"backend={_backend_with_cpu_fallback()}",
        file=sys.stderr,
    )
    return dt_opt, dt_base, mfu


def _fetch(state):
    """Value fetch of one element: the only reliable execution barrier
    on this runtime (block_until_ready/is_ready return early for some
    chained programs — see marginal_time)."""
    leaf = jax.tree.leaves(state)[0]
    return float(jnp.sum(leaf))


def _chain_time_stateful(step, state, iters, warmup=2, windows=2):
    """(marginal dt, evolved state): the state keeps evolving through
    warmup and every timed window (defeats the runtime's cross-process
    result memoization)."""
    for _ in range(warmup):
        state = step(*state)
    _fetch(state)
    box = [state]

    def advance(n):
        for _ in range(n):
            box[0] = step(*box[0])

    dt = marginal_time(advance, lambda: _fetch(box[0]), iters,
                       windows=windows)
    return dt, box[0]


def _chain_time(step, state, iters, warmup=2, windows=2):
    """Microbench timing via :func:`marginal_time`: state evolves
    through every call (defeats the runtime's result memoization)."""
    dt, _ = _chain_time_stateful(step, state, iters, warmup, windows)
    return dt


def _ab_chain_time(step_a, step_b, state, iters, rounds=3):
    """INTERLEAVED A/B timing for ratio metrics: alternate the two arms
    round-robin and report each arm's best marginal.

    Round-5 lesson (the LN microbench regression post-mortem): timing
    arm A fully and then arm B exposes the RATIO to tunnel/runtime
    drift between the two measurement periods — the same code measured
    0.85x (driver), 0.92x, and 1.14x across sessions purely by when
    each arm ran. Alternating rounds puts both arms through the same
    drift, and min-per-arm discards the contended rounds.

    Each arm's state THREADS ACROSS ROUNDS (round 2 continues from
    round 1's evolved carry): restarting from the shared initial state
    would replay a bit-identical (program, inputs) sequence that the
    runtime memoizer serves from cache, and min() would then pick the
    cache-serve time."""
    t_a, t_b = [], []
    s_a = s_b = state
    for _ in range(rounds):
        dt, s_a = _chain_time_stateful(step_a, s_a, iters)
        t_a.append(dt)
        dt, s_b = _chain_time_stateful(step_b, s_b, iters)
        t_b.append(dt)
    return min(t_a), min(t_b)


def bench_layer_norm(fast=False):
    """BASELINE configs[1]: FusedLayerNorm (training dispatch: XLA-fused
    fwd + Pallas bwd) vs stock-XLA LN, fwd+bwd at the shape the
    dispatcher serves — LN between GEMMs (the pre-LN transformer-block
    context), 16 block applications per timed call at the BERT-large
    (8192, 1024) activation shape. Value = speedup (x).

    Post-mortem of the round-4 regression (VERDICT r4 weak #1): the old
    microbench chained 64 BARE LN+residual applications — a shape where
    XLA fuses each LN into the neighboring adds across the whole chain,
    while every standalone Pallas kernel is an HBM fusion barrier; it
    also (until round 4) only differentiated x, so the stock arm never
    computed dgamma/dbeta at all. At that shape the all-Pallas pair
    honestly loses ~10% — but it is not the shape the mode dispatcher
    serves. Measured at THIS shape (v5e, marginal timing, 2026-07-31):
    stock 7.01 ms/call, all-Pallas 7.23, hybrid 5.19 — the round-5
    dispatch (jnp fwd so XLA fuses LN into the GEMM that consumes it;
    Pallas bwd for the one-pass dx + in-kernel dgamma/dbeta) wins
    ~1.35x, which is the honest kernel-tier claim. Gradients flow to
    x, the LN affine params, AND the GEMM weights (the training
    contract; dgamma/dbeta work is paid by both arms)."""
    from apex_tpu.ops.layer_norm import fused_layer_norm_affine
    from apex_tpu.ops.layer_norm import layer_norm_reference as stock_ln

    # Off-TPU this is a flow smoke, not a measurement: the GEMM-sandwich
    # shape is ~1.6 TFLOP per timed call at the real size, far beyond a
    # CI core's budget (the round-4 bare-LN chain was bandwidth-light;
    # this one is deliberately matmul-bound — see docstring)
    on_tpu = _backend_with_cpu_fallback() == "tpu" and not fast
    N, H = (16 * 512, 1024) if on_tpu else (128, 64)
    n_apps = 16 if on_tpu else 2
    ks = jax.random.split(jax.random.PRNGKey(_SALT), 4)
    x0 = jax.random.normal(ks[0], (N, H), jnp.float32)
    w0 = jnp.ones((H,), jnp.float32)
    b0 = jnp.zeros((H,), jnp.float32)
    W1 = jax.random.normal(ks[1], (H, H), jnp.float32) * 0.03
    W2 = jax.random.normal(ks[2], (H, H), jnp.float32) * 0.03

    def mk(fn):
        def block(xb, w, b, W1b, W2b):
            h = jnp.dot(fn(xb, w, b), W1b)
            return jnp.dot(jax.nn.gelu(h), W2b) + xb

        @jax.jit
        def step(x, w, b, W1, W2):
            # W1/W2 are ARGUMENTS inside argnums: as closure constants
            # their cotangent matmuls and saved-activation traffic would
            # be dead-code-eliminated — the same DCE understatement the
            # round-4 post-mortem above describes for dgamma/dbeta
            def loss(x, w, b, W1, W2):
                xb = x.astype(jnp.bfloat16)
                W1b, W2b = W1.astype(jnp.bfloat16), W2.astype(jnp.bfloat16)
                for _ in range(n_apps):
                    xb = block(xb, w, b, W1b, W2b)
                return jnp.sum(xb.astype(jnp.float32) ** 2) / N
            dx, dw, db, dW1, dW2 = jax.grad(
                loss, argnums=(0, 1, 2, 3, 4))(x, w, b, W1, W2)
            # f32 carries with bounded f32-visible updates: a bf16 carry
            # with a tiny step rounds back to the identical input and
            # the runtime memoizer serves the call from cache
            return (0.999 * x - 1e-3 * jnp.tanh(dx),
                    w - 1e-4 * jnp.tanh(dw), b - 1e-4 * jnp.tanh(db),
                    W1 - 1e-4 * jnp.tanh(dW1), W2 - 1e-4 * jnp.tanh(dW2))
        return step

    state = (x0, w0, b0, W1, W2)
    dt_fused, dt_stock = _ab_chain_time(
        mk(fused_layer_norm_affine), mk(stock_ln), state,
        iters=4 if fast else 8, rounds=1 if fast else 3)
    return {
        "metric": "fused_layer_norm_fwdbwd_speedup_vs_xla",
        "value": round(dt_stock / dt_fused, 3),
        "unit": "x",
        "vs_baseline": round(dt_stock / dt_fused, 3),
    }


def bench_fused_lamb(fast=False):
    """BASELINE configs[2]: FusedLAMB (multi_tensor flat-fusion step)
    vs a per-leaf unfused update chain, on a ResNet-50-class param set
    (~25.6M params, 161 leaves; ``fast=True`` shrinks the set for the
    tier-1 smoke). Value = speedup (x)."""
    from apex_tpu.optimizers import FusedLAMB

    rng = np.random.RandomState(_SALT)
    n_conv, n_bn = (5, 10) if fast else (53, 106)
    leaves = {}
    # ResNet-50-ish spectrum: many small conv/bn leaves + a few big ones
    for i in range(n_conv):
        leaves[f"conv{i}"] = jnp.asarray(
            rng.randn(*(3, 3, 128, 256 if i % 3 else 512)).astype("f4") * .01)
    for i in range(n_bn):
        leaves[f"bn{i}"] = jnp.asarray(rng.randn(512).astype("f4"))
    leaves["fc"] = jnp.asarray(
        rng.randn(128 if fast else 2048, 1000).astype("f4") * .01)
    grads = jax.tree.map(lambda p: p * 0.01, leaves)
    n = sum(l.size for l in jax.tree.leaves(leaves))

    opt = FusedLAMB(lr=1e-3)

    # 8 chained optimizer steps per timed call: one step is ~1-2 ms,
    # below the runtime's window-noise floor (same sizing rationale as
    # bench_layer_norm)
    @jax.jit
    def fused_step(params, ost):
        for _ in range(8):
            params, ost = opt.step(grads, ost, params)
        return params, ost

    def eager_one(params, m, v, step):
        # per-leaf unfused chain: the torch-eager per-param analog of
        # the SAME optimizer — including the global-grad-norm clip
        # FusedLAMB performs (max_grad_norm=1.0 default). Round-4 audit:
        # without this the baseline ran strictly less work (no stage-0
        # pass over the gradients) and the "speedup" compared different
        # optimizers (measured 0.84x for that unfair framing).
        step = step + 1
        gn = jnp.sqrt(sum(jnp.sum(grads[k].astype(jnp.float32) ** 2)
                          for k in params))
        clip = jnp.where(gn > 1.0, 1.0 / gn, 1.0)
        new_p, new_m, new_v = {}, {}, {}
        for k in params:
            g = grads[k] * clip
            m_k = 0.9 * m[k] + 0.1 * g
            v_k = 0.999 * v[k] + 0.001 * g * g
            mh = m_k / (1 - 0.9 ** step)
            vh = v_k / (1 - 0.999 ** step)
            upd = mh / (jnp.sqrt(vh) + 1e-6) + 0.01 * params[k]
            tn = jnp.linalg.norm(params[k])
            un = jnp.linalg.norm(upd)
            trust = jnp.where((tn > 0) & (un > 0), tn / un, 1.0)
            new_p[k] = params[k] - 1e-3 * trust * upd
            new_m[k], new_v[k] = m_k, v_k
        return new_p, new_m, new_v, step

    @jax.jit
    def eager_step(params, m, v, step):
        for _ in range(8):  # same 8-step chaining as fused_step
            params, m, v, step = eager_one(params, m, v, step)
        return params, m, v, step

    ost0 = opt.init(leaves)
    iters = 4 if fast else 20
    dt_fused = _chain_time(fused_step, (leaves, ost0), iters=iters)
    zeros = jax.tree.map(jnp.zeros_like, leaves)
    dt_eager = _chain_time(eager_step,
                           (leaves, zeros, zeros, jnp.int32(0)),
                           iters=iters)
    return {
        "metric": "fused_lamb_step_speedup_vs_per_leaf_eager",
        "value": round(dt_eager / dt_fused, 3),
        "unit": "x",
        "vs_baseline": round(dt_eager / dt_fused, 3),
        "n_params": n,
    }


def count_allreduce_bytes(hlo_text):
    """(op_count, total_bytes) of all-reduce collectives in compiled HLO
    text. Round 5: thin wrapper over the general
    :mod:`apex_tpu.utils.hlo_audit` (which also counts all-gather /
    reduce-scatter / all-to-all / collective-permute, so a grad sync
    that silently migrated from all-reduce to a reduce-scatter +
    all-gather pair is caught by the companion ``other_bytes`` field of
    the ddp metric rather than reading as an improvement)."""
    from apex_tpu.utils.hlo_audit import collective_stats

    s = collective_stats(hlo_text)["all-reduce"]
    return s["ops"], s["bytes"]


_DDP_SCALING_CHILD = r"""
import json, sys
import jax, jax.numpy as jnp, numpy as np
from jax.sharding import PartitionSpec as P

dp = int(sys.argv[1])
sync = sys.argv[2] == "sync"  # nosync: same step minus the grad allreduce
sys.path.insert(0, sys.argv[3])
import apex_tpu  # noqa: F401
from apex_tpu.parallel import DistributedDataParallel, SyncBatchNorm
from apex_tpu.utils.collectives import compat_shard_map
import flax.linen as nn

class Net(nn.Module):
    @nn.compact
    def __call__(self, x, train=True):
        for i in range(4):
            x = nn.Conv(32, (3, 3), use_bias=False)(x)
            x = SyncBatchNorm(num_features=32, axis_name="data",
                              channel_last=True)(
                x, use_running_average=not train)
            x = nn.relu(x)
        return jnp.mean(x, axis=(1, 2)) @ jnp.ones((32, 1))

net = Net()
ddp = DistributedDataParallel(axis_name="data")
mesh = jax.make_mesh((dp,), ("data",), devices=jax.devices()[:dp])
rng = np.random.RandomState(0)
xb = jnp.asarray(rng.randn(dp * 8, 16, 16, 3).astype("f4"))
yb = jnp.asarray(rng.randn(dp * 8, 1).astype("f4"))

def init_fn(x):
    return net.init(jax.random.PRNGKey(0), x[:1], train=False)

def train_step(variables, x, y):
    def loss_fn(p):
        out, mut = net.apply(
            {"params": p, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        return jnp.mean((out - y) ** 2), mut
    (loss, mut), g = jax.value_and_grad(loss_fn, has_aux=True)(
        variables["params"])
    if sync:
        g = ddp.allreduce_grads(g)
    p2 = jax.tree.map(lambda p, gg: p - 1e-3 * gg, variables["params"], g)
    return {"params": p2, "batch_stats": mut["batch_stats"]}

variables = jax.jit(compat_shard_map(
    init_fn, mesh=mesh, in_specs=P("data"), out_specs=P()))(xb)
step = jax.jit(compat_shard_map(
    train_step, mesh=mesh, in_specs=(P(), P("data"), P("data")),
    out_specs=P()))
hlo = step.lower(variables, xb, yb).compile().as_text()
grad_bytes = sum(l.size * 4 for l in jax.tree.leaves(variables["params"]))
from apex_tpu.utils.hlo_audit import collective_stats
st = collective_stats(hlo)
other = {k: v for k, v in st.items()
         if k not in ("all-reduce", "total") and v["ops"]}
print(json.dumps({"ops": st["all-reduce"]["ops"],
                  "bytes": st["all-reduce"]["bytes"],
                  "other_ops": sum(v["ops"] for v in other.values()),
                  "other_bytes": sum(v["bytes"] for v in other.values()),
                  "grad_bytes": grad_bytes}))
"""


def bench_ddp_scaling():
    """BASELINE configs[3] (virtual-device proxy for the 8->64->256 pod
    sweep, which needs hardware this harness doesn't have): the
    framework-attributable DDP+SyncBN synchronization traffic at dp=8,
    measured from the compiled HLO — all-reduce bytes per step over the
    ideal one-pass-over-the-gradients bytes. Ideal is slightly above
    1.0 (SyncBN's welford-triple psums ride on top of the grad sync);
    a regression that syncs twice, syncs in a wider dtype, or adds
    per-layer collectives moves the ratio — unlike the round-3
    wall-clock ratio, which sat pinned at its 1.0 clamp because the
    sync cost of this net is below CPU-sim timing noise.

    Audit note (round 4): an explicit-allreduce-removed variant
    compiles to the IDENTICAL program — shard_map AD inserts the
    boundary psum for the replicated params itself, and the vma-aware
    DistributedDataParallel.allreduce_grads recognizes already-invariant
    grads and skips its own sync (the round-2 varying-axes feature
    working as designed). The deliberate-regression demonstration
    (doubled sync moves the metric) lives in
    tests/test_bench_metrics.py."""
    import os
    import subprocess

    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    here = os.path.dirname(os.path.abspath(__file__))

    def run(mode, dp=8):
        out = subprocess.run(
            [sys.executable, "-c", _DDP_SCALING_CHILD, str(dp), mode, here],
            capture_output=True, text=True, timeout=600, env=env)
        if out.returncode != 0:
            raise RuntimeError(out.stderr[-500:])
        return json.loads(out.stdout.strip().splitlines()[-1])

    stats = run("sync")
    ratio = stats["bytes"] / stats["grad_bytes"]
    print(f"# ddp collective audit: {stats['ops']} all-reduces "
          f"({stats['bytes']} B) vs grad bytes {stats['grad_bytes']}; "
          f"other collectives: {stats['other_ops']} op "
          f"({stats['other_bytes']} B)",
          file=sys.stderr)
    return {
        "metric": "ddp_syncbn_allreduce_bytes_over_grad_bytes_8dev",
        "value": round(ratio, 3),
        "unit": "ratio",
        "vs_baseline": round(ratio, 3),
        "allreduce_ops": stats["ops"],
        # grad traffic migrated to reduce-scatter/all-gather/all-to-all
        # would land HERE instead of lowering the headline ratio
        # (advisor r4 #3); expected ~0 for this all-reduce-only step
        "other_collective_bytes": stats["other_bytes"],
    }


def bench_scaled_masked_softmax():
    """FusedScaleMaskSoftmax kernel tier vs stock jnp softmax at the
    BERT-shaped (B, H, S, S) = (16, 16, 512, 512) attention-score
    tensor, fwd+bwd with a padding mask (VERDICT r4 weak #7: the
    softmax tier was justified on speed but had no perf row). This is
    the tier the composed-attention path uses when flash is off — the
    reference justifies ``scaled_masked_softmax_cuda`` purely on this
    comparison (SURVEY §2.2). 4 chained applications/call keep the
    workload above the window-noise floor (each app is a ~268 MB bf16
    tensor fwd+bwd). Interleaved A/B + min-per-arm, like the LN row."""
    from apex_tpu.ops.softmax import scaled_masked_softmax, softmax_reference

    B, H, S = 16, 16, 512
    x0 = jax.random.normal(jax.random.PRNGKey(_SALT), (B, H, S, S),
                           jnp.float32)
    mask = (jax.random.uniform(jax.random.PRNGKey(1), (B, 1, 1, S))
            > 0.9)  # ~10% padded keys

    def mk(fn):
        def many(xb):
            for _ in range(4):
                xb = fn(xb, mask, 0.125) + 0.5 * xb
            return xb

        @jax.jit
        def step(x):
            def loss(x):
                return jnp.sum(many(x.astype(jnp.bfloat16))
                               .astype(jnp.float32) ** 2)
            dx = jax.grad(loss)(x)
            return (0.999 * x - 1e-3 * jnp.tanh(dx),)
        return step

    dt_fused, dt_stock = _ab_chain_time(
        mk(scaled_masked_softmax),
        mk(lambda x, m, s: softmax_reference(x, m, s)), (x0,), iters=6)
    return {
        "metric": "scaled_masked_softmax_fwdbwd_speedup_vs_xla",
        "value": round(dt_stock / dt_fused, 3),
        "unit": "x",
        "vs_baseline": round(dt_stock / dt_fused, 3),
    }


def bench_long_context(seq=4096):
    """Long-context attention on-chip (SURVEY §5 long-context row): GPT-
    medium-class attention (NH=16, D=64) fwd+bwd at S=4096, flash kernel
    vs composed (materialized-score) attention. This records the
    measured basis for the docs' claim that flash "wins outright at
    longer S" — at S=512 the two tie and flash's win is the O(S*D)
    memory; here the (1, 16, S, S) fp32 score tensor alone is ~1 GB and
    the composed path pays it in bandwidth. Dropout 0 in both arms (a
    composed S=4096 dropout mask tensor would not fit; the flash
    dropout path is timed by the headline)."""
    from apex_tpu.ops.flash_attention import flash_attention, mha_reference

    # L=1 at S>=8192: the composed arm materializes an L x 4.3 GB fp32
    # score tensor through fwd+bwd; two layers would not leave room for
    # the backward on the 16 GB chip
    B, NH, D, L = 1, 16, 64, (1 if seq >= 8192 else 2)
    q0 = jax.random.normal(jax.random.PRNGKey(_SALT), (B, NH, seq, D),
                           jnp.float32)

    def mk(attn):
        def loss(qc):
            x = qc.astype(jnp.bfloat16)
            for _ in range(L):
                x = attn(x)
            return jnp.sum(x.astype(jnp.float32) ** 2)

        @jax.jit
        def step(q):
            dq = jax.grad(loss)(q)
            return (0.999 * q - 1e-3 * jnp.tanh(dq),)
        return step

    flash_step = mk(lambda x: flash_attention(x, x, x, None, True, 0.125))
    comp_step = mk(lambda x: mha_reference(x, x, x, None, True, 0.125))
    # Interleaved A/B (round 5): the round-4 driver recorded 1.496x for
    # the same code that measured 2.8x in-session — sequential arms let
    # tunnel drift land entirely on one side. Alternating rounds +
    # min-per-arm brought the spread to +/-15% across sessions.
    dt_flash, dt_comp = _ab_chain_time(flash_step, comp_step, (q0,),
                                       iters=4)
    return {
        "metric": f"long_context_attn_s{seq}_flash_speedup_vs_composed",
        "value": round(dt_comp / dt_flash, 3),
        "unit": "x",
        "vs_baseline": round(dt_comp / dt_flash, 3),
        "flash_ms_per_call": round(dt_flash * 1e3, 2),
    }


def bench_serving(fast=False):
    """Serving section (round 6): the continuous-batching engine
    (apex_tpu.serving) driving GPT decode with the paged KV-cache —
    prefill tokens/s, decode steps/s (one step = one token for every
    active slot), and peak cache-slot utilization. Two phases so the
    numbers don't contaminate each other: a max_new_tokens=1 drain is
    ~pure prefill; a drain with every slot busy is decode-dominated.
    On TPU this runs a GPT-2-small-class config; off-TPU the tiny smoke
    config (flow check, metric named accordingly). ``fast=True`` is the
    tier-1 smoke shape (smallest workload, same code paths)."""
    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.observability import flatten_stats as _flatten_stats
    from apex_tpu.serving import (EngineConfig, InferenceEngine, Request,
                                  SamplingParams)

    on_tpu = _backend_with_cpu_fallback() == "tpu" and not fast
    if on_tpu:
        cfg = GPTConfig.gpt2_small(dropout=0.0, remat=False,
                                   dtype=jnp.bfloat16)
        ecfg = EngineConfig(max_batch=16, block_size=32, num_blocks=512,
                            max_prefill_len=256, max_seq_len=512,
                            kv_dtype=jnp.bfloat16)
        n_req, max_new, prompt_len = 16, 64, 128
    else:
        cfg = GPTConfig.tiny(dropout=0.0, remat=False)
        ecfg = EngineConfig(max_batch=4, block_size=8, num_blocks=64,
                            max_prefill_len=16, max_seq_len=48)
        n_req, max_new, prompt_len = (3, 4, 12) if fast else (6, 8, 12)
    model = GPTLMHeadModel(cfg)
    rng = np.random.RandomState(_SALT)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 8))))
    engine = InferenceEngine(model, params, ecfg)

    def requests(tag, new_tokens):
        return [
            Request(uid=f"{tag}-{i}",
                    prompt=list(rng.randint(0, cfg.vocab_size, prompt_len)),
                    max_new_tokens=new_tokens,
                    sampling=SamplingParams(temperature=1.0, top_k=40))
            for i in range(n_req)
        ]

    # warmup: compile the two programs (prefill + decode)
    for r in requests("warm", 2):
        engine.add_request(r)
    engine.run()

    # phase 1 — prefill throughput (max_new_tokens=1: no decode steps)
    reqs = requests("pre", 1)
    tokens = sum(len(r.prompt) for r in reqs)
    t0 = time.perf_counter()
    for r in reqs:
        engine.add_request(r)
    engine.run()
    prefill_tok_s = tokens / max(time.perf_counter() - t0, 1e-9)

    # phase 2 — decode throughput + peak slot utilization
    s0 = engine.stats()
    util_peak = 0.0
    t0 = time.perf_counter()
    for r in requests("dec", max_new):
        engine.add_request(r)
    while engine.has_work:
        engine.step()
        util_peak = max(util_peak, engine.allocator.utilization)
    dt = time.perf_counter() - t0
    decode_steps = (engine.stats()["num_decode_dispatches"]
                    - s0["num_decode_dispatches"])
    decode_tokens = (engine.stats()["num_tokens_decoded"]
                     - s0["num_tokens_decoded"])
    stats = engine.stats()

    # phase 3 — prefix caching (round 6): decode tokens/s over a fresh
    # prefix-caching engine at 0% prompt overlap (every prompt distinct:
    # pure overhead measurement) vs ~90% overlap (a shared system-prompt
    # head fronting every request — the dominant real-traffic shape,
    # where block sharing skips most prefill work and most prompt-block
    # allocations). Same request count/budgets in both arms.
    import dataclasses as _dc

    # round the shared head DOWN to a block multiple: prefix matching
    # is full-block only, so an unaligned head would cap the achievable
    # hit rate below what the arm's "~90%" label claims
    bs = ecfg.block_size
    shared_len = max(bs * (int(prompt_len * 0.9) // bs), bs)
    shared_head = list(rng.randint(0, cfg.vocab_size, shared_len))

    def _overlap_arm(tag, shared):
        eng = InferenceEngine(model, params,
                              _dc.replace(ecfg, enable_prefix_caching=True))
        for r in requests(f"{tag}-warm", 1):    # compile outside the clock
            eng.add_request(r)
        eng.run()
        s_before = eng.stats()
        tt0 = time.perf_counter()
        for i in range(n_req):
            tail = list(rng.randint(0, cfg.vocab_size,
                                    prompt_len - len(shared)))
            eng.add_request(Request(
                uid=f"{tag}-{i}", prompt=list(shared) + tail,
                max_new_tokens=max_new,
                sampling=SamplingParams(temperature=1.0, top_k=40)))
            eng.step()   # staggered arrivals (continuous traffic), so
            # later requests see the head request's registered blocks
        eng.run()
        tdt = time.perf_counter() - tt0
        s_after = eng.stats()
        toks = n_req * max_new
        d_hits = (s_after["prefix_hit_blocks"]
                  - s_before["prefix_hit_blocks"])
        d_lookups = (s_after["prefix_lookup_blocks"]
                     - s_before["prefix_lookup_blocks"])
        return {
            "decode_tokens_per_sec": round(toks / max(tdt, 1e-9), 3),
            # this arm's hit rate, not the engine-lifetime rate (which
            # the warmup phase's guaranteed misses would dilute)
            "prefix_cache_hit_rate": round(
                d_hits / max(d_lookups, 1), 3),
            "prefill_chunks": int(s_after["num_prefill_chunks"]
                                  - s_before["num_prefill_chunks"]),
            "prompt_blocks_allocated": int(
                s_after["prompt_blocks_allocated"]
                - s_before["prompt_blocks_allocated"]),
        }, s_after

    arm0, _ = _overlap_arm("p0", shared=[])
    arm90, s90 = _overlap_arm("p90", shared=shared_head)

    print(f"# serving: prefill {prefill_tok_s:.1f} tok/s | "
          f"{decode_steps} decode steps in {dt:.3f}s | peak slot "
          f"utilization {util_peak:.3f} | compilations "
          f"{stats['prefill_compilations']}+{stats['decode_compilations']} | "
          f"prefix-cache decode tok/s "
          f"{arm0['decode_tokens_per_sec']:.1f} (0% overlap) -> "
          f"{arm90['decode_tokens_per_sec']:.1f} (~90%, arm hit rate "
          f"{arm90['prefix_cache_hit_rate']:.2f})",
          file=sys.stderr)
    return {
        "metric": ("serving_gpt2s_decode_steps_per_sec" if on_tpu
                   else "serving_tiny_smoke_decode_steps_per_sec"),
        "value": round(decode_steps / max(dt, 1e-9), 3),
        "unit": "steps/sec",
        # no reference arm for serving yet — recorded against itself
        "vs_baseline": 1.0,
        "prefill_tokens_per_sec": round(prefill_tok_s, 1),
        "decode_tokens_per_sec": round(decode_tokens / max(dt, 1e-9), 3),
        "cache_slot_utilization_peak": round(util_peak, 3),
        "jit_programs": int(stats["prefill_compilations"]
                            + stats["decode_compilations"]),
        "prefix_overlap_0pct": arm0,
        "prefix_overlap_90pct": arm90,
        "scheduler_stats": {
            # the sanctioned flattener (docs/observability.md); the
            # nested per-tenant ledger is excluded — it has its own arm;
            # non-numeric entries (the quantization mode strings/None)
            # pass through as-is
            k: (round(v, 4) if isinstance(v, float)
                else int(v) if isinstance(v, (int, bool)) else v)
            for k, v in _flatten_stats(s90, exclude=("tenants",)).items()
        },
    }


def bench_serving_multistep(fast=False):
    """Multi-step fused decode sweep: the same decode-dominated
    workload served at ``decode_steps`` (K) in {1, 4, 8} — K scanned
    decode iterations per dispatch, so one scheduler tick (host table /
    sampling-array work, dispatch, fetch) is amortized over K tokens
    per lane. Reports decode tokens/sec per arm plus the dispatch vs
    token counters that make the amortization observable, and ASSERTS
    the outputs are bit-identical across K (the per-request/per-token
    PRNG keying contract — a throughput knob must never change what
    gets generated). ``vs_baseline`` is the K=max / K=1 tokens/sec
    ratio: the multi-step speedup itself. ``fast=True`` is the tier-1
    smoke shape (smaller sweep + workload, same code path)."""
    import dataclasses as _dc

    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.serving import (EngineConfig, InferenceEngine, Request,
                                  SamplingParams)

    on_tpu = _backend_with_cpu_fallback() == "tpu" and not fast
    if on_tpu:
        cfg = GPTConfig.gpt2_small(dropout=0.0, remat=False,
                                   dtype=jnp.bfloat16)
        ecfg = EngineConfig(max_batch=16, block_size=32, num_blocks=512,
                            max_prefill_len=256, max_seq_len=512,
                            kv_dtype=jnp.bfloat16)
        n_req, max_new, prompt_len = 16, 64, 32
        ks = (1, 4, 8)
    else:
        cfg = GPTConfig.tiny(dropout=0.0, remat=False)
        ecfg = EngineConfig(max_batch=4, block_size=8, num_blocks=64,
                            max_prefill_len=16, max_seq_len=48)
        n_req, max_new, prompt_len = (4, 12, 8) if fast else (8, 24, 8)
        ks = (1, 4) if fast else (1, 4, 8)
    model = GPTLMHeadModel(cfg)
    rng = np.random.RandomState(_SALT + 1)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 8))))
    # mixed greedy / sampled lanes, fixed across arms (the bit-identity
    # check is only meaningful when every arm serves the same stream)
    prompts = [list(rng.randint(0, cfg.vocab_size, prompt_len))
               for _ in range(n_req)]

    def requests(tag):
        return [
            Request(uid=f"{tag}-{i}", prompt=prompts[i],
                    max_new_tokens=max_new,
                    sampling=(SamplingParams() if i % 2 == 0 else
                              SamplingParams(temperature=1.0, top_k=40)))
            for i in range(n_req)
        ]

    sweep, outputs = {}, {}
    for k in ks:
        eng = InferenceEngine(model, params,
                              _dc.replace(ecfg, decode_steps=k))
        for r in requests("warm")[:2]:      # compile outside the clock
            eng.add_request(r)
        eng.run()
        s0 = eng.stats()
        t0 = time.perf_counter()
        for r in requests(f"k{k}"):
            eng.add_request(r)
        out = eng.run()
        tdt = time.perf_counter() - t0
        s1 = eng.stats()
        toks = s1["num_tokens_decoded"] - s0["num_tokens_decoded"]
        sweep[f"k{k}"] = {
            "decode_tokens_per_sec": round(toks / max(tdt, 1e-9), 3),
            "num_decode_dispatches": int(s1["num_decode_dispatches"]
                                         - s0["num_decode_dispatches"]),
            "num_tokens_decoded": int(toks),
            "decode_table_rebuilds": int(s1["decode_table_rebuilds"]
                                         - s0["decode_table_rebuilds"]),
            "decode_compilations": int(s1["decode_compilations"]),
        }
        outputs[k] = {u.split("-", 1)[1]: v for u, v in out.items()}

    identical = all(outputs[k] == outputs[ks[0]] for k in ks)
    ratio = (sweep[f"k{ks[-1]}"]["decode_tokens_per_sec"]
             / max(sweep["k1"]["decode_tokens_per_sec"], 1e-9))
    print("# serving multistep: " + " | ".join(
        f"K={k} {sweep[f'k{k}']['decode_tokens_per_sec']:.1f} tok/s "
        f"({sweep[f'k{k}']['num_decode_dispatches']} dispatches)"
        for k in ks) + f" | K{ks[-1]}/K1 {ratio:.2f}x | "
        f"bit-identical {identical}", file=sys.stderr)
    return {
        "metric": ("serving_gpt2s_multistep_decode_tokens_per_sec"
                   if on_tpu else
                   "serving_tiny_smoke_multistep_decode_tokens_per_sec"),
        "value": sweep[f"k{ks[-1]}"]["decode_tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": round(ratio, 3),     # K=max vs K=1, same workload
        "decode_steps_swept": list(ks),
        "outputs_bit_identical_across_k": bool(identical),
        "sweep": sweep,
    }


def bench_serving_speculative(fast=False):
    """Speculative decoding (round 7): the same decode-dominated
    workload served by the non-speculative K-step scan baseline vs
    draft-and-verify (``spec_tokens``, n-gram prompt-lookup drafter) on
    a REPETITIVE/structured-prompt arm — the traffic speculation
    targets (templated output, code, multi-turn echoes), where the
    drafter's guesses actually get accepted. Reports decode tokens/sec
    per arm, the acceptance rate, and accepted tokens per dispatch
    (tokens-per-target-forward is the whole speculative win), ASSERTS
    greedy output bit-identical between the arms (the certification
    bar: a throughput knob must never change what gets generated) and
    that the drafter accepted a nonzero number of tokens — so a
    regression that silently stops speculating fails the smoke run
    instead of surfacing as a quiet perf loss. ``vs_baseline`` is the
    speculative / non-speculative tokens/sec ratio. ``fast=True`` is
    the tier-1 smoke shape (same code path, smallest workload)."""
    import dataclasses as _dc

    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.serving import EngineConfig, InferenceEngine, Request

    on_tpu = _backend_with_cpu_fallback() == "tpu" and not fast
    if on_tpu:
        cfg = GPTConfig.gpt2_small(dropout=0.0, remat=False,
                                   dtype=jnp.bfloat16)
        ecfg = EngineConfig(max_batch=16, block_size=32, num_blocks=512,
                            max_prefill_len=256, max_seq_len=512,
                            kv_dtype=jnp.bfloat16)
        n_req, max_new, prompt_len, k_base, spec = 16, 96, 64, 8, 12
    elif fast:
        cfg = GPTConfig.tiny(dropout=0.0, remat=False)
        ecfg = EngineConfig(max_batch=4, block_size=8, num_blocks=96,
                            max_prefill_len=16, max_seq_len=96)
        n_req, max_new, prompt_len, k_base, spec = 4, 12, 16, 4, 4
    else:
        # decode-dominated CPU arm at a REAL context length: the
        # speculative win on CPU is gather dominance — the K-step scan
        # gathers the full paged context K times per dispatch, the
        # verify forward once — so the context must be long enough for
        # the gather to be the cost (tok/s is flat vs the scan at
        # context ~16, 1.5-1.7x at 256). spec > K is deliberate: a
        # high-acceptance drafter sustains spans longer than the scan's
        # guaranteed K, the lever the scan itself does not have.
        cfg = GPTConfig.tiny(dropout=0.0, remat=False,
                             max_position_embeddings=512)
        ecfg = EngineConfig(max_batch=4, block_size=16, num_blocks=256,
                            max_prefill_len=256, max_seq_len=448)
        n_req, max_new, prompt_len, k_base, spec = 8, 160, 256, 8, 12
    model = GPTLMHeadModel(cfg)
    rng = np.random.RandomState(_SALT + 2)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 8))))
    # structured prompts: a short random pattern repeated, so the
    # prompt itself seeds the n-gram index; greedy lanes only (greedy
    # repetition attractors are exactly the accept-friendly regime, and
    # greedy is the regime the bit-identity certification covers)
    prompts = []
    for _ in range(n_req):
        pat = list(rng.randint(0, cfg.vocab_size, 4))
        prompts.append((pat * (prompt_len // 4 + 1))[:prompt_len])

    def requests(tag):
        return [Request(uid=f"{tag}-{i}", prompt=prompts[i],
                        max_new_tokens=max_new)
                for i in range(n_req)]

    # interleaved A/B, best-of-reps: each rep times one round of BOTH
    # arms back to back, so machine-load drift lands on both, and the
    # best round per arm is reported — CPU wall clocks are noisy at
    # these sub-second rounds
    reps = 1 if fast else 5
    specs = (("baseline_k", dict(decode_steps=k_base)),
             ("speculative", dict(spec_tokens=spec)))
    engines, arms, outputs = {}, {}, {}
    for name, kw in specs:
        eng = InferenceEngine(model, params, _dc.replace(ecfg, **kw))
        for r in requests("warm")[:2]:      # compile outside the clock
            eng.add_request(r)
        eng.run()
        engines[name] = (eng, eng.stats())
    best = {name: None for name, _ in specs}
    for rep in range(reps):
        for name, _ in specs:
            eng, _ = engines[name]
            t0 = time.perf_counter()
            for r in requests(f"{name}{rep}"):
                eng.add_request(r)
            out = eng.run()
            tdt = time.perf_counter() - t0
            if best[name] is None or tdt < best[name]:
                best[name] = tdt
            outputs[name] = {u.split("-", 1)[1]: v
                             for u, v in out.items()}
    for name, kw in specs:
        eng, s0 = engines[name]
        s1 = eng.stats()
        toks = (s1["num_tokens_decoded"]
                - s0["num_tokens_decoded"]) // reps
        disp = (s1["num_decode_dispatches"]
                - s0["num_decode_dispatches"]) / reps
        arms[name] = {
            "decode_tokens_per_sec": round(
                toks / max(best[name], 1e-9), 3),
            "num_decode_dispatches": round(disp, 1),
            "num_tokens_decoded": int(toks),
            "tokens_per_dispatch": round(toks / max(disp, 1), 3),
            "decode_compilations": int(s1["decode_compilations"]),
        }
        if kw.get("spec_tokens"):
            drafted = (s1["num_draft_tokens"]
                       - s0["num_draft_tokens"]) // reps
            accepted = (s1["num_accepted_tokens"]
                        - s0["num_accepted_tokens"]) // reps
            arms[name].update({
                "num_draft_tokens": int(drafted),
                "num_accepted_tokens": int(accepted),
                "acceptance_rate": round(accepted / max(drafted, 1), 4),
                "accepted_per_dispatch": round(
                    accepted / max(disp, 1), 3),
                "spec_blocks_rolled_back": int(
                    (s1["num_spec_blocks_rolled_back"]
                     - s0["num_spec_blocks_rolled_back"]) // reps),
            })

    identical = outputs["speculative"] == outputs["baseline_k"]
    assert identical, "speculative greedy output diverged from baseline"
    spec_arm = arms["speculative"]
    assert spec_arm["num_accepted_tokens"] > 0, (
        "the n-gram drafter accepted nothing on the structured arm — "
        "speculation is silently off")
    ratio = (spec_arm["decode_tokens_per_sec"]
             / max(arms["baseline_k"]["decode_tokens_per_sec"], 1e-9))
    print(f"# serving speculative: baseline K={k_base} "
          f"{arms['baseline_k']['decode_tokens_per_sec']:.1f} tok/s | "
          f"spec={spec} "
          f"{spec_arm['decode_tokens_per_sec']:.1f} tok/s "
          f"({ratio:.2f}x) | acceptance "
          f"{spec_arm['acceptance_rate']:.2f} | "
          f"{spec_arm['tokens_per_dispatch']:.2f} tok/dispatch | "
          f"bit-identical {identical}", file=sys.stderr)
    return {
        "metric": ("serving_gpt2s_speculative_decode_tokens_per_sec"
                   if on_tpu else
                   "serving_tiny_speculative_decode_tokens_per_sec"),
        "value": spec_arm["decode_tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": round(ratio, 3),     # spec vs K-scan, same stream
        "spec_tokens": spec,
        "baseline_decode_steps": k_base,
        "prompt_len": prompt_len,
        "acceptance_rate": spec_arm["acceptance_rate"],
        "accepted_per_dispatch": spec_arm["accepted_per_dispatch"],
        "outputs_bit_identical": bool(identical),
        "arms": arms,
    }


def _poisson_burst_trace(rng, ticks, base_rate, make_request,
                         burst_start=None, burst_end=None,
                         burst_factor=1):
    """The shared seeded trace builder for the serving stress arms
    (overload, multitenant): per tick, ``Poisson(base_rate)`` arrivals
    — ``burst_factor`` x inside ``[burst_start, burst_end)`` — each
    materialized by ``make_request(tick, k)`` (``k`` = the arrival's
    index within the trace). One generator, one rng, so traces stay
    seeded and COMPARABLE across arms: the same (rng state, rates)
    always yields the same burst."""
    trace, k = [], 0
    for tick in range(ticks):
        burst = (burst_start is not None
                 and burst_start <= tick < burst_end)
        rate = base_rate * (burst_factor if burst else 1)
        for _ in range(int(rng.poisson(rate))):
            trace.append((tick, make_request(tick, k)))
            k += 1
    return trace


def bench_serving_overload(fast=False):
    """Overload / tail-latency arm (round 8): a seeded bursty trace —
    Poisson-ish arrivals with a 4x burst phase in the middle, mixed
    prompt/output lengths, mixed priorities and deadlines — driven
    tick-by-tick through an engine with the full overload-protection
    stack on: bounded queue (``try_add`` sheds at the door), admit-time
    feasibility gate, and degradation-ladder watermarks. Reports
    p50/p99 TTFT (submit -> first host-visible token), p50/p99
    inter-token latency (host-visible gaps; tokens surfacing in the
    same drain batch count as 0), goodput (SLO-attained tokens/s:
    tokens of requests that FINISHED — shed/timed-out requests
    contribute zero) alongside raw generated tokens/s, the shed/timeout
    counts, ladder transitions, and the queue high-water mark — and
    ASSERTS zero engine stalls and a bounded queue, so an overload
    regression fails the bench instead of doubling p99 silently.
    ``vs_baseline`` is goodput / raw throughput (the SLO-attainment
    fraction). ``fast=True`` is the tier-1 smoke shape."""
    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.serving import (EngineConfig, InferenceEngine, Request,
                                  SamplingParams)

    on_tpu = _backend_with_cpu_fallback() == "tpu" and not fast
    if on_tpu:
        cfg = GPTConfig.gpt2_small(dropout=0.0, remat=False,
                                   dtype=jnp.bfloat16)
        ecfg = EngineConfig(max_batch=16, block_size=32, num_blocks=512,
                            max_prefill_len=256, max_seq_len=512,
                            kv_dtype=jnp.bfloat16, max_waiting=64,
                            queue_high_watermark=32,
                            free_block_low_watermark=0.125,
                            degrade_patience=2)
        base_rate, phase_ticks = 1.0, 40
        prompt_lens, max_news = (64, 128, 192), (16, 32, 64)
        deadlines = (None, None, 0.05, 2.0, 6.0)
    else:
        cfg = GPTConfig.tiny(dropout=0.0, remat=False)
        ecfg = EngineConfig(max_batch=4, block_size=8, num_blocks=64,
                            max_prefill_len=16, max_seq_len=48,
                            max_waiting=8, queue_high_watermark=5,
                            free_block_low_watermark=0.125,
                            degrade_patience=2)
        base_rate = 0.3 if fast else 0.4
        phase_ticks = 8 if fast else 24
        prompt_lens, max_news = (6, 10, 14), (3, 5, 8)
        # the 0.02 s class is the feasibility-gate bait: once the EWMAs
        # see real dispatch times it is shed at admission, not timed out
        deadlines = (None, None, 0.02, 1.5, 5.0)
    model = GPTLMHeadModel(cfg)
    rng = np.random.RandomState(_SALT + 3)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 8))))
    engine = InferenceEngine(model, params, ecfg)

    # warmup: compile the two programs outside the clock
    for i in range(2):
        engine.add_request(Request(
            uid=f"warm-{i}",
            prompt=list(rng.randint(0, cfg.vocab_size, prompt_lens[0])),
            max_new_tokens=2))
    engine.run()

    # the trace, built up front (seeded => the same burst every round):
    # arrivals-per-tick ~ Poisson(rate); the middle phase runs at 4x
    def make_request(tick, uid):
        dl = deadlines[int(rng.randint(len(deadlines)))]
        return Request(
            uid=f"o{uid}",
            prompt=list(rng.randint(
                0, cfg.vocab_size,
                int(rng.choice(prompt_lens)))),
            max_new_tokens=int(rng.choice(max_news)),
            priority=int(rng.choice((0, 1, 2), p=(0.3, 0.5, 0.2))),
            deadline_s=dl,
            sampling=(SamplingParams() if uid % 2 == 0 else
                      SamplingParams(temperature=1.0, top_k=40)))

    trace = _poisson_burst_trace(
        rng, ticks=3 * phase_ticks, base_rate=base_rate,
        make_request=make_request, burst_start=phase_ticks,
        burst_end=2 * phase_ticks, burst_factor=4)

    submit_t, first_tok_t, last_obs_t, last_counts = {}, {}, {}, {}
    ttfts, gaps = [], []
    shed_at_door = stalls = 0

    def observe(now):
        # host-visible token counts for every request still owned by
        # the engine (finished-but-undrained, resident, or requeued)
        counts = {u: len(t) for u, t in engine.finished.items()}
        for s in engine.slots:
            if s is not None:
                counts[s.request.uid] = (len(s.generated) if s.started
                                         else len(s.entry.generated))
        for e in engine.waiting:
            counts[e.request.uid] = len(e.generated)
        for u, n in counts.items():
            prev = last_counts.get(u, 0)
            if n <= prev or u not in submit_t:
                continue
            if u not in first_tok_t:
                first_tok_t[u] = now
                ttfts.append(now - submit_t[u])
                if n > 1:   # surfaced in the same drain batch
                    gaps.extend([0.0] * (n - 1))
            else:
                gaps.extend([(now - last_obs_t[u]) / (n - prev)]
                            * (n - prev))
            last_obs_t[u] = now
            last_counts[u] = n

    t0 = time.perf_counter()
    i = tick = 0
    while i < len(trace) or engine.has_work:
        while i < len(trace) and trace[i][0] <= tick:
            req = trace[i][1]
            submit_t[req.uid] = time.perf_counter()
            if not engine.try_add(req):      # bounded queue: shed at
                shed_at_door += 1            # the door, explicitly
                submit_t.pop(req.uid, None)
            i += 1
        had_work = engine.has_work
        progressed = engine.step()
        if had_work and not progressed:
            stalls += 1
        observe(time.perf_counter())
        tick += 1
    wall = time.perf_counter() - t0

    results = engine.run(return_status=True)   # drain terminal maps
    status_counts = {}
    for r in results.values():
        status_counts[r.status] = status_counts.get(r.status, 0) + 1
    raw_tokens = sum(len(r.tokens) for r in results.values())
    good_tokens = sum(len(r.tokens) for r in results.values()
                      if r.status == "finished")
    goodput = good_tokens / max(wall, 1e-9)
    raw_tps = raw_tokens / max(wall, 1e-9)
    stats = engine.stats()

    assert stalls == 0, f"{stalls} no-progress ticks with work remaining"
    # client adds are bounded by max_waiting; preemption/recovery
    # requeues of residents can push past it by at most max_batch
    assert (stats["queue_depth_peak"]
            <= ecfg.max_waiting + ecfg.max_batch), stats
    assert status_counts.get("finished", 0) > 0, status_counts

    # the ONE shared percentile helper (linear interpolation, same
    # rule as StepTimer and the obs histograms — docs/observability.md)
    from apex_tpu.observability import percentile

    def pct(xs, q):
        return percentile(xs, q) if xs else 0.0

    print(f"# serving overload: {len(trace)} offered "
          f"({shed_at_door} shed at door) over {tick} ticks | "
          f"goodput {goodput:.1f} of {raw_tps:.1f} tok/s | TTFT p50 "
          f"{pct(ttfts, 50) * 1e3:.1f}ms p99 {pct(ttfts, 99) * 1e3:.1f}ms"
          f" | ITL p50 {pct(gaps, 50) * 1e3:.2f}ms p99 "
          f"{pct(gaps, 99) * 1e3:.2f}ms | queue peak "
          f"{int(stats['queue_depth_peak'])}/{ecfg.max_waiting} | "
          f"rejected {int(stats['num_rejected_infeasible'])} infeasible"
          f" + {int(stats['num_rejected_queue_full'])} full | ladder "
          f"down {int(stats['num_degrade_steps_down'])} / up "
          f"{int(stats['num_degrade_steps_up'])}", file=sys.stderr)
    return {
        "metric": ("serving_gpt2s_overload_goodput_tokens_per_sec"
                   if on_tpu else
                   "serving_tiny_overload_goodput_tokens_per_sec"),
        "value": round(goodput, 3),
        "unit": "tokens/sec",
        # the SLO-attainment fraction: how much of the raw token
        # stream belonged to requests that actually finished
        "vs_baseline": round(goodput / max(raw_tps, 1e-9), 4),
        "burst_factor": 4,
        "num_requests_offered": len(trace),
        "num_requests_admitted": len(results),
        "num_shed_at_door": shed_at_door,
        "status_counts": status_counts,
        "p50_ttft_s": round(pct(ttfts, 50), 6),
        "p99_ttft_s": round(pct(ttfts, 99), 6),
        "p50_itl_s": round(pct(gaps, 50), 6),
        "p99_itl_s": round(pct(gaps, 99), 6),
        "goodput_tokens_per_sec": round(goodput, 3),
        "decode_tokens_per_sec": round(raw_tps, 3),
        "slo_attainment": round(good_tokens / max(raw_tokens, 1), 4),
        "num_stalls": stalls,
        "max_waiting": int(ecfg.max_waiting),
        "max_batch": int(ecfg.max_batch),
        "queue_depth_peak": int(stats["queue_depth_peak"]),
        "num_rejected_queue_full": int(stats["num_rejected_queue_full"]),
        "num_rejected_infeasible": int(stats["num_rejected_infeasible"]),
        "num_timeouts": int(stats["num_timeouts"]),
        "num_preemptions": int(stats["num_preemptions"]),
        "degrade_steps_down": int(stats["num_degrade_steps_down"]),
        "degrade_steps_up": int(stats["num_degrade_steps_up"]),
        "queue_wait_mean_s": round(float(stats["queue_wait_mean_s"]), 6),
        "queue_wait_max_s": round(float(stats["queue_wait_max_s"]), 6),
    }


def bench_serving_multitenant(fast=False):
    """Multi-tenant isolation arm (round 10): one ADVERSARIAL flood
    tenant against two well-behaved tenants with deadlines, all
    sharing a prefix-cached pool under the tenancy stack — weighted
    DRR admission, per-tenant quotas (waiting cap + resident-block
    ceiling + token-rate budget on the flood), streaming delivery.

    Three phases: (1) the victims run SOLO (their exact seeded traces,
    no flood) to baseline per-tenant p99 TTFT; (2) the same victim
    traces run against the flood — the arm reports per-tenant goodput
    and p99 TTFT and ASSERTS the flood is the only tenant ever shed or
    throttled and the victims' p99 TTFT (in scheduler ticks — the
    deterministic unit) stays within its bound of the solo baseline;
    (3) a chaos engine mixes aborts, quota sheds, injected
    prefill/decode faults, and degradation-ladder steps over the same
    trace shape, then must pass ``check_allocator_integrity`` (the
    per-tenant refcount split certified exactly) with every accepted
    request terminal. ``vs_baseline`` is combined victim goodput /
    solo victim goodput. ``fast=True`` is the tier-1 smoke shape."""
    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.serving import (EngineConfig, InferenceEngine, Request,
                                  SamplingParams, TenantQuota)
    from apex_tpu.utils.faults import FaultPlan, FaultSpec

    on_tpu = _backend_with_cpu_fallback() == "tpu" and not fast
    if on_tpu:
        cfg = GPTConfig.gpt2_small(dropout=0.0, remat=False,
                                   dtype=jnp.bfloat16)
        ekw = dict(max_batch=16, block_size=32, num_blocks=512,
                   max_prefill_len=256, max_seq_len=512,
                   kv_dtype=jnp.bfloat16, max_waiting=64,
                   enable_prefix_caching=True)
        victim_rate, flood_rate, ticks = 0.5, 4.0, 80
        prompt_lens, max_news = (64, 128), (16, 32)
        flood_quota = TenantQuota(max_waiting=8, max_resident_blocks=24,
                                  tokens_per_s=2000.0)
    else:
        cfg = GPTConfig.tiny(dropout=0.0, remat=False)
        ekw = dict(max_batch=4, block_size=8, num_blocks=64,
                   max_prefill_len=16, max_seq_len=48, max_waiting=24,
                   enable_prefix_caching=True)
        victim_rate = 0.25 if fast else 0.35
        flood_rate = 1.5
        ticks = 24 if fast else 48
        prompt_lens, max_news = (6, 10), (3, 5)
        flood_quota = TenantQuota(max_waiting=4, max_resident_blocks=5,
                                  tokens_per_s=5000.0)
    tenancy = dict(
        tenant_weights={"acme": 4, "bolt": 4, "flood": 1},
        tenant_quotas={"flood": flood_quota},
        drr_quantum=16)
    model = GPTLMHeadModel(cfg)
    # FIXED seeds (not _SALT): this arm asserts on shed attribution,
    # tail-latency bounds, and chaos-path coverage — the trace must be
    # the same every round or the asserts flake
    init_rng = np.random.RandomState(1789)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(init_rng.randint(0, cfg.vocab_size, (1, 8))))

    def victim_trace():
        # victims get their OWN rng so the solo and combined runs see
        # byte-identical victim traffic
        rng = np.random.RandomState(1790)

        def make(tick, k):
            tenant = ("acme", "bolt")[k % 2]
            return Request(
                uid=f"{tenant}-{k}",
                prompt=list(rng.randint(0, cfg.vocab_size,
                                        int(rng.choice(prompt_lens)))),
                max_new_tokens=int(rng.choice(max_news)),
                tenant=tenant, deadline_s=30.0,
                sampling=(SamplingParams() if k % 2 == 0 else
                          SamplingParams(temperature=1.0, top_k=40)))

        return _poisson_burst_trace(rng, ticks=ticks,
                                    base_rate=victim_rate,
                                    make_request=make)

    def flood_trace():
        rng = np.random.RandomState(1791)
        shared = list(rng.randint(0, cfg.vocab_size, prompt_lens[-1]))

        def make(tick, k):
            # the adversary: high rate, no deadlines, identical
            # prompts (it also tries to squat on the prefix cache)
            return Request(uid=f"flood-{k}", prompt=list(shared),
                           max_new_tokens=int(max_news[-1]),
                           tenant="flood")

        return _poisson_burst_trace(rng, ticks=ticks,
                                    base_rate=flood_rate,
                                    make_request=make)

    def drive(engine, trace, abort_every=None):
        """Tick the engine through the trace; per-uid submit tick and
        first-token tick (host-visible, via the streaming API), door
        sheds per tenant, optional every-Nth-accepted abort schedule.
        Returns (ttft_ticks per uid, door_sheds per tenant, aborted
        uids, wall seconds, stalls)."""
        submit, first = {}, {}
        sheds, aborted, accepted = {}, [], []
        stalls = 0
        t0 = time.perf_counter()
        i = tick = 0
        while i < len(trace) or engine.has_work:
            while i < len(trace) and trace[i][0] <= tick:
                req = trace[i][1]
                if engine.try_add(req):
                    submit[req.uid] = tick
                    accepted.append(req.uid)
                    if (abort_every
                            and len(accepted) % abort_every == 0):
                        aborted.append(req.uid)
                else:
                    t = req.tenant
                    sheds[t] = sheds.get(t, 0) + 1
                i += 1
            for uid in aborted[:]:
                if engine.abort(uid):
                    aborted.remove(uid)
                    aborted.append("done:" + uid)
            had = engine.has_work
            progressed = engine.step()
            if had and not progressed:
                stalls += 1
            for uid, tok, last in engine.pop_stream_events():
                if tok >= 0 and uid not in first and uid in submit:
                    first[uid] = tick
            tick += 1
        wall = time.perf_counter() - t0
        ttft = {u: first[u] - submit[u] for u in first}
        return ttft, sheds, aborted, wall, stalls

    from apex_tpu.observability import percentile

    def pct(xs, q):
        return percentile(xs, q) if xs else 0.0

    victims = victim_trace()

    # phase 1: victims solo — the baseline each tenant is entitled to
    engine = InferenceEngine(model, params, EngineConfig(**ekw, **tenancy))
    ttft_solo, _, _, wall_solo, stalls0 = drive(engine, victims)
    solo_res = engine.run(return_status=True)
    solo_good = {t: sum(len(r.tokens) for u, r in solo_res.items()
                        if r.status == "finished" and u.startswith(t))
                 for t in ("acme", "bolt")}
    solo_p99 = {t: pct([v for u, v in ttft_solo.items()
                        if u.startswith(t)], 99)
                for t in ("acme", "bolt")}

    # phase 2: the same victim traffic + the flood
    combined = sorted(victims + flood_trace(), key=lambda x: x[0])
    engine = InferenceEngine(model, params, EngineConfig(**ekw, **tenancy))
    ttft_mix, sheds, _, wall_mix, stalls1 = drive(engine, combined)
    mix_res = engine.run(return_status=True)
    stats = engine.stats()
    tstats = stats["tenants"]
    good = {t: sum(len(r.tokens) for u, r in mix_res.items()
                   if r.status == "finished" and u.startswith(t))
            for t in ("acme", "bolt", "flood")}
    mix_p99 = {t: pct([v for u, v in ttft_mix.items()
                       if u.startswith(t)], 99)
               for t in ("acme", "bolt")}
    bad_status = {u: r.status for u, r in mix_res.items()
                  if r.status in ("throttled", "rejected")}

    assert stalls0 == stalls1 == 0, (stalls0, stalls1)
    # isolation bar 1: the flood is the ONLY tenant ever shed at the
    # door or throttled by quota — victims never pay for it
    assert all(t == "flood" for t in sheds), sheds
    assert all(u.startswith("flood") for u in bad_status), bad_status
    assert stats["num_throttled"] > 0 or sheds, (
        "the flood was never shed — the arm is not exercising quotas")
    # isolation bar 2: victim tail latency holds within its bound of
    # the solo baseline (ticks — the deterministic scheduler unit)
    for t in ("acme", "bolt"):
        bound = 3.0 * solo_p99[t] + 12.0
        assert mix_p99[t] <= bound, (
            f"victim {t}: p99 TTFT {mix_p99[t]} ticks vs solo "
            f"{solo_p99[t]} (bound {bound})")
        assert good[t] > 0, good

    # phase 3: chaos — aborts + quota sheds + faults + ladder steps,
    # then the allocator must account for every block exactly
    faults = FaultPlan([
        FaultSpec(site="prefill", kind="transient", every=11),
        FaultSpec(site="decode", kind="transient", every=13),
    ], seed=1792)
    engine = InferenceEngine(
        model, params,
        EngineConfig(**{**ekw, "max_waiting": 8}, **tenancy,
                     # low watermarks: the chaos phase must actually
                     # walk the ladder (the flood quota caps its queue
                     # share at 4, so 4 is the reachable pressure mark)
                     queue_high_watermark=4,
                     free_block_low_watermark=0.25,
                     degrade_patience=1, max_dispatch_retries=3),
        faults=faults)
    _, chaos_sheds, chaos_aborts, _, chaos_stalls = drive(
        engine, combined, abort_every=5)
    chaos_res = engine.run(return_status=True)
    engine.check_allocator_integrity()
    cstats = engine.stats()
    assert chaos_stalls == 0
    assert cstats["num_cancelled"] > 0, "chaos fired no aborts"
    assert cstats["num_dispatch_retries"] > 0, "chaos fired no faults"
    assert (cstats["num_throttled"] > 0 or chaos_sheds), \
        "chaos fired no quota sheds"
    assert cstats["num_degrade_steps_down"] > 0, \
        "chaos never stepped the ladder"

    victim_good = (good["acme"] + good["bolt"]) / max(wall_mix, 1e-9)
    solo_victim_good = ((solo_good["acme"] + solo_good["bolt"])
                        / max(wall_solo, 1e-9))
    print(f"# serving multitenant: victims solo p99 TTFT "
          f"{solo_p99['acme']:.0f}/{solo_p99['bolt']:.0f} ticks -> "
          f"vs flood {mix_p99['acme']:.0f}/{mix_p99['bolt']:.0f} | "
          f"victim goodput {victim_good:.1f} (solo "
          f"{solo_victim_good:.1f}) tok/s | flood finished "
          f"{good['flood']} tok, shed {sheds.get('flood', 0)} door + "
          f"{int(stats['num_throttled'])} throttled | chaos: "
          f"{int(cstats['num_cancelled'])} aborts, "
          f"{int(cstats['num_dispatch_retries'])} retries, ladder down "
          f"{int(cstats['num_degrade_steps_down'])}, integrity OK",
          file=sys.stderr)
    return {
        "metric": ("serving_gpt2s_multitenant_victim_goodput_tok_per_sec"
                   if on_tpu else
                   "serving_tiny_multitenant_victim_goodput_tok_per_sec"),
        "value": round(victim_good, 3),
        "unit": "tokens/sec",
        # isolation quality: combined-run victim goodput vs their solo
        # entitlement (1.0 = the flood cost the victims nothing)
        "vs_baseline": round(victim_good / max(solo_victim_good, 1e-9),
                             4),
        "per_tenant": {
            t: {"goodput_tokens": good[t],
                "p99_ttft_ticks": mix_p99.get(t),
                "solo_p99_ttft_ticks": solo_p99.get(t),
                "door_sheds": sheds.get(t, 0),
                "throttled": int(tstats.get(t, {}).get(
                    "statuses", {}).get("throttled", 0))}
            for t in ("acme", "bolt", "flood")},
        "num_offered": len(combined),
        "flood_only_shed": True,
        "chaos_aborts": int(cstats["num_cancelled"]),
        "chaos_retries": int(cstats["num_dispatch_retries"]),
        "chaos_ladder_steps_down": int(cstats["num_degrade_steps_down"]),
        "chaos_throttled": int(cstats["num_throttled"]),
        "allocator_integrity_ok": True,
    }


def bench_serving_kv_memory(fast=False):
    """Memory scale-up arm (round 11, docs/serving.md memory tiers):
    the capacity story of quantized KV blocks and the host-RAM spill
    tier, measured where it matters — concurrent residents under a
    FIXED device byte budget, and recompute avoided on a re-serve.

    Phase 1 (capacity): the same seeded bursty trace served by two
    engines whose pools hold the SAME number of KV bytes — one storing
    full-precision (fp32) blocks, one int8-with-scales blocks (so the
    int8 pool holds ~2.7x the block count). Reports peak concurrent
    residents and decode tokens/s per arm and ASSERTS the int8 pool
    sustains >= 1.5x the fp peak (the acceptance bar: quantization
    must buy real concurrency, not just smaller numbers). Both arms
    replay identical prompts/arrivals, and ``vs_baseline`` is the
    residents ratio.

    Phase 2 (spill): an int8 + prefix-caching engine with the host
    spill tier serves distinct prompts, takes a full rung-2-style
    flush (every evictable block spilled to host RAM), then RE-SERVES
    the same prompts — prefix hits now re-admit by device upload
    instead of recompute. Reports the spill hit rate (asserted
    nonzero) and asserts the re-serve outputs are token-identical to
    the first pass (greedy + deterministic engine: the upload path
    must not perturb a single token). ``fast=True`` is the tier-1
    smoke shape."""
    import dataclasses as _dc

    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.serving import (EngineConfig, InferenceEngine,
                                  Request, kv_block_bytes)

    # FIXED seeds, not _SALT: this arm asserts (like the multitenant
    # arm), so the workload must be the workload the asserts were
    # designed against
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (1, 8))))
    bs, hd = 8, cfg.hidden_size // cfg.num_heads
    fp_block = kv_block_bytes(cfg.num_layers, bs, cfg.num_heads, hd,
                              dtype=jnp.float32)
    q_block = kv_block_bytes(cfg.num_layers, bs, cfg.num_heads, hd,
                             quantization="int8")
    fp_blocks = 10
    budget = fp_blocks * fp_block
    int8_blocks = budget // q_block
    plen, new = 16, 16          # 32 tokens = 4 blocks per resident
    ticks = 6 if fast else 12
    base_rate = 1.5 if fast else 2.0

    def capacity_arm(quant, num_blocks):
        ecfg = EngineConfig(max_batch=8, block_size=bs,
                            num_blocks=int(num_blocks),
                            max_prefill_len=16, max_seq_len=32,
                            decode_steps=4, kv_dtype=jnp.float32,
                            kv_quantization=quant)
        eng = InferenceEngine(model, params, ecfg)
        eng.add_request(Request(uid="warm", prompt=[1] * plen,
                                max_new_tokens=2))
        eng.run()               # compile outside the clock
        rr = np.random.RandomState(1)

        def make(tick, k):
            return Request(
                uid=f"m{k}",
                prompt=list(rr.randint(0, cfg.vocab_size, plen)),
                max_new_tokens=new)

        trace = _poisson_burst_trace(
            np.random.RandomState(2), ticks=ticks,
            base_rate=base_rate, make_request=make,
            burst_start=ticks // 3, burst_end=2 * ticks // 3,
            burst_factor=2)
        s0 = eng.stats()
        peak = 0
        t0 = time.perf_counter()
        ti = 0
        for tick in range(ticks):
            while ti < len(trace) and trace[ti][0] <= tick:
                eng.add_request(trace[ti][1])
                ti += 1
            eng.step()
            peak = max(peak, int(eng.stats()["active_slots"]))
        while eng.has_work:
            eng.step()
            peak = max(peak, int(eng.stats()["active_slots"]))
        dt = time.perf_counter() - t0
        s1 = eng.stats()
        toks = s1["num_tokens_decoded"] - s0["num_tokens_decoded"]
        return {
            "num_blocks": int(num_blocks),
            "block_bytes": int(fp_block if quant is None else q_block),
            "peak_residents": peak,
            "decode_tokens_per_sec": round(toks / max(dt, 1e-9), 3),
            "decode_tokens": int(toks),
            "preemptions": int(s1["num_preemptions"]),
            "wall_s": round(dt, 4),
        }, len(trace)

    fp_arm, offered = capacity_arm(None, fp_blocks)
    int8_arm, _ = capacity_arm("int8", int8_blocks)
    ratio = int8_arm["peak_residents"] / max(fp_arm["peak_residents"], 1)
    assert ratio >= 1.5, (
        f"int8 storage must sustain >= 1.5x the fp concurrent "
        f"residents under an equal byte budget "
        f"(got {int8_arm['peak_residents']} vs "
        f"{fp_arm['peak_residents']})")
    # both arms served the identical trace; token counts must agree
    # (no EOS in play — a divergence means an arm silently dropped
    # work, which would invalidate the tokens/s comparison)
    assert int8_arm["decode_tokens"] == fp_arm["decode_tokens"], (
        fp_arm, int8_arm)

    # phase 2: spill tier hit rate on a re-serve pass
    scfg = EngineConfig(max_batch=2, block_size=bs, num_blocks=8,
                        max_prefill_len=16, max_seq_len=32,
                        kv_dtype=jnp.float32, kv_quantization="int8",
                        enable_prefix_caching=True,
                        spill_max_bytes=64 * q_block)
    eng = InferenceEngine(model, params, scfg)
    rr = np.random.RandomState(3)
    prompts = [list(rr.randint(0, cfg.vocab_size, plen))
               for _ in range(3 if fast else 6)]

    def serve(tag):
        for i, p in enumerate(prompts):
            eng.add_request(Request(uid=f"{tag}{i}", prompt=p,
                                    max_new_tokens=4))
        return eng.run()

    first = serve("a")
    eng.allocator.flush_evictable()   # the rung-2 flush: all -> spill
    second = serve("b")
    sstats = eng.stats()
    eng.check_allocator_integrity()
    reserve_identical = all(
        second[f"b{i}"] == first[f"a{i}"]
        for i in range(len(prompts)))
    assert sstats["spill_hits"] > 0 and sstats["spill_hit_rate"] > 0, \
        sstats
    assert reserve_identical, "spill re-admit perturbed tokens"

    print(f"# kv-memory: budget {budget} B -> fp {fp_blocks} blocks "
          f"(peak {fp_arm['peak_residents']} residents, "
          f"{fp_arm['decode_tokens_per_sec']:.1f} tok/s) vs int8 "
          f"{int8_blocks} blocks (peak {int8_arm['peak_residents']}, "
          f"{int8_arm['decode_tokens_per_sec']:.1f} tok/s) = "
          f"{ratio:.2f}x residents | spill hit rate "
          f"{sstats['spill_hit_rate']:.2f} "
          f"({sstats['spill_hits']} uploads)", file=sys.stderr)
    return {
        "metric": "serving_tiny_kv_memory_int8_decode_tokens_per_sec",
        "value": int8_arm["decode_tokens_per_sec"],
        "unit": "tokens/sec",
        # the capacity headline: concurrent residents at int8 vs fp
        # under the same byte budget
        "vs_baseline": round(ratio, 3),
        "residents_ratio": round(ratio, 3),
        "byte_budget": int(budget),
        "num_offered": int(offered),
        "fp": fp_arm,
        "int8": int8_arm,
        "spill": {
            "hits": int(sstats["spill_hits"]),
            "misses": int(sstats["spill_misses"]),
            "hit_rate": round(float(sstats["spill_hit_rate"]), 4),
            "blocks_spilled": int(sstats["num_blocks_spilled"]),
            "bytes": int(sstats["spill_bytes"]),
            "reserve_token_identical": bool(reserve_identical),
        },
    }


def bench_weight_quant(fast=False):
    """Weight-quantization arm (round 19, docs/serving.md memory
    tiers): the capacity + speed story of int8 weight storage with the
    dequant-GEMM read path, measured the PR 11 way — equal-byte-budget
    arms.

    Phase 1 (capacity): the model's device param bytes at fp32 vs
    int8-with-scales storage (``gpt_param_bytes`` over the exact trees
    the engine serves). Under a FIXED HBM budget the quantized
    representation serves ``fp_bytes / q_bytes`` x the model bytes per
    chip — equivalently that many more concurrent model residents
    (multi-model serving) or a model that many times bigger. ASSERTS
    the ratio >= 1.8x (the acceptance bar: int8+scale overhead must
    not eat the 4x dtype win down to marginal).

    Phase 2 (speed + certification): the same seeded greedy trace
    served by an fp engine and a weight_quantization="int8" engine at
    equal model/config. Reports decode tokens/s per arm and ASSERTS
    the outputs are token-identical — the greedy-decode certification
    of the quantized logits, riding the bench so a numerics regression
    fails the smoke run, not just tier-1. ``fast=True`` is the tier-1
    smoke shape."""
    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.models.gpt import gpt_param_bytes, quantize_gpt_model
    from apex_tpu.serving import EngineConfig, InferenceEngine, Request

    # FIXED seeds, not _SALT: this arm asserts (token identity), so
    # the workload must be the workload the asserts were designed
    # against
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (1, 8))))

    # phase 1: model bytes per chip at an equal HBM budget
    fp_bytes = gpt_param_bytes(params)
    _, qparams = quantize_gpt_model(model, params, "int8")
    q_bytes = gpt_param_bytes(qparams)
    bytes_ratio = fp_bytes / q_bytes
    budget = 4 * fp_bytes           # a budget that fits 4 fp residents
    fp_residents = budget // fp_bytes
    q_residents = budget // q_bytes
    assert bytes_ratio >= 1.8, (
        f"int8 weight storage must serve >= 1.8x the model bytes per "
        f"chip at an equal HBM budget (got {fp_bytes} fp -> {q_bytes} "
        f"quantized = {bytes_ratio:.2f}x)")

    # phase 2: decode tok/s fp vs int8 at equal model, token-identity
    # asserted (greedy + deterministic engine)
    rr = np.random.RandomState(1)
    n_req, plen, new = (3, 12, 8) if fast else (6, 16, 16)
    prompts = [list(rr.randint(0, cfg.vocab_size, plen))
               for _ in range(n_req)]

    def speed_arm(mode):
        ecfg = EngineConfig(max_batch=4, block_size=8,
                            num_blocks=32, max_prefill_len=16,
                            max_seq_len=48, decode_steps=4,
                            weight_quantization=mode)
        eng = InferenceEngine(model, params, ecfg)
        eng.add_request(Request(uid="warm", prompt=[1] * plen,
                                max_new_tokens=2))
        eng.run()               # compile outside the clock
        for i, p in enumerate(prompts):
            eng.add_request(Request(uid=f"r{i}", prompt=p,
                                    max_new_tokens=new))
        s0 = eng.stats()
        t0 = time.perf_counter()
        outs = eng.run()
        dt = time.perf_counter() - t0
        toks = (eng.stats()["num_tokens_decoded"]
                - s0["num_tokens_decoded"])
        return outs, {
            "decode_tokens_per_sec": round(toks / max(dt, 1e-9), 3),
            "decode_tokens": int(toks),
            "wall_s": round(dt, 4),
        }

    fp_outs, fp_arm = speed_arm(None)
    q_outs, q_arm = speed_arm("int8")
    assert q_outs == fp_outs, (
        "int8 weight storage must decode token-identical to fp on the "
        "greedy certification trace")

    print(f"# weight-quant: {fp_bytes} fp param bytes -> {q_bytes} "
          f"int8 = {bytes_ratio:.2f}x model bytes/chip "
          f"({q_residents} vs {fp_residents} residents at a "
          f"{budget} B budget) | decode "
          f"{fp_arm['decode_tokens_per_sec']:.1f} tok/s fp vs "
          f"{q_arm['decode_tokens_per_sec']:.1f} tok/s int8, "
          f"token-identical", file=sys.stderr)
    return {
        "metric": "serving_tiny_weight_quant_int8_decode_tokens_per_sec",
        "value": q_arm["decode_tokens_per_sec"],
        "unit": "tokens/sec",
        # the capacity headline: model bytes served per chip at an
        # equal HBM budget, int8 vs fp
        "vs_baseline": round(bytes_ratio, 3),
        "bytes_ratio": round(bytes_ratio, 3),
        "fp_param_bytes": int(fp_bytes),
        "int8_param_bytes": int(q_bytes),
        "byte_budget": int(budget),
        "fp_residents": int(fp_residents),
        "int8_residents": int(q_residents),
        "greedy_token_identical": bool(q_outs == fp_outs),
        "fp": fp_arm,
        "int8": q_arm,
    }


def bench_serving_fleet(fast=False):
    """Fleet chaos arm (round 12, docs/fleet.md): the crash-tolerance
    story of the multi-replica router, certified where it matters —
    a replica KILLED mid-burst under seeded faults.

    Three phases: (0) identity — a 1-replica fleet must be
    BIT-IDENTICAL to the bare engine (outputs, terminal statuses, and
    the engine's full ``stats()`` dict, schedule counters included);
    (1) a 3-replica fleet serves a seeded Poisson-burst trace with
    shared-prefix groups (the affinity bait) kill-free, for the
    baseline p99 TTFT and goodput; (2) the SAME trace runs with
    seeded transient faults on every replica, a ``drain_replica``
    migration mid-run, and one replica hard-killed mid-burst
    (``kill_replica`` — recovery from the last periodic checkpoint
    alone) — the arm asserts ZERO lost accepted requests (every
    accepted uid terminal exactly once, ``num_lost_requests == 0``),
    at least one failover and one migration actually fired, and the
    kill-run victims' p99 TTFT (scheduler ticks, the deterministic
    unit) holds within its bound of the no-kill baseline.
    ``vs_baseline`` is kill-run goodput / no-kill goodput.
    ``fast=True`` is the tier-1 smoke shape."""
    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.observability import percentile
    from apex_tpu.serving import (EngineConfig, FleetConfig, FleetRouter,
                                  InferenceEngine, Request,
                                  SamplingParams)
    from apex_tpu.utils.faults import FaultPlan, FaultSpec

    on_tpu = _backend_with_cpu_fallback() == "tpu" and not fast
    if on_tpu:
        cfg = GPTConfig.gpt2_small(dropout=0.0, remat=False,
                                   dtype=jnp.bfloat16)
        ekw = dict(max_batch=8, block_size=32, num_blocks=256,
                   max_prefill_len=128, max_seq_len=384,
                   kv_dtype=jnp.bfloat16, enable_prefix_caching=True,
                   snapshot_interval_ticks=2, max_waiting=64, seed=11)
        ticks, rate = 60, 0.8
        prompt_lens, max_news = (48, 96), (12, 24)
        kill_tick, drain_tick = 24, 36
    else:
        cfg = GPTConfig.tiny(dropout=0.0, remat=False)
        ekw = dict(max_batch=4, block_size=8, num_blocks=64,
                   max_prefill_len=16, max_seq_len=48,
                   enable_prefix_caching=True,
                   snapshot_interval_ticks=2, max_waiting=32, seed=11)
        ticks = 16 if fast else 28
        rate = 0.5 if fast else 0.7
        prompt_lens, max_news = (8, 14), (4, 6)
        kill_tick = 6 if fast else 10
        drain_tick = 10 if fast else 16
    model = GPTLMHeadModel(cfg)
    # FIXED seeds (not _SALT): the arm asserts on zero-lost, failover
    # coverage, and a tail-latency bound — the trace must be the same
    # every round or the asserts flake
    init_rng = np.random.RandomState(1812)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(init_rng.randint(0, cfg.vocab_size, (1, 8))))

    # shared-prefix groups: requests within a group open with the same
    # block-aligned head, so affinity routing has something to win
    prefix_rng = np.random.RandomState(1813)
    prefixes = [list(prefix_rng.randint(0, cfg.vocab_size,
                                        prompt_lens[0]))
                for _ in range(3)]

    def make_trace():
        rng = np.random.RandomState(1814)

        def make(tick, k):
            head = prefixes[k % len(prefixes)]
            tail_len = int(rng.choice(prompt_lens)) - len(head) // 2
            prompt = head + list(rng.randint(0, cfg.vocab_size,
                                             max(1, tail_len)))
            prompt = prompt[:prompt_lens[-1]]
            samp = (SamplingParams() if k % 2 else
                    SamplingParams(temperature=1.0, top_k=40))
            new = int(rng.choice(max_news))
            # a FACTORY per arrival: each drive builds fresh Request
            # objects (engines write the terminal status onto them)
            return lambda: Request(uid=f"q{k}", prompt=list(prompt),
                                   max_new_tokens=new, sampling=samp)

        return _poisson_burst_trace(
            rng, ticks=ticks, base_rate=rate, make_request=make,
            burst_start=ticks // 3, burst_end=2 * ticks // 3,
            burst_factor=3)

    def drive(router, trace, kill_at=None, kill_idx=None,
              drain_at=None, drain_idx=None):
        """Tick the fleet through the trace; per-uid submit/first-token
        ticks via the stream feed, the kill/drain chaos moves at their
        scheduled ticks (victims = the killed replica's owners at the
        kill). Returns (ttft_ticks, accepted, victims, wall_s)."""
        submit, first = {}, {}
        accepted, victims = [], None
        t0 = time.perf_counter()
        i = tick = 0
        while i < len(trace) or router.has_work:
            while i < len(trace) and trace[i][0] <= tick:
                req = trace[i][1]()
                if router.try_add(req):
                    submit[req.uid] = tick
                    accepted.append(req.uid)
                i += 1
            if (kill_at is not None and tick == kill_at
                    and router.replicas[kill_idx].alive):
                victims = [u for u, o in router.owners().items()
                           if o == kill_idx]
                router.kill_replica(kill_idx)
            if (drain_at is not None and tick == drain_at
                    and router.replicas[drain_idx].alive):
                router.drain_replica(drain_idx)
            router.step()
            for uid, tok, last in router.pop_stream_events():
                if tok >= 0 and uid not in first and uid in submit:
                    first[uid] = tick
            tick += 1
        wall = time.perf_counter() - t0
        ttft = {u: first[u] - submit[u] for u in first}
        return ttft, accepted, victims, wall

    def pct(xs, q):
        return percentile(xs, q) if xs else 0.0

    # -- phase 0: the 1-replica identity cert (constant clock: every
    # time-derived stat equal by construction, so the FULL stats dict
    # compares) --
    ident = make_trace()[:8]
    bare = InferenceEngine(model, params, EngineConfig(**ekw),
                           clock=lambda: 0.0)
    for _, mk in ident:
        bare.add_request(mk())
    bare_res = bare.run(return_status=True)
    bare_stats = bare.stats()
    fleet1 = FleetRouter(model, params, EngineConfig(**ekw),
                         FleetConfig(num_replicas=1),
                         clock=lambda: 0.0)
    for _, mk in ident:
        fleet1.add_request(mk())
    one_res = fleet1.run(return_status=True)
    identity_ok = (
        {u: (r.tokens, r.status) for u, r in bare_res.items()}
        == {u: (r.tokens, r.status) for u, r in one_res.items()}
        and fleet1.replicas[0].engine.stats() == bare_stats)
    assert identity_ok, "1-replica fleet diverged from the bare engine"

    # -- phase 1: 3 replicas, no kill — the baseline --
    trace = make_trace()
    router = FleetRouter(model, params, EngineConfig(**ekw),
                         FleetConfig(num_replicas=3))
    ttft_base, accepted_base, _, wall_base = drive(router, trace)
    base_res = router.run(return_status=True)
    base_stats = router.stats()
    assert set(base_res) >= set(accepted_base), "baseline lost requests"
    assert base_stats["num_lost_requests"] == 0
    base_good = sum(len(r.tokens) for r in base_res.values()
                    if r.status == "finished") / max(wall_base, 1e-9)
    p99_base = pct(list(ttft_base.values()), 99)

    # -- phase 2: same trace + seeded transient faults on every
    # replica + a drain-and-migrate + one replica hard-killed
    # mid-burst --
    faults = [FaultPlan([FaultSpec(site="prefill", kind="transient",
                                   every=9)], seed=1815),
              FaultPlan([FaultSpec(site="decode", kind="transient",
                                   every=11)], seed=1816),
              FaultPlan([FaultSpec(site="decode", kind="transient",
                                   every=13)], seed=1817)]
    router = FleetRouter(model, params,
                         EngineConfig(**ekw, max_dispatch_retries=3),
                         FleetConfig(num_replicas=3),
                         faults=faults)
    ttft_kill, accepted, victims, wall_kill = drive(
        router, trace, kill_at=kill_tick, kill_idx=1,
        drain_at=drain_tick, drain_idx=2)
    kill_res = router.run(return_status=True)
    stats = router.stats()
    # the headline asserts: zero lost accepted requests, exactly one
    # terminal per accepted uid, the chaos actually fired
    missing = set(accepted) - set(kill_res)
    assert not missing, f"lost accepted requests: {sorted(missing)}"
    assert stats["num_lost_requests"] == 0, stats["num_lost_requests"]
    assert len(set(accepted)) == len(accepted)
    assert stats["num_failovers"] >= 1, "the kill never fired"
    assert stats["num_migrations"] >= 1, "the drain never migrated"
    for rep in router.replicas:
        if rep.alive and rep.engine is not None:
            rep.engine.check_allocator_integrity()
    n_finished = sum(r.status == "finished" for r in kill_res.values())
    assert n_finished > 0
    # victim tail latency: bounded vs the no-kill baseline (ticks —
    # the deterministic unit; victims pay the failover re-prefill)
    victims = victims or []
    victim_ttft = [ttft_kill[u] for u in victims if u in ttft_kill]
    p99_victim = pct(victim_ttft, 99)
    victim_bound = 4.0 * p99_base + 16.0
    assert p99_victim <= victim_bound, (
        f"victim p99 TTFT {p99_victim} ticks vs baseline {p99_base} "
        f"(bound {victim_bound})")
    kill_good = sum(len(r.tokens) for r in kill_res.values()
                    if r.status == "finished") / max(wall_kill, 1e-9)

    print(f"# serving fleet: identity OK | baseline p99 TTFT "
          f"{p99_base:.0f} ticks, goodput {base_good:.1f} tok/s | "
          f"kill@{kill_tick} (victims {len(victims)}) p99 "
          f"{p99_victim:.0f} ticks (bound {victim_bound:.0f}), "
          f"goodput {kill_good:.1f} tok/s | failovers "
          f"{stats['num_failovers']}, migrations "
          f"{stats['num_migrated_requests']} req, reinjected "
          f"{stats['num_reinjected_requests']}, duplicates dropped "
          f"{stats['num_duplicate_results']}, lost "
          f"{stats['num_lost_requests']}", file=sys.stderr)
    return {
        "metric": ("serving_gpt2s_fleet_kill_goodput_tok_per_sec"
                   if on_tpu else
                   "serving_tiny_fleet_kill_goodput_tok_per_sec"),
        "value": round(kill_good, 3),
        "unit": "tokens/sec",
        # crash-tolerance quality: goodput under a replica kill vs the
        # kill-free fleet (1.0 = the kill cost nothing)
        "vs_baseline": round(kill_good / max(base_good, 1e-9), 4),
        "identity_ok": True,
        "zero_lost": True,
        "num_offered": len(trace),
        "num_accepted": len(accepted),
        "num_victims": len(victims),
        "victim_p99_ttft_ticks": round(float(p99_victim), 2),
        "victim_p99_bound_ticks": round(float(victim_bound), 2),
        "baseline_p99_ttft_ticks": round(float(p99_base), 2),
        "num_failovers": int(stats["num_failovers"]),
        "num_migrations": int(stats["num_migrations"]),
        "num_migrated_requests": int(stats["num_migrated_requests"]),
        "num_reinjected_requests":
            int(stats["num_reinjected_requests"]),
        "num_duplicate_results": int(stats["num_duplicate_results"]),
        "num_lost_requests": int(stats["num_lost_requests"]),
        "num_affinity_hits": int(stats["num_affinity_hits"]),
        "status_counts": {
            s: sum(r.status == s for r in kill_res.values())
            for s in {r.status for r in kill_res.values()}},
        "allocator_integrity_ok": True,
    }


def bench_serving_integrity(fast=False):
    """Data-integrity chaos arm (round 13, docs/robustness.md "Data
    integrity"): the end-to-end corruption story, certified where it
    matters — seeded "corrupt" faults at EVERY checksum point, and a
    silently-wrong-compute replica caught by the fleet's determinism
    cross-check.

    Three phases: (0) identity — integrity machinery fully disabled
    (``verify_artifacts=False``, no scrub, no cross-check) must be
    BIT-IDENTICAL to checksums-on, bare engine AND 1-replica fleet
    (outputs, statuses, the full stats dict): verification is pure
    detection, and enabling checksums alone changes no served token.
    (1) artifact chaos — an engine whose spill tier rots under a
    seeded plan must serve the identical tokens by recompute, and a
    2-replica fleet under corrupt plans covering
    spill_put/spill_get/checkpoint/export/import, with a migration and
    a hard kill mid-run, must finish with ZERO lost accepted requests,
    every accepted uid terminal exactly once, and every fired
    corruption caught (refused imports / corrupt checkpoints / spill
    discards all counted). (2) SDC — a 3-replica fleet with a
    ``"corrupt"`` decode fault on replica 0 and the cross-check on
    must detect the diverging replica, retire it, and lose nothing;
    DETECTION LATENCY (router ticks from the first corrupt token to
    the suspect verdict) is the reported metric. ``vs_baseline`` is
    SDC-phase goodput over the clean phase-0 fleet goodput (the price
    of serving through a corrupting replica + its retirement).
    ``fast=True`` is the tier-1 smoke shape."""
    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.serving import (EngineConfig, FleetConfig, FleetRouter,
                                  InferenceEngine, Request,
                                  SamplingParams)
    from apex_tpu.utils.faults import FaultPlan, FaultSpec

    on_tpu = _backend_with_cpu_fallback() == "tpu" and not fast
    if on_tpu:
        cfg = GPTConfig.gpt2_small(dropout=0.0, remat=False,
                                   dtype=jnp.bfloat16)
        ekw = dict(max_batch=8, block_size=32, num_blocks=96,
                   max_prefill_len=128, max_seq_len=384,
                   kv_dtype=jnp.bfloat16, enable_prefix_caching=True,
                   spill_max_bytes=64 << 20,
                   snapshot_interval_ticks=2, seed=13)
        n_req, new_tokens = 24, 16
    else:
        cfg = GPTConfig.tiny(dropout=0.0, remat=False)
        ekw = dict(max_batch=2, block_size=4, num_blocks=10,
                   max_prefill_len=8, max_seq_len=32,
                   enable_prefix_caching=True, spill_max_bytes=1 << 20,
                   snapshot_interval_ticks=2, seed=13)
        n_req, new_tokens = (8 if fast else 12), 4
    model = GPTLMHeadModel(cfg)
    init_rng = np.random.RandomState(1905)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(init_rng.randint(0, cfg.vocab_size, (1, 8))))
    # FIXED seeds: every phase asserts — the trace must not drift
    rng = np.random.RandomState(1906)
    prompts = [list(rng.randint(1, cfg.vocab_size, 8))
               for _ in range(6)]

    def requests(prefix):
        out = []
        for k in range(n_req):
            samp = (SamplingParams(temperature=1.0, top_k=20)
                    if k % 2 else SamplingParams())
            out.append(Request(f"{prefix}{k}",
                               list(prompts[k % len(prompts)]),
                               max_new_tokens=new_tokens,
                               sampling=samp))
        return out

    def resdict(res):
        return {u: (tuple(r.tokens), r.status) for u, r in res.items()}

    # -- phase 0: integrity-off bit-identity (constant clock so the
    # full stats dict compares) --
    def engine_run(verify):
        eng = InferenceEngine(
            model, params, EngineConfig(**ekw, verify_artifacts=verify),
            clock=lambda: 0.0)
        for r in requests("i"):
            eng.add_request(r)
        return resdict(eng.run(return_status=True)), eng.stats()

    off_res, off_stats = engine_run(False)
    on_res, on_stats = engine_run(True)
    assert off_res == on_res, "checksums changed served tokens"
    assert off_stats == on_stats, "checksums changed schedule counters"

    def fleet_run(verify):
        t0 = time.perf_counter()
        fl = FleetRouter(model, params,
                         EngineConfig(**ekw, verify_artifacts=verify),
                         FleetConfig(num_replicas=1),
                         clock=lambda: 0.0)
        for r in requests("f"):
            fl.add_request(r)
        res = resdict(fl.run(return_status=True))
        return res, fl.replicas[0].engine.stats(), \
            time.perf_counter() - t0

    f_off, fs_off, _ = fleet_run(False)
    f_on, fs_on, wall_clean = fleet_run(True)
    assert f_off == f_on and fs_off == fs_on, \
        "1-replica fleet diverged across verify_artifacts"
    identity_ok = True
    clean_tokens = sum(len(t) for t, _ in f_on.values())
    clean_good = clean_tokens / max(wall_clean, 1e-9)

    # -- phase 1a: spill rot served by recompute, token-identically --
    def spill_serve(plan):
        eng = InferenceEngine(model, params, EngineConfig(**ekw),
                              faults=plan, clock=lambda: 0.0)
        outs = {}
        for wave in range(2):
            for k, p in enumerate(prompts):
                eng.add_request(Request(f"s{wave}.{k}", list(p),
                                        max_new_tokens=new_tokens))
                outs.update(eng.run())
        return outs, eng.stats()

    clean_spill, clean_sst = spill_serve(None)
    rot_plan = FaultPlan([FaultSpec(site="spill_put", kind="corrupt",
                                    every=2)], seed=1907)
    rot_spill, rot_sst = spill_serve(rot_plan)
    assert rot_spill == clean_spill, "corrupt spill changed tokens"
    spill_discards = int(rot_sst["num_spill_corrupt_discards"])
    assert spill_discards > 0, "the spill rot never fired"

    # -- phase 1b: fleet-wide artifact chaos + migrate + kill --
    def chaos_plan(seed):
        return FaultPlan([
            FaultSpec(site="spill_put", kind="corrupt", every=3),
            FaultSpec(site="spill_get", kind="corrupt", every=4),
            FaultSpec(site="checkpoint", kind="corrupt", every=2),
            FaultSpec(site="export", kind="corrupt", every=2),
            FaultSpec(site="import", kind="corrupt", every=2),
        ], seed=seed)

    fl = FleetRouter(model, params,
                     EngineConfig(**ekw, scrub_interval_ticks=3),
                     FleetConfig(num_replicas=2, respawn=True),
                     faults=[chaos_plan(1908), chaos_plan(1909)])
    accepted = []
    for r in requests("a"):
        if fl.try_add(r):
            accepted.append(r.uid)
    for _ in range(3):
        fl.step()
    owners = fl.owners()
    if owners:
        u = sorted(owners)[0]
        fl.migrate([u], owners[u])
    fl.step()
    fl.kill_replica(0)
    chaos_res = fl.run(return_status=True)
    chaos_stats = fl.stats()
    missing = set(accepted) - set(chaos_res)
    assert not missing, f"lost accepted requests: {sorted(missing)}"
    assert chaos_stats["num_lost_requests"] == 0
    chaos_detections = (
        chaos_stats["num_refused_imports"]
        + chaos_stats["num_corrupt_checkpoints"]
        + sum(rep.engine.stats()["num_corruptions_detected"]
              for rep in fl.replicas
              if rep.alive and rep.engine is not None))
    assert chaos_detections > 0, "artifact chaos never detected"

    # -- phase 2: the SDC cross-check --
    sdc_plan = FaultPlan([FaultSpec(site="decode", kind="corrupt",
                                    every=3)], seed=1910)
    fl = FleetRouter(model, params, EngineConfig(**ekw),
                     FleetConfig(num_replicas=3,
                                 sdc_check_interval_ticks=2),
                     faults=[sdc_plan, None, None])
    sdc_accepted = []
    for r in requests("d"):
        if fl.try_add(r):
            sdc_accepted.append(r.uid)
    first_corrupt_tick = suspect_tick = None
    tick = 0
    t0 = time.perf_counter()
    while fl.has_work:
        fl.step()
        tick += 1
        if first_corrupt_tick is None and any(
                kind == "corrupt" for _, kind, _ in sdc_plan.fired):
            first_corrupt_tick = tick
        if (suspect_tick is None
                and fl.stats()["num_sdc_suspects"] >= 1):
            suspect_tick = tick
    wall_sdc = time.perf_counter() - t0
    sdc_res = fl.run(return_status=True)
    sdc_stats = fl.stats()
    assert first_corrupt_tick is not None, "the SDC fault never fired"
    assert suspect_tick is not None, \
        "the cross-check never caught the corrupt replica"
    assert not fl.replicas[0].alive
    assert sdc_stats["num_lost_requests"] == 0
    assert set(sdc_res) == set(sdc_accepted), "terminals not exactly-once"
    detection_latency = suspect_tick - first_corrupt_tick
    sdc_tokens = sum(len(r.tokens) for r in sdc_res.values())
    sdc_good = sdc_tokens / max(wall_sdc, 1e-9)

    print(f"# serving integrity: identity OK | spill rot "
          f"{spill_discards} discards served token-identically | "
          f"artifact chaos {chaos_detections} detections, lost "
          f"{chaos_stats['num_lost_requests']} | SDC caught in "
          f"{detection_latency} ticks (corrupt@{first_corrupt_tick} -> "
          f"suspect@{suspect_tick}), checks "
          f"{sdc_stats['num_sdc_checks']}, goodput {sdc_good:.1f} "
          f"tok/s vs clean {clean_good:.1f}", file=sys.stderr)
    return {
        "metric": ("serving_gpt2s_integrity_sdc_detection_latency_ticks"
                   if on_tpu else
                   "serving_tiny_integrity_sdc_detection_latency_ticks"),
        "value": float(detection_latency),
        "unit": "ticks",
        # the cost of serving through a corrupting replica + its
        # retirement, relative to the clean 1-replica fleet
        "vs_baseline": round(sdc_good / max(clean_good, 1e-9), 4),
        "identity_ok": identity_ok,
        "spill_corrupt_discards": spill_discards,
        "spill_served_token_identical": True,
        "chaos_detections": int(chaos_detections),
        "chaos_refused_imports":
            int(chaos_stats["num_refused_imports"]),
        "chaos_corrupt_checkpoints":
            int(chaos_stats["num_corrupt_checkpoints"]),
        "chaos_zero_lost": True,
        "sdc_checks": int(sdc_stats["num_sdc_checks"]),
        "sdc_suspects": int(sdc_stats["num_sdc_suspects"]),
        "sdc_first_corrupt_tick": int(first_corrupt_tick),
        "sdc_suspect_tick": int(suspect_tick),
        "sdc_zero_lost": True,
        "sdc_exactly_once": True,
        "sdc_goodput_tok_per_sec": round(sdc_good, 3),
    }


_MESH_SERVING_CHILD = r"""
import json, sys, time
sys.path.insert(0, sys.argv[1])
fast = sys.argv[2] == "1"
import jax, jax.numpy as jnp, numpy as np
from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.serving import EngineConfig, InferenceEngine, Request
from apex_tpu.serving import mesh as mesh_lib

cfg = GPTConfig.tiny(dropout=0.0, remat=False)
model = GPTLMHeadModel(cfg)
params = model.init(jax.random.PRNGKey(0),
                    jnp.asarray(np.random.RandomState(0).randint(
                        0, cfg.vocab_size, (1, 8))))
n_req, plen, new = (8, 16, 12) if fast else (24, 32, 24)

def make_reqs():
    # greedy traffic: the cross-mesh token-identity assertion is
    # certified for argmax lanes (fixed seeds; the sampled story is
    # the tier-1 matrix's)
    rr = np.random.RandomState(4)
    return [Request(uid=f"m{i}",
                    prompt=list(rr.randint(0, cfg.vocab_size, plen)),
                    max_new_tokens=new) for i in range(n_req)]

def econf(mesh_shape):
    return EngineConfig(max_batch=8, block_size=8, num_blocks=64,
                        max_prefill_len=16, max_seq_len=64,
                        decode_steps=4, mesh_shape=mesh_shape, seed=9)

def serve(eng, reqs):
    for r in reqs:
        eng.add_request(r)
    return eng.run(return_status=True)

# phase 0: mesh (1,1) bit-identity to the PRE-MESH engine (the mesh
# layer neutered = the byte-identical old path), constant clock so the
# full stats() dict is comparable
CONST = lambda: 0.0
mesh_eng = InferenceEngine(model, params, econf((1, 1)), clock=CONST)
mesh_res = serve(mesh_eng, make_reqs())
saved = (mesh_lib.shard_params, mesh_lib.shard_cache,
         mesh_lib.program_out_shardings)
mesh_lib.shard_params = lambda mesh, params, pspec_fn=None: params
mesh_lib.shard_cache = lambda mesh, cache: cache
mesh_lib.program_out_shardings = lambda mesh, cache: None
try:
    plain_eng = InferenceEngine(model, params, econf((1, 1)), clock=CONST)
    plain_res = serve(plain_eng, make_reqs())
finally:
    (mesh_lib.shard_params, mesh_lib.shard_cache,
     mesh_lib.program_out_shardings) = saved
assert {u: (r.tokens, r.status) for u, r in mesh_res.items()} \
    == {u: (r.tokens, r.status) for u, r in plain_res.items()}, \
    "mesh (1,1) is not token/status-identical to the pre-mesh engine"
assert mesh_eng.stats() == plain_eng.stats(), \
    "mesh (1,1) perturbed the stats() dict"

# phase 1: the same seeded greedy trace timed at (1,1) vs (1,2)
def arm(mesh_shape):
    eng = InferenceEngine(model, params, econf(mesh_shape))
    eng.add_request(Request(uid="warm", prompt=[1] * 8, max_new_tokens=2))
    eng.run()                       # compile outside the clock
    reqs = make_reqs()
    s0 = eng.stats()
    t0 = time.perf_counter()
    res = serve(eng, reqs)
    dt = time.perf_counter() - t0
    s1 = eng.stats()
    toks = s1["num_tokens_decoded"] - s0["num_tokens_decoded"]
    audit = eng.audit_collectives()     # raises on contract violation
    return {
        "mesh_shape": list(mesh_shape),
        "decode_tokens_per_sec": round(toks / max(dt, 1e-9), 3),
        "decode_tokens": int(toks),
        "wall_s": round(dt, 4),
        "prefill_compilations": int(s1["prefill_compilations"]),
        "decode_compilations": int(s1["decode_compilations"]),
        "collective_ops": {prog: int(st["total"]["ops"])
                           for prog, st in audit.items()},
        "allreduce_ops": {prog: int(st["all-reduce"]["ops"])
                          for prog, st in audit.items()},
        # the spelling-agnostic reduction count (hlo_audit's round-5
        # lesson: XLA may lower one all-reduce as a reduce-scatter +
        # all-gather pair; the raw all-reduce count is reported above
        # as observed truth but never asserted on)
        "reduction_ops": {
            prog: int(st["all-reduce"]["ops"]
                      + st["reduce-scatter"]["ops"])
            for prog, st in audit.items()},
    }, {u: r.tokens for u, r in res.items()}

arm11, out11 = arm((1, 1))
arm12, out12 = arm((1, 2))
assert out11 == out12, \
    "greedy request outputs diverged across mesh shapes"
assert arm11["prefill_compilations"] == 1 \
    and arm11["decode_compilations"] == 1, arm11
assert arm12["prefill_compilations"] == 1 \
    and arm12["decode_compilations"] == 1, arm12
assert all(v == 0 for v in arm11["collective_ops"].values()), arm11
assert all(v >= 1 for v in arm12["reduction_ops"].values()), arm12

print(json.dumps({
    "mesh11_bit_identical": True,
    "cross_mesh_token_identical": True,
    "num_requests": n_req,
    "mesh_1x1": arm11,
    "mesh_1x2": arm12,
}))
"""


def bench_serving_mesh(fast=False):
    """Pod-scale serving arm (round 15, docs/serving.md "Mesh
    sharding"): the GSPMD mesh promotion, certified where it matters —
    the SAME seeded greedy trace served at mesh (1, 1) and (1, 2).

    Runs in a child process with TWO forced CPU host devices
    (``XLA_FLAGS=--xla_force_host_platform_device_count=2`` must be
    set before JAX initializes, and the parent's backend is already
    up), and asserts in-child: mesh (1, 1) BIT-identical to the
    pre-mesh engine (outputs, statuses, full constant-clock stats —
    the mesh layer neutered as the baseline), token-identity of every
    request's output across mesh shapes, compile counts pinned at one
    per program under both meshes, and the hlo_audit collective
    contract (zero collectives at (1, 1); every program shows
    all-reduce traffic at (1, 2) and the contract forbids
    all-to-all). Reports decode tok/s per arm — on a shared-core
    virtual mesh the (1, 2) arm pays the all-reduces without real
    parallel compute, so ``vs_baseline`` (the (1,2)/(1,1) ratio) is
    the honest collective-overhead number, not a speedup claim; on
    real multi-chip hardware the same record becomes the scale-up
    curve. ``fast=True`` is the tier-1 smoke shape."""
    import subprocess

    env = {k: v for k, v in os.environ.items()
           # the pallas read flag would make the child's (1,2) engine
           # refuse construction (the kernel is single-device) — an
           # operator exercising it on the OTHER serving sections must
           # not kill the mesh arm
           if k not in ("PALLAS_AXON_POOL_IPS",
                        "APEX_PAGED_ATTENTION_PALLAS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2")
    here = os.path.dirname(os.path.abspath(__file__))
    out = subprocess.run(
        [sys.executable, "-c", _MESH_SERVING_CHILD, here,
         "1" if fast else "0"],
        capture_output=True, text=True, timeout=600, env=env)
    if out.returncode != 0:
        raise RuntimeError(out.stderr[-800:])
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["mesh11_bit_identical"] is True
    assert rec["cross_mesh_token_identical"] is True
    a11, a12 = rec["mesh_1x1"], rec["mesh_1x2"]
    ratio = (a12["decode_tokens_per_sec"]
             / max(a11["decode_tokens_per_sec"], 1e-9))
    print(f"# serving-mesh: {rec['num_requests']} greedy requests, "
          f"(1,1) {a11['decode_tokens_per_sec']:.1f} tok/s vs (1,2) "
          f"{a12['decode_tokens_per_sec']:.1f} tok/s ({ratio:.2f}x); "
          f"collectives (1,1) {a11['collective_ops']} -> (1,2) "
          f"reductions {a12['reduction_ops']}; bit-identity + "
          f"cross-mesh token identity held", file=sys.stderr)
    return {
        "metric": "serving_tiny_mesh_decode_tokens_per_sec",
        "value": a12["decode_tokens_per_sec"],
        "unit": "tokens/sec",
        # the honest cross-arm number on a virtual mesh: collective
        # overhead, not parallel speedup (see docstring)
        "vs_baseline": round(ratio, 3),
        "mesh11_bit_identical": True,
        "cross_mesh_token_identical": True,
        "num_requests": int(rec["num_requests"]),
        "arms": {"mesh_1x1": a11, "mesh_1x2": a12},
    }


def bench_train_step(fast=False):
    """Fused train step (apex_tpu.train): the whole global optimizer
    step — amp O2 scaled forward/backward, ``accum_steps`` scanned
    microbatches with fp32 on-device accumulation, in-graph overflow
    skip, fused-LAMB update — as ONE donated-buffer dispatch, swept
    over ``accum_steps`` in {1, 4, 8} against the hand-wired
    per-microbatch dispatch loop (``build_reference_loop``: one
    dispatch per microbatch + an apply dispatch, the pre-builder
    wiring). Reports steps/sec per arm, ASSERTS bit-identical final
    params (fused vs loop, every arm — the training analog of the
    serving bench's cross-K certification), and audits the compiled
    program's input-output aliasing so a silently-dropped donation
    reads as a regression, not a warning. ``vs_baseline`` is the
    loop/fused time ratio at the largest accum: the dispatch
    amortization itself. ``fast=True`` is the tier-1 smoke shape."""
    import flax.linen as nn

    import apex_tpu.amp as amp
    from apex_tpu.optimizers import FusedLAMB
    from apex_tpu.train import build_reference_loop, build_train_step

    on_tpu = _backend_with_cpu_fallback() == "tpu" and not fast
    if on_tpu:
        hidden, depth, feat, classes, mb = 2048, 4, 512, 1024, 64
        accums = (1, 4, 8)
        ident_steps, iters = 8, 8
    else:
        hidden, depth, feat, classes, mb = 256, 2, 64, 16, 32
        accums = (1, 4) if fast else (1, 4, 8)
        ident_steps, iters = (4, 4) if fast else (8, 8)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            for _ in range(depth):
                x = nn.Dense(hidden, param_dtype=jnp.float32)(x)
                x = nn.relu(x)
            return nn.Dense(classes, param_dtype=jnp.float32)(x)

    model = Net()
    rng = np.random.RandomState(_SALT + 2)
    max_acc = max(accums)
    xs_all = jnp.asarray(rng.randn(max_acc, mb, feat).astype("f4"))
    ys_all = jnp.asarray(rng.randint(0, classes, (max_acc, mb)))

    p0 = model.init(jax.random.PRNGKey(0), xs_all[0])["params"]
    p0, opt, handle = amp.initialize(
        p0, FusedLAMB(lr=1e-3, weight_decay=0.01), opt_level="O2",
        verbosity=0)
    n_param_leaves = len(jax.tree.leaves(p0))

    def loss_fn(p, batch):
        x, y = batch
        logits = model.apply({"params": p}, x).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(lp, y[:, None], 1))

    def fresh(builder):
        return builder.init(jax.tree.map(jnp.copy, p0))

    def ab_time(stepper_a, state_a, stepper_b, state_b, batch,
                rounds=3):
        """Interleaved A/B marginal timing (the _ab_chain_time
        methodology restated for (state, batch) steppers whose two arms
        carry different state types): alternate arms round-robin so
        both ride the same load drift, keep the min marginal per arm.
        Each arm's state threads across rounds (donating steps consume
        it; a replayed bit-identical sequence would also hit the
        runtime memoizer)."""
        arms = [[stepper_a, state_a, None], [stepper_b, state_b, None]]
        mins = [None, None]
        for arm in arms:                 # compile outside the clock
            arm[1], m = arm[0](arm[1], batch)
            arm[2] = m["loss"]
            float(np.asarray(arm[2]))
        for _ in range(rounds):
            for i, arm in enumerate(arms):
                def advance(n, arm=arm):
                    for _ in range(n):
                        arm[1], m = arm[0](arm[1], batch)
                        arm[2] = m["loss"]

                dt = marginal_time(
                    advance, lambda arm=arm: float(np.asarray(arm[2])),
                    iters)
                mins[i] = dt if mins[i] is None else min(mins[i], dt)
        return mins

    # Donation probe (round-4 verify note: axon accepts a trivial donated
    # jit but real-step donation can still die at run time) — fall back
    # to donate=False so the sweep records rather than vanishing, with
    # the fallback visible in the record.
    donate = True
    probe = build_train_step(loss_fn, opt, amp=handle, accum_steps=1)
    try:
        probe.step(fresh(probe), (xs_all[:1], ys_all[:1]))
    except Exception as e:
        donate = False
        print(f"# train step: donated dispatch failed at run time "
              f"({type(e).__name__}); falling back to donate=False",
              file=sys.stderr)

    sweep, all_identical, alias_pairs = {}, True, None
    for a in accums:
        batch = (xs_all[:a], ys_all[:a])
        ts = build_train_step(loss_fn, opt, amp=handle, accum_steps=a,
                              donate=donate)
        ref = build_reference_loop(loss_fn, opt, amp=handle,
                                   accum_steps=a)
        if a == max_acc:                # donation audit on the big arm
            alias_pairs = ts.alias_stats(fresh(ts), batch)["pairs"]
        # bit-identity certification: same init, same stream, T steps
        sA, sB = fresh(ts), fresh(ref)
        for _ in range(ident_steps):
            sA, _m = ts.step(sA, batch)
            sB, _m = ref.step(sB, batch)
        ident = all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(jax.tree.leaves((sA.params, sA.opt_state)),
                            jax.tree.leaves((sB.params, sB.opt_state))))
        all_identical = all_identical and ident
        dt_fused, dt_loop = ab_time(ts.step, fresh(ts), ref.step,
                                    fresh(ref), batch,
                                    rounds=1 if fast else 3)
        sweep[f"accum{a}"] = {
            "fused_steps_per_sec": round(1.0 / dt_fused, 3),
            "loop_steps_per_sec": round(1.0 / dt_loop, 3),
            "speedup": round(dt_loop / dt_fused, 3),
            "bit_identical": bool(ident),
        }

    if not all_identical:
        # the certification is the point: a fused-vs-loop bit mismatch
        # must fail the section loudly (missing record in the round),
        # never record rc=0 with a quietly-false JSON field
        raise AssertionError(
            "fused-scan vs per-microbatch loop params NOT bit-identical: "
            + json.dumps({k: v["bit_identical"] for k, v in sweep.items()}))
    top = sweep[f"accum{max_acc}"]
    print("# train step: " + " | ".join(
        f"accum={a} fused {sweep[f'accum{a}']['fused_steps_per_sec']:.1f} "
        f"vs loop {sweep[f'accum{a}']['loop_steps_per_sec']:.1f} steps/s "
        f"({sweep[f'accum{a}']['speedup']:.2f}x)" for a in accums)
        + f" | bit-identical {all_identical} | donated alias pairs "
        f"{alias_pairs}/{n_param_leaves} param leaves", file=sys.stderr)
    return {
        "metric": ("train_step_fused_accum8_steps_per_sec" if on_tpu
                   else "train_step_tiny_smoke_fused_steps_per_sec"),
        "value": top["fused_steps_per_sec"],
        "unit": "steps/sec",
        # the fused-vs-per-microbatch-dispatch amortization at max accum
        "vs_baseline": top["speedup"],
        "accum_steps_swept": list(accums),
        "final_params_bit_identical": bool(all_identical),
        "donated": bool(donate),
        "donated_alias_pairs": int(alias_pairs),
        "param_leaves": int(n_param_leaves),
        "sweep": sweep,
    }


_TRAIN_SHARDED_CHILD = r"""
import json, sys, time
sys.path.insert(0, sys.argv[1])
fast = sys.argv[2] == "1"
import jax, jax.numpy as jnp, numpy as np
from apex_tpu.models.gpt import GPTConfig, GPTLMHeadModel, lm_loss
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.serving.mesh import build_mesh
from apex_tpu.train import build_train_step

cfg = GPTConfig.tiny(dropout=0.0, remat=False)
model = GPTLMHeadModel(cfg)
ACCUM, B, S = 2, 4, 16
tokens = jnp.asarray(np.random.RandomState(7).randint(
    0, cfg.vocab_size, (ACCUM, B, S)))
params = jax.device_get(
    model.init(jax.random.PRNGKey(0), tokens[0])["params"])

def loss_fn(p, mb):
    return lm_loss(model.apply({"params": p}, mb), mb)

arms, order = {}, ["meshless", "mesh_1x2", "mesh_2x2"]
for name, shape in zip(order, [None, (1, 2), (2, 2)]):
    opt = DistributedFusedAdam(lr=1e-3, flat_mode="global")
    kw = dict(accum_steps=ACCUM)
    if shape is not None:
        kw.update(mesh=build_mesh(shape), num_heads=cfg.num_heads)
    ts = build_train_step(loss_fn, opt, **kw)
    st = ts.init(jax.tree.map(jnp.asarray, params))
    st, m = ts.step(st, tokens)  # compile outside the clock
    arms[name] = {"ts": ts, "st": st,
                  "loss1": float(jax.device_get(m["loss"]))}

# certification: every mesh arm's first optimizer step lands on the
# meshless loss (the tier-1 matrix holds the bit-level story; here the
# cross-partitioning fp32 drift bound is the gate)
ref = arms["meshless"]["loss1"]
for name in order[1:]:
    got = arms[name]["loss1"]
    assert abs(got - ref) <= 1e-3 * abs(ref) + 1e-5, (name, got, ref)

# interleaved A/B: round-robin the arms so every arm rides the same
# host-load drift; min-of-rounds marginal seconds per global step
iters, rounds = (2, 2) if fast else (4, 3)
best = {n: None for n in order}
for _ in range(rounds):
    for n in order:
        a = arms[n]
        t0 = time.perf_counter()
        for _ in range(iters):
            a["st"], m = a["ts"].step(a["st"], tokens)
        jax.block_until_ready(m["loss"])
        dt = (time.perf_counter() - t0) / iters
        best[n] = dt if best[n] is None else min(best[n], dt)

out = {"arms": {}, "loss_certified": True}
for n in order:
    a, ts = arms[n], arms[n]["ts"]
    rec = {"steps_per_sec": round(1.0 / best[n], 3),
           "compiles": int(ts._jitted._cache_size()),
           "opt_state_bytes_per_shard":
               int(ts._core.optimizer.stats()["opt_state_bytes_per_shard"]),
           "flat_world": int(ts._core.optimizer.stats()["flat_world"])}
    assert rec["compiles"] == 1, (n, rec["compiles"])
    if ts.mesh_shape is not None:
        # raises on any per-mesh contract violation (forbidden
        # all-to-all, missing TP all-reduces, missing ZeRO leg)
        audit = ts.audit_collectives(a["st"], tokens)
        rec["collective_ops"] = {
            k: int(v["ops"]) for k, v in audit["collectives"].items()}
        rec["alias_pairs"] = int(audit["alias"]["pairs"])
        rec["sharded_leaves"] = int(audit["sharded_leaves"])
    out["arms"][n] = rec
print(json.dumps(out))
"""


def bench_train_sharded(fast=False):
    """3D-parallel training arm (round 20, docs/training.md "Sharded
    training"): the GSPMD ``build_train_step(mesh=...)`` promotion —
    scanned accumulation + ZeRO flat-shard optimizer update + tensor-
    parallel activations in ONE donated dispatch — A/B'd against the
    meshless fused step on the same tiny GPT.

    Runs in a child process with FOUR forced CPU host devices (the
    ``XLA_FLAGS`` must land before JAX initializes; the parent backend
    is already up), interleaves the meshless / (1,2) / (2,2) arms
    round-robin so all share the host-load drift, and asserts
    in-child: every mesh arm's loss certified against meshless, the
    compile count pinned at ONE per arm (the spec-canonicalization
    regression gate), and the AOT hlo_audit collective contract per
    mesh shape (all-to-all forbidden; TP all-reduces and the ZeRO
    reduce+gather leg required where the geometry demands them). On a
    shared-core virtual mesh the sharded arms pay the collectives
    without real parallel compute, so ``vs_baseline`` (the
    (2,2)/meshless steps/s ratio) is the honest overhead number, not
    a speedup claim; ``opt_state_bytes_per_shard`` falling from the
    world-1 arms to (2,2) is the ZeRO memory story that survives the
    virtual mesh. ``fast=True`` is the tier-1 smoke shape."""
    import subprocess

    env = {k: v for k, v in os.environ.items()
           # single-device pallas knobs must not leak into the mesh
           # child (same hygiene as bench_serving_mesh)
           if k not in ("PALLAS_AXON_POOL_IPS",
                        "APEX_PAGED_ATTENTION_PALLAS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=4")
    here = os.path.dirname(os.path.abspath(__file__))
    out = subprocess.run(
        [sys.executable, "-c", _TRAIN_SHARDED_CHILD, here,
         "1" if fast else "0"],
        capture_output=True, text=True, timeout=600, env=env)
    if out.returncode != 0:
        raise RuntimeError(out.stderr[-800:])
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    arms = rec["arms"]
    assert rec["loss_certified"] is True
    for n in ("mesh_1x2", "mesh_2x2"):
        assert arms[n]["compiles"] == 1
        assert arms[n]["collective_ops"].get("all-to-all", 0) == 0
        assert arms[n]["alias_pairs"] >= arms[n]["sharded_leaves"] > 0
    # the ZeRO shard: (2,2) has flat_world=2, so each rank holds half
    # the fp32 master/m/v bytes of the world-1 arms (modulo padding)
    assert (arms["mesh_2x2"]["opt_state_bytes_per_shard"]
            < arms["mesh_1x2"]["opt_state_bytes_per_shard"])
    base = arms["meshless"]["steps_per_sec"]
    top = arms["mesh_2x2"]
    ratio = top["steps_per_sec"] / max(base, 1e-9)
    zero_ratio = (arms["meshless"]["opt_state_bytes_per_shard"]
                  / max(top["opt_state_bytes_per_shard"], 1))
    print(f"# train-sharded: meshless {base:.2f} steps/s vs (2,2) "
          f"{top['steps_per_sec']:.2f} steps/s ({ratio:.2f}x); (2,2) "
          f"collectives {top['collective_ops']}; opt-state bytes/shard "
          f"{arms['meshless']['opt_state_bytes_per_shard']} -> "
          f"{top['opt_state_bytes_per_shard']} ({zero_ratio:.2f}x "
          f"ZeRO shrink); loss certified, compiles pinned at 1",
          file=sys.stderr)
    return {
        "metric": "train_tiny_sharded_steps_per_sec",
        "value": top["steps_per_sec"],
        "unit": "steps/sec",
        # the honest cross-arm number on a virtual mesh: collective
        # overhead, not parallel speedup (see docstring)
        "vs_baseline": round(ratio, 3),
        "loss_certified": True,
        "opt_state_bytes_ratio": round(zero_ratio, 3),
        "arms": arms,
    }


def bench_serving_process(fast=False):
    """Out-of-process replica arm (round 16, docs/fleet.md "Process
    replicas" + "Autoscaler"): the child-process serving runtime and
    the elastic autoscaler, certified where they matter — a child
    SIGKILLED for real mid-burst, and a fleet that grows and shrinks
    without flapping.

    Three phases: (0) identity — a 1-process-replica fleet (the engine
    in a CHILD OS process behind the framed stdio RPC) must be
    BIT-IDENTICAL to the in-process 1-replica fleet: outputs, terminal
    statuses, and the full constant-clock fleet ``stats()`` (only the
    per-replica ``mode`` tag differs, popped before compare); (1) a
    2-process-replica fleet serves a seeded Poisson burst while one
    child is ``os.kill``-SIGKILLED mid-burst with respawn on — ZERO
    lost accepted requests, every accepted uid terminal exactly once,
    at least one failover, a FRESH child pid in the victim slot, and
    the victims' p99 TTFT (scheduler ticks) bounded vs the kill-free
    in-process baseline on the same trace; (2) the autoscaler rides a
    burst-then-drain ramp in-process (the control loop is
    mode-agnostic; in-process keeps the phase child-free): the fleet
    grows under load, shrinks back to min when drained, spawn/retire
    counts balance, and an idle tail of ticks shows zero flapping.

    Always the tiny host shape: process replicas are a HOST runtime
    mechanism (device kernels untouched), and two processes cannot
    share one TPU — on a TPU parent the children are forced to
    ``JAX_PLATFORMS=cpu`` and the parent arms pin to the CPU backend
    so phase 0 compares like with like. ``fast=True`` is the tier-1
    smoke shape."""
    import contextlib
    import signal as _signal

    from apex_tpu.models import GPTConfig
    from apex_tpu.observability import percentile
    from apex_tpu.serving import (EngineConfig, FleetConfig, FleetRouter,
                                  Request, SamplingParams)
    from apex_tpu.serving.process_replica import (build_model_from_spec,
                                                  gpt_model_spec)

    backend = _backend_with_cpu_fallback()
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    spec = gpt_model_spec(cfg)
    ekw = dict(max_batch=4, block_size=8, num_blocks=64,
               max_prefill_len=16, max_seq_len=48,
               enable_prefix_caching=True,
               snapshot_interval_ticks=2, max_waiting=32, seed=11)
    ticks = 10 if fast else 16
    rate = 0.5 if fast else 0.7
    prompt_lens, max_news = (8, 14), (4, 6)
    kill_tick = 4 if fast else 6

    stack = contextlib.ExitStack()
    prev_platforms = os.environ.get("JAX_PLATFORMS")
    if backend != "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        stack.enter_context(jax.default_device(jax.devices("cpu")[0]))
    try:
        # the parent builds (model, params) FROM the spec — the same
        # deterministic init the children replay, so the boot
        # checksum handshake passes by construction
        model, params = build_model_from_spec(spec)

        def make_trace():
            rng = np.random.RandomState(1914)

            def make(tick, k):
                prompt = list(rng.randint(1, cfg.vocab_size,
                                          int(rng.choice(prompt_lens))))
                samp = (SamplingParams() if k % 2 else
                        SamplingParams(temperature=1.0, top_k=40))
                new = int(rng.choice(max_news))
                return lambda: Request(uid=f"q{k}", prompt=list(prompt),
                                       max_new_tokens=new, sampling=samp)

            return _poisson_burst_trace(
                rng, ticks=ticks, base_rate=rate, make_request=make,
                burst_start=ticks // 3, burst_end=2 * ticks // 3,
                burst_factor=3)

        def drive(router, trace, kill_at=None, kill_idx=None):
            """Tick through the trace; the kill is a REAL ``os.kill``
            SIGKILL on the child pid (no cooperation — the parent
            discovers the corpse through the RPC layer). Returns
            (ttft_ticks, accepted, victims, wall_s)."""
            submit, first = {}, {}
            accepted, victims = [], None
            t0 = time.perf_counter()
            i = tick = 0
            while i < len(trace) or router.has_work:
                while i < len(trace) and trace[i][0] <= tick:
                    req = trace[i][1]()
                    if router.try_add(req):
                        submit[req.uid] = tick
                        accepted.append(req.uid)
                    i += 1
                if (kill_at is not None and tick == kill_at
                        and router.replicas[kill_idx].alive):
                    victims = [u for u, o in router.owners().items()
                               if o == kill_idx]
                    os.kill(router.replicas[kill_idx].engine.child_pid,
                            _signal.SIGKILL)
                router.step()
                for uid, tok, last in router.pop_stream_events():
                    if tok >= 0 and uid not in first and uid in submit:
                        first[uid] = tick
                tick += 1
            wall = time.perf_counter() - t0
            ttft = {u: first[u] - submit[u] for u in first}
            return ttft, accepted, victims, wall

        def pct(xs, q):
            return percentile(xs, q) if xs else 0.0

        proc_kw = dict(model_spec=spec,
                       child_clock={"kind": "constant", "t": 0.0})

        # -- phase 0: the 1-process-replica identity cert (constant
        # clocks both sides: every time-derived stat equal by
        # construction, so the FULL fleet stats dict compares) --
        ident = make_trace()[:6]

        def run_one(mode):
            kw = proc_kw if mode == "process" else {}
            fleet = FleetRouter(model, params, EngineConfig(**ekw),
                                FleetConfig(num_replicas=1,
                                            replica_mode=mode),
                                clock=lambda: 0.0, **kw)
            try:
                for _, mk in ident:
                    fleet.add_request(mk())
                res = fleet.run(return_status=True)
                stats = json.loads(json.dumps(fleet.stats(),
                                              sort_keys=True,
                                              default=str))
                for row in stats["replicas"].values():
                    row.pop("mode")
                return ({u: (tuple(r.tokens), r.status)
                         for u, r in res.items()}, stats)
            finally:
                fleet.close()

        in_res, in_stats = run_one("in_process")
        pr_res, pr_stats = run_one("process")
        assert pr_res == in_res, \
            "process fleet outputs diverged from in-process"
        assert pr_stats == in_stats, \
            "process fleet stats diverged from in-process"

        # -- phase 1: kill-free in-process baseline, then the same
        # trace on a 2-process-replica fleet with a mid-burst SIGKILL
        # on one child --
        trace = make_trace()
        base = FleetRouter(model, params, EngineConfig(**ekw),
                           FleetConfig(num_replicas=2))
        ttft_base, accepted_base, _, wall_base = drive(base, trace)
        base_res = base.run(return_status=True)
        assert base.stats()["num_lost_requests"] == 0
        base_good = sum(len(r.tokens) for r in base_res.values()
                        if r.status == "finished") / max(wall_base, 1e-9)
        p99_base = pct(list(ttft_base.values()), 99)

        router = FleetRouter(model, params, EngineConfig(**ekw),
                             FleetConfig(num_replicas=2,
                                         replica_mode="process",
                                         respawn=True),
                             **proc_kw)
        try:
            pid0 = router.replicas[0].engine.child_pid
            ttft_kill, accepted, victims, wall_kill = drive(
                router, trace, kill_at=kill_tick, kill_idx=0)
            kill_res = router.run(return_status=True)
            stats = router.stats()
            missing = set(accepted) - set(kill_res)
            assert not missing, \
                f"lost accepted requests: {sorted(missing)}"
            assert stats["num_lost_requests"] == 0
            assert len(set(accepted)) == len(accepted)
            assert stats["num_failovers"] >= 1, "the kill never fired"
            assert stats["num_respawns"] >= 1, "no respawn after kill"
            fresh = router.replicas[0].engine
            pids_fresh = fresh is not None and fresh.child_pid != pid0
            assert pids_fresh, "victim slot did not get a fresh child"
        finally:
            router.close()
        victims = victims or []
        victim_ttft = [ttft_kill[u] for u in victims if u in ttft_kill]
        p99_victim = pct(victim_ttft, 99)
        victim_bound = 4.0 * p99_base + 16.0
        assert p99_victim <= victim_bound, (
            f"victim p99 TTFT {p99_victim} ticks vs baseline "
            f"{p99_base} (bound {victim_bound})")
        kill_good = sum(len(r.tokens) for r in kill_res.values()
                        if r.status == "finished") / max(wall_kill, 1e-9)

        # -- phase 2: the autoscale ramp, in-process (child-free) --
        ramp = FleetRouter(
            model, params,
            EngineConfig(**{**ekw, "max_batch": 1}),
            FleetConfig(num_replicas=1,
                        autoscale_high_watermark=1.0,
                        autoscale_low_watermark=0.5,
                        autoscale_patience=2,
                        autoscale_max_replicas=3))
        n_ramp = 8 if fast else 12
        rng = np.random.RandomState(1915)
        for k in range(n_ramp):
            ramp.add_request(Request(
                uid=f"r{k}", prompt=list(rng.randint(1, cfg.vocab_size,
                                                     6)),
                max_new_tokens=12, sampling=SamplingParams()))
        sizes = []
        while ramp.has_work:
            ramp.step()
            sizes.append(len(ramp._alive()))
        rs = ramp.stats()
        assert max(sizes) > 1, "the ramp never triggered a spawn"
        assert sizes[-1] == 1, "the drained fleet did not shrink to min"
        assert max(sizes) <= 3 and min(sizes) >= 1
        assert rs["num_spawned"] == rs["num_retired"] >= 1
        assert rs["num_lost_requests"] == 0
        assert len(ramp.run()) == n_ramp
        before = (rs["num_spawned"], rs["num_retired"])
        for _ in range(8):                      # idle tail: no flapping
            ramp.step()
        after = ramp.stats()
        flap_free = (after["num_spawned"], after["num_retired"]) == before
        assert flap_free, "the idle fleet flapped"
    finally:
        stack.close()
        if backend != "cpu":
            if prev_platforms is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = prev_platforms

    print(f"# serving process: identity OK | baseline p99 TTFT "
          f"{p99_base:.0f} ticks, goodput {base_good:.1f} tok/s | "
          f"SIGKILL@{kill_tick} (victims {len(victims)}) p99 "
          f"{p99_victim:.0f} ticks (bound {victim_bound:.0f}), "
          f"goodput {kill_good:.1f} tok/s | failovers "
          f"{stats['num_failovers']}, respawns {stats['num_respawns']}, "
          f"rpc retries {stats['num_rpc_retries']}, rpc timeouts "
          f"{stats['num_rpc_timeouts']} | ramp peak {max(sizes)} "
          f"replicas, spawned {after['num_spawned']}, retired "
          f"{after['num_retired']}", file=sys.stderr)
    return {
        "metric": "serving_tiny_process_kill_goodput_tok_per_sec",
        "value": round(kill_good, 3),
        "unit": "tokens/sec",
        # SIGKILL-tolerance quality: goodput with a child killed
        # mid-burst vs the kill-free in-process fleet (wall-clock, so
        # the respawn boot cost shows here, not in ticks)
        "vs_baseline": round(kill_good / max(base_good, 1e-9), 4),
        "identity_ok": True,
        "zero_lost": True,
        "child_pid_fresh": True,
        "num_offered": len(trace),
        "num_accepted": len(accepted),
        "num_victims": len(victims),
        "victim_p99_ttft_ticks": round(float(p99_victim), 2),
        "victim_p99_bound_ticks": round(float(victim_bound), 2),
        "baseline_p99_ttft_ticks": round(float(p99_base), 2),
        "num_failovers": int(stats["num_failovers"]),
        "num_respawns": int(stats["num_respawns"]),
        "num_rpc_retries": int(stats["num_rpc_retries"]),
        "num_rpc_timeouts": int(stats["num_rpc_timeouts"]),
        "num_lost_requests": int(stats["num_lost_requests"]),
        "autoscale_peak_replicas": int(max(sizes)),
        "autoscale_num_spawned": int(after["num_spawned"]),
        "autoscale_num_retired": int(after["num_retired"]),
        "autoscale_flap_free": True,
        "status_counts": {
            s: sum(r.status == s for r in kill_res.values())
            for s in {r.status for r in kill_res.values()}},
    }


def bench_serving_disagg(fast=False):
    """Disaggregated prefill/decode arm (round 17, docs/fleet.md
    "Disaggregated roles"): specialist replicas vs the colocated fleet
    at EQUAL device count, on a trace built to expose the interference
    disaggregation removes — long-decode requests pin a colocated
    replica's lanes for their whole decode, so a newcomer's prefill
    waits out someone else's generation, and every prefill chunk that
    does run lands its latency on the resident decodes sharing the
    tick.

    Three phases: (1) colocated baseline — 2 role-less replicas serve
    a seeded Poisson mix of long-decode and latency-sensitive
    short-prompt requests; TTFT p99 (scheduler ticks), decode goodput
    (wall), and the interference quantified directly: ticks where a
    replica ran a prefill chunk AND stepped live decode lanes
    (chunk-over-decode), plus lane-wait implied by the TTFT tail; (2)
    the SAME trace on a {1 prefill + 1 decode} specialist fleet —
    prefill lanes recycle at handoff instead of being held through
    decode, so the arm asserts the disaggregated TTFT p99 is LOWER
    than colocated, decode specialists never prefilled a fresh
    prompt (their chunk count is bounded by their handoff imports —
    only sub-block tail resumes), the handoff counters moved real
    requests/bytes, and nothing was lost;
    (3) chaos — the prefill specialist is hard-killed mid-trace:
    role fallback + checkpoint failover must finish every accepted
    request with ``num_lost_requests == 0``. ``vs_baseline`` is
    disaggregated p99 / colocated p99 (< 1 = disaggregation pays).
    ``fast=True`` is the tier-1 smoke shape."""
    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.observability import percentile
    from apex_tpu.serving import (EngineConfig, FleetConfig, FleetRouter,
                                  Request, SamplingParams)

    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    # lanes are the contended resource: few of them, long decodes
    ekw = dict(max_batch=2, block_size=8, num_blocks=96,
               max_prefill_len=8, max_seq_len=64,
               enable_prefix_caching=True, spill_max_bytes=1 << 20,
               snapshot_interval_ticks=2, max_waiting=64, seed=11)
    ticks = 14 if fast else 28
    rate = 1.0 if fast else 0.9
    heavy_new = 16 if fast else 24
    kill_tick = 5 if fast else 9
    model = GPTLMHeadModel(cfg)
    # FIXED seeds (not _SALT): the arm asserts a latency ORDERING
    # between two fleets on one trace — the trace must be the same
    # every round or the assert flakes
    init_rng = np.random.RandomState(1712)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(init_rng.randint(0, cfg.vocab_size, (1, 8))))

    def make_trace():
        rng = np.random.RandomState(1713)

        def make(tick, k):
            heavy = (k % 3) != 2
            # single-chunk prompts: the contended resource is the
            # LANE a long decode pins, not prefill chunk bandwidth
            plen = int(rng.randint(6, 9) if heavy
                       else rng.randint(4, 7))
            prompt = list(rng.randint(0, cfg.vocab_size, plen))
            new = (heavy_new + int(rng.randint(0, 4)) if heavy
                   else int(rng.randint(2, 5)))
            samp = (SamplingParams() if k % 2 else
                    SamplingParams(temperature=1.0, top_k=40))
            return lambda: Request(uid=f"q{k}", prompt=list(prompt),
                                   max_new_tokens=new, sampling=samp)

        return _poisson_burst_trace(
            rng, ticks=ticks, base_rate=rate, make_request=make,
            burst_start=ticks // 3, burst_end=2 * ticks // 3,
            burst_factor=2)

    def drive(router, trace, kill_at=None, kill_idx=None):
        """Tick through the trace; per-uid submit/first-token ticks
        via the stream feed. Interference probe per tick: a replica
        that both chunked a prefill and stepped decode lanes charged
        that chunk's latency to the residents (chunk-over-decode).
        Returns (ttft, accepted, contended_ticks, chunks_by_rep,
        wall_s)."""
        submit, first, accepted = {}, {}, []
        contended = 0
        t0 = time.perf_counter()
        i = tick = 0

        def counters():
            out = {}
            for idx, rep in enumerate(router.replicas):
                if rep.alive and rep.engine is not None:
                    s = rep.engine.stats()
                    out[idx] = (int(s["num_prefill_chunks"]),
                                int(s["num_decode_steps"]))
            return out

        before = counters()
        while i < len(trace) or router.has_work:
            while i < len(trace) and trace[i][0] <= tick:
                req = trace[i][1]()
                if router.try_add(req):
                    submit[req.uid] = tick
                    accepted.append(req.uid)
                i += 1
            if (kill_at is not None and tick == kill_at
                    and router.replicas[kill_idx].alive):
                router.kill_replica(kill_idx)
            router.step()
            after = counters()
            for idx in after:
                b = before.get(idx, (0, 0))
                if (after[idx][0] > b[0] and after[idx][1] > b[1]):
                    contended += 1
            before = after
            for uid, tok, last in router.pop_stream_events():
                if tok >= 0 and uid not in first and uid in submit:
                    first[uid] = tick
            tick += 1
        wall = time.perf_counter() - t0
        chunks = {idx: c for idx, (c, _) in before.items()}
        ttft = {u: first[u] - submit[u] for u in first}
        return ttft, accepted, contended, chunks, wall

    def pct(xs, q):
        return percentile(xs, q) if xs else 0.0

    def goodput(res, wall):
        return sum(len(r.tokens) for r in res.values()
                   if r.status == "finished") / max(wall, 1e-9)

    # -- phase 1: the colocated baseline (2 role-less replicas) --
    trace = make_trace()
    colo = FleetRouter(model, params, EngineConfig(**ekw),
                       FleetConfig(num_replicas=2))
    ttft_colo, acc_colo, contended_colo, _, wall_colo = drive(
        colo, trace)
    colo_res = colo.run(return_status=True)
    colo_stats = colo.stats()
    assert not (set(acc_colo) - set(colo_res)), "colocated lost requests"
    assert colo_stats["num_lost_requests"] == 0
    p99_colo = pct(list(ttft_colo.values()), 99)
    good_colo = goodput(colo_res, wall_colo)

    # -- phase 2: the same trace, disaggregated at equal device
    # count ({1 prefill + 1 decode} vs the 2 colocated) --
    disagg = FleetRouter(model, params, EngineConfig(**ekw),
                         FleetConfig(num_replicas=2,
                                     replica_roles=("prefill",
                                                    "decode")))
    ttft_dis, acc_dis, contended_dis, chunks_dis, wall_dis = drive(
        disagg, trace)
    dis_res = disagg.run(return_status=True)
    dis_stats = disagg.stats()
    assert not (set(acc_dis) - set(dis_res)), "disagg lost requests"
    assert dis_stats["num_lost_requests"] == 0
    assert dis_stats["num_handoffs"] >= 1, "no handoff sweep fired"
    assert dis_stats["num_handoff_requests"] >= 1
    assert dis_stats["num_handoff_bytes"] > 0
    decode_rows = {idx: dis_stats["replicas"][str(idx)]
                   for idx in chunks_dis
                   if dis_stats["replicas"][str(idx)]["role"]
                   == "decode"}
    decode_chunks = sum(chunks_dis[idx] for idx in decode_rows)
    decode_imports = sum(int(r["num_migrated_in"])
                         for r in decode_rows.values())
    # a decode specialist never prefills a FRESH prompt: its only
    # chunks are the sub-block tail resumes of handed-off requests
    # (the prefix-cache transport moves full blocks; the tail is
    # shorter than one chunk), so chunks are bounded by imports
    assert decode_chunks <= decode_imports, (
        f"decode specialists ran {decode_chunks} prefill chunks for "
        f"only {decode_imports} handoff imports — fresh prompts "
        f"leaked onto the decode pool")
    p99_dis = pct(list(ttft_dis.values()), 99)
    good_dis = goodput(dis_res, wall_dis)
    # the headline ordering: specialist prefill lanes recycle at the
    # handoff instead of being held hostage through a long decode
    assert p99_dis < p99_colo, (
        f"disaggregated TTFT p99 {p99_dis} ticks did not beat "
        f"colocated {p99_colo}")

    # -- phase 3: the prefill specialist hard-killed mid-trace --
    chaos = FleetRouter(model, params, EngineConfig(**ekw),
                        FleetConfig(num_replicas=2,
                                    replica_roles=("prefill",
                                                   "decode")))
    _, acc_kill, _, _, _ = drive(chaos, trace, kill_at=kill_tick,
                                 kill_idx=0)
    kill_res = chaos.run(return_status=True)
    kill_stats = chaos.stats()
    missing = set(acc_kill) - set(kill_res)
    assert not missing, f"lost accepted requests: {sorted(missing)}"
    assert kill_stats["num_lost_requests"] == 0
    assert kill_stats["num_failovers"] >= 1, "the kill never fired"
    for rep in chaos.replicas:
        if rep.alive and rep.engine is not None:
            rep.engine.check_allocator_integrity()

    print(f"# serving disagg: colocated p99 TTFT {p99_colo:.0f} ticks "
          f"(chunk-over-decode {contended_colo} ticks), goodput "
          f"{good_colo:.1f} tok/s | disagg p99 {p99_dis:.0f} ticks "
          f"(contended {contended_dis}), goodput {good_dis:.1f} tok/s "
          f"| handoffs {dis_stats['num_handoffs']} sweeps / "
          f"{dis_stats['num_handoff_requests']} req / "
          f"{dis_stats['num_handoff_bytes']} B, probes skipped "
          f"{dis_stats['num_affinity_probes_skipped']} | prefill-kill: "
          f"failovers {kill_stats['num_failovers']}, lost "
          f"{kill_stats['num_lost_requests']}", file=sys.stderr)
    return {
        "metric": "serving_tiny_disagg_ttft_p99_ticks",
        "value": round(float(p99_dis), 2),
        "unit": "ticks",
        # the disaggregation win: specialist TTFT p99 over colocated
        # TTFT p99 on the interference trace (< 1 = disagg pays)
        "vs_baseline": round(float(p99_dis) / max(float(p99_colo),
                                                  1e-9), 4),
        "colocated_ttft_p99_ticks": round(float(p99_colo), 2),
        "colocated_goodput_tok_per_sec": round(good_colo, 3),
        "disagg_goodput_tok_per_sec": round(good_dis, 3),
        "colocated_chunk_over_decode_ticks": int(contended_colo),
        "disagg_chunk_over_decode_ticks": int(contended_dis),
        "decode_specialist_prefill_chunks": int(decode_chunks),
        "decode_specialist_imports": int(decode_imports),
        "num_offered": len(trace),
        "num_accepted_colocated": len(acc_colo),
        "num_accepted_disagg": len(acc_dis),
        "num_handoffs": int(dis_stats["num_handoffs"]),
        "num_handoff_requests": int(dis_stats["num_handoff_requests"]),
        "num_handoff_bytes": int(dis_stats["num_handoff_bytes"]),
        "num_affinity_probes_skipped":
            int(dis_stats["num_affinity_probes_skipped"]),
        "kill_num_failovers": int(kill_stats["num_failovers"]),
        "kill_num_lost_requests":
            int(kill_stats["num_lost_requests"]),
        "zero_lost": True,
        "status_counts": {
            s: sum(r.status == s for r in dis_res.values())
            for s in {r.status for r in dis_res.values()}},
        "allocator_integrity_ok": True,
    }


def bench_serving_shared_prefix(fast=False):
    """Fleet-global shared prefix tier arm (round 18, docs/fleet.md
    "Shared prefix tier"): one router-owned, refcount-deduped,
    byte-budgeted KV tier vs per-replica spill at EQUAL device count
    and EQUAL total spill bytes, on an affinity-blind shared-prefix
    trace built to expose what private tiers cannot hold — an ODD
    number of rotating shared prefixes (odd so paired placement can't
    accidentally partition them by replica parity: BOTH replicas see
    EVERY prefix, the affinity-blind worst case) whose deduped
    working set fits the shared budget while the duplicated
    per-replica demand overflows each local LRU.

    Three phases: (1) per-replica baseline — 2 replicas with
    ``affinity_weight=0`` and the whole byte budget split into two
    local spill tiers, each smaller than the full prefix set it must
    hold privately, so steady state keeps missing; (2) the SAME trace
    on the shared arm — local tiers just big enough to land a seeded
    run, the rest of the budget as ``shared_prefix_bytes`` holding
    the DEDUPED set once — asserting the fleet-wide prefix hit rate
    ((hit+spilled-in blocks)/looked-up blocks, summed over replicas)
    BEATS the per-replica arm, steady-state TTFT p99 (scheduler
    ticks, cold warmup excluded) strictly improves, publishes/dedupe/
    hits all moved, and outputs are token-identical across arms (the
    tier is an optimization, never a token source; the trace is
    greedy so each prefix's generated suffix chain dedupes too —
    sampled/int8/spec coverage lives in tests/test_shared_prefix.py);
    (3) chaos — a replica is hard-killed mid-trace with the tier on:
    failover must finish every accepted request with
    ``num_lost_requests == 0`` (the shared tier holds no request
    state, only re-derivable KV bytes). ``vs_baseline`` is
    per-replica hit rate / shared hit rate (< 1 = the shared tier
    pays). ``fast=True`` is the tier-1 smoke shape."""
    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.observability import percentile
    from apex_tpu.serving import (EngineConfig, FleetConfig, FleetRouter,
                                  Request, SamplingParams)

    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    # a SMALL device pool: prefix blocks must be evicted into the
    # spill tiers for either arm to have anything to serve
    ekw = dict(max_batch=2, block_size=4, num_blocks=8,
               max_prefill_len=8, max_seq_len=32,
               enable_prefix_caching=True, snapshot_interval_ticks=2,
               max_waiting=64, seed=11)
    # one 4-token block of fp32 K+V under GPTConfig.tiny (n_embd=128):
    # 2 * 4 * 128 * 4 B — the unit both arms' byte budgets are set in
    blk = 4096
    npref = 7          # ODD (see docstring); 7-block (28-token) heads
    n_reqs = 28 if fast else 56   # 4 / 8 visits per prefix
    kill_pair = 4 if fast else 10
    # EQUAL total spill bytes. Each finished sequence is 8 blocks (28
    # prompt + 4 generated), so the deduped greedy working set is
    # 7 x 8 = 56 blocks. Shared arm: 8-block local tiers (a seeded
    # 7-block run must FIT the landing tier or the import evicts its
    # own head) + a 60-block shared tier holding the set once.
    # Per-replica arm: the same 76-block total split into two 38-block
    # local tiers — each replica needs all 56 blocks privately, so
    # its LRU cycles and steady state keeps missing.
    local_small, shared_bytes = 8 * blk, 60 * blk
    per_replica_local = (2 * local_small + shared_bytes) // 2
    model = GPTLMHeadModel(cfg)
    # FIXED seeds (not _SALT): the arm asserts a hit-rate ORDERING
    # between two fleets on one trace — the trace must be the same
    # every round or the assert flakes
    init_rng = np.random.RandomState(1712)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(init_rng.randint(0, cfg.vocab_size, (1, 8))))

    def make_trace():
        rng = np.random.RandomState(1713)
        prefixes = [list(rng.randint(0, cfg.vocab_size, 28))
                    for _ in range(npref)]

        def make(k):
            prompt = prefixes[k % npref]
            return lambda: Request(uid=f"q{k}", prompt=list(prompt),
                                   max_new_tokens=4,
                                   sampling=SamplingParams())

        return [make(k) for k in range(n_reqs)]

    def drive(router, trace, kill_pair_at=None, kill_idx=None):
        """Submit in PAIRS (backlog spreads a pair across replicas —
        load-only ties would otherwise pile onto slot 0), DRAINING
        between pairs: publishes need device churn to evict blocks
        into the tiers before the next placement probes them, and a
        drained queue keeps the placement-time shared-tier seed
        adjacent to its admission. Per-uid submit/first-token ticks
        via the stream feed."""
        submit, first, accepted = {}, {}, []
        t0 = time.perf_counter()
        tick = 0
        for i in range(0, len(trace), 2):
            if (kill_pair_at is not None and i // 2 == kill_pair_at
                    and router.replicas[kill_idx].alive):
                router.kill_replica(kill_idx)
            for k in (i, i + 1):
                if k < len(trace):
                    req = trace[k]()
                    if router.try_add(req):
                        submit[req.uid] = tick
                        accepted.append(req.uid)
            while router.has_work:
                router.step()
                for uid, tok, _last in router.pop_stream_events():
                    if tok >= 0 and uid not in first and uid in submit:
                        first[uid] = tick
                tick += 1
        wall = time.perf_counter() - t0
        ttft = {u: first[u] - submit[u] for u in first}
        return ttft, accepted, wall

    def fleet_hit_rate(router):
        """(prefix hits + spill/shared re-admissions) / lookups, in
        BLOCKS, summed over alive replicas — shared-tier seeds land
        in the chosen replica's local spill and re-admit through the
        same upload path, so ``spill_hits`` is the one re-admission
        unit both arms share."""
        hit = lookups = 0
        for rep in router.replicas:
            if rep.alive and rep.engine is not None:
                s = rep.engine.stats()
                hit += int(s["prefix_hit_blocks"]) + int(s["spill_hits"])
                lookups += int(s["prefix_lookup_blocks"])
        return hit / max(lookups, 1)

    def pct(xs, q):
        return percentile(xs, q) if xs else 0.0

    def steady(ttft):
        # the steady-state window: the trace's second half, every
        # prefix long since first-seen — cold compulsory misses
        # (identical in both arms) would otherwise drown the tail
        return [ttft[f"q{k}"] for k in range(n_reqs // 2, n_reqs)
                if f"q{k}" in ttft]

    # -- phase 1: per-replica baseline (whole budget split local) --
    trace = make_trace()
    perrep = FleetRouter(
        model, params,
        EngineConfig(spill_max_bytes=per_replica_local, **ekw),
        FleetConfig(num_replicas=2, affinity_weight=0.0))
    ttft_pr, acc_pr, wall_pr = drive(perrep, trace)
    pr_res = perrep.run(return_status=True)
    pr_stats = perrep.stats()
    rate_pr = fleet_hit_rate(perrep)
    assert not (set(acc_pr) - set(pr_res)), "per-replica arm lost requests"
    assert pr_stats["num_lost_requests"] == 0
    p99_pr = pct(steady(ttft_pr), 99)

    # -- phase 2: the same trace, shared tier at equal total bytes --
    shared = FleetRouter(
        model, params,
        EngineConfig(spill_max_bytes=local_small, **ekw),
        FleetConfig(num_replicas=2, affinity_weight=0.0,
                    shared_prefix_bytes=shared_bytes))
    ttft_sh, acc_sh, wall_sh = drive(shared, trace)
    sh_res = shared.run(return_status=True)
    sh_stats = shared.stats()
    rate_sh = fleet_hit_rate(shared)
    assert not (set(acc_sh) - set(sh_res)), "shared arm lost requests"
    assert sh_stats["num_lost_requests"] == 0
    assert sh_stats["num_shared_publishes"] >= 1, "nothing published"
    assert sh_stats["num_shared_dedupe"] >= 1, (
        "no dedupe: both replicas' evictions of one prefix should "
        "collide in the shared tier")
    assert sh_stats["shared_tier_hits"] >= 1, "no shared-tier hit"
    # the tier is an optimization, never a token source: both arms
    # produce the SAME tokens for every request
    assert set(pr_res) == set(sh_res)
    for uid in pr_res:
        assert list(pr_res[uid].tokens) == list(sh_res[uid].tokens), (
            f"{uid}: shared-tier tokens diverged from per-replica")
    p99_sh = pct(steady(ttft_sh), 99)
    # the headline ordering: ONE deduped copy reachable by every
    # replica beats N private copies that each overflow
    assert rate_sh > rate_pr, (
        f"shared-tier fleet hit rate {rate_sh:.3f} did not beat "
        f"per-replica {rate_pr:.3f} at equal total spill bytes")
    assert p99_sh < p99_pr, (
        f"steady-state TTFT p99 {p99_sh} ticks (shared) did not beat "
        f"per-replica {p99_pr}")

    # -- phase 3: a replica hard-killed mid-trace, tier on --
    chaos = FleetRouter(
        model, params,
        EngineConfig(spill_max_bytes=local_small, **ekw),
        FleetConfig(num_replicas=2, affinity_weight=0.0,
                    shared_prefix_bytes=shared_bytes, respawn=True))
    _, acc_kill, _ = drive(chaos, trace, kill_pair_at=kill_pair,
                           kill_idx=0)
    kill_res = chaos.run(return_status=True)
    kill_stats = chaos.stats()
    missing = set(acc_kill) - set(kill_res)
    assert not missing, f"lost accepted requests: {sorted(missing)}"
    assert kill_stats["num_lost_requests"] == 0
    assert kill_stats["num_failovers"] >= 1, "the kill never fired"
    for rep in chaos.replicas:
        if rep.alive and rep.engine is not None:
            rep.engine.check_allocator_integrity()

    print(f"# serving shared prefix: per-replica hit rate "
          f"{rate_pr:.3f} (steady p99 TTFT {p99_pr:.0f} ticks) | "
          f"shared {rate_sh:.3f} (steady p99 {p99_sh:.0f}), "
          f"{sh_stats['num_shared_publishes']} published / "
          f"{sh_stats['num_shared_dedupe']} deduped / "
          f"{sh_stats['shared_tier_hits']} hits / "
          f"{sh_stats['num_shared_evictions']} evictions, tier "
          f"{sh_stats['shared_tier_blocks']} blocks "
          f"{sh_stats['shared_tier_bytes']} B | kill: failovers "
          f"{kill_stats['num_failovers']}, lost "
          f"{kill_stats['num_lost_requests']}", file=sys.stderr)
    return {
        "metric": "serving_tiny_shared_prefix_fleet_hit_rate",
        "value": round(float(rate_sh), 4),
        "unit": "hit_fraction",
        # the dedupe win: per-replica hit rate over shared hit rate
        # at equal total spill bytes (< 1 = the shared tier pays)
        "vs_baseline": round(float(rate_pr) / max(float(rate_sh),
                                                  1e-9), 4),
        "per_replica_hit_rate": round(float(rate_pr), 4),
        "shared_steady_ttft_p99_ticks": round(float(p99_sh), 2),
        "per_replica_steady_ttft_p99_ticks": round(float(p99_pr), 2),
        "total_spill_bytes_per_arm": 2 * local_small + shared_bytes,
        "num_offered": len(trace),
        "num_accepted_shared": len(acc_sh),
        "num_shared_publishes": int(sh_stats["num_shared_publishes"]),
        "num_shared_dedupe": int(sh_stats["num_shared_dedupe"]),
        "shared_tier_hits": int(sh_stats["shared_tier_hits"]),
        "num_shared_evictions": int(sh_stats["num_shared_evictions"]),
        "shared_tier_blocks": int(sh_stats["shared_tier_blocks"]),
        "shared_tier_bytes": int(sh_stats["shared_tier_bytes"]),
        "tokens_identical_across_arms": True,
        "kill_num_failovers": int(kill_stats["num_failovers"]),
        "kill_num_lost_requests": int(kill_stats["num_lost_requests"]),
        "zero_lost": True,
        "status_counts": {
            s: sum(r.status == s for r in sh_res.values())
            for s in {r.status for r in sh_res.values()}},
        "allocator_integrity_ok": True,
    }


def bench_obs_pipeline(fast=False):
    """Observability pipeline certification (docs/observability.md):
    drive a small engine with the full observer attached (tracer +
    flight recorder + metrics), write the dump, and run
    tools/trace_summary.py over it end to end — so the post-mortem
    tooling a dead round depends on is proven by every smoke run, not
    first exercised at the incident. Also re-certifies the
    zero-perturbation contract on this workload: the observed engine's
    outputs must be bit-identical to an unobserved twin's. Value =
    requests summarized; the section FAILS if the dump does not
    round-trip, the summary misses a request, or bit-identity breaks."""
    import importlib.util
    import os as _os
    import tempfile

    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.observability import Observability
    from apex_tpu.serving import (EngineConfig, InferenceEngine, Request,
                                  SamplingParams)

    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    rng = np.random.RandomState(_SALT + 7)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 8))))
    # a pool tight enough to preempt, so the trace exercises the
    # requeue/resume path too
    ekw = dict(max_batch=3, block_size=8, num_blocks=6,
               max_prefill_len=8, max_seq_len=32, seed=3)
    n_req = 3 if fast else 5
    reqs = [Request(uid=f"o{i}",
                    prompt=list(rng.randint(0, cfg.vocab_size, 6 + i)),
                    max_new_tokens=12,
                    sampling=(SamplingParams(temperature=1.0, top_k=16)
                              if i % 2 else SamplingParams()))
            for i in range(n_req)]

    def serve(obs):
        # request objects are reusable across engines: add_request
        # starts a fresh lifecycle (resets the engine-owned status)
        eng = InferenceEngine(model, params, EngineConfig(**ekw),
                              obs=obs)
        for r in reqs:
            eng.add_request(r)
        return eng.run(return_status=True)

    t0 = time.perf_counter()
    plain = serve(None)
    obs = Observability()
    observed = serve(obs)
    identical = ({u: (tuple(r.tokens), r.status)
                  for u, r in plain.items()}
                 == {u: (tuple(r.tokens), r.status)
                     for u, r in observed.items()})
    if not identical:
        raise AssertionError(
            "observability perturbed engine output (tracing on != off)")

    with tempfile.TemporaryDirectory() as td:
        dump_path = obs.dump_to(_os.path.join(td, "dump.json"))
        spec = importlib.util.spec_from_file_location(
            "_trace_summary",
            _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                          "tools", "trace_summary.py"))
        ts = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(ts)
        report = ts.summarize_file(dump_path)
    dt = time.perf_counter() - t0
    missing = [r.uid for r in reqs if f"{r.uid}:" not in report]
    if missing:
        raise AssertionError(
            f"trace summary missed requests {missing}:\n{report}")
    deep = obs.deep_stats()
    print("# obs pipeline: " + report.splitlines()[1]
          + f" | bit-identical {identical}", file=sys.stderr)
    return {
        "metric": "obs_pipeline_smoke_requests_summarized",
        "value": n_req,
        "unit": "requests",
        "vs_baseline": 1.0,
        "bit_identical_with_observer": bool(identical),
        "trace_events": int(deep["trace_events"]),
        "recorder_events": int(deep["recorder_events"]),
        "ttft_observed": int(deep["metrics"]["serving_ttft_s"]["count"]),
        "summary_lines": len(report.splitlines()),
        "wall_s": round(dt, 3),
    }


def main():
    on_tpu = _backend_with_cpu_fallback() == "tpu"
    if "--smoke" in sys.argv:
        # tier-1 guard mode (tests/test_train_step.py): every section in
        # its fastest shape, one JSON line each, rc != 0 if ANY section
        # dies — so a change that would blank a future bench round
        # (BENCH_r01/r05: rc=1, parsed: null) fails CI instead of
        # surfacing months later in a lost perf round.
        failed = []
        for name, fn in (
            ("bench_layer_norm", lambda: bench_layer_norm(fast=True)),
            ("bench_fused_lamb", lambda: bench_fused_lamb(fast=True)),
            ("bench_ddp_scaling", bench_ddp_scaling),
            ("bench_serving", lambda: bench_serving(fast=True)),
            ("bench_serving_multistep",
             lambda: bench_serving_multistep(fast=True)),
            ("bench_serving_speculative",
             lambda: bench_serving_speculative(fast=True)),
            ("bench_serving_overload",
             lambda: bench_serving_overload(fast=True)),
            ("bench_serving_multitenant",
             lambda: bench_serving_multitenant(fast=True)),
            ("bench_serving_kv_memory",
             lambda: bench_serving_kv_memory(fast=True)),
            ("bench_weight_quant",
             lambda: bench_weight_quant(fast=True)),
            ("bench_serving_fleet",
             lambda: bench_serving_fleet(fast=True)),
            ("bench_serving_integrity",
             lambda: bench_serving_integrity(fast=True)),
            ("bench_serving_mesh",
             lambda: bench_serving_mesh(fast=True)),
            ("bench_serving_process",
             lambda: bench_serving_process(fast=True)),
            ("bench_serving_disagg",
             lambda: bench_serving_disagg(fast=True)),
            ("bench_serving_shared_prefix",
             lambda: bench_serving_shared_prefix(fast=True)),
            ("bench_train_step", lambda: bench_train_step(fast=True)),
            ("bench_train_sharded",
             lambda: bench_train_sharded(fast=True)),
            ("bench_obs_pipeline", lambda: bench_obs_pipeline(fast=True)),
        ):
            if not _run_section(name, fn, retries=0):
                failed.append(name)
            _reset()
        if failed:
            print(f"# --smoke: {len(failed)} section(s) failed: "
                  f"{failed}", file=sys.stderr)
            sys.exit(1)
        return
    # Headline: the BASELINE seq-512-class pretraining shape. With the
    # logsumexp MLM loss, B=16 WITHOUT per-layer remat fits the 16 GB
    # chip and beats every remat'd batch (no recompute tax). Round-4
    # re-sweep (marginal timing, same session): B=20 no-remat now TIES
    # B=16 (107.7 vs 105.4 samples/s — round 3 had it 7% behind), and
    # the gathered MLM tail frees enough activation memory that B=24
    # and B=32 now FIT no-remat — but run SLOWER per sample (99.9 /
    # 101.9 samples/s). B=16 stays the recorded peak. The fp32
    # baseline keeps remat (its fp32 activations would not fit
    # otherwise).
    batch, seq = (16, 512) if on_tpu else (2, 32)
    # one retry: a transient tunnel drop mid-headline (compile-service
    # restarts were observed in round 5) must not zero out the whole
    # recorded round
    t_headline = time.perf_counter()
    for attempt in (0, 1):
        try:
            dt_opt, dt_base, mfu = _measure(batch, seq, iters=8,
                                            remat=not on_tpu)
            break
        except Exception as e:
            if attempt:
                # the record of the death IS the artifact here: the
                # re-raise kills the run, so write the section line first
                _emit_section_record("headline", "failed",
                                     time.perf_counter() - t_headline,
                                     error=f"{type(e).__name__}: {e}")
                raise
            print(f"# headline attempt 0 failed ({e}); retrying",
                  file=sys.stderr)
            _reset()
    if on_tpu and "--all-shapes" in sys.argv:
        # secondary shape for comparison with earlier rounds' S=128 runs
        # (off by default: each extra config costs a slow fresh compile
        # and the driver runs this file under a time budget)
        _measure(64, 128, iters=6, with_baseline=False)

    result = {
        "metric": ("bert_large_pretrain_s512_samples_per_sec_per_chip"
                   if on_tpu else "bert_tiny_smoke_samples_per_sec"),
        "value": round(batch / dt_opt, 3),
        "unit": "samples/sec",
        "vs_baseline": round(dt_base / dt_opt, 3),
    }
    _print_record(result)
    _emit_section_record("headline", "ok",
                         time.perf_counter() - t_headline)
    # BASELINE configs[1]-[3] + the serving section (round 6) + the
    # long-context attention record (S=4096 on TPU by default; add
    # S=2048 with --long-context)
    secondary = [bench_layer_norm, bench_fused_lamb, bench_ddp_scaling,
                 bench_serving, bench_serving_multistep,
                 bench_serving_speculative, bench_serving_overload,
                 bench_serving_multitenant, bench_serving_kv_memory,
                 bench_weight_quant,
                 bench_serving_fleet, bench_serving_integrity,
                 bench_serving_mesh, bench_serving_process,
                 bench_serving_disagg, bench_serving_shared_prefix,
                 bench_train_step, bench_train_sharded,
                 bench_obs_pipeline]
    if on_tpu:
        secondary.append(bench_scaled_masked_softmax)
        secondary.append(bench_long_context)

        def bench_long_context_s8192():
            # S=8192 row (round 5): the composed baseline's (1,16,S,S)
            # fp32 score tensor is ~4 GB here — the shape where the
            # flash kernel's O(S*D) memory stops being a luxury
            return bench_long_context(seq=8192)
        secondary.append(bench_long_context_s8192)
        if "--long-context" in sys.argv:
            def bench_long_context_s2048():
                return bench_long_context(seq=2048)
            secondary.append(bench_long_context_s2048)
    _reset()
    for bench_fn in secondary:
        # one retry: the remote-compile tunnel occasionally drops a
        # response mid-read; a secondary metric must not kill the run,
        # and its death must leave a "failed" section record
        _run_section(bench_fn.__name__, bench_fn, retries=1)
        _reset()
    # the round-13 comparer, finally closing its own loop: diff THIS
    # run against the newest recorded round (report only, stderr)
    _print_bench_diff_report()


if __name__ == "__main__":
    main()
