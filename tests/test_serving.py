"""apex_tpu.serving tests (tier-1, CPU): paged KV-cache correctness,
decode parity vs the full-sequence forward, continuous batching with
staggered arrivals/EOS under the two-program compilation contract, and
sampling determinism. (The old tp=2 shard_map decode smoke folded into
the mesh matrix — tests/test_mesh_serving.py.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.serving import (
    BlockAllocator,
    CacheOutOfBlocks,
    EngineConfig,
    InferenceEngine,
    KVCache,
    Request,
    SamplingParams,
    blocks_needed,
    defragment,
    device_block_table,
    gather_kv,
    paged_write,
    sample_tokens,
)


def _tiny_model(**kw):
    kw.setdefault("dropout", 0.0)
    kw.setdefault("remat", False)
    cfg = GPTConfig.tiny(**kw)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return cfg, model, params


def _ids(B, S, vocab=128, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(0, vocab, (B, S)))


# ---------------------------------------------------------------------------
# block allocator + paged write/read primitives
# ---------------------------------------------------------------------------

def test_block_allocator_alloc_free_defrag_accounting():
    a = BlockAllocator(8)
    assert a.num_free == 8 and a.num_used == 0
    first = a.alloc(3)
    assert sorted(first) == [0, 1, 2]      # low ids served first
    assert a.num_used == 3
    assert a.utilization == pytest.approx(3 / 8)
    a.free([first[1]])
    assert a.num_free == 6
    with pytest.raises(ValueError, match="double free"):
        a.free([first[0], first[0]])
    with pytest.raises(CacheOutOfBlocks):
        a.alloc(100)
    assert blocks_needed(17, 8) == 3 and blocks_needed(16, 8) == 2


def test_paged_write_and_gather_roundtrip():
    """Tokens written through a (deliberately scrambled) block table must
    come back in position order; invalid positions must write nothing."""
    L, N, bs, H, D = 2, 6, 4, 2, 3
    cache = KVCache.create(L, N, bs, H, D, dtype=jnp.float32)
    B, S = 2, 10   # spans 3 blocks per sequence
    rng = np.random.RandomState(0)
    vals = jnp.asarray(rng.randn(B, S, H, D).astype("f4"))
    tables = np.array([[5, 0, 3, -1], [2, 4, 1, -1]], np.int32)
    dtbl = device_block_table(tables, N)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    seq_lens = jnp.asarray([10, 7], jnp.int32)   # row 1: tail is padding
    valid = pos < seq_lens[:, None]
    k = paged_write(cache.k, 1, dtbl, pos, vals, valid)

    out = gather_kv(k, 1, dtbl)                  # [B, 4*bs, H, D]
    np.testing.assert_array_equal(np.asarray(out[0, :10]),
                                  np.asarray(vals[0]))
    np.testing.assert_array_equal(np.asarray(out[1, :7]),
                                  np.asarray(vals[1, :7]))
    # the padding positions of row 1 were dropped, not written
    np.testing.assert_array_equal(np.asarray(out[1, 7:10]),
                                  np.zeros((3, H, D), np.float32))
    # layer 0 untouched
    assert float(jnp.max(jnp.abs(k[0]))) == 0.0


def test_defragment_compacts_and_preserves_contents():
    L, N, bs, H, D = 1, 16, 4, 2, 2
    cache = KVCache.create(L, N, bs, H, D, dtype=jnp.float32)
    alloc = BlockAllocator(N)
    rng = np.random.RandomState(1)
    tables = np.full((2, 4), -1, np.int32)
    # interleave allocations from two sequences, then free a third to
    # checkerboard the pool
    other = alloc.alloc(2)
    tables[0, :2] = alloc.alloc(2)
    tables[1, :3] = alloc.alloc(3)
    alloc.free(other)
    vals = [jnp.asarray(rng.randn(1, 8, H, D).astype("f4")),
            jnp.asarray(rng.randn(1, 12, H, D).astype("f4"))]
    for b, (n_tok, v) in enumerate([(8, vals[0]), (12, vals[1])]):
        pos = jnp.arange(n_tok, dtype=jnp.int32)[None]
        k = paged_write(cache.k, 0, device_block_table(tables[b:b + 1], N),
                        pos, v, jnp.ones((1, n_tok), bool))
        cache = cache._replace(k=k)

    before = [np.asarray(gather_kv(cache.k, 0,
                                   device_block_table(tables[b:b + 1], N)))
              for b in range(2)]
    cache2, tables2 = defragment(cache, alloc, tables)
    # live blocks now occupy the low indices, free list is the tail
    assert set(tables2[tables2 >= 0].ravel()) == set(range(5))
    assert alloc.num_free == N - 5
    for b in range(2):
        after = np.asarray(gather_kv(
            cache2.k, 0, device_block_table(tables2[b:b + 1], N)))
        np.testing.assert_array_equal(after, before[b])
    # and the pool still allocates from the compacted tail
    assert sorted(alloc.alloc(2)) == [5, 6]


def test_kv_dtype_follows_amp_policy():
    from apex_tpu.amp import _amp_state
    from apex_tpu.serving import default_kv_dtype

    saved = _amp_state._amp_state.handle
    try:
        _amp_state._amp_state.handle = None
        assert default_kv_dtype() == jnp.dtype(jnp.float32)
        assert default_kv_dtype(jnp.bfloat16) == jnp.dtype(jnp.bfloat16)

        import apex_tpu.amp as amp
        from apex_tpu.optimizers import FusedAdam

        params = {"w": jnp.ones((4, 4), jnp.float32)}
        _, _, handle = amp.initialize(params, FusedAdam(), opt_level="O2",
                                      verbosity=0)
        assert default_kv_dtype() == jnp.dtype(jnp.bfloat16)
        # explicit dtype overrides the policy
        assert default_kv_dtype(jnp.float32) == jnp.dtype(jnp.float32)
        cache = KVCache.create(1, 2, 4, 2, 2)
        assert cache.k.dtype == jnp.bfloat16
    finally:
        _amp_state._amp_state.handle = saved


# ---------------------------------------------------------------------------
# decode parity vs the full-sequence forward (acceptance criterion)
# ---------------------------------------------------------------------------

def test_decode_with_paged_cache_matches_full_forward():
    """Prefill + one-token-at-a-time decode through the paged cache must
    reproduce the full-sequence forward's logits to <= 1e-5 (fp32,
    2-layer GPT) — including ragged prompts (per-row padding)."""
    cfg, model, params = _tiny_model()
    B, S, pre = 2, 24, 16
    ids = _ids(B, S)
    ref = model.apply(params, ids)

    N, bs = 32, 8
    cache = KVCache.create(cfg.num_layers, N, bs, cfg.num_heads,
                           cfg.hidden_size // cfg.num_heads,
                           dtype=jnp.float32)
    alloc = BlockAllocator(N)
    tables = np.full((B, 8), -1, np.int32)
    for b in range(B):
        tables[b, :blocks_needed(S, bs)] = alloc.alloc(blocks_needed(S, bs))
    dtbl = device_block_table(tables, N)

    pos = jnp.broadcast_to(jnp.arange(pre, dtype=jnp.int32)[None], (B, pre))
    logits, cache = model.apply(
        params, ids[:, :pre], kv_cache=cache, block_tables=dtbl,
        cache_positions=pos, seq_lens=jnp.full((B,), pre, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref[:, :pre]),
                               atol=1e-5, rtol=0)

    for t in range(pre, S):
        step, cache = model.apply(
            params, ids[:, t:t + 1], kv_cache=cache, block_tables=dtbl,
            cache_positions=jnp.full((B, 1), t, jnp.int32),
            seq_lens=jnp.full((B,), t + 1, jnp.int32))
        np.testing.assert_allclose(np.asarray(step[:, 0]),
                                   np.asarray(ref[:, t]),
                                   atol=1e-5, rtol=0)


def test_ragged_prefill_masks_padding():
    """A right-padded prefill batch must produce, at each row's true
    positions, the logits of that row's unpadded forward."""
    cfg, model, params = _tiny_model()
    lens = [5, 11]
    P = 16
    ids = _ids(2, P, seed=3)
    N, bs = 16, 4
    cache = KVCache.create(cfg.num_layers, N, bs, cfg.num_heads,
                           cfg.hidden_size // cfg.num_heads,
                           dtype=jnp.float32)
    alloc = BlockAllocator(N)
    tables = np.full((2, 4), -1, np.int32)
    for b, n in enumerate(lens):
        tables[b, :blocks_needed(n, bs)] = alloc.alloc(blocks_needed(n, bs))
    pos = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None], (2, P))
    logits, _ = model.apply(
        params, ids, kv_cache=cache,
        block_tables=device_block_table(tables, N),
        cache_positions=pos, seq_lens=jnp.asarray(lens, jnp.int32))
    for b, n in enumerate(lens):
        solo = model.apply(params, ids[b:b + 1, :n])
        np.testing.assert_allclose(np.asarray(logits[b, :n]),
                                   np.asarray(solo[0]), atol=1e-5, rtol=0)


# ---------------------------------------------------------------------------
# continuous batching engine (acceptance criterion: 8 staggered requests,
# exactly two jit compilations)
# ---------------------------------------------------------------------------

def _build_engine(seed=0, **cfg_kw):
    cfg, model, params = _tiny_model()
    ecfg = EngineConfig(max_batch=4, block_size=8, num_blocks=64,
                        max_prefill_len=16, max_seq_len=64, seed=seed,
                        **cfg_kw)
    return InferenceEngine(model, params, ecfg)


def _staggered_workload(engine):
    """8 requests: 4 up front, 2 scheduler ticks, 4 late arrivals —
    different prompt lengths, generation budgets, and samplers."""
    rng = np.random.RandomState(7)
    reqs = []
    for i in range(8):
        samp = (SamplingParams() if i % 2 == 0 else
                SamplingParams(temperature=0.7, top_k=10, top_p=0.9))
        reqs.append(Request(uid=f"r{i}",
                            prompt=list(rng.randint(0, 128, 3 + i)),
                            max_new_tokens=2 + (i % 4) * 3,
                            sampling=samp))
    for r in reqs[:4]:
        engine.add_request(r)
    engine.step()
    engine.step()
    for r in reqs[4:]:
        engine.add_request(r)
    out = engine.run()
    return reqs, out


def test_continuous_batching_staggered_two_compilations():
    engine = _build_engine()
    reqs, out = _staggered_workload(engine)
    assert set(out) == {r.uid for r in reqs}
    for r in reqs:
        assert len(out[r.uid]) == r.max_new_tokens
        assert all(0 <= t < 128 for t in out[r.uid])
    stats = engine.stats()
    # THE two-program contract: one prefill shape, one decode shape
    assert stats["prefill_compilations"] == 1
    assert stats["decode_compilations"] == 1
    assert stats["num_prefills"] == 8
    # every slot and every block was handed back
    assert stats["active_slots"] == 0
    assert engine.allocator.num_used == 0


def test_engine_is_deterministic_under_a_fixed_seed():
    _, out1 = _staggered_workload(_build_engine(seed=123))
    _, out2 = _staggered_workload(_build_engine(seed=123))
    assert out1 == out2
    # and the sampled half actually depends on the seed
    _, out3 = _staggered_workload(_build_engine(seed=456))
    sampled = [f"r{i}" for i in range(8) if i % 2 == 1]
    assert any(out1[u] != out3[u] for u in sampled)


def test_engine_eos_evicts_early():
    """A request whose eos_token_id equals the token greedy decoding
    actually produces must stop at that token, well before its
    max_new_tokens budget."""
    prompt = list(np.random.RandomState(3).randint(0, 128, 6))
    pilot = _build_engine()
    pilot.add_request(Request(uid="p", prompt=prompt, max_new_tokens=8))
    first = pilot.run()["p"][0]

    engine = _build_engine()
    engine.add_request(Request(uid="q", prompt=prompt, max_new_tokens=8,
                               eos_token_id=int(first)))
    out = engine.run()["q"]
    assert out == [first]
    assert engine.allocator.num_used == 0


def test_engine_admission_control_and_validation():
    engine = _build_engine()
    # prompts longer than the prefill chunk are admissible now (chunked
    # prefill) — only the total budget is capped
    engine.add_request(Request(uid="long-ok", prompt=list(range(17)),
                               max_new_tokens=2))
    with pytest.raises(ValueError, match="max_seq_len"):
        engine.add_request(Request(uid="huge", prompt=[1] * 60))
    with pytest.raises(ValueError, match="max_seq_len"):
        engine.add_request(Request(uid="deep", prompt=[1] * 8,
                                   max_new_tokens=100))
    with pytest.raises(ValueError, match="empty prompt"):
        engine.add_request(Request(uid="empty", prompt=[]))
    with pytest.raises(ValueError, match="max_new_tokens"):
        engine.add_request(Request(uid="zero", prompt=[1],
                                   max_new_tokens=0))
    with pytest.raises(ValueError, match="top_p"):
        engine.add_request(Request(uid="bad", prompt=[1],
                                   sampling=SamplingParams(top_p=0.0)))
    out = engine.run()
    assert set(out) == {"long-ok"}


def test_engine_optimistic_admission_overcommits_and_preempts():
    """Two long-budget requests whose WORST cases together exceed the
    pool are now admitted together on current need (prompt blocks + 1);
    the resulting decode-time exhaustion preempts the youngest lane and
    both still finish with full-length, correct output."""
    cfg, model, params = _tiny_model()
    # pool of 5 blocks; worst case is 8+24=32 tokens -> 4 blocks each,
    # but current need at admission is just 1 prompt block (+1 headroom)
    engine = InferenceEngine(model, params, EngineConfig(
        max_batch=2, block_size=8, num_blocks=5, max_prefill_len=8,
        max_seq_len=32))
    for uid in ("a", "b"):
        engine.add_request(Request(uid=uid, prompt=[1, 2, 3, 4, 5, 6, 7, 8],
                                   max_new_tokens=24))
    engine.step()
    # the old worst-case reservation would have left "b" queued
    assert engine.stats()["active_slots"] == 2
    assert engine.stats()["waiting"] == 0
    out = engine.run()
    assert sorted(out) == ["a", "b"]
    assert all(len(v) == 24 for v in out.values())
    stats = engine.stats()
    assert stats["num_preemptions"] >= 1
    assert stats["prefill_compilations"] == 1
    assert stats["decode_compilations"] == 1
    assert engine.allocator.num_used == 0


def test_exact_fit_request_is_admitted_without_headroom():
    """A request whose whole generation lives inside its prompt's last
    partial block needs NO headroom block: a pool exactly the size of
    blocks_needed(prompt) must serve it (the naive 'prompt blocks + 1'
    admission rule would wrongly raise CacheOutOfBlocks here)."""
    cfg, model, params = _tiny_model()
    engine = InferenceEngine(model, params, EngineConfig(
        max_batch=1, block_size=8, num_blocks=4, max_prefill_len=8,
        max_seq_len=32))
    # 25 + 7 = 32 tokens -> exactly 4 blocks, generation never leaves
    # block 3 (positions 25..31)
    engine.add_request(Request(uid="fit", prompt=[1] * 25,
                               max_new_tokens=7))
    out = engine.run()
    assert len(out["fit"]) == 7
    assert engine.allocator.num_used == 0


def test_preemption_preserves_greedy_outputs():
    """Preemption-under-pressure determinism: the same greedy workload
    served from a pool tight enough to force preemption must emit
    byte-identical tokens to a pool that never preempts (emitted tokens
    are carried across preemption, and the cached re-prefill rebuilds
    the exact same context)."""
    cfg, model, params = _tiny_model()
    rng = np.random.RandomState(11)
    reqs = [Request(uid=f"r{i}", prompt=list(rng.randint(0, 128, 6 + i)),
                    max_new_tokens=20) for i in range(3)]

    def serve(num_blocks):
        engine = InferenceEngine(model, params, EngineConfig(
            max_batch=3, block_size=8, num_blocks=num_blocks,
            max_prefill_len=8, max_seq_len=32))
        for r in reqs:
            engine.add_request(r)
        return engine.run(), engine.stats()

    roomy, roomy_stats = serve(num_blocks=16)
    tight, tight_stats = serve(num_blocks=6)
    assert roomy_stats["num_preemptions"] == 0
    assert tight_stats["num_preemptions"] >= 1
    assert tight == roomy
    assert tight_stats["prefill_compilations"] == 1
    assert tight_stats["decode_compilations"] == 1


def test_block_allocator_refcounts_prefix_index_and_lru_eviction():
    """The prefix-cache contract on the allocator: registered full
    blocks are matchable by hash chain, sharing is refcounted, freed
    registered blocks are retained (cached) until allocation pressure
    evicts them least-recently-used."""
    from apex_tpu.serving import hash_block_tokens

    a = BlockAllocator(4)
    h1 = hash_block_tokens(None, [7] * 8)
    h2 = hash_block_tokens(h1, [9] * 8)
    b = a.alloc(2)
    assert a.register_prefix(h1, b[0]) and a.register_prefix(h2, b[1])
    # a second holder matches the chain and shares by reference
    assert a.match_prefix([h1, h2]) == b
    assert a.refcount(b[0]) == 2 and a.refcount(b[1]) == 2
    # a chain that diverges after the first block matches one block only
    h2x = hash_block_tokens(h1, [1] * 8)
    assert a.match_prefix([h1, h2x]) == [b[0]]
    a.free([b[0]])
    a.free(b)
    a.free(b)   # all references released -> cached, NOT freed
    assert a.num_free == 2 and a.num_cached == 2 and a.num_used == 0
    # matching revives a cached block
    got = a.match_prefix([h1])
    assert got == [b[0]] and a.num_cached == 1 and a.refcount(b[0]) == 1
    a.free(got)  # LRU order is now [b1, b0]: b0 was just revived
    # allocation beyond the free list evicts least-recently-used first
    c = a.alloc(3)
    assert a.num_evictions == 1 and a.num_cached == 1
    assert a.match_prefix([h2]) == []             # h2's block was evicted
    got = a.match_prefix([h1, h2])                # h1's (recent) survived
    assert got == [b[0]]
    a.free(got)
    a.free(c)
    assert a.num_free + a.num_cached == 4 and a.num_used == 0


def test_block_allocator_free_raises_on_double_free_and_unknown_id():
    a = BlockAllocator(4)
    b = a.alloc(1)
    a.free(b)
    with pytest.raises(ValueError, match="double free"):
        a.free(b)
    with pytest.raises(ValueError, match="out of range"):
        a.free([17])
    with pytest.raises(ValueError, match="double free"):
        a.free([2])   # never allocated
    # the failed frees must not have corrupted the free list
    assert sorted(a.alloc(4)) == [0, 1, 2, 3]


def _prefix_engine(model, params, **kw):
    base = dict(max_batch=4, block_size=8, num_blocks=64,
                max_prefill_len=16, max_seq_len=64)
    base.update(kw)
    return InferenceEngine(model, params, EngineConfig(**base))


def test_chunked_prefill_admits_long_prompts_and_matches_monolithic():
    """A prompt longer than the prefill chunk must be admissible and
    emit byte-identical greedy tokens to a monolithic (one-chunk)
    prefill of the same prompt — the chunk loop attends each chunk
    against the previously-written cache blocks, so chunking is purely
    an execution-schedule choice."""
    cfg, model, params = _tiny_model()
    prompt = list(np.random.RandomState(5).randint(0, 128, 40))

    mono = _prefix_engine(model, params, max_prefill_len=48)
    mono.add_request(Request(uid="m", prompt=prompt, max_new_tokens=6))
    ref = mono.run()["m"]
    assert mono.stats()["num_prefill_chunks"] == 1

    chunked = _prefix_engine(model, params, max_prefill_len=48,
                             prefill_chunk=16)
    chunked.add_request(Request(uid="c", prompt=prompt, max_new_tokens=6))
    out = chunked.run()["c"]
    assert out == ref
    stats = chunked.stats()
    assert stats["num_prefill_chunks"] == 3   # ceil(40 / 16)
    assert stats["prefill_compilations"] == 1
    assert stats["decode_compilations"] == 1


def test_prefix_cached_second_serving_allocates_zero_prompt_blocks():
    """THE acceptance scenario: an identical (block-aligned) prompt
    served twice with prefix caching emits identical tokens both times,
    and the second admission matches every prompt block from the cache
    — zero new prompt blocks, and the first-token logits are recomputed
    from shared blocks without a single cache write."""
    cfg, model, params = _tiny_model()
    prompt = list(np.random.RandomState(9).randint(0, 128, 32))  # 4 blocks

    plain = _prefix_engine(model, params)
    plain.add_request(Request(uid="p", prompt=prompt, max_new_tokens=6))
    ref = plain.run()["p"]

    engine = _prefix_engine(model, params, enable_prefix_caching=True)
    engine.add_request(Request(uid="one", prompt=prompt, max_new_tokens=6))
    first = engine.run()["one"]
    assert first == ref
    s1 = engine.stats()
    assert s1["blocks_cached"] > 0          # finished blocks retained
    assert engine.allocator.num_used == 0

    engine.add_request(Request(uid="two", prompt=prompt, max_new_tokens=6))
    second = engine.run()["two"]
    assert second == ref
    s2 = engine.stats()
    # every prompt block came from the cache: nothing newly allocated
    assert s2["prefix_hit_blocks"] - s1["prefix_hit_blocks"] == 4
    assert (s2["prompt_blocks_allocated"]
            == s1["prompt_blocks_allocated"])
    # one logits-only pass replaces the whole prefill
    assert s2["num_prefill_chunks"] - s1["num_prefill_chunks"] == 1
    # the fixed-program contract survives caching, chunking, both runs
    assert s2["prefill_compilations"] == 1
    assert s2["decode_compilations"] == 1
    assert 0.0 < s2["prefix_cache_hit_rate"] <= 1.0


def test_prefix_cache_shares_blocks_between_live_requests():
    """Two concurrent requests with a shared block-aligned prefix:
    the second must reference the first's prompt blocks (refcount 2)
    rather than re-prefilling them, once the first has registered them."""
    cfg, model, params = _tiny_model()
    rng = np.random.RandomState(13)
    shared = list(rng.randint(0, 128, 16))          # 2 full blocks
    a = Request(uid="a", prompt=shared + [3], max_new_tokens=12)
    b = Request(uid="b", prompt=shared + [5], max_new_tokens=12)

    engine = _prefix_engine(model, params, enable_prefix_caching=True)
    engine.add_request(a)
    engine.step()                 # a prefilled; its full blocks registered
    engine.add_request(b)
    engine.step()                 # b admitted: matches the 2 shared blocks
    slot_a = next(s for s in engine.slots if s and s.request.uid == "a")
    slot_b = next(s for s in engine.slots if s and s.request.uid == "b")
    assert slot_b.blocks[:2] == slot_a.blocks[:2]
    assert all(engine.allocator.refcount(x) == 2
               for x in slot_a.blocks[:2])
    out = engine.run()
    # sharing must not contaminate either generation: each must equal
    # its solo (uncached) serving
    for req in (a, b):
        solo = _prefix_engine(model, params)
        solo.add_request(req)
        assert solo.run()[req.uid] == out[req.uid]
    assert engine.allocator.num_used == 0


def test_copy_on_write_unshares_a_shared_partial_tail():
    """If a slot's partial tail block is shared (refcount > 1), the
    decode append must copy it to a private block first — and the copy
    must preserve contents exactly (greedy continuation unchanged)."""
    cfg, model, params = _tiny_model()
    prompt = list(np.random.RandomState(17).randint(0, 128, 12))

    ref_engine = _prefix_engine(model, params, enable_prefix_caching=True)
    ref_engine.add_request(Request(uid="r", prompt=prompt,
                                   max_new_tokens=8))
    ref = ref_engine.run()["r"]

    engine = _prefix_engine(model, params, enable_prefix_caching=True)
    engine.add_request(Request(uid="x", prompt=prompt, max_new_tokens=8))
    engine.step()     # prefill (12 tokens -> blocks [full, partial])
    slot = next(s for s in engine.slots if s is not None)
    tail = slot.blocks[1]
    engine.allocator.acquire([tail])      # simulate a second holder
    out = engine.run()["x"]
    assert engine.stats()["num_cow_copies"] >= 1
    assert out == ref                     # copy preserved the contents
    # the shared original still belongs to the simulated holder
    assert engine.allocator.refcount(tail) == 1
    engine.allocator.free([tail])
    assert engine.allocator.num_used == 0


def test_lru_eviction_keeps_engine_serving_under_cache_pressure():
    """With prefix caching on, finished requests' blocks pile up as
    cached; a stream of distinct prompts must keep serving by evicting
    LRU cached blocks instead of running out of pool."""
    cfg, model, params = _tiny_model()
    engine = _prefix_engine(model, params, num_blocks=16,
                            enable_prefix_caching=True)
    rng = np.random.RandomState(23)
    for i in range(8):
        engine.add_request(Request(uid=f"s{i}",
                                   prompt=list(rng.randint(0, 128, 16)),
                                   max_new_tokens=8))
    out = engine.run()
    assert len(out) == 8 and all(len(v) == 8 for v in out.values())
    stats = engine.stats()
    assert stats["num_cache_evictions"] > 0
    assert stats["prefill_compilations"] == 1
    assert stats["decode_compilations"] == 1


def test_stats_reports_block_accounting_and_scheduler_counters():
    cfg, model, params = _tiny_model()
    engine = _prefix_engine(model, params, enable_prefix_caching=True)
    prompt = list(np.random.RandomState(29).randint(0, 128, 16))
    engine.add_request(Request(uid="a", prompt=prompt, max_new_tokens=4))
    engine.step()   # a prefills and registers its full blocks
    engine.add_request(Request(uid="b", prompt=prompt, max_new_tokens=4))
    engine.run()
    stats = engine.stats()
    for key in ("blocks_free", "blocks_cached", "blocks_active",
                "prefix_cache_hit_rate", "prefix_hit_blocks",
                "prefix_lookup_blocks", "num_preemptions",
                "num_cow_copies", "num_cache_evictions",
                "num_prefill_chunks", "prompt_blocks_allocated"):
        assert key in stats, key
    assert (stats["blocks_free"] + stats["blocks_cached"]
            + stats["blocks_active"]) == engine.config.num_blocks
    assert stats["blocks_active"] == 0          # everything finished
    assert stats["prefix_hit_blocks"] >= 2      # b reused a's blocks
    assert 0.0 <= stats["prefix_cache_hit_rate"] <= 1.0


def _multistep_engine(model, params, k, seed=11, **kw):
    base = dict(max_batch=4, block_size=8, num_blocks=64,
                max_prefill_len=16, max_seq_len=64, seed=seed,
                decode_steps=k)
    base.update(kw)
    return InferenceEngine(model, params, EngineConfig(**base))


def _multistep_workload(engine):
    """6 staggered requests, mixed greedy/sampled, generation budgets
    deliberately NOT multiples of 4 or 8 so lanes finish mid-scan."""
    rng = np.random.RandomState(37)
    reqs = []
    for i in range(6):
        samp = (SamplingParams() if i % 2 == 0 else
                SamplingParams(temperature=0.9, top_k=12, top_p=0.85))
        reqs.append(Request(uid=f"m{i}",
                            prompt=list(rng.randint(0, 128, 4 + 2 * i)),
                            max_new_tokens=3 + (i % 3) * 5,
                            sampling=samp))
    for r in reqs[:3]:
        engine.add_request(r)
    engine.step()
    engine.step()
    for r in reqs[3:]:
        engine.add_request(r)
    return reqs, engine.run()


def test_multistep_decode_outputs_identical_across_k():
    """THE multi-step acceptance scenario: greedy AND seeded-sampled
    outputs are bit-identical for decode_steps in {1, 4, 8} (per-
    request/per-token PRNG keys make generation schedule-invariant),
    the compile contract stays one prefill + one decode program, and
    K > 1 actually amortizes dispatches over tokens."""
    cfg, model, params = _tiny_model()
    outs, stats = {}, {}
    for k in (1, 4, 8):
        engine = _multistep_engine(model, params, k)
        _, outs[k] = _multistep_workload(engine)
        s = engine.stats()
        assert s["prefill_compilations"] == 1
        assert s["decode_compilations"] == 1
        assert engine.allocator.num_used == 0
        stats[k] = s
    assert outs[1] == outs[4] == outs[8]
    # same tokens, fewer dispatches: the amortization is observable
    assert (stats[1]["num_tokens_decoded"] == stats[4]["num_tokens_decoded"]
            == stats[8]["num_tokens_decoded"])
    assert stats[4]["num_decode_dispatches"] < stats[1]["num_decode_dispatches"]
    assert stats[8]["num_decode_dispatches"] <= stats[4]["num_decode_dispatches"]
    # and the sampled half still actually depends on the engine seed
    _, alt = _multistep_workload(_multistep_engine(model, params, 8,
                                                   seed=999))
    sampled = [f"m{i}" for i in range(6) if i % 2 == 1]
    assert any(alt[u] != outs[8][u] for u in sampled)


def test_multistep_eos_and_budget_freeze_lanes_mid_scan():
    """A lane that samples EOS (or exhausts max_new_tokens) mid-scan
    must freeze on-device — later scan iterations emit the sentinel and
    write nothing — and the host must finish it on exactly the same
    token a K=1 engine would."""
    cfg, model, params = _tiny_model()
    prompt = list(np.random.RandomState(31).randint(0, 128, 6))
    pilot = _multistep_engine(model, params, 1)
    pilot.add_request(Request(uid="p", prompt=prompt, max_new_tokens=6))
    ref = pilot.run()["p"]

    # eos on (the first occurrence of) the 4th greedy token: fires on
    # scan iteration 2 or 3 of the single K=8 dispatch
    eos = int(ref[3])
    expected = ref[: ref.index(eos) + 1]
    engine = _multistep_engine(model, params, 8)
    engine.add_request(Request(uid="e", prompt=prompt, max_new_tokens=6,
                               eos_token_id=eos))
    engine.add_request(Request(uid="b", prompt=prompt, max_new_tokens=6))
    out = engine.run()
    assert out["e"] == expected
    assert out["b"] == ref
    stats = engine.stats()
    # both lanes' whole generation fits inside single K=8 dispatches
    # (budget 5 < 8 after the prefill-sampled first token)
    total_decode = (len(expected) - 1) + (len(ref) - 1)
    assert stats["num_tokens_decoded"] == total_decode
    assert stats["num_decode_dispatches"] <= 2
    assert stats["decode_compilations"] == 1
    assert engine.allocator.num_used == 0


def test_multistep_preemption_resume_is_deterministic():
    """Preemption-under-pressure at K=4, with a SAMPLED lane in the
    mix: a pool tight enough to force preemption (granularity is now K
    tokens of block headroom) must emit byte-identical tokens to a
    roomy pool — and to a roomy K=1 engine — because emitted tokens are
    carried across preemption and per-token keys make the resumed
    sampling continue the same draw sequence."""
    cfg, model, params = _tiny_model()
    rng = np.random.RandomState(19)
    reqs = [Request(uid=f"r{i}", prompt=list(rng.randint(0, 128, 6 + i)),
                    max_new_tokens=20,
                    sampling=(SamplingParams(temperature=0.8, top_k=12)
                              if i == 1 else SamplingParams()))
            for i in range(3)]

    def serve(num_blocks, k):
        engine = InferenceEngine(model, params, EngineConfig(
            max_batch=3, block_size=8, num_blocks=num_blocks,
            max_prefill_len=8, max_seq_len=32, decode_steps=k, seed=5))
        for r in reqs:
            engine.add_request(r)
        return engine.run(), engine.stats()

    roomy, roomy_stats = serve(num_blocks=16, k=4)
    tight, tight_stats = serve(num_blocks=6, k=4)
    single, single_stats = serve(num_blocks=16, k=1)
    assert roomy_stats["num_preemptions"] == 0
    assert tight_stats["num_preemptions"] >= 1
    assert tight == roomy == single
    for s in (roomy_stats, tight_stats, single_stats):
        assert s["prefill_compilations"] == 1
        assert s["decode_compilations"] == 1


def test_stats_split_decode_dispatches_from_tokens_with_alias():
    """stats() reports num_decode_dispatches and num_tokens_decoded
    separately; the legacy num_decode_steps key survives as an alias
    for dispatches (its pre-multistep meaning)."""
    cfg, model, params = _tiny_model()
    engine = _multistep_engine(model, params, 4)
    for uid in ("a", "b"):
        engine.add_request(Request(uid=uid, prompt=[3, 1, 4, 1, 5],
                                   max_new_tokens=9))
    out = engine.run()
    stats = engine.stats()
    # every generated token past the prefill-sampled first one came
    # from a decode dispatch
    decode_tokens = sum(len(v) - 1 for v in out.values())
    assert stats["num_tokens_decoded"] == decode_tokens
    assert stats["num_decode_steps"] == stats["num_decode_dispatches"]
    assert stats["num_decode_dispatches"] < stats["num_tokens_decoded"]
    # the dirty-tracked table uploaded at most once per dispatch
    assert stats["decode_table_rebuilds"] <= stats["num_decode_dispatches"]


def test_engine_raises_when_pool_can_never_serve_the_queue():
    """A request whose prompt needs more blocks than the whole pool must
    raise CacheOutOfBlocks instead of spinning the scheduler forever."""
    cfg, model, params = _tiny_model()
    engine = InferenceEngine(model, params, EngineConfig(
        max_batch=2, block_size=8, num_blocks=2, max_prefill_len=16,
        max_seq_len=32))
    engine.add_request(Request(uid="big", prompt=[1] * 16,
                               max_new_tokens=2))
    with pytest.raises(CacheOutOfBlocks):
        engine.run()


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

def test_sampling_greedy_topk_topp_determinism():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(4, 64).astype("f4") * 2.0)
    key = jax.random.PRNGKey(42)
    ones = jnp.ones((4,), jnp.float32)
    zeros_i = jnp.zeros((4,), jnp.int32)

    # temperature <= 0: exact argmax
    toks = sample_tokens(logits, key, jnp.zeros((4,)), zeros_i, ones)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(jnp.argmax(logits, -1)))
    # top_k = 1 is greedy regardless of temperature
    toks = sample_tokens(logits, key, ones * 5.0,
                         jnp.ones((4,), jnp.int32), ones)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(jnp.argmax(logits, -1)))
    # a vanishing nucleus keeps only the argmax token
    toks = sample_tokens(logits, key, ones, zeros_i, ones * 1e-6)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(jnp.argmax(logits, -1)))
    # fixed key -> identical draws; different key -> (some) different
    a = sample_tokens(logits, key, ones, zeros_i, ones)
    b = sample_tokens(logits, key, ones, zeros_i, ones)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    draws = np.stack([
        np.asarray(sample_tokens(logits, jax.random.PRNGKey(s), ones * 2.0,
                                 zeros_i, ones))
        for s in range(16)])
    assert len(np.unique(draws)) > 1

    # top-k draws stay inside the k most likely tokens
    k = 5
    topk_sets = np.asarray(jnp.argsort(-logits, axis=-1)[:, :k])
    for s in range(16):
        toks = np.asarray(sample_tokens(
            logits, jax.random.PRNGKey(s), ones * 3.0,
            jnp.full((4,), k, jnp.int32), ones))
        for row in range(4):
            assert toks[row] in topk_sets[row]


def test_sampling_top_k_at_least_vocab_equals_disabled():
    """The documented alias: top_k >= V keeps every rank, so it must
    draw exactly what top_k = 0 (disabled) draws under the same key —
    and validate() must accept it (it cannot clamp: the vocabulary size
    is a model property the params object never sees)."""
    rng = np.random.RandomState(2)
    V = 32
    logits = jnp.asarray(rng.randn(4, V).astype("f4") * 2.0)
    ones = jnp.ones((4,), jnp.float32)
    SamplingParams(temperature=1.0, top_k=10 ** 6).validate()
    for s in range(8):
        key = jax.random.PRNGKey(s)
        ref = np.asarray(sample_tokens(logits, key, ones,
                                       jnp.zeros((4,), jnp.int32), ones))
        for k in (V, V + 1, 10 ** 6):
            got = np.asarray(sample_tokens(
                logits, key, ones, jnp.full((4,), k, jnp.int32), ones))
            np.testing.assert_array_equal(got, ref)


def test_sample_tokens_per_lane_draws_are_lane_invariant():
    """The property the multi-step decode keys rely on: a row's draw
    depends only on ITS key and logits — permuting the batch permutes
    the draws, it never changes them (the shared-key sampler folds the
    row index into the noise, so this deliberately does NOT hold for
    sample_tokens)."""
    from apex_tpu.serving import sample_tokens_per_lane

    rng = np.random.RandomState(4)
    logits = jnp.asarray(rng.randn(3, 64).astype("f4") * 2.0)
    keys = jnp.stack([jax.random.PRNGKey(s) for s in (100, 101, 102)])
    ones = jnp.ones((3,), jnp.float32)
    zeros_i = jnp.zeros((3,), jnp.int32)
    out = np.asarray(sample_tokens_per_lane(logits, keys, ones * 1.5,
                                            zeros_i, ones))
    perm = np.array([2, 0, 1])
    out_p = np.asarray(sample_tokens_per_lane(
        logits[perm], keys[perm], ones * 1.5, zeros_i, ones))
    np.testing.assert_array_equal(out_p, out[perm])
    # greedy rows ignore the key entirely
    greedy = np.asarray(sample_tokens_per_lane(
        logits, keys, jnp.zeros((3,)), zeros_i, ones))
    np.testing.assert_array_equal(greedy,
                                  np.asarray(jnp.argmax(logits, -1)))


def test_device_mirror_rebuilds_only_after_invalidate():
    from apex_tpu.serving import DeviceMirror

    calls = []

    def build():
        calls.append(1)
        return len(calls)

    m = DeviceMirror()
    assert m.dirty
    assert m.get(build) == 1 and m.get(build) == 1 and len(calls) == 1
    assert not m.dirty
    m.invalidate()
    assert m.dirty
    assert m.get(build) == 2 and len(calls) == 2


def test_bench_serving_multistep_section_smoke():
    """The bench serving section's decode_steps sweep (fast shape) must
    run end-to-end, report the new dispatch/token counters per arm, and
    certify bit-identical outputs across K."""
    import importlib.util
    import pathlib

    path = pathlib.Path(__file__).resolve().parents[1] / "bench.py"
    spec = importlib.util.spec_from_file_location("_bench_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.bench_serving_multistep(fast=True)
    assert rec["unit"] == "tokens/sec"
    assert rec["outputs_bit_identical_across_k"] is True
    assert rec["decode_steps_swept"] == [1, 4]
    sweep = rec["sweep"]
    assert set(sweep) == {"k1", "k4"}
    for arm in sweep.values():
        for key in ("decode_tokens_per_sec", "num_decode_dispatches",
                    "num_tokens_decoded", "decode_table_rebuilds",
                    "decode_compilations"):
            assert key in arm, key
        assert arm["decode_compilations"] == 1
        assert arm["decode_tokens_per_sec"] > 0
    assert (sweep["k4"]["num_decode_dispatches"]
            < sweep["k1"]["num_decode_dispatches"])
    assert (sweep["k4"]["num_tokens_decoded"]
            == sweep["k1"]["num_tokens_decoded"])
    assert rec["vs_baseline"] > 0


def test_sampling_top_p_renormalizes_over_top_k_survivors():
    """The documented composition: top-p mass is measured over the
    RENORMALIZED top-k distribution. Logits (3.0, 1.9, rest 1.0):
    within top-2 token 0 holds e^3/(e^3+e^1.9) ~ 0.75 of the mass, so
    top_p=0.7 must always return token 0 — while over the full
    vocabulary token 0 holds only ~0.10, under which token 1 would
    (wrongly) stay sampleable ~25% of draws."""
    logits = np.full((1, 64), 1.0, np.float32)
    logits[0, 0], logits[0, 1] = 3.0, 1.9
    logits = jnp.asarray(logits)
    ones = jnp.ones((1,), jnp.float32)
    for s in range(32):
        tok = int(sample_tokens(logits, jax.random.PRNGKey(s),
                                ones, jnp.full((1,), 2, jnp.int32),
                                ones * 0.7)[0])
        assert tok == 0


